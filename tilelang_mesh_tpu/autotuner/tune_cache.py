"""Content-addressed, mergeable fleet tune cache (docs/autotuning.md).

One process's completed autotune sweep should warm every other process
on the fleet — including ones on other machines whose cache dirs are
aggregated offline. Entries are keyed on

    sha256(kernel source sha, shape bucket, arch, resolved pass config,
           CODEGEN_VERSION, schema)

so a codegen change, a different chip, or a different pass configuration
can never resurrect a stale winner, and live in ``env.tune_cache_dir()``
as one JSON file per key, written with the crash-safe kernel-cache
discipline (``cache/kernel_cache.py``):

- **atomic writes** — tmp file + ``os.replace``; a crash leaves the old
  entry or a tmp file, never a torn entry;
- **checksummed entries** — every payload carries a sha256 of its own
  canonical JSON, verified on every read;
- **quarantine, never silent deletion** — a corrupt entry moves to
  ``<root>/.quarantine/`` (counted + traced) so the damage stays
  inspectable.

Entries are **mergeable**: two payloads for the same key union their
trial lists (per-config best latency wins) and keep the better best
config, so fleet aggregation is a commutative fold::

    python -m tilelang_mesh_tpu.autotuner.tune_cache merge <dir>...

merges other runners' cache dirs into this machine's root. The
autotuner consults the cache before sweeping (a hit is a
zero-measurement warm start), records every completed sweep, and seeds
its cost model from the recorded (features, latency) samples of sibling
shape buckets; serving ``warmup()`` consults it for per-bucket kernel
configs (serving/batcher.py).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..env import env
from ..observability import tracer as _trace

try:
    import fcntl
except ImportError:          # non-POSIX: locking degrades to process-local
    fcntl = None  # type: ignore[assignment]

logger = logging.getLogger("tilelang_mesh_tpu.autotune")

__all__ = ["TuneCache", "merge_payloads", "main", "SCHEMA"]

#: entry-format version: part of the key, so a schema change simply
#: starts a fresh namespace instead of tripping over old entries
SCHEMA = 1
QUARANTINE_DIR = ".quarantine"


def _canonical(payload: dict) -> str:
    return json.dumps(payload, sort_keys=True, separators=(",", ":"),
                      default=str)


def entry_checksum(payload: dict) -> str:
    body = {k: v for k, v in payload.items() if k != "checksum"}
    return hashlib.sha256(_canonical(body).encode()).hexdigest()


def _config_key(cfg: dict) -> str:
    return json.dumps(cfg, sort_keys=True, default=str)


def _tuning_body(payload: dict) -> dict:
    """The entry minus its provenance (checksum, merge counter): what
    idempotence and unchanged-detection are judged on."""
    return {k: v for k, v in payload.items()
            if k not in ("checksum", "merges")}


def merge_payloads(a: dict, b: dict) -> dict:
    """Commutative, idempotent merge of two entries for the SAME key:
    trials union per config (lower measured latency wins), best config
    re-derived from the union. The merge counter takes the max of both
    sides and bumps only when the union actually changed the tuning
    payload — so re-merging identical entries is a fixed point (a cron'd
    ``tune_cache merge`` of the same dirs converges instead of churning
    checksums forever)."""
    trials: Dict[str, dict] = {}
    for src in (a, b):
        for t in src.get("trials") or []:
            if not isinstance(t, dict) or "config" not in t:
                continue
            ck = _config_key(t["config"])
            prev = trials.get(ck)
            lat = t.get("latency_ms")
            if prev is None or (
                    lat is not None
                    and (prev.get("latency_ms") is None
                         or lat < prev["latency_ms"])):
                trials[ck] = dict(t)
    measured = [t for t in trials.values()
                if t.get("latency_ms") is not None]
    out = _tuning_body(a)
    out["trials"] = sorted(trials.values(), key=lambda t: _config_key(
        t["config"]))
    if measured:
        best = min(measured, key=lambda t: t["latency_ms"])
        out["best_config"] = best["config"]
        out["best_latency_ms"] = best["latency_ms"]
    changed = _canonical(_tuning_body(a)) != _canonical(out)
    out["merges"] = max(int(a.get("merges") or 0),
                        int(b.get("merges") or 0)) + (1 if changed else 0)
    return out


class TuneCache:
    """One directory of checksummed, atomically-written tune entries."""

    def __init__(self, root=None):
        self.root = Path(root) if root is not None else env.tune_cache_dir()

    # -- keying --------------------------------------------------------
    @staticmethod
    def key(source_sha: str, shape_bucket: str, arch: str,
            pass_cfg: Optional[dict] = None) -> str:
        from ..cache.kernel_cache import CODEGEN_VERSION
        h = hashlib.sha256()
        h.update(source_sha.encode())
        h.update(shape_bucket.encode())
        h.update(arch.encode())
        h.update(json.dumps(pass_cfg or {}, sort_keys=True,
                            default=str).encode())
        h.update(str(CODEGEN_VERSION).encode())
        h.update(str(SCHEMA).encode())
        return h.hexdigest()

    def _path(self, key: str) -> Path:
        return self.root / f"{key}.json"

    @contextlib.contextmanager
    def _key_lock(self, key: str):
        """Serialize cross-process read-merge-write cycles of one entry
        (the same flock discipline as the kernel cache: advisory and
        kernel-released on crash, so a dead writer can never wedge the
        fleet tier; degrades to nothing where fcntl is unavailable)."""
        if fcntl is None:
            yield
            return
        lock_dir = self.root / ".locks"
        lock_dir.mkdir(parents=True, exist_ok=True)
        fd = os.open(lock_dir / f"{key}.lock", os.O_CREAT | os.O_RDWR,
                     0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- read / write --------------------------------------------------
    def _quarantine(self, path: Path, reason: str) -> None:
        qroot = self.root / QUARANTINE_DIR
        qroot.mkdir(parents=True, exist_ok=True)
        dest = qroot / path.name
        n = 0
        while dest.exists():
            n += 1
            dest = qroot / f"{path.name}.{n}"
        try:
            os.replace(path, dest)
        except OSError:
            try:
                path.unlink()
            except OSError:
                dest = None
        _trace.inc("tune.cache.quarantined")
        _trace.event("tune.cache.quarantine", "autotune",
                     entry=path.name, reason=reason,
                     dest=str(dest) if dest else "removed")
        logger.warning("quarantined corrupt tune-cache entry %s (%s)%s",
                       path.name, reason, f" -> {dest}" if dest else "")

    @staticmethod
    def _verify(payload) -> Optional[str]:
        """None when the payload is intact, else the corruption reason."""
        if not isinstance(payload, dict):
            return "not a JSON object"
        if payload.get("schema") != SCHEMA:
            return f"schema {payload.get('schema')!r} != {SCHEMA}"
        expect = payload.get("checksum")
        actual = entry_checksum(payload)
        if expect != actual:
            return (f"checksum mismatch (expect {str(expect)[:12]}…, "
                    f"got {actual[:12]}…)")
        return None

    def get(self, key: str) -> Optional[dict]:
        p = self._path(key)
        if not p.exists():
            return None
        try:
            payload = json.loads(p.read_text())
        except (OSError, ValueError) as e:
            self._quarantine(p, f"{type(e).__name__}: {e}")
            return None
        reason = self._verify(payload)
        if reason is not None:
            self._quarantine(p, reason)
            return None
        return payload

    def put(self, key: str, payload: dict) -> None:
        from ..cache.kernel_cache import CODEGEN_VERSION, atomic_write
        body = {k: v for k, v in payload.items() if k != "checksum"}
        body.setdefault("schema", SCHEMA)
        body.setdefault("codegen_version", CODEGEN_VERSION)
        body["checksum"] = entry_checksum(body)
        self.root.mkdir(parents=True, exist_ok=True)
        try:
            atomic_write(self._path(key), json.dumps(body, indent=1))
        except OSError as e:    # a full disk degrades the fleet tier,
            logger.warning(     # never the sweep that produced the result
                "tune-cache write failed for %s: %s", key, e)
            return
        _trace.inc("tune.cache.writes")

    def record(self, key: str, payload: dict) -> None:
        """Write-or-merge under the per-key lock: a concurrent writer's
        trials survive (two processes finishing the same sweep both
        contribute; without the lock the read-merge-write cycles would
        interleave and the loser's trials would vanish)."""
        with self._key_lock(key):
            existing = self.get(key)
            self.put(key, merge_payloads(existing, payload)
                     if existing else payload)

    # -- enumeration / model seeding -----------------------------------
    def entries(self) -> Iterator[Tuple[str, dict]]:
        if not self.root.is_dir():
            return
        for p in sorted(self.root.glob("*.json")):
            payload = self.get(p.stem)
            if payload is not None:
                yield p.stem, payload

    def samples(self, source_sha: str,
                arch: str) -> List[Tuple[dict, float]]:
        """(features, measured_ms) pairs recorded for this kernel source
        on this arch across EVERY shape bucket — the cost model's warm
        start for a bucket it has never measured."""
        out: List[Tuple[dict, float]] = []
        for _key, payload in self.entries():
            if payload.get("source_sha") != source_sha or \
                    payload.get("arch") != arch:
                continue
            for t in payload.get("trials") or []:
                feats = t.get("features")
                lat = t.get("latency_ms")
                if isinstance(feats, dict) and lat:
                    out.append((feats, float(lat)))
        return out

    def stats(self) -> dict:
        entries = list(self.entries())
        qdir = self.root / QUARANTINE_DIR
        return {
            "root": str(self.root),
            "entries": len(entries),
            "trials": sum(len(p.get("trials") or []) for _, p in entries),
            "merges": sum(int(p.get("merges") or 0) for _, p in entries),
            "quarantined": len(list(qdir.glob("*")))
            if qdir.is_dir() else 0,
        }

    # -- fleet aggregation ---------------------------------------------
    def merge_from(self, sources: Sequence) -> dict:
        """Fold other cache dirs into this one. Corrupt source entries
        are counted and skipped (never quarantined in-place — the source
        dir may be another machine's artifact, read-only by contract)."""
        stats = {"examined": 0, "new": 0, "merged": 0, "unchanged": 0,
                 "corrupt": 0}
        for src in sources:
            src = Path(src)
            if not src.is_dir():
                continue
            for p in sorted(src.glob("*.json")):
                stats["examined"] += 1
                try:
                    theirs = json.loads(p.read_text())
                except (OSError, ValueError):
                    stats["corrupt"] += 1
                    continue
                if self._verify(theirs) is not None:
                    stats["corrupt"] += 1
                    continue
                key = p.stem
                with self._key_lock(key):
                    mine = self.get(key)
                    if mine is None:
                        self.put(key, theirs)
                        stats["new"] += 1
                        continue
                    merged = merge_payloads(mine, theirs)
                    if _canonical({k: v for k, v in mine.items()
                                   if k != "checksum"}) == \
                            _canonical({k: v for k, v in merged.items()
                                        if k != "checksum"}):
                        stats["unchanged"] += 1
                    else:
                        self.put(key, merged)
                        stats["merged"] += 1
        n = stats["new"] + stats["merged"]
        if n:
            _trace.inc("tune.cache.merged", n)
        _trace.event("tune.cache.merge", "autotune", **stats)
        return stats


# ---------------------------------------------------------------------------
# CLI: fleet aggregation + inspection
# ---------------------------------------------------------------------------

def _fmt_list(cache: TuneCache) -> str:
    lines = [f"tune cache @ {cache.root}"]
    for key, p in cache.entries():
        lat = p.get("best_latency_ms")
        tail = (f"best={p.get('best_config')} ({lat:.4f} ms)"
                if lat is not None else "(no measured trials)")
        lines.append(
            f"  {key[:12]}…  {p.get('factory', '?'):24s} "
            f"arch={p.get('arch', '?'):8s} "
            f"trials={len(p.get('trials') or []):3d} "
            f"merges={p.get('merges', 0)} {tail}")
    if len(lines) == 1:
        lines.append("  (empty)")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    import sys as _sys
    ap = argparse.ArgumentParser(
        prog="python -m tilelang_mesh_tpu.autotuner.tune_cache",
        description="Fleet tune cache: merge other runners' sweep "
                    "results into this machine's cache, or inspect it "
                    "(docs/autotuning.md).")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_mg = sub.add_parser(
        "merge", help="fold other tune-cache dirs into the local root "
                      "(checksummed entries; per-config best wins)")
    p_mg.add_argument("sources", nargs="+", help="tune-cache dir(s)")
    p_mg.add_argument("--into", metavar="DIR",
                      help="destination root (default: "
                           "env.tune_cache_dir())")
    p_ls = sub.add_parser("list", help="entries in a tune-cache dir")
    p_ls.add_argument("--root", metavar="DIR")
    p_st = sub.add_parser("stats", help="entry/trial/merge totals")
    p_st.add_argument("--root", metavar="DIR")
    for p in (p_mg, p_ls, p_st):
        p.add_argument("--json", action="store_true",
                       help="machine-readable JSON output")
    args = ap.parse_args(list(_sys.argv[1:] if argv is None else argv))
    if args.cmd == "merge":
        cache = TuneCache(args.into) if args.into else TuneCache()
        stats = cache.merge_from(args.sources)
        if args.json:
            print(json.dumps(stats, indent=2))  # noqa: T201
        else:
            print(f"merged into {cache.root}: "  # noqa: T201
                  f"{stats['new']} new, {stats['merged']} merged, "
                  f"{stats['unchanged']} unchanged, "
                  f"{stats['corrupt']} corrupt skipped "
                  f"({stats['examined']} examined)")
        return 0
    cache = TuneCache(args.root) if args.root else TuneCache()
    if args.cmd == "list":
        if args.json:
            print(json.dumps(  # noqa: T201
                {k: p for k, p in cache.entries()}, indent=2))
        else:
            print(_fmt_list(cache))  # noqa: T201
        return 0
    stats = cache.stats()
    print(json.dumps(stats, indent=2) if args.json  # noqa: T201
          else "\n".join(f"{k}: {v}" for k, v in stats.items()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
