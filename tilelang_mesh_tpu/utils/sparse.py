"""2:4 structured-sparsity host utilities.

Mirror of the reference's tilelang/utils/sparse.py (compress /
randn_semi_sparse, which delegate to CUTLASS/torch packed formats). TPU
re-design: there is no sparse-MXU instruction, so kernels decompress tiles
in VMEM and run the dense MXU — the win is the halved HBM traffic on the
sparse operand. The metadata format is therefore chosen for VPU decompress,
not for an mma.sp instruction: one int8 per kept value giving its slot
(0..3) inside its group of four along K.

  A (M, K), 2:4 sparse  ->  A_sparse (M, K//2) values, E (M, K//2) int8
"""

from __future__ import annotations

import numpy as np


def randn_semi_sparse(M: int, K: int, dtype=np.float32,
                      seed: int = 0) -> np.ndarray:
    """Random dense matrix with exact 2:4 sparsity along K
    (reference tilelang/utils/sparse.py:108 randn_semi_sparse)."""
    if K % 4:
        raise ValueError(f"K must be a multiple of 4, got {K}")
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((M, K)).astype(dtype)
    groups = a.reshape(M, K // 4, 4)
    # keep the two largest |x| per group, zero the rest
    order = np.argsort(-np.abs(groups), axis=2)
    mask = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask, order[:, :, :2], True, axis=2)
    return (groups * mask).reshape(M, K)


def compress(A: np.ndarray):
    """Compress a 2:4-sparse (M, K) matrix into (values, metadata)
    (reference tilelang/utils/sparse.py:76 compress).

    Returns (A_sparse (M, K//2) of A.dtype, E (M, K//2) int8) where
    E[i, g*2+s] is the position (0..3) of value A_sparse[i, g*2+s] inside
    K-group g. Groups with fewer than two nonzeros keep zeros in the unused
    slots (their positions are the remaining indices, in order).
    """
    M, K = A.shape
    if K % 4:
        raise ValueError(f"K must be a multiple of 4, got {K}")
    groups = A.reshape(M, K // 4, 4)
    nonzero = groups != 0
    if (nonzero.sum(axis=2) > 2).any():
        raise ValueError("matrix is not 2:4 sparse: a group of 4 along K "
                         "has more than 2 nonzeros")
    # stable order: nonzero positions first, then zeros — always 2 slots
    key = np.where(nonzero, 0, 1) * 4 + np.arange(4)
    order = np.argsort(key, axis=2, kind="stable")[:, :, :2]
    order.sort(axis=2)  # keep original K order between the two kept slots
    vals = np.take_along_axis(groups, order, axis=2)
    return (vals.reshape(M, K // 2).astype(A.dtype),
            order.reshape(M, K // 2).astype(np.int8))


def decompress(A_sparse: np.ndarray, E: np.ndarray) -> np.ndarray:
    """Inverse of compress (host reference for tests)."""
    M, half = A_sparse.shape
    K = half * 2
    out = np.zeros((M, K // 4, 4), dtype=A_sparse.dtype)
    vals = A_sparse.reshape(M, K // 4, 2)
    idx = E.reshape(M, K // 4, 2).astype(np.int64)
    np.put_along_axis(out, idx, vals, axis=2)
    return out.reshape(M, K)
