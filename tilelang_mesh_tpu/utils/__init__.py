from .target import (determine_target, TPU_TARGET_DESC, target_is_mesh,
                     mesh_dims_from_target, make_mesh_target,
                     target_is_interpret, tpu_available)
from .tensor import (TensorSupplyType, get_tensor_supply, to_jax, copy_back,
                     assert_allclose, torch_assert_close)
