"""Tensor supply + comparison helpers for testing and profiling.

Reference: /root/reference/tilelang/utils/tensor.py (TensorSupplyType,
torch_assert_close). JAX-native: supplies jnp arrays; accepts numpy / torch
CPU tensors at the boundary for API parity.
"""

from __future__ import annotations

from enum import Enum
from typing import Any, Optional, Sequence

import numpy as np


class TensorSupplyType(Enum):
    Integer = 1
    Uniform = 2
    Normal = 3
    Randn = 4
    Zero = 5
    One = 6
    Auto = 7


def _np_dtype(dtype: str):
    import jax.numpy as jnp
    return np.dtype(jnp.dtype(dtype))


def get_tensor_supply(supply_type: TensorSupplyType = TensorSupplyType.Auto,
                      seed: int = 0):
    rng = np.random.default_rng(seed)

    def supply(shape: Sequence[int], dtype: str):
        import jax.numpy as jnp
        jdt = jnp.dtype(dtype)
        st = supply_type
        if st == TensorSupplyType.Auto:
            st = (TensorSupplyType.Integer
                  if jnp.issubdtype(jdt, jnp.integer) else
                  TensorSupplyType.Normal)
        if st == TensorSupplyType.Zero:
            return jnp.zeros(shape, jdt)
        if st == TensorSupplyType.One:
            return jnp.ones(shape, jdt)
        if st == TensorSupplyType.Integer:
            return jnp.asarray(rng.integers(-4, 5, size=shape), dtype=jdt)
        if st == TensorSupplyType.Uniform:
            return jnp.asarray(rng.uniform(-1, 1, size=shape), dtype=jdt)
        # Normal / Randn
        return jnp.asarray(rng.standard_normal(size=shape), dtype=jdt)

    return supply


def to_jax(x: Any):
    """Convert torch / numpy / python inputs to jax arrays (zero-copy where
    possible via dlpack)."""
    import jax
    import jax.numpy as jnp
    if isinstance(x, jax.Array):
        return x
    mod = type(x).__module__
    if mod.startswith("torch"):
        if x.device.type != "cpu":
            raise ValueError("only CPU torch tensors can cross into the TPU "
                             "runtime")
        return jnp.asarray(x.detach().numpy())
    return jnp.asarray(x)


def copy_back(dst: Any, src) -> None:
    """Write a jax result back into a caller-provided torch/numpy output
    buffer (reference-style `kernel(a, b, c)` call convention)."""
    arr = np.asarray(src)
    mod = type(dst).__module__
    if mod.startswith("torch"):
        import torch
        dst.copy_(torch.from_numpy(arr.copy()))
    elif isinstance(dst, np.ndarray):
        np.copyto(dst, arr)
    else:
        raise TypeError(f"cannot copy kernel output back into {type(dst)}")


def assert_allclose(actual, expected, rtol: float = 1e-2, atol: float = 1e-2,
                    max_mismatched_ratio: float = 0.01):
    """Numeric comparison with a mismatch budget (reference
    torch_assert_close semantics)."""
    a = np.asarray(actual, dtype=np.float64)
    e = np.asarray(expected, dtype=np.float64)
    assert a.shape == e.shape, f"shape mismatch {a.shape} vs {e.shape}"
    close = np.isclose(a, e, rtol=rtol, atol=atol)
    mismatched = (~close).sum()
    total = close.size
    if mismatched > max_mismatched_ratio * total:
        idx = np.argwhere(~close)[:5]
        samples = [f"  at {tuple(i)}: got {a[tuple(i)]}, want {e[tuple(i)]}"
                   for i in idx]
        raise AssertionError(
            f"{mismatched}/{total} elements "
            f"({100.0 * mismatched / total:.2f}%) mismatched "
            f"(budget {100 * max_mismatched_ratio:.2f}%), rtol={rtol}, "
            f"atol={atol}\n" + "\n".join(samples))


torch_assert_close = assert_allclose
