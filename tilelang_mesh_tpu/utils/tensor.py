"""Tensor supply + comparison helpers for testing and profiling.

Reference: /root/reference/tilelang/utils/tensor.py (TensorSupplyType,
torch_assert_close). JAX-native: supplies jnp arrays; accepts numpy / torch
CPU tensors at the boundary for API parity.
"""

from __future__ import annotations

from enum import Enum
from typing import Any, Optional, Sequence

import numpy as np


class TensorSupplyType(Enum):
    Integer = 1
    Uniform = 2
    Normal = 3
    Randn = 4
    Zero = 5
    One = 6
    Auto = 7


def _np_dtype(dtype: str):
    import jax.numpy as jnp
    return np.dtype(jnp.dtype(dtype))


def get_tensor_supply(supply_type: TensorSupplyType = TensorSupplyType.Auto,
                      seed: int = 0):
    rng = np.random.default_rng(seed)

    def supply(shape: Sequence[int], dtype: str):
        import jax.numpy as jnp
        jdt = jnp.dtype(dtype)
        st = supply_type
        if st == TensorSupplyType.Auto:
            st = (TensorSupplyType.Integer
                  if jnp.issubdtype(jdt, jnp.integer) else
                  TensorSupplyType.Normal)
        if st == TensorSupplyType.Zero:
            return jnp.zeros(shape, jdt)
        if st == TensorSupplyType.One:
            return jnp.ones(shape, jdt)
        if st == TensorSupplyType.Integer:
            return jnp.asarray(rng.integers(-4, 5, size=shape), dtype=jdt)
        if st == TensorSupplyType.Uniform:
            return jnp.asarray(rng.uniform(-1, 1, size=shape), dtype=jdt)
        # Normal / Randn
        return jnp.asarray(rng.standard_normal(size=shape), dtype=jdt)

    return supply


def _dlpack_import(x):
    """Best-effort dlpack ingestion (None = caller must fall back to the
    copying path). Raising here would turn an unsupported-but-valid
    input (non-contiguous view, exotic dtype, unaligned buffer) into an
    error the copying path handles fine.

    Only used when the process's default backend IS the host platform:
    a dlpack import of host memory commits the array to a CPU device,
    and ``jit`` follows committed inputs — on a TPU-default process that
    would silently drag the whole dispatch onto the host instead of
    staging the buffer to HBM like ``jnp.asarray`` does."""
    try:
        import jax
        if jax.default_backend() != "cpu":
            return None
        from jax import dlpack as _jdl
        return _jdl.from_dlpack(x)
    except Exception:
        return None


def to_jax(x: Any, zero_copy: bool = True):
    """Convert torch / numpy / python inputs to jax arrays — zero-copy
    where possible via the ``__dlpack__`` protocol, one copy otherwise.

    CPU torch tensors go through ``jax.dlpack`` (this is also the only
    path that can carry bfloat16, which numpy cannot represent); inputs
    that dlpack rejects (non-contiguous views, unsupported dtypes) fall
    back to a detach+copy. Contiguous aligned numpy arrays take the same
    dlpack route; everything else is ``jnp.asarray``. Note the dlpack
    contract: when the backend does alias the caller's buffer, mutating
    the source after the call is undefined — see the zero-copy matrix in
    docs/host_dispatch.md.

    ``zero_copy=False`` skips dlpack entirely: a dlpack import commits
    the result to ONE device, which a multi-device consumer (MeshKernel
    shard_map inputs) must not receive — mesh marshalling needs the
    uncommitted ``jnp.asarray`` form XLA can reshard.
    """
    import jax
    import jax.numpy as jnp
    if isinstance(x, jax.Array):
        return x
    mod = type(x).__module__
    if mod.startswith("torch"):
        if x.device.type != "cpu":
            raise ValueError("only CPU torch tensors can cross into the TPU "
                             "runtime")
        t = x.detach() if x.requires_grad else x
        if zero_copy:
            j = _dlpack_import(t)
            if j is not None:
                return j
        if not t.is_contiguous():
            t = t.contiguous()
        if zero_copy:
            j = _dlpack_import(t)
            if j is not None:
                return j
        try:
            return jnp.asarray(t.numpy())
        except TypeError:
            # numpy cannot represent this dtype (bfloat16 & friends):
            # dlpack is the only no-intermediate route — but it commits
            # the result to one device, so a zero_copy=False caller
            # (mesh marshalling) must take the float32 round-trip even
            # here
            if zero_copy:
                j = _dlpack_import(t.contiguous())
                if j is not None:
                    return j
            return jnp.asarray(t.float().numpy()).astype(
                jnp.dtype(str(t.dtype).replace("torch.", "")))
    if zero_copy and isinstance(x, np.ndarray) and \
            x.flags.c_contiguous and x.ctypes.data % 16 == 0:
        j = _dlpack_import(x)
        if j is not None:
            return j
    return jnp.asarray(x)


def copy_back(dst: Any, src) -> None:
    """Write a jax result back into a caller-provided torch/numpy output
    buffer (reference-style `kernel(a, b, c)` call convention).

    Torch destinations read the jax buffer through dlpack (zero-copy
    view, bfloat16-capable) and let ``Tensor.copy_`` do the one
    unavoidable write into the caller's memory. The numpy fallback only
    copies when the jax-backed view is non-contiguous (``np.asarray`` of
    a jax array is already a host view; the old unconditional
    ``arr.copy()`` doubled the transfer)."""
    mod = type(dst).__module__
    if mod.startswith("torch"):
        import torch
        view = None
        try:
            view = torch.from_dlpack(src)
        except Exception:
            pass
        if view is None:
            arr = np.asarray(src)
            if not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr)
            view = torch.from_numpy(arr) if arr.flags.writeable \
                else torch.from_numpy(arr.copy())
        dst.copy_(view)
    elif isinstance(dst, np.ndarray):
        np.copyto(dst, np.asarray(src))
    else:
        raise TypeError(f"cannot copy kernel output back into {type(dst)}")


def assert_allclose(actual, expected, rtol: float = 1e-2, atol: float = 1e-2,
                    max_mismatched_ratio: float = 0.01):
    """Numeric comparison with a mismatch budget (reference
    torch_assert_close semantics)."""
    a = np.asarray(actual, dtype=np.float64)
    e = np.asarray(expected, dtype=np.float64)
    assert a.shape == e.shape, f"shape mismatch {a.shape} vs {e.shape}"
    close = np.isclose(a, e, rtol=rtol, atol=atol)
    mismatched = (~close).sum()
    total = close.size
    if mismatched > max_mismatched_ratio * total:
        idx = np.argwhere(~close)[:5]
        samples = [f"  at {tuple(i)}: got {a[tuple(i)]}, want {e[tuple(i)]}"
                   for i in idx]
        raise AssertionError(
            f"{mismatched}/{total} elements "
            f"({100.0 * mismatched / total:.2f}%) mismatched "
            f"(budget {100 * max_mismatched_ratio:.2f}%), rtol={rtol}, "
            f"atol={atol}\n" + "\n".join(samples))


torch_assert_close = assert_allclose
