"""Target determination.

Reference: /root/reference/tilelang/utils/target.py (determine_target:76,
SUNMMIO_TARGET_DESC:21). Our targets:

  "tpu"            — compile Pallas to Mosaic, run on the local TPU
  "cpu"            — Pallas interpret mode (CI / no-hardware development)
  "tpu-mesh[RxC]"  — SPMD over an RxC jax Mesh (the Sunmmio-mesh analog);
                     mesh dims ride in the target string exactly like the
                     reference's mattr=device_mesh_nrow_4,device_mesh_ncol_4
  "auto"           — tpu if a TPU is attached else cpu
"""

from __future__ import annotations

import functools
import re
from typing import Optional, Tuple

TPU_TARGET_DESC = "tpu"
TPU_MESH_TARGET_DESC = "tpu-mesh[{nrow}x{ncol}]"

AVAILABLE_TARGETS = ("auto", "tpu", "cpu", "tpu-mesh")

_MESH_RE = re.compile(r"^(tpu|cpu)-mesh\[(\d+)x(\d+)\]$")


@functools.lru_cache(maxsize=1)
def tpu_available() -> bool:
    try:
        import jax
        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except Exception:
        return False


def determine_target(target: str = "auto",
                     return_object: bool = False) -> str:
    """Canonicalize a target string (reference determine_target:76)."""
    if target in (None, "auto"):
        return "tpu" if tpu_available() else "cpu"
    if target in ("tpu", "cpu"):
        return target
    if _MESH_RE.match(target):
        return target
    raise ValueError(f"Unknown target {target!r}; expected one of "
                     f"{AVAILABLE_TARGETS} or 'tpu-mesh[RxC]'")


def target_is_mesh(target: str) -> bool:
    return _MESH_RE.match(target) is not None


def mesh_dims_from_target(target: str) -> Optional[Tuple[int, int]]:
    m = _MESH_RE.match(target)
    if m is None:
        return None
    return (int(m.group(2)), int(m.group(3)))


def make_mesh_target(nrow: int, ncol: int, base: str = "auto") -> str:
    base = determine_target(base)
    return f"{base}-mesh[{nrow}x{ncol}]"


def target_is_interpret(target: str) -> bool:
    """Interpret-mode Pallas for cpu targets (SURVEY §4: CPU fallback)."""
    from ..env import env
    if env.TL_TPU_FORCE_INTERPRET:
        return True
    return target.startswith("cpu")
