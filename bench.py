"""Headline benchmark (BASELINE config #1): bf16 GEMM through the tile
pipeline vs a hand-written Pallas matmul on the same chip.

Prints ONE JSON line:
  {"metric": ..., "value": <TFLOPS of the framework kernel>,
   "unit": "TFLOPS", "vs_baseline": <framework / hand-written Pallas>}

vs_baseline >= 0.9 means within 10% of the hand-written kernel (the
BASELINE.md target); > 1.0 means beating it.
"""

import functools
import json
import sys
import time

import numpy as np


def _hand_pallas_matmul(M, N, K, bm, bn, bk):
    """The hand-written Pallas baseline the framework competes against."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(a, b, o, acc):
        k = pl.program_id(2)

        @pl.when(k == 0)
        def _():
            acc[...] = jnp.zeros_like(acc)

        acc[...] += jnp.dot(a[...], b[...],
                            preferred_element_type=jnp.float32)

        @pl.when(k == pl.num_programs(2) - 1)
        def _():
            o[...] = acc[...].astype(o.dtype)

    return pl.pallas_call(
        kern,
        grid=(M // bm, N // bn, K // bk),
        in_specs=[pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                  pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=(M * K + K * N + M * N) * 2,
            transcendentals=0),
    )


_TARGET_LOOP_S = 1.0   # in-loop work per timed call; >> fixed-cost noise
_MAX_REP = 200_000


def _make_runner(fn, args):
    """jit(run(n, *args)): n iterations of fn inside one fori_loop, outputs
    tied into the carry with optimization_barrier so XLA can't hoist or
    dead-code them, reduced to ONE scalar fetched to host (4-byte
    transfer) to synchronize. n is a RUNTIME value: one compile serves
    every rep count.

    Round 1 timed `np.asarray(full_result)`, which shipped the whole output
    over the device tunnel (~seconds for large outputs) and swamped the
    kernel time; `jax.block_until_ready` does not synchronize on this
    platform, so a value fetch is the only honest fence.
    """
    import jax
    import jax.numpy as jnp

    def body(i, carry):
        outs = fn(*carry)
        outs = outs if isinstance(outs, tuple) else (outs,)
        tied = jax.lax.optimization_barrier(tuple(carry) + outs)
        return tuple(tied[:len(carry)]), tied[len(carry)]

    @jax.jit
    def run(n, *ins):
        # seed the output slot with one real evaluation so the carry's
        # shape/dtype matches fn's first output (it need not match ins[0])
        outs0 = fn(*ins)
        outs0 = outs0 if isinstance(outs0, tuple) else (outs0,)
        _, last = jax.lax.fori_loop(
            0, n, lambda i, c: body(i, c[0]), (tuple(ins), outs0[0]))
        return last.ravel()[0].astype(jnp.float32)

    return run


def _t(run, n, args):
    t0 = time.perf_counter()
    float(run(n, *args))
    return time.perf_counter() - t0


def _calibrate(run, args):
    """Grow n until the loop body accounts for ~_TARGET_LOOP_S of wall time
    beyond the fixed per-call cost (~65 ms tunnel RPC on this setup)."""
    float(run(1, *args))  # compile + warm
    t1 = min(_t(run, 1, args) for _ in range(2))
    n = 8
    while n < _MAX_REP:
        tn = _t(run, n, args)
        if tn - t1 >= _TARGET_LOOP_S:
            return n
        dt = max((tn - t1) / (n - 1), 1e-7)
        n = min(max(int(1.3 * _TARGET_LOOP_S / dt), n * 4), _MAX_REP)
    return _MAX_REP


def _slope(run, args, rep_hi):
    """One slope sample: (T(hi) - T(lo)) / (hi - lo), cancelling every
    fixed per-call cost (dispatch, tunnel RPC, scalar readback)."""
    rep_lo = max(1, rep_hi // 4)
    t_lo = _t(run, rep_lo, args)
    t_hi = _t(run, rep_hi, args)
    return max((t_hi - t_lo) / (rep_hi - rep_lo), 1e-9)


def _time_fn(fn, args, rep=None, rounds=3):
    """Median per-iteration device time of fn(*args), adaptive rep count.

    The device behind the tunnel is shared: throughput drifts, so each
    estimate is the median of `rounds` slope samples.
    """
    run = _make_runner(fn, args)
    rep_hi = _calibrate(run, args) if rep is None else rep
    samples = sorted(_slope(run, args, rep_hi) for _ in range(rounds))
    return samples[len(samples) // 2]


def _compare(ours_fn, ref_fn, args, rounds=3):
    """Interleaved A/B timing: per-round (ours, ref) slope pairs taken
    back-to-back so device-throughput drift cancels in the ratio; returns
    (dt_ours, dt_ref, vs_baseline) with the per-round median ratio."""
    run_o = _make_runner(ours_fn, args)
    run_r = _make_runner(ref_fn, args)
    rep_o = _calibrate(run_o, args)
    rep_r = _calibrate(run_r, args)
    pairs = [(_slope(run_o, args, rep_o), _slope(run_r, args, rep_r))
             for _ in range(rounds)]
    ratios = sorted(r / o for o, r in pairs)
    vs = ratios[len(ratios) // 2]
    dts_o = sorted(o for o, _ in pairs)
    dts_r = sorted(r for _, r in pairs)
    return (dts_o[len(dts_o) // 2], dts_r[len(dts_r) // 2], vs)


def main():
    import jax
    import jax.numpy as jnp

    M = N = K = 1024
    flops = 2.0 * M * N * K
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.bfloat16)

    # framework kernel (autotuned over a few carver hints)
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel
    best_ours = None
    for cfg in ({"block_M": 256, "block_N": 256, "block_K": 512},
                {"block_M": 512, "block_N": 256, "block_K": 256},
                {"block_M": 256, "block_N": 512, "block_K": 512},
                {"block_M": 128, "block_N": 256, "block_K": 1024}):
        try:
            k = matmul_kernel(M, N, K, in_dtype="bfloat16",
                              num_stages=2, **cfg)
            dt = _time_fn(k.func, (a, b), rep=30)
            if best_ours is None or dt < best_ours:
                best_ours = dt
        except Exception as e:
            print(f"# config {cfg} failed: {e}", file=sys.stderr)
    assert best_ours is not None, "no framework config compiled"

    # hand-written Pallas baseline (same tile sweep)
    best_ref = None
    for bm, bn, bk in ((256, 256, 512), (512, 256, 256), (256, 512, 512)):
        try:
            ref = _hand_pallas_matmul(M, N, K, bm, bn, bk)
            dt = _time_fn(ref, (a, b), rep=30)
            if best_ref is None or dt < best_ref:
                best_ref = dt
        except Exception as e:
            print(f"# ref ({bm},{bn},{bk}) failed: {e}", file=sys.stderr)

    ours_tflops = flops / best_ours / 1e12
    ref_tflops = flops / best_ref / 1e12 if best_ref else float("nan")
    vs = ours_tflops / ref_tflops if best_ref else 0.0
    print(json.dumps({
        "metric": "bf16 GEMM 1024^3 (tile DSL vs hand-written Pallas)",
        "value": round(ours_tflops, 2),
        "unit": "TFLOPS",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
