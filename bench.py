"""Headline benchmark (BASELINE config #1): bf16 GEMM through the tile
pipeline vs a hand-written Pallas matmul on the same chip.

Prints ONE JSON line:
  {"metric": ..., "value": <TFLOPS of the framework kernel>,
   "unit": "TFLOPS", "vs_baseline": <framework / hand-written Pallas>}

vs_baseline >= 0.9 means within 10% of the hand-written kernel (the
BASELINE.md target); > 1.0 means beating it.
"""

import functools
import json
import sys
import time

import numpy as np


def _hand_pallas_matmul(M, N, K, bm, bn, bk):
    """The hand-written Pallas baseline the framework competes against."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(a, b, o, acc):
        k = pl.program_id(2)

        @pl.when(k == 0)
        def _():
            acc[...] = jnp.zeros_like(acc)

        acc[...] += jnp.dot(a[...], b[...],
                            preferred_element_type=jnp.float32)

        @pl.when(k == pl.num_programs(2) - 1)
        def _():
            o[...] = acc[...].astype(o.dtype)

    return pl.pallas_call(
        kern,
        grid=(M // bm, N // bn, K // bk),
        in_specs=[pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                  pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=(M * K + K * N + M * N) * 2,
            transcendentals=0),
    )


def _time_fn(fn, args, rep):
    """In-graph loop timing (optimization_barrier-tied, see profiler)."""
    import jax

    def body(i, carry):
        outs = fn(*carry)
        outs = outs if isinstance(outs, tuple) else (outs,)
        tied = jax.lax.optimization_barrier(tuple(carry) + outs)
        return tuple(tied[:len(carry)])

    @functools.partial(jax.jit, static_argnames=("n",))
    def run(n, *ins):
        return jax.lax.fori_loop(0, n, body, tuple(ins))

    r = run(3, *args)
    np.asarray(r[0]).ravel()[:1]  # force
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        r = run(rep, *args)
        np.asarray(r[0]).ravel()[:1]
        best = min(best, (time.perf_counter() - t0) / rep)
    return best


def main():
    import jax
    import jax.numpy as jnp

    M = N = K = 1024
    flops = 2.0 * M * N * K
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.bfloat16)

    # framework kernel (autotuned over a few carver hints)
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel
    best_ours = None
    for cfg in ({"block_M": 256, "block_N": 256, "block_K": 512},
                {"block_M": 512, "block_N": 256, "block_K": 256},
                {"block_M": 256, "block_N": 512, "block_K": 512},
                {"block_M": 128, "block_N": 256, "block_K": 1024}):
        try:
            k = matmul_kernel(M, N, K, in_dtype="bfloat16",
                              num_stages=2, **cfg)
            dt = _time_fn(k.func, (a, b), rep=30)
            if best_ours is None or dt < best_ours:
                best_ours = dt
        except Exception as e:
            print(f"# config {cfg} failed: {e}", file=sys.stderr)
    assert best_ours is not None, "no framework config compiled"

    # hand-written Pallas baseline (same tile sweep)
    best_ref = None
    for bm, bn, bk in ((256, 256, 512), (512, 256, 256), (256, 512, 512)):
        try:
            ref = _hand_pallas_matmul(M, N, K, bm, bn, bk)
            dt = _time_fn(ref, (a, b), rep=30)
            if best_ref is None or dt < best_ref:
                best_ref = dt
        except Exception as e:
            print(f"# ref ({bm},{bn},{bk}) failed: {e}", file=sys.stderr)

    ours_tflops = flops / best_ours / 1e12
    ref_tflops = flops / best_ref / 1e12 if best_ref else float("nan")
    vs = ours_tflops / ref_tflops if best_ref else 0.0
    print(json.dumps({
        "metric": "bf16 GEMM 1024^3 (tile DSL vs hand-written Pallas)",
        "value": round(ours_tflops, 2),
        "unit": "TFLOPS",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
