"""Headline benchmarks: the 5 BASELINE.md configs, framework kernels vs
hand-written Pallas / XLA baselines, interleaved A/B on the same chip.

Prints ONE JSON line per config:
  {"metric": ..., "value": <TFLOPS>, "unit": "TFLOPS",
   "vs_baseline": <baseline_ms / ours_ms>, "latency_ms": ...,
   "baseline_ms": ...}
and a final headline line (the flagship GEMM) carrying
"geomean_vs_baseline" over every config that ran.

vs_baseline >= 0.9 means within 10% of the baseline (the BASELINE.md
target); > 1.0 means beating it.

Process architecture (round-4 hardening; do not regress): the parent
process NEVER imports jax — each config runs in its own bounded
subprocess (--child), so a tunnel worker that faults mid-sweep kills at
most that one config's process. The parent re-emits each child's JSON
line as it completes, probes the worker between configs from fresh
subprocesses (bounded by a total dead-probe budget), waits out a
post-fault recovery window at startup instead of aborting, and always
prints the headline geomean over whatever ran: partial capture, rc=0.

Methodology (hard-learned across rounds; do not regress):
- Timing is the SLOPE of wall time vs in-loop rep count: T(hi)-T(lo) over
  hi-lo cancels every fixed per-call cost (~65 ms tunnel RPC here).
- Rep counts are ALWAYS calibrated until the loop body dominates; the
  calibration's first call is also the compile+warmup. Never pass a fixed
  rep count: an uncalibrated loop makes the slope noise-dominated and
  round 2 shipped a 2.1e6-TFLOPS artifact that way.
- A/B pairs are taken back-to-back per round (interleaved) so shared-chip
  throughput drift cancels in the ratio.
- Every result is validated: a slope at the clamp floor or a TFLOPS above
  the chip's physical peak raises BenchError instead of being printed.
- Outputs are cross-checked numerically before timing: a wrong kernel's
  latency is meaningless.
"""

import argparse
import functools
import json
import math
import os
import sys
import time

import numpy as np

_TARGET_LOOP_S = 0.6   # in-loop work per timed call; >> fixed-cost noise
_MAX_REP = 200_000
_SLOPE_FLOOR = 1e-9    # clamp floor: a slope here means the measurement broke


class BenchError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# chip model (for physical-plausibility caps)
# ---------------------------------------------------------------------------

def _chip_peak_tflops():
    """Dense peak matmul TFLOPS (+ HBM GB/s) by device kind."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return {"bf16": 197.0, "f32": 98.0, "i8": 394.0, "hbm_gbs": 819.0}
    if "v5p" in kind or "v5" in kind:
        return {"bf16": 459.0, "f32": 229.0, "i8": 918.0,
                "hbm_gbs": 2765.0}
    if "v4" in kind:
        return {"bf16": 275.0, "f32": 137.0, "i8": 275.0,
                "hbm_gbs": 1228.0}
    if "v6" in kind or "trillium" in kind:
        return {"bf16": 918.0, "f32": 459.0, "i8": 1836.0,
                "hbm_gbs": 1640.0}
    return {"bf16": 1000.0, "f32": 500.0, "i8": 2000.0,
            "hbm_gbs": 4000.0}  # unknown: loose


# ---------------------------------------------------------------------------
# timing core
# ---------------------------------------------------------------------------

def _make_runner(fn, args):
    """jit(run(n, *args)): n iterations of fn inside one fori_loop, outputs
    tied into the carry with optimization_barrier so XLA can't hoist or
    dead-code them, reduced to ONE scalar fetched to host (4-byte
    transfer) to synchronize. n is a RUNTIME value: one compile serves
    every rep count. (`jax.block_until_ready` does not synchronize on the
    tunneled platform; the value fetch is the only honest fence.)"""
    import jax
    import jax.numpy as jnp

    def body(i, carry):
        outs = fn(*carry)
        outs = outs if isinstance(outs, tuple) else (outs,)
        tied = jax.lax.optimization_barrier(tuple(carry) + outs)
        return tuple(tied[:len(carry)]), tied[len(carry)]

    @jax.jit
    def run(n, *ins):
        outs0 = fn(*ins)
        outs0 = outs0 if isinstance(outs0, tuple) else (outs0,)
        _, last = jax.lax.fori_loop(
            0, n, lambda i, c: body(i, c[0]), (tuple(ins), outs0[0]))
        return jnp.asarray(last).ravel()[0].astype(jnp.float32)

    return run


def _t(run, n, args):
    # armed-faults-only visit of the device.dispatch site (children
    # only; the env check keeps the common path import- and branch-free
    # and the jax-free parent never times): the timed loop is where a
    # bench run actually touches the device, so this is where a chaos
    # run kills the "worker" mid-config
    if os.environ.get("TL_TPU_FAULTS"):
        from tilelang_mesh_tpu.resilience import faults as _faults
        _faults.maybe_fail("device.dispatch", where="bench.timing")
    t0 = time.perf_counter()
    float(run(n, *args))
    return time.perf_counter() - t0


def _calibrate(run, args):
    """Grow n until the loop body accounts for ~_TARGET_LOOP_S of wall time
    beyond the fixed per-call cost. The first call is compile + warmup."""
    float(run(1, *args))  # compile + warm — NEVER skip
    t1 = min(_t(run, 1, args) for _ in range(2))
    n = 8
    while n < _MAX_REP:
        tn = _t(run, n, args)
        if tn - t1 >= _TARGET_LOOP_S:
            return n
        dt = max((tn - t1) / (n - 1), 1e-7)
        n = min(max(int(1.3 * _TARGET_LOOP_S / dt), n * 4), _MAX_REP)
    return _MAX_REP


def _slope(run, args, rep_hi):
    """One slope sample: (T(hi) - T(lo)) / (hi - lo), cancelling every
    fixed per-call cost (dispatch, tunnel RPC, scalar readback)."""
    rep_lo = max(1, rep_hi // 4)
    t_lo = _t(run, rep_lo, args)
    t_hi = _t(run, rep_hi, args)
    return max((t_hi - t_lo) / (rep_hi - rep_lo), _SLOPE_FLOOR)


def _time_fn(fn, args, rep=None, rounds=3):
    """Median per-iteration device time of fn(*args). `rep` is accepted
    for the benchmark/ suite scripts but treated as a floor only — the
    count is still calibrated so the loop dominates fixed costs."""
    run = _make_runner(fn, args)
    rep_hi = _calibrate(run, args)
    if rep is not None:
        rep_hi = max(rep_hi, rep)
    samples = sorted(_slope(run, args, rep_hi) for _ in range(rounds))
    dt = samples[len(samples) // 2]
    if dt <= _SLOPE_FLOOR * 2:
        raise BenchError(f"slope clamped ({dt:.2e}s): measurement broken")
    return dt


def _slope_stats(samples_s, rep):
    """Latency digest of per-iteration slope samples: p50/p90/p99/MAD in
    ms plus sample/rep counts — the noise information the perf-diff gate
    (tools/perfdiff.py) keys its median+MAD thresholds on. Delegates to
    the profiler's ONE digest implementation (imported lazily: only
    children import the package; the parent orchestrator must never
    touch jax)."""
    from tilelang_mesh_tpu.profiler import _stats_ms
    st = _stats_ms([x * 1e3 for x in samples_s], reps=rep)
    return {
        "p50_ms": round(st["p50_ms"], 5),
        "p90_ms": round(st["p90_ms"], 5),
        "p99_ms": round(st["p99_ms"], 5),
        "mad_ms": round(st["mad_ms"], 6),
        "samples": st["samples"],
        "reps": st["reps"],
    }


def _compare(ours_fn, ref_fn, args, rounds=3, ref_args=None):
    """Interleaved A/B timing: per-round (ours, ref) slope pairs taken
    back-to-back so device-throughput drift cancels in the ratio; returns
    (dt_ours, dt_ref, vs_baseline, stats_ours, stats_ref) with the
    per-round median ratio and the per-side latency digests."""
    ref_args = args if ref_args is None else ref_args
    run_o = _make_runner(ours_fn, args)
    run_r = _make_runner(ref_fn, ref_args)
    rep_o = _calibrate(run_o, args)
    rep_r = _calibrate(run_r, ref_args)
    pairs = [(_slope(run_o, args, rep_o), _slope(run_r, ref_args, rep_r))
             for _ in range(rounds)]
    for o, r in pairs:
        if o <= _SLOPE_FLOOR * 2 or r <= _SLOPE_FLOOR * 2:
            raise BenchError(
                f"slope clamped (ours={o:.2e}s ref={r:.2e}s): "
                "measurement broken")
    ratios = sorted(r / o for o, r in pairs)
    vs = ratios[len(ratios) // 2]
    dts_o = sorted(o for o, _ in pairs)
    dts_r = sorted(r for _, r in pairs)
    st_o = _slope_stats((o for o, _ in pairs), rep_o)
    st_r = _slope_stats((r for _, r in pairs), rep_r)
    return (dts_o[len(dts_o) // 2], dts_r[len(dts_r) // 2], vs, st_o, st_r)


def _pick_best(cands, check, what, rounds=1):
    """Shared candidate sweep: each (name, build, args) entry is built
    lazily (build() -> fn, so one candidate's compile failure only skips
    that candidate) and numerically validated via check(out) BEFORE it
    may win on speed. Returns the fastest passing (name, fn, args)."""
    best = None
    for name, build, args in cands:
        try:
            fn = build()
            if check is not None:
                check(fn(*args))
            dt = _time_fn(fn, args, rounds=rounds)
            if best is None or dt < best[1]:
                best = ((name, fn, args), dt)
        except Exception as e:
            # a DEVICE loss is not a candidate failure: the worker is
            # gone, and grinding through the remaining candidates would
            # burn the whole per-config budget on a dead device — let
            # the config-level failover re-run the sweep on the next
            # backend instead
            from tilelang_mesh_tpu.resilience.errors import classify
            if classify(e) == "device_loss":
                raise
            print(f"# {what} '{name}' failed: {str(e)[:200]}",
                  file=sys.stderr)
    if best is None:
        raise BenchError(f"no {what} candidate ran")
    return best[0]


def _gemm_vmem_est(bm, bn, bk, ns):
    """Scoped-VMEM estimate of a GEMM tile (bf16 operands, f32 acc):
    used to order sweep candidates smallest-first so the riskiest shape
    cannot take out the sweep (a Mosaic fault kills the subprocess and
    the shared tunnel worker)."""
    return (bm * bk + bk * bn) * 2 * ns + bm * bn * 4


def _check_close(ours, ref, rel_tol):
    """Relative Frobenius error — a wrong kernel's latency is
    meaningless, so every config cross-checks before timing."""
    a = np.asarray(ours, np.float32)
    b = np.asarray(ref, np.float32)
    denom = float(np.linalg.norm(b.ravel())) or 1.0
    err = float(np.linalg.norm((a - b).ravel())) / denom
    if not math.isfinite(err) or err > rel_tol:
        raise BenchError(f"numeric mismatch: rel err {err:.3e} > {rel_tol}")


# ---------------------------------------------------------------------------
# baselines (hand-written Pallas / XLA)
# ---------------------------------------------------------------------------

def _hand_pallas_matmul(M, N, K, bm, bn, bk, dtype="bfloat16",
                        out_dtype=None):
    """The hand-written Pallas baseline the framework competes against."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    out_dtype = out_dtype or dtype

    def kern(a, b, o, acc):
        k = pl.program_id(2)

        @pl.when(k == 0)
        def _():
            acc[...] = jnp.zeros_like(acc)

        acc[...] += jnp.dot(a[...], b[...],
                            preferred_element_type=jnp.float32)

        @pl.when(k == pl.num_programs(2) - 1)
        def _():
            o[...] = acc[...].astype(o.dtype)

    return pl.pallas_call(
        kern,
        grid=(M // bm, N // bn, K // bk),
        in_specs=[pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                  pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.dtype(out_dtype)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=(M * K + K * N + M * N) * 2,
            transcendentals=0),
    )


# ---------------------------------------------------------------------------
# configs — each returns dict(metric, flops, peak_class, ours, ref, args,
#                             [ref_args], rel_tol)
# ---------------------------------------------------------------------------

def cfg_gemm(M, N, K, dtype="bfloat16"):
    import jax.numpy as jnp
    from tilelang_mesh_tpu.carver import MatmulTemplate
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.dtype(dtype))
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.dtype(dtype))

    hints = MatmulTemplate(M, N, K, dtype).hints(2)
    cfgs = [dict(h.config, num_stages=2) for h in hints] or [
        {"block_M": 256, "block_N": 256, "block_K": 512, "num_stages": 2}]
    # pipeline-depth variant of the top hint: gemm_large measured 0.87x
    # of the MXU roofline at ns=2 — deeper staging may close DMA
    # bubbles. The carver's budget filter assumed ns=2, so re-check the
    # ns=3 footprint against the measured Mosaic fault boundary (a
    # fault kills the child AND the shared tunnel worker)
    from tilelang_mesh_tpu.carver import auto_arch
    ns3 = dict(cfgs[0], num_stages=3)
    if _gemm_vmem_est(ns3["block_M"], ns3["block_N"], ns3["block_K"], 3) \
            <= 0.42 * auto_arch().vmem_bytes:
        cfgs.append(ns3)
    cfgs.sort(key=lambda c: _gemm_vmem_est(
        c["block_M"], c["block_N"], c["block_K"], c["num_stages"]))

    want = jnp.dot(a, b, preferred_element_type=jnp.float32)
    check = functools.partial(_check_close, ref=want, rel_tol=3e-2)

    _, ours, _ = _pick_best(
        [(str(c),
          lambda c=c: matmul_kernel(M, N, K, in_dtype=dtype, **c).func,
          (a, b)) for c in cfgs],
        check, "framework gemm")
    _, ref, _ = _pick_best(
        [(str(blk),
          lambda blk=blk: _hand_pallas_matmul(M, N, K, *blk, dtype),
          (a, b))
         # the baseline ignores num_stages: dedup by block shape so the
         # ns=2/ns=3 pair doesn't compile+time the same kernel twice
         for blk in dict.fromkeys(
             (c["block_M"], c["block_N"], c["block_K"]) for c in cfgs)],
        check, "hand-pallas gemm")
    return dict(metric=f"{dtype} GEMM {M}x{N}x{K} (tile DSL vs "
                       f"hand-written Pallas)",
                flops=2.0 * M * N * K, peak_class="bf16",
                ours=ours, ref=ref, args=(a, b), rel_tol=3e-2,
                checked=True)


def _mesh_scope_summary(kern, *args):
    """Drive a few scoped dispatches of a compiled mesh kernel through
    ``MeshKernel.__call__`` with tl-mesh-scope on and return the compact
    mesh summary the bench record embeds (per-link ledger bytes,
    conservation verdict, sampled comm latency) — the runtime
    counterpart of the record's static comm-opt wire-byte fields."""
    import os
    from tilelang_mesh_tpu.observability import meshscope as _ms
    prev = os.environ.get("TL_TPU_MESH_SCOPE")
    os.environ["TL_TPU_MESH_SCOPE"] = "1"
    try:
        _ms.reset()
        for _ in range(3):
            kern(*args)
        s = _ms.mesh_snapshot()
        return {
            "schema": s["schema"], "mesh": s["mesh"],
            "dispatches": s["dispatches"],
            "conservation_ok": bool(s["conservation"]["ok"]),
            "ledger_bytes": s["conservation"]["ledger_bytes"],
            "links": {k: v["bytes"] for k, v in s["links"].items()},
            "top_links": s["top_links"],
            "latency": s["latency"],
        }
    except Exception as e:  # noqa: BLE001 — the summary is additive,
        return {"error": f"{type(e).__name__}: {e}"}  # never a bench kill
    finally:
        _ms.reset()
        if prev is None:
            os.environ.pop("TL_TPU_MESH_SCOPE", None)
        else:
            os.environ["TL_TPU_MESH_SCOPE"] = prev


def cfg_mesh_allreduce_smoke(rows=2, cols=2, n=64, m=128):
    """CI perf-smoke config for the mesh comm path: a 2x2 mesh program
    whose two same-payload all_reduces are deduped+fused into ONE psum
    by the collective optimizer (transform/comm_opt.py), timed against
    the same math written directly as a jax shard_map psum. CPU-safe:
    the parent injects --xla_force_host_platform_device_count for this
    config, so the comm-opt win is visible in the perf trajectory and
    the CI perf-smoke job without TPU hardware."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    import tilelang_mesh_tpu as tilelang
    import tilelang_mesh_tpu.language as T
    from tilelang_mesh_tpu.parallel import mesh_config
    from tilelang_mesh_tpu.parallel.device_mesh import (make_jax_mesh,
                                                        shard_map_compat)

    if len(jax.devices()) < rows * cols:
        raise BenchError(
            f"mesh_allreduce_smoke needs {rows * cols} devices, have "
            f"{len(jax.devices())} (set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={rows * cols})")

    mesh_t = (rows, cols)
    shard = T.MeshShardingPolicy(cross_mesh_dim=0)
    with mesh_config(rows, cols):
        @T.prim_func
        def k(A: T.MeshTensor((rows * cols * n, m), shard, mesh_t,
                              "float32"),
              B: T.MeshTensor((rows * cols * n, 1), shard, mesh_t,
                              "float32"),
              C: T.MeshTensor((rows * cols * n, 1), shard, mesh_t,
                              "float32")):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment((n, m), "float32")
                o1 = T.alloc_fragment((n, 1), "float32")
                o2 = T.alloc_fragment((n, 1), "float32")
                T.copy(A, x)
                # identical payloads: the optimizer dedupes them into one
                # wire transfer (slot sharing), halving post-opt bytes
                T.comm.all_reduce(x, o1, "sum", "all", dim=1)
                T.comm.all_reduce(x, o2, "sum", "all", dim=1)
                T.copy(o1, B)
                T.copy(o2, C)
        kern = tilelang.compile(k, target=f"cpu-mesh[{rows}x{cols}]")

    mesh = make_jax_mesh(rows, cols)
    spec = P(("x", "y"), None)

    def local(xs):
        s = lax.psum(jnp.sum(xs, axis=1, keepdims=True), ("x", "y"))
        return s, s

    ref = jax.jit(shard_map_compat(local, mesh, (spec,), (spec, spec)))

    rng = np.random.default_rng(11)
    a = jnp.asarray(rng.standard_normal((rows * cols * n, m)) * 0.1,
                    jnp.float32)
    extra = {}
    opt = kern.get_comm_opt() or {}
    if opt:
        extra = {"comm_pre_opt_wire_bytes": opt.get("pre_wire_bytes"),
                 "comm_post_opt_wire_bytes": opt.get("post_wire_bytes"),
                 "comm_hops_saved": opt.get("hops_saved")}
    extra["mesh"] = _mesh_scope_summary(kern, a)
    return dict(metric=f"mesh all_reduce smoke {rows}x{cols} n={n} m={m} "
                       f"(tile DSL comm-opt vs jax shard_map psum)",
                flops=2.0 * rows * cols * n * m,
                bytes=float(rows * cols * n * m * 4), peak_class="f32",
                ours=kern.func, ref=ref, args=(a,), rel_tol=1e-5,
                extra=extra)


def cfg_gemm_smoke(M=256, N=256, K=256, dtype="float32"):
    """CI perf-smoke config: tiny GEMM against the plain XLA dot
    reference. Unlike cfg_gemm it needs no hand-Pallas baseline, so it
    runs anywhere — CPU interpret mode included — which is what the
    ci.yml perf-smoke step and the checked-in perf baseline use."""
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel

    rng = np.random.default_rng(7)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.dtype(dtype))
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.dtype(dtype))
    ours = matmul_kernel(M, N, K, in_dtype=dtype, out_dtype="float32",
                         block_M=128, block_N=128, block_K=128).func

    def ref(a_, b_):
        return jnp.dot(a_, b_, preferred_element_type=jnp.float32)

    return dict(metric=f"{dtype} GEMM {M}x{N}x{K} smoke "
                       f"(tile DSL vs XLA dot)",
                flops=2.0 * M * N * K, peak_class="f32",
                ours=ours, ref=ref, args=(a, b), rel_tol=3e-2)


def cfg_dispatch_overhead_smoke(M=128, calls=300):
    """CI perf-smoke config for the host dispatch fast path
    (jit/dispatch.py; docs/host_dispatch.md): a small GEMM whose device
    time is tiny, so the per-call Python marshalling cost dominates the
    request latency — exactly the regime ROADMAP item 5 targets. The
    kernel is driven through ``JITKernel.__call__`` twice, once with
    ``TL_TPU_FAST_DISPATCH=0`` (the legacy marshalling loop) and once
    on the precompiled dispatch plan; both overhead distributions come
    out of the shared ``dispatch.overhead`` histogram via
    ``Profiler.dispatch_overhead``. Headline value = warm calls/sec on
    the fast path; ``vs_baseline`` = legacy/fast overhead p50 ratio
    (the acceptance gate wants >= 2). CPU-safe: runs identically on the
    host platform and on a real TPU."""
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel

    kern = matmul_kernel(M, M, M, in_dtype="float32", out_dtype="float32",
                         block_M=M, block_N=M, block_K=M)
    rng = np.random.default_rng(13)
    a = jnp.asarray(rng.standard_normal((M, M)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((M, M)) * 0.1, jnp.float32)
    prof = kern.get_profiler()

    def run():
        prev = os.environ.get("TL_TPU_FAST_DISPATCH")
        try:
            os.environ["TL_TPU_FAST_DISPATCH"] = "0"
            legacy = prof.dispatch_overhead(calls=calls,
                                            input_tensors=(a, b))
            os.environ["TL_TPU_FAST_DISPATCH"] = "1"
            fast = prof.dispatch_overhead(calls=calls,
                                          input_tensors=(a, b))
        finally:
            if prev is None:
                os.environ.pop("TL_TPU_FAST_DISPATCH", None)
            else:
                os.environ["TL_TPU_FAST_DISPATCH"] = prev
        f50 = fast["overhead_p50_us"] or 0.0
        l50 = legacy["overhead_p50_us"] or 0.0
        ratio = l50 / f50 if f50 else None
        noise_us = max(fast["overhead_iqr2_us"] or 0.0,
                       legacy["overhead_iqr2_us"] or 0.0)
        return {
            "value": fast["calls_per_sec"],
            "unit": "calls/s",
            # >1 means the fast path beats legacy marshalling
            "vs_baseline": round(ratio, 4) if ratio else None,
            # perf-diff gate inputs: the FAST path's host overhead is
            # the guarded latency (a regression here is a fast-path
            # regression, which is what this config exists to catch)
            "latency_ms": round(f50 / 1e3, 6),
            "baseline_ms": round(l50 / 1e3, 6),
            "latency_p50_ms": round(f50 / 1e3, 6),
            "latency_p90_ms": round((fast["overhead_p90_us"] or 0.0) / 1e3,
                                    6),
            "latency_p99_ms": round((fast["overhead_p99_us"] or 0.0) / 1e3,
                                    6),
            "latency_mad_ms": round(noise_us / 1e3, 6),
            "latency_samples": fast["overhead_samples"],
            "reps": calls,
            "baseline_mad_ms": round((legacy["overhead_iqr2_us"] or 0.0)
                                     / 1e3, 6),
            "host_overhead_p50_us_fast": f50,
            "host_overhead_p50_us_legacy": l50,
            "overhead_ratio": round(ratio, 4) if ratio else None,
            "calls_per_sec_fast": fast["calls_per_sec"],
            "calls_per_sec_legacy": legacy["calls_per_sec"],
        }

    return dict(metric=f"host dispatch overhead {M}x{M}x{M} GEMM "
                       f"(fast dispatch plan vs legacy marshalling)",
                custom_run=run)


def cfg_vmem_repack_smoke(M=256, N=256, reps=60):
    """CI perf-smoke config for the tile-opt VMEM re-packing rewrite
    (transform/tile_opt.py; docs/tile_opt.md): a two-stage elementwise
    kernel whose stages each stage a full (M, N) f32 tile through their
    OWN scratch buffer. Unpacked, the kernel keeps two resident tiles;
    the repack rewrite proves the lifetimes disjoint (the TL005
    interval model) and aliases both onto one arena slot, so the same
    tiles fit half the scratch budget. Headline value =
    unpacked/repacked resident-scratch footprint ratio (straight from
    ``attrs["tile_opt"]["repack"]``); ``vs_baseline`` = unpacked /
    repacked latency (≈1 on CPU interpret — the footprint is the
    hardware win, Mosaic allocates one buffer where it allocated two).
    The record also carries the real ops-library evidence: the adjacent
    nibble-unpack T.Parallel regions of ``ops/dequant_gemm`` fused by
    the same pass (``ops_kernel``/``ops_rewrites``). CPU-safe; run
    with TL_TPU_SELFCHECK=1 the first calls also differentially check
    the optimized lowerings against TL_TPU_TILE_OPT=0."""
    import time

    import jax
    import jax.numpy as jnp
    import tilelang_mesh_tpu as tilelang
    import tilelang_mesh_tpu.language as T

    @T.prim_func
    def repack_smoke(A: T.Tensor((M, N), "float32"),
                     B: T.Tensor((M, N), "float32"),
                     O1: T.Tensor((M, N), "float32"),
                     O2: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            t1 = T.alloc_shared((M, N), "float32")
            t2 = T.alloc_shared((M, N), "float32")
            T.copy(A, t1)
            for i, j in T.Parallel(M, N):
                t1[i, j] = t1[i, j] * 2.0 + 1.0
            T.copy(t1, O1)
            T.copy(B, t2)
            for i, j in T.Parallel(M, N):
                t2[i, j] = t2[i, j] * 3.0 - 1.0
            T.copy(t2, O2)

    k_opt = tilelang.compile(repack_smoke)
    k_raw = tilelang.compile(repack_smoke,
                             pass_configs={"tl.tpu.tile_opt": "0"})
    rng = np.random.default_rng(11)
    a = jnp.asarray(rng.standard_normal((M, N)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((M, N)) * 0.1, jnp.float32)

    def timed(kern):
        jax.block_until_ready(kern(a, b))           # warm (compile)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(kern(a, b))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        med = ts[len(ts) // 2]
        mad = sorted(abs(t - med) for t in ts)[len(ts) // 2]
        return med, mad, ts

    def run():
        ro = k_opt(a, b)
        rr = k_raw(a, b)
        for x, y in zip(ro, rr):
            _check_close(x, y, 1e-6)
        rec_opt = k_opt.artifact.attrs.get("tile_opt") or {}
        rp = rec_opt.get("repack") or {}
        pre, post = rp.get("pre_bytes", 0), rp.get("post_bytes", 0)
        if not pre or post >= pre:
            raise BenchError(
                "vmem_repack_smoke: the repack rewrite did not fire "
                f"(pre={pre}B post={post}B) — the config exists to "
                "measure it")
        t_opt, mad_o, _ = timed(k_opt)
        t_raw, mad_r, _ = timed(k_raw)
        # real ops-library evidence: the same pass suite on dequant_gemm
        from tilelang_mesh_tpu.ops.dequant_gemm import dequant_gemm_kernel
        ops_rec = dequant_gemm_kernel(256, 256, 512).artifact.attrs.get(
            "tile_opt") or {}
        return {
            "value": round(pre / post, 4),
            "unit": "x smaller scratch",
            "vs_baseline": round(t_raw / t_opt, 4) if t_opt else None,
            "latency_ms": round(t_opt * 1e3, 4),
            "baseline_ms": round(t_raw * 1e3, 4),
            "latency_p50_ms": round(t_opt * 1e3, 4),
            "latency_p90_ms": round(t_opt * 1e3, 4),
            "latency_p99_ms": round(t_opt * 1e3, 4),
            "latency_mad_ms": round(mad_o * 1e3, 4),
            "latency_samples": reps,
            "reps": reps,
            "baseline_mad_ms": round(mad_r * 1e3, 4),
            "scratch_bytes_unpacked": pre,
            "scratch_bytes_repacked": post,
            "tile_opt_rewrites": rec_opt.get("rewrites"),
            "ops_kernel": "dequant_gemm",
            "ops_rewrites": ops_rec.get("rewrites"),
        }

    return dict(metric=f"tile-opt VMEM repack smoke {M}x{N} f32 "
                       f"(repacked vs unpacked scratch footprint)",
                custom_run=run)


def cfg_dtype_narrow_smoke(M=128, N=128, reps=60):
    """CI perf-smoke config for the tile-opt dtype-narrowing rewrite
    (transform/tile_opt.py; docs/tile_opt.md): a five-stage elementwise
    chain over bounded O(1) values staged through f32 fragment scratch.
    The TL007/TL008 dual-track interpretation proves each intermediate's
    sound interval and accumulated error bound fit bfloat16, so
    ``TL_TPU_TILE_OPT=auto`` thins the scratch to half the bytes (the
    DMA-endpoint buffers stay f32 — narrowing never changes a wire
    dtype). Headline value = unnarrowed/narrowed resident scratch ratio,
    derived from the FEATURES_VERSION 2 ``vmem_occupancy`` feature of
    the two lowerings; ``vs_baseline`` = unnarrowed/narrowed latency
    (≈1 on CPU interpret — the footprint is the hardware win, plus
    halved VPU operand traffic Mosaic can exploit). Run under
    TL_TPU_SELFCHECK=1 the first optimized call is differentially
    checked against the TL_TPU_TILE_OPT=0 twin within bf16 tolerance.
    CPU-safe."""
    import time

    import jax
    import jax.numpy as jnp
    import tilelang_mesh_tpu as tilelang
    import tilelang_mesh_tpu.language as T

    @T.prim_func
    def narrow_smoke(A: T.Tensor((M, N), "float32"),
                     O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            src = T.alloc_shared((M, N), "float32")
            u1 = T.alloc_fragment((M, N), "float32")
            u2 = T.alloc_fragment((M, N), "float32")
            u3 = T.alloc_fragment((M, N), "float32")
            u4 = T.alloc_fragment((M, N), "float32")
            u5 = T.alloc_fragment((M, N), "float32")
            dst = T.alloc_shared((M, N), "float32")
            T.copy(A, src)
            # sigmoid bounds the chain's root in (0, 1) regardless of
            # the input range — everything downstream is then provably
            # O(1), which is what the narrowing proof needs
            for i, j in T.Parallel(M, N):
                u1[i, j] = T.sigmoid(src[i, j])
            for i, j in T.Parallel(M, N):
                u2[i, j] = u1[i, j] * u1[i, j]
            for i, j in T.Parallel(M, N):
                u3[i, j] = u2[i, j] * 0.5 + u1[i, j] * 0.25
            for i, j in T.Parallel(M, N):
                u4[i, j] = u3[i, j] * u3[i, j] * 0.5
            for i, j in T.Parallel(M, N):
                u5[i, j] = u4[i, j] * 0.5 + u3[i, j] * 0.125
            for i, j in T.Parallel(M, N):
                dst[i, j] = u5[i, j] * 2.0
            T.copy(dst, O)

    k_opt = tilelang.compile(narrow_smoke,
                             pass_configs={"tl.tpu.tile_opt": "auto"})
    k_raw = tilelang.compile(narrow_smoke,
                             pass_configs={"tl.tpu.tile_opt": "0"})
    rng = np.random.default_rng(13)
    # inputs in [-1, 1]: every stage stays O(1), exactly the regime the
    # narrowing proof's interval/error gates admit
    a = jnp.asarray(rng.uniform(-1.0, 1.0, (M, N)), jnp.float32)

    def scratch_bytes(kern):
        from tilelang_mesh_tpu.transform.plan import _DEFAULT_VMEM_BUDGET
        f = kern.artifact.attrs.get("features") or {}
        occ = float(f.get("vmem_occupancy") or 0.0)
        return round(occ * _DEFAULT_VMEM_BUDGET) - \
            int(f.get("vmem_block_bytes") or 0)

    def timed(kern):
        jax.block_until_ready(kern(a))              # warm (compile)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(kern(a))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        med = ts[len(ts) // 2]
        mad = sorted(abs(t - med) for t in ts)[len(ts) // 2]
        return med, mad

    def run():
        ro = k_opt(a)
        rr = k_raw(a)
        # the narrowed kernel rounds through bf16 internally: compare
        # within the bf16 band, same contract as the selfcheck
        _check_close(ro, rr, 2e-2)
        rec_opt = k_opt.artifact.attrs.get("tile_opt") or {}
        nar = rec_opt.get("narrow") or {}
        if not nar.get("buffers"):
            raise BenchError(
                "dtype_narrow_smoke: the narrow rewrite did not fire "
                f"(record: {nar}) — the config exists to measure it")
        pre, post = scratch_bytes(k_raw), scratch_bytes(k_opt)
        if not post or pre <= post:
            raise BenchError(
                f"dtype_narrow_smoke: no footprint win (pre={pre}B "
                f"post={post}B)")
        t_opt, mad_o = timed(k_opt)
        t_raw, mad_r = timed(k_raw)
        sched = rec_opt.get("sched") or {}
        return {
            "value": round(pre / post, 4),
            "unit": "x smaller scratch",
            "vs_baseline": round(t_raw / t_opt, 4) if t_opt else None,
            "latency_ms": round(t_opt * 1e3, 4),
            "baseline_ms": round(t_raw * 1e3, 4),
            "latency_p50_ms": round(t_opt * 1e3, 4),
            "latency_p90_ms": round(t_opt * 1e3, 4),
            "latency_p99_ms": round(t_opt * 1e3, 4),
            "latency_mad_ms": round(mad_o * 1e3, 4),
            "latency_samples": reps,
            "reps": reps,
            "baseline_mad_ms": round(mad_r * 1e3, 4),
            "scratch_bytes_unnarrowed": pre,
            "scratch_bytes_narrowed": post,
            "narrowed_buffers": nar.get("buffers"),
            "narrowed_bytes_saved": nar.get("bytes"),
            "narrow_proofs": nar.get("proofs"),
            "sched_chosen": sched.get("chosen"),
            "sched_predicted_ms": sched.get("predicted_ms"),
            "tile_opt_rewrites": rec_opt.get("rewrites"),
        }

    return dict(metric=f"tile-opt dtype narrow smoke {M}x{N} f32->bf16 "
                       f"(narrowed vs unnarrowed scratch footprint)",
                custom_run=run)


def cfg_autotune_smoke(M_seed=128, M_target=256):
    """CI tune-smoke config for cost-model-guided autotuning
    (autotuner/cost_model.py + tune_cache.py; docs/autotuning.md): a
    seeded 8-config GEMM sweep run four ways in ONE child process with
    isolated cache dirs. (1) a cold model-mode sweep on a small seed
    bucket — full sweep by construction (the cold-model fallback), it
    seeds the fitted residual and the fleet tune cache; (2) a
    ``TL_TPU_TUNE=bruteforce`` sweep on the target bucket — the pre-model
    trial count and winner; (3) a warm model-mode sweep on the target
    bucket — the model ranks the space from the sibling bucket's samples
    and measures only the top-K + epsilon tail; (4) a fresh tuner on the
    target bucket with the legacy result cache bypassed — the fleet
    tune-cache warm start, which must measure ZERO trials. Headline
    value (= ``vs_baseline``, CI gate >= 2) is the measured-trial
    reduction of (3) vs (2); the record embeds the chosen-vs-bruteforce
    latency ratio so the perf-diff harness guards tuned-config QUALITY
    over time, not just trial count. CPU-safe."""
    import tempfile

    from tilelang_mesh_tpu.autotuner import AutoTuner
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel
    from tilelang_mesh_tpu.profiler import Profiler

    cfgs = [{"block_M": bm, "block_N": bn, "block_K": bk}
            for bm, bn, bk in [(32, 32, 32), (32, 64, 64), (64, 64, 64),
                               (64, 128, 128), (128, 128, 128),
                               (128, 32, 64), (64, 32, 128),
                               (128, 64, 32)]]
    kw = dict(in_dtype="float32", out_dtype="float32")

    def tuner(cache=True):
        # rep=3: sub-ms CPU trials are noisy enough that rep=2 lets the
        # measured ordering (and so the chosen-vs-brute quality ratio)
        # wander run to run
        return AutoTuner(matmul_kernel, cfgs, warmup=1, rep=3,
                         cache_results=cache)

    def run():
        # isolated cache dirs: the tune cache derives from the autotune
        # dir, so one env var isolates both tiers (this runs in the
        # per-config child process — the parent env is untouched)
        root = tempfile.mkdtemp(prefix="tltpu-bench-tune-")
        os.environ["TL_TPU_AUTOTUNE_CACHE_DIR"] = os.path.join(
            root, "autotune")
        os.environ.pop("TL_TPU_TUNE_CACHE_DIR", None)
        prev_mode = os.environ.pop("TL_TPU_TUNE", None)
        try:
            seed = tuner().run(M_seed, M_seed, M_seed, **kw)
            os.environ["TL_TPU_TUNE"] = "bruteforce"
            brute = tuner(cache=False).run(M_target, M_target, M_target,
                                           **kw)
            os.environ.pop("TL_TPU_TUNE", None)
            model = tuner().run(M_target, M_target, M_target, **kw)
            warm = tuner(cache=False).run(M_target, M_target, M_target,
                                          **kw)
        finally:
            if prev_mode is None:
                os.environ.pop("TL_TPU_TUNE", None)
            else:
                os.environ["TL_TPU_TUNE"] = prev_mode
        if seed.trials_measured != len(cfgs):
            raise BenchError(
                "autotune_smoke: the cold-model seed sweep must measure "
                f"every config ({seed.trials_measured}/{len(cfgs)})")
        if model.trials_measured >= brute.trials_measured:
            raise BenchError(
                "autotune_smoke: the warm model pruned nothing "
                f"({model.trials_measured} vs bruteforce "
                f"{brute.trials_measured}) — the config exists to "
                "measure the reduction")
        if not warm.from_cache or warm.trials_measured != 0:
            raise BenchError(
                "autotune_smoke: the fleet tune-cache warm start must "
                f"measure zero trials (measured {warm.trials_measured}, "
                f"from_cache={warm.from_cache})")
        reduction = brute.trials_measured / max(1, model.trials_measured)
        # noise floor for the perf-diff gate: re-measure the chosen
        # kernel a few times and take the median absolute deviation
        prof = Profiler(model.kernel)
        lats = sorted([model.latency_ms]
                      + [prof.do_bench(warmup=1, rep=2) for _ in range(3)])
        med = lats[len(lats) // 2]
        mad = sorted(abs(x - med) for x in lats)[len(lats) // 2]
        return {
            "value": round(reduction, 4),
            "unit": "x fewer measured trials",
            # >= 2 is the tune-smoke acceptance gate
            "vs_baseline": round(reduction, 4),
            # perf-diff gate inputs: the latency of the MODEL-CHOSEN
            # config vs the bruteforce winner's — a regression here means
            # pruning started discarding the real winners
            "latency_ms": round(model.latency_ms, 6),
            "baseline_ms": round(brute.latency_ms, 6),
            "latency_p50_ms": round(model.latency_ms, 6),
            "latency_p90_ms": round(max(lats), 6),
            "latency_p99_ms": round(max(lats), 6),
            "latency_mad_ms": round(max(mad, 1e-6), 6),
            "latency_samples": len(lats),
            "reps": len(cfgs),
            "baseline_mad_ms": round(max(mad, 1e-6), 6),
            "trials_measured_model": model.trials_measured,
            "trials_measured_bruteforce": brute.trials_measured,
            "trials_pruned": model.trials_pruned,
            "model_rank_agreement": model.model_agreement,
            "chosen_config": model.config,
            "bruteforce_config": brute.config,
            "chosen_vs_bruteforce": round(
                model.latency_ms / brute.latency_ms, 4)
            if brute.latency_ms else None,
            "warm_start_trials": warm.trials_measured,
            "seed_trials": seed.trials_measured,
        }

    return dict(metric=f"cost-model autotune smoke {M_target}^3 GEMM "
                       f"x{len(cfgs)} configs (model-guided trials vs "
                       f"bruteforce)",
                custom_run=run)


def cfg_serve_smoke(requests=64):
    """CI serve-smoke config for the serving engine (serving/;
    docs/serving.md): a seeded request storm through the
    continuous-batching scheduler on a tiny paged flash-decode
    workload. Headline value = served requests/sec with batching;
    ``vs_baseline`` = batched throughput over the SAME requests served
    unbatched (batch bucket 1) — the continuous-batching win the
    subsystem exists for (> 1 means batching pays). Every request must
    retire as ``result`` and the KV slabs must balance to zero or the
    config raises (a serving smoke that leaks or drops is a failure,
    not a slow run). CPU-safe: the decode kernels run identically on
    the host platform tiers."""
    from tilelang_mesh_tpu.observability import histogram as _h
    from tilelang_mesh_tpu.serving import (FlashDecodeWorkload,
                                           PagedKVAllocator,
                                           ServingEngine)

    def build_engine(batch_buckets, name):
        alloc = PagedKVAllocator(n_pages=256, page_size=8, heads=2,
                                 head_dim=64)
        wl = FlashDecodeWorkload(alloc, batch_buckets=batch_buckets,
                                 page_buckets=(2,))
        eng = ServingEngine(wl, name=name)
        eng.warmup()
        return eng

    def drive(eng):
        rng = np.random.default_rng(11)
        t0 = time.perf_counter()
        reqs = [eng.submit(context_tokens=16,
                           new_tokens=int(rng.integers(1, 3)),
                           seed=int(rng.integers(1 << 30)))
                for _ in range(requests)]
        eng.run()
        wall = time.perf_counter() - t0
        bad = [r.req_id for r in reqs if r.outcome != "result"]
        if bad:
            raise BenchError(f"serve_smoke: {len(bad)} request(s) did "
                             f"not retire as result: {bad[:8]}")
        if eng.workload.allocator.in_use:
            raise BenchError("serve_smoke: leaked KV slabs "
                             f"({eng.workload.allocator.leak_check()})")
        return wall, eng

    def _step_hist():
        h = _h.get_histogram("kernel.latency", kernel="serve.step",
                             source="serving")
        return None if h is None else _h.Histogram.from_dict(h.to_dict())

    def run():
        eng_b = build_engine((8,), "smoke-batched")
        eng_s = build_engine((1,), "smoke-sequential")
        before = _step_hist()
        wall_b, eng_b = drive(eng_b)
        win = _step_hist().minus(before)       # batched steps only
        wall_s, eng_s = drive(eng_s)

        def q_ms(h, q):
            v = h.quantile(q) if h and h.count else None
            return round(v * 1e3, 4) if v is not None else None

        iqr2 = None
        if win and win.count:
            iqr2 = round(((win.quantile(0.75) or 0)
                          - (win.quantile(0.25) or 0)) / 2 * 1e3, 5)
        return {
            "value": round(requests / wall_b, 1),
            "unit": "req/s",
            # >1 = continuous batching beats unbatched serving
            "vs_baseline": round(wall_s / wall_b, 4),
            "latency_ms": round(wall_b / max(eng_b.stats()["steps"], 1)
                                * 1e3, 4),
            "baseline_ms": round(wall_s * 1e3 / requests, 4),
            "latency_p50_ms": q_ms(win, 0.50),
            "latency_p90_ms": q_ms(win, 0.90),
            "latency_p99_ms": q_ms(win, 0.99),
            "latency_mad_ms": iqr2,
            "latency_samples": win.count if win else 0,
            "reps": requests,
            "baseline_mad_ms": iqr2,
            "requests": requests,
            "batched_steps": eng_b.stats()["steps"],
            "sequential_steps": eng_s.stats()["steps"],
            "req_per_sec_batched": round(requests / wall_b, 1),
            "req_per_sec_sequential": round(requests / wall_s, 1),
            "kv_pages_allocated":
                eng_b.workload.allocator.alloc_count,
        }

    return dict(metric=f"serving engine smoke: {requests} requests, "
                       f"paged flash decode (continuous batching vs "
                       f"unbatched)",
                custom_run=run)


def cfg_mesh_serve_smoke(requests=48):
    """CI mesh-serve-smoke config for elastic mesh serving
    (serving/mesh_workload.py; docs/serving.md): the request storm
    through a ``MeshDecodeWorkload`` whose decode step is sharded over
    a 2x2 host device mesh (``head_parallel``), with a mesh slice
    killed mid-drive so the record carries REAL reshard accounting
    (layout ladder walked, KV migrated byte-conserved). Headline value
    = served req/s on the elastic mesh path ACROSS the reshard;
    ``vs_baseline`` = that against the same requests on the single-host
    ``no_sharding`` workload. Sharding a tiny decode over host devices
    buys no speed — the gate is the CONTRACT: every request must
    retire ``result``, KV slabs must balance to zero, and the slice
    kill must produce >= 1 reshard, or the config raises. CPU-safe:
    the mesh is forced host devices (``_config_env``)."""
    from tilelang_mesh_tpu.observability import histogram as _h
    from tilelang_mesh_tpu.resilience import inject
    from tilelang_mesh_tpu.serving import (FlashDecodeWorkload,
                                           MeshDecodeWorkload,
                                           PagedKVAllocator,
                                           ServingEngine, serving_state)

    def build_engine(mesh, name):
        alloc = PagedKVAllocator(n_pages=256, page_size=8, heads=2,
                                 head_dim=64)
        if mesh:
            wl = MeshDecodeWorkload(alloc, batch_buckets=(8,),
                                    page_buckets=(2,))
        else:
            wl = FlashDecodeWorkload(alloc, batch_buckets=(8,),
                                     page_buckets=(2,))
        eng = ServingEngine(wl, name=name)
        eng.warmup()
        return eng

    def drive(eng, kill_at=None):
        rng = np.random.default_rng(11)
        t0 = time.perf_counter()
        reqs = []
        for i in range(requests):
            reqs.append(eng.submit(context_tokens=16,
                                   new_tokens=int(rng.integers(1, 3)),
                                   seed=int(rng.integers(1 << 30))))
            if kill_at is not None and i == kill_at:
                with inject("serve.shard", kind="unreachable", times=1):
                    eng.step()
        eng.run()
        wall = time.perf_counter() - t0
        bad = [r.req_id for r in reqs if r.outcome != "result"]
        if bad:
            raise BenchError(f"mesh_serve_smoke: {len(bad)} request(s) "
                             f"did not retire as result: {bad[:8]}")
        if eng.workload.allocator.in_use:
            raise BenchError(
                "mesh_serve_smoke: leaked KV slabs "
                f"({eng.workload.allocator.leak_check()})")
        return wall, eng

    def _step_hist():
        h = _h.get_histogram("kernel.latency", kernel="serve.step",
                             source="serving")
        return None if h is None else _h.Histogram.from_dict(h.to_dict())

    def run():
        import os
        # scope on for the drive: the straggler probe sweeps feed the
        # tl-mesh-scope skew baseline, so the record's mesh summary
        # carries real sweep accounting
        os.environ["TL_TPU_MESH_SCOPE"] = "1"
        eng_m = build_engine(True, "mesh-smoke")
        first_layout = eng_m.workload.layout.name
        before = _step_hist()
        wall_m, eng_m = drive(eng_m, kill_at=requests // 2)
        win = _step_hist().minus(before)       # mesh steps only
        if eng_m.reshards < 1:
            raise BenchError("mesh_serve_smoke: the mid-drive slice "
                             "kill produced no reshard")
        eng_s = build_engine(False, "mesh-smoke-ref")
        wall_s, eng_s = drive(eng_s)

        def q_ms(h, q):
            v = h.quantile(q) if h and h.count else None
            return round(v * 1e3, 4) if v is not None else None

        iqr2 = None
        if win and win.count:
            iqr2 = round(((win.quantile(0.75) or 0)
                          - (win.quantile(0.25) or 0)) / 2 * 1e3, 5)
        from tilelang_mesh_tpu import observability as _obs
        serving = _obs.metrics_summary()["serving"]
        return {
            "value": round(requests / wall_m, 1),
            "unit": "req/s",
            # mesh-elastic throughput over the single-host reference
            # (informational on CPU; the contract is the gate)
            "vs_baseline": round(wall_s / wall_m, 4),
            "latency_ms": round(wall_m / max(eng_m.stats()["steps"], 1)
                                * 1e3, 4),
            "baseline_ms": round(wall_s
                                 / max(eng_s.stats()["steps"], 1)
                                 * 1e3, 4),
            "latency_p50_ms": q_ms(win, 0.50),
            "latency_p90_ms": q_ms(win, 0.90),
            "latency_p99_ms": q_ms(win, 0.99),
            "latency_mad_ms": iqr2,
            "latency_samples": win.count if win else 0,
            "reps": requests,
            "baseline_mad_ms": iqr2,
            "requests": requests,
            # the elastic accounting the CI gate reads
            "layout_first": first_layout,
            "layout_final": eng_m.workload.layout.name,
            "layout_ladder": [r.name for r in eng_m.workload.ladder],
            "reshards": eng_m.reshards,
            "kv_pages_migrated": serving["kv_pages_migrated"],
            "shard_skew": serving_state().get("shard_skew"),
            "mesh_steps": eng_m.stats()["steps"],
            "single_host_steps": eng_s.stats()["steps"],
            "mesh": _serve_mesh_summary(),
        }

    def _serve_mesh_summary():
        try:
            from tilelang_mesh_tpu.observability import meshscope as _ms
            s = _ms.mesh_snapshot()
            return {"schema": s["schema"], "skew": s["skew"],
                    "dispatches": s["dispatches"]}
        except Exception as e:  # noqa: BLE001 — additive, never a kill
            return {"error": f"{type(e).__name__}: {e}"}

    return dict(metric=f"elastic mesh serving smoke: {requests} "
                       f"requests on a 2x2 host mesh, slice kill + "
                       f"live reshard (vs single-host decode)",
                custom_run=run)


def cfg_serve_prefill_smoke(requests=12, shared_pages=32):
    """CI serve-lifecycle config for the full-lifecycle serving path
    (serving/prefix_cache.py; docs/serving.md "Full-lifecycle
    serving"): ``requests`` requests sharing one ``shared_pages``-page
    system prompt are served twice — COLD (prefix cache off: every
    request pays the full O(prompt) chunked prefill) and WARM (a fresh
    prefix cache seeded by one request: every subsequent request
    restores the prompt's KV pages checksummed instead of recomputing
    them). Headline value AND ``vs_baseline`` = the warm-prefix
    speedup (cold wall / warm wall) — the CI gate is >= 2x. Every
    request must retire ``result`` with zero leaked slabs or the
    config raises. CPU-safe: prefill fill + page restore are
    host-side; the decode step runs identically on the host tiers."""
    import tempfile

    from tilelang_mesh_tpu.serving import (FlashDecodeWorkload,
                                           PagedKVAllocator,
                                           PrefixKVCache, ServingEngine)

    PS, H, D = 16, 4, 64
    shared = [int(t) for t in
              np.random.default_rng(23).integers(
                  0, 1 << 20, size=shared_pages * PS)]

    def build_engine(prefix_cache, name):
        alloc = PagedKVAllocator(n_pages=1024, page_size=PS, heads=H,
                                 head_dim=D)
        wl = FlashDecodeWorkload(alloc, batch_buckets=(1,),
                                 page_buckets=(2,),
                                 prefix_cache=prefix_cache)
        eng = ServingEngine(wl, name=name)
        eng.warmup()
        return eng

    def drive(eng, n, label):
        rng = np.random.default_rng(11)
        t0 = time.perf_counter()
        reqs = [eng.submit(context_tokens=len(shared),
                           prompt_tokens=list(shared), new_tokens=1,
                           seed=int(rng.integers(1 << 30)))
                for _ in range(n)]
        eng.run()
        wall = time.perf_counter() - t0
        bad = [r.req_id for r in reqs if r.outcome != "result"]
        if bad:
            raise BenchError(f"serve_prefill_smoke[{label}]: {len(bad)} "
                             f"request(s) did not retire as result: "
                             f"{bad[:8]}")
        if eng.workload.allocator.in_use:
            raise BenchError(
                f"serve_prefill_smoke[{label}]: leaked KV slabs "
                f"({eng.workload.allocator.leak_check()})")
        return wall

    def run():
        # cold: every request pays the full chunked prefill
        eng_cold = build_engine(False, "prefill-cold")
        wall_cold = drive(eng_cold, requests, "cold")
        # warm: a fresh hermetic prefix tier, seeded by ONE request
        cache = PrefixKVCache(
            root=tempfile.mkdtemp(prefix="tltpu-prefix-smoke-"),
            page_budget=4 * shared_pages)
        eng_warm = build_engine(cache, "prefill-warm")
        drive(eng_warm, 1, "seed")            # the fleet's first tenant
        walls = [drive(eng_warm, requests, "warm") for _ in range(2)]
        wall_warm = min(walls)
        mad = max(abs(walls[0] - walls[1]) / 2, 1e-6)
        stats = cache.stats()
        if stats["hits"] < requests:
            raise BenchError(
                f"serve_prefill_smoke: expected >= {requests} prefix "
                f"hits, got {stats['hits']}")
        speedup = wall_cold / wall_warm
        return {
            "value": round(speedup, 4),
            "unit": "x warm-prefix speedup",
            # >= 2 is the serve-lifecycle acceptance gate
            "vs_baseline": round(speedup, 4),
            "latency_ms": round(wall_warm / requests * 1e3, 4),
            "baseline_ms": round(wall_cold / requests * 1e3, 4),
            "latency_p50_ms": round(wall_warm / requests * 1e3, 4),
            "latency_p90_ms": round(max(walls) / requests * 1e3, 4),
            "latency_p99_ms": round(max(walls) / requests * 1e3, 4),
            "latency_mad_ms": round(mad / requests * 1e3, 5),
            "latency_samples": len(walls),
            "reps": requests,
            "baseline_mad_ms": round(mad / requests * 1e3, 5),
            "requests": requests,
            "shared_prompt_tokens": len(shared),
            "prefix_hits": stats["hits"],
            "prefix_bytes_saved": stats["bytes_saved"],
            "prefill_ms_per_request_cold": round(
                wall_cold / requests * 1e3, 4),
            "restore_ms_per_request_warm": round(
                wall_warm / requests * 1e3, 4),
        }

    return dict(metric=f"full-lifecycle serving smoke: {requests} "
                       f"requests sharing a {shared_pages * PS}-token "
                       f"system prompt (warm prefix restore vs cold "
                       f"chunked prefill)",
                custom_run=run)


def cfg_flash(D, S=2048, B=2, H=16, causal=True):
    import jax.numpy as jnp
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as jax_flash)
    from tilelang_mesh_tpu.ops.flash_attention import mha_fwd_kernel

    rng = np.random.default_rng(1)
    shp = (B, H, S, D)
    q = jnp.asarray(rng.standard_normal(shp) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal(shp) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal(shp) * 0.3, jnp.bfloat16)
    sm = 1.0 / math.sqrt(D)

    def ref(q, k, v):
        return jax_flash(q, k, v, causal=causal, sm_scale=sm)

    ref_out = ref(q, k, v)
    check = functools.partial(_check_close, ref=ref_out, rel_tol=3e-2)
    # Candidate ladder from the carver's roofline-ranked policy (its
    # scoped-VMEM budget excludes the configs that fault the TPU worker,
    # e.g. (512,512) at d=128); every candidate is still numerically
    # cross-checked before it can win.
    from tilelang_mesh_tpu.carver import FlashAttentionTemplate
    hints = FlashAttentionTemplate(S, S, D, batch_heads=B * H,
                                   causal=causal).hints(4)
    cands = [(h.config["block_M"], h.config["block_N"]) for h in hints]
    _, kern_fn, _ = _pick_best(
        [(f"({bm},{bn})",
          lambda bm=bm, bn=bn: mha_fwd_kernel(
              B, H, S, S, D, block_M=min(bm, S), block_N=min(bn, S),
              causal=causal, sm_scale=sm, dtype="bfloat16",
              num_stages=2).func,
          (q, k, v)) for bm, bn in cands],
        check, f"flash d={D}")

    # causal halves the realized flops
    flops = 4.0 * B * H * S * S * D * (0.5 if causal else 1.0)
    return dict(metric=f"flash-attn MHA fwd d={D} S={S} causal={causal} "
                       f"(tile DSL vs jax pallas flash)",
                flops=flops, peak_class="bf16",
                ours=kern_fn, ref=ref, args=(q, k, v), rel_tol=3e-2,
                checked=True)


def cfg_fp8_gemm(M=4096, N=4096, K=4096):
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel

    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.float8_e4m3fn)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.float8_e4m3fn)

    kern = matmul_kernel(M, N, K, block_M=256, block_N=256, block_K=512,
                         in_dtype="float8_e4m3fn", out_dtype="float32")
    ref = _hand_pallas_matmul(M, N, K, 256, 256, 512, "float8_e4m3fn",
                              out_dtype="float32")
    return dict(metric=f"fp8(e4m3) GEMM {M}x{N}x{K} (tile DSL vs "
                       f"hand-written Pallas)",
                flops=2.0 * M * N * K, peak_class="i8",
                ours=kern.func, ref=ref, args=(a, b), rel_tol=1e-1)


def cfg_w4a16(M=4096, N=4096, K=4096, gs=512):
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.dequant_gemm import (dequant_gemm_kernel,
                                                    dequant_matmul_twopass)
    from tilelang_mesh_tpu.quantize.quantization import (
        dequantize_int4_planar_ref, quantize_int4_planar)

    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.bfloat16)
    w = rng.standard_normal((K, N)).astype(np.float32) * 0.1
    gs = min(gs, K // 2)
    packed_np, scales_np = quantize_int4_planar(w, group_size=gs)
    packed = jnp.asarray(packed_np)
    scales = jnp.asarray(scales_np)

    K2 = K // 2
    G2 = K2 // gs
    a_planar = a.reshape(M, 2, K2)
    s3 = scales.reshape(2, G2, N)
    want = np.asarray(a, np.float32) @ dequantize_int4_planar_ref(
        packed_np, scales_np, group_size=gs)

    check = functools.partial(_check_close, ref=want, rel_tol=4e-2)

    # framework side: fused tile kernel vs two-pass (dequant kernel +
    # large-tile GEMM) — the fused form wins skinny-M, two-pass wins
    # compute-bound prefill. The two-pass GEMM tile/pipeline is swept:
    # the r3 capture lost 7.6% to the XLA baseline with the single
    # hand-picked (1024,1024,512,ns2) shape
    def _twopass(bm, bn, bk, ns):
        return lambda a_, p_, s_: dequant_matmul_twopass(
            a_, p_, s_, block_M=bm, block_N=bn, block_K=bk, dq_block=gs,
            num_stages=ns)

    # smallest scoped-VMEM first — a Mosaic fault kills the whole config
    # subprocess AND the shared worker, so the riskiest shapes run last;
    # the historically faulting fused kernel runs at the very end
    tp_shapes = sorted(((1024, 1024, 512, 2),
                        (1024, 1024, 512, 3),
                        (512, 1024, 1024, 2),
                        (1024, 512, 1024, 2),
                        (512, 2048, 512, 2)),
                       key=lambda s: _gemm_vmem_est(*s))
    o_name, ours, args = _pick_best(
        [(f"twopass[{bm}x{bn}x{bk},ns{ns}]",
          functools.partial(_twopass, bm, bn, bk, ns),
          (a, packed, scales))
         for bm, bn, bk, ns in tp_shapes] +
        [("fused",
          lambda: dequant_gemm_kernel(M, N, K, block_M=512, block_N=512,
                                      block_K2=gs, group_size=gs,
                                      in_dtype="bfloat16").func,
          (a_planar, packed, s3))],
        check, "w4a16 framework")

    # baseline side: hand-written Pallas fused dequant-GEMM vs XLA
    # dequant+matmul — take the stronger
    def hand_pallas(bm=512, bn=512):
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kern(alo, ahi, p, s, o, acc):
            kk = pl.program_id(2)

            @pl.when(kk == 0)
            def _():
                acc[...] = jnp.zeros_like(acc)

            pi = p[...].astype(jnp.int32)
            sl = s[0, kk, :][None, :]
            sh = s[1, kk, :][None, :]
            bl = (((pi & 0xF).astype(jnp.float32) - 8.0) * sl
                  ).astype(jnp.bfloat16)
            bh = (((pi >> 4) & 0xF).astype(jnp.float32) - 8.0) * sh
            bh = bh.astype(jnp.bfloat16)
            acc[...] += jnp.dot(alo[...], bl,
                                preferred_element_type=jnp.float32)
            acc[...] += jnp.dot(ahi[...], bh,
                                preferred_element_type=jnp.float32)

            @pl.when(kk == pl.num_programs(2) - 1)
            def _():
                o[...] = acc[...].astype(o.dtype)

        return pl.pallas_call(
            kern,
            grid=(M // bm, N // bn, K2 // gs),
            in_specs=[
                pl.BlockSpec((bm, gs), lambda i, j, k: (i, k)),
                pl.BlockSpec((bm, gs), lambda i, j, k: (i, k)),
                pl.BlockSpec((gs, bn), lambda i, j, k: (k, j)),
                pl.BlockSpec((2, G2, bn), lambda i, j, k: (0, 0, j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
            out_shape=jax.ShapeDtypeStruct((M, N), jnp.bfloat16),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
        )

    def xla_ref(a_, p_, s_):
        lo = (p_ & 0xF).astype(jnp.float32) - 8.0
        hi = (p_ >> 4).astype(jnp.float32) - 8.0
        sl = jnp.repeat(s_[0], gs, axis=0)
        sh = jnp.repeat(s_[1], gs, axis=0)
        bl = (lo * sl).astype(jnp.bfloat16)
        bh = (hi * sh).astype(jnp.bfloat16)
        bd = jnp.concatenate([bl, bh], axis=0)
        return jnp.dot(a_, bd,
                       preferred_element_type=jnp.float32
                       ).astype(jnp.bfloat16)

    r_name, ref, ref_args = _pick_best(
        [("hand-pallas-fused", hand_pallas,
          (a_planar[:, 0, :], a_planar[:, 1, :], packed, s3)),
         ("xla-dequant-dot", lambda: xla_ref, (a, packed, s3))],
        check, "w4a16 baseline")

    return dict(metric=f"w4a16 dequant GEMM {M}x{N}x{K} gs={gs} (tile DSL "
                       f"[{o_name}] vs strongest of hand-Pallas/XLA "
                       f"[{r_name}])",
                flops=2.0 * M * N * K, peak_class="bf16",
                ours=ours, ref=ref, args=args, ref_args=ref_args,
                rel_tol=4e-2, checked=True)


def cfg_w4a8(M=4096, N=4096, K=4096):
    """int4-weight x int8-activation GEMM on the int8 MXU path (2x bf16
    rate; reference examples/dequantize_gemm/example_dequant_gemm_w4a8.py
    family). Baseline: XLA's own int8 pipeline over the same packed
    operands (unpack int4 -> int8, lax.dot int32 accum, f32 epilogue)."""
    import jax
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.dequant_gemm import (
        quantize_w4_per_channel, w4a8_gemm_kernel)

    rng = np.random.default_rng(9)
    x = rng.standard_normal((M, K)).astype(np.float32)
    w = rng.standard_normal((K, N)).astype(np.float32) * 0.1
    packed_np, sw_np = quantize_w4_per_channel(w)
    from tilelang_mesh_tpu.ops.bitnet import quantize_activations
    q, a_scale = quantize_activations(jnp.asarray(x))
    K2 = K // 2
    qp = q.reshape(M, 2, K2)
    packed = jnp.asarray(packed_np)
    sw = jnp.asarray(sw_np).reshape(1, N)
    sa = (1.0 / a_scale).astype(jnp.float32)

    def ref(qp_, packed_, sw_, sa_):
        p32 = packed_.astype(jnp.int32)
        lo = (p32 & 0xF).astype(jnp.int8) - 8
        hi = (p32 >> 4).astype(jnp.int8) - 8
        acc = (jax.lax.dot(qp_[:, 0, :], lo,
                           preferred_element_type=jnp.int32)
               + jax.lax.dot(qp_[:, 1, :], hi,
                             preferred_element_type=jnp.int32))
        return acc.astype(jnp.float32) * sa_ * sw_

    want = ref(qp, packed, sw, sa)
    check = functools.partial(_check_close, ref=want, rel_tol=1e-3)
    # the roofline model (benchmark/roofline.py) says the fused decode
    # is the bound at small block_M — per-tile B re-decode scales with
    # M/block_M — so the sweep leans into LARGE bm
    cfgs = [(min(bm, M), min(bn, N), min(bk2, K2), ns)
            for bm, bn, bk2, ns in
            ((128, 256, 512, 2), (256, 256, 512, 2), (128, 512, 512, 2),
             (256, 512, 256, 2), (256, 256, 1024, 2),
             (512, 512, 256, 2), (512, 256, 512, 2),
             (1024, 256, 256, 2))]
    cfgs = list(dict.fromkeys(cfgs))          # dedupe after clamping
    cfgs.sort(key=lambda c: _gemm_vmem_est(c[0], c[1], c[2] * 2, c[3]))
    _, ours, _ = _pick_best(
        [(f"[{bm}x{bn}xk2={bk2},ns{ns}]",
          lambda bm=bm, bn=bn, bk2=bk2, ns=ns: w4a8_gemm_kernel(
              M, N, K, bm, bn, bk2, ns).func,
          (qp, packed, sw, sa)) for bm, bn, bk2, ns in cfgs],
        check, "w4a8")

    return dict(metric=f"w4a8 int4xint8 GEMM {M}x{N}x{K} (tile DSL vs "
                       f"XLA int8 dequant+dot)",
                flops=2.0 * M * N * K, peak_class="i8",
                ours=ours, ref=jax.jit(ref), args=(qp, packed, sw, sa),
                rel_tol=1e-3, checked=True)


def cfg_mla_decode(B=4, H=128, S=4096, dc=512, dr=64):
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.mla import mla_decode, mla_decode_reference

    rng = np.random.default_rng(4)
    qc = jnp.asarray(rng.standard_normal((B, H, dc)) * 0.1, jnp.bfloat16)
    qr = jnp.asarray(rng.standard_normal((B, H, dr)) * 0.1, jnp.bfloat16)
    ckv = jnp.asarray(rng.standard_normal((B, S, dc)) * 0.1, jnp.bfloat16)
    kpe = jnp.asarray(rng.standard_normal((B, S, dr)) * 0.1, jnp.bfloat16)

    def ref(qc, qr, ckv, kpe):
        return mla_decode_reference(qc, qr, ckv, kpe)

    # few-split/large-chunk wins on v5e: one (H, S) score pass keeps the
    # MXU busy and the online-softmax VPU work off the critical path
    ref_out = ref(qc, qr, ckv, kpe)
    check = functools.partial(_check_close, ref=ref_out, rel_tol=4e-2)
    _, ours, _ = _pick_best(
        [(f"ns={ns} bn={bn}",
          lambda ns=ns, bn=bn: (lambda a, b, c, d: mla_decode(
              a, b, c, d, n_split=ns, block_N=bn)),
          (qc, qr, ckv, kpe))
         for ns, bn in ((1, min(4096, S)), (2, min(2048, S // 2)),
                        (4, min(1024, S // 4)), (8, min(512, S // 8)))],
        check, "mla decode")

    flops = 2.0 * B * H * S * (dc + dr) + 2.0 * B * H * S * dc
    return dict(metric=f"MLA decode B={B} H={H} S={S} dc={dc} dr={dr} "
                       f"(tile DSL split-KV vs XLA attention)",
                flops=flops, peak_class="bf16",
                ours=ours, ref=ref, args=(qc, qr, ckv, kpe), rel_tol=4e-2,
                checked=True)


def cfg_paged_decode(B=4, H=32, S=8192, D=128, page=128):
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.flash_decoding import (
        flash_decode_paged_pool, pages_to_hmajor)

    rng = np.random.default_rng(5)
    n_pages = B * S // page
    kv_pages = jnp.asarray(rng.standard_normal((n_pages, page, H, D)) * 0.1,
                           jnp.bfloat16)
    v_pages = jnp.asarray(rng.standard_normal((n_pages, page, H, D)) * 0.1,
                          jnp.bfloat16)
    table = jnp.asarray(
        rng.permutation(n_pages).reshape(B, S // page), jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)) * 0.1, jnp.bfloat16)
    sm = 1.0 / math.sqrt(D)
    # the serving system maintains the pool in the walkable H-major
    # layout persistently; building it here sits OUTSIDE the timed loop
    kp = pages_to_hmajor(kv_pages)
    vp = pages_to_hmajor(v_pages)

    def walk(q, kp, vp, tab):
        # in-kernel page walk: pages DMA'd at table-driven offsets, no
        # XLA gather pass over the cache
        return flash_decode_paged_pool(q, kp, vp, tab, page, sm_scale=sm,
                                       n_split=2)

    def gather(q, kpages, vpages, tab):
        from tilelang_mesh_tpu.ops.flash_decoding import flash_decode_paged
        return flash_decode_paged(q, kpages, vpages, tab, sm_scale=sm,
                                  block_N=1024, n_split=2)

    def ref(q, kpages, vpages, tab):
        k = jnp.take(kpages, tab, axis=0).reshape(B, S, H, D)
        v = jnp.take(vpages, tab, axis=0).reshape(B, S, H, D)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * sm
        import jax
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    want = ref(q, kv_pages, v_pages, table)
    check = functools.partial(_check_close, ref=want, rel_tol=4e-2)
    # hardware decides walk vs gather: the serial table-driven DMA walk
    # skips the cache-wide gather pass, but Mosaic pipelines the
    # contiguous kernel's fetches better — measure both, record both
    # head-to-head latencies (VERDICT r4 weak #4), race the winner
    cands = {}
    for nm, fn, fa in (("inkernel-walk", walk, (q, kp, vp, table)),
                       ("xla-gather", gather,
                        (q, kv_pages, v_pages, table))):
        try:    # per-candidate isolation, as _pick_best gives: one
                # faulting path must not zero the whole config
            check(fn(*fa))
            cands[nm] = (_time_fn(fn, fa, rounds=2), fn, fa)
        except Exception as e:
            print(f"# paged decode '{nm}' failed: {str(e)[:200]}",
                  file=sys.stderr)
    if not cands:
        raise BenchError("no paged decode candidate ran")
    o_name = min(cands, key=lambda n: cands[n][0])
    _, ours, args = cands[o_name]
    # a failed candidate's key is OMITTED rather than recorded as
    # float('nan'): json.dumps emits NaN as a non-standard token that
    # breaks strict JSON consumers (ADVICE r5)
    extra = {}
    if "inkernel-walk" in cands:
        extra["walk_ms"] = round(cands["inkernel-walk"][0] * 1e3, 4)
    if "xla-gather" in cands:
        extra["gather_ms"] = round(cands["xla-gather"][0] * 1e3, 4)

    # decode is bandwidth-bound: the mandatory traffic is one pass over
    # the K and V caches (+ negligible q/o); report achieved GB/s
    dsize = jnp.dtype(jnp.bfloat16).itemsize
    kv_bytes = 2.0 * B * S * H * D * dsize
    return dict(metric=f"paged flash-decode B={B} H={H} S={S} D={D} "
                       f"({o_name} vs XLA gather+attention, KV GB/s)",
                flops=4.0 * B * H * S * D, bytes=kv_bytes,
                peak_class="bf16",
                ours=ours, ref=ref, args=args,
                ref_args=(q, kv_pages, v_pages, table), rel_tol=4e-2,
                checked=True,
                extra=extra)


def cfg_mamba2_chunk(B=8, S=4096, H=80, P=64, N=128):
    """Mamba2 SSD chunk scan — the reference's published-numbers family
    (/root/reference/benchmark/mamba2/README.md: batch=8 heads=80 dim=64
    dstate=128, 126.5-135.7 TFLOPs on H800). Ours = the tile-DSL kernel
    (ops/mamba2.py); baseline = the same chunk-parallel SSD algorithm in
    plain jax left to XLA (ops/mamba2.mamba2_chunk_scan_xla). FLOPs use
    the reference README's formula (intra-chunk causal half + state
    output term) for cross-table comparability."""
    import jax
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.mamba2 import (mamba2_chunk_scan,
                                              mamba2_chunk_scan_xla)

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((B, S, H, P)) * 0.3, jnp.bfloat16)
    dt = jnp.asarray(rng.uniform(0.01, 0.1, (B, S, H)), jnp.float32)
    A = jnp.asarray(-rng.uniform(0.5, 1.5, (H,)), jnp.float32)
    Bm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.bfloat16)
    Cm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.bfloat16)

    chunk_ref = 256
    ref256 = jax.jit(functools.partial(mamba2_chunk_scan_xla,
                                       chunk=chunk_ref))
    want = ref256(x, dt, A, Bm, Cm)
    # the baseline also gets its best chunk (candidates cross-check
    # against each other — chunk-size invariance is pinned in
    # tests/test_mamba2.py); chunk=256 reuses the already-compiled fn
    _, ref, _ = _pick_best(
        [("xla chunk=128",
          lambda: jax.jit(functools.partial(mamba2_chunk_scan_xla,
                                            chunk=128)),
          (x, dt, A, Bm, Cm)),
         ("xla chunk=256", lambda: ref256, (x, dt, A, Bm, Cm))],
        functools.partial(_check_close, ref=want, rel_tol=1e-2),
        "mamba2 XLA baseline")
    check = functools.partial(_check_close, ref=want, rel_tol=5e-2)
    _, ours, _ = _pick_best(
        [(f"chunk={c}",
          lambda c=c: (lambda *a: mamba2_chunk_scan(*a, chunk=c)),
          (x, dt, A, Bm, Cm)) for c in (128, 256)],
        check, "mamba2 chunk scan")

    flops = (2.0 * B * S * chunk_ref * H * P * 0.5
             + 2.0 * B * S * H * P * N)
    return dict(metric=f"mamba2 SSD chunk scan B={B} S={S} H={H} P={P} "
                       f"N={N} (tile DSL vs XLA chunked SSD)",
                flops=flops, peak_class="bf16",
                ours=ours, ref=ref, args=(x, dt, A, Bm, Cm), rel_tol=5e-2,
                checked=True)


def cfg_gdn_fwd(B=8, H=16, Tt=4096, K=128, V=128):
    """Gated DeltaNet chunked forward: tile kernel (in-kernel WY with
    Neumann-doubling inverse, ops/gdn.py) vs the same chunk-parallel WY
    algorithm in plain jax/XLA (gdn_chunk_fwd). Reference family:
    examples/gdn (chunk_delta_h / wy_fast / chunk_o pieces). FLOPs count
    the algorithm's mandatory matmul work per token — causal intra-chunk
    QK^T and attn@V halves plus the three state-space products — and
    exclude the WY-inverse overhead (an implementation detail both
    sides pay)."""
    import jax
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.gdn import gdn_chunk_fwd, gdn_chunk_fwd_tl

    rng = np.random.default_rng(8)
    q = jnp.asarray(rng.standard_normal((B, H, Tt, K)) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, H, Tt, K)), jnp.float32)
    k = jnp.asarray(k / jnp.linalg.norm(k, axis=-1, keepdims=True),
                    jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, H, Tt, V)) * 0.3, jnp.bfloat16)
    g = jnp.asarray(rng.uniform(-0.2, 0.0, (B, H, Tt)), jnp.float32)
    beta = jnp.asarray(rng.uniform(0.1, 0.9, (B, H, Tt)), jnp.float32)

    ref = jax.jit(functools.partial(gdn_chunk_fwd, chunk_size=64))
    want = ref(q, k, v, g, beta)
    check = functools.partial(_check_close, ref=want, rel_tol=6e-2)
    o_name, ours, _ = _pick_best(
        [(f"chunk={c}",
          lambda c=c: (lambda *a: gdn_chunk_fwd_tl(*a, chunk_size=c)),
          (q, k, v, g, beta)) for c in (64, 128)],
        check, "gdn tile kernel")

    # FLOPs at a FIXED nominal chunk: the C*(K+V) term grows with the
    # chunk size, so counting the winner's chunk inflates TFLOPS when a
    # larger chunk wins and breaks comparability across sweeps (ADVICE
    # r5). Latency still picks the winner; vs_baseline is the headline.
    C_NOM = 64
    flops = B * H * Tt * (C_NOM * (K + V) + 6.0 * K * V)
    return dict(metric=f"GDN chunked fwd B={B} H={H} T={Tt} K={K} V={V} "
                       f"{o_name} (tile DSL vs XLA chunked WY)",
                flops=flops, peak_class="bf16",
                ours=ours, ref=ref, args=(q, k, v, g, beta), rel_tol=6e-2,
                checked=True)


def cfg_moe_grouped(E=8, M=512, K=2048, N=2048):
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.grouped_gemm import grouped_matmul

    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((E, M, K)) * 0.1, jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((E, K, N)) * 0.1, jnp.bfloat16)

    def ref(x, w):
        return jnp.einsum("emk,ekn->emn", x, w,
                          preferred_element_type=jnp.float32
                          ).astype(x.dtype)

    # per-expert matmul configs from the carver's roofline ranking, plus
    # hand-picked shapes and a pipeline-depth sweep: the r3 capture lost
    # 8% to XLA's batched matmul with the ranked-only candidates
    from tilelang_mesh_tpu.carver import MatmulTemplate
    cfgs = [dict(h.config, num_stages=2)
            for h in MatmulTemplate(M, N, K, "bfloat16").hints(3)]
    cfgs += [
        {"block_M": 512, "block_N": 2048, "block_K": 512, "num_stages": 2},
        {"block_M": 512, "block_N": 2048, "block_K": 512, "num_stages": 3},
        {"block_M": 512, "block_N": 1024, "block_K": 1024, "num_stages": 2},
        {"block_M": 256, "block_N": 2048, "block_K": 1024, "num_stages": 2},
        {"block_M": 512, "block_N": 1024, "block_K": 512, "num_stages": 3},
    ]
    cfgs = list({tuple(sorted(c.items())): c for c in cfgs}.values())

    cfgs.sort(key=lambda c: _gemm_vmem_est(
        c["block_M"], c["block_N"], c["block_K"], c["num_stages"]))
    want = ref(x, w)
    check = functools.partial(_check_close, ref=want, rel_tol=3e-2)
    _, ours, _ = _pick_best(
        [(str(c),
          lambda c=c: (lambda x_, w_: grouped_matmul(
              x_, w_, block_M=c["block_M"], block_N=c["block_N"],
              block_K=c["block_K"], num_stages=c["num_stages"])),
          (x, w)) for c in cfgs],
        check, "moe grouped")

    return dict(metric=f"fusedmoe grouped GEMM E={E} {M}x{N}x{K} "
                       f"(tile DSL vs XLA batched matmul)",
                flops=2.0 * E * M * N * K, peak_class="bf16",
                ours=ours, ref=ref, args=(x, w), rel_tol=3e-2,
                checked=True)


# ---------------------------------------------------------------------------

def run_config(name, build, peaks, rounds=3):
    """Build, cross-check, time, validate, and report one config."""
    spec = build()
    if "custom_run" in spec:
        # self-measuring config (dispatch_overhead_smoke): the builder
        # returns a callable producing the record fields directly —
        # host-side overhead is not a device-slope measurement, so the
        # interleaved A/B timing and peak-capping above don't apply
        rec = dict(spec["custom_run"]())
        rec.setdefault("metric", spec.get("metric", name))
        rec["config"] = name
        rec.update(spec.get("extra", {}))
        return rec
    args = spec["args"]
    ref_args = spec.get("ref_args", args)
    if not spec.get("checked"):
        # numeric cross-check; configs whose builder already validated
        # every candidate (checked=True) skip this second full-output
        # device eval + host transfer
        ours_out = spec["ours"](*args)
        ref_out = spec["ref"](*ref_args)
        ours_out = ours_out[0] if isinstance(ours_out, tuple) else ours_out
        ref_out = ref_out[0] if isinstance(ref_out, tuple) else ref_out
        _check_close(ours_out, ref_out, spec["rel_tol"])

    dt_o, dt_r, vs, st_o, st_r = _compare(spec["ours"], spec["ref"], args,
                                          rounds=rounds, ref_args=ref_args)
    if spec.get("bytes"):
        # bandwidth-bound config (decode): report achieved GB/s of the
        # mandatory traffic, capped against the chip's HBM bandwidth
        val = spec["bytes"] / dt_o / 1e9
        ref_val = spec["bytes"] / dt_r / 1e9
        unit = "GB/s"
        cap = peaks["hbm_gbs"] * 1.1
    else:
        val = spec["flops"] / dt_o / 1e12
        ref_val = spec["flops"] / dt_r / 1e12
        unit = "TFLOPS"
        cap = peaks[spec["peak_class"]] * 1.1
    if val > cap or ref_val > cap:
        raise BenchError(
            f"{val:.1f} / {ref_val:.1f} (baseline) {unit} exceeds "
            f"physical peak {cap:.0f}: measurement broken")
    peak = cap / 1.1
    rec = {
        "metric": spec["metric"],
        "value": round(val, 2),
        "unit": unit,
        "vs_baseline": round(vs, 4),
        "latency_ms": round(dt_o * 1e3, 4),
        "baseline_ms": round(dt_r * 1e3, 4),
        # latency distribution + noise (perf-diff gate inputs)
        "latency_p50_ms": st_o["p50_ms"],
        "latency_p90_ms": st_o["p90_ms"],
        "latency_p99_ms": st_o["p99_ms"],
        "latency_mad_ms": st_o["mad_ms"],
        "latency_samples": st_o["samples"],
        "reps": st_o["reps"],
        "baseline_mad_ms": st_r["mad_ms"],
        # roofline: achieved fraction of this chip's relevant peak
        "peak": round(peak, 1),
        "utilization": round(val / peak, 4) if peak else None,
        "config": name,
    }
    rec.update(spec.get("extra", {}))
    return rec


def _attach_sol(rec: dict, name: str) -> dict:
    """With TL_TPU_SOL=1 in the child's environment, embed the
    config's speed-of-light summary into the benchmark record: the
    dominant (most-sampled) kernel's achieved vs roofline-predicted
    latency, SoL %, and bottleneck term, plus the number of profiled
    kernels. Must run BEFORE _attach_observability — that helper
    resets the whole observability state, SoL aggregates included."""
    try:
        from tilelang_mesh_tpu.observability import sol as _sol
        if not _sol.sol_enabled():
            return rec
        recs = _sol.sol_records()
        if not recs:
            return rec
        best = max(recs, key=lambda r: r.get("count") or 0)
        rec["sol"] = {
            "kernel": best["kernel"],
            "achieved_ms": best.get("achieved_ms"),
            "predicted_ms": best.get("predicted_ms"),
            "sol_pct": best.get("sol_pct"),
            "bottleneck": best.get("bottleneck"),
            "kernels": len(recs),
        }
        from tilelang_mesh_tpu.observability import trace_enabled
        if not trace_enabled():
            # per-config semantics for --in-process mode: without
            # tracing, _attach_observability won't reset for us (with
            # tracing, it resets AFTER writing the trace artifacts the
            # SoL rows must land in, so leave the state to it there)
            _sol.reset()
    except Exception as e:  # profiling must never take down a capture
        rec["sol"] = {"error": f"{type(e).__name__}: {e}"}
    return rec


def _attach_observability(rec: dict, name: str) -> dict:
    """With TL_TPU_TRACE=1 in the child's environment, export this
    config's trace (Chrome JSON + JSONL under TL_TPU_TRACE_DIR) and
    embed the artifact paths, the compile-time breakdown by lowering
    phase, cache tier statistics, and collective accounting into the
    benchmark record — every BENCH_r* line becomes self-documenting and
    a failed run leaves a span-attributable trail instead of nothing."""
    try:
        from tilelang_mesh_tpu.env import env
        from tilelang_mesh_tpu.observability import (LOWER_PHASES,
                                                     metrics_summary,
                                                     reset, trace_enabled,
                                                     write_chrome_trace,
                                                     write_jsonl)
        if not trace_enabled():
            return rec
        d = env.trace_dir()
        chrome = write_chrome_trace(d / f"bench_{name}.trace.json")
        jsonl = write_jsonl(d / f"bench_{name}.trace.jsonl")
        summ = metrics_summary()
        phase_ms = {ph: round(v["total_ms"], 3)
                    for ph, v in summ["spans"].items()
                    if ph in LOWER_PHASES}
        rec["observability"] = {
            "trace": str(chrome),
            "trace_jsonl": str(jsonl),
            "compile_phase_ms": phase_ms,
            "cache": summ["cache"],
            "collectives": summ["collectives"],
            "runtime": summ.get("runtime", {}),
        }
        # per-config semantics: the next config (--in-process mode runs
        # many in one process) must not inherit this one's spans/counters
        reset()
    except Exception as e:  # tracing must never take down a capture
        rec["observability"] = {"error": f"{type(e).__name__}: {e}"}
        _reset_tracer()
    return rec


def _backends_used(counters_raw: dict) -> list:
    """Backend names that built kernels this process, from the tracer's
    structured ``(name, labels) -> value`` counter map (sorted)."""
    return sorted({dict(labels).get("backend")
                   for (name, labels), _ in counters_raw.items()
                   if name == "backend.build"} - {None})


def _run_config_failover(name, builder, peaks, rounds, cfg_timeout):
    """run_config under the backend-registry failover contract: a
    config dying with a device-loss error (the worker died mid-config —
    surfaced from the timed loop, a kernel dispatch, or the candidate
    sweep) marks the serving backend unhealthy in the registry and
    re-runs ONCE; the rebuilt kernels' chain walks then land on the
    next healthy tier, so the sweep produces a record instead of
    burning the per-config budget on a dead device."""
    try:
        return _watchdog(
            lambda: run_config(name, builder, peaks, rounds=rounds),
            f"config {name}", cfg_timeout)
    except Exception as e:
        from tilelang_mesh_tpu.env import env as _tl_env
        from tilelang_mesh_tpu.resilience.errors import classify
        if classify(e) != "device_loss" or \
                _tl_env.TL_TPU_FALLBACK == "none":
            # fail-fast contract: fallback disabled means NO config
            # retry either — same rule the kernel layers apply
            raise
        import tilelang_mesh_tpu as tilelang
        from tilelang_mesh_tpu.codegen.backends import registry
        from tilelang_mesh_tpu.observability import get_tracer
        reg = registry()
        used = set(_backends_used(get_tracer().counters_raw()))
        # the tier that was serving = the CHAIN-earliest backend that
        # built kernels (a kernel may have degraded to a later tier;
        # alphabetical order would blame the fallback, not the primary)
        frm = next((b.name for b in reg.chain() if b.name in used),
                   sorted(used)[0] if used else "tpu-pallas")
        nxt = reg.next_healthy(reg.chain(), frm)
        if nxt is None:
            raise          # spent chain: don't poison the terminal tier
        reg.mark_unhealthy(frm, e)
        reg.note_failover(frm=frm, to=nxt.name, kernel=f"bench.{name}",
                          during="bench", error=e)
        print(f"# config {name}: device loss on backend {frm} "
              f"({type(e).__name__}: {str(e)[:160]}); retrying once on "
              f"{nxt.name}", file=sys.stderr, flush=True)
        # drop BOTH kernel tiers: the object cache and every factory
        # callsite cache — a cached kernel pins the dead backend's
        # jitted callable, and only a rebuild re-walks the chain
        tilelang.clear_cache()
        from tilelang_mesh_tpu.jit import clear_factory_caches
        clear_factory_caches()
        return _watchdog(
            lambda: run_config(name, builder, peaks, rounds=rounds),
            f"config {name} (failover)", cfg_timeout)


def _attach_backend_state(rec: dict) -> dict:
    """Name the execution tiers that served this config: the backends
    that built kernels (``backends_used``), the failover count, and the
    registry health snapshot — a hermetic/failed-over record says WHICH
    fallback produced its numbers. Must run BEFORE _attach_observability
    (which resets the tracer's counters)."""
    try:
        from tilelang_mesh_tpu.codegen.backends import registry
        from tilelang_mesh_tpu.observability import get_tracer
        raw = get_tracer().counters_raw()
        fo = sum(v for (name, _), v in raw.items()
                 if name == "backend.failover")
        rec["backends_used"] = _backends_used(raw)
        rec["backend_failovers"] = fo
        rec["backend_health"] = registry().snapshot()
    except Exception:  # accounting must never take down a capture
        pass
    return rec


def _reset_tracer() -> None:
    """Best-effort per-config tracer reset for the paths that never reach
    a successful _attach_observability export (failed configs in
    --in-process mode): without it, the NEXT config's trace would
    inherit this one's spans and counters.

    Known limit of --in-process (debugging) mode: a config abandoned by
    the watchdog leaves a zombie thread that may keep recording into
    later configs' traces after this reset. Per-config attribution is
    only guaranteed in the default subprocess mode, where the process
    boundary quarantines it."""
    try:
        from tilelang_mesh_tpu.observability import reset
        reset()
    except Exception:
        pass


def _watchdog(fn, what: str, timeout_s: float):
    """Run fn() on a daemon thread, bounded by timeout_s: a worker that
    dies mid-call HANGS the jax call (no error), so abandoning the
    thread is the only way to keep the bench moving. Fast failures are
    relayed as themselves; a hang raises TimeoutError naming `what`."""
    import queue
    import threading
    qq: "queue.Queue" = queue.Queue(maxsize=1)

    def _t():
        try:
            qq.put((True, fn()))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            qq.put((False, e))

    t = threading.Thread(target=_t, daemon=True)
    t.start()
    try:
        ok, val = qq.get(timeout=timeout_s)
    except queue.Empty:
        raise TimeoutError(
            f"{what} exceeded {timeout_s:.0f}s (worker wedged?); "
            f"abandoned") from None
    if not ok:
        raise val
    return val


def _probe_device(timeout_s: float):
    """Probe the default jax platform through the backend registry
    (tilelang_mesh_tpu.codegen.backends — ONE probe implementation for
    bench, jit, and the autotuner). Returns ``None`` when healthy, else
    the classified ``TLError``: a ``DeviceLossError`` for a dead worker,
    a ``TLTimeoutError`` for a wedged one (a kernel fault kills the
    tunnel's worker for many minutes and a backend-init attempt then
    HANGS, not errors — the registry's bounded probe abandons its
    thread). The verdict is cached in the registry's health state, so
    in-child consumers (kernel builds, failover walks) reuse it for the
    probe TTL instead of re-touching the device. NEVER touches jax on
    this thread: after a wedged probe, any jax call here would block on
    the same backend-init lock the abandoned probe thread holds."""
    from tilelang_mesh_tpu.codegen.backends import probe_default_device
    return probe_default_device(timeout_s, record=True)


def exit_code(strict: bool, n_failed: int) -> int:
    """Process exit code policy: partial sweeps stay green (driver
    capture mode) unless --strict (CI) is set and a config failed."""
    return 2 if (strict and n_failed) else 0


# Configs that run without TPU hardware (interpret / host platform):
# the CI perf-smoke job runs exactly these, and a sweep whose startup
# probe finds the TPU worker dead still runs them (on the host platform)
# instead of producing an empty artifact.
CPU_SAFE_CONFIGS = ("gemm_smoke", "dispatch_overhead_smoke",
                    "vmem_repack_smoke", "dtype_narrow_smoke",
                    "autotune_smoke",
                    "serve_prefill_smoke",
                    "mesh_allreduce_smoke",
                    "serve_smoke", "mesh_serve_smoke")


def _config_env(name: str, tpu_alive: bool) -> dict:
    """Per-config child-process env overrides: the mesh smoke config
    needs forced host devices for its 2x2 CPU mesh, and CPU-safe configs
    fall back to the host platform when the TPU worker is down."""
    over = {}
    if name in ("mesh_allreduce_smoke", "mesh_serve_smoke"):
        # these configs are DEFINED as host-device mesh smokes (their
        # checked-in baselines were captured on CPU devices): pin the
        # platform so a TPU host doesn't silently benchmark the mesh
        # on TPU against a CPU baseline, and force the host device
        # count their 2x2 meshes need
        over["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            over["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    if not tpu_alive and name in CPU_SAFE_CONFIGS:
        over["JAX_PLATFORMS"] = "cpu"
    return over


def _hermetic_env(name: str, device_loss_at=None) -> dict:
    """Child-process env for ``--hermetic``: pin the host platform, arm
    the ``device.probe`` fault so the TPU tier is dead inside the child
    too (the child's registry records it), give the chain both host
    tiers to fail over across, and — for the chaos driver — arm a
    one-shot ``device.dispatch`` loss inside the victim config."""
    over = {"JAX_PLATFORMS": "cpu", "TL_TPU_BENCH_HERMETIC": "1"}
    if not os.environ.get("TL_TPU_BACKENDS"):
        over["TL_TPU_BACKENDS"] = "tpu-pallas,host-xla,host-interpret"
    clauses = [os.environ.get("TL_TPU_FAULTS", "")]
    if "device.probe" not in clauses[0]:
        clauses.append("device.probe:kind=unreachable")
    if device_loss_at == name:
        clauses.append("device.dispatch:kind=unreachable:times=1")
    over["TL_TPU_FAULTS"] = ";".join(c for c in clauses if c)
    return over


def _config_builders(q: bool):
    """The sweep, riskiest last: a kernel fault kills the tunnel's TPU
    worker for many minutes, losing every config after it — the blast
    radius of the riskiest config must not include the others."""
    return [
        ("gemm_smoke", lambda: cfg_gemm_smoke()),
        ("dispatch_overhead_smoke", lambda: cfg_dispatch_overhead_smoke()),
        ("vmem_repack_smoke", lambda: cfg_vmem_repack_smoke()),
        ("dtype_narrow_smoke", lambda: cfg_dtype_narrow_smoke()),
        ("autotune_smoke", lambda: cfg_autotune_smoke()),
        ("mesh_allreduce_smoke", lambda: cfg_mesh_allreduce_smoke()),
        ("serve_smoke", lambda: cfg_serve_smoke()),
        ("mesh_serve_smoke", lambda: cfg_mesh_serve_smoke()),
        ("serve_prefill_smoke", lambda: cfg_serve_prefill_smoke()),
        ("gemm_quickstart", lambda: cfg_gemm(1024, 1024, 1024)),
        ("gemm_large", lambda: cfg_gemm(*(2048, 2048, 2048) if q
                                        else (8192, 8192, 4096))),
        ("flash_d64", lambda: cfg_flash(64, S=512 if q else 2048)),
        ("flash_d128", lambda: cfg_flash(128, S=512 if q else 2048)),
        ("flash_d128_full", lambda: cfg_flash(128, S=512 if q else 2048,
                                              causal=False)),
        ("fp8_gemm", lambda: cfg_fp8_gemm(*(1024,) * 3 if q
                                          else (4096,) * 3)),
        ("mla_decode", lambda: cfg_mla_decode(S=1024 if q else 4096)),
        ("mamba2_chunk", lambda: cfg_mamba2_chunk(
            *(2, 1024, 8, 64, 64) if q else (8, 4096, 80, 64, 128))),
        ("gdn_fwd", lambda: cfg_gdn_fwd(
            *(1, 4, 512, 64, 64) if q else (8, 16, 4096, 128, 128))),
        ("paged_decode", lambda: cfg_paged_decode(S=2048 if q else 8192)),
        ("moe_grouped", lambda: cfg_moe_grouped(M=256 if q else 512)),
        ("w4a8_gemm", lambda: cfg_w4a8(*(1024,) * 3 if q
                                       else (4096,) * 3)),
        ("w4a16_gemm", lambda: cfg_w4a16(*(1024,) * 3 if q
                                         else (4096,) * 3)),
    ]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _child_main(args) -> None:
    """Run ONE config in this process (spawned by the parent): probe
    briefly, measure, print the JSON record, hard-exit. In-process
    watchdogs still bound every jax call — a worker that dies mid-call
    HANGS the call, and only an abandoned daemon thread plus os._exit
    keeps this child from wedging (the parent's subprocess timeout is
    the outer backstop)."""
    q = args.quick
    name = args.child
    builders = dict(_config_builders(q))
    if name not in builders:
        print(json.dumps({"config": name, "error": "unknown config"}),
              flush=True)
        os._exit(3)
    if os.environ.get("TL_TPU_BENCH_HERMETIC"):
        # hermetic child: probe the TPU tier through the registry ONCE
        # so its dead verdict (armed device.probe fault, or simply no
        # TPU attached) is cached health state every kernel build's
        # chain walk reuses — and the record's snapshot shows it
        from tilelang_mesh_tpu.codegen.backends import registry
        registry().is_available("tpu-pallas")
    probe_s = _env_float("TL_TPU_BENCH_CHILD_PROBE_TIMEOUT", 120)
    perr = _probe_device(probe_s)
    if perr is not None:
        from tilelang_mesh_tpu.resilience.errors import classify
        print(json.dumps({"config": name, "error": str(perr),
                          "error_kind": classify(perr)}), flush=True)
        os._exit(3)
    cfg_timeout = _env_float("TL_TPU_BENCH_CONFIG_TIMEOUT", 1800)
    if cfg_timeout <= 0:
        cfg_timeout = 1800.0
    try:
        peaks = _watchdog(_chip_peak_tflops, "device model probe", probe_s)
        rec = _run_config_failover(name, builders[name], peaks,
                                   1 if q else 3, cfg_timeout)
    except Exception as e:
        print(f"# config {name} FAILED: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        print(json.dumps({"config": name, "error": str(e)[:300]}),
              flush=True)
        sys.stdout.flush()
        os._exit(3)
    rec = _attach_backend_state(rec)
    rec = _attach_sol(rec, name)
    rec = _attach_observability(rec, name)
    print(json.dumps(rec), flush=True)
    sys.stdout.flush()
    os._exit(0)


def _spawn_probe(timeout_s: float) -> bool:
    """Probe the TPU from a FRESH subprocess (the parent never imports
    jax, so a wedged backend can never take the orchestrator down).
    Deliberately a minimal jax one-liner, NOT the package's
    probe_default_device: the full package import costs seconds, and a
    mid-sweep recovery probe runs under the shrinking dead_budget —
    import time eating the budget would misreport a recovered worker as
    dead. This wrapper only needs alive/dead; the classified in-process
    probe (registry probe_default_device) lives in the children."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax.numpy as jnp; "
             "jnp.ones((8, 128)).sum().block_until_ready()"],
            timeout=timeout_s, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, start_new_session=True)
        return r.returncode == 0
    except Exception:
        return False


# Parent-side cache of spawn-probe verdicts, honoring the backend
# registry's TTL knob. The parent cannot hold the registry itself (any
# tilelang_mesh_tpu import loads jax — forbidden here), so it caches the
# subprocess verdicts under the same TL_TPU_BACKEND_PROBE_TTL_S the
# in-child registry uses; the children seed their registries from their
# own probes.
_PROBE_CACHE = {"at": None, "ok": None}


def _probe_ttl_s() -> float:
    return _env_float("TL_TPU_BACKEND_PROBE_TTL_S", 30.0)


def _spawn_probe_cached(timeout_s: float) -> bool:
    now = time.monotonic()
    if _PROBE_CACHE["at"] is not None and \
            now - _PROBE_CACHE["at"] < _probe_ttl_s():
        return _PROBE_CACHE["ok"]
    ok = _spawn_probe(timeout_s)
    _PROBE_CACHE["at"] = time.monotonic()
    _PROBE_CACHE["ok"] = ok
    return ok


def _spawn_config(name: str, q: bool, timeout_s: float, extra_env=None):
    """Run one config in a fresh child process; returns (rec | None,
    error | None). The child prints its own JSON line, which is re-read
    from its stdout and re-emitted by the caller; on timeout the whole
    process group is killed so a wedged jax runtime cannot linger.
    ``extra_env`` overlays the child's environment (host-platform
    fallback / forced device counts for the CPU-safe configs)."""
    import signal
    import subprocess
    cmd = [sys.executable, os.path.abspath(__file__), "--child", name]
    if q:
        cmd.append("--quick")
    child_env = None
    if extra_env:
        child_env = dict(os.environ)
        child_env.update(extra_env)
    try:
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                             start_new_session=True, env=child_env)
        out, _ = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        except Exception:
            pass
        p.wait()
        return None, (f"config subprocess exceeded {timeout_s:.0f}s "
                      f"(worker wedged?); killed")
    except Exception as e:
        return None, f"config subprocess failed: {type(e).__name__}: {e}"
    rec = None
    for line in (out or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if cand.get("config") == name:
            rec = cand
    if rec is None:
        return None, f"config subprocess rc={p.returncode}, no record"
    if "error" in rec:
        return None, rec["error"]
    return rec, None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small shapes (smoke test, not a benchmark)")
    ap.add_argument("--only", type=str, default=None,
                    help="comma-separated config names")
    ap.add_argument("--child", type=str, default=None,
                    help=argparse.SUPPRESS)   # internal: run one config
    ap.add_argument("--in-process", action="store_true",
                    help="run configs in THIS process (debugging; the "
                         "default isolates each config in a subprocess "
                         "so a dead tunnel worker cannot zero the run)")
    ap.add_argument("--probe-timeout", type=float,
                    default=_env_float("TL_TPU_BENCH_PROBE_TIMEOUT", 600),
                    help="bound (seconds) on the single startup TPU "
                         "probe; an unreachable worker skips every "
                         "TPU-only config immediately (CPU-safe configs "
                         "still run); <= 0 skips the probe entirely")
    ap.add_argument("--strict", action="store_true",
                    help="exit 2 if ANY config failed (CI mode); the "
                         "default keeps partial sweeps green so a dead "
                         "tunnel worker late in the run cannot zero the "
                         "whole capture")
    ap.add_argument("--hermetic", action="store_true",
                    help="run ONLY the CPU-safe configs through the "
                         "backend registry with the TPU tier forcibly "
                         "marked dead (device.probe fault armed): a "
                         "sweep that always produces numbers, rc=0, "
                         "regardless of TPU health — the CI "
                         "hermetic-bench job and the verify.chaos "
                         "--device-loss driver run this")
    ap.add_argument("--device-loss-at", type=str, default=None,
                    help=argparse.SUPPRESS)   # internal (chaos driver):
    # arm a one-shot device.dispatch loss inside the NAMED config's
    # child, simulating the worker dying at that point mid-sweep
    args = ap.parse_args()

    if args.child:
        _child_main(args)
        return

    q = args.quick
    configs = _config_builders(q)
    skipped_records = []   # explicit skip_reason records (never silent)
    if args.hermetic:
        # hermetic sweep: the CPU-safe set only, every config through
        # the backend registry with the TPU tier dead — guaranteed to
        # produce numbers on the host fallback tiers. TPU-only configs
        # are not silently omitted: each gets a skip record naming the
        # capability filter, so a snapshot reader can tell "filtered by
        # design" from "failed to produce numbers".
        keep = set(args.only.split(",")) if args.only else None
        # configs excluded by an explicit --only are out of the run's
        # scope by user choice and get no record; only capability
        # filtering (TPU-only in a CPU-safe sweep) is surfaced
        skipped_records = [
            {"config": n, "skipped": True,
             "skip_reason": "capability filter: TPU-only config; the "
                            "hermetic sweep runs the CPU-safe set"}
            for n, _ in configs if n not in CPU_SAFE_CONFIGS
            and (keep is None or n in keep)]
        configs = [(n, b) for n, b in configs if n in CPU_SAFE_CONFIGS
                   and (keep is None or n in keep)]
    elif args.only:
        keep = set(args.only.split(","))
        configs = [(n, b) for n, b in configs if n in keep]
    else:
        # the CPU-safe smoke configs exist for the CI perf-smoke job
        # (--only) and as perf-diff baseline anchors; a default sweep
        # excludes them so the tiny host-platform comparisons cannot
        # shift the headline geomean_vs_baseline of the BENCH_r*
        # trajectory
        configs = [(n, b) for n, b in configs
                   if n not in CPU_SAFE_CONFIGS]
    names = [n for n, _ in configs]

    cfg_timeout = _env_float("TL_TPU_BENCH_CONFIG_TIMEOUT", 1800)
    if cfg_timeout <= 0:
        cfg_timeout = 1800.0   # cannot be disabled: a wedged worker
        # would hang the driver's bench forever

    # startup: probe the device ONCE, bounded — a dead worker costs one
    # bounded probe, then every TPU-only config is skipped immediately.
    # (The round-5 capture instead re-probed per config until the 600s
    # budget expired, burning ~10 minutes to produce an empty artifact.)
    # A CPU-safe-only run skips the probe ONLY when JAX_PLATFORMS is
    # pinned in the environment (the CI fast path): with the platform
    # unpinned the probe's answer still decides whether _config_env
    # must force the children onto the host platform, and skipping it
    # would hand each child to a possibly-dead default backend.
    probe_s = _env_float("TL_TPU_BENCH_PARENT_PROBE_TIMEOUT", 75)
    alive = True
    dead_reason = "unreachable at the startup probe"
    tpu_needed = any(n not in CPU_SAFE_CONFIGS for n in names) \
        or not os.environ.get("JAX_PLATFORMS")
    if args.hermetic:
        # the TPU tier is dead BY CONSTRUCTION: no probe, no recovery
        # budget — the whole point is numbers without TPU health
        alive = False
        dead_reason = "hermetic mode: TPU backend forcibly marked dead"
        print("# hermetic sweep: TPU backend forcibly marked dead; "
              f"CPU-safe configs ({', '.join(n for n in names)}) run "
              "through the backend failover chain", file=sys.stderr,
              flush=True)
    elif args.probe_timeout > 0 and not args.in_process and tpu_needed:
        alive = _spawn_probe_cached(min(probe_s, args.probe_timeout))
        if not alive:
            print("# TPU worker unreachable (probed once); skipping "
                  "TPU-only configs — CPU-safe configs "
                  f"({', '.join(CPU_SAFE_CONFIGS)}) still run on the "
                  "host platform", file=sys.stderr, flush=True)
    # mid-sweep recovery probes share ONE bounded budget; a worker
    # already dead at startup gets none (probe once, skip immediately),
    # while a worker lost mid-sweep — possibly a transient blip — gets
    # a chance to be noticed recovering. Verdicts are TTL-cached
    # (TL_TPU_BACKEND_PROBE_TTL_S, mirroring the in-child registry) so
    # back-to-back failed configs cannot burn the budget re-probing.
    dead_budget = _env_float("TL_TPU_BENCH_DEAD_PROBE_BUDGET",
                             300 if alive and not args.hermetic else 0)

    results = []
    headline = None
    builders = dict(configs)
    peaks = None
    for rec in skipped_records:
        print(json.dumps(rec), flush=True)
    for name in names:
        skip_reason = None
        if args.in_process:
            # legacy single-process path (debugging)
            try:
                if peaks is None:
                    peaks = _watchdog(_chip_peak_tflops,
                                      "device model probe", cfg_timeout)
                rec = _watchdog(
                    lambda: run_config(name, builders[name], peaks,
                                       rounds=1 if q else 3),
                    f"config {name}", cfg_timeout)
                rec = _attach_backend_state(rec)
                rec = _attach_sol(rec, name)
                rec = _attach_observability(rec, name)
                err = None
            except Exception as e:
                rec, err = None, f"{type(e).__name__}: {e}"
                _reset_tracer()
        else:
            if not alive and name not in CPU_SAFE_CONFIGS \
                    and dead_budget > 0:
                # a worker lost MID-SWEEP may be a transient blip:
                # re-probe (bounded by the shared dead budget) so a
                # recovery doesn't forfeit the rest of the sweep. The
                # startup-dead case never enters here with the default
                # budget spent on one bounded probe.
                t0 = time.time()
                alive = _spawn_probe_cached(min(probe_s, dead_budget))
                dead_budget -= time.time() - t0
            if alive or name in CPU_SAFE_CONFIGS:
                # the child pays jax import + probes before its own
                # watchdog starts: give its subprocess that allowance on
                # top of cfg_timeout so a slow-but-legitimate config is
                # never misreported as a wedged worker
                child_env = _config_env(name, alive)
                if args.hermetic:
                    child_env.update(_hermetic_env(name,
                                                   args.device_loss_at))
                rec, err = _spawn_config(name, q, cfg_timeout + 300,
                                         extra_env=child_env)
                if rec is None and "worker" in (err or "").lower():
                    if alive:
                        dead_reason = (f"lost mid-sweep at config "
                                       f"{name} ({(err or '')[:120]})")
                    alive = False
            else:
                rec, err = None, f"skipped: TPU worker {dead_reason}"
                skip_reason = f"dead tier: TPU worker {dead_reason}"
        if rec is not None:
            print(json.dumps(rec), flush=True)
            results.append(rec)
            if name == "gemm_large":
                headline = rec
        else:
            print(f"# config {name} FAILED: {err}", file=sys.stderr,
                  flush=True)
            failed_rec = {"config": name, "error": (err or "")[:300]}
            if skip_reason:
                # an explicit skip is not a failure: name the dead tier
                # so snapshot readers can tell "worker down" from
                # "config broken" without parsing error strings
                failed_rec["skipped"] = True
                failed_rec["skip_reason"] = skip_reason[:300]
            print(json.dumps(failed_rec), flush=True)


    ok = results
    if not ok:
        print(json.dumps({"metric": "bench", "value": 0.0, "unit": "TFLOPS",
                          "vs_baseline": 0.0,
                          "error": "every config failed"}), flush=True)
        sys.exit(1)
    geo = math.exp(sum(math.log(max(r["vs_baseline"], 1e-6)) for r in ok)
                   / len(ok))
    headline = dict(headline or ok[0])
    headline["geomean_vs_baseline"] = round(geo, 4)
    n_failed = len(configs) - len(ok)
    headline["n_configs_ok"] = len(ok)
    headline["n_configs_failed"] = n_failed
    print(json.dumps(headline), flush=True)
    sys.stdout.flush()
    # hard exit: in-process mode can hold abandoned watchdog threads
    # inside native jax calls, which abort interpreter finalization
    os._exit(exit_code(args.strict, n_failed))


if __name__ == "__main__":
    main()
