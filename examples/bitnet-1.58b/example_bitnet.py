"""BitNet b1.58 ternary-weight inference (reference examples/bitnet-1.58b).

The reference ships a full HF BitNet model; the kernel capability it rests
on is BitLinear (utils_quant.py): absmean-ternarized weights packed int2,
per-token int8 activations, int8 GEMM, scale-out. This example builds a
BitNet FFN block (gate/up/down BitLinears + squared-ReLU) on the TPU
kernels of ops/bitnet.py and checks it against the float emulation
(eval_correctness.py behavior).
"""

import numpy as np

from tilelang_mesh_tpu.ops.bitnet import (bitnet_linear,
                                          bitnet_linear_reference,
                                          pack_ternary)


def weight_quant_ternary(w: np.ndarray):
    """Reference utils_quant.py BitLinear.weight_quant: scale by mean |w|,
    round-clip to {-1, 0, 1}; returns (ternary, w_scale)."""
    scale = 1.0 / max(np.abs(w).mean(), 1e-5)
    tern = np.clip(np.round(w * scale), -1, 1).astype(np.int8)
    return tern, scale


class BitFFN:
    """gate/up/down BitLinear FFN with squared ReLU (BitNet b1.58 block)."""

    def __init__(self, d_model, d_ff, seed=0):
        rng = np.random.default_rng(seed)
        self.packed, self.scales, self.ternary = {}, {}, {}
        for name, shape in (("gate", (d_model, d_ff)),
                            ("up", (d_model, d_ff)),
                            ("down", (d_ff, d_model))):
            w = (rng.standard_normal(shape) / np.sqrt(shape[0])).astype(
                np.float32)
            tern, scale = weight_quant_ternary(w)
            self.packed[name] = pack_ternary(tern)
            self.scales[name] = scale
            self.ternary[name] = tern

    def __call__(self, x, reference=False):
        import jax.numpy as jnp
        lin = (lambda x, n: bitnet_linear_reference(
            x, self.ternary[n], self.scales[n])) if reference else \
            (lambda x, n: bitnet_linear(x, self.packed[n], self.scales[n]))
        g = lin(x, "gate")
        u = lin(x, "up")
        h = jnp.square(jnp.maximum(g, 0.0)) * u  # squared-ReLU gating
        return lin(h, "down")


def main(batch=4, seq=32, d_model=512, d_ff=1024):
    import jax.numpy as jnp
    ffn = BitFFN(d_model, d_ff)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal(
            (batch, seq, d_model), dtype=np.float32))
    y = np.asarray(ffn(x))
    ref = np.asarray(ffn(x, reference=True))
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)
    dense_bytes = sum(t.size * 4 for t in ffn.ternary.values())
    packed_bytes = sum(p.nbytes for p in ffn.packed.values())
    print(f"BitNet FFN ({d_model}->{d_ff}) kernel == float emulation ✓ "
          f"(weights {dense_bytes} B fp32 -> {packed_bytes} B int2)")


if __name__ == "__main__":
    main()
