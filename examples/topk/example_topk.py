"""Row-wise top-k (MoE gate style) through the tile pipeline.

Behavioral mirror of the reference's examples/topk/example_topk.py: iterative
argmax-and-mask — k rounds of (row max, index-of-max via masked iota-max,
mask out the winner). The reference spreads rows over CUDA threads; here each
round is a VPU-wide reduction over the (blk_m, N) fragment, and k is a static
trace-time unroll (k is tiny in MoE gating).
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


@tilelang.jit
def tl_topk(M, N, topk, blk_m=128, dtype="float32"):
    @T.prim_func
    def topk_kernel(logits: T.Tensor((M, N), dtype),
                    topk_gates: T.Tensor((M, topk), dtype),
                    topk_indices: T.Tensor((M, topk), "int32")):
        with T.Kernel(T.ceildiv(M, blk_m)) as bx:
            frag = T.alloc_fragment((blk_m, N), dtype)
            max_val = T.alloc_fragment((blk_m,), dtype)
            expand_idx = T.alloc_fragment((blk_m, N), "int32")
            max_idx = T.alloc_fragment((blk_m,), "int32")
            gates = T.alloc_fragment((blk_m, topk), dtype)
            indices = T.alloc_fragment((blk_m, topk), "int32")

            T.copy(logits[bx * blk_m, 0], frag)
            for k in range(topk):
                T.reduce_max(frag, max_val, dim=1, clear=True)
                # smallest index attaining the max (torch.topk tie rule):
                # mask iota where not max, take min == -max of negated
                for i, j in T.Parallel(blk_m, N):
                    expand_idx[i, j] = T.if_then_else(
                        max_val[i] == frag[i, j], -j, -(N + 1))
                T.reduce_max(expand_idx, max_idx, dim=1, clear=True)
                for i, j in T.Parallel(blk_m, N):
                    frag[i, j] = T.if_then_else(
                        max_idx[i] == -j, -T.infinity(dtype), frag[i, j])
                for i in T.Parallel(blk_m):
                    gates[i, k] = max_val[i]
                    indices[i, k] = -max_idx[i]
            T.copy(gates, topk_gates[bx * blk_m, 0])
            T.copy(indices, topk_indices[bx * blk_m, 0])

    return topk_kernel


def ref_topk(logits, k):
    idx = np.argsort(-logits, axis=1, kind="stable")[:, :k]
    gates = np.take_along_axis(logits, idx, axis=1)
    return gates, idx.astype(np.int32)


def main(M=256, N=128, topk=8):
    kernel = tl_topk(M, N, topk)
    rng = np.random.default_rng(0)
    logits = rng.standard_normal((M, N), dtype=np.float32)
    gates = np.empty((M, topk), dtype=np.float32)
    indices = np.empty((M, topk), dtype=np.int32)
    kernel(logits, gates, indices)
    ref_g, ref_i = ref_topk(logits, topk)
    np.testing.assert_allclose(gates, ref_g, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(indices, ref_i)
    print(f"top-{topk} over {M}x{N}: gates and indices match ✓")


if __name__ == "__main__":
    main()
