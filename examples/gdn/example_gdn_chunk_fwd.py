"""Gated DeltaNet chunked forward (reference examples/gdn behavior:
chunk_scaled_dot_kkt + wy_fast + chunk_delta_h + chunk_o composed)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gdn import gdn_chunk_fwd, gdn_reference


def main(B=1, H=2, T=128, K=32, V=32, chunk=32):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, T, K)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, T, K)), jnp.float32)
    k = k / jnp.linalg.norm(k, axis=-1, keepdims=True)
    v = jnp.asarray(rng.standard_normal((B, H, T, V)), jnp.float32)
    g = jnp.asarray(rng.uniform(-0.2, 0.0, (B, H, T)), jnp.float32)
    beta = jnp.asarray(rng.uniform(0.0, 1.0, (B, H, T)), jnp.float32)
    out, h = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=chunk,
                           output_final_state=True)
    ref, h_ref = gdn_reference(q, k, v, g, beta, output_final_state=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref),
                               rtol=2e-2, atol=2e-2)
    print("gated delta-net chunked forward matches sequential delta rule.")


if __name__ == "__main__":
    main()
