"""Gated DeltaNet forward as ONE tile kernel (reference examples/gdn
splits the chunk math into per-piece CUDA kernels: example_wy_fast.py
computes the WY triangular inverse by per-warp forward substitution,
example_chunk_delta_h.py carries the state, example_chunk_o.py emits the
output).

TPU re-design: all pieces fuse into a single kernel (grid (H, B),
in-kernel chunk recurrence), and the WY inverse (I + A)^{-1} is
computed by NEUMANN DOUBLING — A is strictly lower triangular so the
series terminates, and S <- S + N^{2^k} S doubles the covered powers
per step: ceil(log2(C)) - 1 pairs of C x C MXU matmuls replace the
C-step serial substitution that would stall the VPU."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gdn import (gdn_chunk_fwd, gdn_chunk_fwd_tl,
                                       gdn_reference)


def main(B=1, H=2, T=256, K=64, V=64, chunk=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, T, K)), jnp.float32)
    k = rng.standard_normal((B, H, T, K))
    k = jnp.asarray(k / np.linalg.norm(k, axis=-1, keepdims=True),
                    jnp.float32)                       # l2-normalized keys
    v = jnp.asarray(rng.standard_normal((B, H, T, V)), jnp.float32)
    g = jnp.asarray(rng.uniform(-0.2, 0.0, (B, H, T)), jnp.float32)
    beta = jnp.asarray(rng.uniform(0.0, 1.0, (B, H, T)), jnp.float32)

    out = gdn_chunk_fwd_tl(q, k, v, g, beta, chunk_size=chunk)
    ref = gdn_reference(q, k, v, g, beta)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    print(f"tile-kernel GDN (chunk={chunk}) matches the sequential "
          f"delta rule.")

    xla = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=chunk)
    np.testing.assert_allclose(np.asarray(out), np.asarray(xla),
                               rtol=2e-2, atol=2e-2)
    print("tile kernel and XLA chunked WY implementations agree "
          "(the benchmark's A/B pair, bench.py::cfg_gdn_fwd).")


if __name__ == "__main__":
    main()
