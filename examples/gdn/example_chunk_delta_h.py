"""The cross-chunk state carry of Gated DeltaNet (reference
examples/gdn/example_chunk_delta_h.py behavior): the (K, V) state after
the chunked forward must equal the state the sequential delta rule
reaches token by token — including from a nonzero initial state."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gdn import gdn_chunk_fwd, gdn_reference


def main(B=1, H=2, T=128, K=32, V=32):
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, T, K)), jnp.float32)
    k = rng.standard_normal((B, H, T, K))
    k = jnp.asarray(k / np.linalg.norm(k, axis=-1, keepdims=True),
                    jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, T, V)), jnp.float32)
    g = jnp.asarray(rng.uniform(-0.2, 0.0, (B, H, T)), jnp.float32)
    beta = jnp.asarray(rng.uniform(0.0, 1.0, (B, H, T)), jnp.float32)
    h0 = jnp.asarray(rng.standard_normal((B, H, K, V)) * 0.3, jnp.float32)

    o, h_chunk = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=32,
                               initial_state=h0, output_final_state=True)
    o_ref, h_ref = gdn_reference(q, k, v, g, beta, initial_state=h0,
                                 output_final_state=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(h_chunk), np.asarray(h_ref),
                               rtol=2e-2, atol=2e-2)
    print("chunked state carry (with initial state) matches the "
          "sequential delta rule's final state.")


if __name__ == "__main__":
    main()
