"""The WY representation stage of Gated DeltaNet (reference
examples/gdn/example_wy_fast.py behavior): T_mat = (I + A)^{-1} for the
strictly-lower decay-scaled K K^T, and the factors
w = T_mat (beta e^gc k), u = T_mat (beta v).

The reference computes T_mat by per-warp forward substitution; the XLA
path here uses a batched unit-triangular solve, and the tile kernel
(gdn_chunk_fwd_kernel) uses Neumann doubling on the MXU — this example
pins that all three agree with the algebraic definition."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gdn import (gdn_chunk_cumsum,
                                       gdn_scaled_dot_kkt, gdn_wy_fast)


def main(B=1, H=2, T=128, K=32, V=32, C=64):
    rng = np.random.default_rng(0)
    k = rng.standard_normal((B, H, T, K))
    k = jnp.asarray(k / np.linalg.norm(k, axis=-1, keepdims=True),
                    jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, T, V)), jnp.float32)
    g = jnp.asarray(rng.uniform(-0.2, 0.0, (B, H, T)), jnp.float32)
    beta = jnp.asarray(rng.uniform(0.0, 1.0, (B, H, T)), jnp.float32)

    N = T // C
    kf = k.reshape(B, H, N, C, K)
    vf = v.reshape(B, H, N, C, V)
    bf = beta.reshape(B, H, N, C)

    gc = gdn_chunk_cumsum(g, C)
    A = gdn_scaled_dot_kkt(kf, bf, gc)
    # strictly lower triangular by construction
    assert np.allclose(np.triu(np.asarray(A), 0), 0.0)

    w, u, T_mat = gdn_wy_fast(kf, vf, bf, gc, A)
    # (I + A) T_mat == I  — the defining identity
    eye = np.eye(C, dtype=np.float32)
    prod = np.einsum("bhnij,bhnjk->bhnik",
                     np.asarray(A) + eye, np.asarray(T_mat))
    np.testing.assert_allclose(prod, np.broadcast_to(eye, prod.shape),
                               rtol=1e-4, atol=1e-4)
    print("(I + A) @ T_mat == I: WY inverse correct.")

    # w/u satisfy their definitions
    np.testing.assert_allclose(
        np.asarray(w),
        np.einsum("bhnij,bhnjk->bhnik", np.asarray(T_mat),
                  np.asarray(bf)[..., None] * np.exp(np.asarray(gc))[..., None]
                  * np.asarray(kf)),
        rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(u),
        np.einsum("bhnij,bhnjv->bhniv", np.asarray(T_mat),
                  np.asarray(bf)[..., None] * np.asarray(vf)),
        rtol=1e-4, atol=1e-4)
    print("WY factors w (state-eating keys) and u (injected values) "
          "match their definitions.")


if __name__ == "__main__":
    main()
