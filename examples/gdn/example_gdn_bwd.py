"""Gated DeltaNet backward (reference examples/gdn
example_chunk_delta_bwd.py / example_chunk_o_bwd.py behavior): on TPU
the chunked delta-rule scan is a lax.scan over MXU-sized chunk GEMMs, so
the backward IS jax AD through the scan — no hand-written bwd kernel
zoo; gradcheck against the sequential reference."""

import jax
import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.gdn import gdn_chunk_fwd


def _gdn_sequential(q, k, v, g, beta):
    """Token-sequential gated delta rule in jax (AD-able ground truth;
    the numpy gdn_reference is not differentiable)."""
    B, H, S, N = q.shape
    P = v.shape[-1]
    scale = 1.0 / np.sqrt(N)

    def step(h, inp):
        qt, kt, vt, gt, bt = inp
        h = h * jnp.exp(gt)[..., None, None]
        kv = jnp.einsum("bhkv,bhk->bhv", h, kt)
        v_new = bt[..., None] * (vt - kv)
        h = h + jnp.einsum("bhk,bhv->bhkv", kt, v_new)
        o = jnp.einsum("bhkv,bhk->bhv", h, qt * scale)
        return h, o

    h0 = jnp.zeros((B, H, N, P), jnp.float32)
    xs = (jnp.moveaxis(q, 2, 0), jnp.moveaxis(k, 2, 0),
          jnp.moveaxis(v, 2, 0), jnp.moveaxis(g, 2, 0),
          jnp.moveaxis(beta, 2, 0))
    _, os = jax.lax.scan(step, h0, xs)
    return jnp.moveaxis(os, 0, 2)


def main(B=1, H=2, S=128, P=64, N=64, chunk=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, N)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, N)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, P)) * 0.3, jnp.float32)
    g = jnp.asarray(-rng.uniform(0.05, 0.3, (B, H, S)), jnp.float32)
    beta = jnp.asarray(rng.uniform(0.3, 0.9, (B, H, S)), jnp.float32)
    go = jnp.asarray(rng.standard_normal((B, H, S, P)), jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(gdn_chunk_fwd(q, k, v, g, beta,
                                     chunk_size=chunk) * go)

    def loss_ref(q, k, v):
        return jnp.sum(_gdn_sequential(q, k, v, g, beta) * go)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)
    print(f"GDN bwd (S={S}, chunk={chunk}): gradients through the "
          f"chunked scan match the sequential reference.")


if __name__ == "__main__":
    main()
