"""w4a16 dequantize GEMM (reference examples/dequantize_gemm)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.dequant_gemm import dequant_matmul
from tilelang_mesh_tpu.quantize import (dequantize_int4_planar_ref,
                                        quantize_int4_planar)


def main(M=256, N=256, K=1024, group_size=128):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)), jnp.float32)
    w = rng.standard_normal((K, N)).astype(np.float32)
    packed, scales = quantize_int4_planar(w, group_size)
    out = dequant_matmul(a, jnp.asarray(packed), jnp.asarray(scales),
                         group_size=group_size, block_K2=group_size)
    deq = dequantize_int4_planar_ref(packed, scales, group_size)
    a_np = np.asarray(a)
    ref = np.concatenate([a_np[:, :K // 2], a_np[:, K // 2:]], 1) @ deq
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-2, atol=5e-1)
    print("w4a16 dequant GEMM matches dequantized reference.")
    print(f"weight memory: {packed.nbytes + scales.nbytes} bytes vs "
          f"{w.astype(np.float16).nbytes} (fp16)")


if __name__ == "__main__":
    main()
