"""w4a16 dequant GEMV — the decode-time shape where FUSED dequant wins
(reference examples/dequantize_gemm/example_dequant_gemv_fp16xint4.py
behavior).

At decode the GEMM is pure bandwidth: the weight matrix is the traffic,
so reading it as int4 (a quarter of bf16 bytes) and dequantizing in
VMEM beats any two-pass scheme that materializes bf16 weights through
HBM. This is the same fused kernel the benchmark sweeps for prefill
(bench.py::cfg_w4a16), at the shape where it is the clear winner.

M=8, not 1: the VPU/MXU minimum tile is (8, 128), so a lone decode row
is padded to 8 rows anyway — batching 8 decode tokens (or speculative
candidates) costs nothing and is the realistic serving shape."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.dequant_gemm import dequant_gemm_kernel
from tilelang_mesh_tpu.quantize.quantization import (
    dequantize_int4_planar_ref, quantize_int4_planar)


def main(M=8, N=512, K=1024, gs=256):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.bfloat16)
    w = rng.standard_normal((K, N)).astype(np.float32) * 0.1
    packed_np, scales_np = quantize_int4_planar(w, group_size=gs)

    K2 = K // 2
    kern = dequant_gemm_kernel(M, N, K, block_M=M, block_N=128,
                               block_K2=gs, group_size=gs,
                               in_dtype="bfloat16")
    out = kern(a.reshape(M, 2, K2), jnp.asarray(packed_np),
               jnp.asarray(scales_np).reshape(2, K2 // gs, N))
    want = np.asarray(a, np.float32) @ dequantize_int4_planar_ref(
        packed_np, scales_np, group_size=gs)
    rel = (np.linalg.norm(np.asarray(out, np.float32) - want)
           / np.linalg.norm(want))
    assert rel < 4e-2, rel
    print(f"w4a16 dequant GEMV M={M}: fused in-VMEM dequant correct "
          f"(rel err {rel:.1e}); weight traffic is K*N/2 bytes vs "
          f"{2 * K * N} for bf16.")


if __name__ == "__main__":
    main()
