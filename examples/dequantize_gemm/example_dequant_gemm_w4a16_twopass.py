"""w4a16 two-pass prefill path (reference examples/dequantize_gemm
fast-dequant variants): materialize bf16 weights once with the VPU
dequant kernel, then one large-tile MXU GEMM — the compute-bound
counterpart of the fused kernel (example_dequant_gemm_w4a16.py), which
re-unpacks the weight tile per M-block and wins only skinny-M decode."""

import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.dequant_gemm import dequant_matmul_twopass
from tilelang_mesh_tpu.quantize.quantization import (
    dequantize_int4_planar_ref, quantize_int4_planar)


def main(M=256, N=512, K=512, gs=128):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.bfloat16)
    w = rng.standard_normal((K, N)).astype(np.float32) * 0.1
    packed, scales = quantize_int4_planar(w, group_size=gs)

    out = dequant_matmul_twopass(a, jnp.asarray(packed),
                                 jnp.asarray(scales),
                                 block_M=128, block_N=256, block_K=128,
                                 dq_block=gs)
    want = np.asarray(a, np.float32) @ dequantize_int4_planar_ref(
        packed, scales, group_size=gs)
    np.testing.assert_allclose(np.asarray(out, np.float32), want,
                               rtol=6e-2, atol=6e-2)
    print(f"w4a16 two-pass GEMM {M}x{N}x{K} gs={gs} matches the "
          f"dequantized-dense reference.")


if __name__ == "__main__":
    main()
