"""bf16 x MXFP4 dequantize GEMM (reference examples/dequantize_gemm/
example_dequant_gemm_bf16_mxfp4_hopper.py).

Weights are OCP-MX fp4 (e2m1) packed two per byte with one e8m0 shared
scale per 32-element K group. The reference decodes via LOP3 lookup tables
in CUDA; here the decode is pure VPU arithmetic — sign/exponent/mantissa
split with exp2 — fused into the K loop ahead of each bf16 MXU dot.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.quantize.quantization import (dequantize_mxfp4_ref,
                                                     pack_mxfp4,
                                                     quantize_mxfp4)

GROUP = 32


@tilelang.jit
def dequant_gemm_mxfp4(M, N, K, block_M=128, block_N=128, block_K=128,
                       num_stages=2):
    n_seg = block_K // GROUP

    @T.prim_func
    def mxfp4_gemm(A: T.Tensor((M, K), "bfloat16"),
                   Wp: T.Tensor((K // 2, N), "int8"),
                   Se: T.Tensor((K // GROUP, N), "uint8"),
                   C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_s = T.alloc_shared((block_M, block_K), "bfloat16")
            Wp_s = T.alloc_shared((block_K // 2, block_N), "int8")
            Se_s = T.alloc_shared((n_seg, block_N), "uint8")
            W_s = T.alloc_shared((block_K, block_N), "bfloat16")
            acc = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(acc)
            for ko in T.Pipelined(T.ceildiv(K, block_K),
                                  num_stages=num_stages):
                T.copy(A[by * block_M, ko * block_K], A_s)
                T.copy(Wp[ko * block_K // 2, bx * block_N], Wp_s)
                T.copy(Se[ko * n_seg, bx * block_N], Se_s)
                # VPU e2m1 decode, one 32-row scale group at a time
                for seg in range(n_seg):
                    for g, p, j in T.Parallel(GROUP // 2, 2, block_N):
                        code = (T.shift_right(
                            Wp_s[seg * (GROUP // 2) + g, j], 4 * p) & 15)
                        e = T.shift_right(code, 1) & 3
                        m = T.cast(code & 1, "float32")
                        mag = T.if_then_else(
                            e == 0, 0.5 * m,
                            T.exp2(T.cast(e - 1, "float32")) *
                            (1.0 + 0.5 * m))
                        sgn = 1.0 - 2.0 * T.cast(
                            T.shift_right(code, 3) & 1, "float32")
                        scale = T.exp2(
                            T.cast(Se_s[seg, j], "float32") - 127.0)
                        W_s[seg * GROUP + g * 2 + p, j] = sgn * mag * scale
                T.gemm(A_s, W_s, acc)
            T.copy(acc, C[by * block_M, bx * block_N])

    return mxfp4_gemm


def main(M=128, N=256, K=256):
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K)).astype(np.float32)
    w = rng.standard_normal((K, N)).astype(np.float32)
    codes, se = quantize_mxfp4(w, GROUP)
    packed = pack_mxfp4(codes)

    kernel = dequant_gemm_mxfp4(M, N, K)
    c = np.empty((M, N), np.float32)
    kernel(jnp.asarray(a, jnp.bfloat16), packed, se, c)

    w_deq = dequantize_mxfp4_ref(packed, se, GROUP)
    ref = np.asarray(jnp.asarray(a, jnp.bfloat16), np.float32) @ w_deq
    np.testing.assert_allclose(c, ref, rtol=5e-2, atol=5e-1)
    rel = np.abs(c - a @ w).mean() / np.abs(a @ w).mean()
    print(f"bf16 x mxfp4 dequant GEMM {M}x{N}x{K} ✓ "
          f"(4-bit end-to-end relerr {rel:.2%})")


if __name__ == "__main__":
    main()
