"""w4a8 dequant GEMM (reference examples/dequantize_gemm/
example_dequant_gemm_w4a8.py behavior): int4 weights, int8 activations,
the whole K reduction on the int8 MXU path (2x the bf16 rate on TPU),
one f32 scale epilogue.

Scales are per-output-channel (weights) and per-token (activations), so
dequantization commutes with the integer dot — the kernel is EXACT
vs integer math, and the example pins that."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.bitnet import quantize_activations
from tilelang_mesh_tpu.ops.dequant_gemm import (quantize_w4_per_channel,
                                                w4a8_matmul)


def main(M=128, N=256, K=512):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((M, K)).astype(np.float32)
    w = rng.standard_normal((K, N)).astype(np.float32) * 0.1

    packed, sw = quantize_w4_per_channel(w)
    out = np.asarray(w4a8_matmul(jnp.asarray(x), packed, sw))

    # exact integer-math reference
    q, s = quantize_activations(jnp.asarray(x))
    wd = np.concatenate([(packed.astype(np.int32) & 0xF) - 8,
                         (packed.astype(np.int32) >> 4) - 8], 0)
    ref = (np.asarray(q, np.int64) @ wd).astype(np.float64) \
        / np.asarray(s, np.float64) * sw
    rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
    assert rel < 1e-5, rel
    print(f"w4a8 GEMM exact vs integer reference (rel {rel:.1e}); "
          f"weight bytes {K * N // 2} vs {2 * K * N} bf16.")


if __name__ == "__main__":
    main()
