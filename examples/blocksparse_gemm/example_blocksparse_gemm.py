"""Block-sparse GEMM (reference examples/blocksparse_gemm): a per-output-tile
mask predicates whole (bm, bn) tiles; masked tiles skip all K-loop work."""

import numpy as np

import jax.numpy as jnp

from tilelang_mesh_tpu.ops import blocksparse_matmul


def main(M=256, N=256, K=256, bm=128, bn=128, sparsity=0.5):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.float32)
    mask = jnp.asarray(rng.random((M // bm, N // bn)) > sparsity, jnp.int32)
    c = np.asarray(blocksparse_matmul(a, b, mask, block_M=bm, block_N=bn,
                                      out_dtype="float32"))
    ref = np.asarray(a) @ np.asarray(b)
    dense = np.kron(np.asarray(mask), np.ones((bm, bn))) != 0
    np.testing.assert_allclose(c[dense], ref[dense], rtol=1e-4, atol=1e-4)
    assert np.abs(c[~dense]).max() == 0.0
    print("block-sparse GEMM correct.")


if __name__ == "__main__":
    main()
