"""Linear attention backward via operand-swapped reuse of the forward
kernel (reference examples/linear_attention/example_linear_attn_bwd.py).

For o_t = sum_{s<=t} (q_t.k_s) v_s the three gradients are themselves
causal/anti-causal linear attentions:

  dq_t = sum_{s<=t} (do_t.v_s) k_s          = linattn(do, v, k)
  dv_t = sum_{i>=t} (k_t.q_i) do_i          = rev(linattn(rev k, rev q, rev do))
  dk_t = sum_{i>=t} (v_t.do_i) q_i          = rev(linattn(rev v, rev do, rev q))

so the backward pass is three invocations of the SAME chunked MXU kernel —
no separate bwd kernel needed (the reference writes one by hand in CUDA).
"""

import numpy as np

from tilelang_mesh_tpu.ops.linear_attention import (
    linear_attention, linear_attention_reference)


def linear_attention_grads(q, k, v, do, chunk=128):
    import jax.numpy as jnp
    rev = lambda x: jnp.flip(x, axis=2)
    dq = linear_attention(do, v, k, chunk=chunk)
    dv = rev(linear_attention(rev(k), rev(q), rev(do), chunk=chunk))
    dk = rev(linear_attention(rev(v), rev(do), rev(q), chunk=chunk))
    return dq, dk, dv


def main(B=1, H=2, S=256, D=64):
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    q = rng.standard_normal((B, H, S, D), dtype=np.float32) * 0.3
    k = rng.standard_normal((B, H, S, D), dtype=np.float32) * 0.3
    v = rng.standard_normal((B, H, S, D), dtype=np.float32)
    do = rng.standard_normal((B, H, S, D), dtype=np.float32)

    dq, dk, dv = linear_attention_grads(q, k, v, do)
    # autodiff reference through the dense formulation
    f = lambda q, k, v: jnp.sum(
        linear_attention_reference(q, k, v).astype(jnp.float32) *
        jnp.asarray(do))
    rq, rk, rv = jax.grad(f, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b, n in ((dq, rq, "dq"), (dk, rk, "dk"), (dv, rv, "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-2, atol=1e-1)
    # the same wiring ships integrated: linear_attention(...,
    # backward="kernel") is differentiable via custom_vjp
    g = jax.grad(lambda q, k, v: jnp.sum(
        linear_attention(q, k, v, backward="kernel") *
        jnp.asarray(do)), argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(g, (rq, rk, rv)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-2, atol=1e-1)
    print("linear attention bwd: three operand-swapped fwd kernels "
          "reproduce autodiff grads ✓ (and backward='kernel' wires "
          "them into custom_vjp)")


if __name__ == "__main__":
    main()
