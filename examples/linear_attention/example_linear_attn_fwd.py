"""Chunked causal linear attention forward (reference
examples/linear_attention/example_linear_attn_fwd.py)."""

import numpy as np

from tilelang_mesh_tpu.ops.linear_attention import (
    linear_attention, linear_attention_reference)


def main(B=1, H=4, S=512, D=64):
    rng = np.random.default_rng(0)
    q = rng.standard_normal((B, H, S, D), dtype=np.float32) * 0.3
    k = rng.standard_normal((B, H, S, D), dtype=np.float32) * 0.3
    v = rng.standard_normal((B, H, S, D), dtype=np.float32)
    out = np.asarray(linear_attention(q, k, v, chunk=128))
    ref = np.asarray(linear_attention_reference(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-1)
    print(f"linear attention fwd B{B} H{H} S{S} D{D}: chunked == dense ✓")


if __name__ == "__main__":
    main()
