"""Mamba2 cross-chunk state carry (reference examples/linear_attention/
example_mamba_chunk_state.py stage): the (N, P) state handed from chunk
c to chunk c+1 must make the chunked scan EXACTLY prefix-consistent —
the first T0 outputs of a long scan equal the scan of the T0-prefix,
for any chunking of either."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.mamba2 import (mamba2_chunk_scan,
                                          mamba2_chunk_scan_xla)


def main(B=1, S=512, H=2, P=32, N=32, T0=256):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, S, H, P)) * 0.4, jnp.float32)
    dt = jnp.asarray(rng.uniform(0.01, 0.1, (B, S, H)), jnp.float32)
    A = jnp.asarray(-rng.uniform(0.5, 1.5, (H,)), jnp.float32)
    Bm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    Cm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)

    for impl, name in ((mamba2_chunk_scan, "tile kernel"),
                       (mamba2_chunk_scan_xla, "XLA baseline")):
        full = np.asarray(impl(x, dt, A, Bm, Cm, chunk=128))
        prefix = np.asarray(impl(x[:, :T0], dt[:, :T0], A, Bm[:, :T0],
                                 Cm[:, :T0], chunk=64))
        np.testing.assert_allclose(full[:, :T0], prefix, rtol=2e-2,
                                   atol=2e-2)
        print(f"{name}: first {T0} outputs of the chunked scan match "
              f"the prefix scan (state carry exact, chunking-invariant).")


if __name__ == "__main__":
    main()
