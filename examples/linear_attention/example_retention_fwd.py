"""Retention (RetNet) forward: linear attention with per-head exponential
decay (reference examples/linear_attention/example_retention_fwd.py)."""

import numpy as np

from tilelang_mesh_tpu.ops.linear_attention import (retention,
                                                    retention_reference)


def main(B=1, H=4, S=256, D=64):
    rng = np.random.default_rng(0)
    q = rng.standard_normal((B, H, S, D), dtype=np.float32) * 0.3
    k = rng.standard_normal((B, H, S, D), dtype=np.float32) * 0.3
    v = rng.standard_normal((B, H, S, D), dtype=np.float32)
    gamma = 1.0 - 2.0 ** (-5.0 - np.arange(H, dtype=np.float32))
    out = np.asarray(retention(q, k, v, gamma, chunk=64))
    ref = np.asarray(retention_reference(q, k, v, gamma))
    np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-2)
    print(f"retention fwd (decays {np.round(gamma, 4)}): chunked == dense ✓")


if __name__ == "__main__":
    main()
