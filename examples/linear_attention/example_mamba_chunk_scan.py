"""Mamba2 chunk scan (reference examples/linear_attention/
example_mamba_chunk_scan.py; benchmarked in benchmark/mamba2)."""

import numpy as np

from tilelang_mesh_tpu.ops.mamba2 import mamba2_chunk_scan, mamba2_reference


def main(B=1, S=512, H=4, P=64, N=64):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((B, S, H, P), dtype=np.float32)
    dt = (0.5 + rng.random((B, S, H))).astype(np.float32)
    A = (-0.5 - rng.random(H)).astype(np.float32)
    Bm = rng.standard_normal((B, S, N), dtype=np.float32)
    Cm = rng.standard_normal((B, S, N), dtype=np.float32)
    y = np.asarray(mamba2_chunk_scan(x, dt, A, Bm, Cm, chunk=128))
    ref = np.asarray(mamba2_reference(x, dt, A, Bm, Cm))
    np.testing.assert_allclose(y, ref, rtol=1e-2, atol=1e-1)
    print(f"mamba2 chunk scan B{B} S{S} H{H} P{P} N{N}: matches "
          "sequential SSM recurrence ✓")


if __name__ == "__main__":
    main()
