"""Render fragment (sublane, lane) packings (reference examples/plot_layout/
fragment_mma_load_a.py — which plots CUDA mma thread fragments; on TPU the
analog is the dtype-dependent (sublane, lane) VMEM tile packing)."""

from tilelang_mesh_tpu.analysis import (visualize_fragment,
                                        visualize_mesh_blocks)


def main():
    for bits in (32, 16, 8):
        txt = visualize_fragment(16, 256, dtype_bits=bits, max_rows=4,
                                 max_cols=6)
        print(txt)
        assert "sublane=" in txt and "lane=" in txt
    mesh = visualize_mesh_blocks(4, 4)
    print(mesh)
    assert "4x4 mesh" in mesh
    print("fragment + mesh layout maps rendered ✓")


if __name__ == "__main__":
    main()
