"""Direct elementwise kernel over the grid (reference examples/elementwise)."""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def add_kernel(M, N, bm, bn, dtype="float32"):
    @T.prim_func
    def add(A: T.Tensor((M, N), dtype),
            B: T.Tensor((M, N), dtype),
            C: T.Tensor((M, N), dtype)):
        with T.Kernel(T.ceildiv(N, bn), T.ceildiv(M, bm)) as (bx, by):
            for i, j in T.Parallel(bm, bn):
                C[by * bm + i, bx * bn + j] = \
                    A[by * bm + i, bx * bn + j] + B[by * bm + i, bx * bn + j]
    return tilelang.compile(add)


def main(M=512, N=512):
    k = add_kernel(M, N, 128, 128)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, N), dtype=np.float32)
    b = rng.standard_normal((M, N), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(k(a, b)), a + b, rtol=1e-6,
                               atol=1e-6)
    print("elementwise add correct.")


if __name__ == "__main__":
    main()
