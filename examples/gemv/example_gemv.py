"""GEMV (reference examples/gemv/example_gemv.py: C = A @ B.T with A (K,),
B (N, K)). On TPU the reduction rides the MXU as a (1, bk) x (bk, bn) gemm
per N block instead of per-thread scalar accumulation."""

import numpy as np

import jax.numpy as jnp

from tilelang_mesh_tpu.ops import gemv


def main(N=384, K=512):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((K,)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((N, K)) * 0.1, jnp.float32)
    c = gemv(a, b, out_dtype="float32")
    np.testing.assert_allclose(np.asarray(c),
                               np.asarray(b) @ np.asarray(a),
                               rtol=1e-4, atol=1e-4)
    print("gemv correct.")


if __name__ == "__main__":
    main()
