"""FlashAttention backward in the BSHD layout (reference
examples/flash_attention/example_mha_bwd_bshd.py behavior): gradients
flow through the layout transpose into the dKdV/dQ tile kernels."""

import numpy as np
import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_attention import (flash_attention,
                                                   _reference_attention)


def main(B=1, S=256, H=2, D=64, causal=True):
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.3, jnp.float32)
    g = jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.3, jnp.float32)

    def loss_kernel(q, k, v):
        t = lambda x: jnp.moveaxis(x, 1, 2)
        o = flash_attention(t(q), t(k), t(v), causal=causal)
        return (jnp.moveaxis(o, 2, 1) * g).sum()

    def loss_ref(q, k, v):
        t = lambda x: jnp.moveaxis(x, 1, 2)
        o = _reference_attention(t(q), t(k), t(v), causal,
                                 1.0 / np.sqrt(D))
        return (jnp.moveaxis(o, 2, 1) * g).sum()

    dq, dk, dv = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in ((dq, rq, "dQ"), (dk, rk, "dK"), (dv, rv, "dV")):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-2, atol=3e-2)
        print(f"BSHD {name} matches jax AD of the dense reference.")


if __name__ == "__main__":
    main()
