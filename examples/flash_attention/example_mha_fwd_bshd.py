"""FlashAttention forward in the BSHD layout (reference
examples/flash_attention/example_mha_fwd_bshd.py behavior).

Framework tensors often arrive as (batch, seq, heads, dim). On TPU the
kernel wants the head axis in the grid and the (seq, dim) plane
contiguous in VMEM — i.e. BHSD — so the BSHD entry point is a transpose
at the boundary, fused by XLA into the surrounding program rather than
a second kernel family (the reference instead re-instantiates its CUDA
kernel per layout)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_attention import (flash_attention,
                                                   _reference_attention)


def flash_attention_bshd(q, k, v, causal=False, sm_scale=None):
    """q/k/v (B, S, H, D) -> (B, S, H, D)."""
    to_bhsd = lambda x: jnp.moveaxis(x, 1, 2)
    o = flash_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal=causal,
                        sm_scale=sm_scale)
    return jnp.moveaxis(o, 2, 1)


def main(B=1, S=512, H=4, D=64, causal=True):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    out = flash_attention_bshd(q, k, v, causal=causal)
    ref = _reference_attention(
        *(jnp.moveaxis(x, 1, 2) for x in (q, k, v)), causal,
        1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(jnp.moveaxis(out, 1, 2)),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)
    print(f"BSHD flash attention fwd (causal={causal}) matches reference.")


if __name__ == "__main__":
    main()
