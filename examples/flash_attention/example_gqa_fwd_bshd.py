"""GQA forward in the BSHD layout (reference
examples/flash_attention/example_gqa_fwd_bshd.py behavior): grouped KV
heads, layout adapted at the boundary."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops import gqa_attention
from tilelang_mesh_tpu.ops.flash_attention import _reference_attention


def main(B=1, S=512, Hq=8, Hkv=2, D=64, causal=True):
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((B, S, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), jnp.float32)

    t = lambda x: jnp.moveaxis(x, 1, 2)
    o = jnp.moveaxis(gqa_attention(t(q), t(k), t(v), causal=causal), 2, 1)

    group = Hq // Hkv
    kx = jnp.repeat(t(k), group, axis=1)
    vx = jnp.repeat(t(v), group, axis=1)
    ref = _reference_attention(t(q), kx, vx, causal, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(t(o)), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    print(f"BSHD GQA fwd Hq={Hq} Hkv={Hkv} matches the grouped reference.")


if __name__ == "__main__":
    main()
