"""Varlen (ragged-batch) FlashAttention forward with cu_seqlens packing
(reference examples/flash_attention/example_mha_fwd_varlen.py behavior:
packed (total, H, D) tensors, no attention across sequence boundaries)."""

import numpy as np

from tilelang_mesh_tpu.ops import flash_attention_varlen


def main(B=4, max_seqlen=96, H=4, D=64, causal=True):
    rng = np.random.default_rng(0)
    lens = rng.integers(1, max_seqlen + 1, B)
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    total = int(cu[-1])
    q = rng.standard_normal((total, H, D)).astype(np.float32)
    k = rng.standard_normal((total, H, D)).astype(np.float32)
    v = rng.standard_normal((total, H, D)).astype(np.float32)

    out = np.asarray(flash_attention_varlen(q, k, v, cu, cu, causal=causal,
                                            block_M=32, block_N=32))

    # padded-dense reference, per sequence
    for b in range(B):
        qi, ki, vi = (x[cu[b]:cu[b + 1]] for x in (q, k, v))
        s = np.einsum("qhd,khd->hqk", qi, ki) / np.sqrt(D)
        if causal:
            L = qi.shape[0]
            s = np.where(np.arange(L)[:, None] >= np.arange(L)[None, :],
                         s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("hqk,khd->qhd", p, vi)
        np.testing.assert_allclose(out[cu[b]:cu[b + 1]], ref, rtol=2e-2,
                                   atol=2e-2)
    print(f"varlen MHA fwd matches per-sequence reference "
          f"(B={B}, lens={lens.tolist()}, causal={causal}).")


if __name__ == "__main__":
    main()
