"""FlashAttention backward through the dKdV/dQ tile kernels (reference
examples/flash_attention/example_mha_bwd_bshd.py behavior): gradients
from the custom-vjp path must match jax AD of the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.flash_attention import (_reference_attention,
                                                   flash_attention)


def main(B=1, H=4, S=128, D=64, causal=True):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_M=64, block_N=64) * g)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal,
                                            1.0 / np.sqrt(D)) * g)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-2,
                                   atol=3e-2, err_msg=name)
    print(f"flash attention bwd (causal={causal}) gradients match jax AD.")


if __name__ == "__main__":
    main()
