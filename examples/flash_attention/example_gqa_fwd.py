"""Grouped-query attention forward (reference
examples/flash_attention/example_gqa_fwd_bshd.py behavior): Hkv < Hq
query heads share each KV head through the block-mapped KV fetch."""

import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.gqa import _reference_gqa, gqa_attention


def main(B=1, Hq=8, Hkv=2, S=256, D=64, causal=True):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)

    out = gqa_attention(q, k, v, causal=causal)
    ref = _reference_gqa(q, k, v, causal, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)
    print(f"GQA fwd (Hq={Hq}, Hkv={Hkv}, causal={causal}) matches "
          f"reference.")


if __name__ == "__main__":
    main()
