"""Varlen attention with the bottom-right causal alignment
(FlashAttention >= 2.1 convention; cf. the reference's varlen examples).

When a sequence's q and k lengths differ — speculative decoding,
suffix-scoring, chunked prefill — "causal" is ambiguous: anchor the
diagonal at the START of both sequences (top-left, local positions) or
at the END (bottom-right, the upstream convention where the LAST query
sees every key). Both are supported; this example shows they differ and
that bottom-right matches the per-sequence dense reference."""

import numpy as np

from tilelang_mesh_tpu.ops import flash_attention_varlen


def _dense_ref(q, k, v, lens_q, lens_k, align):
    B, Sq, H, D = q.shape
    out = np.zeros_like(q)
    for b in range(B):
        for h in range(H):
            qi, ki, vi = (q[b, :lens_q[b], h], k[b, :lens_k[b], h],
                          v[b, :lens_k[b], h])
            s = (qi @ ki.T) / np.sqrt(D)
            off = (lens_k[b] - lens_q[b]) if align == "bottom-right" else 0
            mask = (np.arange(s.shape[0])[:, None] + off
                    >= np.arange(s.shape[1])[None, :])
            s = np.where(mask, s, -np.inf)
            with np.errstate(invalid="ignore"):
                p = np.exp(s - s.max(-1, keepdims=True, initial=-np.inf))
            p = np.nan_to_num(p)
            denom = p.sum(-1, keepdims=True)
            out[b, :lens_q[b], h] = np.where(denom > 0,
                                             p / np.maximum(denom, 1e-30),
                                             0.0) @ vi
    return out


def main(B=3, H=2, D=32):
    rng = np.random.default_rng(3)
    lens_q = np.array([9, 24, 40])
    lens_k = np.array([17, 24, 30])   # mixed: longer and shorter than q
    q = rng.standard_normal((B, lens_q.max(), H, D)).astype(np.float32)
    k = rng.standard_normal((B, lens_k.max(), H, D)).astype(np.float32)
    v = rng.standard_normal((B, lens_k.max(), H, D)).astype(np.float32)
    pack = lambda x, lens: np.concatenate(
        [x[b, :lens[b]] for b in range(B)], 0)
    cu_q = np.concatenate([[0], np.cumsum(lens_q)]).astype(np.int32)
    cu_k = np.concatenate([[0], np.cumsum(lens_k)]).astype(np.int32)

    outs = {}
    for align in ("top-left", "bottom-right"):
        o = np.asarray(flash_attention_varlen(
            pack(q, lens_q), pack(k, lens_k), pack(v, lens_k), cu_q, cu_k,
            causal=True, causal_align=align, block_M=32, block_N=32))
        ref = pack(_dense_ref(q, k, v, lens_q, lens_k, align), lens_q)
        np.testing.assert_allclose(o, ref, rtol=2e-2, atol=2e-2)
        outs[align] = o
        print(f"varlen causal ({align}) matches the dense reference.")
    assert np.abs(outs["top-left"] - outs["bottom-right"]).max() > 1e-3, \
        "conventions must differ when lens_q != lens_k"
    print("the two alignments disagree on cross-length sequences, "
          "as they must.")


if __name__ == "__main__":
    main()
