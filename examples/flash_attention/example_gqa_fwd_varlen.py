"""Varlen grouped-query attention forward (reference
examples/flash_attention/example_gqa_fwd_varlen.py behavior): packed
ragged batch where Hkv < Hq query heads share each KV head."""

import numpy as np

from tilelang_mesh_tpu.ops import flash_attention_varlen


def main(B=3, max_seqlen=80, Hq=8, Hkv=2, D=64):
    rng = np.random.default_rng(1)
    lens = rng.integers(1, max_seqlen + 1, B)
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    total = int(cu[-1])
    q = rng.standard_normal((total, Hq, D)).astype(np.float32)
    k = rng.standard_normal((total, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((total, Hkv, D)).astype(np.float32)

    out = np.asarray(flash_attention_varlen(q, k, v, cu, cu, causal=True,
                                            block_M=32, block_N=32))

    group = Hq // Hkv
    for b in range(B):
        qi = q[cu[b]:cu[b + 1]]
        ki = k[cu[b]:cu[b + 1]]
        vi = v[cu[b]:cu[b + 1]]
        L = qi.shape[0]
        for h in range(Hq):
            s = (qi[:, h] @ ki[:, h // group].T) / np.sqrt(D)
            s = np.where(np.arange(L)[:, None] >= np.arange(L)[None, :],
                         s, -np.inf)
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            np.testing.assert_allclose(out[cu[b]:cu[b + 1], h],
                                       p @ vi[:, h // group],
                                       rtol=2e-2, atol=2e-2)
    print(f"varlen GQA fwd matches reference (B={B}, Hq={Hq}, Hkv={Hkv}, "
          f"lens={lens.tolist()}).")


if __name__ == "__main__":
    main()
