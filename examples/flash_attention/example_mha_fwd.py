"""FlashAttention forward (reference examples/flash_attention/
example_mha_fwd_bhsd.py behavior)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_attention import (flash_attention,
                                                   _reference_attention,
                                                   mha_fwd_kernel)


def main(B=1, H=4, S=512, D=64, causal=True):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    out = flash_attention(q, k, v, causal=causal)
    ref = _reference_attention(q, k, v, causal, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)
    print(f"flash attention fwd (causal={causal}) matches reference.")
    kern = mha_fwd_kernel(B, H, S, S, D, causal=causal, dtype="float32")
    lat = kern.get_profiler().do_bench(warmup=1, rep=5, backend="wall")
    print(f"latency: {lat:.3f} ms")


if __name__ == "__main__":
    main()
