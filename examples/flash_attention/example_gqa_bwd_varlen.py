"""Varlen GQA backward (reference
examples/flash_attention/example_gqa_bwd_tma_reduce_varlen.py behavior):
gradients through the packed ragged batch — the document masks drive the
dKdV/dQ recompute kernels, dK/dV accumulate across the query-head group,
and no gradient crosses a sequence boundary."""

import jax
import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops import flash_attention_varlen


def main(Hq=4, Hkv=2, D=64):
    rng = np.random.default_rng(0)
    lens = [40, 56, 24]
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    total = int(cu[-1])
    q = jnp.asarray(rng.standard_normal((total, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, Hkv, D)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((total, Hq, D)), jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention_varlen(
            q, k, v, cu, cu, causal=True, block_M=32, block_N=32) * g)

    def loss_ref(q, k, v):
        group = Hq // Hkv
        tot = 0.0
        for b in range(len(lens)):
            qi = q[cu[b]:cu[b + 1]]
            ki = jnp.repeat(k[cu[b]:cu[b + 1]], group, axis=1)
            vi = jnp.repeat(v[cu[b]:cu[b + 1]], group, axis=1)
            s = jnp.einsum("qhd,khd->hqk", qi, ki) / np.sqrt(D)
            Li = qi.shape[0]
            s = jnp.where(jnp.tril(jnp.ones((Li, Li), bool))[None], s,
                          -jnp.inf)
            p = jnp.exp(s - s.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            o = jnp.einsum("hqk,khd->qhd", p, vi)
            tot = tot + jnp.sum(o * g[cu[b]:cu[b + 1]])
        return tot

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)
    print(f"varlen GQA bwd (lens={lens}, Hq={Hq}, Hkv={Hkv}) gradients "
          f"match jax AD; no cross-sequence gradient flow.")


if __name__ == "__main__":
    main()
