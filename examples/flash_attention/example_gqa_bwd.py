"""GQA backward through the group-accumulating dKdV kernel (reference
examples/flash_attention/example_gqa_bwd.py behavior): dK/dV sum the
contributions of every query head in the group."""

import jax
import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.gqa import _reference_gqa, gqa_attention


def main(B=1, Hq=4, Hkv=2, S=128, D=64, causal=True):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(gqa_attention(q, k, v, causal=causal,
                                     block_M=64, block_N=64) * g)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_gqa(q, k, v, causal,
                                      1.0 / np.sqrt(D)) * g)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-2,
                                   atol=3e-2, err_msg=name)
    print(f"GQA bwd (Hq={Hq}, Hkv={Hkv}) gradients match jax AD, "
          f"dK/dV accumulated across the {Hq // Hkv}-head group.")


if __name__ == "__main__":
    main()
