"""Per-kernel compile configuration (reference examples/compile_flags/
usecase.py, which passes nvcc flags like -O3/--use_fast_math).

On TPU the compile knobs are pass_configs threaded to the Mosaic pipeline:
fast-math intrinsic lowering, VMEM budget, grid dimension semantics
("parallel"/"arbitrary" per axis), and interpret mode
(tilelang_mesh_tpu/transform/pass_config.py PassConfigKey).
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

M = N = 512


def make_func():
    @T.prim_func
    def softmax_scale(A: T.Tensor((M, N), "float32"),
                      B: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(M, 128)) as bx:
            s = T.alloc_shared((128, N), "float32")
            m = T.alloc_fragment((128,), "float32")
            T.copy(A[bx * 128, 0], s)
            T.reduce_max(s, m, dim=1, clear=True)
            for i, j in T.Parallel(128, N):
                s[i, j] = T.exp(s[i, j] - m[i])
            T.copy(s, B[bx * 128, 0])
    return softmax_scale


def main():
    a = np.random.default_rng(0).standard_normal((M, N), dtype=np.float32)
    ref = np.exp(a - a.max(axis=1, keepdims=True))

    # default compile
    k_plain = tilelang.compile(make_func())
    # fast-math: T.exp lowers to the fast exp2-based approximation
    k_fast = tilelang.compile(
        make_func(),
        pass_configs={tilelang.PassConfigKey.TL_ENABLE_FAST_MATH: True})
    # explicit grid semantics + VMEM budget for the Mosaic compiler
    k_tuned = tilelang.compile(
        make_func(),
        pass_configs={"tl.tpu.dimension_semantics": ("arbitrary",),
                      "tl.tpu.vmem_limit_bytes": 64 * 1024 * 1024})

    for name, k, tol in (("default", k_plain, 1e-5),
                         ("fast-math", k_fast, 1e-2),
                         ("tuned", k_tuned, 1e-5)):
        out = np.empty((M, N), np.float32)
        k(a, out)
        np.testing.assert_allclose(out, ref, rtol=tol, atol=tol)
        print(f"{name:10s} compile: correct (tol {tol})")
    src = k_tuned.get_kernel_source()
    assert f"vmem_limit_bytes={64 * 1024 * 1024}" in src, \
        "pass configs must reach the generated pallas_call"
    print("pass_configs reached the generated kernel ✓")


if __name__ == "__main__":
    main()
