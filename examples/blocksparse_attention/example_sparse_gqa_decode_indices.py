"""Sparse GQA decode from per-head block indices (reference
examples/blocksparse_attention/example_tilelang_sparse_gqa_decode_varlen_indice.py
behavior): at decode time each KV head attends only its selected cache
blocks — the serving-side sparse-attention configuration.

On TPU this is the NSA selected-branch decode kernel: the block index
list drives data-dependent DMA of just the live blocks; grouped query
heads (GQA) share each KV head's selection."""

import math

import numpy as np
import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.nsa import nsa_decode


def main(B=2, HQ=8, H=2, Tk=1024, D=64, BS=64, S=6):
    rng = np.random.default_rng(0)
    G = HQ // H
    q = jnp.asarray(rng.standard_normal((B, HQ, D)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Tk, H, D)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Tk, H, D)) * 0.3, jnp.float32)
    # each head selects S distinct cache blocks (always incl. the last —
    # the block holding the current token)
    n_blocks = Tk // BS
    bi = np.stack([np.stack([
        np.sort(np.concatenate([
            rng.choice(n_blocks - 1, S - 1, replace=False),
            [n_blocks - 1]]))
        for _ in range(H)]) for _ in range(B)]).astype(np.int32)
    g_slc = jnp.ones((B, HQ), jnp.float32)

    out = nsa_decode(q, k, v, g_slc, jnp.asarray(bi), block_size=BS)

    # dense reference over ONLY the selected tokens
    sm = 1.0 / math.sqrt(D)
    want = np.zeros((B, HQ, D), np.float32)
    for b in range(B):
        for hq in range(HQ):
            h = hq // G
            rows = np.concatenate(
                [np.arange(i * BS, (i + 1) * BS) for i in bi[b, h]])
            ks, vs = np.asarray(k)[b, rows, h], np.asarray(v)[b, rows, h]
            s = ks @ np.asarray(q)[b, hq] * sm
            p = np.exp(s - s.max())
            want[b, hq] = (p / p.sum()) @ vs
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-2, atol=2e-2)
    print(f"sparse GQA decode over {S}/{n_blocks} selected blocks "
          f"matches the dense-over-selection reference.")


if __name__ == "__main__":
    main()
