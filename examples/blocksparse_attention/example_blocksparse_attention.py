"""Block-sparse attention with a user-supplied block mask (reference
examples/blocksparse_attention behavior)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.blocksparse_attention import (
    blocksparse_attention, blocksparse_reference)


def main(B=1, H=2, S=256, D=64, bm=64, bn=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    mask = jnp.asarray(rng.integers(0, 2, (B, H, S // bm, S // bn)),
                       jnp.int32)
    mask = mask.at[:, :, jnp.arange(S // bm), jnp.arange(S // bn)].set(1)
    for causal in (False, True):
        out = blocksparse_attention(q, k, v, mask, block_M=bm, block_N=bn,
                                    causal=causal)
        ref = blocksparse_reference(q, k, v, mask, bm, bn, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)
    print("block-sparse attention (dense-mask + causal) matches reference.")


if __name__ == "__main__":
    main()
