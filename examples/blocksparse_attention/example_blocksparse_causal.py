"""Block-sparse attention composed WITH the elementwise causal mask
(reference examples/blocksparse_attention causal variants — the
seer-attention configuration): the block mask prunes whole KV tiles,
causal masking handles the diagonal, and a local-band mask demonstrates
sliding-window sparsity."""

import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.blocksparse_attention import blocksparse_attention


def main(B=1, H=4, S=512, D=64, BM=128, band=2):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)

    nb = S // BM
    # local band: query block i attends key blocks (i-band, i]
    bi = np.arange(nb)
    mask = ((bi[:, None] - bi[None, :] >= 0) &
            (bi[:, None] - bi[None, :] < band)).astype(np.int32)
    block_mask = jnp.asarray(np.broadcast_to(mask, (B, H, nb, nb)))

    out = np.asarray(blocksparse_attention(q, k, v, block_mask,
                                           block_M=BM, block_N=BM,
                                           causal=True))

    # dense reference with the same band+causal mask
    rows = np.arange(S)
    block = rows // BM
    vis = ((block[:, None] - block[None, :] >= 0) &
           (block[:, None] - block[None, :] < band) &
           (rows[:, None] >= rows[None, :]))
    s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k)) \
        / np.sqrt(D)
    s = np.where(vis, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, np.asarray(v))
    np.testing.assert_allclose(out, want, rtol=2e-2, atol=2e-2)
    dens = mask.mean()
    print(f"block-sparse causal band attention (density {dens:.2f}) "
          f"matches the dense-masked reference.")


if __name__ == "__main__":
    main()
