"""Expert-parallel fused MoE over a device mesh with ICI all-to-all
(reference examples/fusedmoe; BASELINE config #5)."""

import numpy as np
import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.parallel.moe import make_moe_layer, moe_reference


def main(T=512, d=128, f=256, E=8, top_k=2):
    n = min(len(jax.devices()), E)
    while E % n:
        n -= 1
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:n]), ("ep",))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((T, d)) * 0.5, jnp.float32)
    wr = jnp.asarray(rng.standard_normal((d, E)) * 0.5, jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((E, d, f)) * 0.2, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((E, f, d)) * 0.2, jnp.float32)
    layer = make_moe_layer(mesh, "ep", top_k=top_k, capacity_factor=8.0)
    out = layer(x, wr, w1, w2)
    ref = moe_reference(x, wr, w1, w2, top_k)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-2,
                               atol=3e-1)
    print(f"fused MoE over {n}-device ep mesh matches dense reference.")


if __name__ == "__main__":
    main()
