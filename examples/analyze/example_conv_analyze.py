"""Static perf analysis of the conv kernel (reference
examples/analyze/example_conv_analyze.py behavior): the analyzer walks
the traced tile IR, counts FLOPs and HBM bytes, and reports per-arch
roofline estimates — before anything compiles or runs."""

import os
import sys

# the conv factory lives in a sibling example; make direct invocation
# (python examples/analyze/example_conv_analyze.py) find the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from tilelang_mesh_tpu.carver import TPU_V5E, TPU_V5P
from tilelang_mesh_tpu.tools import Analyzer


def main(N=8, C=128, H=32, W=32, F=128, K=3):
    """The shifted-window conv (examples/convolution) at a standard
    ResNet-ish shape, analyzed for two TPU generations."""
    from examples.convolution.example_convolution import convolution

    # analyze the TRACED prim_func, pre-compilation (the analyzer works
    # on tile IR; @tilelang.jit keeps the raw factory on __wrapped__)
    pf = convolution.__wrapped__(N, C, H, W, F, K, 1, 1, 1)
    for arch in (TPU_V5E, TPU_V5P):
        r = Analyzer.analysis(pf, arch)
        print(f"{arch.name}: {r}")


if __name__ == "__main__":
    main()
