"""Static perf analysis of a kernel (reference examples/analyze:
tilelang/tools/Analyzer)."""

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.tools import Analyzer
from tilelang_mesh_tpu.carver import TPU_V5E, TPU_V5P


def main(M=4096, N=4096, K=4096):
    @T.prim_func
    def gemm(A: T.Tensor((M, K), "bfloat16"),
             B: T.Tensor((K, N), "bfloat16"),
             C: T.Tensor((M, N), "bfloat16")):
        with T.Kernel(T.ceildiv(N, 256), T.ceildiv(M, 256)) as (bx, by):
            A_s = T.alloc_shared((256, 512), "bfloat16")
            B_s = T.alloc_shared((512, 256), "bfloat16")
            C_l = T.alloc_fragment((256, 256), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, 512), num_stages=2):
                T.copy(A[by * 256, ko * 512], A_s)
                T.copy(B[ko * 512, bx * 256], B_s)
                T.gemm(A_s, B_s, C_l)
            T.copy(C_l, C[by * 256, bx * 256])

    for arch in (TPU_V5E, TPU_V5P):
        r = Analyzer.analysis(gemm, arch)
        print(f"{arch.name}: {r}")


if __name__ == "__main__":
    main()
