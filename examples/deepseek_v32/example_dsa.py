"""DeepSeek V3.2 sparse attention pipeline (reference examples/deepseek_v32).

End-to-end: lightning indexer (relu(qI·kI) head-mix) -> causal top-k token
selector -> sparse MLA attention over only the selected latent-KV tokens.
The three tile kernels mirror fp8_lighting_indexer.py, topk_selector.py and
sparse_mla_fwd.py; the gather rides data-dependent in-kernel DMA.
"""

import numpy as np

from tilelang_mesh_tpu.ops.dsa import (lightning_indexer, sparse_mla_fwd,
                                       sparse_mla_reference, topk_selector)


def main(B=1, S=64, Skv=128, HI=4, DI=32, H=8, D=128, DT=64, topk=32):
    rng = np.random.default_rng(0)
    q_idx = rng.standard_normal((B, S, HI, DI), dtype=np.float32)
    k_idx = rng.standard_normal((B, Skv, DI), dtype=np.float32)
    w = rng.standard_normal((B, S, HI)).astype(np.float32)

    logits = lightning_indexer(q_idx, k_idx, w)
    indices = topk_selector(logits, topk)
    print(f"indexer+selector: each of {S} query tokens picked top-{topk} "
          f"of {Skv} KV tokens (causal)")

    q = rng.standard_normal((B, S, H, D + DT), dtype=np.float32)
    kv = rng.standard_normal((B, Skv, D + DT), dtype=np.float32)
    o, lse = sparse_mla_fwd(q, kv, np.asarray(indices), block_I=16)
    o_ref, lse_ref = sparse_mla_reference(q, kv, np.asarray(indices))
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               rtol=1e-3, atol=1e-3)
    print("sparse MLA over selected tokens matches dense-gather reference ✓")


if __name__ == "__main__":
    main()
