"""Split-KV decode with an explicit split count (reference
examples/flash_decoding split variants): n_split is the
latency/parallelism knob — every split processes S/n_split of the KV
cache in a parallel grid step and a tiny XLA epilogue merges the
(o, m, l) partials. Outputs must be identical across split counts."""

import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.flash_decoding import flash_decode


def main(B=2, H=8, S=2048, D=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)

    # dense reference
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    want = np.asarray(jnp.einsum("bhqk,bhkd->bhqd", p, v))

    outs = {}
    for n_split in (1, 4, 8):
        o = np.asarray(flash_decode(q, k, v, n_split=n_split))
        np.testing.assert_allclose(o, want, rtol=2e-2, atol=2e-2)
        outs[n_split] = o
    np.testing.assert_allclose(outs[1], outs[8], rtol=1e-3, atol=1e-3)
    print(f"flash decode B={B} H={H} S={S}: splits 1/4/8 agree and "
          f"match the dense reference.")


if __name__ == "__main__":
    main()
