"""Split-KV flash decoding + paged KV (reference examples/flash_decoding)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_attention import _reference_attention
from tilelang_mesh_tpu.ops.flash_decoding import (flash_decode,
                                                  flash_decode_paged)


def main(B=2, H=4, S=1024, D=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    out = flash_decode(q, k, v, n_split=8)
    ref = _reference_attention(q, k, v, False, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)
    print("split-KV decode matches dense attention.")

    # paged variant
    page, per_seq, n_pages = 128, S // 128, 32
    kp = jnp.asarray(rng.standard_normal((n_pages, page, H, D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((n_pages, page, H, D)), jnp.float32)
    table = jnp.asarray(rng.choice(n_pages, (B, per_seq), replace=False),
                        jnp.int32)
    out_p = flash_decode_paged(q, kp, vp, table)
    print("paged decode output:", out_p.shape)


if __name__ == "__main__":
    main()
