"""GQA decode (reference examples/flash_decoding/example_gqa_decode.py
behavior): one query token per sequence, grouped query heads sharing
each KV head's cache — the bandwidth-bound serving configuration where
GQA earns its keep (KV traffic is divided by the group size).

TPU shape: the (8, 128) min-tile means a query block is at least 8
rows, so the GROUP's query rows (group <= 8) ride in one tile: q is
reshaped to (B, Hkv, group, D) and padded to 8 rows, and plain flash
attention over Hkv heads streams each KV head's cache exactly ONCE for
the whole group."""

import math

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops import flash_attention


def gqa_decode(q, k, v, sm_scale=None):
    """q (B, Hq, D) one token; k/v (B, Hkv, S, D) cache -> (B, Hq, D).

    The group's rows share one query tile: each KV head's cache is
    fetched once per GROUP, not once per query head (group <= 8)."""
    B, Hq, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    group = Hq // Hkv
    assert group <= 8, "one min-tile carries at most 8 query rows"
    # (B, Hkv, group, D), padded to the 8-row min-tile
    qg = q.reshape(B, Hkv, group, D)
    qp = jnp.pad(qg, ((0, 0), (0, 0), (0, 8 - group), (0, 0)))
    o = flash_attention(qp, k, v, causal=False, sm_scale=sm_scale,
                        block_M=8, block_N=min(512, S))
    return o[:, :, :group, :].reshape(B, Hq, D)


def main(B=2, Hq=8, Hkv=2, S=2048, D=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)) * 0.3, jnp.float32)

    out = gqa_decode(q, k, v)

    group = Hq // Hkv
    sm = 1.0 / math.sqrt(D)
    want = np.zeros((B, Hq, D), np.float32)
    for b in range(B):
        for h in range(Hq):
            ks, vs = np.asarray(k)[b, h // group], np.asarray(v)[b, h // group]
            s = ks @ np.asarray(q)[b, h] * sm
            p = np.exp(s - s.max())
            want[b, h] = (p / p.sum()) @ vs
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-2, atol=2e-2)
    print(f"GQA decode Hq={Hq} Hkv={Hkv}: KV streamed once per group, "
          f"matches dense attention.")


if __name__ == "__main__":
    main()
