"""Paged-KV flash decoding (reference examples/deepseek_mla/
example_mla_decode_paged.py class of serving workload, for plain MHA).

The KV cache lives in a page pool indexed by a per-sequence page table
(vLLM layout). Two TPU strategies, both in the box:

- gather-then-kernel (`flash_decode_paged`): one XLA gather makes the
  cache contiguous, then the dense split-KV decode kernel runs — XLA
  pipelines the gather well, and the kernel's fetches stay sequential.
- in-kernel page walking (`flash_decode_paged_pool`): the kernel DMAs
  each page at its table-driven offset from an H-major pool — no
  cache-wide gather pass at all; the mandatory traffic drops to one
  read of the LIVE pages.

`bench.py::cfg_paged_decode` races both on hardware and keeps the
faster; this example checks both against dense attention."""

import math

import numpy as np
import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_decoding import (flash_decode_paged,
                                                  flash_decode_paged_pool,
                                                  pages_to_hmajor)


def main(B=2, H=4, S=1024, D=64, page=128):
    rng = np.random.default_rng(0)
    n_pages = B * S // page
    k_pages = jnp.asarray(rng.standard_normal((n_pages, page, H, D)) * 0.2,
                          jnp.float32)
    v_pages = jnp.asarray(rng.standard_normal((n_pages, page, H, D)) * 0.2,
                          jnp.float32)
    table = jnp.asarray(rng.permutation(n_pages).reshape(B, S // page),
                        jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)) * 0.2, jnp.float32)
    sm = 1.0 / math.sqrt(D)

    k = jnp.take(k_pages, table, axis=0).reshape(B, S, H, D)
    v = jnp.take(v_pages, table, axis=0).reshape(B, S, H, D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k.transpose(0, 2, 1, 3)) * sm
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1),
                      v.transpose(0, 2, 1, 3))

    o_gather = flash_decode_paged(q, k_pages, v_pages, table, sm_scale=sm,
                                  block_N=256, n_split=2)
    np.testing.assert_allclose(np.asarray(o_gather), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
    print("gather-then-kernel paged decode matches dense attention.")

    kp, vp = pages_to_hmajor(k_pages), pages_to_hmajor(v_pages)
    o_walk = flash_decode_paged_pool(q, kp, vp, table, page, sm_scale=sm,
                                     n_split=2)
    np.testing.assert_allclose(np.asarray(o_walk), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
    print("in-kernel page-walking decode matches dense attention "
          "(no gather pass).")


if __name__ == "__main__":
    main()
