"""Scheduling knobs and what they do to the plan (reference
examples/gemm/example_gemm_schedule.py territory): the same GEMM at
different tile shapes and pipeline depths, with the planner's decisions
printed side by side — on TPU "scheduling" is tile choice + staging
depth; Mosaic owns the instruction-level schedule."""

import numpy as np
import jax.numpy as jnp

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def make(M, N, K, bm, bn, bk, stages):
    @T.prim_func
    def gemm(A: T.Tensor((M, K), "float32"),
             B: T.Tensor((K, N), "float32"),
             C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(N, bn), T.ceildiv(M, bm)) as (bx, by):
            A_s = T.alloc_shared((bm, bk), "float32")
            B_s = T.alloc_shared((bk, bn), "float32")
            C_l = T.alloc_fragment((bm, bn), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, bk), num_stages=stages):
                T.copy(A[by * bm, ko * bk], A_s)
                T.copy(B[ko * bk, bx * bn], B_s)
                T.gemm(A_s, B_s, C_l)
            T.copy(C_l, C[by * bm, bx * bn])
    return tilelang.compile(gemm)


def main(M=256, N=256, K=512):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    ref = a @ b

    outs = []
    for bm, bn, bk, st in ((128, 128, 64, 1), (128, 128, 64, 3),
                           (256, 128, 128, 2)):
        kern = make(M, N, K, bm, bn, bk, st)
        c = np.asarray(kern(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_allclose(c, ref, rtol=1e-2, atol=1e-1)
        plan = kern.get_plan()
        print(f"--- tiles ({bm},{bn},{bk}) stages={st}")
        print("\n".join(plan.splitlines()[:6]))
        outs.append(c)
    # staging depth never changes numerics (same reduction order)...
    np.testing.assert_allclose(outs[1], outs[0], rtol=1e-7, atol=1e-7)
    # ...while a different block_K only reassociates the f32 sum
    np.testing.assert_allclose(outs[2], outs[0], rtol=1e-4, atol=1e-3)
    print("schedules agree (staging: bitwise; tile shape: up to f32 "
          "reassociation); only the plan differs.")


if __name__ == "__main__":
    main()
