"""Autotuned GEMM: carver hints -> config grid -> profiled best
(reference examples/gemm autotune flow)."""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.carver import MatmulTemplate


def make_factory(M, N, K, dtype="float32"):
    @tilelang.jit
    def matmul(M, N, K, block_M=128, block_N=128, block_K=64):
        @T.prim_func
        def kernel(A: T.Tensor((M, K), dtype),
                   B: T.Tensor((K, N), dtype),
                   C: T.Tensor((M, N), dtype)):
            with T.Kernel(T.ceildiv(N, block_N),
                          T.ceildiv(M, block_M)) as (bx, by):
                A_s = T.alloc_shared((block_M, block_K), dtype)
                B_s = T.alloc_shared((block_K, block_N), dtype)
                C_l = T.alloc_fragment((block_M, block_N), "float32")
                T.clear(C_l)
                for ko in T.Pipelined(T.ceildiv(K, block_K), num_stages=2):
                    T.copy(A[by * block_M, ko * block_K], A_s)
                    T.copy(B[ko * block_K, bx * block_N], B_s)
                    T.gemm(A_s, B_s, C_l)
                T.copy(C_l, C[by * block_M, bx * block_N])
        return kernel
    return matmul


def main(M=256, N=256, K=256):
    # the template IS the config grid: autotune asks the carver's
    # roofline-ranked policy for candidates at tune time
    tuned = tilelang.autotune(
        template=lambda M, N, K: MatmulTemplate(M, N, K, "float32"),
        topk=3, warmup=1, rep=3)(make_factory(M, N, K))
    kernel = tuned(M, N, K)
    print("carver candidates:",
          [r["config"] for r in kernel.autotune_results])
    print(f"best config: {kernel.config} @ {kernel.latency:.3f} ms")
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(kernel(a, b)), a @ b, rtol=1e-2,
                               atol=1e-1)
    print("autotuned GEMM correct.")


if __name__ == "__main__":
    main()
