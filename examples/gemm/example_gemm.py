"""The canonical GEMM (reference examples/gemm/example_gemm.py): the
quickstart's kernel without the fused epilogue — bf16 tiles on the MXU,
f32 accumulation, double-buffered K loop."""

import numpy as np
import jax.numpy as jnp

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


@tilelang.jit
def matmul(M, N, K, block_M=128, block_N=128, block_K=64,
           dtype="bfloat16"):
    @T.prim_func
    def gemm(A: T.Tensor((M, K), dtype),
             B: T.Tensor((K, N), dtype),
             C: T.Tensor((M, N), dtype)):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_s = T.alloc_shared((block_M, block_K), dtype)
            B_s = T.alloc_shared((block_K, block_N), dtype)
            C_l = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, block_K), num_stages=2):
                T.copy(A[by * block_M, ko * block_K], A_s)
                T.copy(B[ko * block_K, bx * block_N], B_s)
                T.gemm(A_s, B_s, C_l)
            T.copy(C_l, C[by * block_M, bx * block_N])
    return gemm


def main(M=256, N=256, K=256):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)), jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((K, N)), jnp.bfloat16)
    kernel = matmul(M, N, K)
    c = np.asarray(kernel(a, b), np.float32)
    ref = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    np.testing.assert_allclose(c, ref, rtol=2e-2, atol=2.0)
    print("bf16 GEMM matches the f32 product of bf16-rounded inputs.")


if __name__ == "__main__":
    main()
