"""IR-derived autotuning: no template, no config list — the tuner traces
the factory at its default tile params, classifies the kernel from its
tile IR, reconstructs M/N/K from the grid and loop extents, and sweeps
the carver's roofline-ranked space (reference flow:
carver/roller/node.py PrimFuncNode -> policy -> tuner grid)."""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def main(M=256, N=256, K=256):
    @tilelang.autotune(topk=3, warmup=1, rep=3)
    @tilelang.jit
    def matmul(M, N, K, block_M=128, block_N=128, block_K=64):
        @T.prim_func
        def kernel(A: T.Tensor((M, K), "float32"),
                   B: T.Tensor((K, N), "float32"),
                   C: T.Tensor((M, N), "float32")):
            with T.Kernel(T.ceildiv(N, block_N),
                          T.ceildiv(M, block_M)) as (bx, by):
                A_s = T.alloc_shared((block_M, block_K), "float32")
                B_s = T.alloc_shared((block_K, block_N), "float32")
                C_l = T.alloc_fragment((block_M, block_N), "float32")
                T.clear(C_l)
                for ko in T.Pipelined(T.ceildiv(K, block_K), num_stages=2):
                    T.copy(A[by * block_M, ko * block_K], A_s)
                    T.copy(B[ko * block_K, bx * block_N], B_s)
                    T.gemm(A_s, B_s, C_l)
                T.copy(C_l, C[by * block_M, bx * block_N])
        return kernel

    kernel = matmul(M, N, K)
    print("IR-derived candidates:",
          [r["config"] for r in kernel.autotune_results])
    print(f"best config: {kernel.config} @ {kernel.latency:.3f} ms")
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(kernel(a, b)), a @ b, rtol=1e-2,
                               atol=1e-1)
    print("IR-derived autotuned GEMM correct.")


if __name__ == "__main__":
    main()
