"""Sharded GEMM with MeshTensor parameters.

Mirror of the reference's examples/gemm/example_gemm_with_mesh_tensor.py:
kernel args are distributed tensors; the kernel body indexes the *local
shard*. On TPU the mesh is a jax device mesh and the sharded kernel runs
under shard_map.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.parallel import mesh_config


def matmul(M, N, K, block_M, block_N, block_K, mesh_device_config=(1, 1),
           dtype="float32"):
    with mesh_config(*mesh_device_config):
        @T.prim_func
        def gemm(
            A: T.MeshTensor((M, K), T.MeshShardingPolicy(y=0),
                            mesh_device_config, dtype),
            B: T.MeshTensor((K, N), T.MeshShardingPolicy(
                replicate=T.MeshReplicationType.ALL),
                mesh_device_config, dtype),
            C: T.MeshTensor((M, N), T.MeshShardingPolicy(y=0),
                            mesh_device_config, dtype),
        ):
            sharded_M, sharded_K = A.shape
            _, sharded_N = B.shape
            with T.Kernel(T.ceildiv(sharded_N, block_N),
                          T.ceildiv(sharded_M, block_M)) as (bx, by):
                A_shared = T.alloc_shared((block_M, block_K), dtype)
                B_shared = T.alloc_shared((block_K, block_N), dtype)
                C_local = T.alloc_fragment((block_M, block_N), "float32")
                T.clear(C_local)
                for k in T.Pipelined(T.ceildiv(sharded_K, block_K),
                                     num_stages=3):
                    T.copy(A[by * block_M, k * block_K], A_shared)
                    T.copy(B[k * block_K, bx * block_N], B_shared)
                    T.gemm(A_shared, B_shared, C_local)
                T.copy(C_local, C[by * block_M, bx * block_N])
        nrow, ncol = mesh_device_config
        return tilelang.compile(
            gemm, target=tilelang.determine_target() +
            f"-mesh[{nrow}x{ncol}]")


def main():
    import jax
    n = len(jax.devices())
    mesh_cfg = (2, 2) if n >= 4 else (1, 1)
    M = N = K = 256
    kernel = matmul(M, N, K, 64, 128, 64, mesh_cfg)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    c = kernel(a, b)
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-2, atol=1e-1)
    print(f"MeshTensor GEMM on mesh {mesh_cfg}: all checks passed.")
    print(kernel.get_plan())


if __name__ == "__main__":
    main()
