"""Dynamic-shape GEMM with bucketing (reference
examples/dynamic_shape/example_dynamic.py).

The reference compiles one CUDA kernel with symbolic M/N/K (tail-split
pass-configs); XLA requires static shapes, so the TPU design is per-shape
specialization (lazy_jit) plus *bucketing*: pad the dynamic dim up to the
next bucket so an unbounded stream of shapes compiles only O(log) kernels.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

M = T.dynamic("m")
N, K = 256, 512
BM = 64


@tilelang.lazy_jit(out_idx=[2],
                   pass_configs={"tl.disable_dynamic_tail_split": True,
                                 "tl.dynamic_alignment": 8})
def matmul_dyn(A: T.Tensor((M, K), "float32"),
               B: T.Tensor((K, N), "float32"),
               C: T.Tensor((M, N), "float32")):
    with T.Kernel(T.ceildiv(M, BM), T.ceildiv(N, 128)) as (bx, by):
        A_s = T.alloc_shared((BM, K), "float32")
        B_s = T.alloc_shared((K, 128), "float32")
        C_l = T.alloc_fragment((BM, 128), "float32")
        T.copy(A[bx * BM, 0], A_s)
        T.copy(B[0, by * 128], B_s)
        T.gemm(A_s, B_s, C_l, clear_accum=True)
        T.copy(C_l, C[bx * BM, by * 128])


def bucket(m: int) -> int:
    """Round m up to the next power-of-two multiple of BM (>= BM)."""
    b = BM
    while b < m:
        b *= 2
    return b


def matmul_bucketed(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    m = a.shape[0]
    mb = bucket(m)
    if mb != m:
        a = np.concatenate([a, np.zeros((mb - m, a.shape[1]), a.dtype)])
    return np.asarray(matmul_dyn(a, b))[:m]


# Since round 3 the manual padding above is built in:
# lazy_jit(dynamic_bucket=N) rounds dyn dims up, zero-pads inputs, and
# slices dyn output dims back — one decorator kwarg instead of a wrapper.
@tilelang.lazy_jit(out_idx=[2], dynamic_bucket=BM)
def matmul_auto(A: T.Tensor((M, K), "float32"),
                B: T.Tensor((K, N), "float32"),
                C: T.Tensor((M, N), "float32")):
    with T.Kernel(T.ceildiv(M, BM), T.ceildiv(N, 128)) as (bx, by):
        A_s = T.alloc_shared((BM, K), "float32")
        B_s = T.alloc_shared((K, 128), "float32")
        C_l = T.alloc_fragment((BM, 128), "float32")
        T.copy(A[bx * BM, 0], A_s)
        T.copy(B[0, by * 128], B_s)
        T.gemm(A_s, B_s, C_l, clear_accum=True)
        T.copy(C_l, C[bx * BM, by * 128])


def main():
    rng = np.random.default_rng(0)
    b = rng.standard_normal((K, N), dtype=np.float32)
    for m in (64, 100, 128, 999, 777):
        a = rng.standard_normal((m, K), dtype=np.float32)
        c = matmul_bucketed(a, b)
        np.testing.assert_allclose(c, a @ b, rtol=1e-2, atol=1e-1)
        print(f"m={m:4d} -> bucket {bucket(m):4d}: correct "
              f"({len(matmul_dyn._kernels)} kernels compiled)")
    # 100→128 and 999/777→1024 share buckets: only 3 kernels for 5 shapes
    assert len(matmul_dyn._kernels) == 3

    # built-in bucketing: same shapes through dynamic_bucket=BM
    for m in (64, 100, 999):
        a = rng.standard_normal((m, K), dtype=np.float32)
        c = np.asarray(matmul_auto(a, b))
        assert c.shape == (m, N)
        np.testing.assert_allclose(c, a @ b, rtol=1e-2, atol=1e-1)
    print(f"dynamic_bucket=BM: {len(matmul_auto._kernels)} kernels "
          f"for 3 shapes")


if __name__ == "__main__":
    main()
