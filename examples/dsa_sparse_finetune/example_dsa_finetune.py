"""Sparse fine-tuning through DSA attention (reference
examples/dsa_sparse_finetune: dsa.py + sparse_mla_bwd.py).

The sparse MLA op is made differentiable with jax.custom_vjp: the forward
pass runs the gather kernel (with LSE saved), the backward recomputes
through an XLA take_along_axis gather — gradients flow into both the
queries and the latent KV cache, which is exactly what DSA fine-tuning
updates. A tiny training loop drives the loss down to show the path works
end to end.
"""

import numpy as np

from tilelang_mesh_tpu.ops.dsa import (lightning_indexer, make_sparse_mla,
                                       sparse_mla_reference, topk_selector)


def main(B=1, S=32, Skv=64, H=4, D=128, DT=64, topk=16):
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(0)

    # select tokens once with the indexer (indices are not differentiated,
    # matching the reference finetune setup)
    q_idx = rng.standard_normal((B, S, 4, 32), dtype=np.float32)
    k_idx = rng.standard_normal((B, Skv, 32), dtype=np.float32)
    w = rng.standard_normal((B, S, 4)).astype(np.float32)
    indices = np.asarray(topk_selector(lightning_indexer(q_idx, k_idx, w),
                                       topk))

    sparse_mla = make_sparse_mla(block_I=16)
    q = jnp.asarray(rng.standard_normal((B, S, H, D + DT),
                                        dtype=np.float32))
    kv = jnp.asarray(rng.standard_normal((B, Skv, D + DT),
                                         dtype=np.float32))
    target = jnp.asarray(rng.standard_normal((B, S, H, D),
                                             dtype=np.float32))

    def loss_fn(q, kv):
        o = sparse_mla(q, kv, indices)
        return jnp.mean((o.astype(jnp.float32) - target) ** 2)

    # gradient check vs the pure-XLA dense-gather reference
    ref_loss = lambda q, kv: jnp.mean(
        (sparse_mla_reference(q, kv, indices)[0].astype(jnp.float32)
         - target) ** 2)
    g_kernel = jax.grad(loss_fn, argnums=(0, 1))(q, kv)
    g_ref = jax.grad(ref_loss, argnums=(0, 1))(q, kv)
    for a, b, name in zip(g_kernel, g_ref, ("dq", "dkv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=5e-3)
    print("grads through sparse MLA match the dense-gather reference ✓")

    losses = []
    lr = 0.05
    for step in range(8):
        l, (dq, dkv) = jax.value_and_grad(loss_fn, argnums=(0, 1))(q, kv)
        q, kv = q - lr * dq, kv - lr * dkv
        losses.append(float(l))
    assert losses[-1] < losses[0], f"loss must fall: {losses}"
    print(f"finetune loop: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
          f"over {len(losses)} steps ✓")


if __name__ == "__main__":
    main()
