"""Seer attention: learned-gate block-sparse causal attention (reference
examples/seer_attention/block_sparse_attn_tilelang.py behavior)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.seer_attention import (seer_attention,
                                                  seer_reference)


def main(B=1, H=2, S=256, D=64, bm=64, bn=64, topk=2):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    gates = jnp.asarray(rng.standard_normal((B, H, S // bm, S // bn)),
                        jnp.float32)
    out = seer_attention(q, k, v, gates, topk=topk, block_M=bm, block_N=bn)
    ref = seer_reference(q, k, v, gates, topk, bm, bn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    print(f"seer attention (top-{topk} gated blocks) matches reference.")


if __name__ == "__main__":
    main()
