"""Fast Walsh-Hadamard transform through the tile pipeline.

Behavioral mirror of the reference's examples/hadamard_transform/
example_hadamard.py (which butterflies via warp shuffles + smem exchanges).
TPU-first redesign: no shuffle network exists, but the MXU *is* a Hadamard
engine — factor H_n = (H_m ⊗ I_k)(I_m ⊗ H_k) with n = m*k and apply each
factor as dense GEMMs against the small Hadamard matrices:

  stage A (I_m ⊗ H_k): m contiguous (b, k) column slices  @ H_k
  stage B (H_m ⊗ I_k): k stride-k  (b, m) column gathers  @ H_m

Both stages are MXU matmuls of ±1 matrices, so the O(n log n) butterfly is
traded for O(n·(m+k)) FLOPs that run at matmul throughput — the standard
tensor-core Hadamard trick, here on the systolic array.
"""

import math

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def hadamard_matrix(n: int) -> np.ndarray:
    h = np.array([[1.0]], dtype=np.float32)
    while h.shape[0] < n:
        h = np.block([[h, h], [h, -h]])
    return h


@tilelang.jit
def hadamard(b, n, blk_b=128, dtype="float32"):
    assert n & (n - 1) == 0, "n must be a power of 2"
    logn = int(math.log2(n))
    m = 1 << (logn // 2)
    k = n // m

    @T.prim_func
    def hadamard_kernel(X: T.Tensor((b, n), dtype),
                        Hk: T.Tensor((k, k), dtype),
                        Hm: T.Tensor((m, m), dtype),
                        Out: T.Tensor((b, n), dtype)):
        with T.Kernel(T.ceildiv(b, blk_b)) as bx:
            x = T.alloc_shared((blk_b, n), dtype)
            hk = T.alloc_shared((k, k), dtype)
            hm = T.alloc_shared((m, m), dtype)
            stage_a = T.alloc_fragment((blk_b, n), "float32")
            col = T.alloc_shared((blk_b, m), dtype)
            seg = T.alloc_fragment((blk_b, m), "float32")

            T.copy(X[bx * blk_b, 0], x)
            T.copy(Hk, hk)
            T.copy(Hm, hm)
            # stage A: each k-wide column block through H_k (H_k symmetric)
            for s in range(m):
                T.gemm(x[0:blk_b, s * k:(s + 1) * k], hk,
                       stage_a[0:blk_b, s * k:(s + 1) * k], clear_accum=True)
            for i, j in T.Parallel(blk_b, n):
                x[i, j] = stage_a[i, j]
            # stage B: each stride-k column gather through H_m
            for j in range(k):
                for i, q in T.Parallel(blk_b, m):
                    col[i, q] = x[i, q * k + j]
                T.gemm(col, hm, seg, clear_accum=True)
                for i, q in T.Parallel(blk_b, m):
                    stage_a[i, q * k + j] = seg[i, q]
            T.copy(stage_a, Out[bx * blk_b, 0])

    return hadamard_kernel


def main(b=128, n=1024):
    kernel = hadamard(b, n)
    logn = int(math.log2(n))
    m = 1 << (logn // 2)
    k = n // m
    rng = np.random.default_rng(0)
    x = rng.standard_normal((b, n), dtype=np.float32)
    out = np.empty((b, n), dtype=np.float32)
    kernel(x, hadamard_matrix(k), hadamard_matrix(m), out)
    ref = x @ hadamard_matrix(n)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-2)
    print(f"Hadamard transform b={b} n={n} (H_{m} x H_{k} factorization) ✓")


if __name__ == "__main__":
    main()
