"""Split-K GEMM with atomic accumulation (reference
examples/gemm_splitk/example_tilelang_gemm_splitk_vectorize_atomicadd.py
behavior): every K-split adds its partial tile directly into the global
output with T.atomic_add.

On TPU grid steps execute sequentially per core, so the 'atomic' is a
plain read-modify-write on the revisited output tile — same program
shape as the reference, no partial buffer, no second reduction pass."""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


@tilelang.jit
def splitk_atomic(M, N, K, SK, block_M=128, block_N=128, block_K=128):
    assert K % SK == 0 and (K // SK) % block_K == 0, \
        "K must split evenly (ragged splits would double-count rows)"
    KS = K // SK

    @T.prim_func
    def gemm(A: T.Tensor((M, K), "float32"),
             B: T.Tensor((K, N), "float32"),
             C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M), SK) \
                as (bx, by, bk):
            A_s = T.alloc_shared((block_M, block_K), "float32")
            B_s = T.alloc_shared((block_K, block_N), "float32")
            acc = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(acc)
            for ko in T.Pipelined(T.ceildiv(KS, block_K), num_stages=2):
                T.copy(A[by * block_M, bk * KS + ko * block_K], A_s)
                T.copy(B[bk * KS + ko * block_K, bx * block_N], B_s)
                T.gemm(A_s, B_s, acc)
            # each split accumulates into the SAME output tile
            T.atomic_add(C[by * block_M, bx * block_N], acc)

    return gemm


def main(M=256, N=256, K=1024, SK=4):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K)).astype(np.float32) * 0.1
    b = rng.standard_normal((K, N)).astype(np.float32) * 0.1
    kern = splitk_atomic(M, N, K, SK)
    c = np.zeros((M, N), np.float32)
    kern(a, b, c)
    np.testing.assert_allclose(c, a @ b, rtol=1e-4, atol=1e-4)
    print(f"split-K={SK} atomic-accumulate GEMM correct.")


if __name__ == "__main__":
    main()
