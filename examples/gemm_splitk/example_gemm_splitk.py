"""Split-K GEMM (reference examples/gemm_splitk): the K reduction is split
over a parallel grid axis. The reference combines partials with atomic_add;
TPU has no HBM atomics, so each split writes its partial and XLA sums them."""

import numpy as np

import jax.numpy as jnp

from tilelang_mesh_tpu.ops import matmul_splitk


def main(M=256, N=256, K=1024):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.float32)
    c = matmul_splitk(a, b, n_split=4, out_dtype="float32")
    np.testing.assert_allclose(np.asarray(c),
                               np.asarray(a) @ np.asarray(b),
                               rtol=1e-4, atol=1e-4)
    print("split-K GEMM correct.")


if __name__ == "__main__":
    main()
