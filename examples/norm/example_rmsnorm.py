"""RMSNorm as a tile kernel (reference examples/norm)."""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def rmsnorm_kernel(M, N, block_M, dtype="float32", eps=1e-6):
    @T.prim_func
    def rmsnorm(A: T.Tensor((M, N), dtype),
                W: T.Tensor((N,), dtype),
                B: T.Tensor((M, N), dtype)):
        with T.Kernel(T.ceildiv(M, block_M)) as bx:
            A_s = T.alloc_shared((block_M, N), dtype)
            W_s = T.alloc_shared((N,), dtype)
            sq = T.alloc_fragment((block_M, N), "float32")
            ms = T.alloc_fragment((block_M,), "float32")
            T.copy(A[bx * block_M, 0], A_s)
            T.copy(W, W_s)
            for i, j in T.Parallel(block_M, N):
                sq[i, j] = A_s[i, j] * A_s[i, j]
            T.reduce_sum(sq, ms, dim=1)
            for i, j in T.Parallel(block_M, N):
                sq[i, j] = A_s[i, j] * T.rsqrt(ms[i] / N + eps) * W_s[j]
            T.copy(sq, B[bx * block_M, 0])
    return tilelang.compile(rmsnorm)


def main(M=512, N=256):
    k = rmsnorm_kernel(M, N, 128)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, N), dtype=np.float32)
    w = rng.standard_normal((N,), dtype=np.float32)
    out = k(a, w)
    ref = a / np.sqrt((a * a).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)
    print("rmsnorm kernel matches reference.")


if __name__ == "__main__":
    main()
