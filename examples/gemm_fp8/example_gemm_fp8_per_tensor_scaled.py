"""Per-tensor scaled fp8 GEMM — the training/serving recipe (reference
examples/gemm_fp8 family): activations and weights are cast to e4m3
with per-tensor scales chosen from their absmax, the MXU runs the fp8
product, and the epilogue multiplies the two scales back out in f32.

The scale epilogue fuses into the GEMM kernel's output loop — zero
extra HBM traffic, exactly like the quickstart's ReLU."""

import numpy as np
import jax.numpy as jnp

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

E4M3_MAX = 448.0


@tilelang.jit
def scaled_fp8_gemm(M, N, K, block_M, block_N, block_K):
    @T.prim_func
    def kern(A: T.Tensor((M, K), "float8_e4m3fn"),
             B: T.Tensor((K, N), "float8_e4m3fn"),
             Sc: T.Tensor((1, 1), "float32"),       # s_a * s_b
             C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_s = T.alloc_shared((block_M, block_K), "float8_e4m3fn")
            B_s = T.alloc_shared((block_K, block_N), "float8_e4m3fn")
            s_s = T.alloc_shared((1, 1), "float32")
            C_l = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(C_l)
            T.copy(Sc, s_s)
            for ko in T.Pipelined(T.ceildiv(K, block_K), num_stages=2):
                T.copy(A[by * block_M, ko * block_K], A_s)
                T.copy(B[ko * block_K, bx * block_N], B_s)
                T.gemm(A_s, B_s, C_l)
            for i, j in T.Parallel(block_M, block_N):
                C_l[i, j] = C_l[i, j] * s_s[0, 0]   # fused de-scale
            T.copy(C_l, C[by * block_M, bx * block_N])
    return kern


def main(M=256, N=256, K=256):
    rng = np.random.default_rng(0)
    a32 = rng.standard_normal((M, K)).astype(np.float32) * 3.0
    b32 = rng.standard_normal((K, N)).astype(np.float32) * 0.02

    s_a = float(np.abs(a32).max()) / E4M3_MAX
    s_b = float(np.abs(b32).max()) / E4M3_MAX
    a8 = jnp.asarray(a32 / s_a, jnp.float8_e4m3fn)
    b8 = jnp.asarray(b32 / s_b, jnp.float8_e4m3fn)
    sc = jnp.full((1, 1), s_a * s_b, jnp.float32)

    kern = scaled_fp8_gemm(M, N, K, 128, 128, 128)
    out = np.asarray(kern(a8, b8, sc))

    # truth from the actually-representable (rounded) fp8 values
    ref = (np.asarray(a8, np.float32) @ np.asarray(b8, np.float32)) \
        * (s_a * s_b)
    rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
    assert rel < 5e-2, rel
    print(f"per-tensor scaled fp8 GEMM correct (rel err {rel:.1e}; "
          f"s_a={s_a:.3g}, s_b={s_b:.3g}, de-scale fused in epilogue).")


if __name__ == "__main__":
    main()
