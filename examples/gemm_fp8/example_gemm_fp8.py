"""fp8 (e4m3) GEMM (reference benchmark/matmul_fp8)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gemm import matmul_kernel


def main(M=512, N=512, K=512):
    k = matmul_kernel(M, N, K, 128, 128, 128, in_dtype="float8_e4m3fn",
                      out_dtype="float32")
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.3, jnp.float8_e4m3fn)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.3, jnp.float8_e4m3fn)
    out = k(a, b)
    ref = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=5e-2, atol=5e-1)
    print("fp8 GEMM matches fp32 reference of fp8-rounded inputs.")


if __name__ == "__main__":
    main()
