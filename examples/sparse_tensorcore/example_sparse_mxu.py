"""Sparse "tensor core" GEMM on the MXU in bf16 (reference
examples/sparse_tensorcore/tilelang_example_sparse_tensorcore.py +
examples/gemm_sp/example_custom_compress.py).

Demonstrates the full custom-compress path: host 2:4 compression to the
int8 slot metadata format, metadata round-trip check, then a bf16 sparse
GEMM whose tiles decompress in VMEM ahead of the dense MXU dot.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.utils.sparse import (compress, decompress,
                                            randn_semi_sparse)


@tilelang.jit
def matmul_sp_bf16(M, N, K, block_M=128, block_N=128, block_K=128):
    @T.prim_func
    def kernel(A_sparse: T.Tensor((M, K // 2), "bfloat16"),
               E: T.Tensor((M, K // 2), "int8"),
               B: T.Tensor((K, N), "bfloat16"),
               C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_s = T.alloc_shared((block_M, block_K // 2), "bfloat16")
            E_s = T.alloc_shared((block_M, block_K // 2), "int8")
            B_s = T.alloc_shared((block_K, block_N), "bfloat16")
            C_l = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, block_K), num_stages=2):
                T.copy(A_sparse[by * block_M, ko * block_K // 2], A_s)
                T.copy(E[by * block_M, ko * block_K // 2], E_s)
                T.copy(B[ko * block_K, bx * block_N], B_s)
                T.gemm_sp(A_s, E_s, B_s, C_l)
            T.copy(C_l, C[by * block_M, bx * block_N])

    return kernel


def main(M=256, N=256, K=512):
    a = randn_semi_sparse(M, K, seed=0)
    a_sparse, e = compress(a)
    np.testing.assert_array_equal(decompress(a_sparse, e), a)
    print("compress/decompress metadata round-trip exact ✓")

    b = np.random.default_rng(1).standard_normal((K, N), dtype=np.float32)
    kernel = matmul_sp_bf16(M, N, K)
    c = np.empty((M, N), dtype=np.float32)
    import jax.numpy as jnp
    kernel(jnp.asarray(a_sparse, jnp.bfloat16), e,
           jnp.asarray(b, jnp.bfloat16), c)
    ref = np.asarray(jnp.asarray(a, jnp.bfloat16) @
                     jnp.asarray(b, jnp.bfloat16), np.float32)
    np.testing.assert_allclose(c, ref, rtol=5e-2, atol=5e-1)
    print(f"bf16 2:4 sparse GEMM {M}x{N}x{K} on the MXU ✓")


if __name__ == "__main__":
    main()
