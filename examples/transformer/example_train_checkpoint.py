"""Train the flagship transformer with orbax checkpoint save/resume.

The reference's checkpoint story covers compilation artifacts only
(SURVEY §5.4: kernel cache + autotune results). This example covers the
MODEL tier our framework adds on top: a tile-kernel transformer trained
for a few steps, checkpointed with orbax, and resumed bit-exactly —
the full train/save/restore loop a framework user needs.
"""

import tempfile

import jax
import jax.numpy as jnp
import numpy as np


def main(steps: int = 4, resume_at: int = 2):
    import orbax.checkpoint as ocp

    from tilelang_mesh_tpu.models import (ModelConfig, init_params,
                                          make_train_step)

    cfg = ModelConfig(vocab=64, d_model=64, n_layers=2, n_heads=4,
                      d_ff=128, max_seq=32, dtype=jnp.float32,
                      use_flash=False)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (4, cfg.max_seq + 1)),
                         jnp.int32)

    params = init_params(jax.random.PRNGKey(0), cfg)
    init, step = make_train_step(cfg, lr=1e-3)
    opt_state = init(params)

    ckpt_dir = tempfile.mkdtemp(prefix="tltpu-ckpt-")
    ckptr = ocp.StandardCheckpointer()

    losses = []
    for i in range(steps):
        if i == resume_at:
            ckptr.save(f"{ckpt_dir}/step{i}",
                       {"params": params, "opt_state": opt_state})
            ckptr.wait_until_finished()
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    print("losses:", [f"{l:.4f}" for l in losses])

    # resume from the checkpoint and replay: must match bit-exactly
    restored = ckptr.restore(
        f"{ckpt_dir}/step{resume_at}",
        {"params": params, "opt_state": opt_state})
    r_params, r_opt = restored["params"], restored["opt_state"]
    replay = []
    for i in range(resume_at, steps):
        r_params, r_opt, loss = step(r_params, r_opt, tokens)
        replay.append(float(loss))
    print("replayed:", [f"{l:.4f}" for l in replay])
    np.testing.assert_allclose(replay, losses[resume_at:], rtol=0, atol=0)
    print("checkpoint resume is bit-exact.")
    return losses, replay


if __name__ == "__main__":
    main()
