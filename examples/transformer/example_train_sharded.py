"""Train the flagship transformer with the megatron-style dp x tp sharded
step (the model tier built on the kernel library)."""

import numpy as np
import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.models import (ModelConfig, init_params,
                                      make_sharded_train_step)


def main(steps=3):
    n = len(jax.devices())
    tp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = n // tp
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()[:dp * tp]).reshape(dp, tp), ("dp", "tp"))
    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    cfg = ModelConfig(vocab=128, d_model=64, n_layers=2, n_heads=4,
                      d_ff=128, max_seq=64, use_flash=on_tpu)
    params = init_params(jax.random.PRNGKey(0), cfg)
    init, make = make_sharded_train_step(cfg, mesh, lr=1e-2)
    opt_state = init(params)
    step = make(params, opt_state)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (dp * 2, cfg.max_seq + 1)),
                         jnp.int32)
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
        print(f"step {i}: loss {float(loss):.4f}  (mesh dp={dp} tp={tp})")


if __name__ == "__main__":
    main()
