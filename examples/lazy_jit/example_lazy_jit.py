"""lazy_jit: shape-from-tensor kernel specialization (reference
examples/lazy_jit/lazyjit.ipynb + tilelang/jit/__init__.py:547).

Declare shapes with T.dynamic symbols; the first call with each concrete
shape traces + compiles a specialized kernel (XLA needs static shapes), and
later calls reuse the per-shape cache — the pragmatic answer to dynamic
shapes on TPU (SURVEY §7 hard-parts)."""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

M = T.dynamic("m")   # number of tokens: varies call to call
N, K = 256, 256
BM = 64


@tilelang.lazy_jit(out_idx=[2])
def matmul(A: T.Tensor((M, K), "float32"),
           B: T.Tensor((K, N), "float32"),
           C: T.Tensor((M, N), "float32")):
    with T.Kernel(T.ceildiv(M, BM), T.ceildiv(N, 128)) as (bx, by):
        A_s = T.alloc_shared((BM, K), "float32")
        B_s = T.alloc_shared((K, 128), "float32")
        C_l = T.alloc_fragment((BM, 128), "float32")
        T.copy(A[bx * BM, 0], A_s)
        T.copy(B[0, by * 128], B_s)
        T.gemm(A_s, B_s, C_l, clear_accum=True)
        T.copy(C_l, C[bx * BM, by * 128])


def main():
    rng = np.random.default_rng(0)
    b = rng.standard_normal((K, N), dtype=np.float32)
    for m in (64, 192, 64, 320):
        a = rng.standard_normal((m, K), dtype=np.float32)
        c = np.asarray(matmul(a, b))
        np.testing.assert_allclose(c, a @ b, rtol=1e-2, atol=1e-1)
        print(f"m={m:4d}: correct "
              f"({len(matmul._kernels)} specialized kernel(s) cached)")
    assert len(matmul._kernels) == 3, "m=64 must hit the cache"


if __name__ == "__main__":
    main()
