"""Stream-K GEMM (reference examples/gemm_streamk): the flat (tile, k-chunk)
iteration space is balanced over a fixed number of programs. Host plans
contiguous segments; the kernel runs a dynamic-extent K loop per segment with
dynamic-offset DMA; an XLA segment-sum performs the cross-segment fixup the
reference does with atomics."""

import numpy as np

import jax.numpy as jnp

from tilelang_mesh_tpu.ops import matmul_streamk


def main(M=256, N=384, K=512):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.float32)
    c = matmul_streamk(a, b, n_programs=6, out_dtype="float32")
    np.testing.assert_allclose(np.asarray(c),
                               np.asarray(a) @ np.asarray(b),
                               rtol=1e-4, atol=1e-4)
    print("stream-K GEMM correct.")


if __name__ == "__main__":
    main()
