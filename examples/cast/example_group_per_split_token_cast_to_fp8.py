"""Grouped per-split-token fp8 quantization (reference
examples/cast/example_group_per_split_token_cast_to_fp8.py behavior):
each token row is cut into groups of 128 lanes and every (token, group)
gets its OWN scale — the finer granularity fp8 training recipes use for
activations (a single outlier no longer flattens the whole row).

TPU shape: the group is a GRID axis, so each step is a contiguous
(rows, 128) tile — rowwise absmax, scale, cast, two aligned stores."""

import numpy as np
import jax.numpy as jnp

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

_E4M3_MAX = 448.0
_GS = 128


def group_cast_kernel(M, N, bm):
    G = N // _GS

    @T.prim_func
    def cast_fp8_group(X: T.Tensor((M, N), "float32"),
                       Y: T.Tensor((M, N), "float8_e4m3fn"),
                       Sc: T.Tensor((M, G), "float32")):
        with T.Kernel(T.ceildiv(M, bm), G) as (bx, bg):
            x = T.alloc_fragment((bm, _GS), "float32")
            ax = T.alloc_fragment((bm, _GS), "float32")
            amax = T.alloc_fragment((bm,), "float32")
            y = T.alloc_fragment((bm, _GS), "float8_e4m3fn")
            sc = T.alloc_fragment((bm, 1), "float32")
            T.copy(X[bx * bm, bg * _GS], x)
            for i, j in T.Parallel(bm, _GS):
                ax[i, j] = T.abs(x[i, j])
            T.reduce_max(ax, amax, dim=1)
            for i in T.Parallel(bm):
                sc[i, 0] = T.max(amax[i] / _E4M3_MAX, 1e-8)
            for i, j in T.Parallel(bm, _GS):
                y[i, j] = T.cast(x[i, j] / sc[i, 0], "float8_e4m3fn")
            T.copy(y, Y[bx * bm, bg * _GS])
            T.copy(sc, Sc[bx * bm, bg])
    return tilelang.compile(cast_fp8_group)


def main(M=128, N=512, bm=64):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((M, N)).astype(np.float32)
    x[7, 3] = 100.0                       # an outlier in one group
    kern = group_cast_kernel(M, N, bm)
    yj, scj = kern(jnp.asarray(x))
    y, sc = np.asarray(yj, np.float32), np.asarray(scj)

    G = N // _GS
    xg = x.reshape(M, G, _GS)
    sc_ref = np.maximum(np.abs(xg).max(-1) / _E4M3_MAX, 1e-8)
    np.testing.assert_allclose(sc, sc_ref, rtol=1e-6, atol=1e-8)
    # reconstruction error bounded by fp8 resolution per group
    recon = y * np.repeat(sc, _GS, axis=1)
    err = np.abs(recon - x) / np.maximum(np.repeat(sc, _GS, 1) * 16, 1e-8)
    assert err.max() < 2.0, err.max()
    # the outlier only coarsened ITS group, not the rest of the row
    fine = np.abs(recon[7, 200:] - x[7, 200:]).max()
    assert fine < np.abs(x[7, 200:]).max() * 0.1
    print(f"grouped per-(token, 128-lane) fp8 cast correct: "
          f"{G} scales/row; outlier contained to its group.")


if __name__ == "__main__":
    main()
