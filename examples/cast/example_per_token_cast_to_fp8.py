"""Per-token fp8 quantization (reference
examples/cast/example_per_token_cast_to_fp8.py behavior): each token row
gets its own scale = rowwise absmax / 448 (e4m3 max), the row is divided
by it and cast to fp8 — one VPU pass: reduce_max + scale + cast."""

import jax.numpy as jnp
import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

_E4M3_MAX = 448.0


def per_token_cast_kernel(M, N, bm):
    @T.prim_func
    def cast_fp8(X: T.Tensor((M, N), "float32"),
                 Y: T.Tensor((M, N), "float8_e4m3fn"),
                 Sc: T.Tensor((M, 1), "float32")):
        with T.Kernel(T.ceildiv(M, bm)) as bx:
            x = T.alloc_fragment((bm, N), "float32")
            ax = T.alloc_fragment((bm, N), "float32")
            amax = T.alloc_fragment((bm,), "float32")
            y = T.alloc_fragment((bm, N), "float8_e4m3fn")
            sc = T.alloc_fragment((bm, 1), "float32")
            T.copy(X[bx * bm, 0], x)
            for i, j in T.Parallel(bm, N):
                ax[i, j] = T.abs(x[i, j])
            T.reduce_max(ax, amax, dim=1)
            for i, j in T.Parallel(bm, N):
                y[i, j] = T.cast(
                    x[i, j] / T.max(amax[i] / _E4M3_MAX, 1e-8),
                    "float8_e4m3fn")
            for i in T.Parallel(bm):
                sc[i, 0] = T.max(amax[i] / _E4M3_MAX, 1e-8)
            T.copy(y, Y[bx * bm, 0])
            T.copy(sc, Sc[bx * bm, 0])
    return tilelang.compile(cast_fp8)


def main(M=256, N=512):
    k = per_token_cast_kernel(M, N, 128)
    rng = np.random.default_rng(0)
    x = (rng.standard_normal((M, N)) * rng.uniform(
        0.01, 30.0, (M, 1))).astype(np.float32)
    y = np.empty((M, N), dtype=jnp.float8_e4m3fn)
    sc = np.empty((M, 1), np.float32)
    k(x, y, sc)
    # dequantized result must round-trip within fp8 relative precision
    back = np.asarray(y, np.float32) * sc
    scale_ref = np.maximum(np.abs(x).max(1, keepdims=True) / 448.0, 1e-8)
    np.testing.assert_allclose(sc, scale_ref, rtol=1e-5)
    err = np.abs(back - x) / np.maximum(np.abs(x), sc)  # e4m3 ulp scale
    assert float(err.max()) < 0.08, f"fp8 round-trip err {err.max():.3f}"
    print(f"per-token fp8 cast {M}x{N}: scales exact, round-trip within "
          f"e4m3 precision.")


if __name__ == "__main__":
    main()
