"""Dtype cast kernel (reference examples/cast)."""

import numpy as np
import jax.numpy as jnp

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def cast_kernel(M, N, bm, src_dtype, dst_dtype):
    @T.prim_func
    def cast(A: T.Tensor((M, N), src_dtype),
             B: T.Tensor((M, N), dst_dtype)):
        with T.Kernel(T.ceildiv(M, bm)) as bx:
            s = T.alloc_shared((bm, N), src_dtype)
            T.copy(A[bx * bm, 0], s)
            T.copy(s, B[bx * bm, 0])
    return tilelang.compile(cast)


def main(M=512, N=256):
    k = cast_kernel(M, N, 128, "float32", "bfloat16")
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, N), dtype=np.float32)
    out = np.asarray(k(a), np.float32)
    ref = np.asarray(jnp.asarray(a, jnp.bfloat16), np.float32)
    np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-2)
    print("cast f32 -> bf16 correct.")


if __name__ == "__main__":
    main()
