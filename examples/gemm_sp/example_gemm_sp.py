"""2:4 structured-sparse GEMM (reference examples/gemm_sp/example_gemm_sp.py).

The reference compresses A with CUTLASS metadata and hits mma.sp; here the
host compresses with the int8 slot format (utils/sparse.py), the kernel
streams the half-width values + metadata from HBM (half the A bandwidth of
a dense GEMM) and T.gemm_sp decompresses each tile in VMEM before a dense
MXU dot.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.utils.sparse import compress, randn_semi_sparse


@tilelang.jit
def matmul_sp(M, N, K, block_M=128, block_N=128, block_K=128,
              dtype="float32", accum_dtype="float32", num_stages=2):
    @T.prim_func
    def gemm_sp_kernel(
            A_sparse: T.Tensor((M, K // 2), dtype),
            E: T.Tensor((M, K // 2), "int8"),
            B: T.Tensor((K, N), dtype),
            C: T.Tensor((M, N), accum_dtype)):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_shared = T.alloc_shared((block_M, block_K // 2), dtype)
            E_shared = T.alloc_shared((block_M, block_K // 2), "int8")
            B_shared = T.alloc_shared((block_K, block_N), dtype)
            C_local = T.alloc_fragment((block_M, block_N), accum_dtype)
            T.clear(C_local)
            for ko in T.Pipelined(T.ceildiv(K, block_K),
                                  num_stages=num_stages):
                T.copy(A_sparse[by * block_M, ko * block_K // 2], A_shared)
                T.copy(E[by * block_M, ko * block_K // 2], E_shared)
                T.copy(B[ko * block_K, bx * block_N], B_shared)
                T.gemm_sp(A_shared, E_shared, B_shared, C_local)
            T.copy(C_local, C[by * block_M, bx * block_N])

    return gemm_sp_kernel


def main(M=256, N=256, K=256):
    a = randn_semi_sparse(M, K, dtype=np.float32, seed=0)
    b = np.random.default_rng(1).standard_normal((K, N), dtype=np.float32)
    a_sparse, e = compress(a)
    assert a_sparse.shape == (M, K // 2) and e.dtype == np.int8

    kernel = matmul_sp(M, N, K)
    c = np.empty((M, N), dtype=np.float32)
    kernel(a_sparse, e, b, c)
    np.testing.assert_allclose(c, a @ b, rtol=1e-2, atol=1e-1)
    print(f"2:4 sparse GEMM {M}x{N}x{K}: matches dense reference ✓ "
          f"(A bytes halved: {a.nbytes} -> {a_sparse.nbytes + e.nbytes})")


if __name__ == "__main__":
    main()
