"""Visualize the planner's layout decisions for a kernel (reference
examples/visual_layout_inference/visual_layout_inference.py — dumps the
LayoutInference pass results; here the analog is the kernel plan's
BlockSpec table + generated source)."""

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.analysis import visualize_plan


def main(M=256, N=256, K=256):
    @T.prim_func
    def matmul(A: T.Tensor((M, K), "float32"),
               B: T.Tensor((K, N), "float32"),
               C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(N, 128), T.ceildiv(M, 128)) as (bx, by):
            A_s = T.alloc_shared((128, 128), "float32")
            B_s = T.alloc_shared((128, 128), "float32")
            C_l = T.alloc_fragment((128, 128), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, 128), num_stages=2):
                T.copy(A[by * 128, ko * 128], A_s)
                T.copy(B[ko * 128, bx * 128], B_s)
                T.gemm(A_s, B_s, C_l)
            T.copy(C_l, C[by * 128, bx * 128])

    kernel = tilelang.compile(matmul)
    txt = visualize_plan(kernel.artifact)
    print(txt)
    assert "grid=" in txt and "block" in txt
    print("plan visualization: every buffer above shows its BlockSpec "
          "mapping (or any(hbm) for explicit-DMA operands) ✓")


if __name__ == "__main__":
    main()
