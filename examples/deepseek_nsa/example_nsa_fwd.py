"""Native Sparse Attention forward (reference examples/deepseek_nsa/
example_tilelang_nsa_fwd.py behavior): per-token selected KV blocks +
gated sliding window."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.nsa import nsa_attention, nsa_reference


def main(B=1, T=64, HQ=4, H=2, D=32, S=3, BS=16, window=24):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, T, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    g_slc = jnp.asarray(rng.uniform(0.2, 1.0, (B, T, HQ)), jnp.float32)
    g_swa = jnp.asarray(rng.uniform(0.2, 1.0, (B, T, HQ)), jnp.float32)
    bi = np.full((B, T, H, S), -1, np.int64)
    for b in range(B):
        for t in range(T):
            own = t // BS
            for h in range(H):
                picks = rng.choice(own + 1, size=min(S, own + 1),
                                   replace=False)
                bi[b, t, h, :len(picks)] = picks
                if own not in picks:
                    bi[b, t, h, 0] = own
    bi = jnp.asarray(bi, jnp.int32)
    out = nsa_attention(q, k, v, g_slc, g_swa, bi, block_size=BS,
                        window_size=window)
    ref = nsa_reference(q, k, v, g_slc, g_swa, bi, block_size=BS,
                        window_size=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    print("NSA forward (selected blocks + sliding window) matches "
          "reference.")


if __name__ == "__main__":
    main()
