"""NSA backward (reference examples/deepseek_nsa
example_tilelang_nsa_bwd.py behavior, selected branch / window 0):
dK/dV resolve the data-dependent scatter by inverting the per-token
block selection into a dense mask (the reference's flash_bwd_block_mask
step, done here with XLA one_hot+sum) and sweeping tokens per KV block;
dQ mirrors the forward's gather. Gates multiply outside the custom_vjp,
so d(g_slc) falls out of jax AD."""

import jax
import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.nsa import nsa_attention


def main(B=1, Tq=64, HQ=4, H=2, D=32, S=3, BS=16):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Tq, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    g = jnp.asarray(rng.uniform(0.2, 1.0, (B, Tq, HQ)), jnp.float32)
    go = jnp.asarray(rng.standard_normal((B, Tq, HQ, D)), jnp.float32)
    # causal selections: each token selects its own block + random past
    bi = np.zeros((B, Tq, H, S), np.int64)
    for b in range(B):
        for t in range(Tq):
            own = t // BS
            for h in range(H):
                picks = rng.choice(own + 1, size=min(S, own + 1),
                                   replace=False)
                row = np.full(S, -1)
                row[:len(picks)] = picks
                if own not in picks:
                    row[0] = own
                bi[b, t, h] = row
    bi = jnp.asarray(bi, jnp.int32)

    def loss(q, k, v, g):
        o = nsa_attention(q, k, v, g, jnp.zeros_like(g), bi,
                          block_size=BS, backward="kernel")
        return jnp.sum(o * go)

    dq, dk, dv, dg = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, g)
    for name, x in (("dQ", dq), ("dK", dk), ("dV", dv), ("dG", dg)):
        assert np.isfinite(np.asarray(x)).all(), name
    # finite-difference spot check on one scalar of g
    eps = 1e-3
    g2 = g.at[0, 5, 1].add(eps)
    fd = float((loss(q, k, v, g2) - loss(q, k, v, g)) / eps)
    np.testing.assert_allclose(float(dg[0, 5, 1]), fd, rtol=5e-2,
                               atol=5e-2)
    print(f"NSA bwd (Tq={Tq}, S={S}, BS={BS}): finite gradients, "
          f"dG finite-difference check passes ({float(dg[0, 5, 1]):.4f} "
          f"vs {fd:.4f}).")


if __name__ == "__main__":
    main()
