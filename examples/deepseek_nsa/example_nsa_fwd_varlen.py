"""Varlen (ragged-batch) NSA forward (reference examples/deepseek_nsa
example_tilelang_nsa_fwd_varlen.py behavior): packed tokens with
sequence-LOCAL selected-block ids; the wrapper converts them to raw
packed row offsets and a per-token sequence-end bound masks keys past
the boundary, so the gather kernel needs no per-sequence bases."""

import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.nsa import nsa_attention_varlen, nsa_reference


def main(HQ=4, H=2, D=32, S=3, BS=8):
    rng = np.random.default_rng(0)
    lens = [30, 45, 14]
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    total = int(cu[-1])
    q = jnp.asarray(rng.standard_normal((total, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, H, D)), jnp.float32)
    g = jnp.asarray(rng.uniform(0.2, 1.0, (total, HQ)), jnp.float32)

    bi = np.full((total, H, S), -1, np.int64)
    for b in range(len(lens)):
        for tl in range(lens[b]):
            own = tl // BS
            for h in range(H):
                picks = rng.choice(own + 1, size=min(S, own + 1),
                                   replace=False)
                row = np.full(S, -1)
                row[:len(picks)] = picks
                if own not in picks:
                    row[0] = own
                bi[cu[b] + tl, h] = row
    bi = jnp.asarray(bi, jnp.int32)

    out = np.asarray(nsa_attention_varlen(q, k, v, g, bi, cu,
                                          block_size=BS))
    for b in range(len(lens)):
        lo, hi = int(cu[b]), int(cu[b + 1])
        ref = nsa_reference(q[None, lo:hi], k[None, lo:hi],
                            v[None, lo:hi], g[None, lo:hi],
                            jnp.zeros((1, hi - lo, HQ), jnp.float32),
                            bi[None, lo:hi], block_size=BS)
        np.testing.assert_allclose(out[lo:hi], np.asarray(ref)[0],
                                   rtol=2e-2, atol=2e-2)
    print(f"varlen NSA fwd (lens={lens}, S={S}, BS={BS}) matches the "
          f"per-sequence reference; no cross-boundary attention.")


if __name__ == "__main__":
    main()
