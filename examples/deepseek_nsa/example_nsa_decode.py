"""NSA decode step (reference examples/deepseek_nsa/
example_tilelang_nsa_decode.py behavior): one query token, gathered
selected KV blocks."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.nsa import nsa_decode


def main(B=1, Tk=128, HQ=4, H=2, D=32, S=4, BS=16):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Tk, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Tk, H, D)), jnp.float32)
    g = jnp.asarray(rng.uniform(0.2, 1.0, (B, HQ)), jnp.float32)
    bi = np.stack([rng.choice(Tk // BS, S, replace=False)
                   for _ in range(B * H)]).reshape(B, H, S)
    out = nsa_decode(q, k, v, g, jnp.asarray(bi, jnp.int32), block_size=BS)

    # dense check against gathered softmax
    kn, vn = np.asarray(k), np.asarray(v)
    G = HQ // H
    ref = np.zeros((B, HQ, D), np.float32)
    for b in range(B):
        for h in range(HQ):
            hk = h // G
            idx = (bi[b, hk][:, None] * BS + np.arange(BS)).ravel()
            sc = np.asarray(q)[b, h] @ kn[b, idx, hk].T / np.sqrt(D)
            p = np.exp(sc - sc.max())
            p /= p.sum()
            ref[b, h] = p @ vn[b, idx, hk] * np.asarray(g)[b, h]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-2, atol=2e-2)
    print("NSA decode matches gathered-softmax reference.")


if __name__ == "__main__":
    main()
