"""MInference vertical-slash sparse attention (reference
examples/minference/example_vertical_slash_sparse_attn.py behavior),
including the estimation step that picks the vertical/slash indices from
last-window attention mass."""

import numpy as np
import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.minference import (
    vertical_slash_sparse_attention, vs_sparse_reference)


def _estimate_indices(q, k, n_vertical, n_slash, last_q=32):
    """Pick columns/diagonals with the largest attention mass from the last
    `last_q` queries (cf. reference main():567-581)."""
    B, H, S, D = q.shape
    qk = jnp.einsum("bhqd,bhkd->bhqk", q[:, :, -last_q:], k) / np.sqrt(D)
    qi = jnp.arange(S - last_q, S)[:, None]
    kj = jnp.arange(S)[None, :]
    qk = jnp.where(qi >= kj, qk, -jnp.inf)
    p = jax.nn.softmax(qk, axis=-1)
    vertical = p.sum(2)                                   # (B,H,S)
    v_idx = jnp.argsort(-vertical, axis=-1)[..., :n_vertical]
    # diagonal mass: offset o = qi - kj
    offs = (qi - kj)                                      # (last_q, S)
    slash = jnp.zeros((B, H, S), jnp.float32)
    slash = slash.at[:, :, jnp.clip(offs, 0, S - 1)].add(
        jnp.where(offs >= 0, p, 0.0))
    s_idx = jnp.argsort(-slash, axis=-1)[..., :n_slash]
    return v_idx.astype(jnp.int32), s_idx.astype(jnp.int32)


def main(B=1, H=2, S=256, D=64, n_vertical=16, n_slash=8):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v_idx, s_idx = _estimate_indices(q, k, n_vertical, n_slash)
    out = vertical_slash_sparse_attention(q, k, v, v_idx, s_idx,
                                          block_M=64, block_N=64)
    ref = vs_sparse_reference(q, k, v, v_idx, s_idx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    print("vertical-slash sparse attention matches reference.")


if __name__ == "__main__":
    main()
