"""Varlen (ragged) grouped GEMM fwd + bwd — the MoE token-sorted layout
(reference examples/grouped_gemm/example_grouped_gemm_fwd.py/_bwd.py).

Tokens for all experts are concatenated along M; each m-block's (expert,
row-start) is a host-precomputed table (group sizes are static), the kernel
writes a block-padded output, and pad rows are dropped on the host — every
store stays a full BlockSpec tile. Backward reuses the same kernel:
dA = varlen_gmm(dC, B, trans_b=True); dB falls to per-group MXU einsums.
"""

import numpy as np

from tilelang_mesh_tpu.ops.grouped_gemm import (
    varlen_grouped_matmul, varlen_grouped_matmul_reference)


def main(sizes=(200, 0, 129, 64, 301), K=128, N=256):
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    E = len(sizes)
    a = jnp.asarray(rng.standard_normal((sum(sizes), K)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((E, K, N)), jnp.float32)

    out = varlen_grouped_matmul(a, b, sizes)
    ref = varlen_grouped_matmul_reference(a, b, sizes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-2, atol=1e-1)
    print(f"varlen grouped GEMM fwd over groups {sizes}: correct "
          "(empty group + ragged tails handled) ✓")

    # backward: dA through the same kernel with B transposed
    dc = jnp.asarray(rng.standard_normal(out.shape), jnp.float32)
    bt = jnp.transpose(b, (0, 2, 1))
    da = varlen_grouped_matmul(dc, bt, sizes, trans_b=False)
    da_ref = varlen_grouped_matmul_reference(dc, bt, sizes)
    np.testing.assert_allclose(np.asarray(da), np.asarray(da_ref),
                               rtol=1e-2, atol=1e-1)
    # dB: per-group A^T dC (static segment einsums on the MXU)
    off = 0
    for e, s in enumerate(sizes):
        db_e = a[off:off + s].T @ dc[off:off + s]
        ref_e = np.asarray(a[off:off + s]).T @ np.asarray(dc[off:off + s])
        np.testing.assert_allclose(np.asarray(db_e), ref_e, rtol=1e-2,
                                   atol=1e-1)
        off += s
    print("varlen grouped GEMM bwd (dA via trans_b kernel, dB per-group) ✓")


if __name__ == "__main__":
    main()
