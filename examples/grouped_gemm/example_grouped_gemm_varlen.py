"""Varlen (ragged) grouped GEMM fwd + bwd — the MoE token-sorted layout
(reference examples/grouped_gemm/example_grouped_gemm_fwd.py/_bwd.py).

Tokens for all experts are concatenated along M; each m-block's (expert,
row-start) is a host-precomputed table (group sizes are static), the kernel
writes a block-padded output, and pad rows are dropped on the host — every
store stays a full BlockSpec tile. Backward reuses the same kernel:
dA = varlen_gmm(dC, B, trans_b=True); dB falls to per-group MXU einsums.
"""

import numpy as np

from tilelang_mesh_tpu.ops.grouped_gemm import (
    varlen_grouped_matmul, varlen_grouped_matmul_reference)


def main(sizes=(200, 0, 129, 64, 301), K=128, N=256):
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    E = len(sizes)
    a = jnp.asarray(rng.standard_normal((sum(sizes), K)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((E, K, N)), jnp.float32)

    out = varlen_grouped_matmul(a, b, sizes)
    ref = varlen_grouped_matmul_reference(a, b, sizes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-2, atol=1e-1)
    print(f"varlen grouped GEMM fwd over groups {sizes}: correct "
          "(empty group + ragged tails handled) ✓")

    # backward: dA = dC @ B^T — the SAME kernel with trans_b=True (B is
    # (E, K, N); transposing happens inside the tile loop, no host copy).
    # Checked against autodiff through the dense per-group reference.
    import jax
    dc = jnp.asarray(rng.standard_normal(out.shape), jnp.float32)
    da = varlen_grouped_matmul(dc, b, sizes, trans_b=True,
                               block_N=128, block_K=64)  # rectangular tile
    loss = lambda aa: jnp.sum(
        varlen_grouped_matmul_reference(aa, b, sizes) * dc)
    da_ref = jax.grad(loss)(a)
    np.testing.assert_allclose(np.asarray(da), np.asarray(da_ref),
                               rtol=1e-2, atol=1e-1)
    # dB: per-group A^T dC (segment einsums on the MXU), vs autodiff
    loss_b = lambda bb: jnp.sum(
        varlen_grouped_matmul_reference(a, bb, sizes) * dc)
    db_ref = jax.grad(loss_b)(b)
    off = 0
    for e, s in enumerate(sizes):
        db_e = a[off:off + s].T @ dc[off:off + s]
        np.testing.assert_allclose(np.asarray(db_e),
                                   np.asarray(db_ref[e]), rtol=1e-2,
                                   atol=1e-1)
        off += s
    print("varlen grouped GEMM bwd (dA via trans_b=True kernel vs "
          "autodiff; dB per-group vs autodiff) ✓")


if __name__ == "__main__":
    main()
