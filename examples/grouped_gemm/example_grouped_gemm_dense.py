"""Dense grouped GEMM (reference examples/grouped_gemm): out[e] = X[e] @
W[e] with the expert index as an extra parallel grid dimension, so every
expert's tiles ride one pipelined K loop (the compute core the fusedmoe
example builds on; the ragged-batch form is
example_grouped_gemm_varlen.py)."""

import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.grouped_gemm import grouped_matmul


def main(E=4, M=128, K=256, N=256):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((E, M, K)) * 0.1, jnp.float32)
    w = jnp.asarray(rng.standard_normal((E, K, N)) * 0.1, jnp.float32)

    out = grouped_matmul(x, w, block_M=128, block_N=128, block_K=128)
    want = np.einsum("emk,ekn->emn", np.asarray(x), np.asarray(w))
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-2,
                               atol=2e-2)
    print(f"grouped GEMM E={E} {M}x{K}x{N} matches einsum.")


if __name__ == "__main__":
    main()
