"""Quickstart: tiled GEMM + ReLU through the TPU tile pipeline.

Mirror of the reference's examples/quickstart.py (canonical GEMM+ReLU)
re-founded on jax: bfloat16 tiles on the MXU, f32 accumulation, Mosaic
double-buffering the K loop.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


@tilelang.jit
def matmul(M, N, K, block_M, block_N, block_K, dtype="float32",
           accum_dtype="float32"):

    @T.prim_func
    def matmul_relu_kernel(
            A: T.Tensor((M, K), dtype),
            B: T.Tensor((K, N), dtype),
            C: T.Tensor((M, N), dtype)):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M),
                      threads=128) as (bx, by):
            A_shared = T.alloc_shared((block_M, block_K), dtype)
            B_shared = T.alloc_shared((block_K, block_N), dtype)
            C_local = T.alloc_fragment((block_M, block_N), accum_dtype)
            T.clear(C_local)
            for ko in T.Pipelined(T.ceildiv(K, block_K), num_stages=3):
                T.copy(A[by * block_M, ko * block_K], A_shared)
                T.copy(B[ko * block_K, bx * block_N], B_shared)
                T.gemm(A_shared, B_shared, C_local)
            for i, j in T.Parallel(block_M, block_N):
                C_local[i, j] = T.max(C_local[i, j], 0)
            T.copy(C_local, C[by * block_M, bx * block_N])

    return matmul_relu_kernel


def main(M=512, N=512, K=512):
    kernel = matmul(M, N, K, 128, 128, 64)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)

    c = np.empty((M, N), dtype=np.float32)
    kernel(a, b, c)            # reference-style output-arg call
    ref_c = np.maximum(a @ b, 0)
    np.testing.assert_allclose(c, ref_c, rtol=1e-2, atol=1e-1)
    print("Kernel output matches the reference.")

    profiler = kernel.get_profiler(
        tensor_supply_type=tilelang.TensorSupplyType.Normal)
    latency = profiler.do_bench(warmup=1, rep=5, backend="wall")
    print(f"Latency: {latency:.3f} ms")
    print("Generated Pallas source:\n",
          "\n".join(kernel.get_kernel_source().splitlines()[:12]), "...")


if __name__ == "__main__":
    main()
