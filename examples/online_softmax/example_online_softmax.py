"""Online (blockwise) softmax — the flash-attention building block as a
standalone kernel (reference examples/online_softmax)."""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def online_softmax_kernel(M, N, block_N, dtype="float32"):
    """Two-pass-free softmax: stream over N blocks keeping running
    (max, sum) stats, then rescale."""
    NB = N // block_N

    @T.prim_func
    def softmax(A: T.Tensor((M, N), dtype),
                B: T.Tensor((M, N), dtype)):
        with T.Kernel(1) as bz:
            A_s = T.alloc_shared((M, N), dtype)
            blk = T.alloc_fragment((M, block_N), "float32")
            m = T.alloc_fragment((M,), "float32")
            m_new = T.alloc_fragment((M,), "float32")
            bmax = T.alloc_fragment((M,), "float32")
            l = T.alloc_fragment((M,), "float32")
            bsum = T.alloc_fragment((M,), "float32")
            T.copy(A, A_s)
            T.fill(m, -T.infinity("float32"))
            T.fill(l, 0)
            for nb in T.serial(NB):
                T.copy(A_s[:, nb * block_N:(nb + 1) * block_N], blk)
                T.reduce_max(blk, bmax, dim=1)
                for i in T.Parallel(M):
                    m_new[i] = T.max(m[i], bmax[i])
                for i, j in T.Parallel(M, block_N):
                    blk[i, j] = T.exp(blk[i, j] - m_new[i])
                T.reduce_sum(blk, bsum, dim=1)
                for i in T.Parallel(M):
                    l[i] = l[i] * T.exp(m[i] - m_new[i]) + bsum[i]
                for i in T.Parallel(M):
                    m[i] = m_new[i]
            for i, j in T.Parallel(M, N):
                # clamped divide: a fully-underflowed row's normalizer
                # is 0.0 and the bare divide is 0/0 = NaN (tl-num TL009)
                A_s[i, j] = T.exp(A_s[i, j] - m[i]) / T.max(l[i], 1e-30)
            T.copy(A_s, B)
    return tilelang.compile(softmax)


def main(M=128, N=512):
    k = online_softmax_kernel(M, N, 128)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, N), dtype=np.float32)
    e = np.exp(a - a.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(k(a)), ref, rtol=1e-3, atol=1e-4)
    print("online softmax matches reference.")


if __name__ == "__main__":
    main()
