"""Attention sink + sliding window (reference examples/attention_sink
sliding-window variants): the sink logit keeps early-token mass stable
while the window masks keys older than `window_size`; fully-outside KV
tiles are skipped at block granularity."""

import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.attention_sink import (attention_sink,
                                                  attention_sink_reference)


def main(B=1, Hq=4, Hkv=2, S=512, D=64, window=256):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    sinks = jnp.asarray(rng.standard_normal((Hq,)), jnp.float32)

    out = attention_sink(q, k, v, sinks, causal=True, window_size=window,
                         block_M=128, block_N=128)
    want = attention_sink_reference(q, k, v, sinks, causal=True,
                                    window_size=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
    print(f"sink + sliding-window attention (W={window}, GQA "
          f"{Hq}/{Hkv}) matches reference.")


if __name__ == "__main__":
    main()
