"""Attention-sink forward, MHA + sliding window (reference
examples/attention_sink/example_mha_sink_fwd_bhsd.py behavior)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.attention_sink import (attention_sink,
                                                  attention_sink_reference)


def main(B=1, H=4, S=256, D=64, window=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    sinks = jnp.asarray(rng.standard_normal((H,)), jnp.float32)
    for w in (None, window):
        out = attention_sink(q, k, v, sinks, causal=True, window_size=w,
                             block_M=64, block_N=64)
        ref = attention_sink_reference(q, k, v, sinks, causal=True,
                                       window_size=w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)
    print("sink attention (full + sliding window) matches reference.")


if __name__ == "__main__":
    main()
