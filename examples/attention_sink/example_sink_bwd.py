"""Attention-sink backward (reference examples/attention_sink
example_mha_sink_bwd_bhsd.py / example_gqa_sink_bwd_bhsd.py behavior):
the sink only shifts the softmax normalizer, so the sink-less GQA
partial stats plus one XLA fold give exactly the lse the standard
dKdV/dQ recompute kernels need; d(sinks) is the closed form
-sum(p_sink * delta)."""

import jax
import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.attention_sink import (attention_sink,
                                                  attention_sink_reference)


def main(B=1, Hq=4, Hkv=2, S=128, D=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    sinks = jnp.asarray(rng.standard_normal((Hq,)), jnp.float32)
    go = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)

    def loss_kernel(q, k, v, sinks):
        return jnp.sum(attention_sink(q, k, v, sinks, causal=True,
                                      block_M=64, block_N=64,
                                      backward="kernel") * go)

    def loss_ref(q, k, v, sinks):
        return jnp.sum(attention_sink_reference(q, k, v, sinks,
                                                causal=True) * go)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2, 3))(q, k, v, sinks)
    want = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, sinks)
    for name, a, b in zip(("dQ", "dK", "dV", "dSinks"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)
    print(f"sink attention bwd (GQA {Hq}/{Hkv}): all four gradients "
          f"incl. d(sinks) match jax AD.")


if __name__ == "__main__":
    main()
