"""Attention-sink forward, GQA (reference examples/attention_sink/
example_gqa_sink_fwd_bhsd_wgmma_pipelined.py behavior — the pipelining is
Mosaic's job on TPU)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.attention_sink import (attention_sink,
                                                  attention_sink_reference)


def main(B=1, Hq=8, Hkv=2, S=256, D=64):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    sinks = jnp.asarray(rng.standard_normal((Hq,)), jnp.float32)
    out = attention_sink(q, k, v, sinks, causal=True, block_M=64,
                         block_N=64)
    ref = attention_sink_reference(q, k, v, sinks, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    print("GQA sink attention matches reference.")


if __name__ == "__main__":
    main()
