"""Autotuned Conv2D: sweep the F-tile over the profiler, pick the best
(reference examples/convolution/example_convolution_autotune.py flow)."""

import pathlib
import sys

import numpy as np

import tilelang_mesh_tpu as tilelang

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
from example_convolution import convolution, ref_conv2d  # noqa: E402


def main(N=2, C=128, H=16, W=16, F=256, K=3, S=1, D=1, P=1):
    configs = [{"block_F": bf} for bf in (64, 128, 256) if bf <= F]
    tuned = tilelang.autotune(configs=configs, warmup=1, rep=3)(convolution)
    kernel = tuned(N, C, H, W, F, K, S, D, P)
    print(f"best config: {kernel.config} @ {kernel.latency:.3f} ms")

    rng = np.random.default_rng(0)
    data = rng.standard_normal((N, H, W, C), dtype=np.float32)
    weight = rng.standard_normal((K, K, C, F), dtype=np.float32)
    padded = np.pad(data, ((0, 0), (P, P), (P, P), (0, 0)))
    OH = (H + 2 * P - D * (K - 1) - 1) // S + 1
    OW = (W + 2 * P - D * (K - 1) - 1) // S + 1
    out = np.empty((N, OH, OW, F), dtype=np.float32)
    kernel(padded, weight, out)
    ref = np.asarray(ref_conv2d(data, weight, S, P, D))
    np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-1)
    print("autotuned conv2d correct.")


if __name__ == "__main__":
    main()
