"""Conv2D NHWC through the TPU tile pipeline.

Behavioral mirror of the reference's examples/convolution/example_convolution.py
(im2col + GEMM on tensor cores), re-founded for the MXU: instead of an im2col
gather (TMA on Hopper, predicated T.Parallel gather elsewhere), the kernel
computes conv as K*K *shifted-window GEMMs* — for each kernel tap (kh, kw) the
input window is a contiguous (or stride-S strided) VMEM slice, so every FLOP
runs on the MXU and no gather ever materializes. Padding is applied on the
host (the reference host-side permutes layouts; we host-side pad), keeping the
kernel free of boundary predicates.

Layout: data NHWC, weight (KH, KW, C, F), out (N, OH, OW, F) — same as the
reference example.
"""

import argparse

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


@tilelang.jit
def convolution(N, C, H, W, F, K, S, D, P, block_F=128,
                dtype="float32", accum_dtype="float32"):
    """Returns a kernel taking (padded_data, weight, out)."""
    KH = KW = K
    OH = (H + 2 * P - D * (KH - 1) - 1) // S + 1
    OW = (W + 2 * P - D * (KW - 1) - 1) // S + 1
    HP, WP = H + 2 * P, W + 2 * P
    h_span = D * (KH - 1) + 1  # input rows touched per output row

    @T.prim_func
    def conv2d(data: T.Tensor((N, HP, WP, C), dtype),
               weight: T.Tensor((KH, KW, C, F), dtype),
               out: T.Tensor((N, OH, OW, F), accum_dtype)):
        with T.Kernel(T.ceildiv(F, block_F), N, OH) as (bf, n, oh):
            # input row slab for this output row: all KH taps' rows
            rows = T.alloc_shared((h_span, WP, C), dtype)
            # full weight block for this F-tile rides the Pallas BlockSpec
            w_blk = T.alloc_shared((KH, KW, C, block_F), dtype)
            a_win = T.alloc_shared((OW, C), dtype)
            acc = T.alloc_fragment((OW, block_F), accum_dtype)

            T.copy(data[n, oh * S, 0, 0], rows)
            T.copy(weight[0, 0, 0, bf * block_F], w_blk)
            T.clear(acc)
            for kh in range(KH):
                for kw in range(KW):
                    if S == 1:
                        T.gemm(rows[kh * D, kw * D:kw * D + OW, 0:C],
                               w_blk[kh, kw, 0:C, 0:block_F], acc)
                    else:
                        for i, j in T.Parallel(OW, C):
                            a_win[i, j] = rows[kh * D, i * S + kw * D, j]
                        T.gemm(a_win, w_blk[kh, kw, 0:C, 0:block_F], acc)
            T.copy(acc, out[n, oh, 0, bf * block_F])

    return conv2d


def ref_conv2d(data, weight, stride, padding, dilation):
    import jax
    return jax.lax.conv_general_dilated(
        data, weight,
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        rhs_dilation=(dilation, dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def run(N, C, H, W, F, K, S, D, P, block_F=128, check=True):
    kernel = convolution(N, C, H, W, F, K, S, D, P, block_F)
    rng = np.random.default_rng(0)
    data = rng.standard_normal((N, H, W, C), dtype=np.float32)
    weight = rng.standard_normal((K, K, C, F), dtype=np.float32)
    padded = np.pad(data, ((0, 0), (P, P), (P, P), (0, 0)))

    OH = (H + 2 * P - D * (K - 1) - 1) // S + 1
    OW = (W + 2 * P - D * (K - 1) - 1) // S + 1
    out = np.empty((N, OH, OW, F), dtype=np.float32)
    kernel(padded, weight, out)
    if check:
        ref = np.asarray(ref_conv2d(data, weight, S, P, D))
        np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-1)
        print(f"conv2d N{N} C{C} H{H} W{W} F{F} K{K} S{S} D{D} P{P}: "
              "matches lax.conv_general_dilated ✓")
    return kernel


def main(argv=()):
    argv = list(argv) if argv is not None else None
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=4)
    p.add_argument("--c", type=int, default=128)
    p.add_argument("--h", type=int, default=32)
    p.add_argument("--w", type=int, default=32)
    p.add_argument("--f", type=int, default=128)
    p.add_argument("--k", type=int, default=3)
    p.add_argument("--s", type=int, default=1)
    p.add_argument("--d", type=int, default=1)
    p.add_argument("--p", type=int, default=1)
    a = p.parse_args(argv)
    kernel = run(a.n, a.c, a.h, a.w, a.f, a.k, a.s, a.d, a.p)
    prof = kernel.get_profiler()
    print(f"latency: {prof.do_bench(warmup=2, rep=5, backend='wall'):.3f} ms")


if __name__ == "__main__":
    main(None)
