"""Sequence-parallel ring attention over an ICI ring (composition of the
mesh collectives + partial flash kernel; SURVEY §5.7 flagship demo)."""

import numpy as np
import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_attention import _reference_attention
from tilelang_mesh_tpu.parallel.ring_attention import make_ring_attention


def main(B=1, H=2, S=1024, D=64):
    n = 4 if len(jax.devices()) >= 4 else 1
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:n]), ("sp",))
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    fn = make_ring_attention(mesh, "sp", causal=True)
    out = fn(q, k, v)
    ref = _reference_attention(q, k, v, True, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)
    print(f"ring attention over {n} devices matches full causal attention "
          f"(seq {S} split into {S // n}-token shards).")


if __name__ == "__main__":
    main()
