"""DeepSeek MLA decode (reference examples/deepseek_mla)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.mla import mla_decode, mla_decode_reference


def main(B=2, H=16, S=1024, dc=256, dr=32):
    rng = np.random.default_rng(0)
    qc = jnp.asarray(rng.standard_normal((B, H, dc)) * 0.3, jnp.float32)
    qr = jnp.asarray(rng.standard_normal((B, H, dr)) * 0.3, jnp.float32)
    ckv = jnp.asarray(rng.standard_normal((B, S, dc)) * 0.3, jnp.float32)
    kpe = jnp.asarray(rng.standard_normal((B, S, dr)) * 0.3, jnp.float32)
    out = mla_decode(qc, qr, ckv, kpe, n_split=4)
    ref = mla_decode_reference(qc, qr, ckv, kpe)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)
    print("MLA decode matches reference; latent output:", out.shape)


if __name__ == "__main__":
    main()
