"""MLA decode split-KV sweep (the TPU answer to the reference's
examples/deepseek_mla/example_mla_decode_persistent.py /
example_mla_decode_ws.py scheduling variants).

On GPUs those variants re-schedule warps/CTAs; on TPU the scheduling
lever for one-token decode is `n_split` — how many cache chunks produce
partial online-softmax statistics in parallel before the exact merge.
With the block size held FIXED, every split count reduces the same
blocks in the same order, so outputs agree to float-merge tightness;
hardware picks the fastest (bench.py::cfg_mla_decode sweeps this)."""

import numpy as np
import jax.numpy as jnp

from tilelang_mesh_tpu.ops import mla_decode, mla_decode_reference


def main(B=1, H=8, S=1024, dc=256, dr=32):
    rng = np.random.default_rng(0)
    q_l = jnp.asarray(rng.standard_normal((B, H, dc)) * 0.1, jnp.float32)
    q_r = jnp.asarray(rng.standard_normal((B, H, dr)) * 0.1, jnp.float32)
    ckv = jnp.asarray(rng.standard_normal((B, S, dc)) * 0.1, jnp.float32)
    kpe = jnp.asarray(rng.standard_normal((B, S, dr)) * 0.1, jnp.float32)

    want = np.asarray(mla_decode_reference(q_l, q_r, ckv, kpe))
    outs = {}
    for ns in (1, 2, 4, 8):
        # FIXED block_N: every split count reduces identical blocks in
        # identical order, isolating the merge as the only variable
        o = np.asarray(mla_decode(q_l, q_r, ckv, kpe, n_split=ns,
                                  block_N=128))
        np.testing.assert_allclose(o, want, rtol=2e-2, atol=2e-2)
        outs[ns] = o
    # the split-KV merge itself: near-bitwise across split counts
    for ns in (2, 4, 8):
        np.testing.assert_allclose(outs[ns], outs[1], rtol=2e-6,
                                   atol=2e-7)
    print("MLA decode split-KV: n_split in {1,2,4,8} all match the XLA "
          "reference; at fixed block_N the merge is float-exact.")


if __name__ == "__main__":
    main()
