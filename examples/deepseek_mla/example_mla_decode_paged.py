"""Paged-KV flash decode (reference
examples/deepseek_mla/example_mla_decode_paged.py behavior): the KV cache
lives in fixed-size pages addressed through a per-sequence page table;
pages are gathered at the XLA level and fed to the split-KV kernel."""

import jax.numpy as jnp
import numpy as np

from tilelang_mesh_tpu.ops.flash_decoding import (flash_decode,
                                                  flash_decode_paged)


def main(B=2, H=4, D=64, page_size=64, pages_per_seq=4, n_pages=16):
    rng = np.random.default_rng(0)
    S = page_size * pages_per_seq
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)), jnp.float32)
    kv_pages = jnp.asarray(
        rng.standard_normal((n_pages, page_size, H, D)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((n_pages, page_size, H, D)), jnp.float32)
    # distinct random pages per sequence
    table = np.stack([rng.choice(n_pages, pages_per_seq, replace=False)
                      for _ in range(B)]).astype(np.int32)

    out = flash_decode_paged(q, kv_pages, v_pages, jnp.asarray(table))

    # reference: materialize each sequence's KV contiguously
    k = np.take(np.asarray(kv_pages), table, 0).reshape(B, S, H, D)
    v = np.take(np.asarray(v_pages), table, 0).reshape(B, S, H, D)
    ref = flash_decode(q, jnp.asarray(k.transpose(0, 2, 1, 3)),
                       jnp.asarray(v.transpose(0, 2, 1, 3)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)
    print(f"paged decode (B={B}, {pages_per_seq} pages x {page_size}) "
          f"matches contiguous decode.")


if __name__ == "__main__":
    main()
