"""Software-pipelined GEMM, stage-2 (reference examples/warp_specialize/
example_warp_specialize_gemm_softpipe_stage2.py).

The reference's soft-pipeline variant lets the compiler rotate
multi-versioned smem buffers (InjectSoftwarePipeline). The TPU analog is
T.Pipelined(num_stages=2): the K loop becomes a serial Pallas grid axis and
Mosaic multi-buffers the BlockSpec fetches — the same prologue/steady/
epilogue rotation, synthesized by the compiler instead of spelled with
semaphores (contrast with example_dma_compute_overlap.py)."""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


@tilelang.jit
def matmul_softpipe(M, N, K, block_M=128, block_N=128, block_K=128,
                    num_stages=2, dtype="float32"):
    @T.prim_func
    def gemm_sp2(A: T.Tensor((M, K), dtype),
                 B: T.Tensor((K, N), dtype),
                 C: T.Tensor((M, N), dtype)):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_s = T.alloc_shared((block_M, block_K), dtype)
            B_s = T.alloc_shared((block_K, block_N), dtype)
            acc = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(acc)
            for ko in T.Pipelined(T.ceildiv(K, block_K),
                                  num_stages=num_stages):
                T.copy(A[by * block_M, ko * block_K], A_s)
                T.copy(B[ko * block_K, bx * block_N], B_s)
                T.gemm(A_s, B_s, acc)
            T.copy(acc, C[by * block_M, bx * block_N])

    return gemm_sp2


def main(M=256, N=256, K=512):
    kernel = matmul_softpipe(M, N, K)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    c = np.empty((M, N), np.float32)
    kernel(a, b, c)
    np.testing.assert_allclose(c, a @ b, rtol=1e-2, atol=1e-1)
    lat = kernel.get_profiler().do_bench(warmup=2, rep=5, backend="wall")
    print(f"soft-pipelined GEMM correct; latency {lat:.3f} ms")


if __name__ == "__main__":
    main()
