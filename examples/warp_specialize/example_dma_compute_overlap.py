"""Producer/consumer overlap with explicit semaphores (reference
examples/warp_specialize/example_warp_specialize_gemm_copy_0_gemm_1.py).

The reference splits 256 threads into a copy warp-group and an MMA
warp-group handshaking via mbarriers (T.ws(0/1), T.alloc_barrier,
barrier_arrive/wait). TPUs have no warps: the same overlap is expressed as
*split-phase DMA* — T.copy_async issues the next K-slab's fetch while the
MXU consumes the current one, and T.copy_wait blocks on the DMA semaphore
exactly where the mbarrier wait sat. Same schedule, two hardware idioms.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


@tilelang.jit
def matmul_overlap(M, N, K, block_M=128, block_N=128, block_K=128,
                   dtype="float32"):
    nstep = (K + block_K - 1) // block_K

    @T.prim_func
    def gemm_db(A: T.Tensor((M, K), dtype),
                B: T.Tensor((K, N), dtype),
                C: T.Tensor((M, N), dtype)):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_s = T.alloc_shared((2, block_M, block_K), dtype)
            B_s = T.alloc_shared((2, block_K, block_N), dtype)
            acc = T.alloc_fragment((block_M, block_N), "float32")
            sems = T.alloc_semaphore(4)  # 2 slots x {A, B}
            T.clear(acc)
            # prologue: the "producer" issues slot 0 (data_is_ready analog)
            T.copy_async(A[by * block_M, 0],
                         A_s[0, 0:block_M, 0:block_K], sems, 0)
            T.copy_async(B[0, bx * block_N],
                         B_s[0, 0:block_K, 0:block_N], sems, 2)
            for ko in range(nstep):
                cur, nxt = ko % 2, (ko + 1) % 2
                if ko + 1 < nstep:  # producer runs one slab ahead
                    T.copy_async(A[by * block_M, (ko + 1) * block_K],
                                 A_s[nxt, 0:block_M, 0:block_K], sems, nxt)
                    T.copy_async(B[(ko + 1) * block_K, bx * block_N],
                                 B_s[nxt, 0:block_K, 0:block_N],
                                 sems, 2 + nxt)
                # consumer waits where the reference had barrier_wait
                T.copy_wait(A[by * block_M, ko * block_K],
                            A_s[cur, 0:block_M, 0:block_K], sems, cur)
                T.copy_wait(B[ko * block_K, bx * block_N],
                            B_s[cur, 0:block_K, 0:block_N], sems, 2 + cur)
                T.gemm(A_s[cur, 0:block_M, 0:block_K],
                       B_s[cur, 0:block_K, 0:block_N], acc)
            T.copy(acc, C[by * block_M, bx * block_N])

    return gemm_db


def main(M=256, N=256, K=512):
    kernel = matmul_overlap(M, N, K)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    c = np.empty((M, N), np.float32)
    kernel(a, b, c)
    np.testing.assert_allclose(c, a @ b, rtol=1e-2, atol=1e-1)
    src = kernel.get_kernel_source()
    assert "rt.dma_start" in src and "rt.dma_wait" in src
    print("split-phase DMA GEMM correct; "
          f"{src.count('rt.dma_start')} starts / "
          f"{src.count('rt.dma_wait')} waits in the generated kernel")


if __name__ == "__main__":
    main()
