"""DeepGEMM-style fp8 GEMM with 128-block scaling factors (reference
examples/deepseek_deepgemm/example_deepgemm_fp8_2xAcc.py).

A is float8_e4m3 with one f32 scale per (row, 128-wide K group); B is
row-major (N, K) fp8 with one scale per (128-block of N, K group). Each
K-block partial product is computed in fp8 on the MXU with f32
accumulation, then promoted into the running accumulator scaled by
scale_a * scale_b — the "2x accumulation" trick that recovers fp8 dynamic
range. The reference's Hopper-specific pieces (TMA store, L2 swizzle,
warp split) dissolve into Mosaic's pipeline.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

GROUP = 128


@tilelang.jit
def deepgemm_fp8(M, N, K, block_N=128, out_dtype="float32",
                 num_stages=2):
    block_M, block_K = 128, GROUP
    k_groups = (K + GROUP - 1) // GROUP
    if block_N % GROUP:
        raise ValueError(f"block_N ({block_N}) must be a multiple of the "
                         f"scale group size {GROUP}")
    n_segs = block_N // GROUP  # scale rows covered by one N block

    @T.prim_func
    def gemm_fp8_blockscaled(
            A: T.Tensor((M, K), "float8_e4m3fn"),
            B: T.Tensor((N, K), "float8_e4m3fn"),
            C: T.Tensor((M, N), out_dtype),
            scales_a: T.Tensor((M, k_groups), "float32"),
            scales_b: T.Tensor((N // GROUP, k_groups), "float32")):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_s = T.alloc_shared((block_M, block_K), "float8_e4m3fn")
            B_s = T.alloc_shared((block_N, block_K), "float8_e4m3fn")
            sa_s = T.alloc_shared((block_M, 1), "float32")
            sb_s = T.alloc_shared((n_segs, 1), "float32")
            C_partial = T.alloc_fragment((block_M, block_N), "float32")
            C_accum = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(C_accum)
            for k in T.Pipelined(T.ceildiv(K, block_K),
                                 num_stages=num_stages):
                T.copy(A[by * block_M, k * block_K], A_s)
                T.copy(B[bx * block_N, k * block_K], B_s)
                T.copy(scales_a[by * block_M, k], sa_s)
                T.copy(scales_b[bx * n_segs, k], sb_s)
                T.gemm(A_s, B_s, C_partial, transpose_B=True,
                       clear_accum=True)
                # each GROUP-wide N segment carries its own B scale
                for seg in range(n_segs):
                    for i, j in T.Parallel(block_M, GROUP):
                        C_accum[i, seg * GROUP + j] += (
                            C_partial[i, seg * GROUP + j] *
                            (sa_s[i, 0] * sb_s[seg, 0]))
            T.copy(C_accum, C[by * block_M, bx * block_N])

    return gemm_fp8_blockscaled


def quant_fp8_rowwise(x):
    """Per-(row, 128-group) e4m3 quantization: scale = absmax/448."""
    M, K = x.shape
    g = x.reshape(M, K // GROUP, GROUP)
    absmax = np.clip(np.abs(g).max(axis=2), 1e-4, None)
    scales = (absmax / 448.0).astype(np.float32)
    q = g / scales[:, :, None]
    import jax.numpy as jnp
    return (np.asarray(jnp.asarray(q.reshape(M, K), jnp.float8_e4m3fn)),
            scales)


def quant_fp8_blockwise(x):
    """Per-(128x128 block) e4m3 quantization for the weight operand."""
    N, K = x.shape
    g = x.reshape(N // GROUP, GROUP, K // GROUP, GROUP)
    absmax = np.clip(np.abs(g).max(axis=(1, 3)), 1e-4, None)
    scales = (absmax / 448.0).astype(np.float32)
    q = g / scales[:, None, :, None]
    import jax.numpy as jnp
    return (np.asarray(jnp.asarray(
        q.transpose(0, 1, 2, 3).reshape(N, K), jnp.float8_e4m3fn)),
        scales)


def main(M=256, N=256, K=512):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((N, K), dtype=np.float32)
    a_q, sa = quant_fp8_rowwise(a)
    b_q, sb = quant_fp8_blockwise(b)

    kernel = deepgemm_fp8(M, N, K)
    c = np.empty((M, N), dtype=np.float32)
    kernel(a_q, b_q, c, sa, sb)

    # reference: dequantized fp8 operands in f32 (isolates kernel error
    # from quantization error, like the reference's ref_program)
    import jax.numpy as jnp
    a_deq = np.asarray(a_q, np.float32).reshape(M, K // GROUP, GROUP) * \
        sa[:, :, None]
    b_deq = (np.asarray(b_q, np.float32)
             .reshape(N // GROUP, GROUP, K // GROUP, GROUP) *
             sb[:, None, :, None])
    ref = a_deq.reshape(M, K) @ b_deq.reshape(N, K).T
    np.testing.assert_allclose(c, ref, rtol=5e-2, atol=5e-1)
    rel = np.abs(c - a @ b.T).mean() / np.abs(a @ b.T).mean()
    print(f"fp8 block-scaled GEMM {M}x{N}x{K} ✓ "
          f"(end-to-end quantization relerr {rel:.3%})")


if __name__ == "__main__":
    main()
