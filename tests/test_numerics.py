"""tl-num numerical-safety analysis suite (analysis/absint.py,
analysis/numerics.py; docs/static_analysis.md "tl-num").

Five layers:

1. **Domain units** — interval arithmetic, saturation-to-unknown, join
   semantics of the dual-track abstract value.
2. **Rule fire / no-fire pairs** — each of TL007-TL010 on its canonical
   bug AND on the guarded idiom the ops library uses (clamped divide,
   max-subtracted exp, planar +8 decode, f32 accumulation), plus the
   seeded mutation sweep (tools/num_sweep.py) across seeds.
3. **Proof precision contract** — the exact golden set of (kernel,
   rule, severity) findings over the shipped ops library: zero errors,
   and the warning set is pinned so precision drift is a visible diff.
4. **Finiteness proofs & TL_TPU_SANITIZE=auto** — attrs["numerics"] on
   plain + mesh artifacts, differential parity vs =1, the
   sanitize.elided counter, and the elision-never-skips-unproven
   guarantee under a comm.collective corrupt fault.
5. **Surfacing** — plan_desc lint block, strict-mode escalation with
   the flight-recorder dump naming kernel+rules, CLI loc round-trip,
   severity summary, cache-key separation of the tl-num knobs.
"""

import json
import math

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.analysis import (SemanticError, collect_diagnostics,
                                        analyze_numerics)
from tilelang_mesh_tpu.analysis.absint import (INF, AbsVal, av_div, av_max,
                                               av_mul, mk)
from tilelang_mesh_tpu.cache.kernel_cache import _CACHE, KernelCache
from tilelang_mesh_tpu.observability import get_tracer
from tilelang_mesh_tpu.parallel import mesh_config
from tilelang_mesh_tpu.resilience import inject
from tilelang_mesh_tpu.verify import NumericError
from tilelang_mesh_tpu.verify.runtime import sanitize_mode


@pytest.fixture(autouse=True)
def _hermetic(monkeypatch):
    for var in ("TL_TPU_SANITIZE", "TL_TPU_LINT", "TL_TPU_TRACE",
                "TL_TPU_FAULTS", "TL_TPU_NUM_ASSUME_ABS",
                "TL_TPU_RUNTIME_METRICS"):
        monkeypatch.delenv(var, raising=False)
    _CACHE.clear()
    get_tracer().reset()
    obs.reset()
    yield
    _CACHE.clear()
    get_tracer().reset()
    obs.reset()


def _rules(func, **kw):
    return {d.rule for d in collect_diagnostics(func, with_plan=False, **kw)}


def _diags(func):
    return collect_diagnostics(func, with_plan=False)


# ---------------------------------------------------------------------------
# 1. domain units
# ---------------------------------------------------------------------------


def test_interval_mul_signs():
    a = mk(-2.0, 3.0, -2.0, 3.0, True)
    b = mk(-5.0, 4.0, -5.0, 4.0, True)
    r = av_mul(a, b)
    assert (r.lo, r.hi) == (-15.0, 12.0)
    assert (r.slo, r.shi) == (-15.0, 12.0)


def test_interval_div_excludes_zero():
    a = mk(1.0, 10.0, 1.0, 10.0, True)
    b = mk(2.0, 4.0, 2.0, 4.0, True)
    r = av_div(a, b)
    assert r.lo == 0.25 and r.hi == 5.0 and r.finite


def test_saturation_to_unknown():
    """Bounds past any dtype's range become +-inf (unknown) — a guard
    epsilon must not manufacture a fake bounded-overflow proof."""
    a = mk(0.0, 1e30, 0.0, 1e30, True)
    b = mk(1e-300, 1.0, 1e-300, 1.0, True)
    r = av_div(a, b)
    assert r.shi == INF and r.hi == INF


def test_join_intersects_relational_state():
    a = AbsVal(0.0, 1.0, 0.0, 1.0, finite=True, unit_dim=1)
    b = AbsVal(0.0, 2.0, 0.0, 2.0, finite=True, unit_dim=0)
    j = a.join(b)
    assert j.unit_dim is None and j.hi == 2.0 and j.finite


def test_av_max_drops_facts():
    from tilelang_mesh_tpu.analysis.absint import DomFact
    a = AbsVal(0.0, 1.0, 0.0, 1.0, finite=True,
               facts=frozenset({DomFact(1, 0, 1, True)}))
    assert av_max(a, AbsVal.const(0.5)).facts == frozenset()


# ---------------------------------------------------------------------------
# 2. fire / no-fire pairs
# ---------------------------------------------------------------------------


def _int_accum_kernel(acc_dtype):
    @T.prim_func
    def k(A: T.Tensor((128, 512), "int8"), B: T.Tensor((512, 128), "int8"),
          C: T.Tensor((128, 128), "float32")):
        with T.Kernel(1) as bx:
            acc = T.alloc_fragment((128, 128), acc_dtype)
            out = T.alloc_fragment((128, 128), "float32")
            T.clear(acc)
            T.gemm(A, B, acc)
            for i, j in T.Parallel(128, 128):
                out[i, j] = T.cast(acc[i, j], "float32")
            T.copy(out, C)
    return k


def test_tl007_int_wrap_fires_and_int32_silent():
    assert "TL007" in _rules(_int_accum_kernel("int16").func)
    assert "TL007" not in _rules(_int_accum_kernel("int32").func)


def test_tl007_is_error_severity():
    d = [x for x in _diags(_int_accum_kernel("int16").func)
         if x.rule == "TL007"]
    assert d and all(x.severity == "error" for x in d)
    assert "int16" in d[0].message


def _range_kernel(dst_dtype):
    @T.prim_func
    def k(C: T.Tensor((8, 128), dst_dtype)):
        with T.Kernel(1) as bx:
            a = T.alloc_fragment((8, 128), "float32")
            b = T.alloc_fragment((8, 128), dst_dtype)
            T.fill(a, 1.7e38)
            for i, j in T.Parallel(8, 128):
                b[i, j] = a[i, j] + a[i, j]
            T.copy(b, C)
    return k


def test_tl007_bf16_range_escape():
    """3.4e38 fits float32 (3.4028e38) but not bfloat16 (3.3895e38)."""
    assert "TL007" in _rules(_range_kernel("bfloat16").func)
    assert "TL007" not in _rules(_range_kernel("float32").func)


def _gemm_accum_kernel(accum_dtype, nk):
    @T.prim_func
    def k(A: T.Tensor((128, nk * 128), "bfloat16"),
          B: T.Tensor((nk * 128, 128), "bfloat16"),
          C: T.Tensor((128, 128), "bfloat16")):
        with T.Kernel(1) as bx:
            a_s = T.alloc_shared((128, 128), "bfloat16")
            b_s = T.alloc_shared((128, 128), "bfloat16")
            c_l = T.alloc_fragment((128, 128), accum_dtype)
            c_o = T.alloc_fragment((128, 128), "bfloat16")
            T.clear(c_l)
            for ko in T.Pipelined(nk):
                T.copy(A[0, ko * 128], a_s)
                T.copy(B[ko * 128, 0], b_s)
                T.gemm(a_s, b_s, c_l)
            for i, j in T.Parallel(128, 128):
                c_o[i, j] = T.cast(c_l[i, j], "bfloat16")
            T.copy(c_o, C)
    return k


def test_tl008_bf16_accum_large_k_fires():
    found = [d for d in _diags(_gemm_accum_kernel("bfloat16", 32).func)
             if d.rule == "TL008"]
    assert found and found[0].severity == "warning"
    assert "float32" in found[0].message      # the fix suggestion


def test_tl008_f32_accum_idiom_silent():
    """The f32-accumulate idiom every ops kernel uses, at the same K."""
    assert "TL008" not in _rules(_gemm_accum_kernel("float32", 32).func)


def test_tl008_bf16_small_k_silent():
    """4 trips x 2^-8 = 0.0156 stays under the 1/16 threshold."""
    assert "TL008" not in _rules(_gemm_accum_kernel("bfloat16", 4).func)


def _softmax_kernel(max_sub, guard="none"):
    @T.prim_func
    def k(A: T.Tensor((8, 128), "float32"), O: T.Tensor((8, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_fragment((8, 128), "float32")
            mx = T.alloc_fragment((8,), "float32")
            den = T.alloc_fragment((8,), "float32")
            T.copy(A, s)
            T.reduce_max(s, mx, dim=1)
            for i, j in T.Parallel(8, 128):
                if max_sub:
                    s[i, j] = T.exp(s[i, j] - mx[i])
                else:
                    s[i, j] = T.exp(s[i, j])
            T.reduce_sum(s, den, dim=1)
            for i, j in T.Parallel(8, 128):
                if guard == "clamp":
                    s[i, j] = s[i, j] / T.max(den[i], 1e-30)
                elif guard == "where":
                    s[i, j] = T.if_then_else(den[i] > 0.0,
                                             s[i, j] / den[i], 0.0)
                else:
                    s[i, j] = s[i, j] / den[i]
            T.copy(s, O)
    return k


def test_tl009_softmax_idiom_proven_safe():
    """The headline proof: exp(x - rowmax(x)) <= 1 AND the normalizer
    rowsum >= 1 (the argmax term is exactly exp(0)=1) — the bare divide
    after a TIGHT max-subtraction is clean with no guard at all."""
    assert not _diags(_softmax_kernel(max_sub=True).func)


def test_tl009_missing_max_subtraction_warns():
    d = [x for x in _diags(_softmax_kernel(max_sub=False).func)
         if x.rule == "TL009"]
    assert d and any("max" in x.message for x in d)


def _nontight_div_kernel(guard):
    """Flash-class: the -1e30 floor makes the max non-tight, so the
    normalizer's >= 1 proof is gone — the divide needs a guard."""
    @T.prim_func
    def k(A: T.Tensor((8, 128), "float32"), O: T.Tensor((8, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_fragment((8, 128), "float32")
            mx = T.alloc_fragment((8,), "float32")
            m2 = T.alloc_fragment((8,), "float32")
            den = T.alloc_fragment((8,), "float32")
            T.copy(A, s)
            T.reduce_max(s, mx, dim=1)
            for i in T.Parallel(8):
                m2[i] = T.max(mx[i], -1e30)
            for i, j in T.Parallel(8, 128):
                s[i, j] = T.exp(s[i, j] - m2[i])
            T.reduce_sum(s, den, dim=1)
            for i, j in T.Parallel(8, 128):
                if guard == "clamp":
                    s[i, j] = s[i, j] / T.max(den[i], 1e-30)
                elif guard == "where":
                    s[i, j] = T.if_then_else(den[i] > 0.0,
                                             s[i, j] / den[i], 0.0)
                else:
                    s[i, j] = s[i, j] / den[i]
            T.copy(s, O)
    return k


def test_tl009_unguarded_division_is_error():
    d = [x for x in _diags(_nontight_div_kernel("none").func)
         if x.rule == "TL009"]
    assert d and d[0].severity == "error"


def test_tl009_clamped_divide_silent():
    assert "TL009" not in _rules(_nontight_div_kernel("clamp").func)


def test_tl009_where_guarded_divide_silent():
    assert "TL009" not in _rules(_nontight_div_kernel("where").func)


def _log_kernel(guarded):
    @T.prim_func
    def k(A: T.Tensor((8, 128), "float32"), O: T.Tensor((8, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_fragment((8, 128), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(8, 128):
                if guarded:
                    s[i, j] = T.log2(T.max(s[i, j], 1e-30))
                else:
                    s[i, j] = T.log2(s[i, j])
            T.copy(s, O)
    return k


def test_tl009_log_of_raw_input_warns_and_clamp_silences():
    d = [x for x in _diags(_log_kernel(False).func) if x.rule == "TL009"]
    assert d and d[0].severity == "warning"
    assert "65536" in d[0].message        # names the assumption
    assert "TL009" not in _rules(_log_kernel(True).func)


def test_tl009_rsqrt_of_square_plus_eps_silent():
    """x*x is recognized as nonnegative (the rmsnorm guard shape)."""
    @T.prim_func
    def k(A: T.Tensor((8, 128), "float32"), O: T.Tensor((8, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_fragment((8, 128), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(8, 128):
                s[i, j] = T.rsqrt(s[i, j] * s[i, j] + 1e-6)
            T.copy(s, O)
    assert "TL009" not in _rules(k.func)


def _decode_kernel(zp, mask=0xF):
    @T.prim_func
    def k(Bp: T.Tensor((256, 128), "uint8"), S: T.Tensor((1, 128), "float32"),
          Bd: T.Tensor((256, 128), "float32")):
        with T.Kernel(1) as bx:
            d = T.alloc_fragment((256, 128), "float32")
            for i, j in T.Parallel(256, 128):
                d[i, j] = (T.cast(T.bitwise_and(
                    T.cast(Bp[i, j], "int32"), mask), "float32")
                    - float(zp)) * S[0, j]
            T.copy(d, Bd)
    return k


def test_tl010_bad_zero_point_fires_planar_decode_silent():
    d = [x for x in _diags(_decode_kernel(16).func) if x.rule == "TL010"]
    assert d and d[0].severity == "error" and "envelope" in d[0].message
    assert "TL010" not in _rules(_decode_kernel(8).func)   # the +8 bias
    assert "TL010" not in _rules(_decode_kernel(0).func)   # unsigned


def test_tl010_twos_complement_branch_decode_silent():
    """(q & 0xF) then where(q >= 8, q - 16, q): the q-16 arm judges
    against its branch-refined [8, 15] sub-range — a legal decode."""
    @T.prim_func
    def k(Bp: T.Tensor((256, 128), "uint8"),
          Bd: T.Tensor((256, 128), "float32")):
        with T.Kernel(1) as bx:
            q = T.alloc_fragment((256, 128), "int32")
            d = T.alloc_fragment((256, 128), "float32")
            for i, j in T.Parallel(256, 128):
                q[i, j] = T.bitwise_and(T.cast(Bp[i, j], "int32"), 0xF)
            for i, j in T.Parallel(256, 128):
                d[i, j] = T.cast(T.if_then_else(
                    q[i, j] >= 8, q[i, j] - 16, q[i, j]), "float32")
            T.copy(d, Bd)
    assert "TL010" not in _rules(k.func)


def test_mutation_sweep_all_rules_fire():
    from tilelang_mesh_tpu.tools.num_sweep import run_sweep
    for seed in (0, 1, 2):
        rep = run_sweep(seed)
        assert rep["ok"], rep
        assert rep["rules_fired"] == ["TL007", "TL008", "TL009", "TL010"]


# ---------------------------------------------------------------------------
# 3. ops-library precision golden
# ---------------------------------------------------------------------------

#: the exact tl-num finding surface over the shipped ops library at the
#: smoke seeds — every entry is a CONTRACT-dependent warning (raw-input
#: exp/log the kernel cannot bound); zero errors is the CI gate. A new
#: entry here must be justified the way these are.
OPS_GOLDEN_WARNINGS = {
    ("attention_sink", "sink_fwd", "TL009", "warning"),
    ("flash_attention_bwd", "dkdv", "TL009", "warning"),
    ("flash_attention_bwd", "dq", "TL009", "warning"),
    ("flash_attention_varlen", "vdkdv", "TL009", "warning"),
    ("flash_attention_varlen", "vdq", "TL009", "warning"),
    ("gdn", "gdn_fwd", "TL009", "warning"),
    ("gqa_bwd", "dkdv", "TL009", "warning"),
    ("gqa_bwd", "dq", "TL009", "warning"),
    ("linear_attention", "retention", "TL009", "warning"),
    ("mamba2", "ssd", "TL009", "warning"),
    ("nsa_bwd", "nsa_dkdv", "TL009", "warning"),
    ("nsa_bwd", "nsa_dq", "TL009", "warning"),
}

#: ops kernels whose every floating output is proven finite (the
#: TL_TPU_SANITIZE=auto elision set must never silently shrink)
OPS_PROVEN_MIN = {
    ("dequant_gemm", "main"), ("dequant_gemm", "dq"),
    ("dequant_gemm", "w4a8"), ("gemm", "gemm"),
    ("flash_decoding", "dec"), ("flash_decoding", "pdec"),
    ("mla", "mla"), ("linear_attention", "lin_attn"),
}


def test_ops_library_numerics_golden():
    from pathlib import Path

    from tilelang_mesh_tpu.tools.lint import collect_module_kernels
    ops = Path(tilelang.__file__).parent / "ops"
    got = set()
    proven = set()
    for f in sorted(ops.glob("*.py")):
        if f.name.startswith("_"):
            continue
        objs, _notes = collect_module_kernels(f)
        for obj in objs:
            res = analyze_numerics(obj.func)
            for d in res.findings:
                got.add((f.stem, obj.func.name, d.rule, d.severity))
            if res.proven_finite:
                proven.add((f.stem, obj.func.name))
    assert not {g for g in got if g[3] == "error"}, got
    assert got == OPS_GOLDEN_WARNINGS, got ^ OPS_GOLDEN_WARNINGS
    assert OPS_PROVEN_MIN <= proven, OPS_PROVEN_MIN - proven


def test_quantize_module_lints_clean_and_proves():
    """The quantize/ factory added to the lint sweep: clean at every
    severity, outputs proven finite (clamp + guarded divide)."""
    from pathlib import Path

    from tilelang_mesh_tpu.tools.lint import lint_targets
    qdir = Path(tilelang.__file__).parent / "quantize"
    rep = lint_targets([str(qdir)])
    assert rep["kernels_linted"] >= 1
    assert rep["summary"]["total"] == 0, rep["findings"]


def test_quantize_act_kernel_numerics():
    from tilelang_mesh_tpu.quantize.quantization import (
        quantize_act_int8_kernel, quantize_act_int8_ref)
    k = quantize_act_int8_kernel(64, 128, block_M=32)
    assert (k.artifact.attrs.get("numerics") or {}).get("proven_finite")
    x = np.random.default_rng(0).standard_normal((64, 128)) \
        .astype(np.float32) * 3
    q, s = k(x)
    qr, sr = quantize_act_int8_ref(x)
    np.testing.assert_allclose(np.asarray(s), sr, rtol=1e-6)
    assert (np.abs(np.asarray(q).astype(np.int32)
                   - qr.astype(np.int32)) <= 1).all()
    q0, s0 = k(np.zeros((64, 128), np.float32))   # all-zero rows: no NaN
    assert np.isfinite(np.asarray(s0)).all()
    assert (np.asarray(q0) == 0).all()


# ---------------------------------------------------------------------------
# 4. finiteness proofs & TL_TPU_SANITIZE=auto
# ---------------------------------------------------------------------------


def _matmul():
    @T.prim_func
    def mm(A: T.Tensor((128, 256), "float32"),
           B: T.Tensor((256, 128), "float32"),
           C: T.Tensor((128, 128), "float32")):
        with T.Kernel(1) as bx:
            a_s = T.alloc_shared((128, 128), "float32")
            b_s = T.alloc_shared((128, 128), "float32")
            c_l = T.alloc_fragment((128, 128), "float32")
            T.clear(c_l)
            for ko in T.Pipelined(2):
                T.copy(A[0, ko * 128], a_s)
                T.copy(B[ko * 128, 0], b_s)
                T.gemm(a_s, b_s, c_l)
            T.copy(c_l, C)
    return mm


def _exp_kernel():
    @T.prim_func
    def ek(A: T.Tensor((8, 128), "float32"), O: T.Tensor((8, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_fragment((8, 128), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(8, 128):
                s[i, j] = T.exp(s[i, j])
            T.copy(s, O)
    return ek


def test_sanitize_mode_parsing(monkeypatch):
    assert sanitize_mode() == "off"
    monkeypatch.setenv("TL_TPU_SANITIZE", "1")
    assert sanitize_mode() == "on"
    monkeypatch.setenv("TL_TPU_SANITIZE", "auto")
    assert sanitize_mode() == "auto"
    monkeypatch.setenv("TL_TPU_SANITIZE", "yolo")
    with pytest.raises(ValueError, match="TL_TPU_SANITIZE"):
        sanitize_mode()


def test_proof_attrs_on_plain_artifact():
    k = tilelang.compile(_matmul())
    num = k.artifact.attrs.get("numerics")
    assert num and num["proven_finite"] and num["outputs"] == {"C": True}
    _CACHE.clear()
    ke = tilelang.compile(_exp_kernel())
    nume = ke.artifact.attrs.get("numerics")
    assert nume and not nume["proven_finite"]
    assert nume["outputs"] == {"O": False}


def test_lint_off_produces_no_proof(monkeypatch):
    monkeypatch.setenv("TL_TPU_LINT", "0")
    k = tilelang.compile(_matmul())
    assert "numerics" not in k.artifact.attrs


def test_auto_parity_and_elision_on_proven_kernel(monkeypatch):
    """Acceptance: =auto is bit-identical to =1 on the proven kernel
    while skipping the runtime pass (sanitize.elided counts it)."""
    a = np.random.default_rng(0).standard_normal((128, 256)) \
        .astype(np.float32)
    b = np.random.default_rng(1).standard_normal((256, 128)) \
        .astype(np.float32)
    monkeypatch.setenv("TL_TPU_SANITIZE", "1")
    k = tilelang.compile(_matmul())
    r_on = np.asarray(k(a, b))
    counters = get_tracer().counters()
    assert not any("sanitize.elided" in c for c in counters)
    monkeypatch.setenv("TL_TPU_SANITIZE", "auto")
    r_auto = np.asarray(k(a, b))
    np.testing.assert_array_equal(r_on, r_auto)
    counters = get_tracer().counters()
    assert counters.get("sanitize.elided{kernel=mm}", 0) >= 1


def test_auto_still_checks_unproven_kernel(monkeypatch):
    """An unprovable kernel (bare exp) must behave exactly like =1:
    a non-finite output raises in BOTH modes; =auto elides nothing."""
    big = np.full((8, 128), 200.0, np.float32)     # exp(200) = inf
    fine = np.zeros((8, 128), np.float32)
    for mode in ("1", "auto"):
        _CACHE.clear()
        get_tracer().reset()
        monkeypatch.setenv("TL_TPU_SANITIZE", mode)
        k = tilelang.compile(_exp_kernel())
        np.testing.assert_allclose(np.asarray(k(fine)),
                                   np.ones((8, 128), np.float32))
        with pytest.raises(NumericError, match="O"):
            k(big)
        assert not any("sanitize.elided" in c
                       for c in get_tracer().counters())


def test_auto_without_proof_checks_everything(monkeypatch):
    """A proof-less artifact (TL_TPU_LINT=0 compile) proves nothing:
    auto degrades to checking every float output."""
    monkeypatch.setenv("TL_TPU_LINT", "0")
    monkeypatch.setenv("TL_TPU_SANITIZE", "auto")

    @T.prim_func
    def double(A: T.Tensor((8, 128), "float32"),
               B: T.Tensor((8, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((8, 128), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(8, 128):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, B)

    k = tilelang.compile(double)
    bad = np.ones((8, 128), np.float32)
    bad[2, 7] = np.inf
    with pytest.raises(NumericError):
        k(bad)
    assert not any("sanitize.elided" in c for c in get_tracer().counters())


def test_auto_elision_visible_in_overhead_histogram(monkeypatch):
    """The elided path records dispatch overhead like any sampled call —
    the histogram rows are how the win is measured (docs/robustness.md)."""
    monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
    monkeypatch.setenv("TL_TPU_SANITIZE", "auto")
    a = np.random.default_rng(0).standard_normal((128, 256)) \
        .astype(np.float32)
    b = np.random.default_rng(1).standard_normal((256, 128)) \
        .astype(np.float32)
    k = tilelang.compile(_matmul())
    for _ in range(4):
        k(a, b)
    from tilelang_mesh_tpu.observability.runtime import runtime_summary
    rows = runtime_summary()
    assert counters_have_elided()
    assert "fast" in rows["mm"]["host_overhead_by_path"]


def counters_have_elided():
    return any("sanitize.elided" in c for c in get_tracer().counters())


def test_auto_elides_on_proven_ops_kernel(monkeypatch):
    """Acceptance: >= 1 PROVEN ops kernel skips the runtime pass under
    =auto, bit-identical to =1, with the skip visible in the counter."""
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel
    matmul_kernel.cache_clear()
    a = np.random.default_rng(2).standard_normal((128, 128)) \
        .astype(np.float32)
    b = np.random.default_rng(3).standard_normal((128, 128)) \
        .astype(np.float32)
    k = matmul_kernel(128, 128, 128, block_M=128, block_N=128,
                      block_K=128, in_dtype="float32",
                      out_dtype="float32")
    assert (k.artifact.attrs.get("numerics") or {}).get("proven_finite")
    monkeypatch.setenv("TL_TPU_SANITIZE", "1")
    r_on = np.asarray(k(a, b))
    monkeypatch.setenv("TL_TPU_SANITIZE", "auto")
    r_auto = np.asarray(k(a, b))
    np.testing.assert_array_equal(r_on, r_auto)
    assert any("sanitize.elided" in c for c in get_tracer().counters())


# -- mesh: payload elision + corruption --------------------------------------

MESH = (2, 2)
NROW, NCOL = MESH
SHAPE = (8, 128)
TARGET = f"cpu-mesh[{NROW}x{NCOL}]"


def _mglobal(shape=None):
    shape = shape or (NROW * NCOL * SHAPE[0], SHAPE[1])
    return T.MeshTensor(shape, T.MeshShardingPolicy(cross_mesh_dim=0),
                        MESH, "float32")


def _mesh_proven_program():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _mglobal(), B: _mglobal((NROW * NCOL * SHAPE[0], 1))):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment(SHAPE, "float32")
                o = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, x)
                T.comm.all_reduce(x, o, "sum", "h", dim=1)
                T.copy(o, B)
        return k


def _mesh_unproven_program():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _mglobal(), B: _mglobal((NROW * NCOL * SHAPE[0], 1))):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment(SHAPE, "float32")
                e = T.alloc_fragment(SHAPE, "float32")
                o = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, x)
                for i, j in T.Parallel(*SHAPE):
                    e[i, j] = T.exp(x[i, j])      # unbounded payload
                T.comm.all_reduce(e, o, "sum", "h", dim=1)
                T.copy(o, B)
        return k


def _mshards(seed):
    return np.random.default_rng(seed).standard_normal(
        (NROW * NCOL * SHAPE[0], SHAPE[1])).astype(np.float32)


def test_mesh_auto_parity_and_payload_elision(monkeypatch):
    a = _mshards(3)
    monkeypatch.setenv("TL_TPU_SANITIZE", "1")
    k1 = tilelang.compile(_mesh_proven_program(), target=TARGET)
    num = k1.artifact.attrs.get("numerics")
    assert num and num["proven_finite"]
    assert num["payloads"] == [{"buffer": "frag", "proven": True}]
    r1 = np.asarray(k1(a))
    _fn, checks, _el = k1._sanitized_cache["on"]
    assert len(checks) == 2          # payload + output both checked
    monkeypatch.setenv("TL_TPU_SANITIZE", "auto")
    _CACHE.clear()
    get_tracer().reset()
    k2 = tilelang.compile(_mesh_proven_program(), target=TARGET)
    r2 = np.asarray(k2(a))
    np.testing.assert_array_equal(r1, r2)
    fn, checks, elided = k2._sanitized_cache["auto"]
    assert checks == [] and elided == 2
    assert fn is k2.func             # the PLAIN program dispatched
    assert get_tracer().counters().get(
        "sanitize.elided{kernel=k}", 0) == 2


def test_mesh_auto_never_skips_unproven_payload(monkeypatch):
    """Acceptance: a comm.collective corrupt fault on an unprovable
    program is caught identically by =1 and =auto."""
    a = _mshards(4)
    for mode in ("1", "auto"):
        monkeypatch.setenv("TL_TPU_SANITIZE", mode)
        _CACHE.clear()
        with inject("comm.collective", kind="corrupt"):
            k = tilelang.compile(_mesh_unproven_program(), target=TARGET)
            proof = k.artifact.attrs.get("_num_proof")
            assert proof == {"payload_uids": [],
                             "outputs": {"B": False}}
            with pytest.raises(NumericError):
                k(a)


def test_mesh_corrupt_budget_survives_lowering(monkeypatch):
    """A times=1 corrupt clause must poison at the RUNTIME site: the
    lowering-time comm.collective accounting visit must not consume
    the clause's budget (faults.corrupt_armed probe)."""
    monkeypatch.setenv("TL_TPU_SANITIZE", "1")
    _CACHE.clear()
    with inject("comm.collective", kind="corrupt", times=1):
        k = tilelang.compile(_mesh_unproven_program(), target=TARGET)
        with pytest.raises(NumericError):
            k(_mshards(6))


def test_mesh_corrupt_fault_is_noop_when_sanitizer_off():
    """The corrupt kind must not break an unguarded run — it poisons
    silently (the class the sanitizer exists to catch)."""
    _CACHE.clear()
    with inject("comm.collective", kind="corrupt"):
        k = tilelang.compile(_mesh_unproven_program(), target=TARGET)
        out = np.asarray(k(_mshards(5)))
    assert not np.isfinite(out).all()      # the poison went through


# ---------------------------------------------------------------------------
# 5. surfacing
# ---------------------------------------------------------------------------


def test_findings_surface_in_plan_desc_and_attrs():
    k = tilelang.compile(_softmax_kernel(max_sub=False))
    assert "lint[warn]" in k.artifact.plan_desc
    assert "TL009" in k.artifact.plan_desc
    rules = {d["rule"] for d in k.artifact.attrs["lint"]}
    assert "TL009" in rules
    summ = obs.metrics_summary()["lint"]
    assert summ["by_rule"].get("TL009")


def test_clean_kernel_plan_desc_byte_stable():
    k = tilelang.compile(_matmul())
    assert "lint[" not in k.artifact.plan_desc
    assert "lint" not in {a for a in k.artifact.attrs
                          if not a.startswith("_")} or \
        k.artifact.attrs.get("lint") is None


def test_strict_mode_rejects_and_dumps_flight(monkeypatch, tmp_path):
    """Satellite: a strict-mode compile rejection dumps the black box
    naming the kernel and rules."""
    from tilelang_mesh_tpu.observability import flight
    monkeypatch.setenv("TL_TPU_LINT", "strict")
    monkeypatch.setenv("TL_TPU_FLIGHT_DIR", str(tmp_path))
    flight.reset()
    try:
        with pytest.raises(SemanticError, match="TL009"):
            tilelang.compile(_nontight_div_kernel("none"))
        dumps = list(tmp_path.glob("flight_*_strict_lint_*.jsonl"))
        assert dumps, list(tmp_path.iterdir())
        head = json.loads(dumps[0].read_text().splitlines()[0])
        assert head["reason"] == "strict_lint"
        assert head["attrs"]["kernel"] == "k"
        assert "TL009" in head["attrs"]["rules"]
    finally:
        flight.reset()


def test_cli_json_findings_carry_loc_and_severity_summary(tmp_path):
    """Satellite: --json findings emit Diagnostic.loc and the text
    summary counts findings by severity."""
    mod = tmp_path / "badmod.py"
    mod.write_text(
        "import tilelang_mesh_tpu.language as T\n\n"
        "def nomax_kernel(M, N, dtype='float32'):\n"
        "    @T.prim_func\n"
        "    def nm(A: T.Tensor((M, N), dtype), O: T.Tensor((M, N), dtype)):\n"
        "        with T.Kernel(1) as bx:\n"
        "            s = T.alloc_fragment((M, N), 'float32')\n"
        "            T.copy(A, s)\n"
        "            for i, j in T.Parallel(M, N):\n"
        "                s[i, j] = T.exp(s[i, j])\n"
        "            T.copy(s, O)\n"
        "    return nm\n")
    from tilelang_mesh_tpu.tools.lint import format_report, lint_targets
    rep = lint_targets([str(mod)])
    assert rep["findings"], rep
    for f in rep["findings"]:
        assert f.get("loc", "").startswith(str(mod))
    text = format_report(rep)
    assert "by severity: warning=" in text
    assert "errors: 0" in text


def test_cache_key_separates_num_knobs():
    mm = _matmul()
    k0 = KernelCache.key_for(mm.func.script(), "cpu", None, {})
    k1 = KernelCache.key_for(mm.func.script(), "cpu", None,
                             {"tl.tpu.num_assume_abs": 1024.0})
    k2 = KernelCache.key_for(mm.func.script(), "cpu", None,
                             {"tl.tpu.num_err_threshold": 0.5})
    assert len({k0, k1, k2}) == 3


def test_assume_abs_knob_changes_warning_track(monkeypatch):
    """A tiny nominal bound proves the bare exp finite (warning gone)."""
    ek = _exp_kernel()
    assert "TL009" in {
        d.rule for d in collect_diagnostics(ek.func, with_plan=False)}
    diags = collect_diagnostics(
        ek.func, pass_cfg={"tl.tpu.num_assume_abs": 1.0},
        with_plan=False)
    assert "TL009" not in {d.rule for d in diags}


def test_strict_escalation_ignores_warnings():
    """Warnings (contract-dependent hazards) never fail a strict
    compile — only sound-track errors do."""
    from tilelang_mesh_tpu.analysis import run_semantic_checks
    f = _softmax_kernel(max_sub=False).func     # warnings only
    run_semantic_checks(f, {"tl.tpu.lint": "strict"})


def test_numerics_result_payload_uid_semantics():
    res = analyze_numerics(_mesh_proven_program().func)
    assert res.payload_uids_proven()
    res2 = analyze_numerics(_mesh_unproven_program().func)
    assert not res2.payload_uids_proven()
    assert res2.payloads and res2.payloads[0][3] is False
