"""Cross-process autotune-config reuse (reference tuner.py:281-288
persists tuned configs for reload; docs/tutorials/auto_tuning.md
documents the same workflow here).

A second PROCESS building the same tuned kernel must load the winning
config from the on-disk cache without re-sweeping — pinned by running
the same script twice in fresh interpreters against a shared cache dir.
"""

import json
import os
import pathlib
import subprocess
import sys

_SCRIPT = r"""
import json, sys
import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.autotuner import AutoTuner

compiled = []

@tilelang.jit
def factory(M, N, block_M=32):
    compiled.append(block_M)
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"),
          B: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(M, block_M)) as bx:
            s = T.alloc_shared((block_M, N), "float32")
            T.copy(A[bx * block_M, 0], s)
            T.copy(s, B[bx * block_M, 0])
    return k

res = AutoTuner(factory, [{"block_M": 32}, {"block_M": 64}],
                warmup=1, rep=2).run(128, 128)
print(json.dumps({"from_cache": res.from_cache,
                  "config": res.config,
                  "n_compiled": len(compiled)}))
"""


def test_tuned_config_reloads_in_fresh_process(tmp_path):
    env = dict(os.environ)
    env["TL_TPU_AUTOTUNE_CACHE_DIR"] = str(tmp_path / "tune")
    env["TL_TPU_CACHE_DIR"] = str(tmp_path / "kern")
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(pathlib.Path(__file__).resolve().parents[1])

    # a real file, not -c: the disk key hashes inspect.getsource(factory),
    # which needs the source to exist on disk (as user code does)
    script = tmp_path / "tune_once.py"
    script.write_text(_SCRIPT)

    def run_once():
        r = subprocess.run([sys.executable, str(script)], env=env,
                           capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, r.stderr[-2000:]
        return json.loads(r.stdout.strip().splitlines()[-1])

    first = run_once()
    assert not first["from_cache"]
    assert first["n_compiled"] == 2          # full sweep

    second = run_once()                       # FRESH interpreter
    assert second["from_cache"], "second process must reload, not re-sweep"
    assert second["config"] == first["config"]
    assert second["n_compiled"] <= 1          # at most the winner

    # the artifact is reviewable JSON carrying the full sweep
    arts = list((tmp_path / "tune").glob("*.json"))
    assert arts, "no autotune cache artifact written"
    rec = json.loads(arts[0].read_text())
    assert rec["config"] == first["config"]
    assert len(rec["all_results"]) == 2
