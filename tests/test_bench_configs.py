"""The round-5 bench config builders run end-to-end at tiny shapes on
CPU (conftest forces the cpu backend): pins the builder APIs so a
kernel-signature change cannot silently break the measurement sweep the
round depends on.

Numbers produced here are meaningless (interpret mode); only mechanics
are asserted: builders construct, candidates cross-check, run_config
emits a well-formed record with the right unit.
"""

import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

_PEAKS = {"bf16": 1e6, "f32": 5e5, "i8": 2e6, "hbm_gbs": 1e6}


def _run(name, build):
    import bench
    rec = bench.run_config(name, build, _PEAKS, rounds=1)
    assert rec["config"] == name
    assert rec["latency_ms"] > 0 and rec["baseline_ms"] > 0
    assert rec["vs_baseline"] > 0
    return rec


def test_mamba2_chunk_config():
    import bench
    rec = _run("mamba2_chunk",
               lambda: bench.cfg_mamba2_chunk(1, 512, 2, 32, 32))
    assert rec["unit"] == "TFLOPS"


def test_gdn_fwd_config():
    import bench
    rec = _run("gdn_fwd", lambda: bench.cfg_gdn_fwd(1, 2, 256, 32, 32))
    assert rec["unit"] == "TFLOPS"
    # latency picks the winner (named in the metric); FLOPs are counted
    # at the fixed nominal chunk so TFLOPS compare across sweeps
    assert "chunk=" in rec["metric"]


def test_w4a8_config():
    import bench
    rec = _run("w4a8_gemm", lambda: bench.cfg_w4a8(128, 256, 512))
    assert rec["unit"] == "TFLOPS"


def test_paged_decode_config_reports_bandwidth():
    import bench
    rec = _run("paged_decode",
               lambda: bench.cfg_paged_decode(B=1, H=4, S=512, D=64,
                                              page=128))
    assert rec["unit"] == "GB/s"
    assert "walk_ms" in rec and "gather_ms" in rec


def test_all_configs_have_builders():
    import bench
    names = [n for n, _ in bench._config_builders(False)]
    assert names[-1] == "w4a16_gemm", "riskiest config must run last"
    for expected in ("mamba2_chunk", "gdn_fwd", "w4a8_gemm",
                     "paged_decode"):
        assert expected in names


def test_mesh_allreduce_smoke_config():
    """The CPU-safe mesh comm-opt smoke: runs on the 8 forced host
    devices, reports bandwidth, and embeds the collective optimizer's
    pre/post wire-byte accounting in the record."""
    import bench
    rec = _run("mesh_allreduce_smoke",
               lambda: bench.cfg_mesh_allreduce_smoke(n=16, m=128))
    assert rec["unit"] == "GB/s"
    assert rec["comm_post_opt_wire_bytes"] <= rec["comm_pre_opt_wire_bytes"]
    assert rec["comm_hops_saved"] >= 0


def test_serve_smoke_config():
    """The CPU-safe serving smoke: every request must retire as result
    with zero leaked slabs, and the record must carry the batching-win
    ratio plus KV accounting (docs/serving.md)."""
    import bench
    rec = _run("serve_smoke", lambda: bench.cfg_serve_smoke(requests=16))
    assert rec["unit"] == "req/s"
    assert rec["requests"] == 16
    assert rec["kv_pages_allocated"] > 0
    assert rec["req_per_sec_batched"] > 0
    assert rec["batched_steps"] <= rec["sequential_steps"]


def test_mesh_serve_smoke_config():
    """The elastic mesh-serving smoke: the decode step shards over the
    2x2 host mesh, a mid-drive slice kill walks the layout ladder one
    rung down, and the record carries the layout/reshard/migration
    accounting the CI gate reads (docs/serving.md)."""
    import bench
    rec = _run("mesh_serve_smoke",
               lambda: bench.cfg_mesh_serve_smoke(requests=16))
    assert rec["unit"] == "req/s"
    assert rec["requests"] == 16
    assert rec["layout_first"] == "head_parallel:2x2"
    assert rec["reshards"] >= 1
    assert rec["layout_final"] != rec["layout_first"]
    assert rec["kv_pages_migrated"] > 0
    assert rec["layout_ladder"][-1] == "no_sharding"


def test_serve_prefill_smoke_config():
    """The full-lifecycle prefix smoke: every warm request must hit
    the prefix cache and the record must carry the warm-vs-cold
    speedup the serve-lifecycle CI gate reads (docs/serving.md
    "Full-lifecycle serving"). Tiny shapes: mechanics only — the >= 2x
    gate runs at the real shape in CI."""
    import bench
    rec = _run("serve_prefill_smoke",
               lambda: bench.cfg_serve_prefill_smoke(requests=4,
                                                     shared_pages=8))
    assert rec["unit"] == "x warm-prefix speedup"
    assert rec["requests"] == 4
    assert rec["prefix_hits"] >= 2 * 4       # two timed warm rounds
    assert rec["prefix_bytes_saved"] > 0
    assert rec["shared_prompt_tokens"] == 8 * 16


def test_cpu_safe_configs_declared():
    """Probe-once skip logic keys off CPU_SAFE_CONFIGS: both smoke
    configs must be declared CPU-safe and excluded from the default
    TPU sweep's geomean."""
    import bench
    names = [n for n, _ in bench._config_builders(True)]
    for n in bench.CPU_SAFE_CONFIGS:
        assert n in names
    assert "mesh_allreduce_smoke" in bench.CPU_SAFE_CONFIGS
    # the mesh smoke child gets forced host devices (injected, or
    # already present in the ambient flags — conftest sets them here)
    import os
    for cfg in ("mesh_allreduce_smoke", "mesh_serve_smoke"):
        env = bench._config_env(cfg, tpu_alive=True)
        flags = env.get("XLA_FLAGS", os.environ.get("XLA_FLAGS", ""))
        assert "host_platform_device_count" in flags
        assert env.get("JAX_PLATFORMS") == "cpu"
    # CPU-safe configs fall back to the host platform on a dead worker
    env = bench._config_env("gemm_smoke", tpu_alive=False)
    assert env.get("JAX_PLATFORMS") == "cpu"
