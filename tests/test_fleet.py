"""tl-fleet suite (docs/serving.md "Fleet serving & failover"):
supervised multi-engine serving with SLO-aware routing, per-engine
circuit breaking, zero-loss failover, and breaker-gated restarts.

Five layers:

1. **Routing** — weighted least-loaded dispatch over breaker-closed
   LIVE engines; the degraded engine's share drops measurably; an
   over-budget engine loses to an in-budget peer; unroutable
   submissions come back terminal (``shed failover``), never lost.
2. **Breaker semantics** — consecutive step failures eject at the
   threshold; a clean pump resets the count; ``force_open`` ejects
   within the same fleet step; an open engine never receives traffic.
3. **Failover** — an engine killed via the ``serve.engine`` fault site
   exports its in-flight work to healthy peers (warm prefix-cache
   restores where a whole-page prefix exists), writes one
   ``engine_failover`` flight dump naming the victim + every
   re-routed trace id, and a fleet-hosted ``TokenStream`` keeps
   yielding across the kill (the client never learns an engine died).
4. **Restarts** — the dead engine restarts with exponential backoff;
   a failed half-open probe re-opens the breaker with DOUBLED backoff
   and takes no live traffic while open; a passed probe re-admits at
   base backoff and the victim serves traffic again.
5. **Fairness + surfaces** — per-tenant admission share gate and
   weighted round-robin batching; ``metrics_summary`` tenant outcome
   table; ``fleet_health``/``fleet_slo`` registry views; the analyzer
   ``fleet`` summary over trace records.
"""

import itertools
import time

import pytest

from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.observability import flight as _flight
from tilelang_mesh_tpu.resilience import inject
from tilelang_mesh_tpu.serving import (Fleet, FlashDecodeWorkload,
                                       PagedKVAllocator, Router,
                                       ServingEngine, fleet_health,
                                       fleet_slo, registered_fleets,
                                       reset_prefix_cache)

H, D, PS = 2, 64, 8

_seq = itertools.count()


def make_workload(n_pages=128, batch_buckets=(4,), page_buckets=(2,)):
    return FlashDecodeWorkload(
        PagedKVAllocator(n_pages=n_pages, page_size=PS, heads=H,
                         head_dim=D),
        batch_buckets=batch_buckets, page_buckets=page_buckets)


def make_fleet(n_engines=2, **kw):
    # unique fleet names: the registry is process-global and the
    # per-engine step histograms are keyed by engine name
    kw.setdefault("name", f"flt{next(_seq)}")
    return Fleet(make_workload, n_engines=n_engines, **kw)


def counters():
    return obs.get_tracer().counters()


# -- 1. routing ---------------------------------------------------------

def test_fleet_routes_and_completes():
    fleet = make_fleet(n_engines=2)
    reqs = [fleet.submit(2 * PS, new_tokens=2, seed=i)
            for i in range(10)]
    fleet.run()
    assert all(r.outcome == "result" for r in reqs)
    # least-loaded routing alternates over equal queues: both engines
    # carried traffic, and every dispatch left a `route` mark
    assert all(s.submitted > 0 for s in fleet.slots)
    for r in reqs:
        assert "route" in [sp.name for sp in r.trace.spans]
    assert all(not v for v in fleet.leak_check().values())
    assert fleet.outcomes()["result"] == len(reqs)


def test_router_prefers_low_latency_engine_and_budget():
    """SLO-aware dispatch: the degraded engine's share drops
    measurably (the acceptance gate), and with a p99 budget set the
    over-budget engine is avoided entirely while a peer is within."""
    def feed(r, slow, fast):
        # two ticks with step observations BETWEEN them: the windowed
        # p99 is the delta between samples, so the latency must land
        # inside the window, not before the first snapshot
        t0 = time.monotonic() - 1.0
        for eng in (slow, fast):
            r.tick(eng, submitted=0, shed=0, completed=0, now=t0)
        for i in range(20):
            r.observe_step(slow, 0.080)
            r.observe_step(fast, 0.005)
        for eng in (slow, fast):
            r.tick(eng, submitted=20, shed=0, completed=20,
                   now=t0 + 0.5)

    r = Router(eject_threshold=3)
    slow, fast = f"slow{next(_seq)}", f"fast{next(_seq)}"
    feed(r, slow, fast)
    # simulate a dispatch loop: picked engine's queue deepens
    qd = {slow: 0, fast: 0}
    picks = []
    for _ in range(50):
        c = [{"name": slow, "queue_depth": qd[slow]},
             {"name": fast, "queue_depth": qd[fast]}]
        chosen = r.pick(c)
        picks.append(chosen)
        qd[chosen] += 1
    share_slow = picks.count(slow) / len(picks)
    share_fast = picks.count(fast) / len(picks)
    assert share_slow < share_fast
    assert share_slow < 0.2          # 16x p99 ratio -> ~1/16 share
    # budget preference: slow (80ms) is over a 10ms budget, fast is
    # within -> fast wins even with a much deeper queue
    rb = Router(eject_threshold=3, p99_budget_ms=10.0)
    feed(rb, slow, fast)
    assert rb.pick([{"name": slow, "queue_depth": 0},
                    {"name": fast, "queue_depth": 30}]) == fast


def test_unroutable_submission_sheds_failover():
    obs.reset()
    fleet = make_fleet(n_engines=2)
    for s in fleet.slots:
        fleet.router.force_open(s.name)
    req = fleet.submit(2 * PS, new_tokens=1, seed=1)
    assert req.is_terminal
    assert req.outcome == "shed"
    assert req.shed_reason == "failover"
    assert counters()["fleet.unrouted"] == 1


# -- 2. breaker semantics ----------------------------------------------

def test_router_breaker_consecutive_semantics():
    r = Router(eject_threshold=3)
    eng = f"brk{next(_seq)}"
    assert not r.record_failure(eng)
    assert not r.record_failure(eng)
    assert not r.is_open(eng)
    r.note_success(eng)              # clean pump: count restarts at 0
    assert not r.record_failure(eng)
    assert not r.record_failure(eng)
    assert r.record_failure(eng)     # third consecutive trips it
    assert r.is_open(eng)
    r.note_success(eng)              # success does NOT close an open
    assert r.is_open(eng)            # breaker (only a probe reset does)
    assert r.pick([{"name": eng, "queue_depth": 0}]) is None
    r.reset(eng)
    assert not r.is_open(eng)
    other = f"brk{next(_seq)}"
    r.force_open(other)
    assert r.is_open(other)
    assert r.pick([{"name": other, "queue_depth": 0},
                   {"name": eng, "queue_depth": 5}]) == eng


def test_consecutive_step_failures_eject_within_threshold(monkeypatch):
    obs.reset()
    fleet = make_fleet(n_engines=2, router=Router(eject_threshold=3),
                       restart_base_ms=10_000.0)   # keep it down
    victim = fleet.slots[0]
    eng0 = victim.engine

    def flaky_step():
        eng0._step_failures += 1     # what _on_step_failure records
        return True

    monkeypatch.setattr(eng0, "step", flaky_step)
    fleet.step()
    fleet.step()
    assert victim.state == "live"    # two failures: below threshold
    fleet.step()
    assert victim.state == "ejected"
    assert fleet.failovers == 1
    assert fleet.router.is_open(victim.name)
    assert counters()[
        "fleet.failover{engine=%s}" % victim.name] == 1
    # live traffic only reaches the healthy peer while ejected
    for i in range(4):
        fleet.submit(2 * PS, new_tokens=1, seed=i)
    assert victim.submitted == 0
    assert fleet.slots[1].submitted == 4


# -- 3. failover --------------------------------------------------------

def test_zero_loss_failover_warm_restore_and_flight_dump(
        tmp_path, monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path / "px"))
    reset_prefix_cache()
    obs.reset()
    _flight.reset()
    _flight.configure(dump_dir=tmp_path / "flight")
    try:
        fleet = make_fleet(n_engines=2)
        fleet.warmup()
        prompt = [9_000 + i for i in range(2 * PS)]   # 2 whole pages
        seed_req = fleet.submit(len(prompt), new_tokens=1,
                                prompt_tokens=list(prompt), seed=1)
        fleet.run()
        assert seed_req.outcome == "result"   # prefix now cached
        # queue shared-prompt work on BOTH engines, no pumping between
        reqs = [fleet.submit(len(prompt), new_tokens=2,
                             prompt_tokens=list(prompt), seed=2 + i)
                for i in range(6)]
        on_victim = [r for r in reqs
                     if r in fleet.slots[0].engine.requests]
        assert on_victim                      # e0 holds live work
        with inject("serve.engine", kind="unreachable", times=1):
            fleet.step()                      # e0 pumps first -> dies
        assert fleet.slots[0].state == "ejected"
        assert fleet.failovers == 1
        fleet.run()
        assert all(r.outcome == "result" for r in reqs)   # zero loss
        c = counters()
        assert c.get("fleet.failover.warm", 0) >= 1
        assert c.get("fleet.failover.lost", 0) == 0
        victim = fleet.slots[0].name
        dst = fleet.slots[1].name
        assert c["fleet.redispatched{frm=%s,to=%s}"
                 % (victim, dst)] == len(on_victim)
        for r in on_victim:
            names = [sp.name for sp in r.trace.spans]
            assert "failover" in names
        # the black box names the victim and every re-routed trace id
        dumps = sorted((tmp_path / "flight").glob("*.jsonl"))
        assert dumps
        import json
        head = json.loads(dumps[0].read_text().splitlines()[0])
        assert head["reason"] == "engine_failover"
        assert head["attrs"]["victim"] == victim
        moved = set(head["attrs"]["redispatched_trace_ids"])
        assert moved == {r.trace_id for r in on_victim}
        assert head["attrs"]["warm_restores"] >= 1
        # the victim restarts and serves traffic again
        assert fleet.await_readmission(timeout_s=10.0)
        assert fleet.leak_check() and \
            all(not v for v in fleet.leak_check().values())
    finally:
        _flight.configure(dump_dir=None)
        _flight.reset()
        reset_prefix_cache()


def test_token_stream_survives_failover():
    """Satellite bugfix pin: a fleet-hosted TokenStream keeps yielding
    after its engine is killed mid-stream — the request fails over and
    the next pump decodes it on the peer."""
    obs.reset()
    fleet = make_fleet(n_engines=2)
    fleet.warmup()
    stream = fleet.stream(2 * PS, new_tokens=6, seed=7)
    req = stream.request
    # empty queues tie-break deterministically to the first slot,
    # which is also the first engine pumped (and so the one killed)
    assert req in fleet.slots[0].engine.requests
    it = iter(stream)
    first = next(it)
    assert not req.is_terminal
    with inject("serve.engine", kind="unreachable", times=1):
        fleet.step()
    assert fleet.slots[0].state == "ejected"
    rest = list(it)                  # pumps the WHOLE fleet: decodes
    tokens = [first] + rest          # resume on the adopting peer
    assert len(tokens) == 6
    assert req.outcome == "result"
    victim, dst = fleet.slots[0].name, fleet.slots[1].name
    assert counters().get(
        "fleet.redispatched{frm=%s,to=%s}" % (victim, dst), 0) >= 1


# -- 4. restarts --------------------------------------------------------

def test_failed_probe_doubles_backoff_and_blocks_traffic():
    """Satellite: a half-open engine that fails its probe re-opens the
    breaker with DOUBLED backoff and never receives live traffic while
    open; a later passed probe re-admits at base backoff."""
    obs.reset()
    base = 5.0
    fleet = make_fleet(n_engines=2, restart_base_ms=base,
                       restart_max_ms=1000.0)
    victim = fleet.slots[0]
    with inject("serve.engine", kind="unreachable", times=1):
        fleet.step()
    assert victim.state == "ejected"
    assert victim.backoff_ms == base
    time.sleep(2 * base / 1e3)       # past restart_due: probe is due
    with inject("serve.engine", kind="unreachable", times=1):
        fleet.step()                 # the probe itself is killed
    assert victim.state == "ejected"
    assert victim.backoff_ms == 2 * base
    assert fleet.router.is_open(victim.name)
    assert counters()[
        "fleet.probe_failed{engine=%s}" % victim.name] == 1
    # while open: live traffic routes around the victim, always
    before = victim.submitted
    for i in range(4):
        r = fleet.submit(2 * PS, new_tokens=1, seed=i)
        assert not r.is_terminal or r.outcome != "shed"
    assert victim.submitted == before == 0
    assert fleet.slots[1].submitted == 4
    # clean probe after the doubled backoff: re-admitted at base
    assert fleet.await_readmission(timeout_s=10.0)
    assert victim.state == "live"
    assert victim.backoff_ms == base
    assert victim.restarts == 1
    assert counters()["fleet.readmit{engine=%s}" % victim.name] == 1
    fleet.run()                      # finish the queued work first
    # ...and the re-admitted victim serves traffic again
    r = fleet.submit(2 * PS, new_tokens=1, seed=9)
    assert r in victim.engine.requests
    fleet.run()
    assert r.outcome == "result"


def test_fleet_thread_hosting_completes_all():
    fleet = make_fleet(n_engines=2)
    fleet.warmup()
    fleet.start()
    try:
        reqs = [fleet.submit(2 * PS, new_tokens=2, seed=i)
                for i in range(8)]
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and \
                not all(r.is_terminal for r in reqs):
            time.sleep(0.01)
    finally:
        fleet.stop()
    assert all(r.outcome == "result" for r in reqs)


# -- 5. fairness + surfaces --------------------------------------------

def test_tenant_share_gate_sheds_hot_tenant(monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_MAX_QUEUE", "8")
    monkeypatch.setenv("TL_TPU_SERVE_TENANT_MAX_SHARE", "0.25")
    eng = ServingEngine(make_workload(), name=f"tnt{next(_seq)}")
    a1 = eng.submit(2 * PS, new_tokens=1, seed=1, tenant="hot")
    a2 = eng.submit(2 * PS, new_tokens=1, seed=2, tenant="hot")
    a3 = eng.submit(2 * PS, new_tokens=1, seed=3, tenant="hot")
    b1 = eng.submit(2 * PS, new_tokens=1, seed=4, tenant="cold")
    assert not a1.is_terminal and not a2.is_terminal
    assert a3.outcome == "shed"      # 2 in flight = 0.25 * 8: capped
    assert a3.shed_reason == "tenant_share"
    assert not b1.is_terminal        # the other tenant still admits
    eng.run()
    assert all(r.outcome == "result" for r in (a1, a2, b1))


def test_tenant_weighted_round_robin_batch():
    eng = ServingEngine(make_workload(batch_buckets=(4,)),
                        tenant_weights={"a": 3, "b": 1},
                        name=f"wrr{next(_seq)}")
    a = [eng.submit(2 * PS, new_tokens=1, seed=10 + i, tenant="a")
         for i in range(4)]
    b = [eng.submit(2 * PS, new_tokens=1, seed=20 + i, tenant="b")
         for i in range(4)]
    eng.step()
    # one 4-wide batch: 3 picks for "a", 1 for "b", FIFO within tenant
    done = [r for r in a + b if r.is_terminal]
    assert done == [a[0], a[1], a[2], b[0]]
    eng.run()
    assert all(r.outcome == "result" for r in a + b)


def test_tenant_outcome_table_in_metrics_summary():
    obs.reset()
    eng = ServingEngine(make_workload(), name=f"tbl{next(_seq)}")
    for i in range(3):
        eng.submit(2 * PS, new_tokens=1, seed=30 + i, tenant="acme")
    eng.submit(2 * PS, new_tokens=1, seed=40, tenant="globex")
    eng.run()
    table = obs.metrics_summary()["serving"]["tenants"]
    assert table["acme"]["result"] == 3
    assert table["globex"]["result"] == 1


def test_fleet_health_and_slo_registry():
    fleet = make_fleet(n_engines=2)
    for i in range(4):
        fleet.submit(2 * PS, new_tokens=1, seed=i)
    fleet.run()
    assert fleet.name in registered_fleets()
    fh = fleet_health()[fleet.name]
    assert set(fh["engines"]) == {s.name for s in fleet.slots}
    for eng_h in fh["engines"].values():
        assert eng_h["state"] == "live"
        assert eng_h["breaker_open"] is False
    fs = fleet_slo()[fleet.name]
    assert set(fs) <= {s.name for s in fleet.slots}


def test_analyzer_fleet_summary_and_report():
    from tilelang_mesh_tpu.tools.analyzer import (format_fleet_report,
                                                  summarize_fleet)
    records = [
        {"type": "counter", "name": "fleet.dispatch{engine=f/e0}",
         "value": 6},
        {"type": "counter", "name": "fleet.dispatch{engine=f/e1}",
         "value": 2},
        {"type": "counter", "name": "fleet.failover{engine=f/e0}",
         "value": 1},
        {"type": "counter",
         "name": "fleet.redispatched{frm=f/e0,to=f/e1}", "value": 3},
        {"type": "counter", "name": "fleet.failover.warm", "value": 2},
        {"type": "counter", "name": "fleet.probe{engine=f/e0}",
         "value": 2},
        {"type": "counter", "name": "fleet.probe_failed{engine=f/e0}",
         "value": 1},
        {"type": "counter", "name": "fleet.readmit{engine=f/e0}",
         "value": 1},
        {"type": "event", "name": "fleet.failover",
         "attrs": {"fleet": "f", "engine": "f/e0",
                   "error": "DeviceLossError: x"}},
        {"type": "event", "name": "fleet.readmit",
         "attrs": {"fleet": "f", "engine": "f/e0", "restarts": 1}},
    ]
    s = summarize_fleet(records)
    assert s["dispatch"] == {"f/e0": 6, "f/e1": 2}
    assert s["dispatch_share"]["f/e0"] == 0.75
    assert s["failovers"] == {"f/e0": 1}
    assert s["redispatched"] == {"f/e0 -> f/e1": 3}
    assert s["redispatched_total"] == 3
    assert s["warm_restores"] == 2
    assert s["probes"] == {"f/e0": 2}
    assert s["probe_failures"] == {"f/e0": 1}
    assert s["readmits"] == {"f/e0": 1}
    assert s["readmit_events"][0]["restarts"] == 1
    txt = format_fleet_report(records)
    assert "fleet routing:" in txt
    assert "f/e0: 6 dispatched (75.0% share)" in txt
    assert "re-dispatched f/e0 -> f/e1: 3" in txt
    assert format_fleet_report([]) == \
        "fleet: no fleet.* activity in this trace"
