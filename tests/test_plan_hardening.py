"""Plan-inference hardening (round-3): DMA auto-staging of HBM accesses,
modular block-index maps, and VMEM-budget backtracking.

Round-2 verdict #4: the single-pass affine matcher dropped any
non-block-affine param to HBM residency, after which compute reads raised
at codegen. These tests pin the new behavior: such programs now compile
and run through synthesized DMA staging (transform/stage_hbm.py), modular
rasterization maps plan as BlockSpecs with expression index maps, and a
plan that exceeds the VMEM budget demotes copy-only windows to DMA
instead of letting Mosaic fail downstream. Cf. reference
layout_inference.cc:306-939 (constraint search + backtracking).
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.transform.plan import plan_kernel


def _param(plan, name):
    for p in plan.params:
        if p.buffer.name == name:
            return p
    raise AssertionError(f"no param {name}")


# ---------------------------------------------------------------------------
# DMA auto-staging
# ---------------------------------------------------------------------------

def test_staged_gemm_operand_under_serial_loop():
    """A GEMM operand windowed by a serial loop var is not block-affine in
    the grid; it must be staged through DMA, not raise 'stayed in HBM'."""
    NB, M, K, N = 4, 16, 128, 128

    @T.prim_func
    def acc_gemm(A: T.Tensor((NB * M, K), "float32"),
                 B: T.Tensor((K, N), "float32"),
                 O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            Bs = T.alloc_shared((K, N), "float32")
            Cl = T.alloc_fragment((M, N), "float32")
            T.copy(B, Bs)
            T.fill(Cl, 0.0)
            for k in T.serial(NB):
                T.gemm(A[k * M:(k + 1) * M, 0:K], Bs, Cl)
            T.copy(Cl, O)

    plan = plan_kernel(acc_gemm.func)
    assert _param(plan, "A").mode == "any"
    assert any(b.name.startswith("stage_A") for b in plan.scratch), \
        [b.name for b in plan.scratch]

    k = tilelang.compile(acc_gemm)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((NB * M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    out = np.empty((M, N), np.float32)
    k(a, b, out)
    ref = sum(a[i * M:(i + 1) * M] @ b for i in range(NB))
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


def test_staged_elementwise_load_in_parallel_nest():
    """Elementwise reads of an HBM-resident param inside T.Parallel are
    staged as one DMA'd window per nest."""
    NB, M, N = 3, 8, 128

    @T.prim_func
    def acc_rows(A: T.Tensor((NB * M, N), "float32"),
                 O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_fragment((M, N), "float32")
            T.fill(s, 0.0)
            for k in T.serial(NB):
                for i, j in T.Parallel(M, N):
                    s[i, j] = s[i, j] + A[k * M + i, j] * 2.0
            T.copy(s, O)

    plan = plan_kernel(acc_rows.func)
    assert _param(plan, "A").mode == "any"
    assert any(b.name.startswith("stage_A") for b in plan.scratch)

    k = tilelang.compile(acc_rows)
    a = np.random.default_rng(1).standard_normal(
        (NB * M, N)).astype(np.float32)
    out = np.empty((M, N), np.float32)
    k(a, out)
    ref = 2.0 * sum(a[i * M:(i + 1) * M] for i in range(NB))
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_staged_elementwise_store_in_parallel_nest():
    """Elementwise writes to an HBM-resident param are staged in VMEM and
    flushed by one DMA after the nest."""
    NB, M, N = 3, 8, 128

    @T.prim_func
    def scatter_rows(A: T.Tensor((M, N), "float32"),
                     O: T.Tensor((NB * M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for k in T.serial(NB):
                for i, j in T.Parallel(M, N):
                    O[k * M + i, j] = s[i, j] + T.cast(k, "float32")
            T.copy(s, O[0, 0])  # keep O also copy-written: conflicting
            # patterns force residency 'any' even without the serial loop

    plan = plan_kernel(scatter_rows.func)
    assert _param(plan, "O").mode == "any"

    k = tilelang.compile(scatter_rows)
    a = np.random.default_rng(2).standard_normal((M, N)).astype(np.float32)
    out = np.empty((NB * M, N), np.float32)
    k(a, out)
    ref = np.concatenate([a + float(i) for i in range(NB)])
    ref[:M] = a  # final T.copy overwrites block 0
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_hbm_error_only_for_genuinely_unlowerable():
    """Strided (coeff != 1) par access cannot be staged as a contiguous
    window; it must still fail with the clear HBM message."""
    M, N = 8, 128

    @T.prim_func
    def strided(A: T.Tensor((2 * M, N), "float32"),
                O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_fragment((M, N), "float32")
            for k in T.serial(2):
                for i, j in T.Parallel(M, N):
                    s[i, j] = A[i * 2, j]
            T.copy(s, O)

    with pytest.raises(Exception, match="HBM|stage|block"):
        tilelang.compile(strided)


# ---------------------------------------------------------------------------
# modular index maps
# ---------------------------------------------------------------------------

def test_modular_block_index_map():
    """A[(bx % 2) * BM] plans as a BlockSpec with an expression index map
    (not HBM residency)."""
    BM, N, G = 8, 128, 4

    @T.prim_func
    def wrap(A: T.Tensor((2 * BM, N), "float32"),
             O: T.Tensor((G * BM, N), "float32")):
        with T.Kernel(G) as bx:
            s = T.alloc_shared((BM, N), "float32")
            T.copy(A[(bx % 2) * BM, 0], s)
            for i, j in T.Parallel(BM, N):
                s[i, j] = s[i, j] + 1.0
            T.copy(s, O[bx * BM, 0])

    plan = plan_kernel(wrap.func)
    pa = _param(plan, "A")
    assert pa.mode == "block", plan.describe()
    assert any(d.expr is not None for d in pa.block_dims)
    assert "%" in plan.describe()

    k = tilelang.compile(wrap)
    a = np.random.default_rng(3).standard_normal(
        (2 * BM, N)).astype(np.float32)
    out = np.empty((G * BM, N), np.float32)
    k(a, out)
    ref = np.concatenate([a[(g % 2) * BM:((g % 2) + 1) * BM] + 1.0
                          for g in range(G)])
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_swizzled_block_index_map():
    """Rasterization-style map mixing // and %: block index
    (bx // 2) + (bx % 2) * 2 over a 4-block axis."""
    BM, N = 8, 128

    @T.prim_func
    def swz(A: T.Tensor((4 * BM, N), "float32"),
            O: T.Tensor((4 * BM, N), "float32")):
        with T.Kernel(4) as bx:
            s = T.alloc_shared((BM, N), "float32")
            T.copy(A[((bx // 2) + (bx % 2) * 2) * BM, 0], s)
            T.copy(s, O[bx * BM, 0])

    plan = plan_kernel(swz.func)
    assert _param(plan, "A").mode == "block", plan.describe()

    k = tilelang.compile(swz)
    a = np.random.default_rng(4).standard_normal(
        (4 * BM, N)).astype(np.float32)
    out = np.empty_like(a)
    k(a, out)
    perm = [(g // 2) + (g % 2) * 2 for g in range(4)]
    ref = np.concatenate([a[p * BM:(p + 1) * BM] for p in perm])
    np.testing.assert_allclose(out, ref, rtol=1e-6)


# ---------------------------------------------------------------------------
# VMEM-budget backtracking
# ---------------------------------------------------------------------------

def _two_input_kernel():
    M, N = 64, 256

    @T.prim_func
    def add2(A: T.Tensor((M, N), "float32"),
             B: T.Tensor((M, N), "float32"),
             O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            sa = T.alloc_shared((M, N), "float32")
            sb = T.alloc_shared((M, N), "float32")
            T.copy(A, sa)
            T.copy(B, sb)
            for i, j in T.Parallel(M, N):
                sa[i, j] = sa[i, j] + sb[i, j]
            T.copy(sa, O)
    return add2, M, N


def test_vmem_backoff_demotes_largest_copy_only_param():
    add2, M, N = _two_input_kernel()
    # generous budget: everything rides BlockSpecs
    plan = plan_kernel(add2.func)
    assert _param(plan, "A").mode == "block"
    assert _param(plan, "B").mode == "block"
    # starve the budget: one 64 KiB copy-only window is demoted to
    # DMA-fed HBM residency; the rest keep their BlockSpecs
    add2b, _, _ = _two_input_kernel()
    plan2 = plan_kernel(add2b.func,
                        {"tl.tpu.vmem_budget_bytes": 200 * 1024})
    modes = {p.buffer.name: p.mode for p in plan2.params}
    assert modes["A"] == "any", plan2.describe()
    assert modes["B"] == "block" and modes["O"] == "block"

    # and the demoted plan still runs correctly
    k = tilelang.compile(add2b,
                         pass_configs={"tl.tpu.vmem_budget_bytes": 200 * 1024})
    rng = np.random.default_rng(5)
    a = rng.standard_normal((M, N)).astype(np.float32)
    b = rng.standard_normal((M, N)).astype(np.float32)
    out = np.empty((M, N), np.float32)
    k(a, b, out)
    np.testing.assert_allclose(out, a + b, rtol=1e-6)


def test_vmem_backoff_keeps_compute_read_params():
    """A param read directly by T.gemm is not copy-only; the backoff must
    not demote it (staging notwithstanding, block residency is required
    for correctness of the accumulator aliasing) — it stays block even
    under a starved budget."""
    M = 128

    @T.prim_func
    def mm(A: T.Tensor((M, M), "float32"), B: T.Tensor((M, M), "float32"),
           O: T.Tensor((M, M), "float32")):
        with T.Kernel(1) as bx:
            Cl = T.alloc_fragment((M, M), "float32")
            T.gemm(A, B, Cl, clear_accum=True)
            T.copy(Cl, O)

    plan = plan_kernel(mm.func, {"tl.tpu.vmem_budget_bytes": 4096})
    assert _param(plan, "A").mode == "block"
    assert _param(plan, "B").mode == "block"


# ---------------------------------------------------------------------------
# round-3 review regressions
# ---------------------------------------------------------------------------

def test_guarded_store_to_hbm_param_is_rejected_not_corrupted():
    """A store to an HBM-resident param under a T.If INSIDE the Parallel
    nest must not be staged: the unconditional post-nest flush would
    clobber destination blocks whose guard was false. It stays a loud
    compile error."""
    M, N = 8, 128

    @T.prim_func
    def guarded(A: T.Tensor((M, N), "float32"),
                O: T.Tensor((2 * M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for k in T.serial(2):
                for i, j in T.Parallel(M, N):
                    with T.If(k == 0):
                        O[k * M + i, j] = s[i, j]
            T.copy(s, O[0, 0])

    with pytest.raises(Exception, match="HBM|stage"):
        tilelang.compile(guarded)


def test_nonconsecutive_modular_output_revisit_gets_tpu_note():
    """O[(bx % 2) * BM] over 4 grid steps revisits block 0 at steps 0 and
    2 — non-consecutive; the plan must carry a real-TPU error note
    (interpret mode masks the corruption)."""
    BM, N = 8, 128

    @T.prim_func
    def wrapout(A: T.Tensor((4 * BM, N), "float32"),
                O: T.Tensor((2 * BM, N), "float32")):
        with T.Kernel(4) as bx:
            s = T.alloc_shared((BM, N), "float32")
            T.copy(A[bx * BM, 0], s)
            T.copy(s, O[(bx % 2) * BM, 0])

    plan = plan_kernel(wrapout.func)
    po = _param(plan, "O")
    assert po.mode == "block"
    assert po.tpu_note is not None and "consecutive" in po.tpu_note


def test_consecutive_modular_output_revisit_is_legal():
    """O[(bx // 2) * BM] revisits each block on consecutive steps
    (0,0,1,1): legal — no tpu_note, axis demoted to arbitrary, revisit
    recorded."""
    BM, N = 8, 128

    @T.prim_func
    def gather2(A: T.Tensor((4 * BM, N), "float32"),
                O: T.Tensor((2 * BM, N), "float32")):
        with T.Kernel(4) as bx:
            s = T.alloc_shared((BM, N), "float32")
            T.copy(A[bx * BM, 0], s)
            for i, j in T.Parallel(BM, N):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, O[(bx // 2) * BM, 0])

    plan = plan_kernel(gather2.func)
    po = _param(plan, "O")
    assert po.mode == "block"
    assert po.tpu_note is None, po.tpu_note
    assert po.revisit_axes == [0]
    assert plan.grid[0].kind == "arbitrary"

    k = tilelang.compile(gather2)
    a = np.random.default_rng(6).standard_normal(
        (4 * BM, N)).astype(np.float32)
    out = np.empty((2 * BM, N), np.float32)
    k(a, out)
    # last writer per output block wins: bx=1 -> block 0, bx=3 -> block 1
    ref = np.concatenate([a[BM:2 * BM] * 2.0, a[3 * BM:] * 2.0])
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_cross_axis_consecutive_revisit_demotes_axes():
    """((bx + by) % 2) revisits a block across an axis boundary on
    consecutive steps: every stepping axis must lose 'parallel' semantics
    even though stepping either axis alone always changes the block."""
    BM, N = 8, 128

    @T.prim_func
    def diag(A: T.Tensor((4 * BM, N), "float32"),
             O: T.Tensor((2 * BM, N), "float32")):
        with T.Kernel(2, 2) as (bx, by):
            s = T.alloc_shared((BM, N), "float32")
            T.copy(A[(by * 2 + bx) * BM, 0], s)
            T.copy(s, O[((bx + by) % 2) * BM, 0])

    plan = plan_kernel(diag.func)
    po = _param(plan, "O")
    assert po.mode == "block", plan.describe()
    # block sequence over the (by, bx) grid is 0,1,1,0: block 1 is
    # revisited consecutively across a row step -> both axes arbitrary
    assert all(a.kind == "arbitrary" for a in plan.grid), plan.describe()
    assert po.revisit_axes == [0, 1]


def test_staged_scalar_index_load_in_copy_base():
    """A copy whose window base loads from an HBM-resident index table:
    the table element is staged through a (1,)-element DMA and the copy
    base rewritten (previously a tuple-compare TypeError)."""
    M, N, NB = 8, 128, 4
    TBL = 8192  # 32 KiB of int32: too big for SMEM promotion

    @T.prim_func
    def gather(A: T.Tensor((NB * M, N), "float32"),
               IT: T.Tensor((TBL,), "int32"),
               O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            for k in T.serial(1):
                T.copy(A[IT[k] * M, 0], s)
            T.copy(s, O)

    plan = plan_kernel(gather.func)
    assert _param(plan, "IT").mode == "any"
    k = tilelang.compile(gather)
    rng = np.random.default_rng(7)
    a = rng.standard_normal((NB * M, N)).astype(np.float32)
    it = np.zeros((TBL,), np.int32)
    it[0] = 2
    out = np.empty((M, N), np.float32)
    k(a, it, out)
    np.testing.assert_allclose(out, a[2 * M:3 * M], rtol=1e-6)


def test_staging_dedups_identical_windows_across_statements():
    """Two adjacent GEMMs reading the same HBM window share ONE staged
    buffer and one DMA (per-statement caches doubled HBM traffic)."""
    M, K, N = 16, 128, 128

    @T.prim_func
    def twice(A: T.Tensor((2 * M, K), "float32"),
              B: T.Tensor((K, N), "float32"),
              O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            Bs = T.alloc_shared((K, N), "float32")
            C1 = T.alloc_fragment((M, N), "float32")
            C2 = T.alloc_fragment((M, N), "float32")
            T.copy(B, Bs)
            for k in T.serial(2):
                T.gemm(A[k * M:(k + 1) * M, 0:K], Bs, C1,
                       clear_accum=True)
                T.gemm(A[k * M:(k + 1) * M, 0:K], Bs, C2,
                       clear_accum=True)
            for i, j in T.Parallel(M, N):
                C1[i, j] = C1[i, j] + C2[i, j]
            T.copy(C1, O)

    plan = plan_kernel(twice.func)
    stages = [b for b in plan.scratch if b.name.startswith("stage_A")]
    assert len(stages) == 1, [b.name for b in plan.scratch]

    k = tilelang.compile(twice)
    rng = np.random.default_rng(8)
    a = rng.standard_normal((2 * M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    out = np.empty((M, N), np.float32)
    k(a, b, out)
    np.testing.assert_allclose(out, 2 * (a[M:] @ b), rtol=2e-2, atol=2e-2)


def test_staged_window_cache_invalidated_by_parallel_store():
    """A T.Parallel store to the any-mode param between two reads of the
    same window must invalidate the staged-read cache (review repro: the
    second gemm consumed the stale pre-write DMA)."""
    M, K, N = 16, 128, 128

    @T.prim_func
    def rmw(A: T.Tensor((2 * M, K), "float32"),
            B: T.Tensor((K, N), "float32"),
            O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            Bs = T.alloc_shared((K, N), "float32")
            C1 = T.alloc_fragment((M, N), "float32")
            C2 = T.alloc_fragment((M, N), "float32")
            T.copy(B, Bs)
            for k in T.serial(2):
                T.gemm(A[k * M:(k + 1) * M, 0:K], Bs, C1,
                       clear_accum=True)
                for i, j in T.Parallel(M, K):
                    A[k * M + i, j] = 0.0
                T.gemm(A[k * M:(k + 1) * M, 0:K], Bs, C2,
                       clear_accum=True)
            for i, j in T.Parallel(M, N):
                C1[i, j] = C1[i, j] + C2[i, j]
            T.copy(C1, O)

    k = tilelang.compile(rmw)
    rng = np.random.default_rng(9)
    a = rng.standard_normal((2 * M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    out = np.empty((M, N), np.float32)
    k(a.copy(), b, out)
    # second gemm must see the zeroed window: out == A_1 @ B, not 2*A_1@B
    np.testing.assert_allclose(out, a[M:] @ b, rtol=2e-2, atol=2e-2)


def test_num_stages_one_opts_out_of_grid_pipelining():
    """num_stages=1 is a real knob now: the Pipelined loop stays
    in-kernel (serial + DMA through the user's single VMEM tiles), so
    streams are single-buffered; >=2 grid-maps to Mosaic's
    double-buffered pipeline. Numerics identical."""
    def mk(stages):
        @T.prim_func
        def mm(A: T.Tensor((64, 256), "float32"),
               B: T.Tensor((256, 128), "float32"),
               O: T.Tensor((64, 128), "float32")):
            with T.Kernel(1) as bx:
                As = T.alloc_shared((64, 64), "float32")
                Bs = T.alloc_shared((64, 128), "float32")
                Cl = T.alloc_fragment((64, 128), "float32")
                T.fill(Cl, 0.0)
                for ko in T.Pipelined(4, num_stages=stages):
                    T.copy(A[0, ko * 64], As)
                    T.copy(B[ko * 64, 0], Bs)
                    T.gemm(As, Bs, Cl)
                T.copy(Cl, O)
        return mm

    p2 = plan_kernel(mk(2).func)
    p1 = plan_kernel(mk(1).func)
    assert p2.pipeline_axis is not None
    assert p1.pipeline_axis is None
    assert _param(p1, "A").mode == "any"   # DMA-staged, single-buffered

    rng = np.random.default_rng(10)
    a = rng.standard_normal((64, 256)).astype(np.float32)
    b = rng.standard_normal((256, 128)).astype(np.float32)
    want = a @ b
    for st in (1, 2):
        k = tilelang.compile(mk(st))
        o = np.empty((64, 128), np.float32)
        k(a, b, o)
        np.testing.assert_allclose(o, want, rtol=2e-2, atol=2e-2)
