"""BitNet b1.58 int8 x int2 kernels (reference examples/bitnet-1.58b
kernel_benchmark correctness checks)."""

import numpy as np
import pytest

from tilelang_mesh_tpu.ops.bitnet import (bitnet_gemm_kernel, bitnet_linear,
                                          bitnet_linear_reference,
                                          pack_ternary, unpack_ternary)


def test_pack_roundtrip():
    rng = np.random.default_rng(0)
    w = rng.integers(-1, 2, (64, 32)).astype(np.int8)
    np.testing.assert_array_equal(unpack_ternary(pack_ternary(w)), w)


def test_pack_rejects_non_ternary():
    with pytest.raises(ValueError, match="ternary"):
        pack_ternary(np.full((4, 4), 2, np.int8))


@pytest.mark.parametrize("M,N,K", [(1, 128, 256), (64, 128, 256),
                                   (128, 256, 512)])
def test_bitnet_gemm_exact(M, N, K):
    rng = np.random.default_rng(1)
    w = rng.integers(-1, 2, (K, N)).astype(np.int8)
    a = rng.integers(-128, 128, (M, K)).astype(np.int8)
    c = np.asarray(bitnet_gemm_kernel(M, N, K)(a, pack_ternary(w)))
    np.testing.assert_array_equal(
        c, a.astype(np.int32) @ w.astype(np.int32))


def test_bitnet_linear_matches_emulation():
    rng = np.random.default_rng(2)
    K, N = 256, 128
    w = rng.integers(-1, 2, (K, N)).astype(np.int8)
    x = rng.standard_normal((2, 8, K)).astype(np.float32)
    y = np.asarray(bitnet_linear(x, pack_ternary(w), 3.0))
    ref = np.asarray(bitnet_linear_reference(x, w, 3.0))
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)
