"""Golden-IR structural tests of the DSL frontend (SURVEY §4 style 1:
trace a kernel, compare the printed script)."""

import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def _quickstart(M=256, N=256, K=256, bm=128, bn=128, bk=128):
    @T.prim_func
    def matmul_relu_kernel(
            A: T.Tensor((M, K), "float32"),
            B: T.Tensor((K, N), "float32"),
            C: T.Tensor((M, N), "float32"),
    ):
        with T.Kernel(T.ceildiv(N, bn), T.ceildiv(M, bm),
                      threads=128) as (bx, by):
            A_shared = T.alloc_shared((bm, bk), "float32")
            B_shared = T.alloc_shared((bk, bn), "float32")
            C_local = T.alloc_fragment((bm, bn), "float32")
            T.clear(C_local)
            for ko in T.Pipelined(T.ceildiv(K, bk), num_stages=3):
                T.copy(A[by * bm, ko * bk], A_shared)
                T.copy(B[ko * bk, bx * bn], B_shared)
                T.gemm(A_shared, B_shared, C_local)
            for i, j in T.Parallel(bm, bn):
                C_local[i, j] = T.max(C_local[i, j], 0)
            T.copy(C_local, C[by * bm, bx * bn])
    return matmul_relu_kernel


GOLDEN_QUICKSTART = """\
def matmul_relu_kernel(A: Tensor((256, 256), float32), B: Tensor((256, 256), float32), C: Tensor((256, 256), float32)):
  with Kernel((2, 2), threads=128) as (bx, by,):
    shared = alloc((128, 128), float32, scope=shared)
    shared_1 = alloc((128, 128), float32, scope=shared)
    frag = alloc((128, 128), float32, scope=fragment)
    fill(frag[(0, 0); (128, 128)], 0)
    for (ko,) in pipelined((2), num_stages=3):
      copy(A[(by * 128, ko * 128); (128, 128)] -> shared[(0, 0); (128, 128)])
      copy(B[(ko * 128, bx * 128); (128, 128)] -> shared_1[(0, 0); (128, 128)])
      gemm(shared[(0, 0); (128, 128)], shared_1[(0, 0); (128, 128)] -> frag[(0, 0); (128, 128)])
    for (i, j,) in parallel((128, 128)):
      frag[i, j] = max(frag[i, j], 0)
    copy(frag[(0, 0); (128, 128)] -> C[(by * 128, bx * 128); (128, 128)])
"""


def test_quickstart_golden_script():
    assert _quickstart().script() == GOLDEN_QUICKSTART


def test_trace_is_deterministic():
    assert _quickstart().script() == _quickstart().script()


GOLDEN_PLAN = """\
plan(matmul_relu_kernel):
  grid = [by:2:parallel, bx:2:parallel, ko:2:arbitrary]
  in    A: block[128@(by), 128@(ko)] alias=shared
  in    B: block[128@(ko), 128@(bx)] alias=shared_1
  out   C: block[128@(by), 128@(bx)]
  scratch frag: (128, 128) float32 [fragment] @0
  vmem arena: 65536 bytes (liveness-packed)
  phases: init=1 main=3 epi=2
"""


# bk=64 makes A's minor block dim 64 on a 256-wide axis — illegal under
# Mosaic's (8, 128) min-tile rule, and its ko-dependent lane offset can't
# be widened away (Mosaic requires provably 128-aligned lane starts, DMA
# included) — so the plan keeps the block mapping (interpret mode
# executes it) and the generated build() raises a clear error on the
# real-TPU path. B's 64 sits on the second-minor axis (divisible by 8)
# and is legal as-is.
GOLDEN_PLAN_WIDENED = """\
plan(matmul_relu_kernel):
  grid = [by:2:parallel, bx:2:parallel, ko:4:arbitrary]
  in    A: block[128@(by), 64@(ko)] alias=shared
  in    B: block[64@(ko), 128@(bx)] alias=shared_1
  out   C: block[128@(by), 128@(bx)]
  scratch frag: (128, 128) float32 [fragment] @0
  vmem arena: 65536 bytes (liveness-packed)
  phases: init=1 main=3 epi=2
"""


def test_min_tile_illegal_lane_block_raises_on_tpu_path():
    """The same bk=64 kernel must raise the clear Mosaic-legality error
    when built for a real TPU (interpret=False)."""
    art = tilelang.lower(_quickstart(bk=64), target="cpu")
    ns = {}
    exec(compile(art.kernel_source, "<test>", "exec"), ns)
    with pytest.raises(NotImplementedError, match="128-aligned"):
        ns["build"](interpret=False)


def test_min_tile_widening_plan_golden():
    art = tilelang.lower(_quickstart(bk=64), target="cpu")
    assert art.plan_desc == GOLDEN_PLAN_WIDENED


def test_min_tile_widened_kernel_executes():
    import numpy as np
    k = tilelang.compile(_quickstart(bk=64))
    rng = np.random.default_rng(0)
    a = rng.standard_normal((256, 256), dtype=np.float32)
    b = rng.standard_normal((256, 256), dtype=np.float32)
    c = np.empty((256, 256), np.float32)
    k(a, b, c)
    np.testing.assert_allclose(c, np.maximum(a @ b, 0), rtol=2e-2,
                               atol=2e-1)


def test_quickstart_plan_golden():
    art = tilelang.lower(_quickstart(), target="cpu")
    assert art.plan_desc == GOLDEN_PLAN


def test_gemm_shape_validation():
    with pytest.raises(ValueError, match="K mismatch"):
        @T.prim_func
        def bad(A: T.Tensor((128, 64), "float32"),
                B: T.Tensor((32, 128), "float32"),
                C: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                a = T.alloc_shared((128, 64), "float32")
                b = T.alloc_shared((32, 128), "float32")
                c = T.alloc_fragment((128, 128), "float32")
                T.gemm(a, b, c)


def test_copy_extent_validation():
    with pytest.raises(ValueError, match="extent mismatch"):
        @T.prim_func
        def bad(A: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((64, 64), "float32")
                T.copy(A[0:128, 0:128], s)


def test_kernel_frame_requires_static_grid():
    with pytest.raises(ValueError, match="static"):
        @T.prim_func
        def bad(A: T.Tensor((128, 128), "float32"), n: T.dyn("int32")):
            with T.Kernel(n) as bx:
                pass


def test_alloc_outside_prim_func_raises():
    with pytest.raises(RuntimeError):
        T.alloc_shared((8, 8), "float32")


def test_gpu_only_constructs_raise():
    @T.prim_func
    def k(A: T.Tensor((8, 128), "float32")):
        with T.Kernel(1) as bx:
            with pytest.raises(NotImplementedError):
                T.alloc_tmem((8, 128), "float32")
            with pytest.raises(NotImplementedError):
                T.thread_binding()


def test_non_consecutive_output_revisit_flagged():
    """An output whose block is revisited across a non-innermost grid
    axis (the pre-round-3 flash-decoding shape) must carry a tpu_note so
    the real-TPU build fails loudly instead of corrupting the output."""
    NS, H, B, D = 2, 4, 2, 128

    @T.prim_func
    def bad(X: T.Tensor((B, NS, H, D), "float32"),
            O: T.Tensor((B, NS, H, D), "float32")):
        # T.Kernel(NS, H, B) -> grid (bz, by, bs): bs innermost, but O's
        # index omits by (middle axis) once the head dim is widened
        with T.Kernel(NS, H, B) as (bs, by, bz):
            f = T.alloc_fragment((1, D), "float32")
            T.copy(X[bz, bs, by, 0], f)
            for i, j in T.Parallel(1, D):
                f[i, j] = f[i, j] + 1.0
            T.copy(f, O[bz, bs, by, 0])

    art = tilelang.lower(bad, target="cpu")
    ns = {}
    exec(compile(art.kernel_source, "<test>", "exec"), ns)
    with pytest.raises(NotImplementedError, match="consecutive"):
        ns["build"](interpret=False)
    # interpret mode still executes (and is correct there)
    import numpy as np
    k = tilelang.compile(bad)
    x = np.random.default_rng(0).standard_normal(
        (B, NS, H, D)).astype(np.float32)
    out = np.empty_like(x)
    k(x, out)
    np.testing.assert_allclose(out, x + 1.0, rtol=1e-6)


def test_innermost_output_revisit_not_flagged():
    """The corrected axis order (revisited axis innermost) must build
    without a tpu_note."""
    NS, H, B, D = 2, 4, 2, 128

    @T.prim_func
    def good(X: T.Tensor((B, NS, H, D), "float32"),
             O: T.Tensor((B, NS, H, D), "float32")):
        with T.Kernel(H, NS, B) as (by, bs, bz):
            f = T.alloc_fragment((1, D), "float32")
            T.copy(X[bz, bs, by, 0], f)
            for i, j in T.Parallel(1, D):
                f[i, j] = f[i, j] + 1.0
            T.copy(f, O[bz, bs, by, 0])

    art = tilelang.lower(good, target="cpu")
    assert "NotImplementedError" not in art.kernel_source


def test_trailing_unit_axis_revisit_not_flagged():
    """An extent-1 grid axis in an innermost position contributes one
    step and cannot interleave revisits: the consecutiveness check must
    compare against the suffix of stepping (extent>1) axes only."""
    NS, H, B, D = 2, 4, 2, 128

    @T.prim_func
    def ok(X: T.Tensor((B, NS, H, D), "float32"),
           O: T.Tensor((B, NS, H, D), "float32")):
        # unit axis bx is innermost; by (revisited) is next — still
        # consecutive because bx never steps
        with T.Kernel(1, H, NS, B) as (bx, by, bs, bz):
            f = T.alloc_fragment((1, D), "float32")
            T.copy(X[bz, bs, by, 0], f)
            for i, j in T.Parallel(1, D):
                f[i, j] = f[i, j] + 1.0
            T.copy(f, O[bz, bs, by, 0])

    art = tilelang.lower(ok, target="cpu")
    assert "NotImplementedError" not in art.kernel_source
