"""Full plan-text goldens for canonical kernels.

The reference pins each transform pass with golden lowered-IR comparisons
(testing/python/transform/, 18 files of mod.script() string equality).
The analog here: `plan_kernel(...).describe()` is the deterministic
pass-pipeline output — these goldens lock grid mapping, residency
decisions, aliasing, phase splits, and VMEM packing for one kernel per
planner feature. A planning change now shows up as a readable text diff,
not an unexplained perf or numerics shift.
"""

import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.transform.plan import plan_kernel


def test_pipelined_gemm_plan_golden():
    bm, bn, bk = 128, 128, 64
    M = N = K = 256

    @T.prim_func
    def gemm(A: T.Tensor((M, K), "bfloat16"),
             B: T.Tensor((K, N), "bfloat16"),
             C: T.Tensor((M, N), "bfloat16")):
        with T.Kernel(T.ceildiv(N, bn), T.ceildiv(M, bm)) as (bx, by):
            A_s = T.alloc_shared((bm, bk), "bfloat16")
            B_s = T.alloc_shared((bk, bn), "bfloat16")
            C_l = T.alloc_fragment((bm, bn), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, bk)):
                T.copy(A[by * bm, ko * bk], A_s)
                T.copy(B[ko * bk, bx * bn], B_s)
                T.gemm(A_s, B_s, C_l)
            T.copy(C_l, C[by * bm, bx * bn])

    assert plan_kernel(gemm.func).describe() == """\
plan(gemm):
  grid = [by:2:parallel, bx:2:parallel, ko:4:arbitrary]
  in    A: block[128@(by), 64@(ko)] alias=shared
  in    B: block[64@(ko), 128@(bx)] alias=shared_1
  out   C: block[128@(by), 128@(bx)]
  scratch frag: (128, 128) float32 [fragment] @0
  vmem arena: 65536 bytes (liveness-packed)
  phases: init=1 main=3 epi=1
"""


def test_softmax_stats_plan_golden():
    """Online-softmax shape: 1-D stats fragments, no pipeline axis."""
    M, N = 8, 128

    @T.prim_func
    def softmax(A: T.Tensor((M, N), "float32"),
                O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_fragment((M, N), "float32")
            mx = T.alloc_fragment((M,), "float32")
            den = T.alloc_fragment((M,), "float32")
            T.copy(A, s)
            T.reduce_max(s, mx, dim=1)
            for i, j in T.Parallel(M, N):
                s[i, j] = T.exp(s[i, j] - mx[i])
            T.reduce_sum(s, den, dim=1)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] / den[i]
            T.copy(s, O)

    assert plan_kernel(softmax.func).describe() == """\
plan(softmax):
  grid = [bx:1:parallel]
  in    A: block[8@(0), 128@(0)]
  out   O: block[8@(0), 128@(0)]
  scratch frag: (8, 128) float32 [fragment] @0
  scratch frag_1: (8,) float32 [fragment] @4096
  scratch frag_2: (8,) float32 [fragment] @4096
  vmem arena: 8192 bytes (liveness-packed)
  phases: init=0 main=6 epi=0
"""


def test_smem_promotion_plan_golden():
    """A small scalar-read index table lives whole in SMEM."""
    NB, M, N = 4, 8, 128

    @T.prim_func
    def gather(A: T.Tensor((NB * M, N), "float32"),
               TBL: T.Tensor((NB,), "int32"),
               O: T.Tensor((NB * M, N), "float32")):
        with T.Kernel(NB) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A[TBL[bx] * M, 0], s)
            T.copy(s, O[bx * M, 0])

    assert plan_kernel(gather.func).describe() == """\
plan(gather):
  grid = [bx:4:parallel]
  in    A: any(hbm)
  in    TBL: smem(full)
  out   O: block[8@(bx), 128@(0)]
  scratch shared: (8, 128) float32 [shared] @0
  vmem arena: 4096 bytes (liveness-packed)
  phases: init=0 main=2 epi=0
"""


def test_staged_serial_window_plan_golden():
    """Serial-loop GEMM windows: HBM residency + synthesized staging."""
    NB, M, K, N = 2, 16, 128, 128

    @T.prim_func
    def accg(A: T.Tensor((NB * M, K), "float32"),
             B: T.Tensor((K, N), "float32"),
             O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            Bs = T.alloc_shared((K, N), "float32")
            Cl = T.alloc_fragment((M, N), "float32")
            T.copy(B, Bs)
            T.fill(Cl, 0.0)
            for k in T.serial(NB):
                T.gemm(A[k * M:(k + 1) * M, 0:K], Bs, Cl)
            T.copy(Cl, O)

    assert plan_kernel(accg.func).describe() == """\
plan(accg):
  grid = [bx:1:parallel]
  in    A: any(hbm)
  in    B: block[128@(0), 128@(0)] alias=shared
  out   O: block[16@(0), 128@(0)]
  scratch frag: (16, 128) float32 [fragment] @0
  scratch stage_A_1: (16, 128) float32 [shared] @8192
  vmem arena: 16384 bytes (liveness-packed)
  phases: init=0 main=4 epi=0
"""


def test_modular_map_plan_golden():
    """Non-affine (bx % 2) block-index expression in the plan text."""
    BM, N, G = 8, 128, 4

    @T.prim_func
    def wrap(A: T.Tensor((2 * BM, N), "float32"),
             O: T.Tensor((G * BM, N), "float32")):
        with T.Kernel(G) as bx:
            s = T.alloc_shared((BM, N), "float32")
            T.copy(A[(bx % 2) * BM, 0], s)
            T.copy(s, O[bx * BM, 0])

    assert plan_kernel(wrap.func).describe() == """\
plan(wrap):
  grid = [bx:4:parallel]
  in    A: block[8@(bx % 2), 128@(0)] alias=shared
  out   O: block[8@(bx), 128@(0)]
  phases: init=0 main=2 epi=0
"""
