"""Full-lifecycle serving suite (docs/serving.md "Full-lifecycle
serving"): chunked prefill, the content-addressed prefix KV cache,
streaming, cancellation, and temperature/top-p sampling.

Five layers:

1. **Sampling** — greedy/temperature/top-p semantics, determinism
   under a seeded rng, numerical safety of the softmax.
2. **Chunked prefill** — ingest fills exactly one chunk, the engine
   interleaves the remaining chunk units with decode steps (a short
   request completes while a long prompt is still mid-prefill), and
   deadline feasibility folds the chunk count in.
3. **Prefix cache** — restored-prefix decode is BITWISE equal to
   cold-prefill decode (sampled tokens included), corruption
   quarantines via the checksum and the ``cache.disk.read`` fault
   site, eviction respects the page budget, and the fleet disk tier
   warm-starts a second process-alike cache instance.
4. **Streaming + cancellation** — token-at-a-time yield with TTFT
   recorded, early close cancels, and cancellation anywhere in the
   lifecycle (mid-prefill included) frees every KV page.
5. **Surfaces** — metrics_summary / SLO windows / analyzer rows, and
   the offline bucket sweep tool publishing configs serving
   ``warmup()`` adopts.
"""

import numpy as np
import pytest

from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.resilience import inject
from tilelang_mesh_tpu.serving import (FlashDecodeWorkload, OUTCOMES,
                                       PagedKVAllocator, PrefixKVCache,
                                       Request, ServingEngine,
                                       default_prompt, sample_token)

H, D, PS = 2, 64, 8


def make_engine(tmp_path=None, n_pages=128, batch_buckets=(4,),
                page_buckets=(2,), prefix=False, **kw):
    """Engine over a fresh allocator; ``prefix`` is False (off), True
    (fresh tmp-rooted cache), or an explicit PrefixKVCache."""
    alloc = PagedKVAllocator(n_pages=n_pages, page_size=PS, heads=H,
                             head_dim=D)
    if prefix is True:
        prefix = PrefixKVCache(root=tmp_path / "prefix",
                               page_budget=256)
    wl = FlashDecodeWorkload(alloc, batch_buckets=batch_buckets,
                             page_buckets=page_buckets,
                             prefix_cache=prefix or False)
    return ServingEngine(wl, **kw), alloc


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def test_greedy_sampling_is_argmax():
    logits = np.asarray([0.1, 3.0, -1.0, 2.9])
    assert sample_token(logits, temperature=0.0) == 1
    assert sample_token(logits) == 1                 # default = greedy


def test_temperature_sampling_seeded_deterministic():
    logits = np.asarray([1.0, 1.1, 0.9, 1.05])
    a = [sample_token(logits, temperature=0.8,
                      rng=np.random.default_rng(7)) for _ in range(5)]
    b = [sample_token(logits, temperature=0.8,
                      rng=np.random.default_rng(7)) for _ in range(5)]
    assert a == b
    # high temperature spreads mass: many draws hit several tokens
    rng = np.random.default_rng(3)
    seen = {sample_token(logits, temperature=5.0, rng=rng)
            for _ in range(200)}
    assert len(seen) > 1


def test_top_p_truncates_the_tail():
    # one dominant token (~0.73 mass): top_p=0.5 keeps ONLY it
    logits = np.asarray([4.0, 2.0, 1.0, 0.0])
    rng = np.random.default_rng(11)
    draws = {sample_token(logits, temperature=1.0, top_p=0.5, rng=rng)
             for _ in range(100)}
    assert draws == {0}
    # top_p=1.0 keeps the full distribution
    rng = np.random.default_rng(11)
    draws = {sample_token(logits, temperature=1.0, top_p=1.0, rng=rng)
             for _ in range(300)}
    assert len(draws) > 1


def test_sampling_rejects_bad_knobs():
    with pytest.raises(ValueError):
        sample_token(np.asarray([1.0]), top_p=0.0)
    with pytest.raises(ValueError):
        sample_token(np.asarray([]), temperature=0.0)
    with pytest.raises(ValueError):
        Request(context_tokens=16, top_p=1.5)


def test_softmax_underflow_is_safe():
    from tilelang_mesh_tpu.serving.sampling import softmax
    p = softmax(np.asarray([-1e30, -1e30]))
    assert np.isfinite(p).all() and p.sum() == pytest.approx(1.0)


def test_request_prompt_defaults_and_validation():
    r = Request(context_tokens=16, seed=9)
    assert r.prompt_tokens == default_prompt(9, 16)
    assert Request(context_tokens=16, seed=9).prompt_tokens == \
        r.prompt_tokens                       # deterministic per seed
    with pytest.raises(ValueError):
        Request(context_tokens=16, prompt_tokens=[1, 2, 3])
    assert "canceled" in OUTCOMES


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------

def test_short_prompt_ingests_fully_at_submit():
    eng, alloc = make_engine()
    eng.warmup()
    r = eng.submit(context_tokens=16, new_tokens=1, seed=1)
    assert not r.needs_prefill and len(r.pages) == 2
    eng.run()
    assert r.outcome == "result" and alloc.in_use == 0


def test_long_prompt_fills_one_chunk_at_submit(monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    eng, alloc = make_engine()
    eng.warmup()
    r = eng.submit(context_tokens=64, new_tokens=1, seed=2)
    assert r.needs_prefill and r.prefill_pos == 16
    assert len(r.pages) == 2                 # only the chunk's pages
    eng.run()
    assert r.outcome == "result"
    assert r.prefill_pos == 64
    # all 8 context pages were allocated chunk by chunk (retire()
    # already returned them; new_tokens=1 appends no KV)
    assert alloc.alloc_count == 8
    assert alloc.in_use == 0


def test_prefill_interleaves_with_decode(monkeypatch):
    """The tentpole scheduling property: a short request decodes to
    completion while a long prompt is still mid-prefill — chunk units
    never stall the decode path."""
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_PER_STEP", "1")
    eng, alloc = make_engine(n_pages=256)
    eng.warmup()
    long = eng.submit(context_tokens=160, new_tokens=1, seed=1)
    short = eng.submit(context_tokens=16, new_tokens=1, seed=2)
    assert long.needs_prefill
    assert eng.step()          # one chunk of long + short's decode
    assert short.outcome == "result"
    assert long.needs_prefill and not long.is_terminal
    eng.run()
    assert long.outcome == "result"
    assert alloc.in_use == 0
    # the long prompt's chain shows its prefill chunks
    names = [sp.name for sp in long.trace.spans]
    assert names.count("prefill.chunk") >= 2


def test_prefill_chunk_spans_close_cleanly(monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    eng, _ = make_engine()
    eng.warmup()
    r = eng.submit(context_tokens=48, new_tokens=1, seed=3)
    eng.run()
    assert r.outcome == "result" and r.trace.complete


def test_prefill_kv_fault_sheds_terminally(monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "8")
    eng, alloc = make_engine()
    eng.warmup()
    r = eng.submit(context_tokens=64, new_tokens=1, seed=4)
    assert r.needs_prefill
    with inject("serve.kv", kind="transient"):
        eng.run()
    assert r.outcome == "shed" and r.shed_reason == "kv_exhausted"
    assert alloc.in_use == 0


def test_deadline_feasibility_counts_prefill_chunks(monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "8")
    eng, _ = make_engine(n_pages=512, page_buckets=(2,))
    eng.warmup()       # seeds the observed p50 the estimate uses
    from tilelang_mesh_tpu.serving.admission import observed_step_ms
    p50 = observed_step_ms(0.50)
    assert p50 > 0
    # a prompt needing ~60 chunk units with a deadline worth ~2 steps:
    # infeasible BECAUSE of the chunk count
    r = eng.submit(context_tokens=480, new_tokens=1,
                   deadline_ms=2 * p50)
    assert r.outcome == "shed"
    assert r.shed_reason == "deadline_infeasible"


def test_write_span_bounds():
    a = PagedKVAllocator(n_pages=2, page_size=PS, heads=H, head_dim=D)
    page = a.alloc(1, owner=1)[0]
    k = np.ones((H, 3, D), np.float32)
    a.write_span(page, 2, k, 2 * k)
    row = a.row0(page) + 2
    assert float(a.kp[0, row + 2, 0]) == 1.0
    assert float(a.vp[1, row, -1]) == 2.0
    with pytest.raises(IndexError):
        a.write_span(page, PS - 2, k, k)
    a.free(1)


# ---------------------------------------------------------------------------
# prefix cache
# ---------------------------------------------------------------------------

def _prompt(n, seed=23):
    return [int(t) for t in
            np.random.default_rng(seed).integers(0, 1 << 20, size=n)]


def test_restored_prefix_decode_bitwise_equals_cold(tmp_path):
    """The satellite correctness gate: a warm-prefix request's decode
    outputs AND sampled tokens are bit-identical to the cold-prefill
    run of the same request."""
    cache = PrefixKVCache(root=tmp_path / "prefix", page_budget=64)
    prompt = _prompt(32)                       # 4 whole pages
    eng1, alloc1 = make_engine(prefix=cache)
    eng1.warmup()
    r1 = eng1.submit(context_tokens=32, prompt_tokens=prompt,
                     new_tokens=2, seed=5)
    eng1.run()
    assert r1.outcome == "result" and cache.stats()["inserts"] == 1
    # a FRESH engine/allocator sharing the cache: same request replays
    eng2, alloc2 = make_engine(prefix=cache)
    eng2.warmup()
    r2 = eng2.submit(context_tokens=32, prompt_tokens=prompt,
                     new_tokens=2, seed=5)
    assert r2.prefix_tokens == 32 and not r2.needs_prefill
    eng2.run()
    assert r2.outcome == "result"
    assert cache.stats()["hits"] >= 1
    assert np.array_equal(np.asarray(r1.result), np.asarray(r2.result))
    assert r1.generated == r2.generated
    assert alloc1.in_use == 0 and alloc2.in_use == 0


def test_partial_prefix_hit_is_bitwise_correct(tmp_path):
    """A shared prefix + unique suffix: the prefix restores, the
    suffix prefills cold, and the result equals the fully-cold run."""
    cache = PrefixKVCache(root=tmp_path / "prefix", page_budget=64)
    shared = _prompt(32)                       # 4 pages
    suffix = _prompt(8, seed=77)
    prompt = shared + suffix                   # 5 pages
    # seed the cache with the 4-page shared prefix
    eng0, _ = make_engine(prefix=cache)
    eng0.warmup()
    eng0.submit(context_tokens=32, prompt_tokens=shared, seed=1)
    eng0.run()
    # warm: restores 4 pages, prefills 1
    engw, _ = make_engine(prefix=cache)
    engw.warmup()
    rw = engw.submit(context_tokens=40, prompt_tokens=prompt,
                     new_tokens=1, seed=9)
    assert rw.prefix_tokens == 32
    engw.run()
    # cold reference: prefix cache off entirely
    engc, _ = make_engine(prefix=False)
    engc.warmup()
    rc = engc.submit(context_tokens=40, prompt_tokens=prompt,
                     new_tokens=1, seed=9)
    assert rc.prefix_tokens == 0
    engc.run()
    assert rw.outcome == rc.outcome == "result"
    assert np.array_equal(np.asarray(rw.result), np.asarray(rc.result))
    assert rw.generated == rc.generated


def test_prefix_entry_roundtrip_and_lookup_longest(tmp_path):
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    geom = "T:v1:h2:d64:ps8:float32"
    pages2 = [(np.full((H, PS, D), i, np.float32),
               np.full((H, PS, D), -i, np.float32)) for i in range(2)]
    toks = _prompt(24)
    cache.insert(geom, toks[:16], pages2, PS, H, D, "float32")
    # longest whole-page prefix of the 24-token prompt is the 2-page
    # entry (3 pages probed first, misses, then hits 2)
    ent = cache.lookup(geom, toks, PS)
    assert ent is not None and ent.n_pages == 2
    assert cache.lookup(geom, _prompt(16, seed=99), PS) is None
    s = cache.stats()
    assert s["hits"] == 1 and s["misses"] == 1 and s["entries"] == 1


def test_corrupted_disk_entry_quarantines(tmp_path):
    """Checksum rejection: flipped bytes on disk -> quarantined +
    miss, never served (the satellite gate)."""
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    geom = "g"
    toks = _prompt(8)
    pages = [(np.ones((H, PS, D), np.float32),
              np.zeros((H, PS, D), np.float32))]
    ent = cache.insert(geom, toks, pages, PS, H, D, "float32")
    assert cache.flush() == 1          # force the deferred publication
    path = cache.root / f"{ent.key}.json"
    assert path.is_file()
    # corrupt the payload on disk, then drop the memory tier the way
    # a fresh fleet member would start
    import json as _json
    doc = _json.loads(path.read_text())
    doc["pages"][0]["k"] = doc["pages"][0]["v"]     # swapped payload
    path.write_text(_json.dumps(doc))
    fresh = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    assert fresh.lookup(geom, toks, PS) is None
    assert fresh.stats()["quarantined"] == 1
    assert not path.exists()
    q = list((cache.root / ".quarantine").iterdir())
    assert len(q) == 1                    # evidence preserved


def test_disk_read_fault_site_quarantines(tmp_path):
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    geom = "g"
    toks = _prompt(8)
    pages = [(np.ones((H, PS, D), np.float32),
              np.zeros((H, PS, D), np.float32))]
    cache.insert(geom, toks, pages, PS, H, D, "float32")
    cache.flush()
    fresh = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    with inject("cache.disk.read", kind="oserror"):
        assert fresh.lookup(geom, toks, PS) is None
    assert fresh.stats()["quarantined"] == 1


def test_corrupt_memory_entry_rejected_at_restore(tmp_path):
    """Bit rot between insert and restore: the allocator's checksum
    verification rejects the snapshot, the entry is dropped, and the
    request falls back to a (correct) cold prefill."""
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    prompt = _prompt(16)
    eng0, _ = make_engine(prefix=cache)
    eng0.warmup()
    eng0.submit(context_tokens=16, prompt_tokens=prompt, seed=1)
    eng0.run()
    ent = cache.lookup("FlashDecodeWorkload:v1:h2:d64:ps8:float32",
                       prompt, PS)
    assert ent is not None
    ent.pages[0][0][0, 0, 0] += 1.0           # flip a value in place
    eng1, alloc1 = make_engine(prefix=cache)
    eng1.warmup()
    r = eng1.submit(context_tokens=16, prompt_tokens=prompt,
                    new_tokens=1, seed=5)
    assert r.prefix_tokens == 0               # fell back to cold
    eng1.run()
    assert r.outcome == "result" and alloc1.in_use == 0
    s = cache.stats()
    assert s["quarantined"] == 1
    # the cold prefill re-inserted a CLEAN entry under the same key
    # (never-rebuild-in-place: quarantine first, fresh insert after)
    assert s["inserts"] == 2
    ent2 = cache.lookup("FlashDecodeWorkload:v1:h2:d64:ps8:float32",
                        prompt, PS)
    assert ent2 is not None
    from tilelang_mesh_tpu.serving.prefix_cache import _entry_checksum
    got, _ = _entry_checksum(ent2.pages)
    assert got == ent2.checksum               # the fresh entry is clean


def test_eviction_respects_page_budget(tmp_path):
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=4)
    geom = "g"
    for i in range(3):
        pages = [(np.full((H, PS, D), i, np.float32),) * 2] * 2
        pages = [(k.copy(), v.copy()) for k, v in pages]
        cache.insert(geom, _prompt(16, seed=i), pages, PS, H, D,
                     "float32")
    s = cache.stats()
    assert s["evictions"] >= 1 and s["pages"] <= 4
    # survivors publish on flush; evicted entries left no file behind
    cache.flush()
    assert len(list(cache.root.glob("*.json"))) == s["entries"]


def test_disk_publication_deferred_to_first_reuse(tmp_path):
    """Single-use prompts never pay disk serialization on the serving
    path; the first REUSE publishes the entry, and a fresh
    process-alike cache instance then hits it from the fleet tier."""
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    prompt = _prompt(16)
    eng0, _ = make_engine(prefix=cache)
    eng0.warmup()
    eng0.submit(context_tokens=16, prompt_tokens=prompt, seed=1)
    eng0.run()
    assert cache.stats()["inserts"] == 1
    assert list(cache.root.glob("*.json")) == []    # not published yet
    # first reuse: memory hit -> the entry earns its disk file
    eng1, _ = make_engine(prefix=cache)
    eng1.warmup()
    r = eng1.submit(context_tokens=16, prompt_tokens=prompt, seed=2)
    assert r.prefix_tokens == 16
    eng1.run()
    assert len(list(cache.root.glob("*.json"))) == 1
    # a fresh cache instance (new process in the fleet) hits from disk
    fresh = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    ent = fresh.lookup("FlashDecodeWorkload:v1:h2:d64:ps8:float32",
                       prompt, PS)
    assert ent is not None and ent.n_tokens == 16


def test_insert_dedups_by_content_address(tmp_path):
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    prompt = _prompt(16)
    for _ in range(2):
        eng, _ = make_engine(prefix=cache)
        eng.warmup()
        eng.submit(context_tokens=16, prompt_tokens=prompt,
                   seed=1)
        eng.run()
    assert cache.stats()["inserts"] == 1      # second run hit, no dup


def test_env_gated_process_cache(tmp_path, monkeypatch):
    from tilelang_mesh_tpu.serving import (get_prefix_cache,
                                           reset_prefix_cache)
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path / "pp"))
    reset_prefix_cache()
    try:
        monkeypatch.setenv("TL_TPU_SERVE_PREFIX", "0")
        alloc = PagedKVAllocator(n_pages=16, page_size=PS, heads=H,
                                 head_dim=D)
        wl = FlashDecodeWorkload(alloc)
        assert wl.prefix_cache is None
        monkeypatch.setenv("TL_TPU_SERVE_PREFIX", "1")
        wl2 = FlashDecodeWorkload(alloc)
        assert wl2.prefix_cache is get_prefix_cache()
        assert wl2.prefix_cache.root == tmp_path / "pp"
    finally:
        reset_prefix_cache()


# ---------------------------------------------------------------------------
# streaming + cancellation
# ---------------------------------------------------------------------------

def test_stream_yields_tokens_and_records_ttft():
    before = obs.metrics_summary()["serving"]["ttft"]
    eng, alloc = make_engine()
    eng.warmup()
    stream = eng.stream(context_tokens=16, new_tokens=3, seed=7)
    events = list(stream)
    r = stream.request
    assert r.outcome == "result"
    assert [e["index"] for e in events] == [1, 2, 3]
    assert [e["token"] for e in events] == r.generated
    assert r.first_token_t is not None
    after = obs.metrics_summary()["serving"]["ttft"]
    assert (after or {}).get("count", 0) > (before or {}).get("count", 0)
    assert alloc.in_use == 0


def test_stream_early_close_cancels_and_frees():
    eng, alloc = make_engine()
    eng.warmup()
    stream = eng.stream(context_tokens=16, new_tokens=8, seed=7)
    it = iter(stream)
    first = next(it)
    assert first["index"] == 1
    it.close()                               # client disconnect
    r = stream.request
    assert r.outcome == "canceled"
    assert alloc.in_use == 0 and alloc.leak_check() == {}
    assert r.trace.complete


def test_cancel_mid_prefill_leaks_zero_pages(monkeypatch):
    """The satellite leak gate: cancellation while the prompt is still
    filling frees every partially-allocated page."""
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    eng, alloc = make_engine(n_pages=256)
    eng.warmup()
    r = eng.submit(context_tokens=160, new_tokens=1, seed=1)
    eng.step()                               # a couple of chunks in
    assert r.needs_prefill and len(r.pages) > 0 and alloc.in_use > 0
    assert eng.cancel(r)
    assert r.outcome == "canceled"
    assert alloc.in_use == 0 and alloc.leak_check() == {}
    assert r.trace.complete
    # cancel of a terminal request is a no-op
    assert not eng.cancel(r)
    s = eng.stats()
    assert s["outcomes"]["canceled"] == 1


def test_cancel_mid_decode_discards_remaining_steps():
    eng, alloc = make_engine()
    eng.warmup()
    r = eng.submit(context_tokens=16, new_tokens=5, seed=2)
    eng.step()
    assert r.steps_done == 1 and not r.is_terminal
    eng.cancel(r)
    assert r.outcome == "canceled" and r.steps_done == 1
    assert alloc.in_use == 0
    assert obs.metrics_summary()["serving"]["canceled"] >= 1


def test_canceled_requests_count_in_accounting():
    eng, _ = make_engine()
    eng.warmup()
    keep = eng.submit(context_tokens=16, new_tokens=1, seed=1)
    drop = eng.submit(context_tokens=16, new_tokens=4, seed=2)
    eng.cancel(drop)
    eng.run()
    out = eng.outcomes()
    assert out["result"] == 1 and out["canceled"] == 1
    assert keep.outcome == "result" and drop.outcome == "canceled"


# ---------------------------------------------------------------------------
# surfaces: metrics, SLO windows, analyzer
# ---------------------------------------------------------------------------

def test_metrics_summary_lifecycle_sections(tmp_path, monkeypatch):
    obs.reset()
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    prompt = _prompt(32)
    for seed in (1, 2):
        eng, _ = make_engine(prefix=cache)
        eng.warmup()
        eng.submit(context_tokens=32, prompt_tokens=prompt, seed=seed)
        eng.run()
    s = obs.metrics_summary()["serving"]
    # the cold request scheduled one chunk past ingest's synchronous
    # first chunk; the warm request restored everything (zero chunks)
    assert s["prefill_chunks"] >= 1 and s["prefill_tokens"] >= 16
    assert s["ttft"] and s["ttft"]["count"] == 2
    pc = s["prefix_cache"]
    assert pc["hits"] == 1 and pc["inserts"] == 1
    assert pc["bytes_saved"] > 0
    assert "canceled" in s


def test_slo_windows_report_ttft_and_prefix_hit_rate():
    from tilelang_mesh_tpu.observability.histogram import Histogram
    from tilelang_mesh_tpu.observability.slo import SLOEngine
    slo = SLOEngine(windows=[10.0], target=0.999)
    h0 = Histogram()
    t0 = Histogram()
    t0.observe(0.050)
    base = {"t": 100.0, "submitted": 10.0, "shed": 0.0,
            "completed": 10.0, "failed": 0.0, "deadline_exceeded": 0.0,
            "hist": h0, "ttft_hist": t0, "prefix_hits": 2.0,
            "prefix_misses": 2.0}
    t1 = Histogram()
    t1.merge(t0)
    t1.observe(0.080)
    cur = dict(base, t=105.0, submitted=20.0, ttft_hist=t1,
               prefix_hits=8.0, prefix_misses=4.0)
    slo.add(base)
    slo.add(cur)
    w = slo.window_stats(10.0)
    assert w["ttft_p99_ms"] is not None and w["ttft_p99_ms"] > 0
    assert w["prefix_hit_rate"] == pytest.approx(6 / 8)
    # legacy synthetic samples without the new keys stay valid
    slo2 = SLOEngine(windows=[10.0])
    slo2.add({"t": 1.0, "submitted": 1.0, "shed": 0.0, "completed": 0.0,
              "failed": 0.0, "deadline_exceeded": 0.0, "hist": None})
    slo2.add({"t": 5.0, "submitted": 2.0, "shed": 0.0, "completed": 1.0,
              "failed": 0.0, "deadline_exceeded": 0.0, "hist": None})
    w2 = slo2.window_stats(10.0)
    assert w2["ttft_p99_ms"] is None and w2["prefix_hit_rate"] is None


def test_analyzer_serve_report_lifecycle_rows(tmp_path, monkeypatch):
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    obs.reset()
    cache = PrefixKVCache(root=tmp_path / "p", page_budget=64)
    prompt = _prompt(32)
    for seed in (1, 2):
        eng, _ = make_engine(prefix=cache)
        eng.warmup()
        eng.submit(context_tokens=32, prompt_tokens=prompt, seed=seed)
        drop = eng.submit(context_tokens=16, new_tokens=4, seed=9)
        eng.cancel(drop)
        eng.run()
    p = tmp_path / "serve.jsonl"
    obs.write_jsonl(str(p))
    from tilelang_mesh_tpu.tools.analyzer import (format_serve_report,
                                                  summarize_serve)
    recs = obs.read_jsonl(str(p))
    s = summarize_serve(recs)
    assert s["canceled"] == 2
    assert s["prefill_chunks"] >= 1
    # the shared prompt hit once; the second canceled request's
    # identical (seed, ctx) default prompt hit too
    assert s["prefix_cache"]["hits"] >= 1
    text = format_serve_report(recs)
    assert "canceled" in text and "prefix cache" in text
    assert "serve.ttft" in text


def test_prefill_chunk_spans_visible_in_request_timeline(tmp_path,
                                                         monkeypatch):
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    obs.reset()
    eng, _ = make_engine()
    eng.warmup()
    r = eng.submit(context_tokens=48, new_tokens=1, seed=3)
    eng.run()
    p = tmp_path / "t.jsonl"
    obs.write_jsonl(str(p))
    from tilelang_mesh_tpu.tools.analyzer import format_request_report
    text = format_request_report(obs.read_jsonl(str(p)), r.trace_id)
    assert "prefill.chunk" in text


# ---------------------------------------------------------------------------
# offline bucket sweep -> fleet tune cache -> warmup adoption
# ---------------------------------------------------------------------------

def test_serve_sweep_publishes_and_warmup_adopts(tmp_path, monkeypatch):
    monkeypatch.setenv("TL_TPU_TUNE_CACHE_DIR", str(tmp_path / "tune"))
    from tilelang_mesh_tpu.tools.serve_sweep import sweep_workload
    alloc = PagedKVAllocator(n_pages=32, page_size=PS, heads=H,
                             head_dim=D)
    wl = FlashDecodeWorkload(alloc, batch_buckets=(1,),
                             page_buckets=(2,), prefix_cache=False)
    results = sweep_workload(wl, reps=1)
    assert len(results) == 1
    r = results[0]
    assert r["key"] and r["best_config"]["n_split"] in (1, 2)
    assert len(r["trials"]) == 2              # divisors of 2
    # a FRESH workload (fresh process-alike) adopts the swept config
    # with zero measurements at warmup
    before = obs.metrics_summary()["counters"].get(
        "serve.warmup.tuned", 0)
    alloc2 = PagedKVAllocator(n_pages=32, page_size=PS, heads=H,
                              head_dim=D)
    wl2 = FlashDecodeWorkload(alloc2, batch_buckets=(1,),
                              page_buckets=(2,), prefix_cache=False)
    eng = ServingEngine(wl2)
    eng.warmup()
    after = obs.metrics_summary()["counters"].get(
        "serve.warmup.tuned", 0)
    assert after == before + 1
    assert wl2.tuned_config(1, 2) == r["best_config"]


def test_serve_sweep_cli_smoke(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("TL_TPU_TUNE_CACHE_DIR", str(tmp_path / "tune"))
    from tilelang_mesh_tpu.tools.serve_sweep import main
    rc = main(["--batch-buckets", "1", "--page-buckets", "2",
               "--pages", "16", "--reps", "1", "--json"])
    assert rc == 0
    import json as _json
    doc = _json.loads(capsys.readouterr().out)
    assert doc["results"][0]["best_config"]["n_split"] in (1, 2)


# ---------------------------------------------------------------------------
# the lifecycle soak (the CI gate, in-process)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_lifecycle_soak_all_terminal(tmp_path, monkeypatch):
    """The serve-lifecycle CI gate run in-process: mixed shared-prompt
    / long-prompt / decode / stream / cancel traffic with faults armed
    — 100% terminal, zero leaks, >= 1 prefix hit, prefill interleaved,
    decode p99 within budget (verify/chaos.py --serve-lifecycle)."""
    obs.reset()
    # the chaos driver mutates os.environ for its own process (fine
    # from the CLI); in-process, monkeypatch pins + restores the same
    # knobs so this test cannot leak state into later suites
    monkeypatch.setenv("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR",
                       str(tmp_path / "prefix"))
    from tilelang_mesh_tpu.serving import reset_prefix_cache
    from tilelang_mesh_tpu.verify.chaos import run_serve_lifecycle
    try:
        rc = run_serve_lifecycle(tmp_path, seed=7, n_requests=200)
    finally:
        reset_prefix_cache()        # the env-derived root just changed
    assert rc == 0
    import json as _json
    report = _json.loads(
        (tmp_path / "serve_lifecycle_report.json").read_text())
    assert all(report["checks"].values())
    assert report["outcomes"]["pending"] == 0
    assert report["prefix_cache"]["hits"] >= 1
    assert report["outcomes"]["canceled"] >= 1
