"""Collective-optimizer (transform/comm_opt.py) tests.

Style mirrors tests/test_comm.py: (1) golden plan_desc texts for each
rewrite — fused, deduped, eliminated, chunked — the analog of the
reference's lowered-IR comm goldens; (2) numerical equivalence of the
optimized vs unoptimized lowering on the 2x2 CPU mesh; (3) the
TL_TPU_COMM_OPT=0 bypass restoring the exact unoptimized schedule; and
(4) the pre-/post-optimization wire-byte accounting surfaced through
attrs["collectives"], attrs["comm_opt"], and metrics_summary().
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.parallel import mesh_config
from tilelang_mesh_tpu.transform import comm_opt_modes, pass_config

MESH = (2, 2)
NROW, NCOL = MESH
SHAPE = (8, 128)
TARGET = f"cpu-mesh[{NROW}x{NCOL}]"


def _global(shape=None):
    shape = shape or (NROW * NCOL * SHAPE[0], SHAPE[1])
    return T.MeshTensor(shape, T.MeshShardingPolicy(cross_mesh_dim=0),
                        MESH, "float32")


def _shards(rng):
    return rng.standard_normal((NROW * NCOL * SHAPE[0], SHAPE[1]),
                               ).astype(np.float32)


# ---- programs, one per rewrite ---------------------------------------------


def _fused_program():
    """Two same-axis same-type all_reduces on distinct payloads ->
    one batched collective with 2 payload slots."""
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(), B: _global((NROW * NCOL * SHAPE[0], 1)),
              C: _global((NROW * NCOL * SHAPE[0], 1))):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment(SHAPE, "float32")
                y = T.alloc_fragment(SHAPE, "float32")
                o1 = T.alloc_fragment((SHAPE[0], 1), "float32")
                o2 = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, x)
                T.copy(A, y)
                T.comm.all_reduce(x, o1, "sum", "h", dim=1)
                T.comm.all_reduce(y, o2, "sum", "h", dim=1)
                T.copy(o1, B)
                T.copy(o2, C)
        return k


def _dedup_program():
    """A byte-identical duplicate broadcast (dropped) plus a same-payload
    broadcast to a second destination (shares the wire slot)."""
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(), B: _global(), C: _global()):
            with T.Kernel(1) as bx:
                x = T.alloc_shared(SHAPE, "float32")
                d1 = T.alloc_shared(SHAPE, "float32")
                d2 = T.alloc_shared(SHAPE, "float32")
                T.copy(A, x)
                T.comm.broadcast(x, d1, (0, 1), "h")
                T.comm.broadcast(x, d1, (0, 1), "h")   # exact duplicate
                T.comm.broadcast(x, d2, (0, 1), "h")   # same payload
                T.copy(d1, B)
                T.copy(d2, C)
        return k


def _dce_program():
    """An all_reduce whose result is never read again: eliminated, and
    the neighbouring compute segments merge back into one kernel."""
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(), B: _global()):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment(SHAPE, "float32")
                dead = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, x)
                T.comm.all_reduce(x, dead, "sum", "v", dim=1)
                T.copy(x, B)
        return k


def _chunk_program():
    """A large all_gather feeding a consumer copy segment; with the
    chunk threshold lowered it splits into pipelined chunks."""
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(),
              B: _global((NROW * NCOL, NCOL, SHAPE[0], SHAPE[1]))):
            with T.Kernel(1) as bx:
                send = T.alloc_shared(SHAPE, "float32")
                recv = T.alloc_shared((NCOL, *SHAPE), "float32")
                T.copy(A, send)
                T.comm.all_gather(send, recv, "h")
                T.copy(recv, B[0, 0, 0])
        return k


def _lower(pf, **cfg):
    if cfg:
        with pass_config(cfg):
            return tilelang.lower(pf, target=TARGET)
    return tilelang.lower(pf, target=TARGET)


# ---- golden plan_desc per rewrite ------------------------------------------


def test_fused_golden_schedule():
    assert _lower(_fused_program()).plan_desc == """\
mesh_program(k) mesh=(2x2) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(frag_lo, frag_1_lo)
  [1] collective fused[2x allreduce, axis=y, dir=h, slots=2]
        member[0] slot=0: all_reduce(frag -> frag_2, op=sum, dir=h, dim=1, clear=True)
        member[1] slot=1: all_reduce(frag_1 -> frag_3, op=sum, dir=h, dim=1, clear=True)
        noc[0]: bcast core(0, 0) dir=h chunk=0
        noc[1]: bcast core(0, 1) dir=h chunk=1
        noc[2]: bcast core(1, 0) dir=h chunk=0
        noc[3]: bcast core(1, 1) dir=h chunk=1
        cost: 4 steps, 4 hops
        xla: local reduce(dim=1) + psum(axis='y') over 2-slot concat payload (2 members)
  [2] pallas_segment k_seg2 grid=(1,) ins=(frag_2_li, frag_3_li) outs=(B, C)
  comm_opt[fuse,dce,overlap]: wire 256B -> 256B, hops 8 -> 4
    * fuse: 2x all_reduce(frag -> frag_2, op=sum, dir=h, dim=1, clear=True) -> 1 batched op
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None)
  param C: role=out spec=PartitionSpec(('x', 'y'), None)
"""


def test_dedup_golden_schedule():
    assert _lower(_dedup_program()).plan_desc == """\
mesh_program(k) mesh=(2x2) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(shared_lo)
  [1] collective fused[2x broadcast, axis=y, dir=h, slots=1]
        member[0] slot=0: broadcast(shared -> shared_1, src_core=(0, 1), dir=h)
        member[1] slot=0: broadcast(shared -> shared_2, src_core=(0, 1), dir=h)
        noc[0]: bcast core(0, 1) dir=h chunk=0
        cost: 1 steps, 1 hops
        xla: psum(mask(core==(0, 1)), 'y') -> row 0 over 1-slot concat payload (2 members)
  [2] pallas_segment k_seg2 grid=(1,) ins=(shared_1_li, shared_2_li) outs=(B, C)
  comm_opt[fuse,dce,overlap]: wire 12288B -> 4096B, hops 3 -> 1
    * fuse: dropped duplicate broadcast(shared -> shared_1, src_core=(0, 1), dir=h)
    * fuse: 2x broadcast(shared -> shared_1, src_core=(0, 1), dir=h) -> 1 batched op (1 shared payload slot)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None)
  param C: role=out spec=PartitionSpec(('x', 'y'), None)
"""


def test_dce_golden_schedule():
    # no tl.tpu.lint=0 workaround needed anymore: TL006 recognizes that
    # the never-read fragment is written only by a collective the
    # enabled dce rewrite will delete, and stays silent — the deletion
    # is reported through the comm_opt accounting below instead
    assert _lower(_dce_program()).plan_desc == """\
mesh_program(k) mesh=(2x2) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(B)
  comm_opt[fuse,dce,overlap]: wire 128B -> 0B, hops 4 -> 0
    * dce: dropped dead all_reduce(frag -> frag_1, op=sum, dir=v, dim=1, clear=True)
    * dce: merged adjacent compute segments
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None)
"""


def test_chunked_golden_schedule():
    assert _lower(_chunk_program(),
                  **{"tl.tpu.comm_chunk_bytes": 1024}).plan_desc == """\
mesh_program(k) mesh=(2x2) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(shared_lo)
  [1] collective chunked[4] all_gather(shared -> shared_1, dir=h)
        noc[0]: bcast core(0, 0) dir=h chunk=0
        noc[1]: bcast core(0, 1) dir=h chunk=1
        noc[2]: bcast core(1, 0) dir=h chunk=0
        noc[3]: bcast core(1, 1) dir=h chunk=1
        cost: 4 steps, 4 hops
        overlap: 4 x 1024B chunks, transfer(i+1) || compute(i) (double-buffered)
        xla: 4 x [all_gather(axis='y')] on leading-axis chunks
  [2] pallas_segment k_seg2 grid=(1,) ins=(shared_1_li) outs=(B)
  comm_opt[fuse,dce,overlap]: wire 16384B -> 16384B, hops 4 -> 4
    * overlap: all_gather(shared -> shared_1, dir=h) -> 4 pipelined chunks (16384B wire over segment [2]'s compute)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None, None, None)
"""


def test_bypass_restores_unoptimized_schedule(monkeypatch):
    """TL_TPU_COMM_OPT=0 must restore the exact pre-optimizer schedule
    text (the pre-PR plan_desc format: no comm_opt block, no fused or
    chunked collectives)."""
    monkeypatch.setenv("TL_TPU_COMM_OPT", "0")
    art = _lower(_fused_program())
    assert art.attrs["comm_opt"] is None
    assert art.plan_desc == """\
mesh_program(k) mesh=(2x2) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(frag_lo, frag_1_lo)
  [1] collective all_reduce(frag -> frag_2, op=sum, dir=h, dim=1, clear=True)
        noc[0]: bcast core(0, 0) dir=h chunk=0
        noc[1]: bcast core(0, 1) dir=h chunk=1
        noc[2]: bcast core(1, 0) dir=h chunk=0
        noc[3]: bcast core(1, 1) dir=h chunk=1
        cost: 4 steps, 4 hops
        xla: local reduce(dim=1) + psum(axis='y')
  [2] collective all_reduce(frag_1 -> frag_3, op=sum, dir=h, dim=1, clear=True)
        noc[0]: bcast core(0, 0) dir=h chunk=0
        noc[1]: bcast core(0, 1) dir=h chunk=1
        noc[2]: bcast core(1, 0) dir=h chunk=0
        noc[3]: bcast core(1, 1) dir=h chunk=1
        cost: 4 steps, 4 hops
        xla: local reduce(dim=1) + psum(axis='y')
  [3] pallas_segment k_seg3 grid=(1,) ins=(frag_2_li, frag_3_li) outs=(B, C)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None)
  param C: role=out spec=PartitionSpec(('x', 'y'), None)
"""


# ---- mode selection ---------------------------------------------------------


def test_mode_typo_is_loud(monkeypatch):
    """A typo'd mode token must raise, not silently disable the pass."""
    import pytest
    monkeypatch.setenv("TL_TPU_COMM_OPT", "fsue")
    with pytest.raises(ValueError, match="unknown TL_TPU_COMM_OPT"):
        comm_opt_modes()


def test_dce_eliminates_dead_chains():
    """A collective kept alive only by a later dead collective is a
    dead chain: DCE iterates to fixpoint and removes both."""
    def prog():
        with mesh_config(*MESH):
            @T.prim_func
            def k(A: _global(), B: _global()):
                with T.Kernel(1) as bx:
                    x = T.alloc_shared(SHAPE, "float32")
                    mid = T.alloc_shared(SHAPE, "float32")
                    dead = T.alloc_shared(SHAPE, "float32")
                    T.copy(A, x)
                    T.comm.broadcast(x, mid, (0, 0), "h")
                    T.comm.barrier()
                    T.comm.broadcast(mid, dead, (0, 1), "v")
                    T.copy(x, B)
            return k
    art = _lower(prog())
    # both links of the chain are gone; only the barrier remains (the
    # dropped ops appear solely in the dce rewrite log lines)
    assert "collective broadcast" not in art.plan_desc
    assert art.plan_desc.count("collective") == 1  # barrier()
    assert art.attrs["comm_opt"]["post_wire_bytes"] == 0
    assert sum(1 for r in art.attrs["comm_opt"]["rewrites"]
               if r.startswith("dce: dropped")) == 2
    _run_pair(prog, 9)


def test_mode_parsing(monkeypatch):
    monkeypatch.setenv("TL_TPU_COMM_OPT", "1")
    assert comm_opt_modes() == ("fuse", "dce", "overlap")
    monkeypatch.setenv("TL_TPU_COMM_OPT", "0")
    assert comm_opt_modes() == ()
    monkeypatch.setenv("TL_TPU_COMM_OPT", "fuse,dce")
    assert comm_opt_modes() == ("fuse", "dce")
    monkeypatch.setenv("TL_TPU_COMM_OPT", "overlap")
    assert comm_opt_modes() == ("overlap",)
    # pass config wins over the env var
    assert comm_opt_modes({"tl.tpu.comm_opt": "0"}) == ()


def test_mode_subset_gates_rewrites(monkeypatch):
    # dce-only: the dead reduce goes away but nothing fuses
    monkeypatch.setenv("TL_TPU_COMM_OPT", "dce")
    desc = _lower(_fused_program()).plan_desc
    assert "fused[" not in desc
    # fuse-only: nothing is chunked even under the low threshold
    monkeypatch.setenv("TL_TPU_COMM_OPT", "fuse")
    desc = _lower(_chunk_program(),
                  **{"tl.tpu.comm_chunk_bytes": 1024}).plan_desc
    assert "chunked[" not in desc


def test_determinism_across_lowerings():
    """Two lowerings of the same func produce byte-identical schedules
    (grouping keys are canonical — kind + mesh axis + operand identity —
    never dict iteration order)."""
    for prog in (_fused_program, _dedup_program, _dce_program):
        pf = prog()
        assert tilelang.lower(pf, target=TARGET).plan_desc == \
            tilelang.lower(pf, target=TARGET).plan_desc
    pf = _chunk_program()
    cfg = {"tl.tpu.comm_chunk_bytes": 1024}
    assert _lower(pf, **cfg).plan_desc == _lower(pf, **cfg).plan_desc


# ---- numerical equivalence: optimized vs unoptimized ------------------------


def _run_pair(prog, seed, **cfg):
    pf = prog()
    if cfg:
        with pass_config(cfg):
            k_on = tilelang.compile(pf, target=TARGET)
    else:
        k_on = tilelang.compile(pf, target=TARGET)
    with pass_config({"tl.tpu.comm_opt": "0"}):
        k_off = tilelang.compile(pf, target=TARGET)
    a = _shards(np.random.default_rng(seed))
    r_on = k_on(a)
    r_off = k_off(a)
    r_on = r_on if isinstance(r_on, tuple) else (r_on,)
    r_off = r_off if isinstance(r_off, tuple) else (r_off,)
    assert len(r_on) == len(r_off)
    for x_on, x_off in zip(r_on, r_off):
        np.testing.assert_allclose(np.asarray(x_on), np.asarray(x_off),
                                   rtol=1e-6, atol=1e-6)


def test_fused_allreduce_numerics():
    _run_pair(_fused_program, 0)


def test_dedup_broadcast_numerics():
    _run_pair(_dedup_program, 1)


def test_dce_numerics():
    _run_pair(_dce_program, 2)


def test_chunked_allgather_numerics():
    _run_pair(_chunk_program, 3, **{"tl.tpu.comm_chunk_bytes": 1024})


def test_chunked_allreduce_numerics():
    """Chunked all_reduce path: big payload, low threshold."""
    def prog():
        with mesh_config(*MESH):
            @T.prim_func
            def k(A: _global(), B: _global((NROW * NCOL * SHAPE[0], 1))):
                with T.Kernel(1) as bx:
                    x = T.alloc_fragment(SHAPE, "float32")
                    o = T.alloc_fragment((SHAPE[0], 1), "float32")
                    T.copy(A, x)
                    T.comm.all_reduce(x, o, "sum", "all", dim=1)
                    T.copy(o, B)
            return k
    cfg = {"tl.tpu.comm_chunk_bytes": 8, "tl.tpu.comm_chunks": 4}
    with pass_config(cfg):
        desc = tilelang.lower(prog(), target=TARGET).plan_desc
    assert "chunked[4] all_reduce" in desc
    _run_pair(prog, 4, **cfg)


def test_fused_allgather_numerics():
    """Two same-axis all_gathers fuse into one batched gather; the
    split-back must reproduce each member's recv exactly."""
    def prog():
        with mesh_config(*MESH):
            @T.prim_func
            def k(A: _global(),
                  B: _global((NROW * NCOL, NCOL, SHAPE[0], SHAPE[1])),
                  C: _global((NROW * NCOL, NCOL, SHAPE[0], SHAPE[1]))):
                with T.Kernel(1) as bx:
                    s1 = T.alloc_shared(SHAPE, "float32")
                    s2 = T.alloc_shared(SHAPE, "float32")
                    r1 = T.alloc_shared((NCOL, *SHAPE), "float32")
                    r2 = T.alloc_shared((NCOL, *SHAPE), "float32")
                    T.copy(A, s1)
                    T.copy(A, s2)
                    T.comm.all_gather(s1, r1, "h")
                    T.comm.all_gather(s2, r2, "h")
                    T.copy(r1, B[0, 0, 0])
                    T.copy(r2, C[0, 0, 0])
            return k
    art = tilelang.lower(prog(), target=TARGET)
    assert "fused[2x allgather" in art.plan_desc
    _run_pair(prog, 6)


def test_fused_allgather_all_direction_numerics():
    """Fused 2-D ('all') gathers: tuple-axis all_gather ordering must
    survive the concat/split round trip."""
    n_all = NROW * NCOL

    def prog():
        with mesh_config(*MESH):
            @T.prim_func
            def k(A: _global(),
                  B: _global((NROW * NCOL, n_all, SHAPE[0], SHAPE[1])),
                  C: _global((NROW * NCOL, n_all, SHAPE[0], SHAPE[1]))):
                with T.Kernel(1) as bx:
                    s1 = T.alloc_shared(SHAPE, "float32")
                    s2 = T.alloc_shared(SHAPE, "float32")
                    r1 = T.alloc_shared((n_all, *SHAPE), "float32")
                    r2 = T.alloc_shared((n_all, *SHAPE), "float32")
                    T.copy(A, s1)
                    T.copy(A, s2)
                    T.comm.all_gather(s1, r1, "all")
                    T.comm.all_gather(s2, r2, "all")
                    T.copy(r1, B[0, 0, 0])
                    T.copy(r2, C[0, 0, 0])
            return k
    art = tilelang.lower(prog(), target=TARGET)
    assert "fused[2x allgather" in art.plan_desc
    _run_pair(prog, 7)


def test_fused_mixed_clear_numerics():
    """clear=False accumulation stays per-member under fusion."""
    def prog():
        with mesh_config(*MESH):
            @T.prim_func
            def k(A: _global(), B: _global((NROW * NCOL * SHAPE[0], 1)),
                  C: _global((NROW * NCOL * SHAPE[0], 1))):
                with T.Kernel(1) as bx:
                    x = T.alloc_fragment(SHAPE, "float32")
                    o1 = T.alloc_fragment((SHAPE[0], 1), "float32")
                    o2 = T.alloc_fragment((SHAPE[0], 1), "float32")
                    T.copy(A, x)
                    T.fill(o2, 1.0)
                    T.comm.all_reduce(x, o1, "sum", "h", dim=1)
                    T.comm.all_reduce(x, o2, "sum", "h", dim=1,
                                      clear=False)
                    T.copy(o1, B)
                    T.copy(o2, C)
            return k
    art = tilelang.lower(prog(), target=TARGET)
    assert "fused[2x allreduce" in art.plan_desc
    _run_pair(prog, 5)


# ---- accounting -------------------------------------------------------------


def test_fused_accounting_wire_bytes():
    """Acceptance: two same-axis all_reduces -> ONE fused collective in
    plan_desc, and attrs['collectives'] reports post-optimization wire
    bytes <= pre-optimization bytes."""
    art = _lower(_fused_program())
    assert art.plan_desc.count("collective") == 1
    assert "fused[2x allreduce" in art.plan_desc
    recs = art.attrs["collectives"]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["op"] == "fused_allreduce"
    assert rec["members"] == 2 and rec["slots"] == 2
    assert rec["wire_bytes"] <= rec["pre_opt_wire_bytes"]
    opt = art.attrs["comm_opt"]
    assert opt["post_wire_bytes"] <= opt["pre_wire_bytes"]
    assert opt["modes"] == ["fuse", "dce", "overlap"]
    assert any(r.startswith("fuse:") for r in opt["rewrites"])


def test_dedup_halves_wire_bytes():
    art = _lower(_dedup_program())
    opt = art.attrs["comm_opt"]
    # 3 broadcasts emitted, 1 distinct payload crosses the wire
    assert opt["post_wire_bytes"] * 3 == opt["pre_wire_bytes"]
    assert opt["hops_saved"] > 0
    # per-record pre-opt bytes (members + dropped duplicates) agree with
    # the program-level total
    recs = art.attrs["collectives"]
    assert sum(r.get("pre_opt_wire_bytes", r["wire_bytes"])
               for r in recs) == opt["pre_wire_bytes"]


def test_dedup_pair_leaves_single_member_fused_op():
    """A pure duplicate pair: the survivor becomes a 1-member fused op
    carrying the dropped duplicate's pre-optimization bytes."""
    def prog():
        with mesh_config(*MESH):
            @T.prim_func
            def k(A: _global(), B: _global()):
                with T.Kernel(1) as bx:
                    x = T.alloc_shared(SHAPE, "float32")
                    d = T.alloc_shared(SHAPE, "float32")
                    T.copy(A, x)
                    T.comm.broadcast(x, d, (0, 1), "h")
                    T.comm.broadcast(x, d, (0, 1), "h")  # exact dup
                    T.copy(d, B)
            return k
    art = _lower(prog())
    assert "fused[1x broadcast" in art.plan_desc
    rec = art.attrs["collectives"][0]
    assert rec["members"] == 1
    assert rec["pre_opt_wire_bytes"] == 2 * rec["wire_bytes"]
    opt = art.attrs["comm_opt"]
    assert opt["pre_wire_bytes"] == 2 * opt["post_wire_bytes"]
    _run_pair(prog, 8)


def test_dce_accounting_and_segment_merge():
    art = _lower(_dce_program())
    assert art.attrs["collectives"] == []
    assert "collective" not in art.plan_desc
    # the two compute segments merged back into ONE kernel
    assert art.plan_desc.count("pallas_segment") == 1
    opt = art.attrs["comm_opt"]
    assert opt["post_wire_bytes"] == 0 and opt["pre_wire_bytes"] > 0
    assert any(r.startswith("dce:") for r in opt["rewrites"])


def test_comm_opt_counters_and_metrics_summary():
    obs.reset()
    _lower(_fused_program())
    c = obs.get_tracer().counters()
    assert c["comm.opt.rewrites"] >= 1
    assert c["comm.opt.post_wire_bytes"] <= c["comm.opt.pre_wire_bytes"]
    summ = obs.metrics_summary()
    assert summ["collectives"]["post_opt_bytes"] <= \
        summ["collectives"]["pre_opt_bytes"]
    obs.reset()


def test_mesh_kernel_surfaces_comm_opt():
    kern = tilelang.compile(_fused_program(), target=TARGET)
    opt = kern.get_comm_opt()
    assert opt is not None
    assert opt["post_wire_bytes"] <= opt["pre_wire_bytes"]


def test_analyzer_trace_reports_comm_opt(tmp_path, monkeypatch):
    """analyzer trace surfaces the optimizer accounting from a JSONL
    trace (the PR-1 observability pipeline end to end)."""
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    obs.reset()
    _lower(_fused_program())
    p = tmp_path / "t.jsonl"
    obs.write_jsonl(p)
    from tilelang_mesh_tpu.tools.analyzer import (format_trace_report,
                                                  summarize_trace)
    records = obs.read_jsonl(p)
    rep = format_trace_report(records)
    assert "collective optimizer (comm_opt)" in rep
    assert "fused_allreduce" in rep
    s = summarize_trace(records)
    assert s["counters"]["comm.opt.rewrites"] >= 1
    obs.reset()


def test_emit_metadata_attached():
    """language/comm.py attaches emission metadata every optimizer
    consumer can key off (payload bytes + deterministic sequence)."""
    from tilelang_mesh_tpu.ir import CommStmt, collect
    pf = _fused_program()
    comms = collect(pf.func.body if hasattr(pf, "func") else pf.body,
                    lambda s: isinstance(s, CommStmt))
    assert len(comms) == 2
    for c in comms:
        assert c.emit_meta["op"] == "all_reduce"
        assert c.emit_meta["payload_bytes"] > 0
    assert comms[0].emit_meta["seq"] < comms[1].emit_meta["seq"]
