"""T.comm.* collective tests.

Mirrors reference testing/python/language/test_tilelang_language_comm.py:
(1) golden lowering structure (no device), (2) execution semantics on the
8-device virtual CPU mesh under shard_map.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.parallel import mesh_config
from tilelang_mesh_tpu.utils.tensor import assert_allclose

MESH = (2, 4)
NROW, NCOL = MESH
SHAPE = (8, 128)


def _compile(pf):
    return tilelang.compile(pf, target=f"cpu-mesh[{NROW}x{NCOL}]")


def _shards(rng):
    """One distinct local shard per core, assembled into the global array
    for a cross_mesh_dim=0 sharded input."""
    return rng.standard_normal((NROW * NCOL * SHAPE[0], SHAPE[1]),
                               ).astype(np.float32)


def _core_shard(x, r, c):
    n = SHAPE[0]
    i = r * NCOL + c
    return x[i * n:(i + 1) * n]


# ---- golden lowering (style 1: no device) ----------------------------------


def test_broadcast_golden_schedule():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                src = T.alloc_shared(SHAPE, "float32")
                dst = T.alloc_shared(SHAPE, "float32")
                T.copy(A, src)
                T.comm.broadcast(src, dst, (0, 1), "horizontal")
                T.copy(dst, B)

        art = tilelang.lower(k, target=f"cpu-mesh[{NROW}x{NCOL}]")
    desc = art.plan_desc
    assert "collective broadcast" in desc
    assert "src_core=(0, 1)" in desc
    assert "dir=h" in desc
    # compute segments on either side of the collective
    assert desc.count("pallas_segment") == 2


def test_allreduce_golden_schedule():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * SHAPE[0], 1),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                buf = T.alloc_fragment(SHAPE, "float32")
                out = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, buf)
                T.comm.all_reduce(buf, out, "sum", "all", dim=1)
                T.copy(out, B)

        art = tilelang.lower(k, target=f"cpu-mesh[{NROW}x{NCOL}]")
    assert "all_reduce" in art.plan_desc
    assert "op=sum" in art.plan_desc
    assert "dir=all" in art.plan_desc


# ---- execution semantics (8-device mesh) -----------------------------------


def _identity_comm_kernel(comm_body, out_shape=SHAPE):
    """Template: load per-core shard -> collective -> store result."""
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * out_shape[0], out_shape[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                src = T.alloc_shared(SHAPE, "float32")
                dst = T.alloc_shared(out_shape, "float32")
                T.copy(A, src)
                comm_body(src, dst)
                T.copy(dst, B)
        return _compile(k)


def test_broadcast_horizontal_exec():
    def body(src, dst):
        T.comm.fence()
        T.comm.broadcast(src, dst, (1, 2), "h")
        T.comm.barrier()
    k = _identity_comm_kernel(body)
    rng = np.random.default_rng(0)
    a = _shards(rng)
    out = np.asarray(k(a))
    src_val = _core_shard(a, 1, 2)
    for r in range(NROW):
        for c in range(NCOL):
            got = _core_shard(out, r, c)
            if r == 1:  # source row receives
                assert_allclose(got, src_val, rtol=1e-6, atol=1e-6)
            else:       # others keep dst contents (zero-init fragments)
                assert np.allclose(got, 0)


def test_broadcast_all_exec():
    def body(src, dst):
        T.comm.broadcast(src, dst, (0, 3), "all")
    k = _identity_comm_kernel(body)
    rng = np.random.default_rng(1)
    a = _shards(rng)
    out = np.asarray(k(a))
    src_val = _core_shard(a, 0, 3)
    for r in range(NROW):
        for c in range(NCOL):
            assert_allclose(_core_shard(out, r, c), src_val,
                            rtol=1e-6, atol=1e-6)


def test_put_exec():
    def body(src, dst):
        T.comm.put(src, dst, (0, 0), (1, 3))
    k = _identity_comm_kernel(body)
    rng = np.random.default_rng(2)
    a = _shards(rng)
    out = np.asarray(k(a))
    for r in range(NROW):
        for c in range(NCOL):
            got = _core_shard(out, r, c)
            if (r, c) == (1, 3):
                assert_allclose(got, _core_shard(a, 0, 0), rtol=1e-6,
                                atol=1e-6)
            else:
                assert np.allclose(got, 0)


def test_all_gather_horizontal_exec():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL, NCOL, SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                send = T.alloc_shared(SHAPE, "float32")
                recv = T.alloc_shared((NCOL, *SHAPE), "float32")
                T.copy(A, send)
                T.comm.all_gather(send, recv, "h")
                T.copy(recv, B[0, 0, 0])
        kern = _compile(k)
    rng = np.random.default_rng(3)
    a = _shards(rng)
    out = np.asarray(kern(a))  # (NROW*NCOL, NCOL, 8, 128)
    for r in range(NROW):
        for c in range(NCOL):
            got = out[r * NCOL + c]
            for cc in range(NCOL):
                assert_allclose(got[cc], _core_shard(a, r, cc),
                                rtol=1e-6, atol=1e-6)


def test_all_reduce_sum_all_exec():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * SHAPE[0], 1),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                buf = T.alloc_fragment(SHAPE, "float32")
                out = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, buf)
                T.comm.all_reduce(buf, out, "sum", "all", dim=1)
                T.copy(out, B)
        kern = _compile(k)
    rng = np.random.default_rng(4)
    a = _shards(rng)
    out = np.asarray(kern(a))
    # every core ends with the same value: sum over all cores of rowsum
    expected = np.zeros((SHAPE[0], 1), np.float32)
    for r in range(NROW):
        for c in range(NCOL):
            expected += _core_shard(a, r, c).sum(1, keepdims=True)
    n = SHAPE[0]
    for i in range(NROW * NCOL):
        assert_allclose(out[i * n:(i + 1) * n], expected, rtol=1e-4,
                        atol=1e-4)


def test_all_reduce_max_vertical_exec():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * SHAPE[0], 1),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                buf = T.alloc_fragment(SHAPE, "float32")
                out = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, buf)
                T.comm.all_reduce(buf, out, "max", "v", dim=1)
                T.copy(out, B)
        kern = _compile(k)
    rng = np.random.default_rng(5)
    a = _shards(rng)
    out = np.asarray(kern(a))
    n = SHAPE[0]
    for r in range(NROW):
        for c in range(NCOL):
            expected = np.maximum.reduce([
                _core_shard(a, rr, c).max(1, keepdims=True)
                for rr in range(NROW)])
            got = out[(r * NCOL + c) * n:(r * NCOL + c + 1) * n]
            assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


# ---- frontend validation (mirrors reference comm.py asserts) ---------------


def test_comm_shape_validation():
    with mesh_config(*MESH):
        with pytest.raises(AssertionError):
            @T.prim_func
            def bad(A: T.Tensor((8, 128), "float32")):
                with T.Kernel(1) as bx:
                    s = T.alloc_shared((8, 128), "float32")
                    d = T.alloc_shared((8, 64), "float32")  # dtype ok, shape bad
                    T.comm.all_gather(s, d, "h")


def test_comm_core_bounds():
    with mesh_config(*MESH):
        with pytest.raises(AssertionError):
            @T.prim_func
            def bad(A: T.Tensor((8, 128), "float32")):
                with T.Kernel(1) as bx:
                    s = T.alloc_shared((8, 128), "float32")
                    d = T.alloc_shared((8, 128), "float32")
                    T.comm.broadcast(s, d, (5, 0), "all")


def test_comm_reduce_type_validation():
    with mesh_config(*MESH):
        with pytest.raises(AssertionError):
            @T.prim_func
            def bad(A: T.Tensor((8, 128), "float32")):
                with T.Kernel(1) as bx:
                    s = T.alloc_shared((8, 128), "float32")
                    o = T.alloc_shared((8, 1), "float32")
                    T.comm.all_reduce(s, o, "mean", "all")
