"""T.comm.* collective tests.

Mirrors reference testing/python/language/test_tilelang_language_comm.py:
(1) golden lowering structure (no device), (2) execution semantics on the
8-device virtual CPU mesh under shard_map.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.parallel import mesh_config
from tilelang_mesh_tpu.utils.tensor import assert_allclose

MESH = (2, 4)
NROW, NCOL = MESH
SHAPE = (8, 128)


def _compile(pf):
    return tilelang.compile(pf, target=f"cpu-mesh[{NROW}x{NCOL}]")


def _shards(rng):
    """One distinct local shard per core, assembled into the global array
    for a cross_mesh_dim=0 sharded input."""
    return rng.standard_normal((NROW * NCOL * SHAPE[0], SHAPE[1]),
                               ).astype(np.float32)


def _core_shard(x, r, c):
    n = SHAPE[0]
    i = r * NCOL + c
    return x[i * n:(i + 1) * n]


# ---- golden lowering (style 1: no device) ----------------------------------
#
# Full schedule-text comparisons on two mesh shapes, replacing the round-1/2
# keyword greps — the analog of the reference's lowered-IR goldens
# (/root/reference/testing/python/language/test_tilelang_language_comm.py:
# 55-103, where BindTarget(Sunmmio)+LowerTileOp output is compared against
# the expected T.broadcast_ sequence). A schedule regression now changes
# these texts, not just a keyword.


def _bcast_program(mesh):
    nrow, ncol = mesh
    with mesh_config(*mesh):
        @T.prim_func
        def k(A: T.MeshTensor((nrow * ncol * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              mesh, "float32"),
              B: T.MeshTensor((nrow * ncol * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              mesh, "float32")):
            with T.Kernel(1) as bx:
                src = T.alloc_shared(SHAPE, "float32")
                dst = T.alloc_shared(SHAPE, "float32")
                T.copy(A, src)
                T.comm.broadcast(src, dst, (0, 1), "horizontal")
                T.copy(dst, B)
        return tilelang.lower(k, target=f"cpu-mesh[{nrow}x{ncol}]")


def _allgather_program(mesh, direction):
    nrow, ncol = mesh
    n = {"h": ncol, "v": nrow, "all": nrow * ncol}[direction]
    with mesh_config(*mesh):
        @T.prim_func
        def k(A: T.MeshTensor((nrow * ncol * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              mesh, "float32"),
              B: T.MeshTensor((nrow * ncol, n, SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              mesh, "float32")):
            with T.Kernel(1) as bx:
                send = T.alloc_shared(SHAPE, "float32")
                recv = T.alloc_shared((n, *SHAPE), "float32")
                T.copy(A, send)
                T.comm.all_gather(send, recv, direction)
                T.copy(recv, B[0, 0, 0])
        return tilelang.lower(k, target=f"cpu-mesh[{nrow}x{ncol}]")


def _allreduce_program(mesh, direction):
    nrow, ncol = mesh
    with mesh_config(*mesh):
        @T.prim_func
        def k(A: T.MeshTensor((nrow * ncol * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              mesh, "float32"),
              B: T.MeshTensor((nrow * ncol * SHAPE[0], 1),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              mesh, "float32")):
            with T.Kernel(1) as bx:
                buf = T.alloc_fragment(SHAPE, "float32")
                out = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, buf)
                T.comm.all_reduce(buf, out, "sum", direction, dim=1)
                T.copy(out, B)
        return tilelang.lower(k, target=f"cpu-mesh[{nrow}x{ncol}]")


def test_broadcast_golden_schedule_2x4():
    assert _bcast_program((2, 4)).plan_desc == """\
mesh_program(k) mesh=(2x4) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(shared_lo)
  [1] collective broadcast(shared -> shared_1, src_core=(0, 1), dir=h)
        noc[0]: bcast core(0, 1) dir=h chunk=0
        cost: 1 steps, 2 hops
        xla: psum(mask(core==(0, 1)), 'y') -> row 0
  [2] pallas_segment k_seg2 grid=(1,) ins=(shared_1_li) outs=(B)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None)
"""


def test_broadcast_golden_schedule_2x2():
    assert _bcast_program((2, 2)).plan_desc == """\
mesh_program(k) mesh=(2x2) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(shared_lo)
  [1] collective broadcast(shared -> shared_1, src_core=(0, 1), dir=h)
        noc[0]: bcast core(0, 1) dir=h chunk=0
        cost: 1 steps, 1 hops
        xla: psum(mask(core==(0, 1)), 'y') -> row 0
  [2] pallas_segment k_seg2 grid=(1,) ins=(shared_1_li) outs=(B)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None)
"""


def test_allgather_golden_schedule_2x4_h():
    assert _allgather_program((2, 4), "h").plan_desc == """\
mesh_program(k) mesh=(2x4) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(shared_lo)
  [1] collective all_gather(shared -> shared_1, dir=h)
        noc[0]: bcast core(0, 0) dir=h chunk=0
        noc[1]: bcast core(0, 1) dir=h chunk=1
        noc[2]: bcast core(0, 2) dir=h chunk=2
        noc[3]: bcast core(0, 3) dir=h chunk=3
        noc[4]: bcast core(1, 0) dir=h chunk=0
        noc[5]: bcast core(1, 1) dir=h chunk=1
        noc[6]: bcast core(1, 2) dir=h chunk=2
        noc[7]: bcast core(1, 3) dir=h chunk=3
        cost: 8 steps, 20 hops
        xla: all_gather(axis='y')
  [2] pallas_segment k_seg2 grid=(1,) ins=(shared_1_li) outs=(B)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None, None, None)
"""


def test_allgather_golden_schedule_2x2_all():
    """2-D 'all' = horizontal phase then vertical phase of row bundles
    (cf. reference comm.cc:556-596)."""
    assert _allgather_program((2, 2), "all").plan_desc == """\
mesh_program(k) mesh=(2x2) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(shared_lo)
  [1] collective all_gather(shared -> shared_1, dir=all)
        noc[0]: bcast core(0, 0) dir=h chunk=0
        noc[1]: bcast core(0, 1) dir=h chunk=1
        noc[2]: bcast core(1, 0) dir=h chunk=0
        noc[3]: bcast core(1, 1) dir=h chunk=1
        noc[4]: bcast core(0, 0) dir=v chunk=0
        noc[5]: bcast core(1, 0) dir=v chunk=1
        noc[6]: bcast core(0, 1) dir=v chunk=0
        noc[7]: bcast core(1, 1) dir=v chunk=1
        cost: 8 steps, 8 hops
        xla: all_gather(axis=('x', 'y'))
  [2] pallas_segment k_seg2 grid=(1,) ins=(shared_1_li) outs=(B)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None, None, None)
"""


def test_allreduce_golden_schedule_2x4_all():
    """all_reduce 'all' = local reduce + row gather/reduce + column
    gather/reduce (cf. reference comm.cc:783-918)."""
    assert _allreduce_program((2, 4), "all").plan_desc == """\
mesh_program(k) mesh=(2x4) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(frag_lo)
  [1] collective all_reduce(frag -> frag_1, op=sum, dir=all, dim=1, clear=True)
        noc[0]: bcast core(0, 0) dir=h chunk=0
        noc[1]: bcast core(0, 1) dir=h chunk=1
        noc[2]: bcast core(0, 2) dir=h chunk=2
        noc[3]: bcast core(0, 3) dir=h chunk=3
        noc[4]: bcast core(1, 0) dir=h chunk=0
        noc[5]: bcast core(1, 1) dir=h chunk=1
        noc[6]: bcast core(1, 2) dir=h chunk=2
        noc[7]: bcast core(1, 3) dir=h chunk=3
        noc[8]: bcast core(0, 0) dir=v chunk=0
        noc[9]: bcast core(1, 0) dir=v chunk=1
        noc[10]: bcast core(0, 1) dir=v chunk=0
        noc[11]: bcast core(1, 1) dir=v chunk=1
        noc[12]: bcast core(0, 2) dir=v chunk=0
        noc[13]: bcast core(1, 2) dir=v chunk=1
        noc[14]: bcast core(0, 3) dir=v chunk=0
        noc[15]: bcast core(1, 3) dir=v chunk=1
        cost: 16 steps, 28 hops
        xla: local reduce(dim=1) + psum(axis=('x', 'y'))
  [2] pallas_segment k_seg2 grid=(1,) ins=(frag_1_li) outs=(B)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None)
"""


def test_allreduce_golden_schedule_2x2_h():
    assert _allreduce_program((2, 2), "h").plan_desc == """\
mesh_program(k) mesh=(2x2) axes=(x,y):
  [0] pallas_segment k_seg0 grid=(1,) ins=(A) outs=(frag_lo)
  [1] collective all_reduce(frag -> frag_1, op=sum, dir=h, dim=1, clear=True)
        noc[0]: bcast core(0, 0) dir=h chunk=0
        noc[1]: bcast core(0, 1) dir=h chunk=1
        noc[2]: bcast core(1, 0) dir=h chunk=0
        noc[3]: bcast core(1, 1) dir=h chunk=1
        cost: 4 steps, 4 hops
        xla: local reduce(dim=1) + psum(axis='y')
  [2] pallas_segment k_seg2 grid=(1,) ins=(frag_1_li) outs=(B)
  param A: role=in spec=PartitionSpec(('x', 'y'), None)
  param B: role=out spec=PartitionSpec(('x', 'y'), None)
"""


# ---- execution semantics (8-device mesh) -----------------------------------


def _identity_comm_kernel(comm_body, out_shape=SHAPE):
    """Template: load per-core shard -> collective -> store result."""
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * out_shape[0], out_shape[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                src = T.alloc_shared(SHAPE, "float32")
                dst = T.alloc_shared(out_shape, "float32")
                T.copy(A, src)
                comm_body(src, dst)
                T.copy(dst, B)
        return _compile(k)


def test_broadcast_horizontal_exec():
    def body(src, dst):
        T.comm.fence()
        T.comm.broadcast(src, dst, (1, 2), "h")
        T.comm.barrier()
    k = _identity_comm_kernel(body)
    rng = np.random.default_rng(0)
    a = _shards(rng)
    out = np.asarray(k(a))
    src_val = _core_shard(a, 1, 2)
    for r in range(NROW):
        for c in range(NCOL):
            got = _core_shard(out, r, c)
            if r == 1:  # source row receives
                assert_allclose(got, src_val, rtol=1e-6, atol=1e-6)
            else:       # others keep dst contents (zero-init fragments)
                assert np.allclose(got, 0)


def test_broadcast_all_exec():
    def body(src, dst):
        T.comm.broadcast(src, dst, (0, 3), "all")
    k = _identity_comm_kernel(body)
    rng = np.random.default_rng(1)
    a = _shards(rng)
    out = np.asarray(k(a))
    src_val = _core_shard(a, 0, 3)
    for r in range(NROW):
        for c in range(NCOL):
            assert_allclose(_core_shard(out, r, c), src_val,
                            rtol=1e-6, atol=1e-6)


def test_put_exec():
    def body(src, dst):
        T.comm.put(src, dst, (0, 0), (1, 3))
    k = _identity_comm_kernel(body)
    rng = np.random.default_rng(2)
    a = _shards(rng)
    out = np.asarray(k(a))
    for r in range(NROW):
        for c in range(NCOL):
            got = _core_shard(out, r, c)
            if (r, c) == (1, 3):
                assert_allclose(got, _core_shard(a, 0, 0), rtol=1e-6,
                                atol=1e-6)
            else:
                assert np.allclose(got, 0)


def test_all_gather_horizontal_exec():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL, NCOL, SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                send = T.alloc_shared(SHAPE, "float32")
                recv = T.alloc_shared((NCOL, *SHAPE), "float32")
                T.copy(A, send)
                T.comm.all_gather(send, recv, "h")
                T.copy(recv, B[0, 0, 0])
        kern = _compile(k)
    rng = np.random.default_rng(3)
    a = _shards(rng)
    out = np.asarray(kern(a))  # (NROW*NCOL, NCOL, 8, 128)
    for r in range(NROW):
        for c in range(NCOL):
            got = out[r * NCOL + c]
            for cc in range(NCOL):
                assert_allclose(got[cc], _core_shard(a, r, cc),
                                rtol=1e-6, atol=1e-6)


def test_all_reduce_sum_all_exec():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * SHAPE[0], 1),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                buf = T.alloc_fragment(SHAPE, "float32")
                out = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, buf)
                T.comm.all_reduce(buf, out, "sum", "all", dim=1)
                T.copy(out, B)
        kern = _compile(k)
    rng = np.random.default_rng(4)
    a = _shards(rng)
    out = np.asarray(kern(a))
    # every core ends with the same value: sum over all cores of rowsum
    expected = np.zeros((SHAPE[0], 1), np.float32)
    for r in range(NROW):
        for c in range(NCOL):
            expected += _core_shard(a, r, c).sum(1, keepdims=True)
    n = SHAPE[0]
    for i in range(NROW * NCOL):
        assert_allclose(out[i * n:(i + 1) * n], expected, rtol=1e-4,
                        atol=1e-4)


def test_all_reduce_max_vertical_exec():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * SHAPE[0], 1),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                buf = T.alloc_fragment(SHAPE, "float32")
                out = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, buf)
                T.comm.all_reduce(buf, out, "max", "v", dim=1)
                T.copy(out, B)
        kern = _compile(k)
    rng = np.random.default_rng(5)
    a = _shards(rng)
    out = np.asarray(kern(a))
    n = SHAPE[0]
    for r in range(NROW):
        for c in range(NCOL):
            expected = np.maximum.reduce([
                _core_shard(a, rr, c).max(1, keepdims=True)
                for rr in range(NROW)])
            got = out[(r * NCOL + c) * n:(r * NCOL + c + 1) * n]
            assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


# ---- frontend validation (mirrors reference comm.py asserts) ---------------


def test_comm_shape_validation():
    with mesh_config(*MESH):
        with pytest.raises(AssertionError):
            @T.prim_func
            def bad(A: T.Tensor((8, 128), "float32")):
                with T.Kernel(1) as bx:
                    s = T.alloc_shared((8, 128), "float32")
                    d = T.alloc_shared((8, 64), "float32")  # dtype ok, shape bad
                    T.comm.all_gather(s, d, "h")


def test_comm_core_bounds():
    with mesh_config(*MESH):
        with pytest.raises(AssertionError):
            @T.prim_func
            def bad(A: T.Tensor((8, 128), "float32")):
                with T.Kernel(1) as bx:
                    s = T.alloc_shared((8, 128), "float32")
                    d = T.alloc_shared((8, 128), "float32")
                    T.comm.broadcast(s, d, (5, 0), "all")


def test_comm_reduce_type_validation():
    with mesh_config(*MESH):
        with pytest.raises(AssertionError):
            @T.prim_func
            def bad(A: T.Tensor((8, 128), "float32")):
                with T.Kernel(1) as bx:
                    s = T.alloc_shared((8, 128), "float32")
                    o = T.alloc_shared((8, 1), "float32")
                    T.comm.all_reduce(s, o, "mean", "all")


def test_mesh_analyzer_rooflines_collectives():
    """Analyzer.analysis_mesh: compute segments via the per-core
    roofline, collectives via the NoC schedule's hop cost."""
    from tilelang_mesh_tpu.tools.analyzer import Analyzer
    art = _allreduce_program((2, 4), "all")
    res = Analyzer.analysis_mesh(art)
    assert res.n_collectives == 1
    assert res.comm_ms > 0 and res.compute_ms > 0
    assert res.expected_latency_ms == res.comm_ms + res.compute_ms
    assert res.bound in ("comm", "compute")
    # a smaller mesh with a row-only reduce synthesizes fewer hops, so
    # its collective costs less under the same chip model
    art2 = _allreduce_program((2, 2), "h")
    res2 = Analyzer.analysis_mesh(art2)
    assert res2.n_collectives == 1
    assert res2.comm_ms < res.comm_ms


def test_comm_cost_contract():
    """comm_cost: per-hop wire payloads, zero-cost barriers, and a loud
    error for unknown collective types (no silent mis-costing)."""
    from tilelang_mesh_tpu.ir import (Buffer, CommAllReduce, CommBarrier,
                                      CommStmt, Region)
    from tilelang_mesh_tpu.parallel.lowering import (MeshLowerError,
                                                     comm_cost)

    buf = Buffer("b", (8, 128), "float32", "fragment")
    out = Buffer("o", (8, 1), "float32", "fragment")
    ar = CommAllReduce(Region(buf, (0, 0), (8, 128)),
                       Region(out, (0, 0), (8, 1)), "sum", 2, 1, True)
    hops, payload = comm_cost(ar, 2, 4)
    assert payload == 8 * 1 * 4          # the reduced chunk, not the input
    assert hops == 28                    # matches the golden schedule

    assert comm_cost(CommBarrier(), 2, 4) == (0, 0)

    class Mystery(CommStmt):
        pass

    with pytest.raises(MeshLowerError, match="no cost model"):
        comm_cost(Mystery(), 2, 4)


@pytest.mark.parametrize("seed", range(6))
def test_random_collective_chains(seed):
    """Randomized sequences of shape-preserving collectives (broadcast /
    put) chained through ping-pong buffers in ONE kernel, executed on
    the 8-device mesh and checked against a per-core numpy model —
    composition coverage beyond the single-collective exec tests."""
    rng = np.random.default_rng(3000 + seed)
    n_ops = int(rng.integers(2, 5))
    ops = []
    for _ in range(n_ops):
        kind = rng.choice(["broadcast", "put"])
        src = (int(rng.integers(0, NROW)), int(rng.integers(0, NCOL)))
        if kind == "broadcast":
            d = str(rng.choice(["h", "v", "all"]))
            ops.append(("broadcast", src, d))
        else:
            dst = (int(rng.integers(0, NROW)), int(rng.integers(0, NCOL)))
            ops.append(("put", src, dst))

    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                x = T.alloc_shared(SHAPE, "float32")
                y = T.alloc_shared(SHAPE, "float32")
                T.copy(A, x)
                for op in ops:
                    # seed dst with the local value: collectives define
                    # dst only on participating cores
                    T.copy(x, y)
                    if op[0] == "broadcast":
                        T.comm.broadcast(x, y, op[1], op[2])
                    else:
                        T.comm.put(x, y, op[1], op[2])
                    T.comm.barrier()
                    T.copy(y, x)
                T.copy(x, B)
        kern = _compile(k)

    rng2 = np.random.default_rng(seed)
    a = _shards(rng2)
    out = np.asarray(kern(a))

    # numpy per-core model
    state = {(r, c): _core_shard(a, r, c).copy()
             for r in range(NROW) for c in range(NCOL)}
    for op in ops:
        new = {rc: v.copy() for rc, v in state.items()}
        if op[0] == "broadcast":
            (r0, c0), d = op[1], op[2]
            val = state[(r0, c0)]
            for r in range(NROW):
                for c in range(NCOL):
                    if (d == "h" and r == r0) or (d == "v" and c == c0) \
                            or d == "all":
                        new[(r, c)] = val.copy()
        else:
            src, dst = op[1], op[2]
            new[dst] = state[src].copy()
        state = new
    for r in range(NROW):
        for c in range(NCOL):
            got = out[(r * NCOL + c) * SHAPE[0]:
                      (r * NCOL + c + 1) * SHAPE[0]]
            np.testing.assert_allclose(
                got, state[(r, c)], rtol=1e-6, atol=1e-6,
                err_msg=f"core ({r},{c}) after {ops}")
