"""w4a8 dequant GEMM: int8 activations x planar int4 weights on the
int8 MXU path (reference examples/dequantize_gemm/
example_dequant_gemm_w4a8.py capability).

Correctness bar: the kernel must be EXACT against the integer-math
reference (the whole K reduction is int32; the only float op is the
scale epilogue). Accuracy vs f32 is a property of the quantizer, not
the kernel, and gets a loose sanity bound only."""

import numpy as np
import pytest

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.bitnet import quantize_activations
from tilelang_mesh_tpu.ops.dequant_gemm import (pack_planar, pack_reference,
                                                quantize_w4_per_channel,
                                                repack_from_reference,
                                                unpack_planar, w4a8_matmul)


def _int_reference(x, packed, sw):
    """Exact integer-math reference of the w4a8 contract."""
    q, s = quantize_activations(jnp.asarray(x))
    wd = np.concatenate([(packed.astype(np.int32) & 0xF) - 8,
                         (packed.astype(np.int32) >> 4) - 8], 0)
    acc = np.asarray(q, np.int64) @ wd            # exact int
    return acc.astype(np.float64) / np.asarray(s, np.float64) * sw


@pytest.mark.parametrize("M,N,K", [(128, 256, 512), (64, 128, 256)])
def test_w4a8_exact_vs_int_reference(M, N, K):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((M, K)).astype(np.float32)
    w = rng.standard_normal((K, N)).astype(np.float32) * 0.1
    packed, sw = quantize_w4_per_channel(w)
    out = np.asarray(w4a8_matmul(jnp.asarray(x), packed, sw))
    ref = _int_reference(x, packed, sw)
    rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
    assert rel < 1e-5, rel


def test_w4a8_tracks_f32_gemm_loosely():
    """Quantizer sanity: int4-per-channel + int8-per-token lands within
    coarse range of the f32 product on Gaussian data."""
    rng = np.random.default_rng(1)
    M, N, K = 128, 128, 512
    x = rng.standard_normal((M, K)).astype(np.float32)
    w = rng.standard_normal((K, N)).astype(np.float32) * 0.05
    packed, sw = quantize_w4_per_channel(w)
    out = np.asarray(w4a8_matmul(jnp.asarray(x), packed, sw))
    full = x @ w
    rel = np.linalg.norm(out - full) / np.linalg.norm(full)
    assert rel < 0.25, rel


def test_repack_from_reference_roundtrip():
    """Reference layout (per-row K-interleaved, two's-complement
    nibbles) -> planar +8-bias layout is an exact byte permutation:
    repack(pack_reference(q)) == pack_planar(q) for every int4 value,
    and both layouts unpack to the same q."""
    rng = np.random.default_rng(3)
    q = rng.integers(-8, 8, size=(64, 48), dtype=np.int64).astype(np.int32)
    ref_packed = pack_reference(q)
    assert ref_packed.shape == (32, 48) and ref_packed.dtype == np.uint8
    # reference nibble layout: row 2k low, row 2k+1 high, two's complement
    assert (ref_packed[0] & 0xF == (q[0] & 0xF)).all()
    assert ((ref_packed[0] >> 4) == (q[1] & 0xF)).all()
    planar = repack_from_reference(ref_packed)
    np.testing.assert_array_equal(planar, pack_planar(q))
    np.testing.assert_array_equal(unpack_planar(planar), q)
    # full boundary coverage: every nibble value survives the round trip
    edge = np.tile(np.arange(-8, 8, dtype=np.int32), 2).reshape(2, 16)
    edge = np.repeat(edge, 8, axis=0)   # (16, 16), K even
    np.testing.assert_array_equal(
        unpack_planar(repack_from_reference(pack_reference(edge))), edge)


def test_w4a8_matmul_accepts_repacked_reference_weights():
    """A reference-packed checkpoint run through repack_from_reference
    must produce bit-identical GEMM results to native planar packing."""
    rng = np.random.default_rng(4)
    M, N, K = 64, 128, 256
    x = rng.standard_normal((M, K)).astype(np.float32)
    w = rng.standard_normal((K, N)).astype(np.float32) * 0.1
    packed, sw = quantize_w4_per_channel(w)
    ref_packed = pack_reference(unpack_planar(packed))
    out_native = np.asarray(w4a8_matmul(jnp.asarray(x), packed, sw))
    out_repacked = np.asarray(
        w4a8_matmul(jnp.asarray(x), repack_from_reference(ref_packed), sw))
    np.testing.assert_array_equal(out_native, out_repacked)


def test_w4_pack_roundtrip():
    rng = np.random.default_rng(2)
    w = rng.standard_normal((64, 32)).astype(np.float32)
    packed, sw = quantize_w4_per_channel(w)
    assert packed.shape == (32, 32) and packed.dtype == np.uint8
    lo = (packed.astype(np.int32) & 0xF) - 8
    hi = (packed.astype(np.int32) >> 4) - 8
    wd = np.concatenate([lo, hi], 0) * sw
    # dequantized weights within one quantization step everywhere
    assert np.abs(wd - w).max() <= sw.max() * 1.001
