"""Gated DeltaNet chunked forward vs the sequential delta rule
(reference examples/gdn behavior)."""

import numpy as np

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gdn import gdn_chunk_fwd, gdn_reference
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def _inputs(B, H, T, K, V, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, H, T, K)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, T, K)), jnp.float32)
    k = k / jnp.linalg.norm(k, axis=-1, keepdims=True)   # l2-normalized keys
    v = jnp.asarray(rng.standard_normal((B, H, T, V)), jnp.float32)
    g = jnp.asarray(rng.uniform(-0.2, 0.0, (B, H, T)), jnp.float32)
    beta = jnp.asarray(rng.uniform(0.0, 1.0, (B, H, T)), jnp.float32)
    return q, k, v, g, beta


def test_gdn_chunk_matches_sequential():
    B, H, T, K, V = 1, 2, 128, 32, 32
    q, k, v, g, beta = _inputs(B, H, T, K, V)
    out = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=32)
    ref = gdn_reference(q, k, v, g, beta)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_gdn_final_state():
    B, H, T, K, V = 1, 1, 64, 16, 16
    q, k, v, g, beta = _inputs(B, H, T, K, V, seed=1)
    out, h = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=16,
                           output_final_state=True)
    ref, h_ref = gdn_reference(q, k, v, g, beta, output_final_state=True)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)
    assert_allclose(np.asarray(h), np.asarray(h_ref), rtol=2e-2, atol=2e-2)


def test_gdn_initial_state():
    B, H, T, K, V = 1, 1, 32, 16, 16
    q, k, v, g, beta = _inputs(B, H, T, K, V, seed=2)
    rng = np.random.default_rng(3)
    h0 = jnp.asarray(rng.standard_normal((B, H, K, V)) * 0.1, jnp.float32)
    out = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=16, initial_state=h0)
    ref = gdn_reference(q, k, v, g, beta, initial_state=h0)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_gdn_chunk_size_invariance():
    B, H, T, K, V = 1, 1, 64, 16, 16
    q, k, v, g, beta = _inputs(B, H, T, K, V, seed=4)
    o16 = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=16)
    o64 = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=64)
    assert_allclose(np.asarray(o16), np.asarray(o64), rtol=1e-3, atol=1e-3)


def test_gdn_tile_kernel_matches_sequential():
    """The tile-DSL GDN kernel (Neumann-doubling WY inverse, in-kernel
    chunk recurrence) matches the sequential delta rule."""
    from tilelang_mesh_tpu.ops.gdn import gdn_chunk_fwd_tl
    B, H, T, K, V = 1, 2, 128, 32, 32
    q, k, v, g, beta = _inputs(B, H, T, K, V, seed=5)
    out = gdn_chunk_fwd_tl(q, k, v, g, beta, chunk_size=32)
    ref = gdn_reference(q, k, v, g, beta)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_gdn_tile_kernel_chunk_invariance():
    """chunk=16 vs chunk=64 must agree (cross-chunk state carry +
    doubling-iteration count both vary with C)."""
    from tilelang_mesh_tpu.ops.gdn import gdn_chunk_fwd_tl
    B, H, T, K, V = 1, 1, 128, 32, 16
    q, k, v, g, beta = _inputs(B, H, T, K, V, seed=6)
    o16 = gdn_chunk_fwd_tl(q, k, v, g, beta, chunk_size=16)
    o64 = gdn_chunk_fwd_tl(q, k, v, g, beta, chunk_size=64)
    assert_allclose(np.asarray(o16), np.asarray(o64), rtol=1e-3, atol=1e-3)


def test_gdn_tile_kernel_matches_xla_chunked():
    """Tile kernel vs the XLA WY implementation (the benchmark's A/B
    pair, bench.py cfg_gdn_fwd) on identical inputs."""
    from tilelang_mesh_tpu.ops.gdn import gdn_chunk_fwd_tl
    B, H, T, K, V = 2, 2, 256, 64, 64
    q, k, v, g, beta = _inputs(B, H, T, K, V, seed=7)
    out = gdn_chunk_fwd_tl(q, k, v, g, beta, chunk_size=64)
    ref = gdn_chunk_fwd(q, k, v, g, beta, chunk_size=64)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)
