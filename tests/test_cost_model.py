"""Cost-model-guided autotuning + fleet tune cache (docs/autotuning.md).

Covers the four contracts the subsystem makes:

- **feature extraction is deterministic and executes nothing** — two
  lowerings of one kernel yield byte-identical feature dicts, and a
  GEMM's modeled FLOPs are exact;
- **the model never discards the true best** — cold models run the full
  sweep, warm models keep the winner in the measured set on the seeded
  synthetic sweep, and a ranking that disagrees with measurement falls
  back to measuring everything;
- **``TL_TPU_TUNE=bruteforce`` restores pre-model behavior** — every
  config measured, no tune-cache consults, no model fields in the
  records;
- **the tune cache is crash-safe and mergeable** — checksummed entries,
  corruption quarantined (never trusted), commutative merges where the
  per-config best wins, and a completed sweep warm-starting a second
  tuner (and serving ``warmup()``) with ZERO measurements.
"""

import json

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.env import env
from tilelang_mesh_tpu.observability import get_tracer


@pytest.fixture(autouse=True)
def _isolated_dirs(monkeypatch, tmp_path):
    # the fleet tune cache derives from the autotune dir, so one var
    # isolates both tiers per test (warm entries from an earlier test
    # must never change a later test's trial counts)
    monkeypatch.setenv("TL_TPU_AUTOTUNE_CACHE_DIR",
                       str(tmp_path / "autotune"))
    monkeypatch.delenv("TL_TPU_TUNE_CACHE_DIR", raising=False)
    monkeypatch.delenv("TL_TPU_TUNE", raising=False)
    yield


def _make_factory():
    """A tiny tunable copy kernel; every call returns a fresh jit
    factory with IDENTICAL source, so fleet-tier source keying works."""
    @tilelang.jit
    def tune_fac(M, N, block_M=32):
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(T.ceildiv(M, block_M)) as bx:
                s = T.alloc_shared((block_M, N), "float32")
                T.copy(A[bx * block_M, 0], s)
                T.copy(s, B[bx * block_M, 0])
        return k
    return tune_fac


def _fake_bench(monkeypatch, lat_of):
    """Replace Profiler.do_bench with a deterministic latency function
    of the measured kernel's compile-time features — measurement noise
    must not decide these tests."""
    from tilelang_mesh_tpu.autotuner.cost_model import features_from_kernel
    from tilelang_mesh_tpu.profiler import Profiler

    def fake(self, func=None, warmup=3, rep=30, backend="loop",
             input_tensors=None):
        feats = features_from_kernel(self.kernel)
        assert feats is not None, "measured kernel must carry features"
        return float(lat_of(feats))

    monkeypatch.setattr(Profiler, "do_bench", fake)


# ---------------------------------------------------------------------------
# feature extraction
# ---------------------------------------------------------------------------

class TestFeatures:
    def test_extraction_deterministic(self):
        from tilelang_mesh_tpu.engine.lower import lower
        fac = _make_factory()
        pf = fac(64, 128, block_M=32).prim_func
        f1 = lower(pf, target="cpu").attrs["features"]
        f2 = lower(pf, target="cpu").attrs["features"]
        assert f1 == f2
        assert json.dumps(f1, sort_keys=True) == \
            json.dumps(f2, sort_keys=True)

    def test_copy_kernel_features(self):
        from tilelang_mesh_tpu.transform.plan import FEATURES_VERSION
        fac = _make_factory()
        feats = fac(128, 128, block_M=32).artifact.attrs["features"]
        assert feats["version"] == FEATURES_VERSION
        assert feats["flops"] == 0
        assert feats["hbm_bytes"] >= 2 * 128 * 128 * 4   # A in + B out
        assert feats["grid_steps"] == 4                  # 128 / 32
        assert feats["block_rows"] == 32
        assert feats["block_cols"] == 128
        assert feats["dbuf_chains"] == 0

    def test_gemm_flops_exact(self):
        from tilelang_mesh_tpu.ops.gemm import matmul_kernel
        k = matmul_kernel(128, 128, 128, block_M=64, block_N=64,
                          block_K=64, in_dtype="float32",
                          out_dtype="float32")
        feats = k.artifact.attrs["features"]
        assert feats["flops"] == 2 * 128 * 128 * 128
        # the dispatch grid (incl. a grid-mapped pipelined axis) is what
        # the artifact reports
        assert feats["grid_steps"] == int(np.prod(k.artifact.grid))
        assert feats["hbm_bytes"] > 0
        assert feats["vmem_block_bytes"] > 0

    def test_features_survive_disk_cache(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kern"))
        from tilelang_mesh_tpu.cache.kernel_cache import KernelCache
        fac = _make_factory()
        k1 = fac(64, 128, block_M=32)
        feats = k1.artifact.attrs["features"]
        # drop the memory tier: the next build loads the disk artifact
        KernelCache().clear()
        from tilelang_mesh_tpu.jit import clear_factory_caches
        clear_factory_caches()
        k2 = _make_factory()(64, 128, block_M=32)
        assert k2.artifact.attrs["features"] == feats


# ---------------------------------------------------------------------------
# analytic model + ridge residual
# ---------------------------------------------------------------------------

def _feats(**over):
    from tilelang_mesh_tpu.transform.plan import FEATURES_VERSION
    base = {"version": FEATURES_VERSION, "flops": 1 << 30,
            "hbm_bytes": 1 << 24, "vpu_elems": 0, "grid_steps": 16,
            "vmem_arena": 1 << 20, "vmem_block_bytes": 1 << 18,
            "n_scratch": 2, "n_params": 3, "pipelined": 1,
            "block_rows": 128, "block_cols": 128, "block_skew": 1.0,
            "dbuf_chains": 0}
    base.update(over)
    return base


class TestCostModel:
    def test_analytic_monotone_in_flops(self):
        from tilelang_mesh_tpu.autotuner.cost_model import analytic_ms
        assert analytic_ms(_feats(flops=1 << 34)) > \
            analytic_ms(_feats(flops=1 << 30)) > 0

    def test_analytic_overlap_discount(self):
        # a kernel with neither a pipelined grid axis nor a dbuf chain
        # pays the serialization penalty
        from tilelang_mesh_tpu.autotuner.cost_model import analytic_ms
        f_serial = _feats(pipelined=0, dbuf_chains=0,
                          flops=1 << 32, hbm_bytes=1 << 28)
        f_dbuf = _feats(pipelined=0, dbuf_chains=1,
                        flops=1 << 32, hbm_bytes=1 << 28)
        assert analytic_ms(f_serial) > analytic_ms(f_dbuf)

    def test_ridge_fit_round_trip(self):
        from tilelang_mesh_tpu.autotuner.cost_model import (CostModel,
                                                            analytic_ms)
        samples = [(_feats(flops=1 << (28 + i), grid_steps=1 << i), None)
                   for i in range(6)]
        samples = [(f, analytic_ms(f) * 2.5) for f, _ in samples]
        m = CostModel(min_fit=4)
        assert m.seed(samples) == 6
        assert m.fitted
        for f, lat in samples:
            assert m.predict_ms(f) == pytest.approx(lat, rel=0.1)
        # refitting the same data in a second model is bit-deterministic
        m2 = CostModel(min_fit=4)
        m2.seed(samples)
        assert m2.predict_ms(samples[0][0]) == \
            m.predict_ms(samples[0][0])

    def test_cold_below_min_fit(self):
        from tilelang_mesh_tpu.autotuner.cost_model import (CostModel,
                                                            analytic_ms)
        m = CostModel(min_fit=4)
        for i in range(3):
            m.observe(_feats(flops=1 << (28 + i)), 1.0 + i)
        assert not m.fitted
        assert m.confidence_band() is None
        f = _feats()
        assert m.predict_ms(f) == analytic_ms(f, m.arch)

    def test_rejects_mismatched_feature_version(self):
        from tilelang_mesh_tpu.autotuner.cost_model import CostModel
        m = CostModel(min_fit=1)
        assert not m.observe(_feats(version=99), 1.0)
        assert not m.observe(None, 1.0)
        assert not m.observe(_feats(), 0.0)

    def test_rank_agreement(self):
        from tilelang_mesh_tpu.autotuner.cost_model import rank_agreement
        assert rank_agreement([(1, 10), (2, 20), (3, 30)]) == 1.0
        assert rank_agreement([(1, 30), (2, 20), (3, 10)]) == 0.0
        assert rank_agreement([(1, 10)]) is None
        # measured values within the noise tolerance count as ties, not
        # discordance (the top-K configs are near-ties by construction)
        assert rank_agreement([(1.0, 10.0), (2.0, 9.8)]) == 0.5


# ---------------------------------------------------------------------------
# model-guided sweeps
# ---------------------------------------------------------------------------

CFGS = [{"block_M": b} for b in (16, 32, 64, 128)]


class TestGuidedSweep:
    def test_cold_model_runs_full_sweep(self):
        from tilelang_mesh_tpu.autotuner import AutoTuner
        res = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(
            128, 128)
        assert res.trials_measured == len(CFGS)
        assert res.trials_pruned == 0
        assert not res.from_cache
        # the cold sweep seeded the fleet cache
        from tilelang_mesh_tpu.autotuner.tune_cache import TuneCache
        assert TuneCache().stats()["entries"] == 1

    def test_warm_model_prunes_and_keeps_true_best(self, monkeypatch):
        from tilelang_mesh_tpu.autotuner import AutoTuner
        from tilelang_mesh_tpu.autotuner.cost_model import analytic_ms
        # deterministic "hardware": latency = 3x the analytic roofline,
        # so the fitted residual is exactly learnable and the true best
        # config is the analytic best
        _fake_bench(monkeypatch, lambda f: analytic_ms(f) * 3.0)
        seed = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(
            128, 128)
        assert seed.trials_measured == len(CFGS)   # cold: full sweep
        res = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(
            128, 256)                              # sibling shape bucket
        assert res.trials_measured < len(CFGS)
        assert res.trials_pruned >= 1
        assert res.trials_measured + res.trials_pruned == len(CFGS)
        # the model-guided sweep still chose the true best config
        fac = _make_factory()
        best = min(CFGS, key=lambda c: analytic_ms(
            fac(128, 256, **c).artifact.attrs["features"]))
        assert res.config == best
        assert res.model_agreement is None or res.model_agreement >= 0.5

    def test_disagreement_falls_back_to_full_sweep(self, monkeypatch):
        from tilelang_mesh_tpu.autotuner import AutoTuner
        from tilelang_mesh_tpu.autotuner.cost_model import analytic_ms

        # seed bucket: latency grows steeply with the block window,
        # teaching the model "small blocks win" (analytic-relative so
        # the learned correction stays inside the clamp)
        _fake_bench(monkeypatch,
                    lambda f: analytic_ms(f) * (f["block_rows"] / 16) ** 2)
        seed = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(
            128, 128)
        assert seed.trials_measured == len(CFGS)
        # target bucket: the "hardware" inverts — big blocks win. The
        # model's ranking disagrees with what it measures, so the sweep
        # must fall back to measuring EVERYTHING and still find the
        # true winner.
        _fake_bench(monkeypatch,
                    lambda f: analytic_ms(f) * (128 / f["block_rows"]) ** 2)
        res = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(
            128, 256)
        assert res.trials_measured == len(CFGS)
        assert res.trials_pruned == 0
        assert res.config == {"block_M": 128}
        assert get_tracer().counters().get(
            "autotune.model_fallback", 0) >= 1

    def test_early_stop_skips_hopeless_tail(self, monkeypatch):
        from tilelang_mesh_tpu.autotuner import AutoTuner
        from tilelang_mesh_tpu.autotuner.cost_model import analytic_ms
        # widen the measured fraction so the early-stop rule (not the
        # top-K cut) is what trims the sweep; no exploration tail
        monkeypatch.setenv("TL_TPU_TUNE_TOPK", "1.0")
        monkeypatch.setenv("TL_TPU_TUNE_EPS", "0")
        # latency grows quadratically with the block window: steep and
        # learnable, so after 3 measurements every remaining prediction
        # sits far outside the confidence band of the best
        _fake_bench(monkeypatch,
                    lambda f: analytic_ms(f) * (f["block_rows"] / 16) ** 2)
        seed = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(
            128, 128)
        assert seed.trials_measured == len(CFGS)
        res = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(
            128, 256)
        assert res.config == {"block_M": 16}
        assert res.trials_measured == 3          # early stop after 3
        assert res.trials_pruned == 1

    def test_bruteforce_bypasses_model_and_cache(self, monkeypatch):
        from tilelang_mesh_tpu.autotuner import AutoTuner
        # warm fleet cache first (model mode)
        AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(128, 128)
        monkeypatch.setenv("TL_TPU_TUNE", "bruteforce")
        res = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1,
                        cache_results=False).run(128, 128)
        # pre-model behavior: every config measured, no warm start, no
        # model fields in the capture
        assert not res.from_cache
        assert res.trials_measured == len(CFGS)
        assert res.trials_pruned == 0
        assert res.model_agreement is None
        assert len(res.all_results) == len(CFGS)
        for rec in res.all_results:
            assert "predicted_ms" not in rec
            assert "pruned" not in rec
            assert "from_tune_cache" not in rec

    def test_tune_mode_typo_raises(self, monkeypatch):
        from tilelang_mesh_tpu.autotuner import AutoTuner, tune_mode
        monkeypatch.setenv("TL_TPU_TUNE", "banana")
        with pytest.raises(ValueError, match="TL_TPU_TUNE"):
            tune_mode()
        with pytest.raises(ValueError, match="TL_TPU_TUNE"):
            AutoTuner(_make_factory(), CFGS, warmup=1,
                      rep=1).run(128, 128)

    def test_fleet_warm_start_measures_nothing(self):
        from tilelang_mesh_tpu.autotuner import AutoTuner
        first = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(
            128, 128)
        assert first.trials_measured == len(CFGS)
        # a fresh tuner with the LEGACY result cache bypassed: only the
        # fleet tune cache can explain a zero-measurement warm start
        res = AutoTuner(_make_factory(), CFGS, warmup=1, rep=1,
                        cache_results=False).run(128, 128)
        assert res.from_cache
        assert res.trials_measured == 0
        assert res.config == first.config
        assert all(r.get("from_tune_cache") for r in res.all_results)


# ---------------------------------------------------------------------------
# journal resume hardening (the stale-record bugfix)
# ---------------------------------------------------------------------------

class TestJournalStaleness:
    def _journal_for(self, tuner, args, configs):
        key = tuner._disk_key(args, {}, configs)
        return env.autotune_dir() / f"{key}.journal.jsonl"

    def test_journal_skips_stale_codegen(self):
        """A journal record measured under an older CODEGEN_VERSION must
        NOT be resumed — the kernel it timed no longer exists. It is
        skipped with a traced warning and the config re-measures."""
        from tilelang_mesh_tpu.autotuner import (AutoTuner, _JOURNAL_SCHEMA,
                                                 _config_key)
        configs = [{"block_M": 32}, {"block_M": 64}]
        tuner = AutoTuner(_make_factory(), configs, warmup=1, rep=1)
        journal = self._journal_for(tuner, (128, 128), configs)
        journal.parent.mkdir(parents=True, exist_ok=True)
        journal.write_text(json.dumps(
            {"config_key": _config_key(configs[0]), "status": "ok",
             "latency_ms": 0.00001, "schema": _JOURNAL_SCHEMA,
             "codegen_version": 1}) + "\n")
        before = get_tracer().counters().get("autotune.journal.stale", 0)
        res = tuner.run(128, 128)
        assert res.trials_measured == 2          # both re-measured
        assert res.latency_ms != 0.00001
        assert not any(r.get("resumed") for r in res.all_results)
        assert get_tracer().counters()["autotune.journal.stale"] == \
            before + 1

    def test_journal_skips_old_schema_records(self):
        """Pre-stamp records (no schema/codegen fields at all — the old
        config-key schema) are stale by definition."""
        from tilelang_mesh_tpu.autotuner import AutoTuner, _config_key
        configs = [{"block_M": 32}, {"block_M": 64}]
        tuner = AutoTuner(_make_factory(), configs, warmup=1, rep=1)
        journal = self._journal_for(tuner, (128, 128), configs)
        journal.parent.mkdir(parents=True, exist_ok=True)
        journal.write_text(
            json.dumps({"config_key": _config_key(configs[0]),
                        "status": "ok", "latency_ms": 0.00001}) + "\n"
            + json.dumps({"not_a": "journal record"}) + "\n")
        res = tuner.run(128, 128)
        assert res.trials_measured == 2
        assert res.latency_ms != 0.00001

    def test_current_records_still_resume(self):
        from tilelang_mesh_tpu.autotuner import (AutoTuner, _JOURNAL_SCHEMA,
                                                 _config_key)
        from tilelang_mesh_tpu.cache.kernel_cache import CODEGEN_VERSION
        configs = [{"block_M": 32}, {"block_M": 64}]
        tuner = AutoTuner(_make_factory(), configs, warmup=1, rep=1)
        journal = self._journal_for(tuner, (128, 128), configs)
        journal.parent.mkdir(parents=True, exist_ok=True)
        journal.write_text(json.dumps(
            {"config_key": _config_key(configs[0]), "status": "ok",
             "latency_ms": 0.00001, "schema": _JOURNAL_SCHEMA,
             "codegen_version": CODEGEN_VERSION}) + "\n")
        res = tuner.run(128, 128)
        assert res.trials_measured == 1          # one resumed, one run
        assert res.config == configs[0]
        assert res.latency_ms == 0.00001


# ---------------------------------------------------------------------------
# tune cache: crash safety + merge
# ---------------------------------------------------------------------------

def _payload(cfg, lat, source="src", bucket="b", arch="tpu_v5e", **over):
    p = {"source_sha": source, "shape_bucket": bucket, "arch": arch,
         "pass_cfg": {}, "factory": "f", "best_config": cfg,
         "best_latency_ms": lat,
         "trials": [{"config": cfg, "latency_ms": lat}], "merges": 0}
    p.update(over)
    return p


class TestTuneCache:
    def test_put_get_round_trip(self, tmp_path):
        from tilelang_mesh_tpu.autotuner.tune_cache import TuneCache
        c = TuneCache(tmp_path / "tc")
        key = TuneCache.key("s", "b", "tpu_v5e", {})
        c.put(key, _payload({"block_M": 32}, 1.5))
        ent = c.get(key)
        assert ent["best_config"] == {"block_M": 32}
        assert ent["best_latency_ms"] == 1.5
        assert ent["schema"] == 1
        assert "checksum" in ent

    def test_key_covers_identity(self):
        from tilelang_mesh_tpu.autotuner.tune_cache import TuneCache
        base = TuneCache.key("s", "b", "tpu_v5e", {})
        assert TuneCache.key("s2", "b", "tpu_v5e", {}) != base
        assert TuneCache.key("s", "b2", "tpu_v5e", {}) != base
        assert TuneCache.key("s", "b", "tpu_v6e", {}) != base
        assert TuneCache.key("s", "b", "tpu_v5e",
                             {"tl.tpu.tile_opt": "0"}) != base

    def test_corruption_quarantined(self, tmp_path):
        from tilelang_mesh_tpu.autotuner.tune_cache import TuneCache
        c = TuneCache(tmp_path / "tc")
        key = TuneCache.key("s", "b", "a", {})
        c.put(key, _payload({"block_M": 32}, 1.5))
        p = c._path(key)
        # flip a payload byte: the checksum must catch it
        p.write_text(p.read_text().replace('"block_M": 32',
                                           '"block_M": 64'))
        before = get_tracer().counters().get("tune.cache.quarantined", 0)
        assert c.get(key) is None
        assert not p.exists()
        qdir = c.root / ".quarantine"
        assert len(list(qdir.glob("*"))) == 1
        assert get_tracer().counters()["tune.cache.quarantined"] == \
            before + 1

    def test_torn_json_quarantined(self, tmp_path):
        from tilelang_mesh_tpu.autotuner.tune_cache import TuneCache
        c = TuneCache(tmp_path / "tc")
        key = TuneCache.key("s", "b", "a", {})
        c.put(key, _payload({"block_M": 32}, 1.5))
        p = c._path(key)
        p.write_text(p.read_text()[: len(p.read_text()) // 2])
        assert c.get(key) is None
        assert not p.exists()

    def test_merge_payloads_best_wins(self):
        from tilelang_mesh_tpu.autotuner.tune_cache import merge_payloads
        a = _payload({"block_M": 32}, 2.0)
        b = _payload({"block_M": 64}, 1.0)
        m = merge_payloads(a, b)
        assert m["best_config"] == {"block_M": 64}
        assert m["best_latency_ms"] == 1.0
        assert len(m["trials"]) == 2
        assert m["merges"] == 1
        # commutative best/trials (the merge-counter provenance differs
        # by construction, never the tuning payload)
        m2 = merge_payloads(b, a)
        assert m2["best_config"] == m["best_config"]
        assert {json.dumps(t, sort_keys=True) for t in m2["trials"]} == \
            {json.dumps(t, sort_keys=True) for t in m["trials"]}

    def test_merge_same_config_keeps_lower_latency(self):
        from tilelang_mesh_tpu.autotuner.tune_cache import merge_payloads
        a = _payload({"block_M": 32}, 2.0)
        b = _payload({"block_M": 32}, 1.2)
        m = merge_payloads(a, b)
        assert len(m["trials"]) == 1
        assert m["best_latency_ms"] == 1.2

    def test_merge_identical_is_fixed_point(self):
        """Re-merging identical payloads must converge, merge counter
        included — a cron'd `tune_cache merge` of the same dirs would
        otherwise rewrite every entry forever."""
        from tilelang_mesh_tpu.autotuner.tune_cache import merge_payloads
        a = _payload({"block_M": 32}, 2.0, merges=1)
        m = merge_payloads(a, a)
        assert m == {k: v for k, v in a.items() if k != "checksum"}
        assert merge_payloads(m, m) == m

    def test_merge_from_dirs(self, tmp_path):
        from tilelang_mesh_tpu.autotuner.tune_cache import TuneCache
        src1 = TuneCache(tmp_path / "s1")
        src2 = TuneCache(tmp_path / "s2")
        dst = TuneCache(tmp_path / "dst")
        k1 = TuneCache.key("s", "b1", "a", {})
        k2 = TuneCache.key("s", "b2", "a", {})
        src1.put(k1, _payload({"block_M": 32}, 2.0, bucket="b1"))
        src2.put(k1, _payload({"block_M": 64}, 1.0, bucket="b1"))
        src2.put(k2, _payload({"block_M": 16}, 3.0, bucket="b2"))
        # a torn file in a source is skipped, never imported
        (src2.root / f"{'0' * 64}.json").write_text("{ torn")
        stats = dst.merge_from([src1.root, src2.root])
        assert stats["new"] == 2           # k1 from src1, k2 from src2
        assert stats["merged"] == 1        # src2's better k1 merged in
        assert stats["corrupt"] == 1
        assert dst.get(k1)["best_latency_ms"] == 1.0
        assert len(dst.get(k1)["trials"]) == 2
        assert dst.get(k2)["best_config"] == {"block_M": 16}
        # merging again is idempotent
        stats2 = dst.merge_from([src1.root, src2.root])
        assert stats2["new"] == 0 and stats2["merged"] == 0
        assert stats2["unchanged"] == 3

    def test_cli_merge_and_stats(self, tmp_path, capsys):
        from tilelang_mesh_tpu.autotuner.tune_cache import TuneCache, main
        src = TuneCache(tmp_path / "src")
        key = TuneCache.key("s", "b", "a", {})
        src.put(key, _payload({"block_M": 32}, 1.5))
        dst = tmp_path / "dst"
        assert main(["merge", str(src.root), "--into", str(dst)]) == 0
        out = capsys.readouterr().out
        assert "1 new" in out
        assert main(["stats", "--root", str(dst), "--json"]) == 0
        stats = json.loads(capsys.readouterr().out)
        assert stats["entries"] == 1 and stats["trials"] == 1
        assert main(["list", "--root", str(dst)]) == 0
        assert "block_M" in capsys.readouterr().out

    def test_sweep_entry_merges_with_concurrent_writer(self):
        """record() must union with an existing entry, not clobber it —
        two processes finishing the same sweep both contribute trials."""
        from tilelang_mesh_tpu.autotuner.tune_cache import TuneCache
        c = TuneCache()
        key = TuneCache.key("s", "b", "a", {})
        c.record(key, _payload({"block_M": 32}, 2.0))
        c.record(key, _payload({"block_M": 64}, 1.0))
        ent = c.get(key)
        assert len(ent["trials"]) == 2
        assert ent["best_config"] == {"block_M": 64}


# ---------------------------------------------------------------------------
# serving warmup consumption
# ---------------------------------------------------------------------------

class TestServingWarmup:
    def _workload(self):
        from tilelang_mesh_tpu.serving.batcher import FlashDecodeWorkload
        from tilelang_mesh_tpu.serving.kv_cache import PagedKVAllocator
        alloc = PagedKVAllocator(n_pages=8, page_size=8, heads=2,
                                 head_dim=16)
        return FlashDecodeWorkload(alloc, batch_buckets=(1,),
                                   page_buckets=(2,))

    def test_warmup_adopts_fleet_tuned_config(self):
        # an "offline sweep" publishes a tuned split for the bucket…
        wl_pub = self._workload()
        key = wl_pub.record_bucket_tuning(1, 2, {"n_split": 1}, 0.5)
        assert key is not None
        # …and a FRESH serving process adopts it at warmup with zero
        # measurements (the zero-cold-start bucket-config path)
        before = get_tracer().counters().get("serve.warmup.tuned", 0)
        wl = self._workload()
        assert wl.tuned_config(1, 2) == {}
        warmed = wl.warmup()
        assert warmed == 1
        assert wl.tuned_config(1, 2) == {"n_split": 1}
        assert get_tracer().counters()["serve.warmup.tuned"] == before + 1

    def test_warmup_without_entry_is_untuned(self):
        wl = self._workload()
        wl.warmup()
        assert wl.tuned_config(1, 2) == {}

    def test_warmup_adopts_config_published_after_first_miss(self):
        """A miss is not cached forever: a config merged into the fleet
        cache AFTER the first warmup is adopted by the next one."""
        wl = self._workload()
        wl.warmup()
        assert wl.tuned_config(1, 2) == {}
        wl.record_bucket_tuning(1, 2, {"n_split": 2}, 0.4)
        wl.warmup()
        assert wl.tuned_config(1, 2) == {"n_split": 2}

    def test_tuned_dispatch_matches_untuned_numerics(self):
        """A fleet-tuned n_split changes the schedule, never the math."""
        import numpy as _np
        wl_plain = self._workload()
        wl_plain.warmup()
        q = _np.random.default_rng(7).standard_normal(
            (1, 2, 1, 16)).astype(_np.float32)
        table = _np.zeros((1, 2), _np.int32)
        ref = _np.asarray(wl_plain._dispatch(q, table, 1, 2))
        wl_tuned = self._workload()
        wl_tuned.record_bucket_tuning(1, 2, {"n_split": 1}, 0.5)
        wl_tuned.warmup()
        out = _np.asarray(wl_tuned._dispatch(q, table, 1, 2))
        _np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# analyzer + metrics surfacing
# ---------------------------------------------------------------------------

class TestSurfacing:
    def test_analyzer_tune_report(self, tmp_path, capsys):
        from tilelang_mesh_tpu.autotuner import _JOURNAL_SCHEMA
        from tilelang_mesh_tpu.cache.kernel_cache import CODEGEN_VERSION
        from tilelang_mesh_tpu.tools.analyzer import main
        stamp = {"schema": _JOURNAL_SCHEMA,
                 "codegen_version": CODEGEN_VERSION}
        j = tmp_path / "sweep.journal.jsonl"
        j.write_text("\n".join(json.dumps(r) for r in [
            {"config_key": '{"block_M": 32}', "status": "ok",
             "latency_ms": 1.0, "predicted_ms": 1.1, **stamp},
            {"config_key": '{"block_M": 64}', "status": "ok",
             "latency_ms": 2.0, "predicted_ms": 2.4, **stamp},
            {"config_key": '{"block_M": 128}', "status": "pruned",
             "predicted_ms": 9.0, **stamp},
            {"config_key": '{"block_M": 256}', "status": "failed",
             "kind": "deterministic", **stamp},
            # a transient failure later resolved by a resumed ok trial:
            # the report must dedup by config (last record wins), like
            # the tuner's own journal resume does
            {"config_key": '{"block_M": 32}', "status": "ok",
             "latency_ms": 0.9, "predicted_ms": 1.1, **stamp},
        ]) + "\n")
        assert main(["tune", str(j), "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["trials"]["total"] == 4
        assert rep["trials"]["measured"] == 3
        assert rep["trials"]["pruned"] == 1
        row32 = [r for r in rep["rows"]
                 if r["config"] == '{"block_M": 32}']
        assert len(row32) == 1 and row32[0]["latency_ms"] == 0.9
        assert rep["model"]["rank_agreement"] == 1.0
        assert main(["tune", str(j)]) == 0
        text = capsys.readouterr().out
        assert "pruned" in text and "rank agreement" in text

    def test_metrics_summary_autotune_section(self):
        from tilelang_mesh_tpu.autotuner import AutoTuner
        from tilelang_mesh_tpu.observability import metrics_summary
        AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(128, 128)
        at = metrics_summary()["autotune"]
        for k in ("trials_measured", "trials_pruned", "trials_resumed",
                  "tune_cache_hits", "tune_cache_misses",
                  "tune_cache_writes", "journal_stale_skipped",
                  "model_cold_sweeps", "model_fallbacks",
                  "model_rank_agreement"):
            assert k in at
        assert at["trials_measured"] >= len(CFGS)
        assert at["tune_cache_writes"] >= 1

    def test_sweep_records_predictions_in_journal(self, monkeypatch):
        """Warm-model trials journal their predicted_ms so `analyzer
        tune` can reconstruct the predicted-vs-measured table from an
        interrupted sweep's journal."""
        from tilelang_mesh_tpu.autotuner import AutoTuner, _append_journal
        from tilelang_mesh_tpu.autotuner.cost_model import analytic_ms
        _fake_bench(monkeypatch, lambda f: analytic_ms(f) * 3.0)
        AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(128, 128)
        recorded = []
        monkeypatch.setattr(
            "tilelang_mesh_tpu.autotuner._append_journal",
            lambda path, rec: recorded.append(rec) or
            _append_journal(path, rec))
        AutoTuner(_make_factory(), CFGS, warmup=1, rep=1).run(128, 256)
        assert any(r.get("predicted_ms") is not None for r in recorded)
        assert any(r.get("status") == "pruned" for r in recorded)
        assert any(r.get("features") for r in recorded
                   if r.get("status") == "ok")


# ---------------------------------------------------------------------------
# stale-featured artifacts (pre-FEATURES_VERSION-bump caches/journals)
# ---------------------------------------------------------------------------


class TestStaleFeatures:
    class _Art:
        def __init__(self, feats):
            self.attrs = {"features": feats}

    def test_old_schema_skipped_and_counted(self):
        from tilelang_mesh_tpu.autotuner.cost_model import \
            features_from_artifact
        from tilelang_mesh_tpu.transform.plan import FEATURES_VERSION
        before = get_tracer().counters().get(
            "cost_model.features.stale", 0)
        stale = _feats(version=FEATURES_VERSION - 1)
        assert features_from_artifact(self._Art(stale)) is None
        assert get_tracer().counters()["cost_model.features.stale"] == \
            before + 1
        # a missing feature dict is not "stale" — no counter bump
        assert features_from_artifact(self._Art(None)) is None
        assert get_tracer().counters()["cost_model.features.stale"] == \
            before + 1

    def test_current_schema_passes_through(self):
        from tilelang_mesh_tpu.autotuner.cost_model import \
            features_from_artifact
        before = get_tracer().counters().get(
            "cost_model.features.stale", 0)
        out = features_from_artifact(self._Art(_feats()))
        assert out is not None and out["flops"] == _feats()["flops"]
        assert get_tracer().counters().get(
            "cost_model.features.stale", 0) == before

    def test_observe_stale_counted_not_fit(self):
        from tilelang_mesh_tpu.autotuner.cost_model import CostModel
        from tilelang_mesh_tpu.transform.plan import FEATURES_VERSION
        m = CostModel()
        before = get_tracer().counters().get(
            "cost_model.observe.stale", 0)
        assert not m.observe(_feats(version=FEATURES_VERSION - 1), 1.0)
        assert get_tracer().counters()["cost_model.observe.stale"] == \
            before + 1
        # None features / bad latency are rejected but not "stale"
        assert not m.observe(None, 1.0)
        assert not m.observe(_feats(), 0.0)
        assert get_tracer().counters()["cost_model.observe.stale"] == \
            before + 1

    def test_occupancy_feature_present_and_priced(self):
        """FEATURES_VERSION 2: the post-tile-opt resident footprint
        rides the feature dict and feeds the ridge basis."""
        from tilelang_mesh_tpu.autotuner.cost_model import \
            analytic_ms, _phi
        fac = _make_factory()
        feats = fac(128, 128, block_M=32).artifact.attrs["features"]
        assert feats["version"] == 2
        assert 0.0 < feats["vmem_occupancy"] <= 4.0
        lo = _phi(_feats(vmem_occupancy=0.1),
                  analytic_ms(_feats(vmem_occupancy=0.1)))
        hi = _phi(_feats(vmem_occupancy=0.9),
                  analytic_ms(_feats(vmem_occupancy=0.9)))
        assert list(np.ravel(lo)) != list(np.ravel(hi))
