"""Runtime performance observability (ISSUE 3).

Covers: histogram bucket/quantile math, merge, and Prometheus
``_bucket``/``_sum``/``_count`` rendering; runtime dispatch recording
off-by-default (zero observations, no per-kernel state allocated) and
on/sampled when ``TL_TPU_RUNTIME_METRICS=1``; ``metrics_summary()``'s
``runtime`` section; the noise-aware perf-diff gate (a synthetic 2x
regression fails, MAD-level jitter passes, the table names the
regressing config); ``PerfReport`` roofline math against hand-computed
GEMM FLOPs/bytes; and the multi-output ``_consume``/``do_bench`` fix.
"""

import json
import math

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.observability import histogram as hist
from tilelang_mesh_tpu.observability import runtime as rt
from tilelang_mesh_tpu.tools import analyzer
from tilelang_mesh_tpu.tools.perfdiff import (format_perf_diff,
                                              load_bench_records,
                                              perf_diff,
                                              perf_diff_exit_code)


@pytest.fixture(autouse=True)
def _fresh_recorders(monkeypatch):
    """Every test starts with empty histograms/rings and runtime
    recording OFF (the default)."""
    monkeypatch.delenv("TL_TPU_RUNTIME_METRICS", raising=False)
    monkeypatch.delenv("TL_TPU_RUNTIME_SAMPLE", raising=False)
    obs.reset()
    yield
    obs.reset()


@pytest.fixture()
def hermetic_cache(monkeypatch, tmp_path):
    monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
    tilelang.clear_cache()
    yield tmp_path
    tilelang.clear_cache()


def _scale_func(M=64, N=128):
    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, B)
    return scale


# ---------------------------------------------------------------------------
# histogram math
# ---------------------------------------------------------------------------

class TestHistogram:
    def test_bucket_le_semantics(self):
        h = hist.Histogram([1.0, 2.0, 4.0])
        for v, want in [(0.5, 0), (1.0, 0), (1.5, 1), (2.0, 1),
                        (4.0, 2), (5.0, 3)]:
            assert h._bucket_index(v) == want, v
        h.observe(1.0)
        h.observe(5.0)
        assert h.counts == [1, 0, 0, 1]
        assert h.count == 2 and h.sum == 6.0
        assert h.min == 1.0 and h.max == 5.0

    def test_default_bounds_log_spaced(self):
        b = hist.default_bounds()
        assert len(b) == 27
        for lo, hi in zip(b, b[1:]):
            assert hi / lo == pytest.approx(2.0)
        assert b[0] == pytest.approx(1e-6)

    def test_quantiles(self):
        h = hist.Histogram()
        for _ in range(90):
            h.observe(1e-3)
        for _ in range(10):
            h.observe(64e-3)
        # p50 lands in the 1ms bucket, p99 in the 64ms bucket
        assert h.quantile(0.5) <= 2e-3
        assert h.quantile(0.99) >= 30e-3
        assert h.quantile(0.0) == 1e-3        # clamps to observed min
        assert h.quantile(1.0) == 64e-3       # and max
        assert h.mean == pytest.approx((90 * 1e-3 + 10 * 64e-3) / 100)
        assert hist.Histogram().quantile(0.5) is None

    def test_non_finite_observations_dropped(self):
        h = hist.Histogram()
        h.observe(float("nan"))
        h.observe(float("inf"))
        assert h.count == 0

    def test_merge(self):
        a, b = hist.Histogram(), hist.Histogram()
        for v in (1e-4, 2e-4, 3e-4):
            a.observe(v)
        for v in (1e-2, 2e-2):
            b.observe(v)
        a.merge(b)
        assert a.count == 5
        assert a.sum == pytest.approx(6e-4 + 3e-2)
        assert a.max == 2e-2 and a.min == 1e-4
        with pytest.raises(ValueError):
            a.merge(hist.Histogram([1.0, 2.0]))

    def test_round_trip_dict(self):
        h = hist.Histogram()
        for v in (1e-4, 5e-3, 0.2):
            h.observe(v)
        h2 = hist.Histogram.from_dict(json.loads(json.dumps(h.to_dict())))
        assert h2.count == h.count
        assert h2.cumulative() == h.cumulative()
        assert h2.quantile(0.9) == h.quantile(0.9)

    def test_cumulative_is_monotonic_and_totals(self):
        h = hist.Histogram()
        for v in (1e-5, 1e-3, 1e-1, 100.0):  # incl. overflow bucket
            h.observe(v)
        cum = h.cumulative()
        assert cum == sorted(cum)
        assert cum[-1] == h.count == 4


class TestPrometheusRendering:
    def test_bucket_sum_count_series(self):
        obs.observe(rt.HIST_NAME, 0.002, kernel="gemm", source="dispatch")
        obs.observe(rt.HIST_NAME, 0.004, kernel="gemm", source="dispatch")
        text = obs.to_prometheus_text()
        assert "# TYPE tl_tpu_kernel_latency_seconds histogram" in text
        lines = text.splitlines()
        buckets = [l for l in lines
                   if l.startswith("tl_tpu_kernel_latency_seconds_bucket")]
        assert buckets, text
        assert any('le="+Inf"' in l for l in buckets)
        assert all('kernel="gemm"' in l for l in buckets)
        # +Inf bucket equals _count; _sum is the observed total
        inf_val = int([l for l in buckets if 'le="+Inf"' in l][0]
                      .rsplit(" ", 1)[1])
        count = int([l for l in lines if
                     l.startswith("tl_tpu_kernel_latency_seconds_count")][0]
                    .rsplit(" ", 1)[1])
        s = float([l for l in lines if
                   l.startswith("tl_tpu_kernel_latency_seconds_sum")][0]
                  .rsplit(" ", 1)[1])
        assert inf_val == count == 2
        assert s == pytest.approx(0.006)
        # cumulative counts never decrease along the le ladder
        vals = [int(l.rsplit(" ", 1)[1]) for l in buckets]
        assert vals == sorted(vals)

    def test_jsonl_carries_histograms(self):
        obs.observe(rt.HIST_NAME, 0.001, kernel="k1", source="dispatch")
        recs = [json.loads(l) for l in obs.to_jsonl().splitlines()]
        hs = [r for r in recs if r["type"] == "histogram"]
        assert len(hs) == 1
        assert hs[0]["name"] == rt.HIST_NAME
        assert hs[0]["labels"] == {"kernel": "k1", "source": "dispatch"}
        assert hs[0]["count"] == 1


# ---------------------------------------------------------------------------
# runtime dispatch recording
# ---------------------------------------------------------------------------

class TestRuntimeRecording:
    def test_off_by_default_no_observations(self, hermetic_cache):
        k = tilelang.compile(_scale_func(), target="cpu")
        x = np.ones((64, 128), np.float32)
        for _ in range(3):
            k(x)
        # the acceptance bound: zero histogram observations AND no
        # per-kernel state allocated on the disabled hit path
        assert obs.get_registry().total_observations() == 0
        assert rt._states == {}
        assert rt.recent(k.artifact.name) == []
        assert obs.metrics_summary()["runtime"] == {}

    def test_enabled_records_and_rings(self, hermetic_cache, monkeypatch):
        k = tilelang.compile(_scale_func(), target="cpu")
        x = np.ones((64, 128), np.float32)
        monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
        k(x)   # warm-up call: compile time must NOT land in the digest
        assert obs.get_registry().total_observations() == 0
        for _ in range(4):
            k(x)
        h = obs.get_histogram(rt.HIST_NAME, kernel=k.artifact.name,
                              source="dispatch")
        assert h is not None and h.count == 4
        ring = rt.recent(k.artifact.name)
        assert len(ring) == 4
        assert all(r["latency_ms"] > 0 for r in ring)
        assert all(r["source"] == "dispatch" for r in ring)
        summ = obs.metrics_summary()["runtime"]
        assert k.artifact.name in summ
        digest = summ[k.artifact.name]
        assert digest["count"] == 4
        assert digest["p50_ms"] is not None
        assert digest["p99_ms"] >= digest["p50_ms"] > 0
        assert digest["sources"] == ["dispatch"]

    def test_sampling_knob(self, hermetic_cache, monkeypatch):
        k = tilelang.compile(_scale_func(), target="cpu")
        x = np.ones((64, 128), np.float32)
        monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
        monkeypatch.setenv("TL_TPU_RUNTIME_SAMPLE", "3")
        k(x)   # warm-up: not eligible for sampling
        for _ in range(7):
            k(x)
        h = obs.get_histogram(rt.HIST_NAME, kernel=k.artifact.name,
                              source="dispatch")
        assert h is not None and h.count == 2   # warm calls 3 and 6

    def test_ring_buffer_bounded(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_RUNTIME_RING", "4")
        for i in range(10):
            rt.record("k", 1e-3 * (i + 1))
        ring = rt.recent("k")
        assert len(ring) == 4
        assert ring[-1]["latency_ms"] == pytest.approx(10.0)

    def test_results_unchanged_by_recording(self, hermetic_cache,
                                            monkeypatch):
        k = tilelang.compile(_scale_func(), target="cpu")
        x = np.arange(64 * 128, dtype=np.float32).reshape(64, 128)
        off = np.asarray(k(x))
        monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
        on = np.asarray(k(x))
        np.testing.assert_array_equal(off, on)


# ---------------------------------------------------------------------------
# profiler: stats + multi-output consume + PerfReport
# ---------------------------------------------------------------------------

class TestProfilerStats:
    def test_do_bench_stats_fields(self):
        import jax.numpy as jnp
        from tilelang_mesh_tpu.profiler import do_bench_stats

        def f(a):
            return a * 2.0

        stats = do_bench_stats(f, jnp.ones((8, 128)), warmup=1, rep=2,
                               backend="wall")
        for key in ("p50_ms", "p90_ms", "p99_ms", "mean_ms", "min_ms",
                    "max_ms", "mad_ms", "samples", "reps"):
            assert key in stats, key
        assert stats["samples"] == stats["reps"] == 2
        assert stats["min_ms"] <= stats["p50_ms"] <= stats["max_ms"]

    def test_multi_output_wall_timing(self):
        """The wall backend must block on EVERY output leaf — a
        multi-output fn times without error and yields positive
        latency (the old code touched only the first leaf)."""
        import jax.numpy as jnp
        from tilelang_mesh_tpu.profiler import do_bench

        def f(a):
            return a + 1.0, (a * 2.0, a - 1.0)   # nested pytree

        ms = do_bench(f, jnp.ones((8, 128)), warmup=1, rep=3,
                      backend="wall")
        assert ms > 0

    def test_perf_report_roofline_math(self, hermetic_cache):
        from tilelang_mesh_tpu.carver.arch import TPU_V5E
        from tilelang_mesh_tpu.ops.gemm import matmul_kernel

        M = N = K = 128
        k = matmul_kernel(M, N, K, block_M=128, block_N=128, block_K=128,
                          in_dtype="float32", out_dtype="float32")
        rep = k.get_profiler().perf_report(rep=2, rounds=2, backend="wall",
                                           arch=TPU_V5E)
        # hand-computed GEMM work: 2*M*N*K FLOPs; one pass over A, B, C
        assert rep.flops == 2 * M * N * K
        assert rep.bytes_moved == (M * K + K * N + M * N) * 4
        t_s = rep.latency["p50_ms"] / 1e3
        assert rep.achieved_tflops == pytest.approx(
            rep.flops / t_s / 1e12, rel=1e-9)
        assert rep.achieved_gbps == pytest.approx(
            rep.bytes_moved / t_s / 1e9, rel=1e-9)
        assert rep.peak_tflops == TPU_V5E.bf16_tflops
        assert rep.peak_gbps == TPU_V5E.hbm_gbps
        assert rep.compute_utilization == pytest.approx(
            rep.achieved_tflops / TPU_V5E.bf16_tflops)
        assert rep.memory_utilization == pytest.approx(
            rep.achieved_gbps / TPU_V5E.hbm_gbps)
        assert rep.bound in ("compute", "memory")
        assert rep.kernel == k.artifact.name
        assert rep.vmem_ok
        assert rep.ici_wire_bytes == 0 and rep.n_collectives == 0
        # serializes clean
        json.dumps(rep.to_dict())
        # the measured median fed the shared runtime histogram
        assert obs.metrics_summary()["runtime"][k.artifact.name][
            "sources"] == ["bench"]

    def test_perf_report_overrides(self, hermetic_cache):
        k = tilelang.compile(_scale_func(), target="cpu")
        rep = k.get_profiler().perf_report(
            rep=1, rounds=1, backend="wall", flops=10 ** 9,
            bytes_moved=10 ** 6)
        assert rep.flops == 10 ** 9 and rep.bytes_moved == 10 ** 6
        assert rep.achieved_tflops is not None
        assert rep.achieved_gbps is not None


# ---------------------------------------------------------------------------
# autotuner trials feed the histograms
# ---------------------------------------------------------------------------

class TestAutotuneFeedsHistograms:
    def test_trial_latencies_recorded(self, hermetic_cache, tmp_path,
                                      monkeypatch):
        monkeypatch.setenv("TL_TPU_AUTOTUNE_CACHE_DIR",
                           str(tmp_path / "autotune"))

        @tilelang.autotune(configs=[{"block_M": 32}, {"block_M": 64}],
                           warmup=1, rep=1, cache_results=False)
        @tilelang.jit
        def scale(M=64, N=128, block_M=64):
            @T.prim_func
            def f(A: T.Tensor((M, N), "float32"),
                  B: T.Tensor((M, N), "float32")):
                with T.Kernel(M // block_M) as bx:
                    s = T.alloc_shared((block_M, N), "float32")
                    T.copy(A[bx * block_M, 0], s)
                    for i, j in T.Parallel(block_M, N):
                        s[i, j] = s[i, j] * 2.0
                    T.copy(s, B[bx * block_M, 0])
            return f

        scale(64, 128)
        summ = obs.metrics_summary()["runtime"]
        auto = [d for d in summ.values() if "autotune" in d["sources"]]
        assert auto and sum(d["count"] for d in auto) == 2


# ---------------------------------------------------------------------------
# perf-diff gate
# ---------------------------------------------------------------------------

def _rec(config, p50, mad=0.02, **extra):
    return {"config": config, "latency_p50_ms": p50,
            "latency_mad_ms": mad, "reps": 30, **extra}


class TestPerfDiff:
    def test_flags_2x_regression_and_names_config(self):
        base = [_rec("gemm", 1.0), _rec("flash", 5.0, mad=0.1)]
        cur = [_rec("gemm", 2.0), _rec("flash", 5.02, mad=0.1)]
        result = perf_diff(base, cur)
        assert result["regressions"] == ["gemm"]
        assert perf_diff_exit_code(result) == 1
        assert perf_diff_exit_code(result, report_only=True) == 0
        table = format_perf_diff(result)
        assert "gemm" in table and "REGRESSION" in table
        flash_row = [r for r in result["rows"]
                     if r["config"] == "flash"][0]
        assert flash_row["verdict"] == "ok"

    def test_mad_level_jitter_passes(self):
        base = [_rec("gemm", 1.0, mad=0.05), _rec("flash", 5.0, mad=0.2)]
        cur = [_rec("gemm", 1.04, mad=0.05), _rec("flash", 5.15, mad=0.2)]
        result = perf_diff(base, cur)
        assert result["regressions"] == []
        assert perf_diff_exit_code(result) == 0

    def test_improvement_and_missing_and_new(self):
        base = [_rec("a", 2.0), _rec("gone", 1.0)]
        cur = [_rec("a", 1.0), _rec("fresh", 1.0),
               {"config": "dead", "error": "boom"}]
        r = perf_diff(base, cur)
        assert r["improvements"] == ["a"]
        assert set(r["missing"]) == {"gone", "dead"}
        assert r["new"] == ["fresh"]
        assert perf_diff_exit_code(r) == 0   # missing is not a regression

    def test_legacy_median_only_records(self):
        # pre-percentile artifacts (bare latency_ms, no MAD) still diff:
        # the relative floor supplies the noise scale
        base = [{"config": "g", "latency_ms": 1.0}]
        cur2x = [{"config": "g", "latency_ms": 2.0}]
        curok = [{"config": "g", "latency_ms": 1.01}]
        assert perf_diff(base, cur2x)["regressions"] == ["g"]
        assert perf_diff(base, curok)["regressions"] == []

    def test_zero_mad_uses_relative_floor(self):
        # a perfectly stable pair must not flag a 1% wobble
        base = [_rec("g", 1.0, mad=0.0)]
        cur = [_rec("g", 1.01, mad=0.0)]
        assert perf_diff(base, cur)["regressions"] == []

    def test_load_shapes(self, tmp_path):
        recs = [_rec("g", 1.0), {"config": "bad", "error": "x"}]
        jsonl = tmp_path / "a.jsonl"
        jsonl.write_text("\n".join(json.dumps(r) for r in recs)
                         + "\n# comment\n")
        assert len(load_bench_records(jsonl)) == 2
        arr = tmp_path / "b.json"
        arr.write_text(json.dumps(recs))
        assert len(load_bench_records(arr)) == 2
        wrapper = tmp_path / "c.json"
        wrapper.write_text(json.dumps(
            {"n": 1, "rc": 0,
             "tail": "\n".join(json.dumps(r) for r in recs)}))
        assert len(load_bench_records(wrapper)) == 2


# ---------------------------------------------------------------------------
# analyzer CLI
# ---------------------------------------------------------------------------

class TestAnalyzerCLI:
    def _write(self, tmp_path, name, recs):
        p = tmp_path / name
        p.write_text("\n".join(json.dumps(r) for r in recs))
        return p

    def test_perf_diff_exit_codes(self, tmp_path, capsys):
        b = self._write(tmp_path, "base.json", [_rec("gemm", 1.0)])
        bad = self._write(tmp_path, "bad.json", [_rec("gemm", 2.0)])
        ok = self._write(tmp_path, "ok.json", [_rec("gemm", 1.01)])
        assert analyzer.main(["perf-diff", str(b), str(bad)]) == 1
        out = capsys.readouterr().out
        assert "gemm" in out and "REGRESSION" in out
        assert analyzer.main(["perf-diff", str(b), str(ok)]) == 0
        assert analyzer.main(["perf-diff", str(b), str(bad),
                              "--report-only"]) == 0

    def test_legacy_flag_spellings(self, tmp_path, capsys):
        b = self._write(tmp_path, "base.json", [_rec("gemm", 1.0)])
        bad = self._write(tmp_path, "bad.json", [_rec("gemm", 2.0)])
        assert analyzer.main(["--perf-diff", str(b), str(bad)]) == 1
        capsys.readouterr()
        tr = self._write(tmp_path, "t.jsonl", [
            {"type": "span", "name": "plan", "cat": "lower",
             "dur_us": 1000.0}])
        assert analyzer.main(["--trace", str(tr)]) == 0
        assert "plan" in capsys.readouterr().out
        # '=' spelling and combined flags (the pre-subcommand surface)
        assert analyzer.main([f"--trace={tr}"]) == 0
        assert "plan" in capsys.readouterr().out
        assert analyzer.main(["--trace", str(tr),
                              "--faults", str(tr)]) == 0
        out = capsys.readouterr().out
        assert "plan" in out and "no injected faults" in out
        # a gating perf-diff combined with --trace still fails
        assert analyzer.main(["--trace", str(tr),
                              "--perf-diff", str(b), str(bad)]) == 1
        capsys.readouterr()

    def test_json_output_mode(self, tmp_path, capsys):
        b = self._write(tmp_path, "base.json", [_rec("gemm", 1.0)])
        bad = self._write(tmp_path, "bad.json", [_rec("gemm", 2.0)])
        assert analyzer.main(["perf-diff", str(b), str(bad),
                              "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["regressions"] == ["gemm"]
        tr = self._write(tmp_path, "t.jsonl", [
            {"type": "span", "name": "codegen", "cat": "lower",
             "dur_us": 500.0},
            {"type": "counter", "name": "cache.build", "value": 1}])
        assert analyzer.main(["trace", str(tr), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert "codegen" in doc["phases"]
        assert doc["counters"]["cache.build"] == 1
        assert analyzer.main(["faults", str(tr), "--json"]) == 0
        json.loads(capsys.readouterr().out)
