"""DeepSeek V3.2 DSA kernels: indexer, selector, sparse MLA
(reference examples/deepseek_v32/test_tilelang_example_deepseek_v32.py
behavior)."""

import numpy as np
import pytest

from tilelang_mesh_tpu.ops.dsa import (lightning_indexer, sparse_mla_fwd,
                                       sparse_mla_reference, topk_selector)


@pytest.fixture(scope="module")
def pipeline():
    rng = np.random.default_rng(0)
    B, S, Skv, HI, DI = 1, 64, 128, 4, 32
    q_idx = rng.standard_normal((B, S, HI, DI), dtype=np.float32)
    k_idx = rng.standard_normal((B, Skv, DI), dtype=np.float32)
    w = rng.standard_normal((B, S, HI)).astype(np.float32)
    logits = np.asarray(lightning_indexer(q_idx, k_idx, w))
    return rng, q_idx, k_idx, w, logits


def test_indexer_matches_dense(pipeline):
    _, q_idx, k_idx, w, logits = pipeline
    ref = np.einsum("bthd,bjd->bthj", q_idx, k_idx)
    ref = (np.maximum(ref, 0) * w[:, :, :, None]).sum(axis=2)
    S, Skv = logits.shape[1:]
    # queries default to the tail of the KV timeline: offset = Skv - S
    mask = (np.arange(Skv)[None, None, :] <=
            (Skv - S) + np.arange(S)[None, :, None])
    ref = np.where(mask, ref, -np.inf)
    np.testing.assert_allclose(logits, ref, rtol=1e-3, atol=1e-3)


def test_selector_matches_argsort(pipeline):
    _, _, _, _, logits = pipeline
    topk = 32
    idx = np.asarray(topk_selector(logits, topk))
    full = np.where(np.isfinite(logits), logits, -np.inf)
    ref = np.argsort(-full, axis=-1, kind="stable")[..., :topk].astype(
        np.int32)
    vis = np.isfinite(logits).sum(axis=-1)
    for t in range(logits.shape[1]):
        ref[0, t, vis[0, t]:] = -1
    np.testing.assert_array_equal(idx, ref)


def test_sparse_mla_fwd(pipeline):
    rng, _, _, _, logits = pipeline
    idx = np.asarray(topk_selector(logits, 32))
    B, S = logits.shape[:2]
    Skv = logits.shape[2]
    H, D, DT = 8, 128, 64
    q = rng.standard_normal((B, S, H, D + DT), dtype=np.float32)
    kv = rng.standard_normal((B, Skv, D + DT), dtype=np.float32)
    o, lse = sparse_mla_fwd(q, kv, idx, block_I=16)
    o_ref, lse_ref = sparse_mla_reference(q, kv, idx)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               rtol=1e-3, atol=1e-3)


def test_sparse_mla_rejects_indivisible_topk():
    q = np.zeros((1, 8, 4, 192), np.float32)
    kv = np.zeros((1, 16, 192), np.float32)
    idx = np.zeros((1, 8, 30), np.int32)
    with pytest.raises(ValueError, match="multiple of block_I"):
        sparse_mla_fwd(q, kv, idx, block_I=16)


def test_indexer_non_divisible_seq():
    rng = np.random.default_rng(5)
    B, S, Skv, HI, DI = 1, 96, 96, 2, 32  # S % 64 != 0
    q_idx = rng.standard_normal((B, S, HI, DI), dtype=np.float32)
    k_idx = rng.standard_normal((B, Skv, DI), dtype=np.float32)
    w = rng.standard_normal((B, S, HI)).astype(np.float32)
    logits = np.asarray(lightning_indexer(q_idx, k_idx, w))
    ref = np.einsum("bthd,bjd->bthj", q_idx, k_idx)
    ref = (np.maximum(ref, 0) * w[:, :, :, None]).sum(axis=2)
    mask = np.arange(Skv)[None, None, :] <= np.arange(S)[None, :, None]
    ref = np.where(mask, ref, -np.inf)
    np.testing.assert_allclose(logits, ref, rtol=1e-3, atol=1e-3)


def test_indexer_cache_offset():
    # queries are the TAIL of a longer KV timeline: every key must be
    # reachable by the last query
    rng = np.random.default_rng(6)
    B, S, Skv = 1, 32, 64
    q_idx = rng.standard_normal((B, S, 2, 32), dtype=np.float32)
    k_idx = rng.standard_normal((B, Skv, 32), dtype=np.float32)
    w = np.abs(rng.standard_normal((B, S, 2))).astype(np.float32)
    logits = np.asarray(lightning_indexer(q_idx, k_idx, w))
    off = Skv - S
    mask = (np.arange(Skv)[None, None, :] <=
            off + np.arange(S)[None, :, None])
    assert np.isfinite(logits[0, -1]).all(), \
        "last query must see the whole cache"
    assert (np.isfinite(logits) == mask).all()


def test_sparse_mla_tail_dim_required_when_ambiguous():
    q = np.zeros((1, 8, 4, 256), np.float32)  # 256 % 128 == 0: ambiguous
    kv = np.zeros((1, 16, 256), np.float32)
    idx = np.zeros((1, 8, 16), np.int32)
    with pytest.raises(ValueError, match="tail_dim"):
        sparse_mla_fwd(q, kv, idx, block_I=16)
    o, lse = sparse_mla_fwd(q, kv, idx, block_I=16, tail_dim=64)
    assert o.shape == (1, 8, 4, 192)
