"""DeepSeek V3.2 DSA kernels: indexer, selector, sparse MLA
(reference examples/deepseek_v32/test_tilelang_example_deepseek_v32.py
behavior)."""

import numpy as np
import pytest

from tilelang_mesh_tpu.ops.dsa import (lightning_indexer, sparse_mla_fwd,
                                       sparse_mla_reference, topk_selector)


@pytest.fixture(scope="module")
def pipeline():
    rng = np.random.default_rng(0)
    B, S, Skv, HI, DI = 1, 64, 128, 4, 32
    q_idx = rng.standard_normal((B, S, HI, DI), dtype=np.float32)
    k_idx = rng.standard_normal((B, Skv, DI), dtype=np.float32)
    w = rng.standard_normal((B, S, HI)).astype(np.float32)
    logits = np.asarray(lightning_indexer(q_idx, k_idx, w))
    return rng, q_idx, k_idx, w, logits


def test_indexer_matches_dense(pipeline):
    _, q_idx, k_idx, w, logits = pipeline
    ref = np.einsum("bthd,bjd->bthj", q_idx, k_idx)
    ref = (np.maximum(ref, 0) * w[:, :, :, None]).sum(axis=2)
    S, Skv = logits.shape[1:]
    mask = np.arange(Skv)[None, None, :] <= np.arange(S)[None, :, None]
    ref = np.where(mask, ref, -np.inf)
    np.testing.assert_allclose(logits, ref, rtol=1e-3, atol=1e-3)


def test_selector_matches_argsort(pipeline):
    _, _, _, _, logits = pipeline
    topk = 32
    idx = np.asarray(topk_selector(logits, topk))
    full = np.where(np.isfinite(logits), logits, -np.inf)
    ref = np.argsort(-full, axis=-1, kind="stable")[..., :topk].astype(
        np.int32)
    vis = np.isfinite(logits).sum(axis=-1)
    for t in range(logits.shape[1]):
        ref[0, t, vis[0, t]:] = -1
    np.testing.assert_array_equal(idx, ref)


def test_sparse_mla_fwd(pipeline):
    rng, _, _, _, logits = pipeline
    idx = np.asarray(topk_selector(logits, 32))
    B, S = logits.shape[:2]
    Skv = logits.shape[2]
    H, D, DT = 8, 128, 64
    q = rng.standard_normal((B, S, H, D + DT), dtype=np.float32)
    kv = rng.standard_normal((B, Skv, D + DT), dtype=np.float32)
    o, lse = sparse_mla_fwd(q, kv, idx, block_I=16)
    o_ref, lse_ref = sparse_mla_reference(q, kv, idx)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               rtol=1e-3, atol=1e-3)


def test_sparse_mla_rejects_indivisible_topk():
    q = np.zeros((1, 8, 4, 192), np.float32)
    kv = np.zeros((1, 16, 192), np.float32)
    idx = np.zeros((1, 8, 30), np.int32)
    with pytest.raises(ValueError, match="multiple of block_I"):
        sparse_mla_fwd(q, kv, idx, block_I=16)
