"""Varlen (cu_seqlens) flash attention vs a padded-dense reference.

Mirrors the reference's varlen test methodology
(/root/reference/examples/flash_attention/example_mha_fwd_varlen.py
attention_ref with padding masks): random per-sequence lengths, pack,
run the kernel, unpack, compare per sequence. Boundary rule: no
attention across sequences; rows past a sequence's end are zero.
"""

import numpy as np
import pytest

from tilelang_mesh_tpu.ops import flash_attention_varlen


def _ref_dense(q, k, v, lens_q, lens_k, causal, group):
    """Padded-dense reference in f64-ish numpy f32: q (B, maxq, Hq, D)."""
    B, Sq, Hq, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    out = np.zeros_like(q)
    for b in range(B):
        for h in range(Hq):
            hk = h // group
            qi = q[b, :lens_q[b], h]                      # (lq, D)
            ki = k[b, :lens_k[b], hk]
            vi = v[b, :lens_k[b], hk]
            s = (qi @ ki.T) / np.sqrt(D)
            if causal:
                lq, lk = s.shape
                # packed-order causal == local-position causal
                mask = np.arange(lq)[:, None] >= np.arange(lk)[None, :]
                s = np.where(mask, s, -np.inf)
            p = np.exp(s - s.max(-1, keepdims=True))
            denom = p.sum(-1, keepdims=True)
            p = np.where(denom > 0, p / np.maximum(denom, 1e-30), 0.0)
            out[b, :lens_q[b], h] = p @ vi
    return out


def _pack(x, lens):
    """(B, S, H, D) + lens -> (total, H, D)"""
    return np.concatenate([x[b, :lens[b]] for b in range(len(lens))], 0)


def _run_case(B, maxq, maxk, Hq, Hkv, D, causal, seed, same_lens=False):
    rng = np.random.default_rng(seed)
    lens_q = rng.integers(1, maxq + 1, B)
    lens_k = lens_q.copy() if same_lens else rng.integers(1, maxk + 1, B)
    q = rng.standard_normal((B, maxq, Hq, D)).astype(np.float32)
    k = rng.standard_normal((B, maxk, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((B, maxk, Hkv, D)).astype(np.float32)

    cu_q = np.concatenate([[0], np.cumsum(lens_q)]).astype(np.int32)
    cu_k = np.concatenate([[0], np.cumsum(lens_k)]).astype(np.int32)
    o_packed = np.asarray(flash_attention_varlen(
        _pack(q, lens_q), _pack(k, lens_k), _pack(v, lens_k),
        cu_q, cu_k, causal=causal, block_M=32, block_N=32))

    ref = _ref_dense(q, k, v, lens_q, lens_k, causal,
                     group=Hq // Hkv)
    for b in range(B):
        got = o_packed[cu_q[b]:cu_q[b + 1]]
        np.testing.assert_allclose(
            got, ref[b, :lens_q[b]], rtol=2e-2, atol=2e-2,
            err_msg=f"sequence {b} (len {lens_q[b]}) mismatch")


def test_varlen_mha_noncausal():
    _run_case(B=4, maxq=50, maxk=70, Hq=2, Hkv=2, D=64, causal=False,
              seed=0)


def test_varlen_mha_causal():
    _run_case(B=3, maxq=60, maxk=60, Hq=2, Hkv=2, D=64, causal=True,
              seed=1, same_lens=True)


def test_varlen_mha_causal_unequal_qk_lens():
    """Causal masking is on LOCAL positions (top-left aligned), so it
    must stay correct when lens_q != lens_k per sequence."""
    _run_case(B=4, maxq=40, maxk=70, Hq=2, Hkv=2, D=64, causal=True,
              seed=4)


def test_varlen_gqa_noncausal():
    _run_case(B=3, maxq=45, maxk=33, Hq=4, Hkv=2, D=64, causal=False,
              seed=2)


def test_varlen_gqa_causal():
    _run_case(B=3, maxq=40, maxk=40, Hq=4, Hkv=1, D=64, causal=True,
              seed=3, same_lens=True)


def test_varlen_no_cross_sequence_leak():
    """Two sequences with identical queries but different keys must give
    different outputs (a leak would blend them)."""
    rng = np.random.default_rng(7)
    D, H = 64, 1
    lens = [32, 32]
    q1 = rng.standard_normal((32, H, D)).astype(np.float32)
    k1 = rng.standard_normal((32, H, D)).astype(np.float32)
    v1 = rng.standard_normal((32, H, D)).astype(np.float32)
    k2 = rng.standard_normal((32, H, D)).astype(np.float32)
    v2 = rng.standard_normal((32, H, D)).astype(np.float32)
    cu = np.array([0, 32, 64], np.int32)
    out = np.asarray(flash_attention_varlen(
        np.concatenate([q1, q1]), np.concatenate([k1, k2]),
        np.concatenate([v1, v2]), cu, cu, block_M=32, block_N=32))
    # seq 0 must equal single-sequence attention over (q1, k1, v1)
    solo = np.asarray(flash_attention_varlen(
        q1, k1, v1, np.array([0, 32], np.int32),
        np.array([0, 32], np.int32), block_M=32, block_N=32))
    np.testing.assert_allclose(out[:32], solo, rtol=2e-2, atol=2e-2)
    assert not np.allclose(out[:32], out[32:], atol=1e-3), \
        "sequences with different KV produced identical outputs (leak)"


def test_varlen_padded_rows_zero():
    """Rows between cu_seqlens[-1] and the physical end of the packed
    tensor must come back zero."""
    rng = np.random.default_rng(9)
    D, H = 64, 2
    q = rng.standard_normal((40, H, D)).astype(np.float32)
    k = rng.standard_normal((40, H, D)).astype(np.float32)
    v = rng.standard_normal((40, H, D)).astype(np.float32)
    cu = np.array([0, 20, 30], np.int32)  # only 30 of 40 rows are real
    out = np.asarray(flash_attention_varlen(q, k, v, cu, cu,
                                            block_M=32, block_N=32))
    assert np.all(out[30:] == 0.0), "pad rows past cu_seqlens[-1] not zero"


def test_varlen_matches_dense_when_full():
    """One full-length sequence == plain dense attention."""
    from tilelang_mesh_tpu.ops import flash_attention
    rng = np.random.default_rng(11)
    S, H, D = 64, 2, 64
    q = rng.standard_normal((S, H, D)).astype(np.float32)
    k = rng.standard_normal((S, H, D)).astype(np.float32)
    v = rng.standard_normal((S, H, D)).astype(np.float32)
    cu = np.array([0, S], np.int32)
    got = np.asarray(flash_attention_varlen(q, k, v, cu, cu, causal=True,
                                            block_M=32, block_N=32))
    import jax.numpy as jnp
    dense = flash_attention(jnp.asarray(q.transpose(1, 0, 2)[None]),
                            jnp.asarray(k.transpose(1, 0, 2)[None]),
                            jnp.asarray(v.transpose(1, 0, 2)[None]),
                            causal=True, block_M=32, block_N=32)
    dense = np.asarray(dense)[0].transpose(1, 0, 2)
    np.testing.assert_allclose(got, dense, rtol=2e-2, atol=2e-2)


def _varlen_grads(causal, Hq, Hkv, seed):
    """Varlen kernel grads vs jax AD of the per-sequence dense graph."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    lens = [33, 47, 21]
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    total = int(cu[-1])
    D = 64
    q = jnp.asarray(rng.standard_normal((total, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, Hkv, D)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((total, Hq, D)), jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention_varlen(
            q, k, v, cu, cu, causal=causal, block_M=32, block_N=32) * g)

    def ref_dense(q, k, v):
        group = Hq // Hkv
        outs = []
        for b in range(len(lens)):
            qi = q[cu[b]:cu[b + 1]]
            ki = jnp.repeat(k[cu[b]:cu[b + 1]], group, axis=1)
            vi = jnp.repeat(v[cu[b]:cu[b + 1]], group, axis=1)
            s = jnp.einsum("qhd,khd->hqk", qi, ki) / np.sqrt(D)
            if causal:
                Li = qi.shape[0]
                mask = jnp.tril(jnp.ones((Li, Li), bool))
                s = jnp.where(mask[None], s, -jnp.inf)
            p = jnp.exp(s - s.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            outs.append(jnp.einsum("hqk,khd->qhd", p, vi))
        return jnp.concatenate(outs, 0)

    def loss_ref(q, k, v):
        return jnp.sum(ref_dense(q, k, v) * g)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-2, atol=3e-2,
            err_msg=f"{name} (causal={causal}, Hq={Hq}, Hkv={Hkv})")


def test_varlen_bwd_mha():
    _varlen_grads(causal=False, Hq=2, Hkv=2, seed=0)


def test_varlen_bwd_mha_causal():
    _varlen_grads(causal=True, Hq=2, Hkv=2, seed=1)


def test_varlen_bwd_gqa_causal():
    _varlen_grads(causal=True, Hq=4, Hkv=2, seed=2)


def test_varlen_bwd_unequal_qk_lens():
    """Backward with lens_q != lens_k per sequence (cross-attention
    style): the dKdV transposed-liveness sweep and local-position masks
    must stay correct when q and k packing offsets differ."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    lens_q = [20, 35, 11]
    lens_k = [44, 17, 52]
    cu_q = np.concatenate([[0], np.cumsum(lens_q)]).astype(np.int32)
    cu_k = np.concatenate([[0], np.cumsum(lens_k)]).astype(np.int32)
    Hq, Hkv, D = 4, 2, 64
    q = jnp.asarray(rng.standard_normal((int(cu_q[-1]), Hq, D)),
                    jnp.float32)
    k = jnp.asarray(rng.standard_normal((int(cu_k[-1]), Hkv, D)),
                    jnp.float32)
    v = jnp.asarray(rng.standard_normal((int(cu_k[-1]), Hkv, D)),
                    jnp.float32)
    g = jnp.asarray(rng.standard_normal((int(cu_q[-1]), Hq, D)),
                    jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention_varlen(
            q, k, v, cu_q, cu_k, causal=False, block_M=32,
            block_N=32) * g)

    def loss_ref(q, k, v):
        group = Hq // Hkv
        tot = 0.0
        for b in range(len(lens_q)):
            qi = q[cu_q[b]:cu_q[b + 1]]
            ki = jnp.repeat(k[cu_k[b]:cu_k[b + 1]], group, axis=1)
            vi = jnp.repeat(v[cu_k[b]:cu_k[b + 1]], group, axis=1)
            s = jnp.einsum("qhd,khd->hqk", qi, ki) / np.sqrt(D)
            p = jnp.exp(s - s.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            o = jnp.einsum("hqk,khd->qhd", p, vi)
            tot = tot + jnp.sum(o * g[cu_q[b]:cu_q[b + 1]])
        return tot

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)


def test_varlen_bwd_causal_unequal_qk_lens():
    """Causal backward with lens_q != lens_k: LOCAL-position masks in
    the recompute must mirror the forward's top-left alignment."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(6)
    lens_q = [18, 30]
    lens_k = [41, 26]
    cu_q = np.concatenate([[0], np.cumsum(lens_q)]).astype(np.int32)
    cu_k = np.concatenate([[0], np.cumsum(lens_k)]).astype(np.int32)
    H, D = 2, 64
    q = jnp.asarray(rng.standard_normal((int(cu_q[-1]), H, D)),
                    jnp.float32)
    k = jnp.asarray(rng.standard_normal((int(cu_k[-1]), H, D)),
                    jnp.float32)
    v = jnp.asarray(rng.standard_normal((int(cu_k[-1]), H, D)),
                    jnp.float32)
    g = jnp.asarray(rng.standard_normal((int(cu_q[-1]), H, D)),
                    jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention_varlen(
            q, k, v, cu_q, cu_k, causal=True, block_M=32,
            block_N=32) * g)

    def loss_ref(q, k, v):
        tot = 0.0
        for b in range(len(lens_q)):
            qi = q[cu_q[b]:cu_q[b + 1]]
            ki = k[cu_k[b]:cu_k[b + 1]]
            vi = v[cu_k[b]:cu_k[b + 1]]
            lq, lk = qi.shape[0], ki.shape[0]
            s = jnp.einsum("qhd,khd->hqk", qi, ki) / np.sqrt(D)
            mask = np.arange(lq)[:, None] >= np.arange(lk)[None, :]
            s = jnp.where(jnp.asarray(mask)[None], s, -jnp.inf)
            p = jnp.exp(s - s.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            o = jnp.einsum("hqk,khd->qhd", p, vi)
            tot = tot + jnp.sum(o * g[cu_q[b]:cu_q[b + 1]])
        return tot

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)


def test_varlen_head_sharded_under_shard_map():
    """Varlen attention composes with jax.sharding: heads sharded over
    the 8-device mesh via shard_map (each shard runs the packed kernel
    on its head slice; cu_seqlens replicated) == the unsharded result."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    mesh = Mesh(np.asarray(devs[:8]).reshape(8), ("h",))

    rng = np.random.default_rng(0)
    total, H, D = 64, 8, 64
    lens = [30, 34]
    cu = jnp.asarray(np.concatenate([[0], np.cumsum(lens)]
                                    ).astype(np.int32))
    q = jnp.asarray(rng.standard_normal((total, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, H, D)), jnp.float32)

    def shard_fn(q, k, v, cu):
        return flash_attention_varlen(q, k, v, cu, cu, causal=True,
                                      block_M=32, block_N=32)

    from tilelang_mesh_tpu.parallel.device_mesh import shard_map_compat
    sharded = shard_map_compat(
        shard_fn, mesh=mesh,
        in_specs=(P(None, "h", None), P(None, "h", None),
                  P(None, "h", None), P()),
        out_specs=P(None, "h", None))
    got = np.asarray(jax.jit(sharded)(q, k, v, cu))
    want = np.asarray(shard_fn(q, k, v, cu))
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
