"""Ring attention (sequence parallel) + fused MoE (expert parallel) +
grouped GEMM on the 8-device virtual mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.utils.tensor import assert_allclose


def test_grouped_gemm():
    from tilelang_mesh_tpu.ops.grouped_gemm import grouped_matmul
    rng = np.random.default_rng(0)
    E, M, K, N = 4, 128, 256, 128
    x = jnp.asarray(rng.standard_normal((E, M, K)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((E, K, N)), jnp.float32)
    out = grouped_matmul(x, w)
    ref = np.einsum("emk,ekn->emn", np.asarray(x), np.asarray(w))
    assert_allclose(np.asarray(out), ref, rtol=2e-2, atol=2e-1)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    from tilelang_mesh_tpu.parallel.ring_attention import make_ring_attention
    from tilelang_mesh_tpu.ops.flash_attention import _reference_attention
    n = 4
    if len(jax.devices()) < n:
        pytest.skip("needs 4 devices")
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:n]), ("sp",))
    B, H, S, D = 1, 2, 512, 64
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    fn = make_ring_attention(mesh, "sp", causal=causal)
    out = fn(q, k, v)
    ref = _reference_attention(q, k, v, causal, 1.0 / np.sqrt(D))
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_moe_expert_parallel_matches_dense():
    from tilelang_mesh_tpu.parallel.moe import make_moe_layer, moe_reference
    n = 4
    if len(jax.devices()) < n:
        pytest.skip("needs 4 devices")
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:n]), ("ep",))
    rng = np.random.default_rng(2)
    T, d, f, E, top_k = 256, 64, 128, 8, 2
    x = jnp.asarray(rng.standard_normal((T, d)) * 0.5, jnp.float32)
    wr = jnp.asarray(rng.standard_normal((d, E)) * 0.5, jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((E, d, f)) * 0.2, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((E, f, d)) * 0.2, jnp.float32)
    # generous capacity so the dense reference matches (no token drops)
    layer = make_moe_layer(mesh, "ep", top_k=top_k, capacity_factor=8.0,
                           use_tile_kernel=True)
    out = layer(x, wr, w1, w2)
    ref = moe_reference(x, wr, w1, w2, top_k)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-2, atol=3e-1)


def test_moe_capacity_drops_are_deterministic():
    from tilelang_mesh_tpu.parallel.moe import make_moe_layer
    n = 2
    if len(jax.devices()) < n:
        pytest.skip("needs 2 devices")
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:n]), ("ep",))
    rng = np.random.default_rng(3)
    T, d, f, E = 64, 32, 64, 4
    x = jnp.asarray(rng.standard_normal((T, d)), jnp.float32)
    wr = jnp.asarray(rng.standard_normal((d, E)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((E, d, f)) * 0.2, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((E, f, d)) * 0.2, jnp.float32)
    layer = make_moe_layer(mesh, "ep", top_k=1, capacity_factor=0.5,
                           use_tile_kernel=False)
    a = layer(x, wr, w1, w2)
    b = layer(x, wr, w1, w2)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(np.asarray(a)).all()