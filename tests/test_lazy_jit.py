"""lazy_jit shape specialization + dynamic dims + compile flags
(reference testing/python/jit + examples/dynamic_shape behavior)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def _make_lazy(out_idx=None):
    M = T.dynamic("m")
    N = 128

    @tilelang.lazy_jit(out_idx=out_idx)
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(M, 64)) as bx:
            s = T.alloc_shared((64, N), "float32")
            T.copy(A[bx * 64, 0], s)
            for i, j in T.Parallel(64, N):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, B[bx * 64, 0])

    return scale


def test_lazy_jit_specializes_per_shape():
    scale = _make_lazy(out_idx=[1])
    for m in (64, 128, 64, 192):
        a = np.random.default_rng(m).standard_normal((m, 128),
                                                     dtype=np.float32)
        np.testing.assert_allclose(np.asarray(scale(a)), a * 2, rtol=1e-5)
    assert len(scale._kernels) == 3  # m=64 reused


def test_lazy_jit_output_arg_convention():
    scale = _make_lazy()
    a = np.random.default_rng(0).standard_normal((64, 128),
                                                 dtype=np.float32)
    out = np.empty_like(a)
    scale(a, out)
    np.testing.assert_allclose(out, a * 2, rtol=1e-5)


def test_lazy_jit_wrong_arity():
    scale = _make_lazy(out_idx=[1])
    with pytest.raises(TypeError, match="input tensors"):
        scale(np.zeros((64, 128), np.float32), np.zeros((64, 128),
                                                        np.float32))


def test_lazy_jit_inconsistent_dims():
    M = T.dynamic("m")

    @tilelang.lazy_jit(out_idx=[2])
    def add(A: T.Tensor((M, 128), "float32"),
            B: T.Tensor((M, 128), "float32"),
            C: T.Tensor((M, 128), "float32")):
        with T.Kernel(T.ceildiv(M, 64)) as bx:
            s = T.alloc_shared((64, 128), "float32")
            t = T.alloc_shared((64, 128), "float32")
            T.copy(A[bx * 64, 0], s)
            T.copy(B[bx * 64, 0], t)
            for i, j in T.Parallel(64, 128):
                s[i, j] = s[i, j] + t[i, j]
            T.copy(s, C[bx * 64, 0])

    with pytest.raises(ValueError):
        add(np.zeros((64, 128), np.float32), np.zeros((128, 128),
                                                      np.float32))


def test_pass_configs_reach_pallas_call():
    @T.prim_func
    def copy(A: T.Tensor((128, 128), "float32"),
             B: T.Tensor((128, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((128, 128), "float32")
            T.copy(A, s)
            T.copy(s, B)

    k = tilelang.compile(
        copy, pass_configs={"tl.tpu.vmem_limit_bytes": 32 * 1024 * 1024})
    assert f"vmem_limit_bytes={32 * 1024 * 1024}" in k.get_kernel_source()


def test_dimension_semantics_config_including_bare_string():
    def make():
        @T.prim_func
        def copy2(A: T.Tensor((128, 128), "float32"),
                  B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                T.copy(A, s)
                T.copy(s, B)
        return copy2

    k = tilelang.compile(
        make(), pass_configs={"tl.tpu.dimension_semantics": ("arbitrary",)})
    assert 'dimension_semantics=("arbitrary",)' in k.get_kernel_source()
    # a bare string must normalize to a 1-tuple, not iterate per character
    k2 = tilelang.compile(
        make(), pass_configs={"tl.tpu.dimension_semantics": "arbitrary"})
    assert 'dimension_semantics=("arbitrary",)' in k2.get_kernel_source()


def test_lazy_jit_tail_guard_uses_dyn_var():
    # body references M beyond shapes: bounds guard must compile per shape
    M = T.dynamic("m")

    @tilelang.lazy_jit(out_idx=[1])
    def relu_tail(A: T.Tensor((M, 128), "float32"),
                  B: T.Tensor((M, 128), "float32")):
        with T.Kernel(T.ceildiv(M, 64)) as bx:
            s = T.alloc_shared((64, 128), "float32")
            T.copy(A[bx * 64, 0], s)
            for i, j in T.Parallel(64, 128):
                s[i, j] = T.if_then_else(bx * 64 + i < M,
                                         T.max(s[i, j], 0.0), 0.0)
            T.copy(s, B[bx * 64, 0])

    a = np.random.default_rng(0).standard_normal((128, 128),
                                                 dtype=np.float32)
    np.testing.assert_allclose(np.asarray(relu_tail(a)), np.maximum(a, 0),
                               rtol=1e-5)


def test_lazy_jit_out_idx_out_of_range():
    M = T.dynamic("m")

    @tilelang.lazy_jit(out_idx=[5])
    def k(A: T.Tensor((M, 128), "float32"),
          B: T.Tensor((M, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((64, 128), "float32")
            T.copy(A[0, 0], s)
            T.copy(s, B[0, 0])

    with pytest.raises(IndexError, match="out_idx"):
        k(np.zeros((64, 128), np.float32))


def test_dynamic_bucket_one_compile_serves_many_lengths():
    """dynamic_bucket: the dyn dim is rounded up to the bucket, inputs
    zero-padded and outputs sliced — one compiled kernel serves every
    length in the bucket (reference symbolics.py compile-once behavior,
    realized under XLA's static-shape rule)."""
    M = T.dynamic("m")
    N, BK = 128, 128

    @tilelang.lazy_jit(out_idx=[2], dynamic_bucket=128)
    def matvecish(A: T.Tensor((M, N), "float32"),
                  B: T.Tensor((N, N), "float32"),
                  C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(M, BK)) as bx:
            A_s = T.alloc_shared((BK, N), "float32")
            B_s = T.alloc_shared((N, N), "float32")
            acc = T.alloc_fragment((BK, N), "float32")
            T.copy(A[bx * BK, 0], A_s)
            T.copy(B, B_s)
            T.clear(acc)
            T.gemm(A_s, B_s, acc)
            T.copy(acc, C[bx * BK, 0])

    rng = np.random.default_rng(0)
    b = rng.standard_normal((128, 128), dtype=np.float32)
    for m in (100, 60, 128):          # all inside one 128 bucket
        a = rng.standard_normal((m, 128), dtype=np.float32)
        out = np.asarray(matvecish(a, b))
        assert out.shape == (m, 128)
        np.testing.assert_allclose(out, a @ b, rtol=2e-2, atol=2e-1)
    assert len(matvecish._kernels) == 1, "one compile must serve the bucket"
    # a length in the next bucket specializes exactly once more
    a = rng.standard_normal((200, 128), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(matvecish(a, b)), a @ b,
                               rtol=2e-2, atol=2e-1)
    assert len(matvecish._kernels) == 2


def test_dynamic_bucket_with_runtime_length_mask():
    """Exact semantics under padding: the kernel takes the TRUE length as
    a runtime scalar operand and masks — the pattern normalizing kernels
    (softmax/mean) must use, since zero padding is only an identity for
    sum-like ops."""
    M = T.dynamic("m")
    CAP_BLK = 128

    @tilelang.lazy_jit(out_idx=[2], dynamic_bucket=CAP_BLK)
    def row_mean(X: T.Tensor((M, 128), "float32"),
                 L: T.Tensor((1,), "int32"),
                 O: T.Tensor((1, 128), "float32")):
        with T.Kernel(1) as bx:
            acc = T.alloc_fragment((128,), "float32")
            tmp = T.alloc_fragment((CAP_BLK, 128), "float32")
            s = T.alloc_shared((CAP_BLK, 128), "float32")
            T.fill(acc, 0)
            # block count folds against the BUCKETED capacity M at trace
            # time; rows past the true length L are masked out
            for ko in T.serial(T.ceildiv(M, CAP_BLK)):
                T.copy(X[ko * CAP_BLK, 0], s)
                for i, j in T.Parallel(CAP_BLK, 128):
                    tmp[i, j] = T.if_then_else(
                        ko * CAP_BLK + i < L[0], s[i, j], 0.0)
                T.reduce_sum(tmp, acc, dim=0, clear=False)
            for j in T.Parallel(128):
                acc[j] = acc[j] / T.cast(L[0], "float32")
            T.copy(acc, O[0, 0])

    rng = np.random.default_rng(1)
    for m in (100, 60):
        x = rng.standard_normal((m, 128), dtype=np.float32)
        ln = np.asarray([m], np.int32)
        out = np.asarray(row_mean(x, ln))
        np.testing.assert_allclose(out[0], x.mean(axis=0), rtol=1e-4,
                                   atol=1e-4)
    assert len(row_mean._kernels) == 1
