"""lazy_jit shape specialization + dynamic dims + compile flags
(reference testing/python/jit + examples/dynamic_shape behavior)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def _make_lazy(out_idx=None):
    M = T.dynamic("m")
    N = 128

    @tilelang.lazy_jit(out_idx=out_idx)
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(M, 64)) as bx:
            s = T.alloc_shared((64, N), "float32")
            T.copy(A[bx * 64, 0], s)
            for i, j in T.Parallel(64, N):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, B[bx * 64, 0])

    return scale


def test_lazy_jit_specializes_per_shape():
    scale = _make_lazy(out_idx=[1])
    for m in (64, 128, 64, 192):
        a = np.random.default_rng(m).standard_normal((m, 128),
                                                     dtype=np.float32)
        np.testing.assert_allclose(np.asarray(scale(a)), a * 2, rtol=1e-5)
    assert len(scale._kernels) == 3  # m=64 reused


def test_lazy_jit_output_arg_convention():
    scale = _make_lazy()
    a = np.random.default_rng(0).standard_normal((64, 128),
                                                 dtype=np.float32)
    out = np.empty_like(a)
    scale(a, out)
    np.testing.assert_allclose(out, a * 2, rtol=1e-5)


def test_lazy_jit_wrong_arity():
    scale = _make_lazy(out_idx=[1])
    with pytest.raises(TypeError, match="input tensors"):
        scale(np.zeros((64, 128), np.float32), np.zeros((64, 128),
                                                        np.float32))


def test_lazy_jit_inconsistent_dims():
    M = T.dynamic("m")

    @tilelang.lazy_jit(out_idx=[2])
    def add(A: T.Tensor((M, 128), "float32"),
            B: T.Tensor((M, 128), "float32"),
            C: T.Tensor((M, 128), "float32")):
        with T.Kernel(T.ceildiv(M, 64)) as bx:
            s = T.alloc_shared((64, 128), "float32")
            t = T.alloc_shared((64, 128), "float32")
            T.copy(A[bx * 64, 0], s)
            T.copy(B[bx * 64, 0], t)
            for i, j in T.Parallel(64, 128):
                s[i, j] = s[i, j] + t[i, j]
            T.copy(s, C[bx * 64, 0])

    with pytest.raises(ValueError):
        add(np.zeros((64, 128), np.float32), np.zeros((128, 128),
                                                      np.float32))


def test_pass_configs_reach_pallas_call():
    @T.prim_func
    def copy(A: T.Tensor((128, 128), "float32"),
             B: T.Tensor((128, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((128, 128), "float32")
            T.copy(A, s)
            T.copy(s, B)

    k = tilelang.compile(
        copy, pass_configs={"tl.tpu.vmem_limit_bytes": 32 * 1024 * 1024})
    assert f"vmem_limit_bytes={32 * 1024 * 1024}" in k.get_kernel_source()


def test_dimension_semantics_config_including_bare_string():
    def make():
        @T.prim_func
        def copy2(A: T.Tensor((128, 128), "float32"),
                  B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                T.copy(A, s)
                T.copy(s, B)
        return copy2

    k = tilelang.compile(
        make(), pass_configs={"tl.tpu.dimension_semantics": ("arbitrary",)})
    assert 'dimension_semantics=("arbitrary",)' in k.get_kernel_source()
    # a bare string must normalize to a 1-tuple, not iterate per character
    k2 = tilelang.compile(
        make(), pass_configs={"tl.tpu.dimension_semantics": "arbitrary"})
    assert 'dimension_semantics=("arbitrary",)' in k2.get_kernel_source()


def test_lazy_jit_tail_guard_uses_dyn_var():
    # body references M beyond shapes: bounds guard must compile per shape
    M = T.dynamic("m")

    @tilelang.lazy_jit(out_idx=[1])
    def relu_tail(A: T.Tensor((M, 128), "float32"),
                  B: T.Tensor((M, 128), "float32")):
        with T.Kernel(T.ceildiv(M, 64)) as bx:
            s = T.alloc_shared((64, 128), "float32")
            T.copy(A[bx * 64, 0], s)
            for i, j in T.Parallel(64, 128):
                s[i, j] = T.if_then_else(bx * 64 + i < M,
                                         T.max(s[i, j], 0.0), 0.0)
            T.copy(s, B[bx * 64, 0])

    a = np.random.default_rng(0).standard_normal((128, 128),
                                                 dtype=np.float32)
    np.testing.assert_allclose(np.asarray(relu_tail(a)), np.maximum(a, 0),
                               rtol=1e-5)


def test_lazy_jit_out_idx_out_of_range():
    M = T.dynamic("m")

    @tilelang.lazy_jit(out_idx=[5])
    def k(A: T.Tensor((M, 128), "float32"),
          B: T.Tensor((M, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((64, 128), "float32")
            T.copy(A[0, 0], s)
            T.copy(s, B[0, 0])

    with pytest.raises(IndexError, match="out_idx"):
        k(np.zeros((64, 128), np.float32))
