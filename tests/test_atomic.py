"""T.atomic_* lowering (reference src/op/atomic_add.cc semantics).

A global atomic destination accumulates into the tensor's EXISTING
contents: the planner maps it as an inout block (aliased fetch) and
codegen seeds each block's out window from the input at its first
visit. Colliding atomics inside T.Parallel are rejected (VPU lanes
would silently drop updates)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def test_atomic_add_accumulates_into_existing_contents():
    """Split-K-style accumulation: every grid step atomically adds its
    partial tile into the SAME C block (revisited across bs), and C's
    original contents survive (CUDA atomic semantics)."""
    NS, M, N = 4, 128, 128

    @T.prim_func
    def accum(A: T.Tensor((NS * M, N), "float32"),
              C: T.Tensor((M, N), "float32")):
        with T.Kernel(NS) as bs:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A[bs * M, 0], s)
            T.atomic_add(C[0, 0], s)

    k = tilelang.compile(accum)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((NS * M, N)).astype(np.float32)
    c = rng.standard_normal((M, N)).astype(np.float32)
    c0 = c.copy()
    k(a, c)
    want = c0 + a.reshape(NS, M, N).sum(axis=0)
    np.testing.assert_allclose(c, want, rtol=1e-5, atol=1e-5)


def test_atomic_max_into_blocks():
    """Non-revisited atomic (each block visited once) still reads the
    original contents."""
    M, N = 256, 128

    @T.prim_func
    def amax(A: T.Tensor((M, N), "float32"),
             C: T.Tensor((M, N), "float32")):
        with T.Kernel(2) as bx:
            s = T.alloc_shared((128, N), "float32")
            T.copy(A[bx * 128, 0], s)
            T.atomic_max(C[bx * 128, 0], s)

    k = tilelang.compile(amax)
    rng = np.random.default_rng(1)
    a = rng.standard_normal((M, N)).astype(np.float32)
    c = rng.standard_normal((M, N)).astype(np.float32)
    c0 = c.copy()
    k(a, c)
    np.testing.assert_allclose(c, np.maximum(c0, a), rtol=1e-6)


def test_atomic_elementwise_disjoint_in_parallel():
    """Per-element atomics with a bijective index map vectorize fine."""
    M, N = 128, 128

    @T.prim_func
    def bump(A: T.Tensor((M, N), "float32"),
             C: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                T.atomic_add(C[i, j], s[i, j])

    k = tilelang.compile(bump)
    rng = np.random.default_rng(2)
    a = rng.standard_normal((M, N)).astype(np.float32)
    c = rng.standard_normal((M, N)).astype(np.float32)
    c0 = c.copy()
    k(a, c)
    np.testing.assert_allclose(c, c0 + a, rtol=1e-5, atol=1e-5)


def test_atomic_colliding_parallel_rejected():
    """Colliding destinations inside T.Parallel (two lanes per element)
    previously lowered to a silent-wrong-answer vector RMW; they must be
    rejected with reduction guidance (VERDICT r2 weak #4)."""
    M, N = 128, 128

    @T.prim_func
    def histo(A: T.Tensor((M, N), "float32"),
              C: T.Tensor((M,), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                T.atomic_add(C[i], s[i, j])  # j collides

    with pytest.raises(Exception, match="distinct destination|reduce"):
        tilelang.compile(histo)


def test_atomic_with_global_operand_in_parallel():
    """A global tensor read directly as the atomic value must be planned
    like any other elementwise operand (advisor: it previously stayed
    unplanned and failed with an HBM-residency error)."""
    M, N = 128, 128

    @T.prim_func
    def addg(A: T.Tensor((M, N), "float32"),
             C: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            for i, j in T.Parallel(M, N):
                T.atomic_add(C[i, j], A[i, j])

    k = tilelang.compile(addg)
    rng = np.random.default_rng(3)
    a = rng.standard_normal((M, N)).astype(np.float32)
    c = rng.standard_normal((M, N)).astype(np.float32)
    c0 = c.copy()
    k(a, c)
    np.testing.assert_allclose(c, c0 + a, rtol=1e-5, atol=1e-5)


def test_atomic_region_value_in_parallel_rejected():
    """Region-valued atomics inside T.Parallel get the clear guidance
    error, not a cryptic internal one."""
    M, N = 128, 128

    @T.prim_func
    def bad(A: T.Tensor((M, N), "float32"),
            C: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                T.atomic_add(C[i, j], s[i, j:j + 1])

    with pytest.raises(Exception, match="elementwise"):
        tilelang.compile(bad)
