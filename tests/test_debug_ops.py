"""T.print / T.device_assert coverage (reference testing/python/debug +
tilelang/language/print.py). Device-side printing lowers to
pl.debug_print; asserts lower to a guarded debug_print (Mosaic has no
trap op) — both must compile, run, and leave numerics untouched.
"""

import numpy as np

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

M, N = 8, 128


def test_print_buffer_and_scalar_compile_and_run():
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            T.print(s, msg="tile")
            T.print(bx, msg="grid idx")
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] + 1.0
            T.copy(s, O)

    kern = tilelang.compile(k)
    src = kern.get_kernel_source()
    assert src.count("pl.debug_print") == 2
    a = np.random.default_rng(0).standard_normal((M, N)).astype(np.float32)
    out = np.empty_like(a)
    kern(a, out)
    np.testing.assert_allclose(out, a + 1.0, rtol=1e-6)


def test_device_assert_guards_without_perturbing_numerics():
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            T.device_assert(bx >= 0, "grid index sane")
            T.device_assert(bx > 100, "always fails (prints, no trap)")
            T.copy(s, O)

    kern = tilelang.compile(k)
    src = kern.get_kernel_source()
    assert "DEVICE ASSERT FAILED" in src
    a = np.random.default_rng(1).standard_normal((M, N)).astype(np.float32)
    out = np.empty_like(a)
    kern(a, out)
    np.testing.assert_allclose(out, a, rtol=1e-6)
