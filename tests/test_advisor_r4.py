"""Regression pins for the round-4 advisor findings (ADVICE.md r4).

1. flash_attention_varlen causal alignment: the top-left (local
   position) default is documented, and the upstream FlashAttention
   >= 2.1 bottom-right convention is available via
   causal_align="bottom-right" (pos_q + len_k - len_q >= pos_k).
2. nsa_attention_varlen's docstring no longer claims a nonexistent
   "TEnd" mask; it describes the real mechanism (packed causal
   predicate + one block of zero padding).
3. autotune() rejects unknown kwargs with TypeError instead of
   silently ignoring typos; only the reference-parity no-op kwargs
   pass through.
"""

import numpy as np
import pytest

from tilelang_mesh_tpu.ops import flash_attention_varlen


def _ref_dense_align(q, k, v, lens_q, lens_k, align, group):
    """Per-sequence dense reference with selectable causal alignment."""
    B, Sq, Hq, D = q.shape
    out = np.zeros_like(q)
    for b in range(B):
        for h in range(Hq):
            qi = q[b, :lens_q[b], h]
            ki = k[b, :lens_k[b], h // group]
            vi = v[b, :lens_k[b], h // group]
            s = (qi @ ki.T) / np.sqrt(D)
            lq, lk = s.shape
            off = (lk - lq) if align == "bottom-right" else 0
            mask = (np.arange(lq)[:, None] + off) >= np.arange(lk)[None, :]
            s = np.where(mask, s, -np.inf)
            with np.errstate(invalid="ignore"):
                # a fully-masked row (bottom-right, lq > lk) is all -inf
                p = np.exp(s - s.max(-1, keepdims=True, initial=-np.inf))
            p = np.nan_to_num(p, nan=0.0)
            denom = p.sum(-1, keepdims=True)
            p = np.where(denom > 0, p / np.maximum(denom, 1e-30), 0.0)
            out[b, :lens_q[b], h] = p @ vi
    return out


def _pack(x, lens):
    return np.concatenate([x[b, :lens[b]] for b in range(len(lens))], 0)


@pytest.mark.parametrize("align", ["top-left", "bottom-right"])
def test_varlen_causal_alignment(align):
    """Cross-length causal varlen under both alignment conventions
    matches the per-sequence dense reference with the same alignment
    (advisor r4 #1). lens_q != lens_k so the two conventions disagree."""
    B, Hq, Hkv, D = 3, 4, 2, 32
    rng = np.random.default_rng(7)
    lens_q = np.array([17, 5, 40])
    lens_k = np.array([29, 13, 23])     # mixed: lk > lq and lk < lq
    maxq, maxk = lens_q.max(), lens_k.max()
    q = rng.standard_normal((B, maxq, Hq, D)).astype(np.float32)
    k = rng.standard_normal((B, maxk, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((B, maxk, Hkv, D)).astype(np.float32)
    cu_q = np.concatenate([[0], np.cumsum(lens_q)]).astype(np.int32)
    cu_k = np.concatenate([[0], np.cumsum(lens_k)]).astype(np.int32)

    o = np.asarray(flash_attention_varlen(
        _pack(q, lens_q), _pack(k, lens_k), _pack(v, lens_k),
        cu_q, cu_k, causal=True, causal_align=align,
        block_M=32, block_N=32))
    ref = _ref_dense_align(q, k, v, lens_q, lens_k, align, group=2)
    ref_packed = _pack(ref, lens_q)
    np.testing.assert_allclose(o, ref_packed, rtol=2e-2, atol=2e-2)


def test_varlen_alignments_disagree_cross_length():
    """With lens_q != lens_k the two conventions must produce different
    outputs — otherwise the parameter is a silent no-op."""
    B, Hq, Hkv, D = 1, 2, 2, 16
    rng = np.random.default_rng(8)
    lens_q, lens_k = np.array([8]), np.array([24])
    q = rng.standard_normal((B, 8, Hq, D)).astype(np.float32)
    k = rng.standard_normal((B, 24, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((B, 24, Hkv, D)).astype(np.float32)
    cu_q = np.array([0, 8], np.int32)
    cu_k = np.array([0, 24], np.int32)
    o_tl = np.asarray(flash_attention_varlen(
        _pack(q, lens_q), _pack(k, lens_k), _pack(v, lens_k),
        cu_q, cu_k, causal=True, causal_align="top-left",
        block_M=8, block_N=8))
    o_br = np.asarray(flash_attention_varlen(
        _pack(q, lens_q), _pack(k, lens_k), _pack(v, lens_k),
        cu_q, cu_k, causal=True, causal_align="bottom-right",
        block_M=8, block_N=8))
    assert np.abs(o_tl - o_br).max() > 1e-3


def test_varlen_bad_alignment_rejected():
    q = np.zeros((4, 2, 16), np.float32)
    cu = np.array([0, 4], np.int32)
    with pytest.raises(ValueError, match="causal_align"):
        flash_attention_varlen(q, q, q, cu, cu, causal=True,
                               causal_align="diagonal")


def test_varlen_docstring_documents_alignment():
    doc = flash_attention_varlen.__doc__
    assert "top-left" in doc and "bottom-right" in doc
    assert "len_k - len_q" in doc


def test_nsa_varlen_docstring_matches_mechanism():
    """Advisor r4 #2: no phantom 'TEnd'; the documented mechanism is the
    packed causal predicate plus zero padding."""
    import inspect

    from tilelang_mesh_tpu.ops import nsa as nsa_mod
    doc = nsa_mod.nsa_attention_varlen.__doc__
    assert "TEnd" not in doc
    assert "causal predicate" in doc and "zero" in doc
    # and nothing named TEnd exists in the module to drift back in
    src = inspect.getsource(nsa_mod)
    assert "TEnd" not in src


def test_autotune_unknown_kwarg_raises():
    """Advisor r4 #3: a typo must be a TypeError, not a warning."""
    from tilelang_mesh_tpu.autotuner import autotune
    with pytest.raises(TypeError, match="warmups"):
        autotune(warmups=5)
    with pytest.raises(TypeError, match="topk_"):
        autotune(topk_=3)


def test_autotune_parity_kwargs_still_pass():
    """The reference's checking kwargs (tuner.py:685-702) remain
    accepted no-ops so ported call sites keep working."""
    from tilelang_mesh_tpu.autotuner import autotune
    deco = autotune(configs=[{"block": 8}], skip_check=True, rtol=1e-2,
                    atol=1e-2, ref_prog=None)
    assert callable(deco)
