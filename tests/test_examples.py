"""Run every example end-to-end (the reference's test_example_* pattern:
examples double as integration tests, SURVEY §2.4)."""

import pathlib
import runpy
import sys

import pytest

EXAMPLES_DIR = pathlib.Path(__file__).resolve().parents[1] / "examples"

_EXAMPLES = sorted(p for p in EXAMPLES_DIR.rglob("example_*.py"))
_SCRIPTS = [EXAMPLES_DIR / "quickstart.py"] + _EXAMPLES


@pytest.mark.parametrize("script", _SCRIPTS,
                         ids=[str(p.relative_to(EXAMPLES_DIR))
                              for p in _SCRIPTS])
def test_example(script):
    mod = runpy.run_path(str(script))
    assert "main" in mod, f"{script} must define main()"
    mod["main"]()
