"""fleet-proc suite (docs/serving.md "Process isolation & crash
containment"): subprocess engine workers behind the checksummed frame
protocol, SIGKILL-proof zero-loss failover, crash-loop quarantine, and
graceful drain.

Five layers:

1. **Frame protocol** — encode/decode round-trips are bit-exact;
   every adversarial frame (truncated, bit-flipped checksum, oversized
   length prefix, bad magic, header overrun) is a *detected*
   ``FrameError`` classified ``deterministic``, never a silent desync.
2. **Wire formats** — a live ``Request`` and a ``KVSnapshot`` survive
   the pipe byte-conserved; a frame whose body was tampered after the
   crc was stamped still fails the snapshot's own sha256.
3. **Process supervision** — a 2-worker proc fleet serves the same
   tokens the thread fleet does (isolation is behavior-invisible); a
   real ``SIGKILL`` mid-flight loses nothing, the flight dump names
   the dead pid + signal, and the victim restarts under a new pid; a
   torn frame ejects without a worker death; a stalled round-trip
   trips the step watchdog.
4. **Crash containment** — more than ``TL_TPU_FLEET_MAX_RESTARTS``
   deaths inside the window parks the slot (quarantined: no hot
   restart loop) until ``readmit_slot``; ``shutdown(graceful=True)``
   drains, flushes, and returns 0.
5. **Durability + surfaces** — the cache commit fsyncs the file before
   the rename and the directory after it; the analyzer ``fleet``
   report renders worker lifetimes, kill->readmit latency, and the
   ``fleet.ipc.*`` transport counters.
"""

import functools
import itertools
import json
import os
import signal
import struct

import pytest

from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.observability import flight as _flight
from tilelang_mesh_tpu.resilience import inject
from tilelang_mesh_tpu.resilience.errors import classify
from tilelang_mesh_tpu.serving import (Fleet, FrameError,
                                       PagedKVAllocator, Request,
                                       decode_frame, decode_snapshot,
                                       default_workload_factory,
                                       deserialize_request,
                                       encode_frame, encode_snapshot,
                                       reset_prefix_cache,
                                       serialize_request)
from tilelang_mesh_tpu.serving.ipc import MAGIC

PS = 8
_seq = itertools.count()

# spawn pickles the factory by reference: module-level partial only
small_factory = functools.partial(default_workload_factory, n_pages=64)


def make_proc_fleet(n_engines=2, **kw):
    kw.setdefault("name", f"pflt{next(_seq)}")
    return Fleet(small_factory, n_engines=n_engines, isolation="proc",
                 **kw)


def counters():
    return obs.get_tracer().counters()


# -- 1. frame protocol --------------------------------------------------

def test_frame_roundtrip_bit_exact():
    header = {"op": "submit", "cid": 7, "args": {"seed": 3, "t": None}}
    body = bytes(range(256)) * 3
    frame = encode_frame(header, body)
    h2, b2 = decode_frame(frame)
    assert h2 == header
    assert b2 == body
    # deterministic encode: the same message is the same bytes
    assert encode_frame(header, body) == frame
    # empty body round-trips too
    assert decode_frame(encode_frame({"op": "ping"})) == \
        ({"op": "ping"}, b"")


def test_frame_adversarial_decode_classified():
    """Satellite gate: every way a frame can be wrong is a DETECTED,
    classified failure — never an exception escape, never a silent
    desync, never an allocation driven by a hostile length prefix."""
    frame = encode_frame({"op": "step"}, b"x" * 64)
    adversarial = [
        frame[: len(frame) // 2],                      # truncated
        frame[:-10] + bytes([frame[-10] ^ 0x01]) + frame[-9:],  # flip
        MAGIC + struct.pack("<II", (1 << 32) - 1, 0),  # oversized len
        b"NOPE" + frame[4:],                           # bad magic
        b"",                                           # empty
        encode_frame({"op": "x"})[:len(MAGIC) + 8],    # prefix only
    ]
    for bad in adversarial:
        with pytest.raises(FrameError) as ei:
            decode_frame(bad)
        assert classify(ei.value) == "deterministic"
        assert ei.value.site == "fleet.ipc"
    # header length that overruns the payload (crc re-stamped so only
    # the header-length check can reject it)
    payload = struct.pack("<I", 999) + b"{}"
    import zlib
    crafted = MAGIC + struct.pack("<II", len(payload),
                                  zlib.crc32(payload)) + payload
    with pytest.raises(FrameError, match="overruns"):
        decode_frame(crafted)
    # non-object JSON header
    hj = b'["not", "a", "dict"]'
    payload = struct.pack("<I", len(hj)) + hj
    crafted = MAGIC + struct.pack("<II", len(payload),
                                  zlib.crc32(payload)) + payload
    with pytest.raises(FrameError, match="not an object"):
        decode_frame(crafted)


# -- 2. wire formats ----------------------------------------------------

def test_request_wire_roundtrip_bit_exact():
    req = Request(2 * PS, 4, deadline_ms=5000.0, seed=5,
                  payload={"k": "v"},
                  prompt_tokens=list(range(100, 100 + 2 * PS)),
                  temperature=0.7, top_p=0.9, tenant="acme")
    req.steps_done = 2
    req.retries = 1
    req.generated = [11, 12]
    wire = serialize_request(req, cid=42)
    # the image must survive the JSON header of a frame
    wire = json.loads(json.dumps(wire))
    assert wire["cid"] == 42
    assert 0.0 < wire["deadline_ms"] <= 5000.0
    r2 = deserialize_request(wire)
    assert r2.context_tokens == req.context_tokens
    assert r2.new_tokens == req.new_tokens
    assert r2.prompt_tokens == req.prompt_tokens
    assert r2.generated == [11, 12]
    assert r2.steps_done == 2
    assert r2.retries == 1
    assert (r2.temperature, r2.top_p) == (0.7, 0.9)
    assert r2.tenant == "acme"
    assert r2.seed == 5
    assert r2.payload["k"] == "v"
    # the origin trace id rides along for post-mortems
    assert r2.payload["origin_trace_id"] == req.trace_id
    # no deadline stays no deadline
    r3 = deserialize_request(serialize_request(
        Request(PS, 1, prompt_tokens=list(range(PS))), cid=1))
    assert r3.deadline is None


def test_snapshot_wire_roundtrip_and_tamper():
    alloc = PagedKVAllocator(n_pages=8, page_size=PS, heads=2,
                             head_dim=4)
    pages = alloc.alloc(3, owner=77)
    alloc.kp[:, pages[0] * PS:(pages[0] + 1) * PS, :] = 1.5
    alloc.vp[:, pages[1] * PS:(pages[1] + 1) * PS, :] = -2.25
    snap = alloc.snapshot()
    frame = encode_snapshot(snap)
    got = decode_snapshot(frame)
    assert got.owners == {77: pages}
    assert got.checksum == snap.checksum
    import numpy as np
    for p in pages:
        np.testing.assert_array_equal(got.pages[p][0], snap.pages[p][0])
        np.testing.assert_array_equal(got.pages[p][1], snap.pages[p][1])
    # tamper INSIDE a re-stamped frame: the crc passes, the snapshot's
    # own sha256 must still catch it
    header, body = decode_frame(frame)
    body = bytearray(body)
    body[len(body) // 2] ^= 0xFF
    with pytest.raises(FrameError, match="checksum"):
        decode_snapshot(encode_frame(header, bytes(body)))


# -- 3. process supervision ---------------------------------------------

def test_proc_fleet_tokens_match_thread_fleet_and_shutdown(tmp_path,
                                                           monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path / "px"))
    reset_prefix_cache()
    prompt = list(range(300, 300 + 2 * PS))

    def drive(fleet):
        reqs = [fleet.submit(len(prompt), new_tokens=3,
                             prompt_tokens=list(prompt), seed=10 + i)
                for i in range(4)]
        fleet.run()
        return reqs

    ref = drive(Fleet(small_factory, n_engines=2, isolation="thread",
                      name=f"tref{next(_seq)}"))
    fleet = make_proc_fleet(n_engines=2)
    try:
        reqs = drive(fleet)
        assert all(r.outcome == "result" for r in reqs)
        # isolation is behavior-invisible: same tokens, same outcomes
        assert [r.generated for r in reqs] == \
            [r.generated for r in ref]
        # health names real pids and the isolation mode
        h = fleet.health()
        assert h["isolation"] == "proc"
        for s in fleet.slots:
            eh = h["engines"][s.name]
            assert eh["pid"] == s.engine.pid
            assert eh["alive"] is True
        assert all(not v for v in fleet.leak_check().values())
    finally:
        assert fleet.shutdown(graceful=True) == 0
        reset_prefix_cache()
    # after shutdown: admission is closed, terminally (never lost)
    r = fleet.submit(2 * PS, new_tokens=1, seed=99)
    assert r.is_terminal and r.outcome == "shed"


def test_proc_sigkill_zero_loss_flight_dump_and_new_pid(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path / "px"))
    reset_prefix_cache()
    obs.reset()
    _flight.reset()
    _flight.configure(dump_dir=tmp_path / "flight")
    fleet = make_proc_fleet(n_engines=2, restart_base_ms=50.0)
    try:
        prompt = [9_000 + i for i in range(2 * PS)]
        seed_req = fleet.submit(len(prompt), new_tokens=1,
                                prompt_tokens=list(prompt), seed=1)
        fleet.run()
        assert seed_req.outcome == "result"   # prefix published
        reqs = [fleet.submit(len(prompt), new_tokens=2,
                             prompt_tokens=list(prompt), seed=2 + i)
                for i in range(6)]
        victim = fleet.slots[0]
        on_victim = [r for r in reqs if r in victim.engine.requests]
        assert on_victim                      # shadows held supervisor-side
        pid0 = victim.engine.pid
        os.kill(pid0, signal.SIGKILL)
        fleet.step()                          # death detected -> failover
        assert victim.state == "ejected"
        assert fleet.failovers == 1
        fleet.run()
        assert all(r.outcome == "result" for r in reqs)   # zero loss
        c = counters()
        assert c["fleet.worker.death{engine=%s}" % victim.name] == 1
        assert c.get("fleet.failover.lost", 0) == 0
        assert c.get("fleet.failover.warm", 0) >= 1   # disk-tier warm
        # the black box names the dead PROCESS, not just the slot
        dumps = sorted((tmp_path / "flight").glob("*.jsonl"))
        assert dumps
        head = json.loads(dumps[0].read_text().splitlines()[0])
        assert head["reason"] == "engine_failover"
        assert head["attrs"]["victim"] == victim.name
        assert head["attrs"]["pid"] == pid0
        assert head["attrs"]["signal"] == int(signal.SIGKILL)
        assert set(head["attrs"]["redispatched_trace_ids"]) == \
            {r.trace_id for r in on_victim}
        # the victim restarts as a NEW process and serves again
        assert fleet.await_readmission(timeout_s=60.0)
        assert victim.engine.pid != pid0
        assert c["fleet.worker.death{engine=%s}" % victim.name] == 1
    finally:
        fleet.shutdown(graceful=True)
        _flight.configure(dump_dir=None)
        _flight.reset()
        reset_prefix_cache()


def test_torn_frame_ejects_without_worker_death(tmp_path, monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path / "px"))
    reset_prefix_cache()
    obs.reset()
    fleet = make_proc_fleet(n_engines=2, restart_base_ms=50.0)
    try:
        reqs = [fleet.submit(2 * PS, new_tokens=2, seed=i)
                for i in range(4)]
        victim = fleet.slots[0].name
        with inject("fleet.ipc", kind="torn", times=1):
            fleet.step()                 # e0 pumps first: frame torn
        assert fleet.slots[0].state == "ejected"
        # a torn frame is a TRANSPORT failure: the worker process never
        # died — no fleet.worker.death, but a deterministic ipc error
        c = counters()
        assert c.get("fleet.worker.death{engine=%s}" % victim, 0) == 0
        assert any("fleet.ipc.errors" in k and "kind=deterministic" in k
                   and victim in k for k in c)
        fleet.run()
        assert all(r.outcome == "result" for r in reqs)   # zero loss
        assert fleet.await_readmission(timeout_s=60.0)
    finally:
        fleet.shutdown(graceful=True)
        reset_prefix_cache()


def test_stalled_roundtrip_trips_step_watchdog(tmp_path, monkeypatch):
    """The watchdog covers the WHOLE round-trip: a reply that lands
    past ``TL_TPU_FLEET_STEP_TIMEOUT_MS`` is a timeout ejection even
    though the worker is alive and eventually answers."""
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path / "px"))
    reset_prefix_cache()
    obs.reset()
    fleet = make_proc_fleet(n_engines=2, step_timeout_ms=2000.0,
                            restart_base_ms=50.0)
    try:
        fleet.warmup()        # keep compile out of the watchdogged step
        reqs = [fleet.submit(2 * PS, new_tokens=1, seed=i)
                for i in range(4)]
        with inject("fleet.ipc", kind="delay", times=1):
            fleet.step()                 # stalls 2x the watchdog
        assert fleet.slots[0].state == "ejected"
        c = counters()
        assert any("fleet.ipc.errors" in k and "kind=timeout" in k
                   for k in c)
        fleet.run()
        assert all(r.outcome == "result" for r in reqs)
    finally:
        fleet.shutdown(graceful=True)
        reset_prefix_cache()


# -- 4. crash containment -----------------------------------------------

def make_thread_fleet(**kw):
    kw.setdefault("name", f"tflt{next(_seq)}")
    return Fleet(functools.partial(default_workload_factory,
                                   n_pages=128),
                 n_engines=2, isolation="thread", **kw)


def test_crash_loop_quarantine_and_manual_readmit(tmp_path,
                                                  monkeypatch):
    monkeypatch.setenv("TL_TPU_FLEET_MAX_RESTARTS", "1")
    monkeypatch.setenv("TL_TPU_FLEET_RESTART_WINDOW_S", "60")
    obs.reset()
    _flight.reset()
    _flight.configure(dump_dir=tmp_path / "flight")
    try:
        fleet = make_thread_fleet(restart_base_ms=5.0)
        victim = fleet.slots[0]
        with inject("serve.engine", kind="unreachable", times=1):
            fleet.step()
        assert victim.state == "ejected"      # death 1: normal ejection
        assert fleet.await_readmission(timeout_s=10.0)
        with inject("serve.engine", kind="unreachable", times=1):
            fleet.step()
        # death 2 > max_restarts inside the window: PARKED, no restart
        assert victim.state == "quarantined"
        assert counters()[
            "fleet.quarantined{engine=%s}" % victim.name] == 1
        assert victim.name in fleet.health()["quarantined"]
        dumps = sorted((tmp_path / "flight").glob("*.jsonl"))
        heads = [json.loads(d.read_text().splitlines()[0])
                 for d in dumps]
        assert any(h["reason"] == "crash_loop" for h in heads)
        # a parked slot takes no traffic and is NOT probed by stepping
        for i in range(3):
            fleet.submit(2 * PS, new_tokens=1, seed=i)
            fleet.step()
        assert victim.state == "quarantined"
        assert victim.submitted == 0
        fleet.run()
        # the operator override probes NOW and clears the window
        assert fleet.readmit_slot(victim.name) is True
        assert victim.state == "live"
        r = fleet.submit(2 * PS, new_tokens=1, seed=9)
        fleet.run()
        assert r.outcome == "result"
    finally:
        _flight.configure(dump_dir=None)
        _flight.reset()


def test_graceful_shutdown_drains_flushes_and_exits_zero(monkeypatch,
                                                         tmp_path):
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path / "px"))
    reset_prefix_cache()
    try:
        fleet = make_thread_fleet()
        reqs = [fleet.submit(2 * PS, new_tokens=2, seed=i)
                for i in range(5)]
        prev = fleet.install_signal_handler(signal.SIGTERM)
        try:
            assert signal.getsignal(signal.SIGTERM) is not prev
        finally:
            signal.signal(signal.SIGTERM, prev)
        assert fleet.shutdown(graceful=True) == 0
        # drained, not dropped: every in-flight request reached result
        assert all(r.outcome == "result" for r in reqs)
        assert fleet.health()["draining"] is True
        late = fleet.submit(2 * PS, new_tokens=1, seed=77)
        assert late.is_terminal and late.outcome == "shed"
    finally:
        reset_prefix_cache()


# -- 5. durability + surfaces -------------------------------------------

def test_atomic_write_fsyncs_file_then_dir(tmp_path, monkeypatch):
    """Satellite pin: the cache commit is tmp + fsync(file) + rename +
    fsync(dir) — rename alone only orders the directory entry, and a
    host crash could surface a committed name over zero-length data."""
    from tilelang_mesh_tpu.cache.kernel_cache import atomic_write
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(
        os, "fsync", lambda fd: (synced.append(fd), real_fsync(fd))[1])
    target = tmp_path / "entry.json"
    atomic_write(target, '{"v": 1}')
    assert target.read_text() == '{"v": 1}'
    assert len(synced) >= 2              # file fd, then the parent dir
    assert not list(tmp_path.glob("*.tmp.*"))


def test_atomic_write_failed_fsync_leaves_old_state(tmp_path,
                                                    monkeypatch):
    from tilelang_mesh_tpu.cache.kernel_cache import atomic_write
    target = tmp_path / "entry.json"
    atomic_write(target, "old")

    def boom(fd):
        raise OSError("disk gone")

    monkeypatch.setattr(os, "fsync", boom)
    with pytest.raises(OSError, match="disk gone"):
        atomic_write(target, "new")
    # the failed commit is invisible: old content, no tmp debris
    assert target.read_text() == "old"
    assert not list(tmp_path.glob("*.tmp.*"))


def test_flight_dump_commit_is_all_or_nothing(tmp_path):
    """The torn window, via the existing ``cache.disk.write`` fault
    site: a failed dump commit leaves NOTHING on disk (no half-written
    file, no tmp debris) and is non-fatal; the next dump lands whole."""
    _flight.reset()
    _flight.configure(dump_dir=tmp_path)
    try:
        with inject("cache.disk.write", kind="oserror", times=1):
            assert _flight.dump("proc_torn_probe", k=1) is None
        assert list(tmp_path.iterdir()) == []    # nothing committed
        path = _flight.dump("proc_torn_probe", k=2)
        assert path is not None and path.exists()
        head = json.loads(path.read_text().splitlines()[0])
        assert head["reason"] == "proc_torn_probe"
        assert not list(tmp_path.glob("*.tmp.*"))
    finally:
        _flight.configure(dump_dir=None)
        _flight.reset()


def test_analyzer_fleet_proc_section():
    from tilelang_mesh_tpu.tools.analyzer import (format_fleet_report,
                                                  summarize_fleet)
    records = [
        {"type": "counter", "name": "fleet.dispatch{engine=f/e0}",
         "value": 4},
        {"type": "counter", "name": "fleet.worker.spawn{engine=f/e0}",
         "value": 2},
        {"type": "counter", "name": "fleet.worker.death{engine=f/e0}",
         "value": 1},
        {"type": "counter", "name": "fleet.quarantined{engine=f/e1}",
         "value": 1},
        {"type": "counter", "name": "fleet.ipc.tx{engine=f/e0}",
         "value": 10},
        {"type": "counter", "name": "fleet.ipc.rx{engine=f/e0}",
         "value": 9},
        {"type": "counter", "name": "fleet.ipc.bytes_tx{engine=f/e0}",
         "value": 2048},
        {"type": "counter", "name": "fleet.ipc.bytes_rx{engine=f/e0}",
         "value": 4096},
        {"type": "counter",
         "name": "fleet.ipc.errors{engine=f/e0,kind=device_loss}",
         "value": 1},
        {"type": "event", "name": "fleet.worker.spawn",
         "attrs": {"engine": "f/e0", "pid": 1234}},
        {"type": "event", "name": "fleet.worker.spawn",
         "attrs": {"engine": "f/e0", "pid": 1299}},
        {"type": "event", "name": "fleet.worker.death",
         "attrs": {"engine": "f/e0", "pid": 1234, "exitcode": -9,
                   "signal": 9}},
        {"type": "event", "name": "fleet.readmit",
         "attrs": {"fleet": "f", "engine": "f/e0", "restarts": 1,
                   "down_ms": 812.5, "pid": 1299}},
    ]
    s = summarize_fleet(records)
    assert s["worker_spawns"] == {"f/e0": 2}
    assert s["worker_deaths"] == {"f/e0": 1}
    assert s["quarantined"] == {"f/e1": 1}
    assert s["ipc_tx"] == {"f/e0": 10}
    assert s["ipc_errors"] == {"device_loss": 1}
    assert s["kill_to_readmit_ms"] == [812.5]
    assert s["worker_death_events"][0]["pid"] == 1234
    txt = format_fleet_report(records)
    assert "process workers (isolation=proc):" in txt
    assert "f/e0: spawned=2 died=1 pids=[1234, 1299]" in txt
    assert "pid 1234 died (signal 9)" in txt
    assert "f/e1: quarantined x1 (crash loop)" in txt
    assert "kill -> readmit latency: n=1" in txt
    assert "ipc frames:" in txt
    assert "tx=10 rx=9 bytes_tx=2048 bytes_rx=4096" in txt
    assert "errors: device_loss=1" in txt
