"""NSA + attention-sink numerics (reference examples/deepseek_nsa and
examples/attention_sink test behavior)."""

import numpy as np
import pytest

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.attention_sink import (attention_sink,
                                                  attention_sink_reference)
from tilelang_mesh_tpu.ops.nsa import (nsa_attention, nsa_decode,
                                       nsa_reference)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def _rand(shape, seed, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), dtype)


# ---------------------------------------------------------------- sink ----
@pytest.mark.parametrize("window", [None, 48])
def test_attention_sink_mha(window):
    B, H, S, D = 1, 2, 128, 64
    q, k, v = (_rand((B, H, S, D), i) for i in range(3))
    sinks = _rand((H,), 7)
    out = attention_sink(q, k, v, sinks, causal=True, window_size=window,
                         block_M=32, block_N=32)
    ref = attention_sink_reference(q, k, v, sinks, causal=True,
                                   window_size=window)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_attention_sink_gqa():
    B, Hq, Hkv, S, D = 1, 4, 2, 128, 64
    q = _rand((B, Hq, S, D), 0)
    k = _rand((B, Hkv, S, D), 1)
    v = _rand((B, Hkv, S, D), 2)
    sinks = _rand((Hq,), 3)
    out = attention_sink(q, k, v, sinks, causal=True, block_M=64, block_N=64)
    ref = attention_sink_reference(q, k, v, sinks, causal=True)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_attention_sink_noncausal():
    B, H, S, D = 1, 1, 64, 32
    q, k, v = (_rand((B, H, S, D), 10 + i) for i in range(3))
    sinks = jnp.asarray([0.5], jnp.float32)
    out = attention_sink(q, k, v, sinks, causal=False, block_M=32,
                         block_N=32)
    ref = attention_sink_reference(q, k, v, sinks, causal=False)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


# ----------------------------------------------------------------- nsa ----
def _nsa_inputs(B, Tq, HQ, H, D, S, BS, seed=0):
    """Random inputs with valid causal block selections (each token always
    selects its own block, like the reference test generator)."""
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, Tq, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    g_slc = jnp.asarray(rng.uniform(0.2, 1.0, (B, Tq, HQ)), jnp.float32)
    g_swa = jnp.asarray(rng.uniform(0.2, 1.0, (B, Tq, HQ)), jnp.float32)
    bi = np.zeros((B, Tq, H, S), np.int64)
    for b in range(B):
        for t in range(Tq):
            own = t // BS
            for h in range(H):
                picks = rng.choice(own + 1, size=min(S, own + 1),
                                   replace=False)
                row = np.full(S, -1)
                row[:len(picks)] = picks
                if own not in picks:
                    row[0] = own
                bi[b, t, h] = row
    return q, k, v, g_slc, g_swa, jnp.asarray(bi, jnp.int32)


def test_nsa_fwd_selected_only():
    B, Tq, HQ, H, D, S, BS = 1, 64, 4, 2, 32, 3, 16
    q, k, v, g_slc, g_swa, bi = _nsa_inputs(B, Tq, HQ, H, D, S, BS)
    out = nsa_attention(q, k, v, g_slc, g_swa, bi, block_size=BS)
    ref = nsa_reference(q, k, v, g_slc, g_swa, bi, block_size=BS)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_nsa_fwd_with_window():
    B, Tq, HQ, H, D, S, BS = 1, 64, 2, 1, 32, 2, 16
    W = 24
    q, k, v, g_slc, g_swa, bi = _nsa_inputs(B, Tq, HQ, H, D, S, BS, seed=1)
    out = nsa_attention(q, k, v, g_slc, g_swa, bi, block_size=BS,
                        window_size=W)
    ref = nsa_reference(q, k, v, g_slc, g_swa, bi, block_size=BS,
                        window_size=W)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_nsa_fwd_block_counts():
    B, Tq, HQ, H, D, S, BS = 1, 32, 2, 1, 16, 2, 8
    q, k, v, g_slc, g_swa, bi = _nsa_inputs(B, Tq, HQ, H, D, S, BS, seed=2)
    rng = np.random.default_rng(3)
    cnts = jnp.asarray(rng.integers(1, S + 1, (B, Tq, H)), jnp.int32)
    out = nsa_attention(q, k, v, g_slc, g_swa, bi, block_counts=cnts,
                        block_size=BS)
    ref = nsa_reference(q, k, v, g_slc, g_swa, bi, block_counts=cnts,
                        block_size=BS)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_nsa_decode():
    B, Tk, HQ, H, D, S, BS = 1, 64, 4, 2, 32, 3, 16
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((B, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Tk, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Tk, H, D)), jnp.float32)
    g = jnp.asarray(rng.uniform(0.2, 1.0, (B, HQ)), jnp.float32)
    bi = np.stack([rng.choice(Tk // BS, S, replace=False)
                   for _ in range(B * H)]).reshape(B, H, S)
    bi = jnp.asarray(bi, jnp.int32)
    out = nsa_decode(q, k, v, g, bi, block_size=BS)
    # decode == fwd at the last token with those selections
    g_full = jnp.zeros((B, Tk, HQ), jnp.float32).at[:, -1].set(g)
    bi_full = jnp.broadcast_to(bi[:, None], (B, Tk, H, S))
    ref = nsa_reference(jnp.broadcast_to(q[:, None], (B, Tk, HQ, D)),
                        k, v, g_full, jnp.zeros_like(g_full), bi_full,
                        block_size=BS)[:, -1]
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------- seer ----------
def test_seer_attention():
    from tilelang_mesh_tpu.ops.seer_attention import (seer_attention,
                                                      seer_reference)
    B, H, S, D, bm, bn = 1, 2, 128, 32, 32, 32
    q, k, v = (_rand((B, H, S, D), 20 + i) for i in range(3))
    gates = _rand((B, H, S // bm, S // bn), 23)
    out = seer_attention(q, k, v, gates, topk=2, block_M=bm, block_N=bn)
    ref = seer_reference(q, k, v, gates, 2, bm, bn)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_blocksparse_causal():
    from tilelang_mesh_tpu.ops.blocksparse_attention import (
        blocksparse_attention, blocksparse_reference)
    B, H, S, D, bm, bn = 1, 1, 128, 32, 32, 32
    q, k, v = (_rand((B, H, S, D), 30 + i) for i in range(3))
    rng = np.random.default_rng(33)
    mask = jnp.asarray(rng.integers(0, 2, (B, H, S // bm, S // bn)),
                       jnp.int32)
    # keep the diagonal on so no row is fully masked
    mask = mask.at[:, :, jnp.arange(S // bm), jnp.arange(S // bn)].set(1)
    out = blocksparse_attention(q, k, v, mask, block_M=bm, block_N=bn,
                                causal=True)
    ref = blocksparse_reference(q, k, v, mask, bm, bn, causal=True)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


# ----------------------------------------------------- minference ---------
def test_vertical_slash_sparse():
    from tilelang_mesh_tpu.ops.minference import (
        vertical_slash_sparse_attention, vs_sparse_reference)
    B, H, S, D = 1, 2, 256, 32
    q, k, v = (_rand((B, H, S, D), 40 + i) for i in range(3))
    rng = np.random.default_rng(44)
    v_idx = jnp.asarray(np.stack(
        [rng.choice(S, 8, replace=False) for _ in range(B * H)]
    ).reshape(B, H, 8), jnp.int32)
    # always include the main diagonal so every row attends something
    s_idx = jnp.asarray(np.stack(
        [np.concatenate([[0], rng.choice(np.arange(1, S), 3, replace=False)])
         for _ in range(B * H)]).reshape(B, H, 4), jnp.int32)
    out = vertical_slash_sparse_attention(q, k, v, v_idx, s_idx,
                                          block_M=64, block_N=64)
    ref = vs_sparse_reference(q, k, v, v_idx, s_idx)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_seer_rectangular_blocks():
    """block_M != block_N: causal block-visibility must use element ranges
    (regression: kb <= qb is wrong for rectangular blocks)."""
    from tilelang_mesh_tpu.ops.seer_attention import (seer_attention,
                                                      seer_reference,
                                                      seer_block_mask)
    B, H, S, D, bm, bn = 1, 1, 128, 32, 64, 32
    q, k, v = (_rand((B, H, S, D), 50 + i) for i in range(3))
    gates = _rand((B, H, S // bm, S // bn), 53)
    # with topk == nK every causally visible block must be selected: the
    # diagonal key blocks of query block 1 (kb=2,3) must be live
    mask = seer_block_mask(gates, topk=S // bn, block_M=bm, block_N=bn)
    assert bool(mask[0, 0, 1, 2]) and bool(mask[0, 0, 1, 3])
    out = seer_attention(q, k, v, gates, topk=2, block_M=bm, block_N=bn)
    ref = seer_reference(q, k, v, gates, 2, bm, bn)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def _nsa_dense_jax(q, k, v, g_slc, bi, cnt, BS, scale=None):
    """jnp-differentiable dense NSA reference (selected branch only)."""
    import jax.numpy as jnp

    B, Tq, HQ, D = q.shape
    H = k.shape[2]
    G = HQ // H
    S = bi.shape[-1]
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    Tk = k.shape[1]
    # dense visibility (B, Tq, H, Tk) from the block selection
    t = jnp.arange(Tq)[None, :, None, None]
    kk = jnp.arange(Tk)[None, None, None, :]
    vis = jnp.zeros((B, Tq, H, Tk), bool)
    for s in range(S):
        b_s = bi[..., s]                                     # (B,Tq,H)
        ok = (b_s >= 0) & (b_s * BS <= t[..., 0]) & \
             (s < cnt)
        in_blk = (kk // BS == b_s[..., None]) & ok[..., None]
        vis = vis | in_blk
    vis = vis & (kk <= t)
    s_ = jnp.einsum("bthgd,bkhd->bthgk",
                    q.reshape(B, Tq, H, G, D), k) * scale
    s_ = jnp.where(vis[:, :, :, None, :], s_, -jnp.inf)
    m = s_.max(-1, keepdims=True)
    p = jnp.exp(s_ - jnp.where(jnp.isfinite(m), m, 0.0))
    denom = p.sum(-1, keepdims=True)
    p = jnp.where(denom > 0, p / jnp.maximum(denom, 1e-30), 0.0)
    o = jnp.einsum("bthgk,bkhd->bthgd", p, v)
    return (o * g_slc.reshape(B, Tq, H, G)[..., None]
            ).reshape(B, Tq, HQ, D)


def test_nsa_bwd_matches_dense_ad():
    """dQ/dK/dV/dg through the NSA tile backward vs jax AD of the dense
    selected-branch graph (reference example_tilelang_nsa_bwd.py)."""
    import jax

    B, Tq, HQ, H, D, S, BS = 1, 32, 4, 2, 32, 3, 8
    q, k, v, g_slc, _g_swa, bi = _nsa_inputs(B, Tq, HQ, H, D, S, BS,
                                             seed=5)
    cnt = jnp.full((B, Tq, H), S, jnp.int32)
    go = jnp.asarray(np.random.default_rng(9).standard_normal(
        (B, Tq, HQ, D)), jnp.float32)

    def loss_kernel(q, k, v, g_slc):
        o = nsa_attention(q, k, v, g_slc, jnp.zeros_like(g_slc), bi,
                          block_size=BS, backward="kernel")
        return jnp.sum(o * go)

    def loss_ref(q, k, v, g_slc):
        return jnp.sum(_nsa_dense_jax(q, k, v, g_slc, bi, cnt, BS) * go)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2, 3))(q, k, v, g_slc)
    want = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, g_slc)
    for name, a, b in zip(("dQ", "dK", "dV", "dG"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)


def test_nsa_bwd_forward_value_matches_fused():
    """backward='kernel' primal == the fused inference kernel (window
    off, swa gate irrelevant)."""
    B, Tq, HQ, H, D, S, BS = 1, 32, 2, 1, 32, 2, 8
    q, k, v, g_slc, g_swa, bi = _nsa_inputs(B, Tq, HQ, H, D, S, BS,
                                            seed=6)
    a = nsa_attention(q, k, v, g_slc, g_swa, bi, block_size=BS)
    b = nsa_attention(q, k, v, g_slc, g_swa, bi, block_size=BS,
                      backward="kernel")
    assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-2)


def test_nsa_bwd_rejects_window():
    B, Tq, HQ, H, D, S, BS = 1, 16, 2, 1, 16, 2, 8
    q, k, v, g_slc, g_swa, bi = _nsa_inputs(B, Tq, HQ, H, D, S, BS,
                                            seed=7)
    with pytest.raises(ValueError, match="window_size == 0"):
        nsa_attention(q, k, v, g_slc, g_swa, bi, block_size=BS,
                      window_size=8, backward="kernel")


def test_nsa_bwd_duplicate_indices_multiplicity():
    """A block listed twice in block_indices carries 2x softmax mass in
    the forward gather; dK/dV must scale by the multiplicity to stay
    gradients OF the computed primal."""
    import jax

    B, Tq, HQ, H, D, S, BS = 1, 16, 2, 1, 16, 3, 8
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((B, Tq, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    g = jnp.ones((B, Tq, HQ), jnp.float32)
    # every token selects block 0 TWICE plus its own block
    bi = np.zeros((B, Tq, H, S), np.int64)
    for t in range(Tq):
        bi[0, t, 0] = [0, 0, t // BS]
    bi = jnp.asarray(bi, jnp.int32)
    go = jnp.asarray(rng.standard_normal((B, Tq, HQ, D)), jnp.float32)

    def loss(q, k, v):
        o = nsa_attention(q, k, v, g, jnp.zeros_like(g), bi,
                          block_size=BS, backward="kernel")
        return jnp.sum(o * go)

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # finite-difference check on a k element INSIDE the duplicated block
    eps = 1e-3
    k2 = k.at[0, 3, 0, 5].add(eps)
    fd = (float(loss(q, k2, v)) - float(loss(q, k, v))) / eps
    np.testing.assert_allclose(float(got[1][0, 3, 0, 5]), fd, rtol=5e-2,
                               atol=5e-2)


def test_nsa_bwd_rejects_nondivisible_kv():
    B, Tq, HQ, H, D, S, BS = 1, 20, 2, 1, 16, 2, 8   # 20 % 8 != 0
    rng = np.random.default_rng(12)
    q = jnp.asarray(rng.standard_normal((B, Tq, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    g = jnp.ones((B, Tq, HQ), jnp.float32)
    bi = jnp.zeros((B, Tq, H, S), jnp.int32)
    with pytest.raises(ValueError, match="multiple of block_size"):
        nsa_attention(q, k, v, g, g, bi, block_size=BS,
                      backward="kernel")


def test_nsa_varlen_fwd_matches_per_sequence():
    """Varlen NSA == per-sequence dense NSA reference: sequence-local
    block ids, no attention across boundaries."""
    from tilelang_mesh_tpu.ops.nsa import nsa_attention_varlen

    rng = np.random.default_rng(21)
    lens = [24, 40, 9]
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    total = int(cu[-1])
    HQ, H, D, S, BS = 4, 2, 32, 3, 8
    q = jnp.asarray(rng.standard_normal((total, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, H, D)), jnp.float32)
    g = jnp.asarray(rng.uniform(0.2, 1.0, (total, HQ)), jnp.float32)
    # per-token sequence-LOCAL causal selections incl. the own block
    bi = np.full((total, H, S), -1, np.int64)
    for b in range(len(lens)):
        for tl in range(lens[b]):
            own = tl // BS
            for h in range(H):
                picks = rng.choice(own + 1, size=min(S, own + 1),
                                   replace=False)
                row = np.full(S, -1)
                row[:len(picks)] = picks
                if own not in picks:
                    row[0] = own
                bi[cu[b] + tl, h] = row
    bi = jnp.asarray(bi, jnp.int32)

    out = np.asarray(nsa_attention_varlen(q, k, v, g, bi, cu,
                                          block_size=BS))

    # reference: run each sequence through the dense batch NSA reference
    for b in range(len(lens)):
        lo, hi = int(cu[b]), int(cu[b + 1])
        ref = nsa_reference(q[None, lo:hi], k[None, lo:hi],
                            v[None, lo:hi], g[None, lo:hi],
                            jnp.zeros((1, hi - lo, HQ), jnp.float32),
                            bi[None, lo:hi], block_size=BS)
        np.testing.assert_allclose(out[lo:hi], np.asarray(ref)[0],
                                   rtol=2e-2, atol=2e-2,
                                   err_msg=f"sequence {b}")


def test_nsa_varlen_no_cross_sequence_leak():
    """Selecting the LAST local block of a short sequence must not leak
    the next sequence's keys (window pokes past the boundary)."""
    from tilelang_mesh_tpu.ops.nsa import nsa_attention_varlen

    rng = np.random.default_rng(22)
    lens = [12, 20]          # 12 % BS != 0: block 1 of seq 0 is partial
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    total = int(cu[-1])
    HQ, H, D, S, BS = 2, 1, 16, 2, 8
    q = jnp.asarray(rng.standard_normal((total, HQ, D)), jnp.float32)
    k1 = rng.standard_normal((total, H, D)).astype(np.float32)
    k2 = k1.copy()
    k2[12:] += 100.0          # perturb ONLY sequence 1's keys
    v = jnp.asarray(rng.standard_normal((total, H, D)), jnp.float32)
    g = jnp.ones((total, HQ), jnp.float32)
    bi = np.full((total, H, S), -1, np.int64)
    for tl in range(12):
        bi[tl, 0, 0] = tl // BS
        if tl // BS == 1:
            bi[tl, 0, 1] = 0
    for tl in range(20):
        bi[12 + tl, 0, 0] = tl // BS
    bi = jnp.asarray(bi, jnp.int32)

    o1 = np.asarray(nsa_attention_varlen(q, jnp.asarray(k1), v, g, bi,
                                         cu, block_size=BS))
    o2 = np.asarray(nsa_attention_varlen(q, jnp.asarray(k2), v, g, bi,
                                         cu, block_size=BS))
    np.testing.assert_allclose(o1[:12], o2[:12], rtol=1e-5, atol=1e-5,
                               err_msg="sequence 0 saw sequence 1's keys")


@pytest.mark.parametrize("Hq,Hkv", [(2, 2), (4, 2)])
def test_sink_bwd_matches_reference_ad(Hq, Hkv):
    """dQ/dK/dV/dsinks through the sink backward (sink-less recompute
    kernels + XLA sink fold) vs jax AD of the dense sink reference."""
    import jax

    B, S, D = 1, 128, 64
    rng = np.random.default_rng(31)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    sinks = jnp.asarray(rng.standard_normal((Hq,)), jnp.float32)
    go = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)

    def loss_kernel(q, k, v, sinks):
        o = attention_sink(q, k, v, sinks, causal=True, block_M=64,
                           block_N=64, backward="kernel")
        return jnp.sum(o * go)

    def loss_ref(q, k, v, sinks):
        return jnp.sum(attention_sink_reference(
            q, k, v, sinks, causal=True) * go)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2, 3))(q, k, v, sinks)
    want = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, sinks)
    for name, a, b in zip(("dQ", "dK", "dV", "dSinks"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)


def test_sink_bwd_forward_matches_fused():
    B, Hq, Hkv, S, D = 1, 2, 1, 128, 64
    rng = np.random.default_rng(33)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    sinks = jnp.asarray(rng.standard_normal((Hq,)), jnp.float32)
    a = attention_sink(q, k, v, sinks, causal=True, block_M=64,
                       block_N=64)
    b = attention_sink(q, k, v, sinks, causal=True, block_M=64,
                       block_N=64, backward="kernel")
    assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-2)


def test_sink_bwd_rejects_window():
    B, Hq, S, D = 1, 2, 64, 64
    q = jnp.zeros((B, Hq, S, D), jnp.float32)
    sinks = jnp.zeros((Hq,), jnp.float32)
    with pytest.raises(ValueError, match="window_size=None"):
        attention_sink(q, q, q, sinks, causal=True, window_size=32,
                       block_M=64, block_N=64, backward="kernel")
