"""GQA backward kernels vs jax AD of the dense reference.

Mirrors the reference's example_gqa_bwd.py check: dQ/dK/dV from the tile
kernels (dK/dV accumulated across the query-head group) must match
autodiff through the dense softmax-attention graph.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tilelang_mesh_tpu.ops.gqa import _reference_gqa, gqa_attention


def _grads(fn, q, k, v, seed):
    g = jnp.asarray(np.random.default_rng(seed).standard_normal(
        np.asarray(fn(q, k, v)).shape), q.dtype)

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v) * g)

    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_bwd_matches_reference_ad(causal):
    B, Hq, Hkv, S, D = 1, 4, 2, 64, 64
    rng = np.random.default_rng(0 if causal else 1)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)

    kern = lambda q, k, v: gqa_attention(q, k, v, causal=causal,
                                         block_M=32, block_N=32)
    ref = lambda q, k, v: _reference_gqa(q, k, v, causal,
                                         1.0 / np.sqrt(D))
    got = _grads(kern, q, k, v, seed=7)
    want = _grads(ref, q, k, v, seed=7)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-2, atol=3e-2,
            err_msg=f"{name} mismatch (causal={causal})")


def test_gqa_bwd_group_accumulation():
    """With Hkv=1 every query head feeds the same dK/dV: halving the
    number of query heads must (roughly) halve ||dK||, proving the group
    accumulation actually sums over heads."""
    B, S, D = 1, 32, 64
    rng = np.random.default_rng(3)
    k = jnp.asarray(rng.standard_normal((B, 1, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, 1, S, D)), jnp.float32)
    q1 = jnp.asarray(rng.standard_normal((B, 1, S, D)), jnp.float32)
    q4 = jnp.concatenate([q1] * 4, axis=1)

    def dk_norm(q):
        def loss(k):
            return jnp.sum(gqa_attention(q, k, v, block_M=32, block_N=32))
        return float(jnp.linalg.norm(jax.grad(loss)(k)))

    n1, n4 = dk_norm(q1), dk_norm(q4)
    assert 2.0 < n4 / max(n1, 1e-9) < 8.0, (n1, n4)


def test_gqa_fwd_partial_consistent_with_plain():
    """partial kernel's normalized output == plain forward kernel."""
    from tilelang_mesh_tpu.ops.gqa import (gqa_fwd_kernel,
                                           gqa_fwd_partial_kernel)
    B, Hq, Hkv, S, D = 1, 4, 2, 64, 64
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    sm = 1.0 / np.sqrt(D)
    plain = gqa_fwd_kernel(B, Hq, Hkv, S, S, D, 32, 32, True, sm,
                           "float32")(q, k, v)
    acc, m, l = gqa_fwd_partial_kernel(B, Hq, Hkv, S, S, D, 32, 32, True,
                                       sm, "float32")(q, k, v)
    np.testing.assert_allclose(np.asarray(acc / l[..., None]),
                               np.asarray(plain), rtol=2e-2, atol=2e-2)
