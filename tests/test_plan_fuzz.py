"""Seeded planner fuzz: random tiled-copy kernels vs a numpy model.

The reference covers its layout-inference pipeline with hand-picked
golden cases; this adds property-style coverage on top of ours: randomly
generated grids, block shapes, and block-index maps (affine with random
coefficients, modular wraps, swizzles) are planned, compiled
(interpret), executed, and checked against a numpy evaluation of the
same index arithmetic. Every case is deterministic (seeded) so a failure
reproduces; shapes stay tiny so the whole sweep runs in seconds.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

BM, BN = 8, 128


def _case(rng):
    """One random kernel spec: grid extent, #blocks in A, index map."""
    g = int(rng.integers(2, 5))            # grid extent
    nblk = int(rng.integers(1, 5))         # blocks in A
    kind = rng.choice(["affine", "mod", "swizzle", "div"])
    if kind == "affine":
        c = int(rng.integers(0, 2))        # coeff 0 or 1 (whole blocks)
        k = int(rng.integers(0, max(1, nblk - c * (g - 1))))
        fn = lambda bx: c * bx + k
        ok = c * (g - 1) + k < nblk
    elif kind == "mod":
        m = int(rng.integers(1, nblk + 1))
        fn = lambda bx: bx % m
        ok = m <= nblk
    elif kind == "div":
        d = int(rng.integers(1, 4))
        fn = lambda bx: bx // d
        ok = (g - 1) // d < nblk
    else:
        # swizzle over an even grid: (bx // 2) + (bx % 2) * (g // 2)
        fn = lambda bx: (bx // 2) + (bx % 2) * (g // 2)
        ok = max(fn(b) for b in range(g)) < nblk
    return g, nblk, kind, fn, ok


def _build(g, nblk, fn):
    @T.prim_func
    def k(A: T.Tensor((nblk * BM, BN), "float32"),
          O: T.Tensor((g * BM, BN), "float32")):
        with T.Kernel(g) as bx:
            s = T.alloc_shared((BM, BN), "float32")
            T.copy(A[fn(bx) * BM, 0], s)
            for i, j in T.Parallel(BM, BN):
                s[i, j] = s[i, j] + 1.0
            T.copy(s, O[bx * BM, 0])
    return k


@pytest.mark.parametrize("seed", range(12))
def test_random_tiled_copy_kernel(seed):
    rng = np.random.default_rng(1000 + seed)
    g, nblk, kind, fn, ok = _case(rng)
    if not ok:
        pytest.skip("index map exceeds source blocks (generator reject)")
    k = tilelang.compile(_build(g, nblk, fn))
    a = rng.standard_normal((nblk * BM, BN)).astype(np.float32)
    out = np.empty((g * BM, BN), np.float32)
    k(a, out)
    ref = np.concatenate(
        [a[fn(b) * BM:(fn(b) + 1) * BM] + 1.0 for b in range(g)])
    np.testing.assert_allclose(out, ref, rtol=1e-6,
                               err_msg=f"case: g={g} nblk={nblk} {kind}")


@pytest.mark.parametrize("seed", range(8))
def test_random_two_axis_output_map(seed):
    """2-D grids writing O[f(by), g(bx)] blocks: exercises the revisit
    analysis + multi-axis index maps under random coefficients."""
    rng = np.random.default_rng(2000 + seed)
    gy, gx = int(rng.integers(1, 4)), int(rng.integers(1, 4))

    @T.prim_func
    def k(A: T.Tensor((gy * BM, gx * BN), "float32"),
          O: T.Tensor((gy * BM, gx * BN), "float32")):
        with T.Kernel(gx, gy) as (bx, by):
            s = T.alloc_shared((BM, BN), "float32")
            T.copy(A[by * BM, bx * BN], s)
            for i, j in T.Parallel(BM, BN):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, O[by * BM, bx * BN])

    kern = tilelang.compile(k)
    a = rng.standard_normal((gy * BM, gx * BN)).astype(np.float32)
    out = np.empty_like(a)
    kern(a, out)
    np.testing.assert_allclose(out, a * 2.0, rtol=1e-6)
