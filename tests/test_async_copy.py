"""Split-phase DMA (T.copy_async / T.copy_wait / T.alloc_semaphore) —
TPU-native warp-specialization analog (reference
src/transform/warp_specialized_rewriter.cc behavior)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def test_double_buffered_gemm():
    M, N, K, BK = 128, 128, 512, 128
    nstep = K // BK

    @T.prim_func
    def db(A: T.Tensor((M, K), "float32"),
           B: T.Tensor((K, N), "float32"),
           C: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            A_s = T.alloc_shared((2, M, BK), "float32")
            B_s = T.alloc_shared((2, BK, N), "float32")
            acc = T.alloc_fragment((M, N), "float32")
            sems = T.alloc_semaphore(4)
            T.clear(acc)
            T.copy_async(A[0, 0], A_s[0, 0:M, 0:BK], sems, 0)
            T.copy_async(B[0, 0], B_s[0, 0:BK, 0:N], sems, 2)
            for ko in range(nstep):
                cur, nxt = ko % 2, (ko + 1) % 2
                if ko + 1 < nstep:
                    T.copy_async(A[0, (ko + 1) * BK],
                                 A_s[nxt, 0:M, 0:BK], sems, nxt)
                    T.copy_async(B[(ko + 1) * BK, 0],
                                 B_s[nxt, 0:BK, 0:N], sems, 2 + nxt)
                T.copy_wait(A[0, ko * BK], A_s[cur, 0:M, 0:BK], sems, cur)
                T.copy_wait(B[ko * BK, 0], B_s[cur, 0:BK, 0:N],
                            sems, 2 + cur)
                T.gemm(A_s[cur, 0:M, 0:BK], B_s[cur, 0:BK, 0:N], acc)
            T.copy(acc, C)

    k = tilelang.compile(db)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    c = np.empty((M, N), np.float32)
    k(a, b, c)
    np.testing.assert_allclose(c, a @ b, rtol=1e-2, atol=1e-1)


def test_async_vmem_to_hbm_store():
    M, N = 128, 256

    @T.prim_func
    def st(A: T.Tensor((M, N), "float32"),
           B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            sems = T.alloc_semaphore(1)
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] + 1.0
            T.copy_async(s, B, sems, 0)
            T.copy_wait(s, B, sems, 0)

    k = tilelang.compile(st)
    a = np.random.default_rng(1).standard_normal((M, N), dtype=np.float32)
    out = np.empty_like(a)
    k(a, out)
    np.testing.assert_allclose(out, a + 1, rtol=1e-6)


def test_copy_async_requires_semaphore_buffer():
    with pytest.raises(Exception, match="alloc_semaphore"):
        @T.prim_func
        def bad(A: T.Tensor((64, 64), "float32"),
                B: T.Tensor((64, 64), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((64, 64), "float32")
                notsem = T.alloc_shared((4,), "int32")
                T.copy_async(A, s, notsem, 0)

        tilelang.compile(bad)


def test_copy_async_rejects_dtype_conversion():
    with pytest.raises(Exception, match="convert dtypes"):
        @T.prim_func
        def bad(A: T.Tensor((64, 64), "float32"),
                B: T.Tensor((64, 64), "bfloat16")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((64, 64), "bfloat16")
                sems = T.alloc_semaphore(1)
                T.copy_async(A, s, sems, 0)

        tilelang.compile(bad)
