"""Conv2D kernel vs lax.conv_general_dilated (reference
examples/convolution/test_example_convolution.py behavior)."""

import numpy as np
import pytest

from examples.convolution.example_convolution import convolution, ref_conv2d


@pytest.mark.parametrize("N,C,H,W,F,K,S,D,P", [
    (2, 128, 16, 16, 128, 3, 1, 1, 1),   # the canonical 3x3 same conv
    (1, 64, 17, 17, 128, 3, 2, 1, 1),    # stride 2, odd spatial
    (1, 32, 16, 16, 64, 3, 1, 2, 2),     # dilation 2
    (1, 128, 8, 8, 128, 1, 1, 1, 0),     # 1x1 conv == GEMM
    (1, 32, 12, 12, 64, 5, 2, 1, 2),     # 5x5 stride 2
])
def test_conv2d(N, C, H, W, F, K, S, D, P):
    kernel = convolution(N, C, H, W, F, K, S, D, P,
                         block_F=min(128, F))
    rng = np.random.default_rng(0)
    data = rng.standard_normal((N, H, W, C), dtype=np.float32)
    weight = rng.standard_normal((K, K, C, F), dtype=np.float32)
    padded = np.pad(data, ((0, 0), (P, P), (P, P), (0, 0)))
    OH = (H + 2 * P - D * (K - 1) - 1) // S + 1
    OW = (W + 2 * P - D * (K - 1) - 1) // S + 1
    out = np.empty((N, OH, OW, F), dtype=np.float32)
    kernel(padded, weight, out)
    ref = np.asarray(ref_conv2d(data, weight, S, P, D))
    np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-1)
