"""tl-sol suite: kernel-grain speed-of-light profiling, roofline gap
attribution, and tuned-config drift detection (docs/observability.md
"Speed-of-light profiling & drift").

Six layers, mirroring the subsystem:

1. **Analytic terms** — ``analytic_terms`` decomposes the roofline into
   named terms whose total is bit-identical to ``analytic_ms`` (the
   tuner and the profiler must never disagree about the prediction),
   and names the dominant bottleneck.
2. **Sampling** — ``TL_TPU_SOL=1`` alone turns the dispatch timing hook
   on; sampled dispatches aggregate into per-kernel SoL records with
   achieved/predicted/SoL%/gap attribution; off by default means ZERO
   records (the fast-dispatch overhead gate stays honest).
3. **Drift** — the seeded EWMA+MAD detector: stable under noise, fires
   exactly once per episode (edge-triggered), re-fires after the
   episode clears, resets its baseline on config or CODEGEN_VERSION
   change, and every firing raises the counter + flight dump + retune
   queue entry.
4. **Fleet store** — checksummed atomic entries, corruption quarantined
   (never trusted, never deleted), commutative idempotent merges, the
   merge/list/stats CLI.
5. **Surfaces** — the ``/prof`` endpoint, strict Prometheus exposition
   (+Inf bucket == _count), ``analyzer sol`` / ``analyzer flight``,
   the dash SoL trend column (old rounds missing-not-regressed), and
   bench's ``sol`` field.
6. **Serving soak** — a tuned bucket with an injected tiny prediction
   drifts under real step latency: ``sol.drift`` fires, the flight
   dump names the kernel/config, ``/prof`` lists the bucket.
"""

import json
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.observability import flight
from tilelang_mesh_tpu.observability import sol


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset()
    yield
    obs.reset()


def _scale_func(mult=2.0, M=16, N=32):
    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * mult
            T.copy(s, B)
    return scale


def _feats(**over):
    from tilelang_mesh_tpu.transform.plan import FEATURES_VERSION
    base = {"version": FEATURES_VERSION, "flops": 1 << 30,
            "hbm_bytes": 1 << 24, "vpu_elems": 0, "grid_steps": 16,
            "vmem_arena": 1 << 20, "vmem_block_bytes": 1 << 18,
            "n_scratch": 2, "n_params": 3, "pipelined": 1,
            "block_rows": 128, "block_cols": 128, "block_skew": 1.0,
            "dbuf_chains": 0}
    base.update(over)
    return base


# ---------------------------------------------------------------------------
# 1. analytic terms
# ---------------------------------------------------------------------------

class TestAnalyticTerms:
    def test_total_bit_identical_to_analytic_ms(self):
        from tilelang_mesh_tpu.autotuner.cost_model import (analytic_ms,
                                                            analytic_terms)
        for f in (_feats(), _feats(flops=1 << 36),
                  _feats(hbm_bytes=1 << 32, flops=1 << 20),
                  _feats(pipelined=0, dbuf_chains=0),
                  _feats(vpu_elems=1 << 28, flops=0),
                  _feats(grid_steps=4096)):
            terms = analytic_terms(f)
            assert terms["total_ms"] == analytic_ms(f)

    def test_terms_and_bottleneck_named(self):
        from tilelang_mesh_tpu.autotuner.cost_model import analytic_terms
        terms = analytic_terms(_feats())
        for k in ("t_mxu_ms", "t_hbm_ms", "t_vpu_ms", "t_ici_ms",
                  "t_serial_ms", "t_grid_ms", "roof", "bottleneck",
                  "total_ms"):
            assert k in terms
        assert terms["roof"] in ("mxu", "hbm", "vpu")
        assert terms["bottleneck"] in ("mxu", "hbm", "vpu", "ici",
                                       "serial", "grid")
        # a compute monster pins the roof (and bottleneck) on the MXU
        big = analytic_terms(_feats(flops=1 << 44, hbm_bytes=1 << 10,
                                    grid_steps=1))
        assert big["roof"] == "mxu" and big["bottleneck"] == "mxu"


# ---------------------------------------------------------------------------
# 2. dispatch sampling -> SoL records
# ---------------------------------------------------------------------------

@pytest.fixture()
def sol_on(monkeypatch, tmp_path):
    """Profiling ON, every call sampled, hermetic cache dir."""
    monkeypatch.setenv("TL_TPU_SOL", "1")
    monkeypatch.setenv("TL_TPU_RUNTIME_SAMPLE", "1")
    monkeypatch.delenv("TL_TPU_RUNTIME_METRICS", raising=False)
    monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
    tilelang.clear_cache()
    yield tmp_path
    tilelang.clear_cache()


class TestDispatchSampling:
    def test_sampled_dispatch_builds_sol_record(self, sol_on):
        k = tilelang.compile(_scale_func(), target="cpu")
        a = np.random.default_rng(0).random((16, 32), np.float32)
        b = np.zeros((16, 32), np.float32)
        for _ in range(4):
            k(a, b)
        recs = sol.sol_records()
        assert len(recs) == 1
        r = recs[0]
        assert r["kernel"] == "scale"
        assert r["count"] >= 2              # first call warms, unsampled
        assert r["achieved_ms"] > 0
        assert r["predicted_ms"] > 0
        assert 0 < r["sol_pct"] <= 1.5      # CPU achieved >> TPU roofline
        assert r["bottleneck"] in ("mxu", "hbm", "vpu", "ici",
                                   "serial", "grid")
        for key in ("serialization_ms", "ici_ms", "grid_overhead_ms",
                    "host_overhead_ms", "unexplained_ms"):
            assert key in r["gap"]
        # TL_TPU_SOL alone enabled the runtime timing hook
        from tilelang_mesh_tpu.observability import runtime
        assert runtime.runtime_enabled()
        assert obs.get_tracer().counters()["sol.records"] == r["count"]

    def test_off_by_default_no_records(self, monkeypatch, tmp_path):
        monkeypatch.delenv("TL_TPU_SOL", raising=False)
        monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
        tilelang.clear_cache()
        k = tilelang.compile(_scale_func(3.0), target="cpu")
        a = np.ones((16, 32), np.float32)
        b = np.zeros((16, 32), np.float32)
        for _ in range(3):
            k(a, b)
        assert sol.sol_records() == []
        assert "sol.records" not in obs.get_tracer().counters()
        tilelang.clear_cache()

    def test_numerics_unchanged_under_profiling(self, sol_on):
        k = tilelang.compile(_scale_func(2.0), target="cpu")
        a = np.random.default_rng(1).random((16, 32), np.float32)
        b = np.zeros((16, 32), np.float32)
        k(a, b)
        k(a, b)
        np.testing.assert_allclose(b, a * 2.0, rtol=1e-6)


# ---------------------------------------------------------------------------
# 3. drift detection
# ---------------------------------------------------------------------------

@pytest.fixture()
def drift_knobs(monkeypatch):
    monkeypatch.setenv("TL_TPU_SOL_DRIFT", "1")
    monkeypatch.setenv("TL_TPU_SOL_DRIFT_ALPHA", "0.5")
    monkeypatch.setenv("TL_TPU_SOL_DRIFT_WARMUP", "3")
    monkeypatch.setenv("TL_TPU_SOL_DRIFT_SUSTAIN", "2")
    monkeypatch.setenv("TL_TPU_SOL_DRIFT_MADS", "6")
    monkeypatch.setenv("TL_TPU_SOL_DRIFT_MIN_REL", "0.5")


class TestDrift:
    def test_stable_under_seeded_noise(self, drift_knobs):
        rng = np.random.default_rng(42)
        for _ in range(80):
            ev = sol.observe_bucket("wl", "b4:p2",
                                    measured_ms=1.0 + rng.normal(0, 0.05),
                                    predicted_ms=1.0, config={"b": 4})
            assert ev is None
        assert "sol.drift" not in obs.get_tracer().counters()
        assert sol.retune_queue() == []

    def test_fires_once_per_episode_then_refires(self, drift_knobs,
                                                 tmp_path):
        flight.configure(dump_dir=tmp_path)
        events = [sol.observe_bucket("wl", "b4:p2", measured_ms=3.0,
                                     predicted_ms=1.0, config={"b": 4})
                  for _ in range(30)]
        fired = [e for e in events if e is not None]
        assert len(fired) == 1              # edge-triggered, once
        ev = fired[0]
        assert ev["episode"] == 1 and ev["ratio"] > 1.5
        assert obs.get_tracer().counters()["sol.drift"] == 1
        # clearing the episode re-arms the edge
        for _ in range(30):
            sol.observe_bucket("wl", "b4:p2", measured_ms=1.0,
                               predicted_ms=1.0, config={"b": 4})
        second = [sol.observe_bucket("wl", "b4:p2", measured_ms=3.0,
                                     predicted_ms=1.0, config={"b": 4})
                  for _ in range(30)]
        refired = [e for e in second if e is not None]
        assert len(refired) == 1 and refired[0]["episode"] == 2
        assert obs.get_tracer().counters()["sol.drift"] == 2
        # each firing wrote a flight dump naming kernel and config
        dumps = sorted(tmp_path.glob("flight_*_sol_drift_*.jsonl"))
        assert len(dumps) == 2
        hdr = json.loads(dumps[0].read_text().splitlines()[0])
        assert hdr["reason"] == "sol_drift"
        assert hdr["attrs"]["kernel"] == "wl"
        assert hdr["attrs"]["config"] == {"b": 4}

    def test_baseline_resets_on_config_change(self, drift_knobs):
        for _ in range(10):
            sol.observe_bucket("wl", "b4:p2", measured_ms=3.0,
                               predicted_ms=1.0, config={"b": 4})
        # a retune landed: new config -> fresh baseline, back in warmup
        ev = sol.observe_bucket("wl", "b4:p2", measured_ms=3.0,
                                predicted_ms=1.0, config={"b": 8})
        assert ev is None
        st = sol.get_sol()._drift[("wl", "b4:p2")]
        assert st.n == 1 and not st.in_episode

    def test_baseline_resets_on_codegen_version(self, drift_knobs,
                                                monkeypatch):
        for _ in range(10):
            sol.observe_bucket("wl", "b4:p2", measured_ms=3.0,
                               predicted_ms=1.0, config={"b": 4})
        assert sol.get_sol()._drift[("wl", "b4:p2")].in_episode
        from tilelang_mesh_tpu.cache import kernel_cache
        monkeypatch.setattr(kernel_cache, "CODEGEN_VERSION",
                            "test-bumped")
        ev = sol.observe_bucket("wl", "b4:p2", measured_ms=3.0,
                                predicted_ms=1.0, config={"b": 4})
        assert ev is None
        assert sol.get_sol()._drift[("wl", "b4:p2")].n == 1

    def test_retune_queue_order_cap_and_pop(self, drift_knobs,
                                            monkeypatch):
        monkeypatch.setenv("TL_TPU_SOL_RETUNE_MAX", "2")
        for bucket in ("b1:p1", "b2:p2", "b3:p3"):
            for _ in range(10):
                sol.observe_bucket("wl", bucket, measured_ms=3.0,
                                   predicted_ms=1.0, config={})
        q = sol.retune_queue()
        assert [e["bucket"] for e in q] == ["b2:p2", "b3:p3"]  # capped
        assert sol.pop_retune()["bucket"] == "b2:p2"           # FIFO
        assert [e["bucket"] for e in sol.retune_queue()] == ["b3:p3"]

    def test_disabled_drift_never_fires(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_SOL_DRIFT", "0")
        for _ in range(30):
            assert sol.observe_bucket("wl", "b", 99.0, 1.0) is None
        assert sol.retune_queue() == []


# ---------------------------------------------------------------------------
# 4. fleet store
# ---------------------------------------------------------------------------

def _entry(kernel="k", achieved=2.0, predicted=1.0, count=3, **over):
    e = {"schema": sol.SOL_SCHEMA, "kernel": kernel, "arch": "tpu_v5e",
         "count": count, "achieved_ms": achieved,
         "predicted_ms": predicted,
         "sol_pct": (predicted / achieved) if achieved else None,
         "bottleneck": "hbm", "terms": None, "rewrites": [],
         "host_overhead_ms": 0.01, "merges": 0}
    e.update(over)
    return e


class TestSolStore:
    def test_round_trip_checksummed(self, tmp_path):
        store = sol.SolStore(tmp_path / "s")
        key = store.key("k", "tpu_v5e")
        store.record(key, _entry())
        got = store.get(key)
        assert got["kernel"] == "k" and got["achieved_ms"] == 2.0
        assert got["checksum"] == sol.entry_checksum(got)
        assert store.stats()["entries"] == 1

    def test_corruption_quarantined_not_trusted(self, tmp_path):
        store = sol.SolStore(tmp_path / "s")
        key = store.key("k", "tpu_v5e")
        store.record(key, _entry())
        p = store._path(key)
        body = json.loads(p.read_text())
        body["achieved_ms"] = 0.0001      # forged: checksum now stale
        p.write_text(json.dumps(body))
        assert store.get(key) is None     # quarantine-and-miss
        assert not p.exists()
        qdir = store.root / sol.QUARANTINE_DIR
        assert len(list(qdir.glob("*.json*"))) == 1
        assert store.stats()["quarantined"] == 1
        # a fresh record repopulates the slot
        store.record(key, _entry(achieved=1.5))
        assert store.get(key)["achieved_ms"] == 1.5

    def test_merge_commutative_idempotent_best_wins(self):
        a = _entry(achieved=2.0, count=3)
        b = _entry(achieved=1.2, count=5)
        ab = sol.merge_sol_payloads(a, b)
        ba = sol.merge_sol_payloads(b, a)
        assert ab["achieved_ms"] == ba["achieved_ms"] == 1.2
        assert ab["count"] == ba["count"] == 5          # max, not sum
        assert ab["sol_pct"] == pytest.approx(1.0 / 1.2)
        aa = sol.merge_sol_payloads(a, a)
        assert aa["merges"] == 0                        # fixed point
        assert {k: v for k, v in aa.items() if k != "merges"} == \
            {k: v for k, v in a.items() if k != "merges"}

    def test_merge_from_dirs_and_cli(self, tmp_path, capsys):
        src = sol.SolStore(tmp_path / "src")
        src.record(src.key("k1", "a"), _entry(kernel="k1"))
        src.record(src.key("k2", "a"), _entry(kernel="k2", achieved=4.0))
        # corrupt source entry: skipped, counted, never adopted
        bad = src.root / "deadbeef.json"
        bad.write_text("{not json")
        dst = sol.SolStore(tmp_path / "dst")
        dst.record(dst.key("k2", "a"), _entry(kernel="k2", achieved=1.0))
        stats = dst.merge_from([src.root])
        assert stats == {"examined": 3, "new": 1, "merged": 0,
                         "unchanged": 1, "corrupt": 1}
        assert dst.get(dst.key("k2", "a"))["achieved_ms"] == 1.0
        # the CLI spells the same merge + stats + list
        assert sol.main(["merge", str(src.root), "--into",
                         str(tmp_path / "dst2"), "--json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["new"] == 2 and out["corrupt"] == 1
        assert sol.main(["stats", "--root", str(tmp_path / "dst2"),
                         "--json"]) == 0
        st = json.loads(capsys.readouterr().out)
        assert st["entries"] == 2 and st["quarantined"] == 0
        assert sol.main(["list", "--root", str(tmp_path / "dst2")]) == 0
        assert "k1" in capsys.readouterr().out

    def test_write_store_from_live_profiler(self, sol_on, tmp_path):
        k = tilelang.compile(_scale_func(), target="cpu")
        a = np.ones((16, 32), np.float32)
        b = np.zeros((16, 32), np.float32)
        for _ in range(3):
            k(a, b)
        n = sol.write_store(tmp_path / "store")
        assert n == 1
        store = sol.SolStore(tmp_path / "store")
        stats = store.stats()
        assert stats["entries"] == 1 and stats["with_sol_pct"] == 1


# ---------------------------------------------------------------------------
# 5. surfaces: sweep artifact, analyzer, /prof, Prometheus, bench, dash
# ---------------------------------------------------------------------------

def _sweep_artifact(tmp_path):
    """A synthetic two-kernel sweep JSONL (what run_sweep writes)."""
    rows = [
        {"type": "sol_context", "schema": sol.SOL_SCHEMA, "kernels": 2,
         "with_prediction": 2, "dispatched": 2},
        {"type": "sol", "schema": sol.SOL_SCHEMA, "kernel": "gemm",
         "count": 3, "achieved_ms": 2.0, "predicted_ms": 1.0,
         "sol_pct": 0.5, "bottleneck": "mxu", "host_overhead_ms": 0.01,
         "gap": {"serialization_ms": 0.0, "ici_ms": 0.0,
                 "grid_overhead_ms": 0.1, "host_overhead_ms": 0.01,
                 "unexplained_ms": 1.0}, "arch": "tpu_v5e"},
        {"type": "sol", "schema": sol.SOL_SCHEMA, "kernel": "decode",
         "count": 2, "achieved_ms": 4.0, "predicted_ms": None,
         "sol_pct": None, "bottleneck": None, "arch": "tpu_v5e"},
    ]
    p = tmp_path / "sweep.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    return p


class TestAnalyzerSol:
    def test_summarize_and_report(self, tmp_path, capsys):
        from tilelang_mesh_tpu.tools import analyzer
        p = _sweep_artifact(tmp_path)
        assert analyzer.main(["sol", str(p)]) == 0
        out = capsys.readouterr().out
        assert "2 kernel(s), 1 with an analytic prediction" in out
        assert "gemm" in out and "50.0%" in out and "mxu" in out
        assert analyzer.main(["sol", str(p), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["kernels"] == 2 and doc["with_prediction"] == 1
        assert doc["rows"]["gemm"]["sol_pct"] == 0.5
        assert doc["bottlenecks"] == {"mxu": 1}

    def test_store_footer(self, tmp_path, capsys):
        from tilelang_mesh_tpu.tools import analyzer
        store = sol.SolStore(tmp_path / "s")
        store.record(store.key("k", "a"), _entry())
        p = _sweep_artifact(tmp_path)
        assert analyzer.main(["sol", str(p), "--store",
                              str(store.root)]) == 0
        assert "fleet sol store" in capsys.readouterr().out

    def test_scheduler_column(self, tmp_path, capsys):
        """Records carrying the tile-opt auto scheduler's decision get
        a scheduler cell; pre-scheduler records (no "sched" key) render
        '-' so old sweeps keep parsing."""
        from tilelang_mesh_tpu.tools import analyzer
        rows = [
            {"type": "sol", "schema": sol.SOL_SCHEMA, "kernel": "gemm",
             "count": 3, "achieved_ms": 2.0, "predicted_ms": 1.0,
             "sol_pct": 0.5, "bottleneck": "mxu",
             "sched": {"chosen": ["narrow", "fuse"],
                       "gap_closed_ms": 0.0123},
             "arch": "tpu_v5e"},
            {"type": "sol", "schema": sol.SOL_SCHEMA, "kernel": "old",
             "count": 1, "achieved_ms": 1.0, "predicted_ms": 1.0,
             "sol_pct": 1.0, "bottleneck": "hbm", "arch": "tpu_v5e"},
        ]
        p = tmp_path / "sweep.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        assert analyzer.main(["sol", str(p)]) == 0
        out = capsys.readouterr().out
        assert "scheduler" in out
        assert "narrow+fuse (-0.0123ms)" in out
        assert analyzer.main(["sol", str(p), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["rows"]["gemm"]["sched"]["chosen"] == \
            ["narrow", "fuse"]
        assert doc["rows"]["old"]["sched"] is None
        assert analyzer._sched_cell(None) == "-"
        assert analyzer._sched_cell({"chosen": [],
                                     "gap_closed_ms": None}) == "none"


class TestAnalyzerFlight:
    def test_dump_post_mortem(self, tmp_path, capsys):
        from tilelang_mesh_tpu.tools import analyzer
        flight.configure(dump_dir=tmp_path)
        tr = obs.get_tracer()
        tr.inc("sol.records", 7)
        tr.event("sol.drift", "sol", kernel="wl", bucket="b4:p2")
        p = flight.dump("sol_drift", kernel="wl", bucket="b4:p2",
                        config={"b": 4}, predicted_ms=1.0, ewma_ms=3.0,
                        ratio=3.0)
        assert p is not None
        assert analyzer.main(["flight", str(p)]) == 0
        out = capsys.readouterr().out
        assert "reason=sol_drift" in out
        assert "attr kernel = wl" in out
        assert "sol.records" in out and "slo state" in out
        assert analyzer.main(["flight", str(p), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["header"]["attrs"]["bucket"] == "b4:p2"
        assert doc["counters"]["sol.records"] == 7
        assert doc["ring"]["n"] >= 2

    def test_non_dump_exits_nonzero(self, tmp_path, capsys):
        from tilelang_mesh_tpu.tools import analyzer
        p = tmp_path / "not_a_dump.jsonl"
        p.write_text(json.dumps({"type": "span", "name": "x"}) + "\n")
        assert analyzer.main(["flight", str(p)]) == 1
        assert "not a flight dump" in capsys.readouterr().out


def _round(tmp_path, name, n, rc, records):
    tail = "\n".join(json.dumps(r) for r in records)
    p = tmp_path / name
    p.write_text(json.dumps({"n": n, "cmd": "bench", "rc": rc,
                             "tail": tail}))
    return str(p)


class TestDashSolColumn:
    def test_trend_column_and_old_rounds(self, tmp_path, capsys):
        from tilelang_mesh_tpu.tools import analyzer
        # r01: pre-sol round (no sol field) — must still parse, and the
        # column reads '-' (missing-not-regressed, never an error)
        r1 = _round(tmp_path, "BENCH_r01.json", 1, 0,
                    [{"config": "k", "latency_p50_ms": 1.0,
                      "latency_mad_ms": 0.01}])
        r2 = _round(tmp_path, "BENCH_r02.json", 2, 0,
                    [{"config": "k", "latency_p50_ms": 1.01,
                      "latency_mad_ms": 0.01,
                      "sol": {"kernel": "gemm", "sol_pct": 0.42,
                              "bottleneck": "mxu"}}])
        assert analyzer.main(["dash", r1, r2, "--json"]) == 0
        dash = json.loads(capsys.readouterr().out)
        cells = dash["configs"]["k"]["cells"]
        assert cells[0]["sol_pct"] is None
        assert cells[1]["sol_pct"] == 0.42
        assert dash["configs"]["k"]["sol_pct"] == 0.42   # latest wins
        assert analyzer.main(["dash", r1, r2]) == 0
        out = capsys.readouterr().out
        assert "sol%" in out and "42.0%" in out

    def test_checked_in_rounds_still_parse(self, capsys):
        import glob
        from pathlib import Path

        from tilelang_mesh_tpu.tools import analyzer
        repo = Path(__file__).resolve().parent.parent
        rounds = sorted(glob.glob(str(repo / "BENCH_r0*.json")))
        assert len(rounds) >= 5
        assert analyzer.main(["dash", *rounds, "--json"]) == 0
        dash = json.loads(capsys.readouterr().out)
        # pre-sol rounds read '-' in the column: no config may ERROR
        for cfg in dash["configs"].values():
            assert "sol_pct" in cfg


class TestProfEndpoint:
    def test_prof_route_serves_snapshot(self, drift_knobs, monkeypatch,
                                        tmp_path):
        from tilelang_mesh_tpu.observability import server
        monkeypatch.setenv("TL_TPU_SOL", "1")
        flight.configure(dump_dir=tmp_path)
        for _ in range(10):
            sol.observe_bucket("FlashDecodeWorkload", "b4:p2",
                               measured_ms=3.0, predicted_ms=1.0,
                               config={"b": 4})
        srv = server.start_server(port=0)
        try:
            with urllib.request.urlopen(f"{srv.url}/prof",
                                        timeout=5) as r:
                assert r.status == 200
                doc = json.loads(r.read().decode())
            assert doc["schema"] == sol.SOL_SCHEMA
            assert doc["enabled"] is True
            assert doc["drift"]["episodes"] == 1
            assert [e["bucket"] for e in doc["retune_queue"]] == \
                ["b4:p2"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{srv.url}/nope", timeout=5)
            assert ei.value.code == 404
            assert "/prof" in ei.value.read().decode()
        finally:
            srv.stop()


_EXPO_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{([a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*",?)*\})?'
    r' [0-9eE+.\-]+(inf|nan)?$')


def _parse_samples(text):
    """name -> [(labels-dict-frozenset, value)] for every sample line."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _EXPO_LINE.match(line), f"unparseable exposition: {line!r}"
        metric, val = line.rsplit(" ", 1)
        if "{" in metric:
            name, lab = metric.split("{", 1)
            lab = lab.rstrip("}")
            labels = frozenset(
                m.group(0) for m in
                re.finditer(r'[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"',
                            lab))
        else:
            name, labels = metric, frozenset()
        out.setdefault(name, []).append((labels, float(val)))
    return out


class TestPrometheusConformance:
    def test_strict_grammar_inf_bucket_and_sol_series(self, sol_on,
                                                      drift_knobs):
        k = tilelang.compile(_scale_func(), target="cpu")
        a = np.ones((16, 32), np.float32)
        b = np.zeros((16, 32), np.float32)
        for _ in range(4):
            k(a, b)
        for _ in range(10):
            sol.observe_bucket("wl", "b4:p2", measured_ms=3.0,
                               predicted_ms=1.0, config={})
        text = obs.to_prometheus_text()
        samples = _parse_samples(text)
        # TYPE declared at most once per metric family
        types = [ln.split()[2] for ln in text.splitlines()
                 if ln.startswith("# TYPE")]
        assert len(types) == len(set(types))
        # every histogram: the cumulative +Inf bucket equals _count,
        # per label set (strict exposition conformance)
        bucket_names = [n for n in samples if n.endswith("_bucket")]
        assert bucket_names, "expected at least one histogram"
        for bname in bucket_names:
            base = bname[:-len("_bucket")]
            counts = dict(samples[f"{base}_count"])
            infs = {}
            for labels, val in samples[bname]:
                le = next((x for x in labels if x.startswith('le="')),
                          None)
                if le == 'le="+Inf"':
                    infs[labels - {le}] = val
            assert infs, f"{bname} has no +Inf bucket"
            for labels, val in infs.items():
                assert counts[labels] == val
        # the sol series made it out
        assert any('kernel="scale"' in labels
                   for labels, _ in samples["tl_tpu_sol_pct"])
        assert samples["tl_tpu_sol_retune_queue_depth"][0][1] == 1.0
        assert samples["tl_tpu_sol_drift"][0][1] == 1.0

    def test_metrics_summary_has_sol_section(self, sol_on):
        k = tilelang.compile(_scale_func(), target="cpu")
        a = np.ones((16, 32), np.float32)
        b = np.zeros((16, 32), np.float32)
        k(a, b)
        k(a, b)
        summ = obs.metrics_summary()
        assert summ["sol"]["enabled"] is True
        assert "scale" in summ["sol"]["kernels"]

    def test_jsonl_trace_carries_sol_rows(self, sol_on, tmp_path):
        k = tilelang.compile(_scale_func(), target="cpu")
        a = np.ones((16, 32), np.float32)
        b = np.zeros((16, 32), np.float32)
        for _ in range(3):
            k(a, b)
        p = tmp_path / "trace.jsonl"
        obs.write_jsonl(p)
        rows = [json.loads(ln) for ln in p.read_text().splitlines()]
        assert any(r.get("type") == "sol_context" for r in rows)
        srows = [r for r in rows if r.get("type") == "sol"]
        assert len(srows) == 1 and srows[0]["kernel"] == "scale"


class TestBenchAttachSol:
    def test_attaches_dominant_kernel(self, sol_on):
        import bench
        k = tilelang.compile(_scale_func(), target="cpu")
        a = np.ones((16, 32), np.float32)
        b = np.zeros((16, 32), np.float32)
        for _ in range(3):
            k(a, b)
        rec = bench._attach_sol({"config": "x"}, "x")
        assert rec["sol"]["kernel"] == "scale"
        assert 0 < rec["sol"]["sol_pct"] <= 1.5
        assert rec["sol"]["bottleneck"]
        assert rec["sol"]["kernels"] == 1
        # without tracing, attach resets per-config state in-process
        assert sol.sol_records() == []

    def test_noop_when_disabled(self, monkeypatch):
        import bench
        monkeypatch.delenv("TL_TPU_SOL", raising=False)
        rec = bench._attach_sol({"config": "x"}, "x")
        assert "sol" not in rec


class TestSweep:
    def test_single_module_sweep_artifact(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TL_TPU_SOL", "1")   # run_sweep sets these;
        monkeypatch.setenv("TL_TPU_RUNTIME_SAMPLE", "1")  # restore after
        monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
        tilelang.clear_cache()
        out = tmp_path / "sweep.jsonl"
        res = sol.run_sweep(out=str(out), modules="gemm", calls=1,
                            store=str(tmp_path / "store"),
                            write_to_store=True)
        assert res["kernels"] >= 1
        assert res["with_prediction"] >= 1
        assert res["store_entries"] >= 1
        rows = [json.loads(ln) for ln in out.read_text().splitlines()]
        assert rows[0]["type"] == "sol_context"
        srow = next(r for r in rows if r.get("type") == "sol")
        assert 0 < srow["sol_pct"] <= 1.5 and srow["bottleneck"]
        tilelang.clear_cache()


# ---------------------------------------------------------------------------
# 6. serving drift soak
# ---------------------------------------------------------------------------

class TestServingDriftSoak:
    def test_injected_drift_raises_event_dump_and_prof(
            self, drift_knobs, monkeypatch, tmp_path):
        from tilelang_mesh_tpu.observability import server
        from tilelang_mesh_tpu.serving import (FlashDecodeWorkload,
                                               PagedKVAllocator,
                                               ServingEngine)
        monkeypatch.setenv("TL_TPU_AUTOTUNE_CACHE_DIR",
                           str(tmp_path / "autotune"))
        monkeypatch.delenv("TL_TPU_TUNE_CACHE_DIR", raising=False)
        monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
        monkeypatch.setenv("TL_TPU_SOL_DRIFT_WARMUP", "2")
        monkeypatch.setenv("TL_TPU_SOL_DRIFT_SUSTAIN", "2")
        # the EWMA's MAD is seeded from the first step-to-step diffs, so
        # a noisy first post-warmup step inflates MADS*sigma before it
        # converges; a 2.5e6x injected drift doesn't need the 6-MAD bar
        monkeypatch.setenv("TL_TPU_SOL_DRIFT_MADS", "3")
        flight.configure(dump_dir=tmp_path / "dumps")
        tilelang.clear_cache()
        alloc = PagedKVAllocator(n_pages=64, page_size=8, heads=2,
                                 head_dim=64)
        wl = FlashDecodeWorkload(alloc, batch_buckets=(4,),
                                 page_buckets=(2, 4))
        # the injection: publish an absurdly fast tuned latency so real
        # CPU step time reads as sustained drift from the first steps
        for pp in (2, 4):
            assert wl.record_bucket_tuning(4, pp, {"probe": 1},
                                           latency_ms=1e-6)
        eng = ServingEngine(wl)
        wl.warmup()
        assert wl.tuned_prediction_ms(4, 2) == pytest.approx(1e-6)
        # 12 decode steps = 12 observations: enough for the deviation
        # estimate to converge past any slow first step
        for _ in range(4):
            eng.submit(context_tokens=16, new_tokens=12)
        eng.run()
        counters = obs.get_tracer().counters()
        assert counters.get("sol.drift", 0) >= 1
        q = sol.retune_queue()
        assert q and q[0]["kernel"] == "FlashDecodeWorkload"
        assert q[0]["config"] == {"probe": 1}
        dumps = list((tmp_path / "dumps").glob(
            "flight_*_sol_drift_*.jsonl"))
        assert dumps
        hdr = json.loads(dumps[0].read_text().splitlines()[0])
        assert hdr["attrs"]["kernel"] == "FlashDecodeWorkload"
        assert hdr["attrs"]["config"] == {"probe": 1}
        srv = server.start_server(port=0)
        try:
            with urllib.request.urlopen(f"{srv.url}/prof",
                                        timeout=5) as r:
                doc = json.loads(r.read().decode())
            assert any(e["kernel"] == "FlashDecodeWorkload"
                       for e in doc["retune_queue"])
        finally:
            srv.stop()
        tilelang.clear_cache()
