"""MeshTensor sharding math + sharded-kernel execution under shard_map.

Mirrors reference testing/python/language/test_tilelang_language_mesh_tensor.py
(sharding shape unit tests) plus execution on the 8-device virtual CPU mesh.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.parallel import (MeshReplicationType,
                                        MeshShardingPolicy, mesh_config)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


# ---- pure sharding math (style 3: no device) -------------------------------


def test_sharded_shape_xy_split():
    p = MeshShardingPolicy(x=1, y=0)
    # x splits dim1 by ncols, y splits dim0 by nrows
    assert p.sharded_shape((64, 128), 2, 4) == (32, 32)


def test_sharded_shape_replicate_all():
    p = MeshShardingPolicy(replicate=MeshReplicationType.ALL)
    assert p.sharded_shape((64, 128), 2, 4) == (64, 128)


def test_sharded_shape_cross_mesh():
    p = MeshShardingPolicy(cross_mesh_dim=0)
    assert p.sharded_shape((64, 128), 2, 4) == (8, 128)


def test_sharded_shape_row_replicate_y_split():
    p = MeshShardingPolicy(y=0, replicate=MeshReplicationType.ROW)
    assert p.sharded_shape((64, 128), 2, 4) == (32, 128)


def test_policy_validation():
    with pytest.raises(ValueError):
        MeshShardingPolicy(x=0, cross_mesh_dim=1)
    p = MeshShardingPolicy(x=0, replicate=MeshReplicationType.ROW)
    with pytest.raises(ValueError):
        p.sharded_shape((8, 8), 2, 2)


def test_partition_spec():
    from jax.sharding import PartitionSpec as P
    assert MeshShardingPolicy(x=1, y=0).partition_spec(2) == P("x", "y")
    assert MeshShardingPolicy(
        replicate=MeshReplicationType.ALL).partition_spec(2) == P(None, None)
    assert MeshShardingPolicy(cross_mesh_dim=0).partition_spec(2) == \
        P(("x", "y"), None)


# ---- sharded kernel execution ---------------------------------------------


def _mesh_matmul(M, N, K, bm, bn, bk, mesh_cfg, dtype="float32"):
    """The reference's example_gemm_with_mesh_tensor.py brought to TPU:
    A row-sharded, B col-sharded... here all row-sharded on x=1,y=0 like the
    reference's (1,1) demo, generalized to real shards."""

    @T.prim_func
    def gemm(
        A: T.MeshTensor((M, K), T.MeshShardingPolicy(y=0), mesh_cfg, dtype),
        B: T.MeshTensor((K, N), T.MeshShardingPolicy(
            replicate=T.MeshReplicationType.ALL), mesh_cfg, dtype),
        C: T.MeshTensor((M, N), T.MeshShardingPolicy(y=0), mesh_cfg, dtype),
    ):
        sM, sK = A.shape
        _, sN = B.shape
        with T.Kernel(T.ceildiv(sN, bn), T.ceildiv(sM, bm)) as (bx, by):
            A_s = T.alloc_shared((bm, bk), dtype)
            B_s = T.alloc_shared((bk, bn), dtype)
            C_l = T.alloc_fragment((bm, bn), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(sK, bk)):
                T.copy(A[by * bm, ko * bk], A_s)
                T.copy(B[ko * bk, bx * bn], B_s)
                T.gemm(A_s, B_s, C_l)
            T.copy(C_l, C[by * bm, bx * bn])

    return gemm


def test_mesh_tensor_sharded_gemm_2x4():
    """Row-sharded GEMM over the full 2x4 virtual mesh: each core computes
    its row shard against a replicated B."""
    mesh_cfg = (2, 4)
    M, N, K = 512, 128, 128
    with mesh_config(*mesh_cfg):
        pf = _mesh_matmul(M, N, K, 64, 128, 64, mesh_cfg)
        k = tilelang.compile(pf, target="cpu-mesh[2x4]")
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    c = k(a, b)
    assert_allclose(c, a @ b, rtol=2e-2, atol=2e-2)


def test_mesh_tensor_1x1_matches_reference_demo():
    """The reference demo runs MeshTensor on a (1,1) mesh — degenerate
    single-core case must behave like a plain kernel."""
    mesh_cfg = (1, 1)
    M = N = K = 256
    with mesh_config(*mesh_cfg):
        pf = _mesh_matmul(M, N, K, 128, 128, 64, mesh_cfg)
        k = tilelang.compile(pf, target="cpu-mesh[1x1]")
    rng = np.random.default_rng(1)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    assert_allclose(k(a, b), a @ b, rtol=2e-2, atol=2e-2)


def test_mesh_kernel_source_describes_schedule():
    mesh_cfg = (2, 4)
    with mesh_config(*mesh_cfg):
        pf = _mesh_matmul(512, 128, 128, 64, 128, 64, mesh_cfg)
        art = tilelang.lower(pf, target="cpu-mesh[2x4]")
    assert "mesh_program" in art.plan_desc
    assert "pallas_segment" in art.plan_desc
