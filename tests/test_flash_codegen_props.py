"""Generated-source properties of the flash kernels (golden-property
style, cf. the comm golden-schedule tests): pins the round-5 VPU-diet
optimizations so a refactor cannot silently regress them.

1. The softmax scale is folded into Q ONCE, outside the KV loop — no
   per-score multiply by the scale constant anywhere in the source.
2. Causal: the -inf select sits under a block predicate (diagonal
   straddle) INSIDE the visited-guard, so fully-live blocks skip it.
3. Non-causal: no select at all between the two GEMMs.
"""

import re

import pytest

from tilelang_mesh_tpu.ops.flash_attention import mha_fwd_kernel

_SCALE = 0.13371337          # recognizable constant
_SCALE2 = _SCALE * 1.44269504


def _src(causal, block_M=128, block_N=256):
    return mha_fwd_kernel(1, 1, 512, 512, 64, block_M=block_M,
                          block_N=block_N, causal=causal,
                          sm_scale=_SCALE, dtype="float32",
                          num_stages=2).get_kernel_source()


def test_scale_folded_into_q_once():
    src = _src(causal=False)
    hits = [l for l in src.splitlines() if str(_SCALE2)[:8] in l]
    assert len(hits) == 1, hits
    # and it multiplies the Q block, not the score matrix
    assert "Q_ref" in hits[0]


def _score_selects(src):
    """Masked-select lines over the score tile (ignore BlockSpec index
    clamps, which also use jnp.where)."""
    return [l for l in src.splitlines()
            if "jnp.where" in l and "BlockSpec" not in l]


def test_noncausal_has_no_mask_select():
    src = _src(causal=False)
    assert not _score_selects(src)


def test_causal_select_is_diagonal_predicated():
    src = _src(causal=True)
    # exactly one masked select...
    wheres = _score_selects(src)
    assert len(wheres) == 1, wheres
    # ...NESTED under two guards (visited-guard, then the
    # diagonal-straddle predicate): the select's indentation must sit
    # strictly deeper than the innermost pl.when, which itself sits
    # strictly deeper than an enclosing pl.when — textual precedence
    # alone would miss a hoist out of the visited-guard
    def indent(line):
        return len(line) - len(line.lstrip())

    lines = src.splitlines()
    sel_i = lines.index(wheres[0])
    whens = [(i, indent(l)) for i, l in enumerate(lines[:sel_i])
             if l.lstrip().startswith("@pl.when")]
    assert whens, "no guard above the select"
    inner_i, inner_ind = whens[-1]
    assert indent(lines[sel_i]) > inner_ind, \
        "select not inside the innermost guard"
    outer = [w for w in whens[:-1] if w[1] < inner_ind]
    assert outer, "diagonal guard is not nested inside an outer guard"


@pytest.mark.parametrize("causal", [True, False])
def test_single_exp2_pass_per_block(causal):
    """exp2 over scores appears once (the fused stats+P write), plus
    the two per-row rescale exp2s — never a second full-tile pass."""
    src = _src(causal=causal)
    exp2_lines = [l for l in src.splitlines() if "jnp.exp2" in l]
    assert len(exp2_lines) == 3, exp2_lines
