"""Backend registry + device-loss failover tier (codegen/backends.py,
docs/robustness.md "Backend failover").

Everything runs on the forced 8-device CPU mesh: the ``device.probe`` /
``device.dispatch`` fault sites (kind=unreachable) stand in for a dying
TPU worker, so the whole failover path — classification, warm-call
failover, chain semantics, fallback-disabled fail-fast, hermetic bench
plumbing — is deterministic without hardware.
"""

import pathlib
import sys

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.codegen.backends import (BackendHealth,
                                                probe_default_device,
                                                registry)
from tilelang_mesh_tpu.observability import get_tracer, metrics_summary
from tilelang_mesh_tpu.resilience import (DeviceLossError, TLTimeoutError,
                                          classify, inject, is_device_loss,
                                          parse_fault_spec)
from tilelang_mesh_tpu.resilience.errors import InjectedFault

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


@pytest.fixture(autouse=True)
def _fresh_state():
    """Backend health and kernel caches are process-global: every test
    starts from a never-probed registry and an empty cache."""
    registry().reset()
    tilelang.clear_cache()
    get_tracer().reset()
    yield
    registry().reset()
    tilelang.clear_cache()


def _scale_func(mult):
    M, N = 64, 128

    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * mult
            T.copy(s, B)
    return scale


def _run_scale(kernel, mult):
    a = np.arange(64 * 128, dtype=np.float32).reshape(64, 128) / 100
    np.testing.assert_allclose(np.asarray(kernel(a)), a * mult, rtol=1e-6)


# ---------------------------------------------------------------------------
# taxonomy: DeviceLossError + classify() signatures
# ---------------------------------------------------------------------------

class TestDeviceLossClassification:
    def test_device_loss_error_kind(self):
        e = DeviceLossError("worker gone", backend="tpu-pallas")
        assert classify(e) == "device_loss"
        assert e.backend == "tpu-pallas"

    @pytest.mark.parametrize("msg", [
        "DEADLINE_EXCEEDED: deadline exceeded after 59.99s",
        "TPU worker unreachable",
        "failed to connect to all addresses",
        "Socket closed",
        "UNAVAILABLE: connection reset by peer",
    ])
    def test_foreign_signatures_classify_as_device_loss(self, msg):
        # the RuntimeErrors XLA/jax actually surface when the worker dies
        assert classify(RuntimeError(msg)) == "device_loss"
        assert is_device_loss(RuntimeError(msg))

    @pytest.mark.parametrize("msg", [
        "internal error: unreachable code reached",
        "PJRT plugin does not support donation",
    ])
    def test_narrow_markers_skip_deterministic_lookalikes(self, msg):
        # a bare "unreachable"/"pjrt" substring must NOT read as device
        # loss: these are deterministic bugs, and misclassifying them
        # would mark a healthy backend dead for every sibling kernel
        assert classify(RuntimeError(msg)) == "deterministic"

    def test_plain_errors_unaffected(self):
        assert classify(ValueError("bad data")) == "deterministic"
        assert classify(OSError("disk full")) == "transient"
        assert classify(TimeoutError("late")) == "timeout"

    def test_tlerrors_self_classify_never_sniffed(self):
        # a TLError whose MESSAGE matches a marker keeps its own kind
        from tilelang_mesh_tpu.resilience import DeterministicError
        e = DeterministicError("codegen for unreachable branch failed")
        assert classify(e) == "deterministic"

    def test_unreachable_fault_kind(self):
        spec = parse_fault_spec("device.dispatch:kind=unreachable")[0]
        assert spec.kind == "unreachable"
        assert isinstance(InjectedFault.as_kind(
            "unreachable", "device.dispatch"), DeviceLossError)

    def test_recoverable_delegates_to_classify(self):
        # the satellite fix: a dispatch-time PJRT disconnect used to be
        # "deterministic" (not jax-module-raised) and never recovered
        from tilelang_mesh_tpu.jit.kernel import _recoverable
        assert _recoverable(RuntimeError("TPU worker unreachable"))
        assert _recoverable(InjectedFault("chaos"))
        assert _recoverable(NotImplementedError("mosaic op"))
        assert not _recoverable(ValueError("bad data"))
        assert not _recoverable(TypeError("bad operand"))


# ---------------------------------------------------------------------------
# registry: chain parsing, capability filtering, TTL health cache
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_default_chain(self, monkeypatch):
        monkeypatch.delenv("TL_TPU_BACKENDS", raising=False)
        assert [b.name for b in registry().chain()] == \
            ["tpu-pallas", "host-interpret"]

    def test_chain_env_override_and_unknown(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla, host-interpret")
        assert [b.name for b in registry().chain()] == \
            ["host-xla", "host-interpret"]
        monkeypatch.setenv("TL_TPU_BACKENDS", "gpu-cuda")
        with pytest.raises(ValueError, match="unknown backend"):
            registry().chain()

    def test_chain_for_filters_capability(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_BACKENDS",
                           "tpu-pallas,host-xla,host-interpret")
        reg = registry()
        # interpret target: host tiers only
        assert [b.name for b in reg.chain_for("cpu")] == \
            ["host-xla", "host-interpret"]
        # mesh interpret target: host + mesh-capable only
        assert [b.name for b in reg.chain_for("cpu-mesh[2x2]")] == \
            ["host-xla"]
        # tpu target: the chain as given
        assert [b.name for b in reg.chain_for("tpu")] == \
            ["tpu-pallas", "host-xla", "host-interpret"]

    def test_chain_for_never_strands_host_targets(self, monkeypatch):
        # an all-TPU chain cannot leave a cpu target without a backend
        monkeypatch.setenv("TL_TPU_BACKENDS", "tpu-pallas")
        assert [b.name for b in registry().chain_for("cpu")] == \
            ["host-interpret"]
        assert [b.name for b in registry().chain_for("cpu-mesh[2x2]")] == \
            ["host-xla"]

    def test_probe_ttl_caches_verdict(self):
        reg = registry()
        assert reg.is_available("host-interpret")
        assert reg.health("host-interpret").probes == 1
        # fresh verdict: no second probe
        assert reg.is_available("host-interpret")
        assert reg.health("host-interpret").probes == 1
        # expired TTL: re-probe
        assert reg.is_available("host-interpret", ttl_s=0.0)
        assert reg.health("host-interpret").probes == 2

    def test_tpu_probe_dead_without_hardware(self):
        # on the CPU test platform the TPU tier is genuinely unavailable
        assert not registry().is_available("tpu-pallas")
        h = registry().health("tpu-pallas")
        assert h.healthy is False and h.error

    def test_injected_probe_fault_kills_tpu_tier(self):
        with inject("device.probe", kind="unreachable"):
            assert not registry().is_available("tpu-pallas", ttl_s=0.0)

    def test_mark_unhealthy_feeds_breaker(self):
        from tilelang_mesh_tpu.resilience.retry import global_breaker
        from tilelang_mesh_tpu.resilience import error_signature
        reg = registry()
        e = DeviceLossError("worker gone mid-call")
        sig = error_signature(e)
        global_breaker().reset(sig)
        for _ in range(global_breaker().threshold):
            reg.mark_unhealthy("host-xla", e)
        assert global_breaker().is_open(sig)
        assert reg.health("host-xla").healthy is False
        assert reg.health("host-xla").failovers == \
            global_breaker().threshold
        global_breaker().reset(sig)

    def test_health_fresh_semantics(self):
        h = BackendHealth()
        assert not h.fresh(1000.0)     # never probed
        h.healthy, h.checked_at = True, 0.0
        assert not h.fresh(0.0, now=1.0)
        assert h.fresh(10.0, now=1.0)

    def test_probe_default_device_healthy_on_cpu(self):
        assert probe_default_device() is None

    def test_bounded_probe_abandons_wedged_worker(self):
        # a wedged worker: the bounded probe abandons its thread and
        # raises a timeout-kind TLError (never hangs)
        import time as _time
        from tilelang_mesh_tpu.codegen.backends import _bounded
        with pytest.raises(TLTimeoutError):
            _bounded(lambda: _time.sleep(5), "device probe", 0.05)


# ---------------------------------------------------------------------------
# JITKernel: build-time selection + warm-call failover
# ---------------------------------------------------------------------------

class TestJITFailover:
    def test_happy_path_identical_with_and_without_chain(self, monkeypatch):
        # failover must not perturb the healthy path: same plan_desc and
        # kernel source bytes whatever the chain says
        k1 = tilelang.compile(_scale_func(2.25))
        plan1, src1 = k1.get_plan(), k1.get_kernel_source()
        tilelang.clear_cache()
        monkeypatch.setenv("TL_TPU_BACKENDS",
                           "tpu-pallas,host-xla,host-interpret")
        registry().reset()
        k2 = tilelang.compile(_scale_func(2.25))
        assert k2.get_plan() == plan1
        assert k2.get_kernel_source() == src1
        _run_scale(k2, 2.25)

    def test_warm_call_device_loss_fails_over(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla,host-interpret")
        registry().reset()
        get_tracer().reset()
        k = tilelang.compile(_scale_func(3.5))
        _run_scale(k, 3.5)                       # warm
        assert k.backend == "host-xla"
        with inject("device.dispatch", kind="unreachable", times=1):
            _run_scale(k, 3.5)                   # dies + fails over
        assert k.backend == "host-interpret"
        _run_scale(k, 3.5)                       # stays on the fallback
        counters = get_tracer().counters()
        assert counters[
            "backend.failover{frm=host-xla,to=host-interpret}"] == 1
        evs = [e for e in get_tracer().events()
               if e["name"] == "backend.failover"]
        assert len(evs) == 1
        assert evs[0]["attrs"]["frm"] == "host-xla"
        assert evs[0]["attrs"]["to"] == "host-interpret"
        assert evs[0]["attrs"]["during"] == "dispatch"
        # the registry remembers the death for sibling kernels
        assert registry().health("host-xla").healthy is False

    def test_cold_call_device_loss_fails_over(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla,host-interpret")
        registry().reset()
        k = tilelang.compile(_scale_func(4.5))
        with inject("device.dispatch", kind="unreachable", times=1):
            _run_scale(k, 4.5)                   # first call dies mid-compile
        assert k.backend == "host-interpret"

    def test_build_time_failover_when_head_unhealthy(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla,host-interpret")
        registry().reset()
        get_tracer().reset()
        registry().mark_unhealthy("host-xla",
                                  DeviceLossError("worker gone"))
        k = tilelang.compile(_scale_func(5.5))
        assert k.backend == "host-interpret"
        _run_scale(k, 5.5)
        evs = [e for e in get_tracer().events()
               if e["name"] == "backend.failover"]
        assert evs and evs[0]["attrs"]["during"] == "build"

    def test_fallback_none_device_loss_raises(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_FALLBACK", "none")
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla,host-interpret")
        registry().reset()
        k = tilelang.compile(_scale_func(6.5))
        _run_scale(k, 6.5)
        with inject("device.dispatch", kind="unreachable", times=1):
            with pytest.raises(DeviceLossError):
                _run_scale(k, 6.5)

    def test_fallback_none_compile_failure_raises(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_FALLBACK", "none")
        with inject("jit.compile", times=1):
            with pytest.raises(InjectedFault):
                tilelang.compile(_scale_func(7.5))

    def test_single_entry_chain_behaves_like_fallback_none(self,
                                                           monkeypatch):
        # TL_TPU_BACKENDS=<one entry>: nowhere to fail over — a warm
        # device loss raises exactly as TL_TPU_FALLBACK=none would
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla")
        registry().reset()
        k = tilelang.compile(_scale_func(8.5))
        _run_scale(k, 8.5)
        with inject("device.dispatch", kind="unreachable", times=1):
            with pytest.raises(DeviceLossError):
                _run_scale(k, 8.5)

    def test_non_device_loss_warm_errors_propagate(self):
        k = tilelang.compile(_scale_func(9.5))
        _run_scale(k, 9.5)
        with pytest.raises(ValueError):
            k(np.zeros((2, 2), np.float32))      # shape error, no failover


# ---------------------------------------------------------------------------
# MeshKernel: dispatch-time failover
# ---------------------------------------------------------------------------

def _mesh_func(nrow=2, ncol=2, n=8, m=128):
    from tilelang_mesh_tpu.parallel import mesh_config
    with mesh_config(nrow, ncol):
        @T.prim_func
        def mesh_scale(
                A: T.MeshTensor((nrow * ncol * n, m),
                                T.MeshShardingPolicy(cross_mesh_dim=0),
                                (nrow, ncol), "float32"),
                B: T.MeshTensor((nrow * ncol * n, 1),
                                T.MeshShardingPolicy(cross_mesh_dim=0),
                                (nrow, ncol), "float32")):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment((n, m), "float32")
                o = T.alloc_fragment((n, 1), "float32")
                T.copy(A, x)
                T.comm.all_reduce(x, o, "sum", "all", dim=1)
                T.copy(o, B)
        return mesh_scale


class TestMeshFailover:
    def test_mesh_device_loss_rebuild_and_retry(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        registry().reset()
        get_tracer().reset()
        k = tilelang.compile(_mesh_func(), target="cpu-mesh[2x2]")
        assert k.backend == "host-xla"
        a = np.random.default_rng(0).standard_normal(
            (2 * 2 * 8, 128)).astype(np.float32)
        want = np.asarray(k(a))
        with inject("device.dispatch", kind="unreachable", times=1):
            got = np.asarray(k(a))
        np.testing.assert_allclose(got, want, rtol=1e-6)
        evs = [e for e in get_tracer().events()
               if e["name"] == "backend.failover"]
        assert len(evs) == 1
        assert evs[0]["attrs"]["during"] == "dispatch"

    def test_mesh_fallback_none_raises(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_FALLBACK", "none")
        registry().reset()
        k = tilelang.compile(_mesh_func(), target="cpu-mesh[2x2]")
        a = np.zeros((2 * 2 * 8, 128), np.float32)
        k(a)
        with inject("device.dispatch", kind="unreachable", times=1):
            with pytest.raises(DeviceLossError):
                k(a)


# ---------------------------------------------------------------------------
# surfacing: metrics_summary, analyzer, bench plumbing
# ---------------------------------------------------------------------------

class TestSurfacing:
    def test_metrics_summary_backend_fields(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla,host-interpret")
        registry().reset()
        get_tracer().reset()
        k = tilelang.compile(_scale_func(11.5))
        _run_scale(k, 11.5)
        with inject("device.dispatch", kind="unreachable", times=1):
            _run_scale(k, 11.5)
        res = metrics_summary()["resilience"]
        assert res["backend_failovers"] == 1
        assert res["backend_probes"] >= 1
        assert res["backends"]["host-xla"]["healthy"] is False
        assert res["backends"]["host-xla"]["failovers"] == 1

    def test_analyzer_faults_surfaces_failovers(self):
        from tilelang_mesh_tpu.tools.analyzer import (format_faults_report,
                                                      summarize_faults)
        records = [
            {"type": "event", "name": "backend.failover",
             "attrs": {"frm": "tpu-pallas", "to": "host-interpret",
                       "kernel": "k"}},
            {"type": "event", "name": "backend.failover",
             "attrs": {"frm": "tpu-pallas", "to": "host-interpret",
                       "kernel": "k2"}},
            {"type": "counter",
             "name": "backend.probe{backend=tpu-pallas,healthy=false}",
             "value": 3},
        ]
        s = summarize_faults(records)
        assert s["failovers"] == {"tpu-pallas -> host-interpret": 2}
        assert s["backend_health"]["tpu-pallas"] == {
            "probes": 3, "unhealthy_probes": 3}
        rep = format_faults_report(records)
        assert "backend failovers" in rep and "tpu-pallas" in rep

    def test_bench_probe_device_healthy(self):
        import bench
        assert bench._probe_device(60.0) is None

    def test_bench_hermetic_env(self, monkeypatch):
        import bench
        monkeypatch.delenv("TL_TPU_BACKENDS", raising=False)
        monkeypatch.delenv("TL_TPU_FAULTS", raising=False)
        over = bench._hermetic_env("gemm_smoke",
                                   device_loss_at="gemm_smoke")
        assert over["JAX_PLATFORMS"] == "cpu"
        assert over["TL_TPU_BENCH_HERMETIC"] == "1"
        assert "host-interpret" in over["TL_TPU_BACKENDS"]
        assert "device.probe:kind=unreachable" in over["TL_TPU_FAULTS"]
        assert "device.dispatch:kind=unreachable:times=1" in \
            over["TL_TPU_FAULTS"]
        # non-victim configs get no dispatch fault
        over2 = bench._hermetic_env("mesh_allreduce_smoke",
                                    device_loss_at="gemm_smoke")
        assert "device.dispatch" not in over2["TL_TPU_FAULTS"]

    def test_clear_factory_caches_drops_callsite_kernels(self):
        from tilelang_mesh_tpu.jit import clear_factory_caches
        from tilelang_mesh_tpu.ops.gemm import matmul_kernel
        matmul_kernel.cache_clear()
        k1 = matmul_kernel(64, 128, 64, in_dtype="float32",
                           block_M=64, block_N=128, block_K=64)
        assert matmul_kernel.cache_info().currsize == 1
        clear_factory_caches()
        assert matmul_kernel.cache_info().currsize == 0
        # the bench failover retry pairs this with clear_cache(): only
        # then does the rebuilt kernel re-walk the backend chain
        tilelang.clear_cache()
        k2 = matmul_kernel(64, 128, 64, in_dtype="float32",
                           block_M=64, block_N=128, block_K=64)
        assert k2 is not k1


@pytest.mark.slow
def test_hermetic_bench_end_to_end(tmp_path):
    """bench.py --hermetic: rc=0 with every CPU-safe config producing a
    record and the TPU tier dead in each record's health snapshot."""
    import json
    import os
    import subprocess
    import bench
    env = dict(os.environ)
    env.pop("TL_TPU_BACKENDS", None)
    env.pop("TL_TPU_FAULTS", None)
    repo = pathlib.Path(__file__).resolve().parents[1]
    p = subprocess.run(
        [sys.executable, str(repo / "bench.py"), "--hermetic", "--quick"],
        capture_output=True, text=True, env=env, timeout=900)
    assert p.returncode == 0, p.stderr[-2000:]
    recs = {}
    for line in p.stdout.splitlines():
        if line.startswith("{"):
            r = json.loads(line)
            if r.get("config") and "geomean_vs_baseline" not in r:
                recs[r["config"]] = r
    for name in bench.CPU_SAFE_CONFIGS:
        assert name in recs and "error" not in recs[name]
        assert recs[name]["backend_health"]["tpu-pallas"]["healthy"] \
            is False
        assert recs[name]["backends_used"]
