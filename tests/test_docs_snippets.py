"""Every ```python block in docs/ must actually run.

The reference ships user docs whose snippets are exercised in CI; here
each page's python blocks execute top-to-bottom in one shared namespace
(so a later block can use the kernel an earlier block built). Blocks
fenced as anything other than exactly ```python (bash, text,
python-notest, ...) are skipped.
"""

import pathlib
import re

import pytest

DOCS = pathlib.Path(__file__).resolve().parent.parent / "docs"

_FENCE = re.compile(r"^```python\s*$(.*?)^```\s*$", re.M | re.S)

_USER_PAGES = sorted(
    p for p in DOCS.rglob("*.md")
    if "compiler_internals" not in p.parts and _FENCE.search(p.read_text())
)


@pytest.mark.parametrize("page", _USER_PAGES,
                         ids=[str(p.relative_to(DOCS)) for p in _USER_PAGES])
def test_docs_page_snippets_run(page):
    ns: dict = {"__name__": f"docs_snippet_{page.stem}"}
    blocks = _FENCE.findall(page.read_text())
    assert blocks, f"{page} matched the fence scan but has no blocks"
    for i, code in enumerate(blocks):
        try:
            exec(compile(code, f"{page.name}[block {i}]", "exec"), ns)
        except Exception as e:  # noqa: BLE001 - named per block
            raise AssertionError(
                f"{page.relative_to(DOCS)} block {i} failed: "
                f"{type(e).__name__}: {e}") from e


def test_docs_have_user_path():
    """The get-started spine exists (VERDICT r4 missing #3)."""
    for rel in ("get_started/installation.md", "get_started/quickstart.md",
                "get_started/targets.md", "tutorials/auto_tuning.md",
                "tutorials/debugging.md", "tutorials/distributed_mesh.md",
                "deeplearning_operators/matmul.md",
                "deeplearning_operators/flash_attention.md"):
        assert (DOCS / rel).is_file(), f"missing docs page {rel}"
