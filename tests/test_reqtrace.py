"""tl-scope suite: per-request causal tracing, flight recorder, SLO
engine, telemetry endpoint, and the analyzer request/dash views
(docs/observability.md).

Five layers, mirroring the subsystem:

1. **Chains** — RequestTrace span/parent discipline, completeness
   audits (leaked spans, forged parents), the bounded registry's
   completed-first eviction, contextvar propagation into the tracer.
2. **Serving propagation** — trace ids surviving re-queue, retry,
   device-loss failover, and mesh reshard; causal completeness on the
   full 500-request chaos soak at DEFAULTS (flight on, TL_TPU_TRACE
   off).
3. **Flight recorder** — ring eviction, the off switch, dump-on-
   failure naming victim trace ids, and atomicity under injected
   ``cache.disk.write`` faults.
4. **SLO engine** — window math on synthetic samples, burn-rate breach
   edges, the opt-in admission consult, knob validation.
5. **Surfaces** — the HTTP endpoint's four routes (scrape parses as
   exposition format), Prometheus label-value escaping round-trip,
   Chrome-trace flow events, ``analyzer request`` / ``analyzer dash``.
"""

import json
import re
import urllib.request

import pytest

from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.observability import flight, reqtrace
from tilelang_mesh_tpu.observability.histogram import Histogram
from tilelang_mesh_tpu.observability.slo import SLOEngine, parse_windows
from tilelang_mesh_tpu.resilience import inject
from tilelang_mesh_tpu.serving import (FlashDecodeWorkload,
                                       PagedKVAllocator, ServingEngine)

H, D, PS = 2, 64, 8


@pytest.fixture(autouse=True)
def _clean_obs():
    """Chains/flight/SLO are process singletons; every test starts from
    a clean slate (the conftest fixture resets resilience, not these)."""
    obs.reset()
    yield
    obs.reset()


def make_engine(n_pages=64, batch_buckets=(4,), page_buckets=(2, 4),
                **kw):
    alloc = PagedKVAllocator(n_pages=n_pages, page_size=PS, heads=H,
                             head_dim=D)
    wl = FlashDecodeWorkload(alloc, batch_buckets=batch_buckets,
                             page_buckets=page_buckets)
    return ServingEngine(wl, **kw), alloc


# ---------------------------------------------------------------------------
# 1. chains
# ---------------------------------------------------------------------------

def test_chain_parent_links_and_completeness():
    tr = reqtrace.start_trace("request", req=1)
    s1 = tr.span("submit")
    tr.close_span(s1)
    s2 = tr.span("decode.step")
    tr.close_span(s2)
    assert not tr.complete            # no terminal yet
    tr.finish("result")
    assert tr.complete
    spans = tr.to_dict()["spans"]
    assert [sp["parent"] for sp in spans] == [None, s1]


def test_chain_leaked_span_fails_completeness():
    tr = reqtrace.start_trace("request")
    tr.span("decode.step")            # never closed by its owner
    tr.finish("result")
    assert tr.terminal == "result"
    assert not tr.complete            # the leak is the finding
    leaked = [sp for sp in tr.to_dict()["spans"]
              if sp["attrs"].get("leaked")]
    assert len(leaked) == 1


def test_chain_forged_parent_fails_chain_ok():
    tr = reqtrace.start_trace("request")
    tr.span("a", parent=999)          # parent that never existed
    assert not tr.chain_ok()


def test_mark_is_zero_duration_and_chained():
    tr = reqtrace.start_trace("request")
    a = tr.span("submit")
    tr.close_span(a)
    tr.mark("requeue", retries=1)
    d = tr.to_dict()
    m = d["spans"][-1]
    assert m["name"] == "requeue" and m["t1"] is not None
    assert m["parent"] == a


def test_close_span_idempotent_and_trace_ids_unique():
    tr = reqtrace.start_trace("request")
    s = tr.span("x")
    tr.close_span(s, outcome="ok")
    tr.close_span(s, outcome="clobbered")     # dropped, not applied
    assert tr.to_dict()["spans"][0]["attrs"]["outcome"] == "ok"
    ids = {reqtrace.start_trace("request").trace_id for _ in range(50)}
    assert len(ids) == 50


def test_long_lived_chain_bounds_spans():
    """The engine trace records one batch span per step forever: a
    max_spans bound keeps the tail, drops the ancient history, and the
    chain stays well-formed (evicted parents resolve)."""
    tr = reqtrace.start_trace("engine", kind="engine", max_spans=10)
    for i in range(50):
        s = tr.span("serve.batch", batch=i)
        tr.close_span(s)
    d = tr.to_dict()
    assert len(d["spans"]) == 10 and d["dropped"] == 40
    assert [sp["attrs"]["batch"] for sp in d["spans"]] == \
        list(range(40, 50))                  # newest history survives
    assert tr.chain_ok()                     # evicted parents resolve


def test_serving_engine_trace_is_bounded():
    eng, _ = make_engine()
    assert eng.trace.max_spans > 0


def test_registry_evicts_completed_first(monkeypatch):
    monkeypatch.setenv("TL_TPU_REQTRACE_MAX", "3")
    done = reqtrace.start_trace("request", tag="done")
    done.finish("result")
    live = [reqtrace.start_trace("request", tag=f"live{i}")
            for i in range(3)]
    # the completed chain was evicted; all live chains survive
    assert reqtrace.get_trace(done.trace_id) is None
    assert all(reqtrace.get_trace(t.trace_id) is not None for t in live)
    assert reqtrace.evicted() == 1


def test_bind_tags_tracer_records(monkeypatch):
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    tr = reqtrace.start_trace("request")
    sid = tr.span("decode.step")
    with reqtrace.bind(tr.trace_id, sid):
        obs.event("kernel.dispatch", "test", kernel="k")
        with obs.span("inner", "test"):
            pass
    obs.event("outside", "test")
    evs = {e["name"]: e for e in obs.get_tracer().events()}
    assert evs["kernel.dispatch"]["attrs"]["trace_id"] == tr.trace_id
    assert evs["kernel.dispatch"]["attrs"]["parent_span"] == sid
    assert evs["inner"]["attrs"]["trace_id"] == tr.trace_id
    assert "trace_id" not in evs["outside"]["attrs"]


# ---------------------------------------------------------------------------
# 2. serving propagation
# ---------------------------------------------------------------------------

def test_request_chain_through_decode_steps():
    eng, _ = make_engine()
    r = eng.submit(context_tokens=16, new_tokens=3)
    eng.run()
    assert r.outcome == "result" and r.trace.complete
    names = [sp["name"] for sp in r.trace.to_dict()["spans"]]
    assert names[0] == "submit"
    assert names.count("decode.step") == 3
    assert names.count("requeue") == 2        # between the 3 steps


def test_shed_request_chain_closes():
    eng, _ = make_engine()
    eng.drain()
    r = eng.submit(context_tokens=16)
    assert r.outcome == "shed" and r.trace.complete
    assert r.trace.terminal_attrs["shed_reason"] == "draining"


def test_trace_id_survives_retry():
    eng, _ = make_engine()
    r = eng.submit(context_tokens=16, new_tokens=1)
    tid = r.trace_id
    with inject("serve.step", times=1, kind="transient"):
        eng.run()
    assert r.outcome == "result"
    assert r.trace_id == tid
    d = r.trace.to_dict()
    marks = [sp for sp in d["spans"] if sp["name"] == "requeue"]
    assert any(m["attrs"].get("retries", 0) >= 1 for m in marks)
    assert r.trace.complete


def test_trace_id_survives_device_loss_failover():
    eng, _ = make_engine()
    r = eng.submit(context_tokens=16, new_tokens=1)
    with inject("device.dispatch", kind="unreachable", times=1):
        eng.step()
    eng.run()
    assert r.outcome == "result" and r.trace.complete


def test_trace_id_survives_reshard():
    from tilelang_mesh_tpu.serving import MeshDecodeWorkload
    alloc = PagedKVAllocator(n_pages=64, page_size=PS, heads=H,
                             head_dim=D)
    wl = MeshDecodeWorkload(alloc, batch_buckets=(4,), page_buckets=(2,))
    eng = ServingEngine(wl, name="reshard-trace")
    rs = [eng.submit(context_tokens=16, new_tokens=2) for _ in range(3)]
    with inject("serve.shard", kind="unreachable", times=1):
        eng.step()
    eng.run()
    assert eng.reshards >= 1
    for r in rs:
        assert r.outcome == "result" and r.trace.complete
    resharded = [r for r in rs
                 if any(sp["name"] == "reshard"
                        for sp in r.trace.to_dict()["spans"])]
    assert resharded, "the slice loss must land in survivor chains"


def test_batch_step_links_member_traces(monkeypatch):
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    eng, _ = make_engine()
    rs = [eng.submit(context_tokens=16) for _ in range(3)]
    eng.run()
    batches = [e for e in obs.get_tracer().events()
               if e.get("type") == "span"
               and e.get("name") == "serve.batch"]
    assert batches
    linked = set().union(*(set(b["attrs"]["links"]) for b in batches))
    assert {r.trace_id for r in rs} <= linked
    # the engine-trace context tags the dispatch event underneath
    disp = [e for e in obs.get_tracer().events()
            if e.get("name") == "serve.dispatch"]
    assert disp and disp[0]["attrs"]["trace_id"] == eng.trace.trace_id


@pytest.mark.slow
def test_causal_completeness_on_500_request_soak(tmp_path, monkeypatch):
    """The ISSUE 13 acceptance gate, run exactly as CI runs it: the
    500-request chaos soak at DEFAULTS (flight recorder on,
    TL_TPU_TRACE off) must exit 0 with every tl-scope check green."""
    # the driver sandboxes the prefix tier via os.environ (fine as a
    # CLI); monkeypatch registers the var for restoration in-process
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path))
    from tilelang_mesh_tpu.verify import chaos
    rc = chaos.run_serve(tmp_path, seed=13, n_requests=500)
    assert rc == 0
    report = json.loads((tmp_path / "serve_report.json").read_text())
    assert report["checks"]["causal_chains_complete"]
    assert report["checks"]["device_loss_flight_dump_names_victims"]
    assert report["checks"]["flight_dumps_atomic"]
    assert report["causally_incomplete_requests"] == []


# ---------------------------------------------------------------------------
# 3. flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_evicts_oldest(monkeypatch):
    monkeypatch.setenv("TL_TPU_FLIGHT_RING", "8")
    for i in range(20):
        flight.note_event(f"e{i}", "test", {})
    recs = flight.records()
    assert len(recs) == 8
    assert [r["name"] for r in recs] == [f"e{i}" for i in range(12, 20)]


def test_flight_off_switch(monkeypatch, tmp_path):
    monkeypatch.setenv("TL_TPU_FLIGHT", "0")
    flight.note_event("e", "test", {})
    assert flight.records() == []
    flight.configure(dump_dir=tmp_path)
    assert flight.dump("anything") is None
    assert list(tmp_path.iterdir()) == []


def test_flight_captures_counters_and_traced_spans(monkeypatch):
    obs.inc("some.counter", 2, site="x")
    kinds = {r["k"] for r in flight.records()}
    assert "counter" in kinds
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    with obs.span("phase", "test"):
        pass
    assert any(r["k"] == "span" and r["name"] == "phase"
               for r in flight.records())


def test_flight_dump_on_step_failure_names_victims(tmp_path):
    flight.configure(dump_dir=tmp_path)
    eng, _ = make_engine()
    rs = [eng.submit(context_tokens=16) for _ in range(2)]
    with inject("device.dispatch", kind="unreachable", times=1):
        eng.step()
    eng.run()
    dumps = sorted(tmp_path.glob("flight_*.jsonl"))
    heads = [json.loads(p.read_text().splitlines()[0]) for p in dumps]
    victims = [h for h in heads if h["reason"] == "step_failure"
               and h["attrs"]["kind"] == "device_loss"]
    assert victims, [h["reason"] for h in heads]
    assert set(victims[0]["attrs"]["batch_trace_ids"]) == \
        {r.trace_id for r in rs}
    # the dump is a full black box: ring + counter snapshot
    lines = [json.loads(ln) for ln in
             dumps[0].read_text().splitlines() if ln.strip()]
    assert lines[0]["schema"] == flight.FLIGHT_SCHEMA
    assert any(r.get("type") == "counter" for r in lines)
    assert any(r.get("type") == "flight_record" for r in lines)


def test_flight_dump_atomic_under_disk_write_fault(tmp_path):
    flight.configure(dump_dir=tmp_path)
    rec = flight.get_flight()
    with inject("cache.disk.write", kind="oserror"):
        assert flight.dump("step_failure") is None
    assert rec.dump_errors == 1
    # atomicity: NOTHING on disk — no target, no torn tmp file
    assert list(tmp_path.iterdir()) == []
    # and the next dump (fault cleared) succeeds
    assert flight.dump("step_failure") is not None
    assert len(list(tmp_path.glob("flight_*.jsonl"))) == 1


def test_flight_dump_per_reason_cap(tmp_path):
    """A flapping failure source must not fill the disk: past the
    per-reason ceiling dumps are counted, not written."""
    flight.configure(dump_dir=tmp_path)
    rec = flight.get_flight()
    cap = rec.MAX_DUMPS_PER_REASON
    for _ in range(cap + 5):
        flight.dump("step_failure")
    assert flight.dump("slo_breach") is not None   # other reasons live
    assert rec.dumps == cap + 1
    assert rec.dumps_capped == 5
    assert len(list(tmp_path.glob("flight_*step_failure*"))) == cap


def test_selfcheck_divergence_dumps_flight(tmp_path, monkeypatch):
    """The verify-layer triggers share the same black box: a corrupted
    collective schedule caught by the differential selfcheck dumps."""
    monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
    flight.configure(dump_dir=tmp_path)
    import numpy as np
    import tilelang_mesh_tpu as tilelang
    import tilelang_mesh_tpu.language as T
    from tilelang_mesh_tpu.parallel import mesh_config
    with mesh_config(2, 2):
        @T.prim_func
        def ft_fused(A: T.MeshTensor((2 * 2 * 8, 128),
                                     T.MeshShardingPolicy(cross_mesh_dim=0),
                                     (2, 2), "float32"),
                     B: T.MeshTensor((2 * 2 * 8, 1),
                                     T.MeshShardingPolicy(cross_mesh_dim=0),
                                     (2, 2), "float32"),
                     C: T.MeshTensor((2 * 2 * 8, 1),
                                     T.MeshShardingPolicy(cross_mesh_dim=0),
                                     (2, 2), "float32")):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment((8, 128), "float32")
                y = T.alloc_fragment((8, 128), "float32")
                o1 = T.alloc_fragment((8, 1), "float32")
                o2 = T.alloc_fragment((8, 1), "float32")
                T.copy(A, x)
                T.copy(A, y)
                T.comm.all_reduce(x, o1, "sum", "h", dim=1)
                T.comm.all_reduce(y, o2, "sum", "h", dim=1)
                T.copy(o1, B)
                T.copy(o2, C)
        k = tilelang.compile(ft_fused, target="cpu-mesh[2x2]")
    a = np.random.default_rng(0).standard_normal((32, 128)).astype(
        np.float32)
    with inject("comm.fused", kind="corrupt", seed=7):
        k(a)
    dumps = list(tmp_path.glob("flight_*selfcheck_divergence*.jsonl"))
    assert dumps, list(tmp_path.iterdir())


# ---------------------------------------------------------------------------
# 4. SLO engine
# ---------------------------------------------------------------------------

def _sample(t, submitted, shed, hist=None, completed=0):
    return {"t": t, "submitted": float(submitted), "shed": float(shed),
            "completed": float(completed), "failed": 0.0,
            "deadline_exceeded": 0.0, "hist": hist}


def test_slo_window_availability_and_burn():
    s = SLOEngine(windows=[10.0], target=0.99)
    s.add(_sample(0.0, 0, 0))
    s.add(_sample(5.0, 100, 5))
    w = s.window_stats(10.0)
    assert w["submitted"] == 100 and w["shed"] == 5
    assert w["availability"] == pytest.approx(0.95)
    # burn = (1 - 0.95) / (1 - 0.99) = 5x the budgeted error rate
    assert w["burn_rate"] == pytest.approx(5.0)


def test_slo_window_uses_edge_sample_not_lifetime():
    s = SLOEngine(windows=[10.0], target=0.99)
    s.add(_sample(0.0, 1000, 900))       # ancient history: terrible
    s.add(_sample(100.0, 1000, 900))     # window edge
    s.add(_sample(105.0, 1100, 900))     # last 10s: 100 clean submits
    w = s.window_stats(10.0)
    assert w["submitted"] == 100 and w["shed"] == 0
    assert w["availability"] == 1.0 and w["burn_rate"] == 0.0


def test_slo_window_p99_is_deltaed():
    base = Histogram()
    for _ in range(100):
        base.observe(0.001)               # ancient fast steps
    cur = Histogram()
    cur.merge(base)
    for _ in range(100):
        cur.observe(1.0)                  # the window's slow steps
    s = SLOEngine(windows=[10.0], target=0.99)
    s.add(_sample(0.0, 0, 0, hist=base))
    s.add(_sample(5.0, 10, 0, hist=cur))
    p99 = s.window_stats(10.0)["p99_ms"]
    assert p99 is not None and p99 > 100     # the old fast steps are out


def test_slo_breach_edge_fires_once(monkeypatch):
    monkeypatch.setenv("TL_TPU_SLO_BURN_MAX", "2.0")
    s = SLOEngine(windows=[10.0], target=0.99)
    s.add(_sample(0.0, 0, 0))
    s.add(_sample(5.0, 100, 50))          # burn 50x: breach
    assert s.check_breach() is not None
    assert s.check_breach() is None       # same episode: no re-fire
    # the bad window ages out entirely: the last 10s are clean
    s.add(_sample(100.0, 1100, 50))
    assert s.check_breach() is None       # recovered: episode closed
    s.add(_sample(109.0, 1200, 150))      # fresh sheds: new episode
    assert s.check_breach() is not None
    assert s.breaches == 2


def test_slo_admission_consult(monkeypatch):
    from tilelang_mesh_tpu.observability.slo import get_slo
    from tilelang_mesh_tpu.serving import AdmissionController
    monkeypatch.setenv("TL_TPU_SLO_ADMIT", "1")
    monkeypatch.setenv("TL_TPU_SLO_BURN_MAX", "2.0")
    s = get_slo()
    s.add(_sample(0.0, 0, 0))
    s.add(_sample(5.0, 100, 50))
    ok, reason = AdmissionController().decide(
        draining=False, queue_depth=0, free_pages=10, pages_needed=1,
        remaining_s=None, steps_requested=1)
    assert not ok and reason == "overload"
    monkeypatch.setenv("TL_TPU_SLO_ADMIT", "0")
    ok, _ = AdmissionController().decide(
        draining=False, queue_depth=0, free_pages=10, pages_needed=1,
        remaining_s=None, steps_requested=1)
    assert ok


def test_slo_windows_typo_raises(monkeypatch):
    with pytest.raises(ValueError):
        parse_windows("30,oops")
    with pytest.raises(ValueError):
        parse_windows("-5")
    assert parse_windows("300,30") == [30.0, 300.0]


def test_metrics_summary_has_tl_scope_sections():
    eng, _ = make_engine()
    eng.submit(context_tokens=16)
    eng.run()
    m = obs.metrics_summary()
    assert m["slo"]["target"] == pytest.approx(0.999)
    assert m["flight"]["enabled"] is True
    assert m["reqtrace"]["terminal"] == 1
    assert m["reqtrace"]["complete"] == 1


# ---------------------------------------------------------------------------
# 5. surfaces: endpoint, escaping, flow events, analyzer
# ---------------------------------------------------------------------------

_EXPO_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'                       # metric name
    r'(\{([a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*",?)*\})?'  # labels
    r' [0-9eE+.\-]+(inf|nan)?$')


def _assert_valid_exposition(text: str) -> int:
    n = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _EXPO_LINE.match(line), f"unparseable exposition: {line!r}"
        n += 1
    return n


def test_endpoint_routes_and_scrape_parse():
    from tilelang_mesh_tpu.observability import server
    eng, _ = make_engine()
    eng.submit(context_tokens=16)
    eng.run()
    srv = server.start_server(port=0)
    try:
        def get(path):
            with urllib.request.urlopen(f"{srv.url}{path}", timeout=5) as r:
                return r.status, r.read().decode()
        code, metrics = get("/metrics")
        assert code == 200
        assert _assert_valid_exposition(metrics) > 0
        assert "tl_tpu_serve_completed" in metrics
        code, health = get("/healthz")
        assert code == 200 and json.loads(health)["ok"] is True
        code, slo = get("/slo")
        assert code == 200
        assert json.loads(slo)["target"] == pytest.approx(0.999)
        code, fl = get("/flight")
        assert code == 200
        assert json.loads(fl)["enabled"] is True
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/nope")
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_endpoint_off_by_default(monkeypatch):
    from tilelang_mesh_tpu.observability import server
    monkeypatch.delenv("TL_TPU_METRICS_PORT", raising=False)
    assert server.maybe_start() is None


def test_prometheus_label_escaping_round_trip():
    """Adversarial label values — quotes, backslashes, newlines (kernel
    names are user strings) — must survive exposition + unescape."""
    evil = 'kernel "with" \\backslash\\ and\nnewline'
    obs.inc("escape.test", kernel=evil)
    from tilelang_mesh_tpu.observability import histogram as _hist
    _hist.observe("kernel.latency", 0.001, kernel=evil, source="test")
    text = obs.to_prometheus_text()
    _assert_valid_exposition(text)
    m = re.search(r'tl_tpu_escape_test\{kernel="((?:\\.|[^"\\])*)"\} 1',
                  text)
    assert m, text
    unescaped = (m.group(1).replace("\\n", "\n").replace('\\"', '"')
                 .replace("\\\\", "\\"))
    assert unescaped == evil


def test_chrome_trace_flow_events(monkeypatch):
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    eng, _ = make_engine()
    eng.submit(context_tokens=16, new_tokens=2)
    eng.run()
    ct = obs.to_chrome_trace()
    phases = {}
    for e in ct["traceEvents"]:
        phases.setdefault(e["ph"], []).append(e)
    # flow start + steps connect the request's chain and the batch
    # spans that served it into one arrow chain
    assert "s" in phases and ("t" in phases or "f" in phases)
    flow_ids = {e["id"] for e in phases["s"]}
    assert all(isinstance(i, int) and i > 0 for i in flow_ids)
    json.dumps(ct)     # the whole object must stay serializable


def test_jsonl_trace_context_schema_and_versioning(tmp_path):
    eng, _ = make_engine()
    r = eng.submit(context_tokens=16)
    eng.run()
    path = tmp_path / "t.jsonl"
    obs.write_jsonl(path)
    recs = obs.read_jsonl(path)
    header = [x for x in recs if x.get("type") == "trace_context"]
    assert header and header[0]["schema"] == obs.REQTRACE_SCHEMA
    chains = [x for x in recs if x.get("type") == "reqtrace"]
    assert any(c["trace_id"] == r.trace_id and c["complete"]
               for c in chains)
    # a future-schema chain is skipped, not misread
    from tilelang_mesh_tpu.tools.analyzer import summarize_request
    alien = dict(chains[0], schema=99, trace_id="alien-1")
    s = summarize_request(recs + [alien])
    assert s["skipped_other_schema"] == 1
    assert all(row["trace_id"] != "alien-1" for row in s["traces"])


def test_analyzer_request_views(tmp_path, capsys):
    from tilelang_mesh_tpu.tools import analyzer
    eng, _ = make_engine()
    r = eng.submit(context_tokens=16, new_tokens=2)
    eng.run()
    path = tmp_path / "t.jsonl"
    obs.write_jsonl(path)
    assert analyzer.main(["request", str(path)]) == 0
    out = capsys.readouterr().out
    assert r.trace_id in out and "request traces" in out
    assert analyzer.main(["request", str(path),
                          "--trace-id", r.trace_id]) == 0
    out = capsys.readouterr().out
    assert "decode.step" in out and "submit" in out
    assert analyzer.main(["request", str(path), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["schema"] == obs.REQTRACE_SCHEMA


def _round(tmp_path, name, n, rc, records):
    tail = "\n".join(json.dumps(r) for r in records)
    p = tmp_path / name
    p.write_text(json.dumps({"n": n, "cmd": "bench", "rc": rc,
                             "tail": tail}))
    return str(p)


def test_analyzer_dash_flags_regressions_and_missing(tmp_path, capsys):
    from tilelang_mesh_tpu.tools import analyzer
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps([
        {"config": "k", "latency_p50_ms": 1.0, "latency_mad_ms": 0.01}]))
    r1 = _round(tmp_path, "BENCH_r01.json", 1, 0,
                [{"config": "k", "latency_p50_ms": 1.02,
                  "latency_mad_ms": 0.01}])
    r2 = _round(tmp_path, "BENCH_r02.json", 2, 0,
                [{"config": "k", "latency_p50_ms": 3.0,
                  "latency_mad_ms": 0.01}])
    r3 = _round(tmp_path, "BENCH_r03.json", 3, 1,
                [{"config": "k",
                  "error": "skipped: TPU worker unreachable"}])
    assert analyzer.main(["dash", r1, r2, r3,
                          "--baseline", str(base)]) == 0
    out = capsys.readouterr().out
    assert "missing-not-regressed" in out     # the rc=1 round
    assert "REGRESSED: k" in out              # the genuine 3x slowdown
    assert analyzer.main(["dash", r1, r2, r3, "--baseline", str(base),
                          "--json"]) == 0
    dash = json.loads(capsys.readouterr().out)
    cells = dash["configs"]["k"]["cells"]
    assert [c["verdict"] for c in cells] == \
        ["ok", "REGRESSION", "missing-not-regressed"]
    assert dash["rounds"][2]["status"] == "missing-not-regressed"
    assert dash["regressions"] == ["k"]


def test_analyzer_dash_checked_in_rounds(capsys):
    """The acceptance gate: the repo's own BENCH_r0*.json render, and
    the rc=1 rounds r03-r05 read missing-not-regressed."""
    import glob
    from pathlib import Path

    from tilelang_mesh_tpu.tools import analyzer
    repo = Path(__file__).resolve().parent.parent
    rounds = sorted(glob.glob(str(repo / "BENCH_r0*.json")))
    assert len(rounds) >= 5
    assert analyzer.main(["dash", *rounds, "--baseline",
                          str(repo / ".github" / "perf_baseline.json"),
                          "--json"]) == 0
    dash = json.loads(capsys.readouterr().out)
    by_label = {r["label"]: r for r in dash["rounds"]}
    for lbl in ("r03", "r04", "r05"):
        assert by_label[lbl]["rc"] == 1
        assert by_label[lbl]["status"] == "missing-not-regressed"
    assert dash["regressions"] == []          # missing is never regressed
    assert analyzer.main(["dash", *rounds]) == 0
    assert "missing-not-regressed" in capsys.readouterr().out
