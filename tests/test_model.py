"""Flagship transformer: forward, single-device training, and the
megatron-style dp x tp sharded training step on the virtual mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.models import (ModelConfig, forward, init_params,
                                      loss_fn, make_sharded_train_step,
                                      make_train_step)


CFG = ModelConfig(vocab=64, d_model=64, n_layers=2, n_heads=4, d_ff=128,
                  max_seq=32, dtype=jnp.float32, use_flash=False)


def _tokens(rng, b, s):
    return jnp.asarray(rng.integers(0, CFG.vocab, (b, s)), jnp.int32)


def test_forward_shapes():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tok = _tokens(np.random.default_rng(0), 2, CFG.max_seq)
    logits = forward(params, tok, CFG)
    assert logits.shape == (2, CFG.max_seq, CFG.vocab)
    assert np.isfinite(np.asarray(logits)).all()


def test_forward_uses_flash_kernel_matches_reference():
    cfg_flash = ModelConfig(**{**CFG.__dict__, "use_flash": True})
    params = init_params(jax.random.PRNGKey(0), CFG)
    tok = _tokens(np.random.default_rng(1), 2, 32)
    a = forward(params, tok, CFG)
    b = forward(params, tok, cfg_flash)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-2,
                               atol=2e-2)


def test_train_step_reduces_loss():
    params = init_params(jax.random.PRNGKey(0), CFG)
    init, step = make_train_step(CFG, lr=1e-2)
    opt_state = init(params)
    tok = _tokens(np.random.default_rng(2), 4, CFG.max_seq + 1)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tok)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_sharded_train_step_matches_single_device():
    """dp x tp sharded step must produce the same loss trajectory as the
    single-device step (same math, different layout)."""
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs 4 devices")
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    params = init_params(jax.random.PRNGKey(0), CFG)
    tok = _tokens(np.random.default_rng(3), 4, CFG.max_seq + 1)

    init_s, make = make_sharded_train_step(CFG, mesh, lr=1e-2)
    opt_s = init_s(params)
    step_s = make(params, opt_s)
    p1, o1, loss_sharded = step_s(params, opt_s, tok)

    init_1, step_1 = make_train_step(CFG, lr=1e-2)
    opt_1 = init_1(params)
    p2, o2, loss_single = step_1(params, opt_1, tok)

    np.testing.assert_allclose(float(loss_sharded), float(loss_single),
                               rtol=1e-4)
    # updated sharded params must match the single-device update
    flat1 = jax.tree.leaves(p1)
    flat2 = jax.tree.leaves(p2)
    for a, b in zip(flat1, flat2):
        # adamw normalizes by sqrt(nu): tiny psum-ordering differences in
        # grads amplify near zero-curvature entries, so compare loosely
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-2,
                                   atol=5e-3)


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2
    ge.dryrun_multichip(len(jax.devices()))
