"""Elastic mesh-sharded serving suite (serving/mesh_workload.py;
docs/serving.md "Mesh sharding & elastic degradation").

Five layers, mirroring the subsystem:

1. **Layout ladder** — token/ladder parsing, the guaranteed
   ``no_sharding`` terminal rung, the ``TL_TPU_SERVE_LAYOUTS`` knob.
2. **Build-time validation** — head/batch divisibility, unknown mesh
   axis names, too-few devices: every violation is a named
   ``MeshVerifyError`` at workload build, never a shard_map failure
   deep inside XLA.
3. **KV migration** — checksummed ``snapshot()``/``restore()``:
   round-trip byte equality, repacking onto a smaller allocator,
   double-restore rejection, corruption detection, and balanced books
   via ``migrate()``.
4. **Sharded dispatch** — ``shard_map`` decode numerics match the
   single-host workload bit-for-tolerance on head- and batch-parallel
   layouts; the straggler probe fills per-shard histograms.
5. **The elastic contract** — a slice kill mid-decode walks the ladder
   one rung down with live KV migration and zero leaks; the metrics /
   analyzer surfaces report it; the ``--serve-mesh`` chaos driver
   passes end to end (the same driver CI gates with).

Everything runs on the conftest-forced 8-device virtual CPU mesh.
"""

import numpy as np
import pytest

from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.resilience import inject
from tilelang_mesh_tpu.resilience.errors import DeviceLossError
from tilelang_mesh_tpu.serving import (FlashDecodeWorkload, KVCacheExhausted,
                                       MeshDecodeWorkload, MeshLayout,
                                       PagedKVAllocator, ServeShardConfig,
                                       ServingEngine, layout_ladder, migrate,
                                       parse_layout, serving_meta,
                                       serving_state, validate_shard_config)
from tilelang_mesh_tpu.verify.schedule import MeshVerifyError

H, D, PS = 2, 64, 8


def make_alloc(n_pages=64):
    return PagedKVAllocator(n_pages=n_pages, page_size=PS, heads=H,
                            head_dim=D)


def make_mesh_engine(n_pages=64, batch_buckets=(4,), page_buckets=(2,),
                     layouts=None, **kw):
    alloc = make_alloc(n_pages)
    wl = MeshDecodeWorkload(alloc, batch_buckets=batch_buckets,
                            page_buckets=page_buckets, layouts=layouts)
    return ServingEngine(wl, **kw), alloc


# ---------------------------------------------------------------------------
# layout ladder parsing
# ---------------------------------------------------------------------------

def test_parse_layout_tokens():
    lay = parse_layout("head_parallel:2x2")
    assert lay.kind == "head_parallel" and (lay.rows, lay.cols) == (2, 2)
    assert lay.name == "head_parallel:2x2" and lay.sharded
    assert parse_layout("no_sharding").devices == 1
    assert parse_layout("batch_parallel:1x4").cols == 4


@pytest.mark.parametrize("bad", ["", "ring_parallel:2x2", "head_parallel",
                                 "head_parallel:2", "head_parallel:0x2",
                                 "no_sharding:2x2"])
def test_parse_layout_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_layout(bad)


def test_layout_ladder_default_and_terminal_rung(monkeypatch):
    rungs = layout_ladder()
    assert rungs[0].name == "head_parallel:2x2"
    assert rungs[-1].kind == "no_sharding"
    # a ladder without a terminal rung gets no_sharding appended
    rungs = layout_ladder("head_parallel:2x2")
    assert [r.name for r in rungs] == ["head_parallel:2x2", "no_sharding"]
    monkeypatch.setenv("TL_TPU_SERVE_LAYOUTS",
                       "batch_parallel:2x1,no_sharding")
    rungs = layout_ladder()
    assert [r.name for r in rungs] == ["batch_parallel:2x1", "no_sharding"]


def test_workload_honors_env_ladder(monkeypatch):
    monkeypatch.setenv("TL_TPU_SERVE_LAYOUTS", "head_parallel:2x1")
    wl = MeshDecodeWorkload(make_alloc(), batch_buckets=(2,),
                            page_buckets=(2,))
    assert [r.name for r in wl.ladder] == ["head_parallel:2x1",
                                           "no_sharding"]
    assert wl.layout.name == "head_parallel:2x1"


# ---------------------------------------------------------------------------
# build-time validation (satellite: MeshVerifyError, not deep XLA)
# ---------------------------------------------------------------------------

def test_heads_must_divide_sharded_axis():
    alloc = PagedKVAllocator(n_pages=16, page_size=PS, heads=3, head_dim=D)
    with pytest.raises(MeshVerifyError, match="3 head.*not divisible"):
        MeshDecodeWorkload(alloc, batch_buckets=(2,), page_buckets=(2,),
                           layouts="head_parallel:2x2")


def test_batch_buckets_must_divide_sharded_axis():
    with pytest.raises(MeshVerifyError, match=r"batch bucket.*\[1\]"):
        MeshDecodeWorkload(make_alloc(), batch_buckets=(1, 4),
                           page_buckets=(2,), layouts="batch_parallel:2x2")


def test_unknown_mesh_axis_rejected_at_build():
    with pytest.raises(MeshVerifyError, match="mesh axis 'z'"):
        MeshDecodeWorkload(make_alloc(), batch_buckets=(2,),
                           page_buckets=(2,),
                           layouts="head_parallel:2x2",
                           shard_config=ServeShardConfig.head_parallel("z"))


def test_too_few_devices_named_error():
    # conftest forces 8 host devices; a 3x3 mesh cannot build (batch
    # bucket 9 divides the axis, so the DEVICE check is what fires)
    with pytest.raises(MeshVerifyError, match="9 device"):
        MeshDecodeWorkload(make_alloc(), batch_buckets=(9,),
                           page_buckets=(2,), layouts="batch_parallel:3x3")


def test_validate_shard_config_direct():
    lay = MeshLayout("head_parallel", 2, 2)
    validate_shard_config(ServeShardConfig.head_parallel("x"), lay,
                          heads=2, batch_buckets=(4,))
    with pytest.raises(MeshVerifyError):
        validate_shard_config(ServeShardConfig.head_parallel("x"), lay,
                              heads=5, batch_buckets=(4,))
    # no_sharding validates trivially regardless of geometry
    validate_shard_config(ServeShardConfig.no_sharding(),
                          MeshLayout("no_sharding"), heads=5,
                          batch_buckets=(3,))


# ---------------------------------------------------------------------------
# KV snapshot / restore / migrate
# ---------------------------------------------------------------------------

def _fill(alloc, owner, n, seed=0):
    rng = np.random.default_rng(seed)
    pages = alloc.alloc(n, owner)
    for p in pages:
        shape = (alloc.heads, alloc.page_size, alloc.head_dim)
        alloc.fill_page(p, rng.standard_normal(shape).astype(np.float32),
                        rng.standard_normal(shape).astype(np.float32))
    return pages


def test_snapshot_checksum_round_trip():
    src = make_alloc(16)
    pages = _fill(src, owner=1, n=3, seed=7)
    snap = src.snapshot()
    assert snap.n_pages == 3 and snap.nbytes == \
        3 * 2 * H * PS * D * 4
    snap.verify()            # self-consistent
    dst = make_alloc(16)
    mapping = dst.restore(snap)
    assert sorted(mapping) == sorted(pages)
    # bytes land identically (order preserved per owner)
    for old, new in mapping.items():
        r0o, r0n = old * PS, new * PS
        np.testing.assert_array_equal(src.kp[:, r0o:r0o + PS],
                                      dst.kp[:, r0n:r0n + PS])
        np.testing.assert_array_equal(src.vp[:, r0o:r0o + PS],
                                      dst.vp[:, r0n:r0n + PS])
    assert dst.holdings(1) == [mapping[p] for p in pages]


def test_restore_onto_smaller_allocator_repacks():
    src = make_alloc(64)
    # spread pages high in the id space so a smaller target MUST remap
    _fill(src, owner=1, n=2, seed=1)
    _fill(src, owner=2, n=3, seed=2)
    src.free(1)
    pages2 = _fill(src, owner=3, n=2, seed=3)
    snap = src.snapshot()
    dst = make_alloc(8)      # 64-page placement -> 8-page placement
    mapping = dst.restore(snap)
    assert len(mapping) == 5 and dst.in_use == 5
    assert all(new < 8 for new in mapping.values())
    assert dst.holdings(3) == [mapping[p] for p in pages2]


def test_restore_capacity_and_geometry_checks():
    src = make_alloc(16)
    _fill(src, owner=1, n=4)
    snap = src.snapshot()
    tiny = make_alloc(2)
    with pytest.raises(KVCacheExhausted):
        tiny.restore(snap)
    other = PagedKVAllocator(n_pages=16, page_size=PS, heads=H + 2,
                             head_dim=D)
    with pytest.raises(ValueError, match="geometry"):
        other.restore(snap)


def test_double_restore_rejected():
    src = make_alloc(16)
    _fill(src, owner=1, n=2)
    snap = src.snapshot()
    make_alloc(16).restore(snap)
    with pytest.raises(ValueError, match="already restored"):
        make_alloc(16).restore(snap)


def test_corrupted_snapshot_detected():
    src = make_alloc(16)
    pages = _fill(src, owner=1, n=2)
    snap = src.snapshot()
    snap.pages[pages[0]][0][0, 0, 0] += 1.0     # bit-rot in flight
    with pytest.raises(ValueError, match="checksum"):
        make_alloc(16).restore(snap)


def test_restore_frees_target_pages_when_written_bytes_corrupt(monkeypatch):
    """The post-write conservation check is inside the undo scope: a
    corrupted write raises AND releases the freshly allocated target
    pages — no phantom owners leak into the target allocator."""
    src = make_alloc(16)
    _fill(src, owner=1, n=3, seed=3)
    snap = src.snapshot()
    dst = make_alloc(16)
    real_fill = dst.fill_page

    def corrupting_fill(page, k, v):
        real_fill(page, k + 1.0, v)          # write the WRONG bytes

    monkeypatch.setattr(dst, "fill_page", corrupting_fill)
    with pytest.raises(ValueError, match="corrupted"):
        dst.restore(snap)
    assert dst.in_use == 0 and not dst.leak_check()
    assert snap.consumed is False            # still restorable elsewhere
    monkeypatch.undo()
    assert len(dst.restore(snap)) == 3       # clean retry succeeds


def test_reshard_clears_stale_shard_skew_gauge():
    """The old layout's straggler signal dies with its mesh: after a
    reshard the shard_skew gauge is gone until the new rung's first
    probe repopulates it."""
    eng, _ = make_mesh_engine(name="elastic-skew")
    eng.workload.probe_shards()
    from tilelang_mesh_tpu.serving.request import publish_gauges
    publish_gauges(shard_skew=9.9)
    err = DeviceLossError("slice died", site="serve.shard")
    assert eng._maybe_reshard(err) is True
    assert "shard_skew" not in serving_state()


def test_migrate_balances_both_allocators():
    src, dst = make_alloc(16), make_alloc(16)
    _fill(src, owner=1, n=3)
    _fill(src, owner=2, n=2)
    mapping, nbytes = migrate(src, dst)
    assert len(mapping) == 5
    assert nbytes == 5 * 2 * H * PS * D * 4
    assert src.in_use == 0 and not src.leak_check()
    assert dst.in_use == 5
    assert src.alloc_count == src.free_count == 5
    dst.free(1)
    dst.free(2)
    assert dst.in_use == 0 and dst.alloc_count == dst.free_count


# ---------------------------------------------------------------------------
# sharded dispatch numerics + straggler probe
# ---------------------------------------------------------------------------

def _single_host_result(seed, new_tokens=2, **wl_kw):
    alloc = make_alloc()
    wl = FlashDecodeWorkload(alloc, **wl_kw)
    eng = ServingEngine(wl, name="ref")
    r = eng.submit(context_tokens=16, new_tokens=new_tokens, seed=seed)
    eng.run()
    assert r.outcome == "result"
    return np.asarray(r.result)


@pytest.mark.parametrize("layouts", ["head_parallel:2x2",
                                     "batch_parallel:2x2"])
def test_mesh_dispatch_matches_single_host(layouts):
    eng, _ = make_mesh_engine(batch_buckets=(4,), layouts=layouts)
    r = eng.submit(context_tokens=16, new_tokens=2, seed=11)
    eng.run()
    assert r.outcome == "result"
    want = _single_host_result(11, batch_buckets=(4,), page_buckets=(2,))
    np.testing.assert_allclose(np.asarray(r.result), want,
                               rtol=1e-5, atol=1e-5)


def test_mesh_dispatch_multi_request_batch():
    eng, alloc = make_mesh_engine(batch_buckets=(4,))
    reqs = [eng.submit(context_tokens=16, new_tokens=2, seed=i)
            for i in range(3)]
    eng.run()
    assert all(r.outcome == "result" for r in reqs)
    assert alloc.in_use == 0 and not alloc.leak_check()


def test_no_sharding_rung_delegates_to_single_host():
    eng, _ = make_mesh_engine(layouts="no_sharding")
    assert eng.workload.mesh is None
    r = eng.submit(context_tokens=16, new_tokens=1, seed=5)
    eng.run()
    assert r.outcome == "result"


def test_straggler_probe_fills_per_shard_histograms():
    from tilelang_mesh_tpu.observability import histogram as _hist
    eng, _ = make_mesh_engine()
    skew = eng.workload.probe_shards()
    assert skew is not None and skew >= 1.0
    shards = {dict(labels).get("shard")
              for (name, labels), h in _hist.histograms()
              if name == "serve.shard.latency" and h.count}
    assert set(eng.workload.shard_names()) <= shards
    assert len(eng.workload.shard_names()) == 4


def test_engine_publishes_shard_skew_gauge():
    eng, _ = make_mesh_engine()
    eng._shard_probe_every = 1          # probe on every step
    eng.submit(context_tokens=16, new_tokens=1, seed=3)
    eng.run()
    assert serving_state().get("shard_skew", 0) >= 1.0


def test_probe_lost_all_alive():
    eng, _ = make_mesh_engine()
    assert eng.workload.probe_lost() == []


# ---------------------------------------------------------------------------
# the elastic contract: slice loss -> reshard -> migrate -> serve on
# ---------------------------------------------------------------------------

def test_slice_kill_walks_ladder_with_live_migration():
    obs.reset()
    eng, first_alloc = make_mesh_engine(name="elastic")
    eng.warmup()
    reqs = [eng.submit(context_tokens=16, new_tokens=3, seed=i)
            for i in range(3)]
    eng.step()                           # one healthy sharded step
    with inject("serve.shard", kind="unreachable", times=1):
        eng.step()                       # the slice dies mid-step
    eng.run()
    wl = eng.workload
    assert wl.layout.name == "head_parallel:2x1"
    assert eng.reshards == 1
    assert all(r.outcome == "result" for r in reqs)
    # migration swapped allocators; BOTH placements balance to zero
    assert wl.allocator is not first_alloc
    assert first_alloc.in_use == 0 and not first_alloc.leak_check()
    assert wl.allocator.in_use == 0 and not wl.allocator.leak_check()
    assert serving_meta()["layout"] == "head_parallel:2x1"
    s = obs.metrics_summary()["serving"]
    assert s["reshards"] == 1 and s["layout"] == "head_parallel:2x1"
    assert s["kv_pages_migrated"] > 0
    assert s["kv_pages_allocated"] == s["kv_pages_freed"]


def test_second_kill_reaches_no_sharding_terminal_rung():
    eng, _ = make_mesh_engine(name="elastic2")
    for kill in range(2):
        reqs = [eng.submit(context_tokens=16, new_tokens=2, seed=kill * 7 + i)
                for i in range(2)]
        with inject("serve.shard", kind="unreachable", times=1):
            eng.step()
        eng.run()
        assert all(r.outcome == "result" for r in reqs)
    assert eng.workload.layout.name == "no_sharding"
    assert eng.reshards == 2
    # a further device loss on the terminal rung cannot reshard: it
    # takes the ordinary quarantine/retry path and still completes
    r = eng.submit(context_tokens=16, new_tokens=1, seed=99)
    with inject("device.dispatch", kind="unreachable", times=1):
        eng.step()
    eng.run()
    assert r.outcome == "result" and eng.reshards == 2


def test_watchdog_timeout_also_walks_ladder():
    eng, _ = make_mesh_engine(name="elastic-to")
    r = eng.submit(context_tokens=16, new_tokens=1, seed=1)
    with inject("serve.shard", kind="timeout", times=1):
        eng.step()
    eng.run()
    assert eng.reshards == 1
    assert eng.workload.layout.name == "head_parallel:2x1"
    assert r.outcome == "result"


def test_reshard_budget_bounds_ladder_walk():
    eng, _ = make_mesh_engine(name="elastic-budget", retry_max=3)
    eng.reshard_max = 0
    r = eng.submit(context_tokens=16, new_tokens=1, seed=1)
    with inject("serve.shard", kind="unreachable", times=1):
        eng.step()
    eng.run()
    assert eng.reshards == 0
    assert eng.workload.layout.name == "head_parallel:2x2"
    assert r.outcome == "result"         # retried on the same layout


def test_lost_device_quarantined_and_excluded():
    from tilelang_mesh_tpu.codegen.backends import registry
    eng, _ = make_mesh_engine(name="elastic-q")
    eng.submit(context_tokens=16, new_tokens=1, seed=1)
    victim = str(eng.workload.mesh.devices.flat[0])
    err = DeviceLossError("slice died", site="serve.shard")
    err.device = victim
    assert eng._maybe_reshard(err) is True
    assert victim in registry().quarantined_devices()
    assert victim not in eng.workload.layout_stats()["mesh_devices"]
    assert "quarantined_devices" in registry().snapshot()
    eng.run()


def test_deadline_budget_timeout_does_not_reshard():
    """A deadline-derived step-budget timeout (site=serve.step) says
    nothing about mesh health: one tight-deadlined request must not
    halve serving capacity by walking the ladder."""
    eng, _ = make_mesh_engine(name="elastic-ddl")
    r = eng.submit(context_tokens=16, new_tokens=1, seed=1)
    # an injected serve.step timeout carries site=serve.step — the same
    # signature a deadline-derived _bounded_step expiry raises with
    with inject("serve.step", kind="timeout", times=1):
        eng.step()
    assert eng.reshards == 0
    assert eng.workload.layout.name == "head_parallel:2x2"
    eng.run()
    assert r.outcome == "result"         # retried on the same layout


def test_failed_migration_rewarms_on_fresh_placement(monkeypatch):
    """ROADMAP 1(d): when the KV migration fails, the reshard no
    longer gives up — the fresh allocator is installed anyway, live
    requests re-warm (cold re-prefill without a cached prefix), and
    the rung walk still lands."""
    from tilelang_mesh_tpu.serving import kv_cache as kvmod
    obs.reset()
    eng, alloc = make_mesh_engine(name="elastic-migfail")
    # cache disabled -> the re-warm has nothing to restore from and
    # must cold re-prefill (the warm variant is the next test)
    eng.workload.prefix_cache = None
    r = eng.submit(context_tokens=16, new_tokens=1, seed=1)

    def boom(src, dst):
        raise KVCacheExhausted("injected migration failure",
                               site="serve.kv")

    monkeypatch.setattr(kvmod, "migrate", boom)
    err = DeviceLossError("slice died", site="serve.shard")
    assert eng._maybe_reshard(err) is True
    assert eng.reshards == 1
    assert eng.workload.allocator is not alloc
    assert eng.workload.layout.name == "head_parallel:2x1"
    assert serving_meta().get("layout") == "head_parallel:2x1"
    c = obs.get_tracer().counters()
    assert c.get("serve.reshard.rewarm{source=cold}", 0) >= 1
    assert "rewarm" in [sp.name for sp in r.trace.spans]
    monkeypatch.undo()
    eng.run()
    assert r.outcome == "result"


def test_failed_migration_rewarm_hits_prefix_cache(
        tmp_path, monkeypatch):
    """The re-warm path consults the prefix cache first: a live
    request whose whole-page prefix is cached restores WARM on the
    fresh placement (``prefix_cache.hit`` lands on the reshard path)
    instead of cold re-prefilling."""
    from tilelang_mesh_tpu.serving import kv_cache as kvmod
    from tilelang_mesh_tpu.serving import reset_prefix_cache
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path / "px"))
    reset_prefix_cache()
    try:
        obs.reset()
        eng, alloc = make_mesh_engine(name="elastic-migwarm")
        prompt = [11_000 + i for i in range(16)]   # 2 whole pages
        seed_req = eng.submit(context_tokens=16, new_tokens=1, seed=1,
                              prompt_tokens=list(prompt))
        eng.run()
        assert seed_req.outcome == "result"        # prefix now cached
        r = eng.submit(context_tokens=16, new_tokens=1, seed=2,
                       prompt_tokens=list(prompt))
        hits_before = obs.get_tracer().counters().get(
            "prefix_cache.hit", 0)

        def boom(src, dst):
            raise KVCacheExhausted("injected migration failure",
                                   site="serve.kv")

        monkeypatch.setattr(kvmod, "migrate", boom)
        err = DeviceLossError("slice died", site="serve.shard")
        assert eng._maybe_reshard(err) is True
        c = obs.get_tracer().counters()
        assert c.get("serve.reshard.rewarm{source=prefix}", 0) >= 1
        assert c.get("prefix_cache.hit", 0) > hits_before
        assert r.prefix_tokens == 16
        monkeypatch.undo()
        eng.run()
        assert r.outcome == "result"
    finally:
        reset_prefix_cache()


def test_rewarm_failure_does_not_crash_reshard(monkeypatch):
    """A warm-up failure on the new rung is best-effort: the reshard
    still lands (buckets compile lazily on first dispatch) instead of
    escaping step() with the batch stuck non-terminal."""
    eng, _ = make_mesh_engine(name="elastic-warmfail")
    r = eng.submit(context_tokens=16, new_tokens=1, seed=1)
    wl = eng.workload
    monkeypatch.setattr(type(wl), "warmup",
                        lambda self: (_ for _ in ()).throw(
                            RuntimeError("injected warm-up failure")))
    err = DeviceLossError("slice died", site="serve.shard")
    assert eng._maybe_reshard(err) is True
    assert eng.reshards == 1
    assert wl.layout.name == "head_parallel:2x1"
    monkeypatch.undo()
    eng.run()
    assert r.outcome == "result"


def test_later_reshards_exclude_previously_quarantined():
    """A device quarantined by an EARLIER reshard never re-enters a
    layout: the second rung walk excludes the union of every
    quarantined slice, not just the current failure's."""
    from tilelang_mesh_tpu.codegen.backends import registry
    eng, _ = make_mesh_engine(
        name="elastic-q2",
        layouts="head_parallel:2x2,head_parallel:2x1,head_parallel:1x2")
    wl = eng.workload
    victim1 = str(wl.mesh.devices.flat[0])
    err1 = DeviceLossError("slice died", site="serve.shard")
    err1.device = victim1
    assert eng._maybe_reshard(err1) is True
    assert victim1 not in wl.layout_stats()["mesh_devices"]
    victim2 = str(wl.mesh.devices.flat[0])
    err2 = DeviceLossError("slice died", site="serve.shard")
    err2.device = victim2
    assert eng._maybe_reshard(err2) is True
    mesh_devs = wl.layout_stats()["mesh_devices"]
    assert victim1 not in mesh_devs      # the EARLIER quarantine holds
    assert victim2 not in mesh_devs
    assert {victim1, victim2} <= set(registry().quarantined_devices())


def test_requests_survive_reshard_with_correct_results():
    """The correctness half of 'degrades capacity, never correctness':
    a request whose decode spans a reshard produces the same final
    output as the same request served without any failure."""
    eng, _ = make_mesh_engine(name="elastic-num")
    r = eng.submit(context_tokens=16, new_tokens=3, seed=21)
    eng.step()
    with inject("serve.shard", kind="unreachable", times=1):
        eng.step()
    eng.run()
    assert r.outcome == "result"
    want = _single_host_result(21, new_tokens=3, batch_buckets=(4,),
                               page_buckets=(2,))
    np.testing.assert_allclose(np.asarray(r.result), want,
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------

def test_analyzer_serve_mesh_section(tmp_path, monkeypatch):
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    obs.reset()
    eng, _ = make_mesh_engine(name="elastic-an")
    eng._shard_probe_every = 1
    reqs = [eng.submit(context_tokens=16, new_tokens=2, seed=i)
            for i in range(2)]
    with inject("serve.shard", kind="unreachable", times=1):
        eng.step()
    eng.run()
    assert all(r.outcome == "result" for r in reqs)
    p = tmp_path / "mesh.jsonl"
    obs.write_jsonl(str(p))
    from tilelang_mesh_tpu.tools.analyzer import (format_serve_report,
                                                  summarize_serve)
    recs = obs.read_jsonl(str(p))
    s = summarize_serve(recs)
    assert s["reshards"] == 1
    assert s["layout"] == "head_parallel:2x1"
    assert s["reshard_events"][0]["frm"] == "head_parallel:2x2"
    assert s["kv"]["migrated_pages"] > 0
    assert s["shard_latency"]          # per-shard probe digests
    assert s["shard_skew"] is None or s["shard_skew"] >= 1.0
    text = format_serve_report(recs)
    assert "mesh serving (elastic):" in text
    assert "reshard head_parallel:2x2 -> head_parallel:2x1" in text
    assert "per-shard latency" in text


def test_stats_and_layout_stats():
    eng, _ = make_mesh_engine()
    st = eng.stats()
    assert st["reshards"] == 0
    assert st["mesh"]["layout"] == "head_parallel:2x2"
    assert st["mesh"]["ladder"][-1] == "no_sharding"
    assert len(st["mesh"]["mesh_devices"]) == 4


# ---------------------------------------------------------------------------
# the contract, end to end: the --serve-mesh chaos driver
# ---------------------------------------------------------------------------

def test_chaos_serve_mesh_soak(tmp_path, monkeypatch):
    """The ISSUE 9 acceptance gate, run in-process with the exact
    driver CI uses (``verify/chaos.py --serve-mesh``): a seeded storm
    with a mesh slice killed mid-step — 100% terminal outcomes, >= 1
    reshard down the ladder, zero KV leaks, byte-conservation across
    the migration, accounting agreement."""
    obs.reset()
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    # the driver sandboxes the prefix tier via os.environ (fine as a
    # CLI); monkeypatch registers the var for restoration in-process
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path))
    from tilelang_mesh_tpu.verify.chaos import run_serve_mesh
    rc = run_serve_mesh(tmp_path, seed=13, n_requests=120)
    assert rc == 0
    import json
    report = json.loads((tmp_path / "serve_mesh_report.json").read_text())
    assert all(report["checks"].values())
    assert report["reshards"] >= 1
    assert report["final_layout"] != report["first_layout"]
    assert report["outcomes"]["pending"] == 0
    assert report["kv_pages_migrated"] > 0
