"""Test configuration: force an 8-device virtual CPU mesh.

Per SURVEY §4: golden-IR tests need no device; execution tests run in Pallas
interpret mode on CPU; mesh tests run under shard_map on the 8 virtual
devices. Set TL_TPU_TEST_DEVICE=tpu to run execution tests on real hardware
instead.
"""

import os
import tempfile

# Hermetic caches: a warm kernel/autotune cache from a previous run (or the
# user's home dir) must not change test behavior — see round-1 advisor
# finding on test_picks_fastest_and_caches. Done at import time so the dirs
# are in place before tilelang_mesh_tpu reads env vars.
_CACHE_TMP = tempfile.mkdtemp(prefix="tltpu-test-cache-")
os.environ.setdefault("TL_TPU_CACHE_DIR", os.path.join(_CACHE_TMP, "kernels"))
os.environ.setdefault("TL_TPU_AUTOTUNE_CACHE_DIR",
                      os.path.join(_CACHE_TMP, "autotune"))
# ... and the trace dir: the always-on flight recorder dumps its black
# box under <trace dir>/flight on injected failures, which must land in
# the test sandbox, never the user's home
os.environ.setdefault("TL_TPU_TRACE_DIR", os.path.join(_CACHE_TMP, "trace"))

import pytest

_ON_TPU = os.environ.get("TL_TPU_TEST_DEVICE", "cpu") == "tpu"

if not _ON_TPU:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    # Drop any PJRT plugin a sitecustomize may have registered (e.g. a
    # tunneled TPU): CPU tests must never touch real hardware.
    try:
        import jax._src.xla_bridge as _xb
        for _name in list(_xb._backend_factories):
            if _name not in ("cpu", "tpu", "cuda", "rocm", "gpu", "metal"):
                _xb._backend_factories.pop(_name, None)
    except Exception:
        pass


@pytest.fixture(autouse=True)
def _reset_shared_resilience_state():
    """Suite order must not matter: the circuit breaker, the backend
    registry's cached health verdicts, and any leaked fault-injection
    overrides are process-global singletons that one test file could
    otherwise leak into the next (the test_comm_opt -> test_verify
    watchdog interaction noted in CHANGES.md PR 7). Reset them at test
    START so every test sees virgin guard/registry state; per-module
    fixtures that also reset (e.g. test_verify's _hermetic) stay
    correct, just redundant. Kernel/factory caches are deliberately NOT
    cleared here — that would recompile every kernel per test."""
    from tilelang_mesh_tpu.resilience.retry import global_breaker
    global_breaker().reset()
    from tilelang_mesh_tpu.resilience import faults as _faults
    _faults._overrides.clear()
    try:
        from tilelang_mesh_tpu.codegen import backends as _backends
        if _backends._REGISTRY is not None:
            _backends._REGISTRY.reset()
    except Exception:
        pass
    yield
