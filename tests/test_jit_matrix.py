"""JIT-layer matrix coverage (the analog of the reference's per-backend
testing/python/jit/test_tilelang_jit_gemm.py grid): ONE canonical GEMM
driven through every calling convention x dtype x pipeline depth the
jit layer supports, each against the same numpy truth.

The reference's matrix axis is execution backend (cuda/hip/cpu); on TPU
the axes that can actually diverge are the call convention (reference
copy-back vs jax-native vs jax.jit-wrapped), the element dtype, and the
staging depth — each exercises a different slice of kernel.py/lower.py.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

M = N = K = 128


def _make(dtype, num_stages):
    @T.prim_func
    def gemm(A: T.Tensor((M, K), dtype),
             B: T.Tensor((K, N), dtype),
             C: T.Tensor((M, N), dtype)):
        with T.Kernel(T.ceildiv(N, 128), T.ceildiv(M, 128)) as (bx, by):
            A_s = T.alloc_shared((128, 64), dtype)
            B_s = T.alloc_shared((64, 128), dtype)
            C_l = T.alloc_fragment((128, 128), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(K // 64, num_stages=num_stages):
                T.copy(A[by * 128, ko * 64], A_s)
                T.copy(B[ko * 64, bx * 128], B_s)
                T.gemm(A_s, B_s, C_l)
            T.copy(C_l, C[by * 128, bx * 128])
    return tilelang.compile(gemm)


def _data(dtype):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    if dtype == "bfloat16":
        import jax.numpy as jnp
        return jnp.asarray(a, jnp.bfloat16), jnp.asarray(b, jnp.bfloat16)
    return a, b


def _tol(dtype):
    return dict(rtol=2e-2, atol=5e-1) if dtype == "bfloat16" \
        else dict(rtol=1e-2, atol=1e-1)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("num_stages", [1, 2, 3])
@pytest.mark.parametrize("convention", ["copyback", "jax", "jitted"])
def test_gemm_matrix(dtype, num_stages, convention):
    kern = _make(dtype, num_stages)
    a, b = _data(dtype)
    want = np.asarray(a, np.float32) @ np.asarray(b, np.float32)

    if convention == "copyback":
        if dtype == "bfloat16":
            pytest.skip("numpy has no bf16 output buffer")
        c = np.empty((M, N), np.float32)
        kern(a, b, c)
    elif convention == "jax":
        c = np.asarray(kern(a, b), np.float32)
    else:
        import jax
        c = np.asarray(jax.jit(lambda a, b: kern(a, b))(a, b), np.float32)
    np.testing.assert_allclose(c, want, **_tol(dtype))


def test_same_source_across_stage_depths():
    """Pipeline depth changes scheduling, never semantics: all depths
    produce identical plans modulo num_stages and identical outputs."""
    a, b = _data("float32")
    outs = [np.asarray(_make("float32", ns)(a, b)) for ns in (1, 2, 3)]
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], rtol=1e-6, atol=1e-6)


def test_out_idx_inference():
    """Output-parameter inference (out_idx) matches the reference's
    jit(out_idx=...) behavior: the C tensor is synthesized."""
    kern = _make("float32", 2)
    a, b = _data("float32")
    # jax-native call omits C entirely — the jit layer infers it
    c = kern(a, b)
    assert tuple(c.shape) == (M, N)


def test_wrong_arity_and_shape_rejected():
    kern = _make("float32", 2)
    a, b = _data("float32")
    with pytest.raises((ValueError, TypeError)):
        kern(a)                                   # missing operand
    with pytest.raises((ValueError, TypeError)):
        kern(a[:64], b)                           # wrong shape
