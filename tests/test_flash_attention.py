"""FlashAttention kernel numerics (BASELINE config #2; reference
examples/flash_attention test behavior)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_attention import (flash_attention,
                                                   _reference_attention)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def _rand_qkv(B, H, S, D, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("D", [64, 128])
def test_mha_fwd(causal, D):
    B, H, S = 1, 2, 256
    q, k, v = _rand_qkv(B, H, S, D)
    out = flash_attention(q, k, v, causal=causal)
    ref = _reference_attention(q, k, v, causal, 1.0 / np.sqrt(D))
    assert_allclose(np.asarray(out, np.float32),
                    np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2)


def test_mha_fwd_rect_kv():
    """Sq != Sk (decode-with-context shape)."""
    B, H, Sq, Sk, D = 1, 2, 128, 512, 64
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, Sq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, Sk, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, Sk, D)), jnp.float32)
    out = flash_attention(q, k, v)
    ref = _reference_attention(q, k, v, False, 1.0 / np.sqrt(D))
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_mha_bf16():
    B, H, S, D = 1, 2, 256, 64
    q, k, v = _rand_qkv(B, H, S, D, jnp.bfloat16, seed=2)
    out = flash_attention(q, k, v, causal=True)
    ref = _reference_attention(q, k, v, True, 1.0 / np.sqrt(D))
    assert_allclose(np.asarray(out, np.float32),
                    np.asarray(ref, np.float32), rtol=5e-2, atol=5e-2)


def test_mha_grad_flows():
    """custom_vjp backward (rematerialized reference) matches direct AD."""
    B, H, S, D = 1, 1, 128, 64
    q, k, v = _rand_qkv(B, H, S, D, seed=3)

    def loss_fa(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    def loss_ref(q, k, v):
        return _reference_attention(q, k, v, True,
                                    1.0 / np.sqrt(D)).astype(
                                        jnp.float32).sum()

    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-2, atol=5e-2)
