"""Serving-engine suite (serving/; docs/serving.md).

Four layers, mirroring the subsystem:

1. **Primitives** — request lifecycle state machine, the paged-KV slab
   allocator (atomic alloc, double-free rejection, leak check), shape
   bucketing, and the sharding-rule hooks.
2. **Admission control** — every named shed reason is reachable and
   wired to the real machinery (queue bound, circuit breaker, KV
   capacity, deadline feasibility, p99 budget, drain mode).
3. **Failure handling** — injected ``serve.admit`` / ``serve.step`` /
   ``serve.kv`` faults, the retry budget, deterministic failures
   feeding the breaker, device loss mid-batch (quarantine + failover +
   re-admission), and deadline expiry.
4. **The contract** — a seeded 500-request chaos soak (device loss
   mid-batch, ``serve.*`` faults armed, deadline mix) asserting every
   request reaches a terminal outcome, KV slabs balance to zero, and
   the shed/deadline accounting matches the histograms — the same
   driver ``verify/chaos.py --serve`` gates CI with.

Everything is deterministic (seeded faults, seeded request content);
the only wall-clock dependence is deliberate (deadline expiry sleeps).
"""

import time

import numpy as np
import pytest

from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.resilience import inject
from tilelang_mesh_tpu.resilience.retry import global_breaker
from tilelang_mesh_tpu.serving import (AdmissionController,
                                       FlashDecodeWorkload,
                                       KVCacheExhausted,
                                       MLADecodeWorkload,
                                       PagedKVAllocator, Request,
                                       SERVE_BREAKER_SIG, ServeShardConfig,
                                       ServingEngine, match_partition_rules,
                                       serving_state)

H, D, PS = 2, 64, 8


def make_engine(n_pages=64, batch_buckets=(4,), page_buckets=(2, 4),
                **kw):
    alloc = PagedKVAllocator(n_pages=n_pages, page_size=PS, heads=H,
                             head_dim=D)
    wl = FlashDecodeWorkload(alloc, batch_buckets=batch_buckets,
                             page_buckets=page_buckets)
    return ServingEngine(wl, **kw), alloc


# ---------------------------------------------------------------------------
# request lifecycle
# ---------------------------------------------------------------------------

def test_request_lifecycle_states():
    r = Request(context_tokens=16, new_tokens=2, deadline_ms=1000)
    assert r.state == "queued" and not r.is_terminal
    r.admit()
    assert r.state == "admitted"
    r.batch()
    assert r.state == "batched" and r.first_batch_t is not None
    r.requeue()
    assert r.state == "admitted"
    r.finish("result")
    assert r.is_terminal and r.state == "terminal"
    assert [s for s, _ in r.timeline] == [
        "queued", "admitted", "batched", "admitted", "terminal"]


def test_request_double_retirement_raises():
    r = Request(context_tokens=16)
    r.finish("shed", shed_reason="queue_full")
    with pytest.raises(RuntimeError):
        r.finish("result")


def test_request_unknown_outcome_rejected():
    r = Request(context_tokens=16)
    with pytest.raises(ValueError):
        r.finish("evaporated")


def test_request_deadline_arithmetic():
    r = Request(context_tokens=16, deadline_ms=10_000)
    assert 9.0 < r.remaining_s() <= 10.0
    assert not r.expired()
    assert Request(context_tokens=16).remaining_s() is None
    expired = Request(context_tokens=16, deadline_ms=0.0)
    time.sleep(0.002)
    assert expired.expired()
    assert not expired.expired(grace_s=60.0)


# ---------------------------------------------------------------------------
# paged KV allocator
# ---------------------------------------------------------------------------

def test_allocator_alloc_free_balance():
    a = PagedKVAllocator(n_pages=8, page_size=PS, heads=H, head_dim=D)
    pages = a.alloc(3, owner=1)
    assert len(pages) == 3 and a.in_use == 3 and a.free_pages == 5
    assert a.holdings(1) == pages
    assert a.free(1) == 3
    assert a.in_use == 0 and a.alloc_count == a.free_count == 3
    assert a.leak_check() == {}


def test_allocator_exhaustion_is_atomic():
    a = PagedKVAllocator(n_pages=4, page_size=PS, heads=H, head_dim=D)
    a.alloc(3, owner=1)
    with pytest.raises(KVCacheExhausted):
        a.alloc(2, owner=2)
    # the failed alloc must not have consumed the last free page
    assert a.free_pages == 1 and a.holdings(2) == []


def test_allocator_double_and_foreign_free_rejected():
    a = PagedKVAllocator(n_pages=4, page_size=PS, heads=H, head_dim=D)
    p = a.alloc(2, owner=1)
    a.free(1, [p[0]])
    with pytest.raises(ValueError):
        a.free(1, [p[0]])          # double free
    with pytest.raises(ValueError):
        a.free(2, [p[1]])          # foreign free
    assert a.leak_check() == {1: [p[1]]}
    a.free(1)


def test_allocator_hmajor_layout_and_write():
    a = PagedKVAllocator(n_pages=4, page_size=PS, heads=H, head_dim=D)
    page = a.alloc(1, owner=1)[0]
    k = np.full((H, D), 2.0, np.float32)
    v = np.full((H, D), 3.0, np.float32)
    a.write_token(page, 5, k, v)
    row = a.row0(page) + 5
    assert float(a.kp[1, row, 0]) == 2.0
    assert float(a.vp[0, row, -1]) == 3.0
    with pytest.raises(IndexError):
        a.write_token(page, PS, k, v)


def test_allocator_kv_fault_site():
    a = PagedKVAllocator(n_pages=4, page_size=PS, heads=H, head_dim=D)
    with inject("serve.kv", kind="transient"):
        with pytest.raises(Exception):
            a.alloc(1, owner=1)
    assert a.in_use == 0


# ---------------------------------------------------------------------------
# bucketing
# ---------------------------------------------------------------------------

def test_batch_bucket_rounding():
    eng, _ = make_engine(batch_buckets=(1, 2, 4, 8))
    wl = eng.workload
    assert wl.batch_bucket(1) == 1
    assert wl.batch_bucket(3) == 4
    assert wl.batch_bucket(9) == 8      # clamped to the top bucket


def test_window_pages_clamps_to_buckets():
    eng, _ = make_engine(page_buckets=(2, 4))
    wl = eng.workload
    r = Request(context_tokens=2 * PS)       # exactly 2 pages
    assert wl.window_pages(r) == 2
    r3 = Request(context_tokens=3 * PS)      # 3 full pages -> bucket 2
    assert wl.window_pages(r3) == 2
    r5 = Request(context_tokens=5 * PS)      # above top -> suffix of 4
    assert wl.window_pages(r5) == 4


def test_pages_needed_is_worst_case():
    eng, _ = make_engine()
    assert eng.workload.pages_needed(16, 1) == 3     # 17 tokens / 8
    assert eng.workload.pages_needed(16, 8) == 3
    assert eng.workload.pages_needed(16, 9) == 4


def test_ingest_rejects_sub_bucket_context():
    eng, _ = make_engine(page_buckets=(2,))
    with pytest.raises(ValueError):
        eng.submit(context_tokens=PS)        # one page < smallest bucket
    # a rejected (caller-bug) request is never accepted: it must not
    # linger non-terminal in eng.requests, or the all-terminal audit
    # would report a phantom pending request forever
    assert eng.requests == []
    assert eng.outcomes()["pending"] == 0


# ---------------------------------------------------------------------------
# happy path + warm-up
# ---------------------------------------------------------------------------

def test_end_to_end_all_results_and_zero_leaks():
    eng, alloc = make_engine()
    eng.warmup()
    reqs = [eng.submit(context_tokens=16 + 16 * (i % 2), new_tokens=2,
                       seed=i) for i in range(10)]
    eng.run()
    assert all(r.outcome == "result" for r in reqs)
    assert all(np.asarray(r.result).shape == (H, 1, D) for r in reqs)
    assert alloc.in_use == 0 and alloc.leak_check() == {}
    assert alloc.alloc_count == alloc.free_count > 0
    s = eng.stats()
    assert s["outcomes"]["result"] == 10 and s["queue_depth"] == 0


def test_results_match_direct_pool_decode():
    eng, alloc = make_engine(batch_buckets=(1, 4), page_buckets=(2,))
    eng.warmup()
    r = eng.submit(context_tokens=16, new_tokens=1, seed=5)
    pages = list(r.pages)
    kp, vp = alloc.kp.copy(), alloc.vp.copy()
    eng.run()
    assert r.outcome == "result"
    from tilelang_mesh_tpu.ops.flash_decoding import flash_decode_paged_pool
    rng = np.random.default_rng((5, 1, 0))
    q = rng.standard_normal((H, 1, D)).astype(np.float32)[None]
    table = np.asarray([pages[:2]], np.int32)
    ref = np.asarray(flash_decode_paged_pool(
        q, kp, vp, table, PS, sm_scale=eng.workload.sm_scale))
    np.testing.assert_allclose(np.asarray(r.result)[None], ref,
                               rtol=1e-5, atol=1e-5)


def test_continuous_batching_mixes_old_and_new_requests():
    eng, _ = make_engine(batch_buckets=(4,), page_buckets=(2,))
    eng.warmup()
    long = eng.submit(context_tokens=16, new_tokens=3, seed=1)
    eng.step()                                # long did step 1
    short = eng.submit(context_tokens=16, new_tokens=1, seed=2)
    assert eng.step()                         # one batch served BOTH
    assert short.outcome == "result"
    assert long.steps_done == 2 and not long.is_terminal
    eng.run()
    assert long.outcome == "result"


def test_warmup_aot_compiles_each_bucket_once():
    eng, _ = make_engine(batch_buckets=(4,), page_buckets=(2, 4))
    assert eng.warmup() == 2                  # (4,2) and (4,4)
    assert eng.warmup() == 0                  # idempotent
    # warm-up also seeds the step-latency estimate admission reads
    from tilelang_mesh_tpu.serving.admission import observed_step_ms
    assert observed_step_ms(0.5) > 0


def test_page_growth_allocates_midflight():
    # context 23 tokens = 2 full pages + 7 tail; the second generated
    # token fills the tail page and the THIRD allocates a fresh one
    eng, alloc = make_engine(batch_buckets=(1,), page_buckets=(2,))
    eng.warmup()
    r = eng.submit(context_tokens=23, new_tokens=3, seed=3)
    pages_at_admit = len(r.pages)
    eng.run()
    assert r.outcome == "result"
    assert alloc.in_use == 0
    assert alloc.alloc_count == pages_at_admit + 1


# ---------------------------------------------------------------------------
# admission control / shedding
# ---------------------------------------------------------------------------

def test_shed_queue_full():
    eng, _ = make_engine(n_pages=512,
                         admission=AdmissionController(max_queue=2))
    eng.warmup()
    outcomes = [eng.submit(context_tokens=16, seed=i).outcome
                for i in range(4)]
    assert outcomes[:2] == [None, None]
    assert all(o == "shed" for o in outcomes[2:])
    assert [r.shed_reason for r in eng.requests[2:]] == ["queue_full"] * 2
    eng.run()


def test_shed_kv_exhausted_at_admission():
    eng, _ = make_engine(n_pages=4)
    r1 = eng.submit(context_tokens=16, new_tokens=1)   # needs 3 pages
    r2 = eng.submit(context_tokens=16, new_tokens=1)   # only 1 left
    assert r1.outcome is None and r2.outcome == "shed"
    assert r2.shed_reason == "kv_exhausted"
    eng.run()
    assert r1.outcome == "result"


def test_shed_deadline_infeasible():
    eng, _ = make_engine()
    eng.warmup()
    r = eng.submit(context_tokens=16, deadline_ms=0.0)
    assert r.outcome == "shed" and r.shed_reason == "deadline_infeasible"


def test_shed_breaker_open():
    eng, _ = make_engine()
    b = global_breaker()
    for _ in range(b.threshold):
        b.record_failure(SERVE_BREAKER_SIG)
    r = eng.submit(context_tokens=16)
    assert r.outcome == "shed" and r.shed_reason == "breaker_open"
    b.reset()


def test_shed_overload_on_p99_budget():
    eng, _ = make_engine(
        admission=AdmissionController(p99_budget_ms=0.001))
    eng.warmup()     # the measured warm step exceeds 1us by construction
    r = eng.submit(context_tokens=16)
    assert r.outcome == "shed" and r.shed_reason == "overload"


def test_drain_finishes_inflight_and_sheds_new():
    eng, alloc = make_engine()
    eng.warmup()
    inflight = eng.submit(context_tokens=16, new_tokens=2, seed=1)
    eng.drain()
    late = eng.submit(context_tokens=16, seed=2)
    assert late.outcome == "shed" and late.shed_reason == "draining"
    eng.run()
    assert inflight.outcome == "result"
    assert alloc.in_use == 0


def test_admit_fault_sheds_terminally():
    eng, _ = make_engine()
    with inject("serve.admit", kind="transient"):
        r = eng.submit(context_tokens=16)
    assert r.outcome == "shed" and r.shed_reason == "admit_fault"
    assert r.error and "InjectedFault" in r.error


def test_ingest_kv_fault_sheds_terminally():
    eng, alloc = make_engine()
    with inject("serve.kv", kind="oserror"):
        r = eng.submit(context_tokens=16)
    assert r.outcome == "shed" and r.shed_reason == "kv_exhausted"
    assert alloc.in_use == 0


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

def test_deadline_expires_in_queue():
    eng, alloc = make_engine(grace_ms=10.0)
    eng.warmup()
    r = eng.submit(context_tokens=16, deadline_ms=30.0)
    assert r.outcome is None                  # feasible at admission
    time.sleep(0.08)                          # deadline + grace pass
    eng.run()
    assert r.outcome == "deadline_exceeded"
    assert alloc.in_use == 0                  # slabs released on expiry


def test_step_budget_propagates_tightest_deadline():
    eng, _ = make_engine(grace_ms=50.0, step_timeout_ms=0.0)
    eng.warmup()
    a = eng.submit(context_tokens=16, deadline_ms=10_000)
    b = eng.submit(context_tokens=16, deadline_ms=700)
    batch = [a, b]
    budget = eng._step_budget_s(batch)
    # tightest remaining deadline (~0.7s) + grace (0.05s)
    assert 0.5 < budget < 0.76
    eng2, _ = make_engine(step_timeout_ms=200.0)
    r = Request(context_tokens=16)
    assert eng2._step_budget_s([r]) == pytest.approx(0.2)
    assert eng._step_budget_s([Request(context_tokens=16)]) is None
    eng.run()


def test_step_timeout_retries_then_sheds_on_budget():
    # a step that always blows its budget: the deadline'd request is
    # retried within its budget, then shed with reason=retry_budget
    eng, alloc = make_engine(step_timeout_ms=30.0, retry_max=1)
    eng.warmup()
    r = eng.submit(context_tokens=16, deadline_ms=60_000, seed=1)
    orig = eng.workload.run_batch

    def slow(batch):
        time.sleep(0.12)
        return orig(batch)

    eng.workload.run_batch = slow
    eng.run()
    assert r.outcome == "shed" and r.shed_reason == "retry_budget"
    assert r.retries == 1
    assert alloc.in_use == 0


# ---------------------------------------------------------------------------
# step failures: retries, breaker, device loss
# ---------------------------------------------------------------------------

def test_transient_step_fault_retries_to_completion():
    eng, alloc = make_engine()
    eng.warmup()
    reqs = [eng.submit(context_tokens=16, seed=i) for i in range(4)]
    with inject("serve.step", kind="transient", times=1):
        eng.run()
    assert all(r.outcome == "result" for r in reqs)
    assert all(r.retries == 1 for r in reqs)
    assert alloc.in_use == 0


def test_retry_budget_exhaustion_fails_undeadlined():
    eng, alloc = make_engine(retry_max=2)
    eng.warmup()
    r = eng.submit(context_tokens=16, seed=1)
    with inject("serve.step", kind="transient"):      # every step fails
        eng.run()
    assert r.outcome == "failed" and r.retries == 2
    assert "retry budget exhausted" in r.error
    assert alloc.in_use == 0


def test_deterministic_step_fault_fails_batch_and_feeds_breaker():
    eng, alloc = make_engine()
    eng.warmup()
    b = global_breaker()
    for i in range(b.threshold):
        r = eng.submit(context_tokens=16, seed=i)
        with inject("serve.step", kind="deterministic"):
            eng.run()
        assert r.outcome == "failed" and r.retries == 0
    # the rolled-up serve.step signature opened the circuit: admission
    # now sheds at the door
    shed = eng.submit(context_tokens=16)
    assert shed.outcome == "shed" and shed.shed_reason == "breaker_open"
    assert alloc.in_use == 0
    b.reset()


def test_device_loss_midbatch_quarantines_and_readmits():
    eng, alloc = make_engine()
    eng.warmup()
    reqs = [eng.submit(context_tokens=16, seed=i) for i in range(4)]
    before = obs.metrics_summary()["serving"]
    with inject("device.dispatch", kind="unreachable", times=1):
        eng.run()
    after = obs.metrics_summary()["serving"]
    assert all(r.outcome == "result" for r in reqs)
    assert after["failovers"] == before["failovers"] + 1
    assert eng.stats()["failovers"] == 1
    assert alloc.in_use == 0


def test_device_loss_on_expired_request_is_deadline_exceeded():
    eng, alloc = make_engine(grace_ms=0.0)
    eng.warmup()
    r = eng.submit(context_tokens=16, deadline_ms=40.0, seed=1)
    orig = eng.workload.run_batch

    def die_slowly(batch):
        time.sleep(0.08)                      # past the deadline...
        raise RuntimeError("worker unreachable")   # ...then device loss

    eng.workload.run_batch = die_slowly
    eng.step()
    assert r.outcome == "deadline_exceeded"
    assert alloc.in_use == 0
    eng.workload.run_batch = orig


def test_quarantine_blames_serving_tier_not_first_dead_tier(monkeypatch):
    # two successive device losses: the second must mark the tier that
    # is ACTUALLY serving (the first used chain entry not already dead),
    # not re-blame the long-dead chain head and leave the dying tier
    # cached healthy for its TTL
    from tilelang_mesh_tpu.codegen.backends import registry
    monkeypatch.setenv("TL_TPU_BACKENDS",
                       "tpu-pallas,host-xla,host-interpret")
    eng, _ = make_engine()
    reg = registry()
    reg.mark_unhealthy("tpu-pallas", RuntimeError("worker unreachable"))
    monkeypatch.setattr(eng, "_backends_used",
                        lambda: {"tpu-pallas", "host-xla"})
    eng._quarantine_and_failover(RuntimeError("socket closed"))
    assert reg.health("host-xla").healthy is False
    assert reg.health("host-interpret").healthy is not False
    global_breaker().reset()


def test_midflight_kv_fault_sheds_growing_request():
    eng, alloc = make_engine(batch_buckets=(1,), page_buckets=(2,))
    eng.warmup()
    # 2 full pages + full tail: the first generated token needs a page
    r = eng.submit(context_tokens=2 * PS, new_tokens=2, seed=1)
    with inject("serve.kv", kind="transient"):
        eng.run()
    assert r.outcome == "shed" and r.shed_reason == "kv_exhausted"
    assert alloc.in_use == 0


# ---------------------------------------------------------------------------
# MLA workload
# ---------------------------------------------------------------------------

def test_mla_workload_end_to_end_matches_reference():
    dc, dr = 32, 16
    alloc = PagedKVAllocator(n_pages=16, page_size=PS, heads=1,
                             head_dim=dc + dr)
    wl = MLADecodeWorkload(alloc, heads=2, latent_dim=dc, rope_dim=dr,
                           batch_buckets=(1,), page_buckets=(2,))
    eng = ServingEngine(wl, name="mla")
    eng.warmup()
    r = eng.submit(context_tokens=16, new_tokens=1, seed=9)
    rows = alloc.kp[0].copy()
    pages = list(r.pages)
    eng.run()
    assert r.outcome == "result"
    assert np.asarray(r.result).shape == (2, dc)
    assert alloc.in_use == 0
    # reference: gather the pages and run the latent-attention math
    from tilelang_mesh_tpu.ops.mla import mla_decode_reference
    idx = (np.asarray(pages[:2])[:, None] * PS
           + np.arange(PS)[None, :]).reshape(-1)
    seq = rows[idx][None]                       # (1, S, dc+dr)
    rng = np.random.default_rng((9, 1, 0))
    q = rng.standard_normal((2, dc + dr)).astype(np.float32)[None]
    ref = np.asarray(mla_decode_reference(
        q[:, :, :dc].copy(), q[:, :, dc:].copy(),
        seq[:, :, :dc].copy(), seq[:, :, dc:].copy(),
        sm_scale=wl.sm_scale))
    np.testing.assert_allclose(np.asarray(r.result)[None], ref,
                               rtol=2e-2, atol=2e-2)


def test_mla_requires_latent_major_allocator():
    alloc = PagedKVAllocator(n_pages=8, page_size=PS, heads=2, head_dim=D)
    with pytest.raises(ValueError):
        MLADecodeWorkload(alloc, heads=2, latent_dim=32, rope_dim=16)


# ---------------------------------------------------------------------------
# sharding hooks
# ---------------------------------------------------------------------------

def test_match_partition_rules_first_match_wins():
    from jax.sharding import PartitionSpec as P
    rules = [(r"kv/.*", P("x")), (r".*", P())]
    specs = match_partition_rules(rules, ["kv/k_pool", "step/q"])
    assert specs == [P("x"), P()]
    with pytest.raises(ValueError):
        match_partition_rules([(r"kv/.*", P())], ["step/q"])


def test_serve_shard_config_layouts():
    from jax.sharding import PartitionSpec as P
    head = ServeShardConfig.head_parallel("x")
    assert head.kv_pool_hrd == P("x")
    assert head.table_bp == P()
    names = ["kv/k_pool", "step/query", "kv/page_table", "step/out"]
    specs = match_partition_rules(head.rules(), names)
    assert specs == [P("x"), P(None, "x"), P(), P(None, "x")]
    none = ServeShardConfig.no_sharding()
    assert all(s == P() for s in
               match_partition_rules(none.rules(), names))


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------

def test_metrics_summary_serving_section(monkeypatch):
    obs.reset()
    eng, _ = make_engine()
    eng.warmup()
    ok = eng.submit(context_tokens=16, seed=1)
    shed = eng.submit(context_tokens=16, deadline_ms=0.0)
    eng.run()
    s = obs.metrics_summary()["serving"]
    assert s["admitted"] == 1 and s["completed"] == 1
    assert s["shed"]["deadline_infeasible"] == 1 and s["shed_total"] == 1
    assert s["step_latency"]["count"] >= 1
    assert s["gauges"]["queue_depth"] == 0
    assert s["gauges"]["kv_pages_in_use"] == 0
    assert ok.outcome == "result" and shed.outcome == "shed"


def test_analyzer_serve_report(tmp_path, monkeypatch):
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    obs.reset()
    eng, _ = make_engine()
    eng.warmup()
    for i in range(3):
        eng.submit(context_tokens=16, seed=i)
    eng.submit(context_tokens=16, deadline_ms=0.0)
    eng.run()
    p = tmp_path / "serve.jsonl"
    obs.write_jsonl(str(p))
    from tilelang_mesh_tpu.tools.analyzer import (format_serve_report,
                                                  summarize_serve)
    recs = obs.read_jsonl(str(p))
    s = summarize_serve(recs)
    assert s["admitted"] == 3 and s["completed"] == 3
    assert s["shed"] == {"deadline_infeasible": 1}
    assert s["kv"]["balance"] == 0
    text = format_serve_report(recs)
    assert "admitted" in text and "kv pages alloc/free" in text
    assert "serve.step.latency" in text


def test_serving_state_gauges_live():
    eng, _ = make_engine()
    eng.warmup()
    eng.submit(context_tokens=16, seed=1)
    assert serving_state()["queue_depth"] == 1
    eng.run()
    assert serving_state()["queue_depth"] == 0


# ---------------------------------------------------------------------------
# the contract: seeded 500-request chaos soak
# ---------------------------------------------------------------------------

def test_chaos_soak_500_requests_all_terminal(tmp_path, monkeypatch):
    """The ISSUE 8 acceptance gate, run in-process: 500 seeded requests
    with a deadline mix, serve.* faults armed, the device killed once
    mid-batch, and a drain wave — every request must reach a terminal
    outcome, KV slabs must balance to zero, and the shed/deadline
    accounting must match the histograms. Shares the exact driver CI
    runs (``verify/chaos.py --serve``)."""
    obs.reset()
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    # the driver sandboxes the prefix tier via os.environ (fine as a
    # CLI); monkeypatch registers the var for restoration in-process
    monkeypatch.setenv("TL_TPU_SERVE_PREFIX_DIR", str(tmp_path))
    from tilelang_mesh_tpu.verify.chaos import run_serve
    rc = run_serve(tmp_path, seed=7, n_requests=500)
    assert rc == 0
    import json
    report = json.loads((tmp_path / "serve_report.json").read_text())
    assert all(report["checks"].values())
    assert report["outcomes"]["pending"] == 0
    total = sum(v for k, v in report["outcomes"].items()
                if k != "pending")
    assert total == report["requests"] + 12    # + the stall wave
    assert report["kv"]["in_use"] == 0
    assert report["kv"]["alloc_count"] == report["kv"]["free_count"]
    assert (tmp_path / "serve_trace.jsonl").exists()
