"""benchmark/update_results.py: bench JSON lines -> RESULTS.md rows,
incrementally (unmeasured rows keep their old values and dates)."""

import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]


def test_incremental_row_update(tmp_path, monkeypatch):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "update_results", ROOT / "benchmark" / "update_results.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    results = tmp_path / "RESULTS.md"
    results.write_text(
        "# header\n\n"
        f"{mod.BEGIN}\n"
        "| config | metric | value | ours ms | baseline ms | "
        "vs_baseline | measured |\n|---|---|---|---|---|---|---|\n"
        "| gemm_large | old metric | 100.0 TFLOPS | 2.0 | 2.0 | 1.000 "
        "| 2026-01-01 |\n"
        "| flash_d64 | old flash | 30.0 TFLOPS | 0.5 | 2.0 | **4.000** "
        "| 2026-01-01 |\n"
        f"{mod.END}\n\ntrailer\n")
    monkeypatch.setattr(mod, "RESULTS", results)

    jl = tmp_path / "bench.jsonl"
    jl.write_text("\n".join([
        "# noise line",
        json.dumps({"config": "gemm_large", "metric": "new metric",
                    "value": 180.0, "unit": "TFLOPS",
                    "vs_baseline": 1.05, "latency_ms": 1.9,
                    "baseline_ms": 2.0}),
        json.dumps({"config": "paged_decode", "metric": "paged",
                    "value": 700.0, "unit": "GB/s", "vs_baseline": 0.98,
                    "latency_ms": 5.0, "baseline_ms": 4.9,
                    "walk_ms": 5.0, "gather_ms": 5.5}),
        json.dumps({"config": "broken", "error": "skipped"}),
    ]))
    monkeypatch.setattr(sys, "argv",
                        ["update_results.py", str(jl), "--date",
                         "2026-07-31"])
    mod.main()

    out = results.read_text()
    assert "new metric | 180.0 TFLOPS" in out and "2026-07-31" in out
    assert "**1.050**" in out                      # win bolded
    assert "old flash" in out and "2026-01-01" in out   # kept row
    assert "walk=5.0ms gather=5.5ms" in out        # extras surfaced
    assert "broken" not in out                     # error lines dropped
    assert out.startswith("# header") and out.rstrip().endswith("trailer")


def test_roofline_reads_results_table():
    """benchmark/roofline.py derives measured latencies from RESULTS.md
    (single source of truth with update_results.py)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "roofline", ROOT / "benchmark" / "roofline.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    meas = mod._measured_ms()
    assert "gemm_large" in meas and meas["gemm_large"] > 0
    rows = mod.rows()
    byname = {r["name"]: r for r in rows}
    assert abs(byname["gemm_large"]["measured"]
               - meas["gemm_large"]) < 1e-9
