"""2:4 structured-sparse GEMM: T.gemm_sp + utils.sparse
(reference examples/gemm_sp/test_example_gemm_sp.py behavior)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.utils.sparse import (compress, decompress,
                                            randn_semi_sparse)


def test_compress_roundtrip():
    a = randn_semi_sparse(64, 128, seed=3)
    vals, meta = compress(a)
    assert vals.shape == (64, 64) and meta.dtype == np.int8
    assert meta.min() >= 0 and meta.max() <= 3
    np.testing.assert_array_equal(decompress(vals, meta), a)


def test_compress_rejects_dense():
    a = np.ones((4, 8), np.float32)  # 4 nonzeros per group
    with pytest.raises(ValueError, match="not 2:4 sparse"):
        compress(a)


@pytest.mark.parametrize("M,N,K", [(128, 128, 128), (256, 128, 512)])
def test_gemm_sp(M, N, K):
    @T.prim_func
    def kern(A_sparse: T.Tensor((M, K // 2), "float32"),
             E: T.Tensor((M, K // 2), "int8"),
             B: T.Tensor((K, N), "float32"),
             C: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            A_s = T.alloc_shared((M, K // 2), "float32")
            E_s = T.alloc_shared((M, K // 2), "int8")
            B_s = T.alloc_shared((K, N), "float32")
            C_l = T.alloc_fragment((M, N), "float32")
            T.copy(A_sparse, A_s)
            T.copy(E, E_s)
            T.copy(B, B_s)
            T.gemm_sp(A_s, E_s, B_s, C_l, clear_accum=True)
            T.copy(C_l, C)

    k = tilelang.compile(kern)
    a = randn_semi_sparse(M, K, seed=0)
    vals, meta = compress(a)
    b = np.random.default_rng(1).standard_normal((K, N), dtype=np.float32)
    c = np.empty((M, N), np.float32)
    k(vals, meta, b, c)
    np.testing.assert_allclose(c, a @ b, rtol=1e-2, atol=1e-1)


def test_gemm_sp_rejects_sliced_operand():
    M, K, N = 64, 128, 64

    with pytest.raises(Exception, match="whole tiles"):
        @T.prim_func
        def kern(A_sparse: T.Tensor((M, K // 2), "float32"),
                 E: T.Tensor((M, K // 2), "int8"),
                 B: T.Tensor((K, N), "float32"),
                 C: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                A_s = T.alloc_shared((M, K // 2), "float32")
                E_s = T.alloc_shared((M, K // 2), "int8")
                B_s = T.alloc_shared((K, N), "float32")
                C_l = T.alloc_fragment((M, N), "float32")
                T.gemm_sp(A_s[0:32, 0:K // 2], E_s[0:32, 0:K // 2],
                          B_s, C_l)

        tilelang.compile(kern)
