"""tile-opt pass-suite tests (transform/tile_opt.py; docs/tile_opt.md).

Layout:

- mode-knob parsing (TL_TPU_TILE_OPT / tl.tpu.tile_opt);
- per-rewrite golden fire/no-fire pairs: dse (incl. the dead-chain
  fixpoint and TL006 consumption), repack (incl. the overlapping- and
  guarded-lifetime refusals), dbuf (incl. the loop-carried and
  src-clobber refusals), fuse (incl. the shifted-dependency and
  non-injective refusals) — each with numerical equivalence against the
  TL_TPU_TILE_OPT=0 lowering;
- pass-composition determinism: the canonical dse -> repack -> dbuf ->
  fuse pipeline on a kernel that triggers all four, two lowerings
  byte-identical, plus a seeded sweep of generated kernels;
- TL_TPU_TILE_OPT=0 restores the pre-pass plan_desc byte-identically on
  ops-library kernels (and kernels with no rewrite stay byte-stable
  with the pass ON);
- the differential selfcheck (TL_TPU_SELFCHECK=1): a clean optimized
  kernel passes, a deliberately corrupted rewrite raises
  SelfCheckDivergence on the first call (the PR 5 mutation pattern);
- cache-key separation, attrs/counters/metrics_summary surfacing, the
  unified eliminated accounting with comm_opt dce, the analyzer trace
  section, and the lint CLI --fix hint.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.engine.lower import lower
from tilelang_mesh_tpu.transform import tile_opt
from tilelang_mesh_tpu.transform.tile_opt import (DEFAULT_MODES, MODES,
                                                  run_tile_opt,
                                                  tile_opt_modes)

M = N = 128

OFF = {"tl.tpu.tile_opt": "0"}


def _jnp():
    import jax.numpy as jnp
    return jnp


def _rand(shape, seed=0):
    jnp = _jnp()
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), jnp.float32)


def _assert_equivalent(func, *args, pass_configs=None):
    """Numerics of the optimized lowering == the TL_TPU_TILE_OPT=0
    lowering on the same inputs."""
    cfg = dict(pass_configs or {})
    k1 = tilelang.compile(func, target="cpu", pass_configs=cfg or None)
    k0 = tilelang.compile(func, target="cpu",
                          pass_configs={**cfg, **OFF})
    r1, r0 = k1(*args), k0(*args)
    r1 = r1 if isinstance(r1, tuple) else (r1,)
    r0 = r0 if isinstance(r0, tuple) else (r0,)
    for a, b in zip(r1, r0):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    return k1, k0


# ---------------------------------------------------------------------------
# mode knob
# ---------------------------------------------------------------------------


class TestModes:
    def test_default_all(self, monkeypatch):
        monkeypatch.delenv("TL_TPU_TILE_OPT", raising=False)
        assert tile_opt_modes() == DEFAULT_MODES

    def test_off_spellings(self):
        for v in ("0", "off", "false", "none", "no"):
            assert tile_opt_modes({"tl.tpu.tile_opt": v}) == ()

    def test_subset_and_order(self):
        assert tile_opt_modes({"tl.tpu.tile_opt": "fuse,dse"}) == \
            ("dse", "fuse")
        assert tile_opt_modes({"tl.tpu.tile_opt": "repack+dbuf"}) == \
            ("repack", "dbuf")

    def test_env_fallback(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TILE_OPT", "dse")
        assert tile_opt_modes() == ("dse",)
        # pass config wins over env
        assert tile_opt_modes({"tl.tpu.tile_opt": "0"}) == ()

    def test_typo_raises(self):
        with pytest.raises(ValueError, match="TL_TPU_TILE_OPT"):
            tile_opt_modes({"tl.tpu.tile_opt": "dce"})


# ---------------------------------------------------------------------------
# dse
# ---------------------------------------------------------------------------


def _dead_store_kernel():
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            dead = T.alloc_shared((M, N), "float32")
            unused = T.alloc_fragment((8, N), "float32")
            live = T.alloc_shared((M, N), "float32")
            T.copy(A, dead)             # dead store: never read
            T.copy(A, live)
            for i, j in T.Parallel(M, N):
                live[i, j] = live[i, j] * 2.0
            T.copy(live, B)
    return k


class TestDSE:
    def test_golden_block_and_consumed_tl006(self):
        art = lower(_dead_store_kernel(), target="cpu")
        assert "tile_opt[dse,repack,dbuf,fuse]" in art.plan_desc
        assert "dse: removed dead scratch 'shared'" in art.plan_desc
        assert "dse: removed unused alloc 'frag'" in art.plan_desc
        # the auto-fixed TL006 findings are consumed: no lint block
        assert "TL006" not in art.plan_desc
        assert "lint[" not in art.plan_desc
        # the dead buffers are gone from the plan's scratch
        assert "scratch shared:" not in art.plan_desc
        rec = art.attrs["tile_opt"]
        assert rec["dse"] == {"stores": 1, "allocs": 2,
                              "bytes": rec["dse"]["bytes"]}
        assert rec["dse"]["bytes"] > 0
        assert {e["buffer"] for e in rec["eliminated"]} == \
            {"shared", "frag"}
        for e in rec["eliminated"]:
            assert set(e) == {"op", "buffer", "bytes"}

    def test_bypass_restores_pre_pass_text(self):
        f = _dead_store_kernel()
        art0 = lower(f, target="cpu", pass_configs=OFF)
        assert "tile_opt[" not in art0.plan_desc
        assert "tile_opt" not in art0.attrs
        # the lint block (TL006) is back, and the dead scratch planned
        assert "TL006" in art0.plan_desc
        # the unused alloc is back in the planned scratch (the dead
        # copy target itself becomes A's BlockSpec alias when planned)
        assert "scratch frag:" in art0.plan_desc

    def test_numerics_unchanged(self):
        _assert_equivalent(_dead_store_kernel(), _rand((M, N)))

    def test_dead_chain_fixpoint(self):
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                a = T.alloc_shared((M, N), "float32")
                b = T.alloc_shared((M, N), "float32")
                out = T.alloc_shared((M, N), "float32")
                T.copy(A, a)
                T.copy(a, b)            # b never read -> b dead, then a
                T.copy(A, out)
                T.copy(out, B)
        art = lower(k, target="cpu")
        rec = art.attrs["tile_opt"]
        assert {e["buffer"] for e in rec["eliminated"]} == \
            {"shared", "shared_1"}
        assert rec["dse"]["stores"] == 2


# ---------------------------------------------------------------------------
# repack
# ---------------------------------------------------------------------------


def _two_stage_kernel():
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), B: T.Tensor((M, N), "float32"),
          O1: T.Tensor((M, N), "float32"),
          O2: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            t1 = T.alloc_shared((M, N), "float32")
            t2 = T.alloc_shared((M, N), "float32")
            T.copy(A, t1)
            for i, j in T.Parallel(M, N):
                t1[i, j] = t1[i, j] * 2.0
            T.copy(t1, O1)
            T.copy(B, t2)
            for i, j in T.Parallel(M, N):
                t2[i, j] = t2[i, j] + 3.0
            T.copy(t2, O2)
    return k


class TestRepack:
    def test_golden_merge_and_footprint(self):
        art = lower(_two_stage_kernel(), target="cpu")
        assert "repack: 'shared_1' shares the VMEM slot of 'shared'" \
            in art.plan_desc
        rec = art.attrs["tile_opt"]["repack"]
        assert rec["buffers"] == 1
        assert rec["pre_bytes"] == 2 * rec["post_bytes"]
        # the merged buffer is gone from the planned scratch
        assert "scratch shared_1:" not in art.plan_desc
        # the repacked footprint is surfaced on the header line
        assert f"scratch {rec['pre_bytes']}B -> {rec['post_bytes']}B" \
            in art.plan_desc

    def test_numerics_unchanged(self):
        _assert_equivalent(_two_stage_kernel(), _rand((M, N)),
                           _rand((M, N), 1))

    def test_refuses_overlapping_lifetimes(self):
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                t1 = T.alloc_shared((M, N), "float32")
                t2 = T.alloc_shared((M, N), "float32")
                T.copy(A, t1)
                T.copy(A, t2)           # t1 and t2 live simultaneously
                for i, j in T.Parallel(M, N):
                    t1[i, j] = t1[i, j] + t2[i, j]
                T.copy(t1, B)
        art = lower(k, target="cpu")
        assert "repack" not in art.plan_desc
        # no rewrite fired at all -> byte-identical to the bypass
        assert art.plan_desc == lower(k, target="cpu",
                                      pass_configs=OFF).plan_desc

    def test_refuses_guarded_first_write(self):
        """A buffer first written under a branch guard is the
        grid-carried-init idiom — its slot must never be reused."""
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(2) as bx:
                acc = T.alloc_shared((M, N), "float32")
                t = T.alloc_shared((M, N), "float32")
                with T.If(bx == 0):
                    T.copy(A, acc)
                T.copy(acc, B[0, 0])
                T.copy(A, t)
                for i, j in T.Parallel(M, N):
                    t[i, j] = t[i, j] * 2.0
                T.copy(t, B[0, 0])
        art = lower(k, target="cpu")
        assert "repack" not in art.plan_desc


# ---------------------------------------------------------------------------
# dbuf
# ---------------------------------------------------------------------------


def _stream_kernel():
    K, BK = 512, 128

    @T.prim_func
    def k(A: T.Tensor((M, K), "float32"), B: T.Tensor((M, K), "float32")):
        with T.Kernel(1) as bx:
            w = T.alloc_shared((M, BK), "float32")
            for ko in T.serial(K // BK):
                T.copy(A[0, ko * BK], w)
                for i, j in T.Parallel(M, BK):
                    w[i, j] = w[i, j] * 2.0
                T.copy(w, B[0, ko * BK])
    return k


class TestDbuf:
    def test_golden_rotated_slots(self):
        art = lower(_stream_kernel(), target="cpu")
        assert "dbuf: double-buffered 'shared'" in art.plan_desc
        assert art.attrs["tile_opt"]["dbuf"]["chains"] == 1
        # the rewritten kernel carries the slotted buffer + semaphore
        assert "scratch shared_db: (2, 128, 128)" in art.plan_desc
        assert "scratch shared_dbsem: (2,)" in art.plan_desc
        src = art.kernel_source
        assert "rt.dma_start" in src and "rt.dma_wait" in src
        assert "% 2" in src     # the rotated slot index

    def test_numerics_unchanged(self):
        _assert_equivalent(_stream_kernel(), _rand((M, 512)))

    def test_refuses_loop_carried_read(self):
        """A read of the stream buffer BEFORE the copy observes the
        previous iteration's window — re-slotting would hand it data
        from two iterations back."""
        K, BK = 512, 128

        @T.prim_func
        def k(A: T.Tensor((M, K), "float32"),
              B: T.Tensor((M, K), "float32")):
            with T.Kernel(1) as bx:
                w = T.alloc_shared((M, BK), "float32")
                acc = T.alloc_fragment((M, BK), "float32")
                T.clear(acc)
                T.copy(A[0, 0], w)
                for ko in T.serial(K // BK):
                    for i, j in T.Parallel(M, BK):
                        acc[i, j] = acc[i, j] + w[i, j]   # read BEFORE
                    T.copy(A[0, ko * BK], w)              # the refill
                T.copy(acc, B[0, 0])
        art = lower(k, target="cpu")
        assert "dbuf" not in art.plan_desc

    def test_refuses_gather_source_with_updated_index(self):
        """Review regression: a gather-style source `A[idx[0], 0]`
        whose index scratch is updated inside the loop must NOT be
        double-buffered — the prefetch for ko+1 would be addressed
        through ko's stale index value."""
        K, BK = 512, 128

        @T.prim_func
        def k(A: T.Tensor((K, BK), "float32"),
              B: T.Tensor((K // BK, BK), "float32")):
            with T.Kernel(1) as bx:
                idx = T.alloc_var("int32")
                w = T.alloc_shared((1, BK), "float32")
                idx[0] = 0
                for ko in T.serial(K // BK):
                    idx[0] = (idx[0] + 3) % (K // BK)
                    T.copy(A[idx[0] * BK, 0], w)
                    for j in T.Parallel(BK):
                        w[0, j] = w[0, j] * 2.0
                    T.copy(w, B[ko, 0])
        art = lower(k, target="cpu")
        assert "dbuf" not in art.plan_desc
        _assert_equivalent(k, _rand((K, BK)))

    def test_refuses_src_clobber(self):
        """Nothing in the loop may write the DMA source while the
        prefetch is in flight (TL002's clobber hazard)."""
        K, BK = 512, 128

        @T.prim_func
        def k(A: T.Tensor((M, K), "float32"),
              B: T.Tensor((M, K), "float32")):
            with T.Kernel(1) as bx:
                w = T.alloc_shared((M, BK), "float32")
                for ko in T.serial(K // BK):
                    T.copy(A[0, ko * BK], w)
                    for i, j in T.Parallel(M, BK):
                        w[i, j] = w[i, j] * 2.0
                    T.copy(w, A[0, ko * BK])    # writes the source
                    T.copy(w, B[0, ko * BK])
        art = lower(k, target="cpu")
        assert "dbuf" not in art.plan_desc


# ---------------------------------------------------------------------------
# fuse
# ---------------------------------------------------------------------------


def _fusable_kernel():
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), O1: T.Tensor((M, N), "float32"),
          O2: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            lo = T.alloc_fragment((M, N), "float32")
            hi = T.alloc_fragment((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                lo[i, j] = s[i, j] * 2.0
            for i, j in T.Parallel(M, N):
                hi[i, j] = s[i, j] + 1.0
            T.copy(lo, O1)
            T.copy(hi, O2)
    return k


class TestFuse:
    def test_golden_merge(self):
        art = lower(_fusable_kernel(), target="cpu")
        assert "fuse: merged adjacent T.Parallel(128, 128)" \
            in art.plan_desc
        assert art.attrs["tile_opt"]["fuse"]["regions"] == 1
        # two regions became one main statement
        art0 = lower(_fusable_kernel(), target="cpu", pass_configs=OFF)
        def mains(a):
            import re
            return int(re.search(r"main=(\d+)", a.plan_desc).group(1))
        assert mains(art) == mains(art0) - 1

    def test_numerics_unchanged(self):
        _assert_equivalent(_fusable_kernel(), _rand((M, N)))

    def test_refuses_shifted_dependency(self):
        """loop2 reads what loop1 wrote at ANOTHER iteration (the TL001
        collision class: the broadcast read of row 0) — fusing would
        read a not-yet-written element."""
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                t = T.alloc_fragment((M, N), "float32")
                o = T.alloc_fragment((M, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    t[i, j] = s[i, j] * 2.0
                for i, j in T.Parallel(M, N):
                    o[i, j] = t[0, j] + s[i, j]   # reads iteration (0, j)
                T.copy(o, B)
        art = lower(k, target="cpu")
        assert "fuse" not in art.plan_desc
        _assert_equivalent(k, _rand((M, N)))

    def test_refuses_non_injective_write(self):
        """Defense-in-depth at the oracle level: identical affine forms
        whose write misses an extent>1 var (a broadcast store) alias
        elements across iterations — `_fusable` must refuse even though
        the per-pair form comparison passes."""
        from tilelang_mesh_tpu.ir import ForNest, KernelNode

        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                row = T.alloc_fragment((1, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    row[0, j] = s[i, j]
                for i, j in T.Parallel(M, N):
                    row[0, j] = row[0, j] + s[i, j]
                T.copy(s, B)
        kn = next(st for st in k.func.body.stmts
                  if isinstance(st, KernelNode))
        nests = [st for st in kn.body.stmts
                 if isinstance(st, ForNest) and st.kind == "parallel"]
        assert len(nests) == 2
        assert not tile_opt._fusable(nests[0], nests[1])

    def test_chain_fusion(self):
        """Three adjacent independent regions collapse into one."""
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                a = T.alloc_fragment((M, N), "float32")
                b = T.alloc_fragment((M, N), "float32")
                c = T.alloc_fragment((M, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    a[i, j] = s[i, j] * 2.0
                for i, j in T.Parallel(M, N):
                    b[i, j] = s[i, j] + 1.0
                for i, j in T.Parallel(M, N):
                    c[i, j] = a[i, j] + b[i, j]
                T.copy(c, B)
        art = lower(k, target="cpu")
        assert art.attrs["tile_opt"]["fuse"]["regions"] == 2
        _assert_equivalent(k, _rand((M, N)))


# ---------------------------------------------------------------------------
# composition & determinism
# ---------------------------------------------------------------------------


def _composite_kernel():
    """Triggers all four rewrites: a dead buffer (dse), two disjoint
    same-shape stages (repack), a serial-loop HBM stream (dbuf), and
    adjacent independent parallel regions (fuse). The stream buffer's
    shape is distinct from the stage buffers' so repack cannot claim it
    first (composition is deterministic either way — this kernel wants
    all four to fire)."""
    K, BK = 256, 64

    @T.prim_func
    def k(A: T.Tensor((M, K), "float32"), B: T.Tensor((M, N), "float32"),
          O1: T.Tensor((M, K), "float32"),
          O2: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            dead = T.alloc_shared((8, N), "float32")
            w = T.alloc_shared((M, BK), "float32")
            t1 = T.alloc_shared((M, N), "float32")
            t2 = T.alloc_shared((M, N), "float32")
            lo = T.alloc_fragment((M, N), "float32")
            hi = T.alloc_fragment((M, N), "float32")
            T.copy(B[0, 0], dead)               # dse
            for ko in T.serial(K // BK):        # dbuf
                T.copy(A[0, ko * BK], w)
                for i, j in T.Parallel(M, BK):
                    w[i, j] = w[i, j] * 2.0
                T.copy(w, O1[0, ko * BK])
            T.copy(B, t1)                       # repack stage 1
            for i, j in T.Parallel(M, N):
                t1[i, j] = t1[i, j] * 2.0
            T.copy(t1, O2)
            T.copy(B, t2)                       # repack stage 2
            for i, j in T.Parallel(M, N):       # fuse pair
                lo[i, j] = t2[i, j] * 3.0
            for i, j in T.Parallel(M, N):
                hi[i, j] = t2[i, j] - 1.0
            for i, j in T.Parallel(M, N):
                t2[i, j] = lo[i, j] + hi[i, j]
            T.copy(t2, O2)
    return k


class TestComposition:
    def test_all_four_fire_deterministically(self):
        f = _composite_kernel()
        a1 = lower(f, target="cpu")
        a2 = lower(f, target="cpu")
        assert a1.plan_desc == a2.plan_desc
        assert a1.kernel_source == a2.kernel_source
        rec = a1.attrs["tile_opt"]
        assert rec["dse"]["allocs"] >= 1
        assert rec["repack"]["buffers"] >= 1
        assert rec["dbuf"]["chains"] >= 1
        assert rec["fuse"]["regions"] >= 1
        assert rec["modes"] == list(DEFAULT_MODES)

    def test_composite_numerics(self):
        _assert_equivalent(_composite_kernel(), _rand((M, 256)),
                           _rand((M, N), 1))

    def test_bypass_byte_identity(self):
        f = _composite_kernel()
        a0a = lower(f, target="cpu", pass_configs=OFF)
        a0b = lower(f, target="cpu", pass_configs=OFF)
        assert a0a.plan_desc == a0b.plan_desc
        assert "tile_opt" not in a0a.attrs
        assert "tile_opt[" not in a0a.plan_desc

    @pytest.mark.parametrize("seed", [3, 7, 11])
    def test_seeded_generated_kernels_deterministic(self, seed):
        """Seeded sweep: generated kernels with a random mix of dead
        buffers / stages / streams compose deterministically and stay
        numerically equivalent to the bypass lowering."""
        rng = np.random.default_rng(seed)
        n_stage = int(rng.integers(2, 4))
        with_dead = bool(rng.integers(0, 2))
        mul = [float(rng.integers(1, 5)) for _ in range(n_stage)]

        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                if with_dead:
                    dead = T.alloc_shared((M, N), "float32")
                    T.copy(A, dead)
                ts = [T.alloc_shared((M, N), "float32")
                      for _ in range(n_stage)]
                for si, t in enumerate(ts):
                    T.copy(A, t)
                    for i, j in T.Parallel(M, N):
                        t[i, j] = t[i, j] * mul[si]
                    T.copy(t, B)
        a1 = lower(k, target="cpu")
        a2 = lower(k, target="cpu")
        assert a1.plan_desc == a2.plan_desc
        assert a1.kernel_source == a2.kernel_source
        assert "tile_opt[" in a1.plan_desc   # repack (and dse) fire
        _assert_equivalent(k, _rand((M, N), seed))


# ---------------------------------------------------------------------------
# ops-library byte-identity
# ---------------------------------------------------------------------------


class TestOpsLibrary:
    def test_bypass_restores_pre_pass_plan_desc(self, monkeypatch):
        """TL_TPU_TILE_OPT=0 must reproduce the pre-pass plan_desc on
        real ops kernels (no tile_opt block, stable across runs), and a
        kernel with no rewrite must be byte-stable with the pass ON."""
        from tilelang_mesh_tpu.jit import clear_factory_caches
        from tilelang_mesh_tpu.ops.gemm import matmul_kernel
        clear_factory_caches()
        on = matmul_kernel(256, 256, 256, 128, 128, 128).artifact
        monkeypatch.setenv("TL_TPU_TILE_OPT", "0")
        clear_factory_caches()
        off = matmul_kernel(256, 256, 256, 128, 128, 128).artifact
        # plain pipelined GEMM: nothing to rewrite -> byte-identical
        assert on.plan_desc == off.plan_desc
        assert "tile_opt[" not in off.plan_desc

    def test_dequant_gemm_fuse_fires(self, monkeypatch):
        from tilelang_mesh_tpu.jit import clear_factory_caches
        from tilelang_mesh_tpu.ops.dequant_gemm import dequant_gemm_kernel
        clear_factory_caches()
        art = dequant_gemm_kernel(256, 256, 512).artifact
        assert "fuse: merged adjacent T.Parallel(128, 128)" \
            in art.plan_desc
        monkeypatch.setenv("TL_TPU_TILE_OPT", "0")
        clear_factory_caches()
        art0 = dequant_gemm_kernel(256, 256, 512).artifact
        assert "tile_opt[" not in art0.plan_desc
        clear_factory_caches()


# ---------------------------------------------------------------------------
# differential selfcheck (TL_TPU_SELFCHECK=1)
# ---------------------------------------------------------------------------


class TestSelfcheck:
    def test_clean_rewrite_passes(self, monkeypatch):
        from tilelang_mesh_tpu.cache.kernel_cache import clear_cache
        clear_cache()       # a cached kernel was built with the check off
        obs.reset()
        monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
        k = tilelang.compile(_fusable_kernel(), target="cpu")
        r = k(_rand((M, N)))
        assert isinstance(r, tuple) and len(r) == 2
        c = obs.get_tracer().counters()
        assert c.get("verify.selfcheck.runs", 0) >= 1
        assert c.get("verify.selfcheck.ok", 0) >= 1
        assert not c.get("verify.selfcheck.divergence")
        # second call does not re-run the check
        k(_rand((M, N), 1))
        assert obs.get_tracer().counters()[
            "verify.selfcheck.runs"] == c["verify.selfcheck.runs"]

    def test_corrupted_rewrite_caught(self, monkeypatch):
        """PR 5 mutation pattern: corrupt the fuse rewrite so it drops
        a statement — the optimized kernel now computes the wrong
        answer, and the selfcheck must catch it on the first call."""
        from tilelang_mesh_tpu.verify import SelfCheckDivergence
        obs.reset()
        monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
        orig = tile_opt._fuse_pair

        def corrupt(n1, n2):
            merged = orig(n1, n2)
            merged.body.stmts.pop()     # lose the last fused store
            return merged
        monkeypatch.setattr(tile_opt, "_fuse_pair", corrupt)

        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                lo = T.alloc_fragment((M, N), "float32")
                hi = T.alloc_fragment((M, N), "float32")
                o = T.alloc_fragment((M, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    lo[i, j] = s[i, j] * 2.0
                for i, j in T.Parallel(M, N):
                    hi[i, j] = s[i, j] * 3.0
                for i, j in T.Parallel(M, N):
                    o[i, j] = lo[i, j] + hi[i, j]
                T.copy(o, B)
        kern = tilelang.compile(k, target="cpu")
        with pytest.raises(SelfCheckDivergence, match="tile-opt"):
            kern(_rand((M, N)))
        assert obs.get_tracer().counters().get(
            "verify.selfcheck.divergence", 0) >= 1


# ---------------------------------------------------------------------------
# cache key, counters, metrics, analyzer, unified accounting, CLI hint
# ---------------------------------------------------------------------------


class TestSurfacing:
    def test_cache_key_separates_mode_sets(self):
        from tilelang_mesh_tpu.cache.kernel_cache import KernelCache
        k_on = KernelCache.key_for("x", "cpu", None, {})
        k_off = KernelCache.key_for("x", "cpu", None, OFF)
        k_sub = KernelCache.key_for("x", "cpu", None,
                                    {"tl.tpu.tile_opt": "dse"})
        assert len({k_on, k_off, k_sub}) == 3

    def test_ambient_pass_config_respected_by_cache(self):
        """Review regression: cached() keys on the RESOLVED config —
        an ambient pass_config() tile-opt override must not hit the
        default compile's cache entry (and vice versa)."""
        from tilelang_mesh_tpu.transform import pass_config
        f = _fusable_kernel()
        k1 = tilelang.compile(f, target="cpu")
        with pass_config({"tl.tpu.tile_opt": "0"}):
            k0 = tilelang.compile(f, target="cpu")
        assert k0 is not k1
        assert "tile_opt[" in k1.artifact.plan_desc
        assert "tile_opt[" not in k0.artifact.plan_desc

    def test_compile_on_and_off_are_distinct_kernels(self):
        f = _fusable_kernel()
        k1 = tilelang.compile(f, target="cpu")
        k0 = tilelang.compile(f, target="cpu", pass_configs=OFF)
        assert k1 is not k0
        assert k1.artifact.plan_desc != k0.artifact.plan_desc

    def test_counters_and_metrics_summary(self):
        obs.reset()
        lower(_composite_kernel(), target="cpu")
        s = obs.metrics_summary()["tile_opt"]
        assert s["kernels"] >= 1
        assert s["rewrites"] >= 4
        assert set(s["by_mode"]) == {"dse", "repack", "dbuf", "fuse"}
        assert s["dse_bytes"] > 0
        assert s["repack_bytes_saved"] > 0
        assert s["dbuf_chains"] >= 1
        assert s["fuse_regions"] >= 1
        assert s["eliminated_vmem_bytes"] > 0
        assert s["eliminated_wire_bytes"] == 0   # no mesh program ran

    def test_comm_opt_unified_eliminated_record(self):
        """comm_opt's dce now emits the SAME {op, buffer, bytes} record
        shape as tile-opt's dse (the one-table contract)."""
        from tilelang_mesh_tpu.parallel import mesh_config

        with mesh_config(2, 2):
            @T.prim_func
            def k(A: T.MeshTensor((32, 128),
                                  T.MeshShardingPolicy(cross_mesh_dim=0),
                                  (2, 2), "float32"),
                  B: T.MeshTensor((32, 128),
                                  T.MeshShardingPolicy(cross_mesh_dim=0),
                                  (2, 2), "float32")):
                with T.Kernel(1) as bx:
                    x = T.alloc_fragment((8, 128), "float32")
                    dead = T.alloc_fragment((8, 1), "float32")
                    T.copy(A, x)
                    T.comm.all_reduce(x, dead, "sum", "v", dim=1)
                    T.copy(x, B)
        art = tilelang.lower(k, target="cpu-mesh[2x2]")
        elim = art.attrs["comm_opt"]["eliminated"]
        assert len(elim) == 1
        assert set(elim[0]) == {"op", "buffer", "bytes"}
        assert elim[0]["op"] == "CommAllReduce"
        assert elim[0]["buffer"] == "frag_1"
        assert elim[0]["bytes"] > 0
        # ... and TL006 stayed silent on the comm-dce'd buffer
        assert "TL006" not in art.plan_desc

    def test_analyzer_trace_section(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        obs.reset()
        lower(_dead_store_kernel(), target="cpu")
        p = tmp_path / "trace.jsonl"
        obs.write_jsonl(str(p))
        from tilelang_mesh_tpu.tools.analyzer import (_load_trace,
                                                      format_trace_report)
        report = format_trace_report(_load_trace(p))
        assert "tile-IR optimizer (tile_opt)" in report
        assert "eliminated (tile_opt dse + comm_opt dce" in report
        assert "tile_opt" in report

    def test_lint_cli_fix_hint(self, tmp_path):
        mod = tmp_path / "dead_mod.py"
        mod.write_text(
            "import tilelang_mesh_tpu.language as T\n\n"
            "@T.prim_func\n"
            "def k(A: T.Tensor((128, 128), 'float32'),\n"
            "      B: T.Tensor((128, 128), 'float32')):\n"
            "    with T.Kernel(1) as bx:\n"
            "        dead = T.alloc_shared((128, 128), 'float32')\n"
            "        s = T.alloc_shared((128, 128), 'float32')\n"
            "        T.copy(A, dead)\n"
            "        T.copy(A, s)\n"
            "        T.copy(s, B)\n")
        from tilelang_mesh_tpu.tools.lint import (format_report,
                                                  lint_targets)
        report = lint_targets([str(mod)])
        text = format_report(report)
        assert "TL006" in text
        assert "--fix" in text and "TL_TPU_TILE_OPT" in text

    def test_lint_cli_narrow_hint(self, tmp_path):
        """A kernel with a provably-bounded scratch buffer gets the
        TL_TPU_TILE_OPT=narrow --fix hint (the narrow_candidates oracle
        run from the lint CLI), naming kernel and buffer."""
        mod = tmp_path / "narrow_mod.py"
        mod.write_text(
            "import tilelang_mesh_tpu.language as T\n\n"
            "@T.prim_func\n"
            "def k(A: T.Tensor((128, 128), 'float32'),\n"
            "      B: T.Tensor((128, 128), 'float32')):\n"
            "    with T.Kernel(1) as bx:\n"
            "        s = T.alloc_shared((128, 128), 'float32')\n"
            "        u = T.alloc_fragment((128, 128), 'float32')\n"
            "        o = T.alloc_shared((128, 128), 'float32')\n"
            "        T.copy(A, s)\n"
            "        for i, j in T.Parallel(128, 128):\n"
            "            u[i, j] = T.sigmoid(s[i, j])\n"
            "        for i, j in T.Parallel(128, 128):\n"
            "            o[i, j] = u[i, j] * 2.0\n"
            "        T.copy(o, B)\n")
        from tilelang_mesh_tpu.tools.lint import (format_report,
                                                  lint_targets)
        report = lint_targets([str(mod)])
        assert report["summary"]["narrowable"] == 1
        assert report["narrow_hints"] == [
            {"target": str(mod), "kernel": "k", "buffers": ["frag"]}]
        text = format_report(report)
        assert "--fix" in text and "TL_TPU_TILE_OPT=narrow" in text
        assert "k: frag" in text

    def test_run_tile_opt_no_modes_is_identity(self):
        f = _composite_kernel()
        func = f.func
        out, res, findings = run_tile_opt(func, OFF, [])
        assert out is func
        assert res.rewrites == []


# ---------------------------------------------------------------------------
# narrow (value-range-driven dtype narrowing)
# ---------------------------------------------------------------------------

NARROW = {"tl.tpu.tile_opt": "narrow"}


def _bounded_chain_kernel():
    """sigmoid bounds the root in (0, 1): every fragment downstream is
    provably O(1) with zero accumulated error — all three narrow."""
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            u = T.alloc_fragment((M, N), "float32")
            v = T.alloc_fragment((M, N), "float32")
            w = T.alloc_fragment((M, N), "float32")
            o = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                u[i, j] = T.sigmoid(s[i, j])
            for i, j in T.Parallel(M, N):
                v[i, j] = u[i, j] * u[i, j]
            for i, j in T.Parallel(M, N):
                w[i, j] = v[i, j] * 0.5 + u[i, j] * 0.25
            for i, j in T.Parallel(M, N):
                o[i, j] = w[i, j] * 2.0
            T.copy(o, B)
    return k


def _cancellation_kernel():
    """Large-magnitude staging + cancellation: the staged buffer's
    RELATIVE error is tiny (the envelope pre-gate admits it) but the
    downstream subtraction amplifies bf16 rounding into O(64) absolute
    error — the dual-track re-verification must refuse the narrowing."""
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            big = T.alloc_fragment((M, N), "float32")
            o = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                big[i, j] = T.sigmoid(s[i, j]) + 16384.0
            for i, j in T.Parallel(M, N):
                o[i, j] = big[i, j] - 16384.0
            T.copy(o, B)
    return k


def _bounded_input(seed=0):
    jnp = _jnp()
    return jnp.asarray(np.random.default_rng(seed).uniform(
        -1.0, 1.0, (M, N)), jnp.float32)


def _assert_close_bf16(k1, k0, *args):
    r1, r0 = k1(*args), k0(*args)
    r1 = r1 if isinstance(r1, tuple) else (r1,)
    r0 = r0 if isinstance(r0, tuple) else (r0,)
    for a, b in zip(r1, r0):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=1e-2)


class TestNarrow:
    def test_fire_bounded_chain(self):
        art = lower(_bounded_chain_kernel(), target="cpu",
                    pass_configs=NARROW)
        rec = art.attrs["tile_opt"]["narrow"]
        assert rec["buffers"] >= 3
        assert rec["bytes"] >= 3 * M * N * 2
        for p in rec["proofs"]:
            assert p["from"] == "float32" and p["to"] == "bfloat16"
            assert p["interval"][0] >= -1.0 and p["interval"][1] <= 1.5
            assert p["err"] + 2 ** -8 <= 0.0625
            assert p["verify_rounds"] >= 1
        assert "narrow:" in art.plan_desc

    def test_numerics_vs_off(self):
        f = _bounded_chain_kernel()
        k1 = tilelang.compile(f, target="cpu", pass_configs=NARROW)
        k0 = tilelang.compile(f, target="cpu", pass_configs=OFF)
        assert k1.artifact.attrs["tile_opt"]["narrow"]["buffers"] >= 3
        _assert_close_bf16(k1, k0, _bounded_input())

    def test_refuse_unbounded(self):
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                u = T.alloc_fragment((M, N), "float32")
                o = T.alloc_shared((M, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    u[i, j] = s[i, j] * 2.0     # unbounded input: no proof
                for i, j in T.Parallel(M, N):
                    o[i, j] = u[i, j] * 0.5
                T.copy(o, B)
        art = lower(k, target="cpu", pass_configs=NARROW)
        rec = (art.attrs.get("tile_opt") or {}).get("narrow") or {}
        assert not rec.get("buffers")

    def test_refuse_cancellation_via_screen(self):
        """The envelope pre-gate admits the large-magnitude buffer
        (tiny RELATIVE error — the TL008 model carries max(err) through
        subtraction) but the cancellation screen sees that its bf16
        storage rounding is an ABSOLUTE error of ~64 feeding a
        subtraction whose proven result magnitude is ~1, and refuses."""
        art = lower(_cancellation_kernel(), target="cpu",
                    pass_configs=NARROW)
        rec = (art.attrs.get("tile_opt") or {}).get("narrow") or {}
        assert not rec.get("buffers")

    def test_refuse_dma_endpoints(self):
        """Buffers on a global copy leg keep their wire dtype."""
        art = lower(_bounded_chain_kernel(), target="cpu",
                    pass_configs=NARROW)
        narrowed = {p["buffer"]
                    for p in art.attrs["tile_opt"]["narrow"]["proofs"]}
        assert "shared" not in narrowed      # copy src staging
        assert "shared_1" not in narrowed    # copy dst staging

    def test_selfcheck_tolerates_bf16_rounding(self, monkeypatch):
        """A narrowed kernel legitimately differs from the =0 twin by
        bf16 rounding; the selfcheck's tolerance floor (derived from
        the recorded proofs' target dtype) must forgive exactly that."""
        from tilelang_mesh_tpu.cache.kernel_cache import clear_cache
        clear_cache()
        obs.reset()
        monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
        k = tilelang.compile(_bounded_chain_kernel(), target="cpu",
                             pass_configs=NARROW)
        k(_bounded_input())
        c = obs.get_tracer().counters()
        assert c.get("verify.selfcheck.ok", 0) >= 1
        assert not c.get("verify.selfcheck.divergence")


# ---------------------------------------------------------------------------
# compat repack (byte-size-compatible slots)
# ---------------------------------------------------------------------------


class TestCompatRepack:
    def _compat_kernel(self):
        """A dead f32 slot, then a bf16 buffer of the same shape with a
        disjoint lifetime: the compat gate lands the bf16 values in the
        wider slot through an exact-widening cast view."""
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                wide = T.alloc_fragment((M, N), "float32")
                thin = T.alloc_fragment((M, N), "bfloat16")
                o = T.alloc_shared((M, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    wide[i, j] = s[i, j] * 2.0
                for i, j in T.Parallel(M, N):
                    s[i, j] = wide[i, j] + 1.0      # wide dies here
                for i, j in T.Parallel(M, N):
                    thin[i, j] = s[i, j]
                for i, j in T.Parallel(M, N):
                    o[i, j] = thin[i, j] * 0.5
                T.copy(o, B)
        return k

    def test_fire_exact_widening_pair(self):
        f = self._compat_kernel()
        art = lower(f, target="cpu",
                    pass_configs={"tl.tpu.tile_opt": "repack"})
        rec = art.attrs["tile_opt"]["repack"]
        assert rec["compat"] >= 1
        k1 = tilelang.compile(f, target="cpu",
                              pass_configs={"tl.tpu.tile_opt": "repack"})
        k0 = tilelang.compile(f, target="cpu", pass_configs=OFF)
        _assert_close_bf16(k1, k0, _bounded_input())

    def test_fire_composed_with_narrow(self):
        """The ISSUE's composition contract: a buffer the narrow pass
        just thinned becomes newly packable into a wider dead slot."""
        from tilelang_mesh_tpu.ops.softmax import softmax_kernel

        k = softmax_kernel.__wrapped__(256, 128)
        art = lower(k.prim_func if hasattr(k, "prim_func") else k,
                    target="cpu",
                    pass_configs={"tl.tpu.tile_opt": "all"})
        rec = art.attrs["tile_opt"]
        assert rec["narrow"]["buffers"] >= 1
        assert rec["repack"]["compat"] >= 1

    def test_refuse_non_widening_pair(self):
        """i32 -> f32 is not an exact widening (and vice versa): the
        compat gate must refuse even at equal byte size."""
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                ints = T.alloc_fragment((M, N), "int32")
                vals = T.alloc_fragment((M, N), "float32")
                o = T.alloc_shared((M, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    ints[i, j] = 3
                for i, j in T.Parallel(M, N):
                    s[i, j] = s[i, j] + ints[i, j]  # ints dies
                for i, j in T.Parallel(M, N):
                    vals[i, j] = s[i, j] * 0.5
                for i, j in T.Parallel(M, N):
                    o[i, j] = vals[i, j]
                T.copy(o, B)
        art = lower(k, target="cpu",
                    pass_configs={"tl.tpu.tile_opt": "repack"})
        rec = (art.attrs.get("tile_opt") or {}).get("repack") or {}
        assert not rec.get("compat")


# ---------------------------------------------------------------------------
# interleaved fusion
# ---------------------------------------------------------------------------


def _interleaved_kernel(clobber=False):
    """Two reader nests of ``s`` separated by a plain copy.  With
    clobber=False the copy touches unrelated buffers (C -> t): the
    second nest may legally hop over it and fuse with the first.  With
    clobber=True the copy REWRITES s (t -> s): hopping the second nest
    over it would read the stale s, so the disjointness oracle must
    refuse — and adjacent fusion is impossible (the neighbour is a
    CopyStmt, not a nest)."""
    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), B: T.Tensor((M, N), "float32"),
          C: T.Tensor((M, N), "float32"), D: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            t = T.alloc_shared((M, N), "float32")
            w = T.alloc_shared((M, N), "float32")
            u = T.alloc_fragment((M, N), "float32")
            v = T.alloc_fragment((M, N), "float32")
            T.copy(A, s)
            T.copy(C, t)
            for i, j in T.Parallel(M, N):
                u[i, j] = s[i, j] * 2.0
            if clobber:
                T.copy(t, s)                # s := C, conflicts with nest 2
            else:
                T.copy(t, w)                # unrelated to nest 2
            for i, j in T.Parallel(M, N):
                v[i, j] = s[i, j] * 3.0
            T.copy(u, B)
            T.copy(v, D)
    return k


class TestInterleavedFuse:
    def test_fire_across_disjoint_statement(self):
        f = _interleaved_kernel(clobber=False)
        art = lower(f, target="cpu",
                    pass_configs={"tl.tpu.tile_opt": "fuse"})
        rec = art.attrs["tile_opt"]["fuse"]
        assert rec["interleaved"] >= 1
        jnp = _jnp()
        args = (_rand((M, N)), _rand((M, N), 1))
        k1 = tilelang.compile(f, target="cpu",
                              pass_configs={"tl.tpu.tile_opt": "fuse"})
        k0 = tilelang.compile(f, target="cpu", pass_configs=OFF)
        for a, b in zip(k1(*args), k0(*args)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_refuse_when_intervening_writes_source(self):
        art = lower(_interleaved_kernel(clobber=True), target="cpu",
                    pass_configs={"tl.tpu.tile_opt": "fuse"})
        rec = (art.attrs.get("tile_opt") or {}).get("fuse") or {}
        assert not rec.get("interleaved")
        # ...and the clobbered ordering still computes correctly
        f = _interleaved_kernel(clobber=True)
        args = (_rand((M, N)), _rand((M, N), 1))
        k1 = tilelang.compile(f, target="cpu",
                              pass_configs={"tl.tpu.tile_opt": "fuse"})
        k0 = tilelang.compile(f, target="cpu", pass_configs=OFF)
        for a, b in zip(k1(*args), k0(*args)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# cost-model pass scheduler (TL_TPU_TILE_OPT=auto)
# ---------------------------------------------------------------------------

AUTO = {"tl.tpu.tile_opt": "auto"}


class TestAutoScheduler:
    def test_deterministic_double_lowering(self):
        f = _composite_kernel()
        a1 = lower(f, target="cpu", pass_configs=AUTO)
        a2 = lower(f, target="cpu", pass_configs=AUTO)
        assert a1.plan_desc == a2.plan_desc
        assert a1.kernel_source == a2.kernel_source

    def test_never_worse_than_canonical(self):
        for mk in (_composite_kernel, _bounded_chain_kernel,
                   _dead_store_kernel):
            art = lower(mk(), target="cpu", pass_configs=AUTO)
            s = (art.attrs.get("tile_opt") or {}).get("sched")
            if s and s.get("canonical_ms") is not None:
                assert s["predicted_ms"] <= s["canonical_ms"] + 1e-12

    def test_decision_recorded(self):
        art = lower(_bounded_chain_kernel(), target="cpu",
                    pass_configs=AUTO)
        s = art.attrs["tile_opt"]["sched"]
        assert s["chosen"] and "narrow" in s["chosen"]
        assert isinstance(s["candidates"], list) and len(s["candidates"]) >= 2
        assert any(c["modes"] == [] for c in s["candidates"])
        assert s["predicted_ms"] > 0
        assert "auto" in art.plan_desc

    def test_auto_zero_bypass_byte_identical(self):
        f = _composite_kernel()
        a0a = lower(f, target="cpu", pass_configs=OFF)
        a0b = lower(f, target="cpu", pass_configs=OFF)
        assert a0a.plan_desc == a0b.plan_desc
        assert "tile_opt[" not in a0a.plan_desc

    def test_cache_key_auto_distinct(self):
        from tilelang_mesh_tpu.cache.kernel_cache import KernelCache
        k_def = KernelCache.key_for("x", "cpu", None, {})
        k_auto = KernelCache.key_for("x", "cpu", None, AUTO)
        k_off = KernelCache.key_for("x", "cpu", None, OFF)
        assert len({k_def, k_auto, k_off}) == 3


# ---------------------------------------------------------------------------
# seeded mutation sweep: corrupt each proof gate, selfcheck must catch
# ---------------------------------------------------------------------------


class TestMutationSweep:
    def _armed(self, monkeypatch):
        from tilelang_mesh_tpu.cache.kernel_cache import clear_cache
        # disk=True: sibling tests lower these exact kernels unmutated,
        # and a disk-tier hit would silently bypass the corrupted pass
        clear_cache(disk=True)
        obs.reset()
        monkeypatch.setenv("TL_TPU_SELFCHECK", "1")

    def test_narrow_widened_interval_caught(self, monkeypatch):
        """Mutant 1: the interval gate is forced open and the
        re-verification silenced — an int buffer whose values exceed
        the i16 range gets narrowed and wraps; the exact integer
        selfcheck comparison must catch it."""
        from tilelang_mesh_tpu.verify import SelfCheckDivergence
        self._armed(monkeypatch)
        monkeypatch.setattr(tile_opt, "_narrow_fits",
                            lambda env, old, new, thr: True)
        monkeypatch.setattr(tile_opt, "_narrow_verify",
                            lambda *a, **kw: set())

        @T.prim_func
        def k(A: T.Tensor((M, N), "int32"), B: T.Tensor((M, N), "int32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "int32")
                idx = T.alloc_fragment((M, N), "int32")
                o = T.alloc_shared((M, N), "int32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    idx[i, j] = s[i, j] * 0 + 100000    # > i16 range
                for i, j in T.Parallel(M, N):
                    o[i, j] = idx[i, j] + s[i, j] * 0
                T.copy(o, B)
        kern = tilelang.compile(k, target="cpu", pass_configs=NARROW)
        assert kern.artifact.attrs["tile_opt"]["narrow"]["buffers"] >= 1
        jnp = _jnp()
        a = jnp.zeros((M, N), jnp.int32)
        with pytest.raises(SelfCheckDivergence, match="tile-opt"):
            kern(a)

    def test_narrow_dropped_error_term_caught(self, monkeypatch):
        """Mutant 2: the error terms are dropped from the proof gates —
        the envelope gate keeps only its range check, the cancellation
        screen is silenced — so the cancellation kernel narrows; bf16
        rounding of the 16384-magnitude staging buffer amplifies to
        O(64) output error, far beyond the bf16 tolerance band."""
        from tilelang_mesh_tpu.analysis.absint import (dtype_max,
                                                       is_float)
        from tilelang_mesh_tpu.verify import SelfCheckDivergence
        self._armed(monkeypatch)

        def no_err_gate(env, old_dt, new_dt, thr):
            if env is None or not env.sound_bounded():
                return False
            if is_float(old_dt):
                fmax = dtype_max(new_dt)
                return env.finite and env.shi <= fmax \
                    and env.slo >= -fmax     # error term DROPPED
            return True
        monkeypatch.setattr(tile_opt, "_narrow_fits", no_err_gate)
        monkeypatch.setattr(tile_opt, "_cancel_screen",
                            lambda *a, **kw: set())
        monkeypatch.setattr(tile_opt, "_narrow_verify",
                            lambda *a, **kw: set())
        kern = tilelang.compile(_cancellation_kernel(), target="cpu",
                                pass_configs=NARROW)
        assert kern.artifact.attrs["tile_opt"]["narrow"]["buffers"] >= 1
        with pytest.raises(SelfCheckDivergence, match="tile-opt"):
            kern(_bounded_input())

    def test_compat_widening_oracle_caught(self, monkeypatch):
        """Mutant 3: the exact-widening oracle is forced open — a
        fractional f32 buffer lands in a dead i32 slot and truncates;
        the selfcheck must catch the wrong values."""
        from tilelang_mesh_tpu.verify import SelfCheckDivergence
        self._armed(monkeypatch)
        monkeypatch.setattr(tile_opt, "_exact_widens",
                            lambda narrow_dt, wide_dt:
                            narrow_dt != wide_dt)

        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                ints = T.alloc_fragment((M, N), "int32")
                vals = T.alloc_fragment((M, N), "float32")
                o = T.alloc_shared((M, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    ints[i, j] = 3
                for i, j in T.Parallel(M, N):
                    s[i, j] = s[i, j] + ints[i, j]
                for i, j in T.Parallel(M, N):
                    vals[i, j] = s[i, j] * 0.5      # fractional values
                for i, j in T.Parallel(M, N):
                    o[i, j] = vals[i, j]
                T.copy(o, B)
        kern = tilelang.compile(
            k, target="cpu", pass_configs={"tl.tpu.tile_opt": "repack"})
        assert kern.artifact.attrs["tile_opt"]["repack"]["compat"] >= 1
        with pytest.raises(SelfCheckDivergence, match="tile-opt"):
            kern(_bounded_input())

    def test_fuse_overlap_oracle_caught(self, monkeypatch):
        """Mutant 4: the hoist-disjointness oracle is forced open — the
        second reader nest fuses ACROSS the nest that rewrites their
        shared source, reading stale values."""
        from tilelang_mesh_tpu.verify import SelfCheckDivergence
        self._armed(monkeypatch)
        monkeypatch.setattr(tile_opt, "_hoist_disjoint",
                            lambda stmt, nest: True)
        kern = tilelang.compile(
            _interleaved_kernel(clobber=True), target="cpu",
            pass_configs={"tl.tpu.tile_opt": "fuse"})
        assert kern.artifact.attrs["tile_opt"]["fuse"]["interleaved"] >= 1
        with pytest.raises(SelfCheckDivergence, match="tile-opt"):
            kern(_rand((M, N)), _rand((M, N), 1))
