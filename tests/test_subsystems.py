"""Auxiliary-subsystem coverage: kernel cache, autotuner, profiler,
carver, par_compile, env flags (reference testing/python/{cache,autotune,
profiler,carver,env} dirs, SURVEY §4/§5)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.cache.kernel_cache import KernelCache


def _scale_func(mult=2.0, M=64, N=128):
    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * mult
            T.copy(s, B)
    return scale


class TestKernelCache:
    def test_key_depends_on_ir_target_and_configs(self):
        f = _scale_func()
        script = f.func.script()
        k1 = KernelCache.key_for(script, "cpu", None, {})
        assert k1 == KernelCache.key_for(script, "cpu", None, {})
        assert k1 != KernelCache.key_for(script, "tpu", None, {})
        assert k1 != KernelCache.key_for(script, "cpu", [1], {})
        assert k1 != KernelCache.key_for(script, "cpu", None,
                                         {"tl.enable_fast_math": True})
        assert k1 != KernelCache.key_for(script + " ", "cpu", None, {})

    def test_memory_hit_returns_same_kernel(self):
        f = _scale_func(mult=3.0)
        k1 = tilelang.compile(f)
        k2 = tilelang.compile(f)
        assert k1 is k2  # memory tier

    def test_disk_artifact_roundtrip(self):
        f = _scale_func(mult=5.0, M=96)
        k1 = tilelang.compile(f)
        tilelang.cache.kernel_cache._CACHE.clear()  # drop memory tier only
        k2 = tilelang.compile(f)
        assert k1 is not k2
        a = np.random.default_rng(0).standard_normal((96, 128),
                                                     dtype=np.float32)
        np.testing.assert_allclose(np.asarray(k2(a)), a * 5, rtol=1e-6)
        assert k2.get_kernel_source() == k1.get_kernel_source()


class TestAutotuner:
    def test_picks_fastest_and_caches(self):
        calls = []

        @tilelang.jit
        def factory(M, N, block_M=32):
            calls.append(block_M)

            @T.prim_func
            def k(A: T.Tensor((M, N), "float32"),
                  B: T.Tensor((M, N), "float32")):
                with T.Kernel(T.ceildiv(M, block_M)) as bx:
                    s = T.alloc_shared((block_M, N), "float32")
                    T.copy(A[bx * block_M, 0], s)
                    T.copy(s, B[bx * block_M, 0])
            return k

        tuned = tilelang.autotune(configs=[{"block_M": 32},
                                           {"block_M": 64}],
                                  warmup=1, rep=2)(factory)
        kernel = tuned(128, 128)
        assert kernel.config in ({"block_M": 32}, {"block_M": 64})
        assert kernel.latency > 0
        assert set(calls) == {32, 64}  # every config compiled
        # sweep capture: one record per candidate, each with a latency
        assert len(kernel.autotune_results) == 2
        assert all(r["latency_ms"] is not None
                   for r in kernel.autotune_results)

        # Warm disk cache: a fresh tuner for the same (source, args, configs)
        # compiles only the cached winner and reports from_cache.
        calls.clear()
        from tilelang_mesh_tpu.autotuner import AutoTuner
        res = AutoTuner(factory, [{"block_M": 32}, {"block_M": 64}],
                        warmup=1, rep=2).run(128, 128)
        assert res.from_cache
        assert res.config == kernel.config
        # Only the winner is instantiated (jit's own memory cache may even
        # absorb that, so at most one factory call — never a full re-sweep).
        assert len(calls) <= 1

    def test_cache_isolated_per_config_list(self, monkeypatch, tmp_path):
        # Cache key covers the config list: changing candidates re-tunes.
        monkeypatch.setenv("TL_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path))
        calls = []

        @tilelang.jit
        def factory(M, block_M=32):
            calls.append(block_M)

            @T.prim_func
            def k(A: T.Tensor((M, 128), "float32"),
                  B: T.Tensor((M, 128), "float32")):
                with T.Kernel(T.ceildiv(M, block_M)) as bx:
                    s = T.alloc_shared((block_M, 128), "float32")
                    T.copy(A[bx * block_M, 0], s)
                    T.copy(s, B[bx * block_M, 0])
            return k

        from tilelang_mesh_tpu.autotuner import AutoTuner
        AutoTuner(factory, [{"block_M": 32}], warmup=1, rep=1).run(128)
        assert calls == [32]
        calls.clear()
        res = AutoTuner(factory, [{"block_M": 32}, {"block_M": 64}],
                        warmup=1, rep=1).run(128)
        assert not res.from_cache  # different config list -> fresh sweep
        assert 64 in calls  # the new candidate was compiled and benchmarked
        assert len(res.all_results) == 2

    def test_bad_config_is_skipped(self):
        @tilelang.jit
        def factory(M, block_M=32):
            if block_M == 999:
                raise RuntimeError("boom")

            @T.prim_func
            def k(A: T.Tensor((M, 128), "float32"),
                  B: T.Tensor((M, 128), "float32")):
                with T.Kernel(T.ceildiv(M, block_M)) as bx:
                    s = T.alloc_shared((block_M, 128), "float32")
                    T.copy(A[bx * block_M, 0], s)
                    T.copy(s, B[bx * block_M, 0])
            return k

        tuned = tilelang.autotune(configs=[{"block_M": 999},
                                           {"block_M": 64}],
                                  warmup=1, rep=2)(factory)
        kernel = tuned(128)
        assert kernel.config == {"block_M": 64}

    def test_all_configs_failing_raises(self):
        @tilelang.jit
        def factory(M, block_M=0):
            raise RuntimeError("nope")

        tuned = tilelang.autotune(configs=[{"block_M": 1}], warmup=1,
                                  rep=1)(factory)
        with pytest.raises(Exception):
            tuned(128)


class TestProfiler:
    def test_do_bench_and_allclose(self):
        k = tilelang.compile(_scale_func(mult=2.0))
        prof = k.get_profiler()
        lat = prof.do_bench(warmup=1, rep=3, backend="wall")
        assert lat > 0
        prof.assert_allclose(lambda a: a * 2, rtol=1e-5, atol=1e-5)

    def test_allclose_catches_mismatch(self):
        k = tilelang.compile(_scale_func(mult=2.0))
        with pytest.raises(AssertionError):
            k.get_profiler().assert_allclose(lambda a: a * 3, rtol=1e-3,
                                             atol=1e-3)


class TestCarver:
    def test_hints_fit_vmem(self):
        from tilelang_mesh_tpu.carver import MatmulTemplate
        from tilelang_mesh_tpu.carver.arch import auto_arch
        arch = auto_arch()
        hints = MatmulTemplate(4096, 4096, 4096, "bfloat16").hints(topk=5)
        assert hints
        for h in hints:
            cfg = h.config
            assert arch.fits_vmem(
                ((cfg["block_M"], cfg["block_K"]), "bfloat16"),
                ((cfg["block_K"], cfg["block_N"]), "bfloat16"),
                ((cfg["block_M"], cfg["block_N"]), "float32"))

    def test_hints_shrink_for_small_problems(self):
        from tilelang_mesh_tpu.carver import MatmulTemplate
        hints = MatmulTemplate(64, 64, 64, "float32").hints(topk=3)
        for h in hints:
            assert h.config["block_M"] <= 64


class TestParCompile:
    def test_par_compile_matches_serial(self):
        funcs = [_scale_func(mult=float(m), M=32 * m) for m in (1, 2, 3)]
        kernels = tilelang.par_compile(funcs)
        assert len(kernels) == 3
        for m, k in zip((1, 2, 3), kernels):
            a = np.random.default_rng(m).standard_normal(
                (32 * m, 128), dtype=np.float32)
            np.testing.assert_allclose(np.asarray(k(a)), a * m, rtol=1e-6)


class TestEnv:
    def test_env_flags_have_defaults(self):
        from tilelang_mesh_tpu.env import env
        assert isinstance(env.TL_TPU_NUM_COMPILE_THREADS, int)
        assert env.TL_TPU_NUM_COMPILE_THREADS >= 1
        assert isinstance(env.TL_TPU_CACHE_DIR, str)

    def test_force_interpret_flag(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_FORCE_INTERPRET", "1")
        from tilelang_mesh_tpu.env import env
        assert env.TL_TPU_FORCE_INTERPRET
