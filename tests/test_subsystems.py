"""Auxiliary-subsystem coverage: kernel cache, autotuner, profiler,
carver, par_compile, env flags (reference testing/python/{cache,autotune,
profiler,carver,env} dirs, SURVEY §4/§5)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.cache.kernel_cache import KernelCache


def _scale_func(mult=2.0, M=64, N=128):
    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * mult
            T.copy(s, B)
    return scale


class TestKernelCache:
    def test_key_depends_on_ir_target_and_configs(self):
        f = _scale_func()
        script = f.func.script()
        k1 = KernelCache.key_for(script, "cpu", None, {})
        assert k1 == KernelCache.key_for(script, "cpu", None, {})
        assert k1 != KernelCache.key_for(script, "tpu", None, {})
        assert k1 != KernelCache.key_for(script, "cpu", [1], {})
        assert k1 != KernelCache.key_for(script, "cpu", None,
                                         {"tl.enable_fast_math": True})
        assert k1 != KernelCache.key_for(script + " ", "cpu", None, {})

    def test_memory_hit_returns_same_kernel(self):
        f = _scale_func(mult=3.0)
        k1 = tilelang.compile(f)
        k2 = tilelang.compile(f)
        assert k1 is k2  # memory tier

    def test_disk_artifact_roundtrip(self):
        f = _scale_func(mult=5.0, M=96)
        k1 = tilelang.compile(f)
        tilelang.cache.kernel_cache._CACHE.clear()  # drop memory tier only
        k2 = tilelang.compile(f)
        assert k1 is not k2
        a = np.random.default_rng(0).standard_normal((96, 128),
                                                     dtype=np.float32)
        np.testing.assert_allclose(np.asarray(k2(a)), a * 5, rtol=1e-6)
        assert k2.get_kernel_source() == k1.get_kernel_source()

    def test_clear_disk_gives_clean_slate(self):
        from tilelang_mesh_tpu.env import env
        f = _scale_func(mult=7.0, M=96)
        k1 = tilelang.compile(f)
        assert any(env.cache_dir().iterdir())
        # memory-only clear keeps the disk tier…
        tilelang.cache.kernel_cache._CACHE.clear()
        assert any(env.cache_dir().iterdir())
        # …disk=True purges it: the next compile is a full rebuild
        tilelang.cache.kernel_cache._CACHE.clear(disk=True)
        assert not any(env.cache_dir().iterdir())
        k2 = tilelang.compile(f)
        assert k2 is not k1
        assert k2.get_kernel_source() == k1.get_kernel_source()


class TestAutotuner:
    def test_picks_fastest_and_caches(self):
        calls = []

        @tilelang.jit
        def factory(M, N, block_M=32):
            calls.append(block_M)

            @T.prim_func
            def k(A: T.Tensor((M, N), "float32"),
                  B: T.Tensor((M, N), "float32")):
                with T.Kernel(T.ceildiv(M, block_M)) as bx:
                    s = T.alloc_shared((block_M, N), "float32")
                    T.copy(A[bx * block_M, 0], s)
                    T.copy(s, B[bx * block_M, 0])
            return k

        tuned = tilelang.autotune(configs=[{"block_M": 32},
                                           {"block_M": 64}],
                                  warmup=1, rep=2)(factory)
        kernel = tuned(128, 128)
        assert kernel.config in ({"block_M": 32}, {"block_M": 64})
        assert kernel.latency > 0
        assert set(calls) == {32, 64}  # every config compiled
        # sweep capture: one record per candidate, each with a latency
        assert len(kernel.autotune_results) == 2
        assert all(r["latency_ms"] is not None
                   for r in kernel.autotune_results)

        # Warm disk cache: a fresh tuner for the same (source, args, configs)
        # compiles only the cached winner and reports from_cache.
        calls.clear()
        from tilelang_mesh_tpu.autotuner import AutoTuner
        res = AutoTuner(factory, [{"block_M": 32}, {"block_M": 64}],
                        warmup=1, rep=2).run(128, 128)
        assert res.from_cache
        assert res.config == kernel.config
        # Only the winner is instantiated (jit's own memory cache may even
        # absorb that, so at most one factory call — never a full re-sweep).
        assert len(calls) <= 1

    def test_cache_isolated_per_config_list(self, monkeypatch, tmp_path):
        # Cache key covers the config list: changing candidates re-tunes.
        monkeypatch.setenv("TL_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path))
        calls = []

        @tilelang.jit
        def factory(M, block_M=32):
            calls.append(block_M)

            @T.prim_func
            def k(A: T.Tensor((M, 128), "float32"),
                  B: T.Tensor((M, 128), "float32")):
                with T.Kernel(T.ceildiv(M, block_M)) as bx:
                    s = T.alloc_shared((block_M, 128), "float32")
                    T.copy(A[bx * block_M, 0], s)
                    T.copy(s, B[bx * block_M, 0])
            return k

        from tilelang_mesh_tpu.autotuner import AutoTuner
        AutoTuner(factory, [{"block_M": 32}], warmup=1, rep=1).run(128)
        assert calls == [32]
        calls.clear()
        res = AutoTuner(factory, [{"block_M": 32}, {"block_M": 64}],
                        warmup=1, rep=1).run(128)
        assert not res.from_cache  # different config list -> fresh sweep
        assert 64 in calls  # the new candidate was compiled and benchmarked
        assert len(res.all_results) == 2

    def test_bad_config_is_skipped(self):
        @tilelang.jit
        def factory(M, block_M=32):
            if block_M == 999:
                raise RuntimeError("boom")

            @T.prim_func
            def k(A: T.Tensor((M, 128), "float32"),
                  B: T.Tensor((M, 128), "float32")):
                with T.Kernel(T.ceildiv(M, block_M)) as bx:
                    s = T.alloc_shared((block_M, 128), "float32")
                    T.copy(A[bx * block_M, 0], s)
                    T.copy(s, B[bx * block_M, 0])
            return k

        tuned = tilelang.autotune(configs=[{"block_M": 999},
                                           {"block_M": 64}],
                                  warmup=1, rep=2)(factory)
        kernel = tuned(128)
        assert kernel.config == {"block_M": 64}

    def test_all_configs_failing_raises(self):
        @tilelang.jit
        def factory(M, block_M=0):
            raise RuntimeError("nope")

        tuned = tilelang.autotune(configs=[{"block_M": 1}], warmup=1,
                                  rep=1)(factory)
        with pytest.raises(Exception):
            tuned(128)


class TestProfiler:
    def test_do_bench_and_allclose(self):
        k = tilelang.compile(_scale_func(mult=2.0))
        prof = k.get_profiler()
        lat = prof.do_bench(warmup=1, rep=3, backend="wall")
        assert lat > 0
        prof.assert_allclose(lambda a: a * 2, rtol=1e-5, atol=1e-5)

    def test_allclose_catches_mismatch(self):
        k = tilelang.compile(_scale_func(mult=2.0))
        with pytest.raises(AssertionError):
            k.get_profiler().assert_allclose(lambda a: a * 3, rtol=1e-3,
                                             atol=1e-3)


class TestCarver:
    def test_hints_fit_vmem(self):
        from tilelang_mesh_tpu.carver import MatmulTemplate
        from tilelang_mesh_tpu.carver.arch import auto_arch
        arch = auto_arch()
        hints = MatmulTemplate(4096, 4096, 4096, "bfloat16").hints(topk=5)
        assert hints
        for h in hints:
            cfg = h.config
            assert arch.fits_vmem(
                ((cfg["block_M"], cfg["block_K"]), "bfloat16"),
                ((cfg["block_K"], cfg["block_N"]), "bfloat16"),
                ((cfg["block_M"], cfg["block_N"]), "float32"))

    def test_hints_shrink_for_small_problems(self):
        from tilelang_mesh_tpu.carver import MatmulTemplate
        hints = MatmulTemplate(64, 64, 64, "float32").hints(topk=3)
        for h in hints:
            assert h.config["block_M"] <= 64

    def test_roofline_policy_prefers_mxu_saturating_tiles(self):
        """A 128x128-aligned tile must outrank an MXU-starved 8-wide tile
        (round-3: cost-ranked policy vs the old heuristic order)."""
        from tilelang_mesh_tpu.carver import Candidate, DefaultPolicy
        from tilelang_mesh_tpu.carver.arch import TPU_V5E
        pol = DefaultPolicy(TPU_V5E)
        good = Candidate({"block_M": 256, "block_N": 256, "block_K": 512},
                         flops=2.0 * 4096 ** 3, hbm_bytes=3 * 4096 ** 2 * 2,
                         vmem_bytes=1 << 20, n_tiles=2048, utilization=1.0)
        bad = Candidate({"block_M": 8, "block_N": 128, "block_K": 512},
                        flops=2.0 * 4096 ** 3, hbm_bytes=3 * 4096 ** 2 * 2,
                        vmem_bytes=1 << 16, n_tiles=512 * 32 * 8,
                        utilization=8 / 128)
        ranked = pol.rank([bad, good], topk=2)
        assert ranked[0].config["block_M"] == 256
        assert ranked[0].predicted_ms < ranked[1].predicted_ms

    def test_conv_template_ranked_hints(self):
        from tilelang_mesh_tpu.carver import Conv2DTemplate
        from tilelang_mesh_tpu.carver.arch import TPU_V5E
        t = Conv2DTemplate(8, 34, 34, 128, 256, 3, 3, arch=TPU_V5E)
        hints = t.hints(5)
        assert hints
        oh, ow = t.out_hw
        assert (oh, ow) == (32, 32)
        M = 8 * oh * ow
        for h in hints:
            assert M % h.config["block_M"] == 0
            assert 256 % h.config["block_N"] == 0
            # per-tile VMEM within the scoped budget
            assert h.predicted_ms > 0

    def test_gemv_template_is_memory_bound(self):
        """GEMV ranking must be driven by HBM streaming: predicted time
        ~= bytes / bandwidth, far above the MXU flops time."""
        from tilelang_mesh_tpu.carver import GEMVTemplate
        from tilelang_mesh_tpu.carver.arch import TPU_V5E
        hints = GEMVTemplate(8192, 8192, arch=TPU_V5E).hints(3)
        assert hints
        stream_ms = (8192 * 8192 * 2) / (TPU_V5E.hbm_gbps * 1e9) * 1e3
        assert hints[0].predicted_ms >= 0.9 * stream_ms

    def test_flash_template_scoped_vmem_budget(self):
        """The configs that fault a real v5e ((512,512) at d=128) must
        not be ranked; the measured winners must come first."""
        from tilelang_mesh_tpu.carver import FlashAttentionTemplate
        from tilelang_mesh_tpu.carver.arch import TPU_V5E
        d64 = FlashAttentionTemplate(2048, 2048, 64, batch_heads=32,
                                     causal=True, arch=TPU_V5E).hints(8)
        assert d64[0].config == {"block_M": 512, "block_N": 512}
        d128 = FlashAttentionTemplate(2048, 2048, 128, batch_heads=32,
                                      causal=True, arch=TPU_V5E).hints(8)
        assert d128[0].config == {"block_M": 256, "block_N": 512}
        assert {"block_M": 512, "block_N": 512} not in \
            [h.config for h in d128]

    def test_general_reduce_template(self):
        from tilelang_mesh_tpu.carver import GeneralReductionTemplate
        from tilelang_mesh_tpu.carver.arch import TPU_V5E
        hints = GeneralReductionTemplate((4096, 4096),
                                         arch=TPU_V5E).hints(4)
        assert hints
        for h in hints:
            assert 4096 % h.config["block_M"] == 0
            assert 4096 % h.config["block_N"] == 0


class TestParCompile:
    def test_par_compile_matches_serial(self):
        funcs = [_scale_func(mult=float(m), M=32 * m) for m in (1, 2, 3)]
        kernels = tilelang.par_compile(funcs)
        assert len(kernels) == 3
        for m, k in zip((1, 2, 3), kernels):
            a = np.random.default_rng(m).standard_normal(
                (32 * m, 128), dtype=np.float32)
            np.testing.assert_allclose(np.asarray(k(a)), a * m, rtol=1e-6)


class TestEnv:
    def test_env_flags_have_defaults(self):
        from tilelang_mesh_tpu.env import env
        assert isinstance(env.TL_TPU_NUM_COMPILE_THREADS, int)
        assert env.TL_TPU_NUM_COMPILE_THREADS >= 1
        assert isinstance(env.TL_TPU_CACHE_DIR, str)

    def test_force_interpret_flag(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_FORCE_INTERPRET", "1")
        from tilelang_mesh_tpu.env import env
        assert env.TL_TPU_FORCE_INTERPRET


# ---------------------------------------------------------------------------
# Mosaic-level introspection (round-3: reference show_ptx/show_sass analog,
# /root/reference/tilelang/jit/kernel.py:657-734)
# ---------------------------------------------------------------------------

def _intro_kernel():
    import tilelang_mesh_tpu.language as T

    @T.prim_func
    def dbl(A: T.Tensor((8, 128), "float32"), O: T.Tensor((8, 128),
                                                          "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((8, 128), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(8, 128):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, O)
    return dbl


def test_mosaic_introspection_interpret_mode_raises_clearly():
    import os
    if os.environ.get("TL_TPU_TEST_DEVICE", "cpu") == "tpu":
        pytest.skip("real-TPU path covered by test below")
    k = tilelang.compile(_intro_kernel())
    with pytest.raises(NotImplementedError, match="interpret mode"):
        k.get_mosaic()


def test_mosaic_introspection_on_tpu():
    import os
    if os.environ.get("TL_TPU_TEST_DEVICE", "cpu") != "tpu":
        pytest.skip("needs real TPU")
    import tilelang_mesh_tpu as tilelang
    k = tilelang.compile(_intro_kernel())
    mosaic = k.get_mosaic()
    assert "mosaic" in mosaic and "vmem" in mosaic
    hlo = k.get_compiled_hlo()
    assert "tpu_custom_call" in hlo
    mem = k.get_memory_analysis()
    assert mem.generated_code_size_in_bytes > 0
    cost = k.get_cost_analysis()
    assert isinstance(cost, dict)


def test_layout_visualizer_graphical_formats(tmp_path):
    """png/pdf/svg rendering parity with the reference's layout_visual
    (txt output is covered elsewhere)."""
    pytest.importorskip("matplotlib")
    from tilelang_mesh_tpu.analysis.layout_visual import (plot_fragment,
                                                          plot_mesh_blocks,
                                                          plot_plan)
    for ext in ("png", "svg", "pdf"):
        p = tmp_path / f"frag.{ext}"
        plot_fragment(16, 128, 32, path=str(p))
        assert p.exists() and p.stat().st_size > 0
    p = tmp_path / "mesh.png"
    plot_mesh_blocks(2, 4, path=str(p))
    assert p.exists() and p.stat().st_size > 0
    k = tilelang.compile(_scale_func())
    p = tmp_path / "plan.svg"
    plot_plan(k.artifact, path=str(p))
    assert p.exists() and p.stat().st_size > 0
    with pytest.raises(ValueError, match="unsupported"):
        plot_fragment(8, 128, path=str(tmp_path / "frag.bmp"))


def test_static_oob_window_rejected_with_named_error():
    """Constant windows past a buffer's extent fail the pre-lower check
    with the buffer named (LegalizeSafeMemoryAccess's static slice),
    not a downstream broadcast shape mismatch."""
    @T.prim_func
    def oob(A: T.Tensor((8, 128), "float32"),
            O: T.Tensor((16, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((16, 128), "float32")
            T.copy(A[4, 0], s)
            T.copy(s, O)

    with pytest.raises(Exception, match=r"window \[4:20\) exceeds A"):
        tilelang.compile(oob)


def test_ragged_grid_blocks_still_legal():
    """Grid-var-driven last-block overhang is Pallas-masked, not an
    error."""
    import numpy as np

    @T.prim_func
    def ragged(A: T.Tensor((12, 128), "float32"),
               O: T.Tensor((12, 128), "float32")):
        with T.Kernel(2) as bx:
            s = T.alloc_shared((8, 128), "float32")
            T.copy(A[bx * 8, 0], s)
            T.copy(s, O[bx * 8, 0])

    k = tilelang.compile(ragged)
    a = np.random.default_rng(0).standard_normal((12, 128)).astype(
        np.float32)
    out = np.empty_like(a)
    k(a, out)
    np.testing.assert_allclose(out, a, rtol=1e-6)


def test_autotune_from_carver_template():
    """autotune(template=...) derives its config grid from the carver's
    roofline-ranked hints at tune time (reference: carver hints feed the
    tuner)."""
    from tilelang_mesh_tpu.carver import ElementwiseTemplate
    from tilelang_mesh_tpu.carver.arch import TPU_V5E
    seen = []

    @tilelang.jit
    def factory(M, N, block_M=8, block_N=128):
        seen.append((block_M, block_N))

        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(T.ceildiv(M, block_M),
                          T.ceildiv(N, block_N)) as (bx, by):
                s = T.alloc_shared((block_M, block_N), "float32")
                T.copy(A[bx * block_M, by * block_N], s)
                T.copy(s, B[bx * block_M, by * block_N])
        return k

    tuned = tilelang.autotune(
        template=lambda M, N: ElementwiseTemplate((M, N), "float32",
                                                  arch=TPU_V5E),
        topk=3, warmup=1, rep=2)(factory)
    kernel = tuned(64, 256)
    assert kernel.config in [
        {"block_M": bm, "block_N": bn} for bm, bn in seen]
    assert len(kernel.autotune_results) == len(set(seen)) == 3
    assert kernel.latency > 0


def test_autotune_without_configs_enters_derive_mode():
    """No configs and no template is now the IR-derived mode; a factory
    that cannot be analyzed fails at TUNE time with guidance."""
    tuner = tilelang.autotune(warmup=1)(lambda: None)
    with pytest.raises(RuntimeError, match="derive|tunable"):
        tuner()


def test_autotune_template_ignores_factory_kwargs():
    """Call-site tile overrides go to the factory, not the template: the
    template callable only receives the kwargs its signature names."""
    from tilelang_mesh_tpu.carver import ElementwiseTemplate
    from tilelang_mesh_tpu.carver.arch import TPU_V5E

    @tilelang.jit
    def factory(M, N, block_M=8, block_N=128):
        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(T.ceildiv(M, block_M),
                          T.ceildiv(N, block_N)) as (bx, by):
                s = T.alloc_shared((block_M, block_N), "float32")
                T.copy(A[bx * block_M, by * block_N], s)
                T.copy(s, B[bx * block_M, by * block_N])
        return k

    tuned = tilelang.autotune(
        template=lambda M, N: ElementwiseTemplate((M, N), "float32",
                                                  arch=TPU_V5E),
        topk=2, warmup=1, rep=2)(factory)
    kernel = tuned(64, 256, block_N=128)   # explicit factory kwarg
    assert kernel.latency > 0


def test_profiler_trace_capture(tmp_path):
    """jax.profiler trace capture — the CUPTI-capture analog."""
    import os
    k = tilelang.compile(_scale_func(mult=2.0))
    d = k.get_profiler().trace(str(tmp_path / "trace"), steps=2)
    # a trace directory with at least one event file was produced
    found = []
    for root, _dirs, files in os.walk(d):
        found.extend(files)
    assert found, "no trace files captured"


def test_get_lowered_levels():
    k = tilelang.compile(_scale_func())
    s = k.get_lowered("stablehlo")
    assert "module" in s
    assert s == k.get_lowered_hlo()
    with pytest.raises(ValueError, match="mosaic | optimized_hlo"):
        k.get_lowered("ptx")
