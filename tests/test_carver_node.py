"""IR-derived autotune candidates (carver/node.py, the PrimFuncNode
analog — reference carver/roller/node.py:191): autotune() with neither
configs= nor template= must classify the traced kernel, reconstruct the
problem dims from its IR, and produce the same space as the hand
template."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.carver import FlashAttentionTemplate, MatmulTemplate
from tilelang_mesh_tpu.carver.node import analyze_prim_func, derive_template

M, N, K = 256, 512, 384


def _gemm_factory(M, N, K, block_M=64, block_N=128, block_K=64):
    @T.prim_func
    def mm(A: T.Tensor((M, K), "float32"), B: T.Tensor((K, N), "float32"),
           C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            As = T.alloc_shared((block_M, block_K), "float32")
            Bs = T.alloc_shared((block_K, block_N), "float32")
            Cl = T.alloc_fragment((block_M, block_N), "float32")
            T.fill(Cl, 0.0)
            for ko in T.Pipelined(T.ceildiv(K, block_K)):
                T.copy(A[by * block_M, ko * block_K], As)
                T.copy(B[ko * block_K, bx * block_N], Bs)
                T.gemm(As, Bs, Cl)
            T.copy(Cl, C[by * block_M, bx * block_N])
    return tilelang.compile(mm)


def test_gemm_problem_dims_reconstructed():
    """M/N/K recovered from grid extents x traced tile sizes — including
    the minor-vs-major disambiguation when dims share a tile size."""
    k = _gemm_factory(M, N, K)
    t = derive_template(k.prim_func)
    assert isinstance(t, MatmulTemplate)
    assert (t.M, t.N, t.K) == (M, N, K)
    assert t.in_dtype == "float32"


def test_gemm_square_tiles_disambiguated():
    k = _gemm_factory(256, 512, 384, block_M=128, block_N=128,
                      block_K=128)
    t = derive_template(k.prim_func)
    assert (t.M, t.N, t.K) == (256, 512, 384)


def test_derived_space_matches_hand_template():
    """The derived candidate space must equal the hand template's (same
    classifier target, same problem dims => identical hints)."""
    k = _gemm_factory(M, N, K)
    t = derive_template(k.prim_func)
    hand = MatmulTemplate(M, N, K, in_dtype="float32", arch=t.arch)
    derived = [h.config for h in t.hints(8)]
    manual = [h.config for h in hand.hints(8)]
    assert derived == manual


def test_flash_attention_classified():
    from tilelang_mesh_tpu.ops.flash_attention import mha_fwd_kernel
    B, H, S, D = 2, 4, 256, 64
    k = mha_fwd_kernel(B, H, S, S, D, block_M=128, block_N=128,
                       causal=True, dtype="float32")
    t = derive_template(k.prim_func)
    assert isinstance(t, FlashAttentionTemplate)
    assert t.seq_q == S and t.seq_k == S and t.head_dim == D
    assert t.batch_heads == B * H
    assert t.causal is True


def test_flash_noncausal_detected():
    from tilelang_mesh_tpu.ops.flash_attention import mha_fwd_kernel
    k = mha_fwd_kernel(1, 2, 256, 256, 64, block_M=128, block_N=128,
                       causal=False, dtype="float32")
    t = derive_template(k.prim_func)
    assert t.causal is False


def test_autotune_without_template_end_to_end():
    """autotune() with no configs and no template: derives, sweeps, and
    the winning kernel computes the right product."""
    calls = []

    @tilelang.autotune(topk=3, warmup=1, rep=2, cache_results=False)
    def matmul(M, N, K, block_M=64, block_N=128, block_K=64):
        calls.append((block_M, block_N, block_K))
        return _gemm_factory(M, N, K, block_M, block_N, block_K)

    Ms, Ns, Ks = 128, 256, 128
    kernel = matmul(Ms, Ns, Ks)
    assert len(set(calls)) >= 2, f"expected a swept space, got {calls}"
    rng = np.random.default_rng(0)
    a = rng.standard_normal((Ms, Ks)).astype(np.float32)
    b = rng.standard_normal((Ks, Ns)).astype(np.float32)
    c = np.empty((Ms, Ns), np.float32)
    kernel(a, b, c)
    np.testing.assert_allclose(c, a @ b, rtol=2e-2, atol=2e-2)


def test_analyze_collects_structure():
    k = _gemm_factory(M, N, K)
    st = analyze_prim_func(k.prim_func)
    assert len(st.grid) == 2
    assert len(st.gemms) == 1
    assert st.gemms[0].loops, "K loop not captured"
    assert not st.has_exp


def test_derive_falls_back_to_elementwise():
    """A kernel with no MXU work and no reductions gets the elementwise
    space over its largest static global param (documented fallback)."""
    from tilelang_mesh_tpu.carver.roller import ElementwiseTemplate

    @T.prim_func
    def weird(A: T.Tensor((8, 128), "float32")):
        with T.Kernel(1) as bx:
            pass

    t = derive_template(weird)
    assert isinstance(t, ElementwiseTemplate)


def test_elementwise_classified():
    from tilelang_mesh_tpu.carver.roller import ElementwiseTemplate

    @T.prim_func
    def scale(A: T.Tensor((64, 256), "float32"),
              O: T.Tensor((64, 256), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((64, 256), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(64, 256):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, O)

    t = derive_template(scale)
    assert isinstance(t, ElementwiseTemplate)
    assert tuple(t.shape) == (64, 256)


def test_autotune_typo_kwarg_raises():
    with pytest.raises(TypeError, match="configs.*template|did you mean"):
        tilelang.autotune(config=[{"block_M": 128}])


def test_positional_tunable_not_swept():
    """A tunable pinned POSITIONALLY at the call site must be excluded
    from the derived sweep (not collide with the sweep kwargs)."""
    seen = []

    @tilelang.autotune(topk=3, warmup=1, rep=2, cache_results=False)
    def matmul(M, N, K, block_M=64, block_N=128, block_K=64):
        seen.append(block_M)
        return _gemm_factory(M, N, K, block_M, block_N, block_K)

    matmul(128, 256, 128, 32)   # block_M pinned positionally
    assert set(seen) == {32}, f"block_M swept despite being pinned: {seen}"


def test_outer_step_loop_not_counted_as_reduction():
    """An enclosing serial loop that does not step the gemm input
    windows must not inflate the derived K."""
    S, Mi, Ki, Ni = 4, 64, 128, 128

    @T.prim_func
    def multi_step(A: T.Tensor((Mi, Ki), "float32"),
                   B: T.Tensor((Ki, Ni), "float32"),
                   O: T.Tensor((Mi, Ni), "float32")):
        with T.Kernel(1) as bx:
            As = T.alloc_shared((Mi, Ki), "float32")
            Bs = T.alloc_shared((Ki, Ni), "float32")
            Cl = T.alloc_fragment((Mi, Ni), "float32")
            T.copy(A, As)
            T.copy(B, Bs)
            T.fill(Cl, 0.0)
            for _step in T.serial(S):        # NOT a K axis
                T.gemm(As, Bs, Cl)
            T.copy(Cl, O)

    t = derive_template(multi_step)
    assert isinstance(t, MatmulTemplate)
    assert t.K == Ki, f"outer step loop inflated K: {t.K}"
