"""Mesh collective verifier & runtime guardrail suite (verify/;
docs/robustness.md "Schedule verification & guardrails").

Four layers, mirroring the subsystem's wiring points:

1. **Static schedule verifier** — every comm_opt golden program passes
   clean under the default ``TL_TPU_VERIFY=1`` (byte-identical
   plan_desc), and every deliberately corrupted schedule (dropped
   chunk, mismatched fused slot, subset-only barrier, payload/recv
   alias, fused race, broken wire-byte conservation) raises a
   ``MeshVerifyError`` naming the offending op. Corruption is injected
   by wrapping the optimizer the way a miscompiling rewrite would
   misbehave — the verifier must catch it downstream.
2. **Differential self-check** — ``TL_TPU_SELFCHECK=1`` diffs the
   optimized schedule's first call against ``TL_TPU_COMM_OPT=0``;
   seeded corruption on the collective interpret paths triggers
   divergence detection plus fallback to the unoptimized schedule.
3. **Runtime guardrails** — the NaN/Inf sanitizer on collective
   payloads and kernel outputs, and the collective watchdog
   (timeout classification, breaker trip, schedule degradation).
4. **Reporting** — ``verify.*`` counters, ``metrics_summary()
   ["verify"]``, and the ``analyzer verify`` subcommand.

Everything is deterministic (seeded fault clauses, seeded fuzz RNG).
"""

import copy
import time

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.analysis.checkers import SemanticError
from tilelang_mesh_tpu.cache.kernel_cache import _CACHE
from tilelang_mesh_tpu.ir import (Buffer, CommBarrier, CommBroadcast,
                                  CommChunked, CommFused, Region)
from tilelang_mesh_tpu.observability import get_tracer
from tilelang_mesh_tpu.parallel import lowering, mesh_config
from tilelang_mesh_tpu.parallel.lowering import segments_rw
from tilelang_mesh_tpu.resilience import FAULT_SITES, TLTimeoutError, inject
from tilelang_mesh_tpu.resilience.retry import global_breaker
from tilelang_mesh_tpu.transform import pass_config
from tilelang_mesh_tpu.verify import (MeshVerifyError, NumericError,
                                      SelfCheckDivergence, guard_state,
                                      verify_mode, verify_schedule)
from tilelang_mesh_tpu.verify.runtime import watchdog_call

MESH = (2, 2)
NROW, NCOL = MESH
SHAPE = (8, 128)
TARGET = f"cpu-mesh[{NROW}x{NCOL}]"
CHUNK_CFG = {"tl.tpu.comm_chunk_bytes": 1024}


@pytest.fixture(autouse=True)
def _hermetic(monkeypatch):
    """Fresh kernel cache / tracer / breaker and default guard knobs per
    test: degraded-kernel state must never leak between tests."""
    for var in ("TL_TPU_VERIFY", "TL_TPU_SELFCHECK", "TL_TPU_SANITIZE",
                "TL_TPU_COMM_TIMEOUT_MS", "TL_TPU_FAULTS", "TL_TPU_TRACE"):
        monkeypatch.delenv(var, raising=False)
    _CACHE.clear()
    get_tracer().reset()
    obs.reset()
    global_breaker().reset()
    yield
    _CACHE.clear()
    get_tracer().reset()
    obs.reset()
    global_breaker().reset()


def _global(shape=None):
    shape = shape or (NROW * NCOL * SHAPE[0], SHAPE[1])
    return T.MeshTensor(shape, T.MeshShardingPolicy(cross_mesh_dim=0),
                        MESH, "float32")


def _shards(seed):
    return np.random.default_rng(seed).standard_normal(
        (NROW * NCOL * SHAPE[0], SHAPE[1])).astype(np.float32)


def _fused_program():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(), B: _global((NROW * NCOL * SHAPE[0], 1)),
              C: _global((NROW * NCOL * SHAPE[0], 1))):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment(SHAPE, "float32")
                y = T.alloc_fragment(SHAPE, "float32")
                o1 = T.alloc_fragment((SHAPE[0], 1), "float32")
                o2 = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, x)
                T.copy(A, y)
                T.comm.all_reduce(x, o1, "sum", "h", dim=1)
                T.comm.all_reduce(y, o2, "sum", "h", dim=1)
                T.copy(o1, B)
                T.copy(o2, C)
        return k


def _chunk_program():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(),
              B: _global((NROW * NCOL, NCOL, SHAPE[0], SHAPE[1]))):
            with T.Kernel(1) as bx:
                send = T.alloc_shared(SHAPE, "float32")
                recv = T.alloc_shared((NCOL, *SHAPE), "float32")
                T.copy(A, send)
                T.comm.all_gather(send, recv, "h")
                T.copy(recv, B[0, 0, 0])
        return k


def _dedup_program():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(), B: _global(), C: _global()):
            with T.Kernel(1) as bx:
                x = T.alloc_shared(SHAPE, "float32")
                d1 = T.alloc_shared(SHAPE, "float32")
                d2 = T.alloc_shared(SHAPE, "float32")
                T.copy(A, x)
                T.comm.broadcast(x, d1, (0, 1), "h")
                T.comm.broadcast(x, d1, (0, 1), "h")   # exact duplicate
                T.comm.broadcast(x, d2, (0, 1), "h")   # same payload
                T.copy(d1, B)
                T.copy(d2, C)
        return k


def _dce_program():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(), B: _global()):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment(SHAPE, "float32")
                dead = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, x)
                T.comm.all_reduce(x, dead, "sum", "v", dim=1)
                T.copy(x, B)
        return k


def _bcast_program():
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(), B: _global()):
            with T.Kernel(1) as bx:
                x = T.alloc_shared(SHAPE, "float32")
                d = T.alloc_shared(SHAPE, "float32")
                T.copy(A, x)
                T.comm.broadcast(x, d, (0, 1), "h")
                T.copy(d, B)
        return k


def _lower(pf, **cfg):
    if cfg:
        with pass_config(cfg):
            return tilelang.lower(pf, target=TARGET)
    return tilelang.lower(pf, target=TARGET)


def _compile(prog, **cfg):
    if cfg:
        with pass_config(cfg):
            return tilelang.compile(prog(), target=TARGET)
    return tilelang.compile(prog(), target=TARGET)


# ---------------------------------------------------------------------------
# mode parsing
# ---------------------------------------------------------------------------


def test_verify_mode_parsing(monkeypatch):
    assert verify_mode() == "on"                  # default
    monkeypatch.setenv("TL_TPU_VERIFY", "0")
    assert verify_mode() == "off"
    monkeypatch.setenv("TL_TPU_VERIFY", "strict")
    assert verify_mode() == "strict"
    # pass config wins over the env var
    assert verify_mode({"tl.tpu.verify": "off"}) == "off"
    with pytest.raises(ValueError, match="unknown TL_TPU_VERIFY"):
        verify_mode({"tl.tpu.verify": "strcit"})


# ---------------------------------------------------------------------------
# clean schedules verify clean — and plan_desc stays byte-identical
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("prog,cfg", [
    (_fused_program, {}),
    (_chunk_program, CHUNK_CFG),
    (_bcast_program, {}),
])
def test_goldens_pass_clean_and_unchanged(prog, cfg, monkeypatch):
    """Default TL_TPU_VERIFY=1 must neither reject nor reformat any
    existing golden schedule — a clean verification adds nothing."""
    art_on = _lower(prog(), **cfg)
    assert art_on.attrs["verify"] is not None
    assert art_on.attrs["verify"]["warnings"] == []
    assert art_on.attrs["verify"]["checked"] >= 1
    monkeypatch.setenv("TL_TPU_VERIFY", "0")
    art_off = _lower(prog(), **cfg)
    assert art_off.attrs["verify"] is None
    assert art_on.plan_desc == art_off.plan_desc


def test_unoptimized_schedules_also_verified(monkeypatch):
    """The verifier is independent of the optimizer: it runs (and
    passes) on the TL_TPU_COMM_OPT=0 schedule too."""
    monkeypatch.setenv("TL_TPU_COMM_OPT", "0")
    art = _lower(_fused_program())
    assert art.attrs["comm_opt"] is None
    assert art.attrs["verify"]["checked"] == 2    # both raw all_reduces


def test_verify_counters():
    _lower(_fused_program())
    c = obs.metrics_summary()["verify"]
    assert c["schedules"] == 1
    assert c["collectives_checked"] >= 1
    assert c["errors"] == 0


# ---------------------------------------------------------------------------
# mutation tests: a corrupted schedule must raise, naming the op
# ---------------------------------------------------------------------------


def _with_corruption(monkeypatch, corrupt_fn):
    """Wrap the optimizer so its (correct) output is corrupted before
    the verifier sees it — the shape of a miscompiling rewrite."""
    real = lowering.optimize_collectives

    def wrapper(*args, **kwargs):
        res = real(*args, **kwargs)
        corrupt_fn(res)
        res.rewrites.append("corrupted-by-test")  # force application
        return res

    monkeypatch.setattr(lowering, "optimize_collectives", wrapper)


def _first_comm_idx(res):
    return next(i for i, (k, _) in enumerate(res.segments) if k == "comm")


def test_mutation_dropped_chunk(monkeypatch):
    """Chunk count that does not divide the payload's leading axis:
    trailing rows would silently never cross the wire."""
    def corrupt(res):
        i = _first_comm_idx(res)
        res.segments[i] = ("comm", CommChunked(res.segments[i][1], 3))
    _with_corruption(monkeypatch, corrupt)
    with pytest.raises(MeshVerifyError, match=r"dropped chunk.*all_gather"):
        _lower(_chunk_program())   # default threshold: op still raw


def test_mutation_mismatched_fused_slot(monkeypatch):
    """Two members with DIFFERENT payloads forced onto one wire slot:
    one destination would receive the other's bytes."""
    def corrupt(res):
        for i, (k, p) in enumerate(res.segments):
            if k == "comm" and isinstance(p, CommFused):
                p.slots = [0] * len(p.ops)
    _with_corruption(monkeypatch, corrupt)
    with pytest.raises(MeshVerifyError,
                       match=r"mismatched fused slot.*all_reduce"):
        _lower(_fused_program())


def test_mutation_subset_barrier(monkeypatch):
    """A barrier only core 0 reaches: every other core deadlocks."""
    def corrupt(res):
        res.segments.append(("comm", CommBarrier(group=[0])))
    _with_corruption(monkeypatch, corrupt)
    with pytest.raises(MeshVerifyError, match=r"subset barrier.*barrier"):
        _lower(_fused_program())


def test_mutation_payload_recv_alias(monkeypatch):
    """A collective reading the buffer it writes: the NoC schedule
    would consume bytes it is concurrently overwriting."""
    def corrupt(res):
        i = _first_comm_idx(res)
        op = res.segments[i][1]
        res.segments[i] = ("comm", CommBroadcast(
            op.send, op.send, -1, 0, 0, 0))
    _with_corruption(monkeypatch, corrupt)
    with pytest.raises(MeshVerifyError,
                       match=r"payload/recv alias.*broadcast"):
        _lower(_chunk_program())   # default threshold: op still raw


def test_mutation_race_inside_fused(monkeypatch):
    """A fused member reading another member's output: batching
    executes them simultaneously, so the read races the write."""
    def corrupt(res):
        for _, p in res.segments:
            if isinstance(p, CommFused):
                m = copy.copy(p.ops[1])
                m.buffer = p.ops[0].out   # member[1] reads member[0]'s out
                p.ops[1] = m
    _with_corruption(monkeypatch, corrupt)
    with pytest.raises(MeshVerifyError, match=r"race inside fused"):
        _lower(_fused_program())


def test_mutation_wire_byte_conservation(monkeypatch):
    """Accounting drift: the optimizer claims different wire bytes than
    the op sequence actually moves."""
    def corrupt(res):
        res.post_wire_bytes += 64
    _with_corruption(monkeypatch, corrupt)
    with pytest.raises(MeshVerifyError, match=r"wire-byte conservation"):
        _lower(_fused_program())


def test_mutation_off_switch_bypasses(monkeypatch):
    """TL_TPU_VERIFY=0 must bypass the net (escape hatch, documented as
    dangerous) — the corrupted schedule lowers without complaint."""
    def corrupt(res):
        res.segments.append(("comm", CommBarrier(group=[0])))
    _with_corruption(monkeypatch, corrupt)
    monkeypatch.setenv("TL_TPU_VERIFY", "0")
    art = _lower(_fused_program())      # no raise
    assert art.attrs["verify"] is None


def test_strict_escalates_warnings(monkeypatch):
    """A finding that is only a warning by default (frontend/lowering
    payload-byte drift) becomes a hard error under strict."""
    def corrupt(res):
        i = _first_comm_idx(res)
        op = res.segments[i][1]
        meta = dict(getattr(op, "emit_meta", None) or {})
        meta["payload_bytes"] = (meta.get("payload_bytes") or 4096) + 4
        op.emit_meta = meta
    _with_corruption(monkeypatch, corrupt)
    art = _lower(_chunk_program())      # default mode: warning only
    assert "verify[on]" in art.plan_desc
    assert "payload accounting drift" in art.plan_desc
    assert art.attrs["verify"]["warnings"]
    monkeypatch.setenv("TL_TPU_VERIFY", "strict")
    with pytest.raises(MeshVerifyError,
                       match=r"\(strict\).*accounting drift"):
        _lower(_chunk_program())


# ---------------------------------------------------------------------------
# direct unit checks + pre-lower alias checker
# ---------------------------------------------------------------------------


def _mini_segments():
    """A hand-built two-segment schedule for unit-level checks."""
    src = Buffer("src", SHAPE, "float32", "shared")
    dst = Buffer("dst", SHAPE, "float32", "shared")
    bc = CommBroadcast(Region(src, (0, 0), SHAPE),
                       Region(dst, (0, 0), SHAPE), -1, 0, 0, 0)
    return [("comm", bc)], {dst.uid}


def test_verify_schedule_unit_clean():
    segs, gp = _mini_segments()
    rep = verify_schedule(segs, segments_rw(segs), gp, NROW, NCOL)
    assert rep.checked == 1 and not rep.warnings


def test_verify_schedule_unit_off_mode():
    segs, gp = _mini_segments()
    rep = verify_schedule(segs, segments_rw(segs), gp, NROW, NCOL,
                          mode="off")
    assert rep.checked == 0


def test_prelower_alias_checker():
    """User-written aliasing is rejected pre-lower with the T.comm call
    named — before segmentation ever runs."""
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: _global(), B: _global()):
            with T.Kernel(1) as bx:
                x = T.alloc_shared(SHAPE, "float32")
                T.copy(A, x)
                T.comm.broadcast(x, x, (0, 0), "h")
                T.copy(x, B)
    with pytest.raises(SemanticError, match=r"broadcast src/dst alias"):
        tilelang.lower(k, target=TARGET)


# ---------------------------------------------------------------------------
# property fuzz: random comm programs verify clean; corrupted variants
# are flagged
# ---------------------------------------------------------------------------


def _random_program(rng):
    """A random top-level collective sequence (kind/axis/direction/
    payload routing drawn from the rng) over the 2x2 mesh."""
    n_ops = int(rng.integers(1, 5))
    spec = []
    for _ in range(n_ops):
        kind = rng.choice(["broadcast", "all_reduce", "all_gather",
                           "barrier"])
        direction = str(rng.choice(["h", "v", "all"]))
        src = (int(rng.integers(0, NROW)), int(rng.integers(0, NCOL)))
        rt = str(rng.choice(["sum", "max", "min"]))
        spec.append((str(kind), direction, src, rt))

    with mesh_config(*MESH):
        @T.prim_func
        def fuzz(A: _global(), B: _global()):
            with T.Kernel(1) as bx:
                cur = T.alloc_shared(SHAPE, "float32")
                T.copy(A, cur)
                for kind, direction, src, rt in spec:
                    if kind == "broadcast":
                        dst = T.alloc_shared(SHAPE, "float32")
                        T.comm.broadcast(cur, dst, src, direction)
                        cur = dst
                    elif kind == "all_reduce":
                        frag = T.alloc_fragment(SHAPE, "float32")
                        out = T.alloc_fragment((SHAPE[0], 1), "float32")
                        T.copy(A, frag)
                        T.comm.all_reduce(frag, out, rt, direction, dim=1)
                    elif kind == "all_gather":
                        n = {"h": NCOL, "v": NROW,
                             "all": NROW * NCOL}[direction]
                        recv = T.alloc_shared((n, *SHAPE), "float32")
                        T.comm.all_gather(cur, recv, direction)
                    else:
                        T.comm.barrier()
                T.copy(cur, B)
        return fuzz


_CORRUPTIONS = ("chunk3", "alias", "subset_barrier")


def _fuzz_corrupt(res, which):
    from tilelang_mesh_tpu.parallel.lowering import _comm_buffers
    comms = [i for i, (k, p) in enumerate(res.segments)
             if k == "comm" and not isinstance(p, CommBarrier)]
    if which == "subset_barrier" or not comms:
        res.segments.append(("comm", CommBarrier(group=[0])))
        return
    i = comms[0]
    op = res.segments[i][1]
    if which == "chunk3":
        res.segments[i] = ("comm", CommChunked(op, 3))
    else:
        reads, _ = _comm_buffers(op)
        r = reads[0]
        res.segments[i] = ("comm", CommBroadcast(r, r, -1, 0, 0, 0))


def test_fuzz_random_programs_verify_clean_and_corruptions_flagged(
        monkeypatch):
    rng = np.random.default_rng(20260804)
    real = lowering.optimize_collectives
    for trial in range(12):
        pf = _random_program(rng)
        cfg = dict(CHUNK_CFG) if rng.random() < 0.5 else {}
        # 1) the comm_opt-rewritten schedule verifies clean
        monkeypatch.setattr(lowering, "optimize_collectives", real)
        art = _lower(pf, **cfg)
        assert art.attrs["verify"] is not None, f"trial {trial}"
        assert not art.attrs["verify"]["warnings"], f"trial {trial}"
        # 2) the unoptimized schedule verifies clean too
        _lower(pf, **{**cfg, "tl.tpu.comm_opt": "0"})
        # 3) a mutation-corrupted variant is flagged
        which = str(rng.choice(_CORRUPTIONS))

        def wrapper(*args, _w=which, **kwargs):
            res = real(*args, **kwargs)
            _fuzz_corrupt(res, _w)
            res.rewrites.append("corrupted-by-fuzz")
            return res

        monkeypatch.setattr(lowering, "optimize_collectives", wrapper)
        with pytest.raises(MeshVerifyError):
            _lower(pf, **cfg)


# ---------------------------------------------------------------------------
# differential self-check
# ---------------------------------------------------------------------------


def test_selfcheck_clean_pass(monkeypatch):
    monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
    k = _compile(_chunk_program, **CHUNK_CFG)
    a = _shards(0)
    r = np.asarray(k(a))
    v = obs.metrics_summary()["verify"]
    assert v["selfcheck_runs"] == 1 and v["selfcheck_ok"] == 1
    assert v["selfcheck_divergence"] == 0
    # second call: no re-check (first-call-only contract)
    k(a)
    assert obs.metrics_summary()["verify"]["selfcheck_runs"] == 1
    # and the result is actually right
    with pass_config({"tl.tpu.comm_opt": "0"}):
        ref = tilelang.compile(_chunk_program(), target=TARGET)
    np.testing.assert_allclose(r, np.asarray(ref(a)), rtol=1e-6)


@pytest.mark.parametrize("prog,cfg,n_out", [
    (_fused_program, {}, 3),        # fuse rewrite
    (_dedup_program, {}, 3),        # dedup + slot sharing
    (_dce_program, {}, 2),          # dead-collective elimination
    (_chunk_program, CHUNK_CFG, 2),  # overlap chunking
])
def test_selfcheck_confirms_equivalence_for_golden_programs(
        monkeypatch, prog, cfg, n_out):
    """Acceptance: TL_TPU_SELFCHECK=1 confirms optimized-vs-unoptimized
    numerical equivalence for every comm_opt golden program shape on
    the 2x2 CPU mesh."""
    monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
    k = _compile(prog, **cfg)
    assert k.artifact.attrs["comm_opt"]["rewrites"]
    res = k(_shards(10))
    res = res if isinstance(res, tuple) else (res,)
    assert len(res) == n_out - 1    # outputs = params minus the input
    v = obs.metrics_summary()["verify"]
    assert v["selfcheck_runs"] == 1 and v["selfcheck_ok"] == 1
    assert v["selfcheck_divergence"] == 0 and v["degraded_schedules"] == 0


def test_selfcheck_skips_unrewritten_programs(monkeypatch):
    """No rewrites -> optimized == unoptimized; nothing to diff."""
    monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
    k = _compile(_bcast_program)        # single broadcast: no rewrite
    assert k.artifact.attrs["comm_opt"]["rewrites"] == []
    k(_shards(1))
    v = obs.metrics_summary()["verify"]
    assert v["selfcheck_runs"] == 0
    assert v["selfcheck_skipped"] == 1


@pytest.mark.parametrize("site,prog,cfg", [
    ("comm.chunk", _chunk_program, CHUNK_CFG),
    ("comm.fused", _fused_program, {}),
])
def test_selfcheck_catches_injected_corruption(monkeypatch, site, prog,
                                               cfg):
    """Seeded corruption in the optimized interpret path: divergence is
    detected on first call and the kernel falls back to (and returns)
    the unoptimized schedule's result."""
    monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
    a = _shards(2)
    with pass_config({**cfg, "tl.tpu.comm_opt": "0"}):
        ref = tilelang.compile(prog(), target=TARGET)
    want = ref(a)
    want = want if isinstance(want, tuple) else (want,)
    _CACHE.clear()
    with inject(site, kind="corrupt", seed=3):
        k = _compile(prog, **cfg)
        got = k(a)
    got = got if isinstance(got, tuple) else (got,)
    v = obs.metrics_summary()["verify"]
    assert v["selfcheck_divergence"] == 1
    assert v["degraded_schedules"] == 1
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-6, atol=1e-6)
    # degraded permanently: later calls route through the reference
    got2 = k(a)
    got2 = got2 if isinstance(got2, tuple) else (got2,)
    np.testing.assert_allclose(np.asarray(got2[0]), np.asarray(want[0]),
                               rtol=1e-6, atol=1e-6)


def test_selfcheck_divergence_raises_without_fallback(monkeypatch):
    monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
    monkeypatch.setenv("TL_TPU_FALLBACK", "none")
    with inject("comm.chunk", kind="corrupt", seed=3):
        k = _compile(_chunk_program, **CHUNK_CFG)
        with pytest.raises(SelfCheckDivergence, match="diverged"):
            k(_shards(3))


# ---------------------------------------------------------------------------
# numeric sanitizer
# ---------------------------------------------------------------------------


def test_sanitizer_catches_poisoned_mesh_payload(monkeypatch):
    monkeypatch.setenv("TL_TPU_SANITIZE", "1")
    k = _compile(_bcast_program)
    bad = _shards(4)
    bad[0, 0] = np.nan
    with pytest.raises(NumericError, match=r"collective \[1\] payload"):
        k(bad)
    # clean inputs pass through the same sanitized program
    r = np.asarray(k(_shards(4)))
    assert np.isfinite(r).all()
    assert obs.metrics_summary()["verify"]["sanitize_violations"] == 1


def test_sanitizer_catches_nonfinite_kernel_output(monkeypatch):
    """The non-mesh path: JITKernel outputs are checked host-side."""
    M, N = 32, 128

    @T.prim_func
    def double(A: T.Tensor((M, N), "float32"),
               B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * 2.0
            T.copy(s, B)

    k = tilelang.compile(double)
    a = np.ones((M, N), np.float32)
    a[3, 7] = np.inf
    assert not np.isfinite(np.asarray(k(a))).all()   # off: passes through
    monkeypatch.setenv("TL_TPU_SANITIZE", "1")
    with pytest.raises(NumericError, match=r"output 'B'"):
        k(a)
    k(np.ones((M, N), np.float32))                   # clean: fine


def test_guards_disabled_is_zero_overhead():
    """The default dispatch path: no guard state object is allocated,
    no sanitized variant is ever built."""
    assert guard_state() is None
    k = _compile(_bcast_program)
    k(_shards(5))
    assert k._sanitized_cache is None
    assert k._ref_kernel is None
    assert k._delegate is None
    v = obs.metrics_summary()["verify"]
    assert v["selfcheck_runs"] == 0 and v["sanitize_violations"] == 0


# ---------------------------------------------------------------------------
# collective watchdog
# ---------------------------------------------------------------------------


def test_watchdog_call_unit():
    """Wall-clock expiry: the wedged worker is abandoned and the error
    is a timeout TLError attributed to the watchdog site."""
    def wedged():
        time.sleep(5.0)

    t0 = time.perf_counter()
    with pytest.raises(TLTimeoutError, match="watchdog"):
        watchdog_call(wedged, timeout_ms=50, n_collectives=1, kernel="k")
    assert time.perf_counter() - t0 < 2.0
    assert watchdog_call(lambda: 7, timeout_ms=5000, n_collectives=1,
                         kernel="k") == 7


def test_watchdog_classifies_and_degrades(monkeypatch):
    """An injected timeout on the chunked interpret path: classified as
    timeout, breaker fed, kernel degraded to the unoptimized schedule,
    call still returns the right answer."""
    monkeypatch.setenv("TL_TPU_COMM_TIMEOUT_MS", "60000")
    a = _shards(6)
    with pass_config({**CHUNK_CFG, "tl.tpu.comm_opt": "0"}):
        ref = tilelang.compile(_chunk_program(), target=TARGET)
    want = np.asarray(ref(a))
    _CACHE.clear()
    with inject("comm.chunk", kind="timeout"):
        k = _compile(_chunk_program, **CHUNK_CFG)
        got = np.asarray(k(a))
    v = obs.metrics_summary()["verify"]
    assert v["watchdog_timeouts"] == 1
    assert v["degraded_schedules"] == 1
    assert global_breaker()._failures        # signature recorded
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_watchdog_exempts_first_call_compile(monkeypatch):
    """The wall-clock budget arms on WARM dispatches only: the first
    call's jax trace + XLA compile must never trip it. The second
    (warm) call under an absurd budget trips and degrades — and the
    degraded reference's own first call is exempt again."""
    monkeypatch.setenv("TL_TPU_COMM_TIMEOUT_MS", "0.001")
    k = _compile(_chunk_program, **CHUNK_CFG)
    a = _shards(9)
    r1 = np.asarray(k(a))     # compile-heavy first call: exempt
    assert obs.metrics_summary()["verify"]["watchdog_timeouts"] == 0
    r2 = np.asarray(k(a))     # warm call: trips, degrades, still right
    v = obs.metrics_summary()["verify"]
    assert v["watchdog_timeouts"] == 1 and v["degraded_schedules"] == 1
    np.testing.assert_allclose(r2, r1, rtol=1e-6, atol=1e-6)


def test_watchdog_exempts_fresh_sanitized_variant(monkeypatch):
    """Warm gating is per program VARIANT: enabling TL_TPU_SANITIZE
    after warmup compiles a fresh sanitized program, whose first
    (compile) dispatch must also be exempt from the budget."""
    monkeypatch.setenv("TL_TPU_COMM_TIMEOUT_MS", "0.001")
    k = _compile(_chunk_program, **CHUNK_CFG)
    a = _shards(11)
    k(a)                       # plain variant compiles: exempt
    monkeypatch.setenv("TL_TPU_SANITIZE", "1")
    k(a)                       # sanitized variant compiles: exempt too
    assert obs.metrics_summary()["verify"]["watchdog_timeouts"] == 0


def test_watchdog_first_call_exemption_survives_polluted_globals(
        monkeypatch):
    """Regression guard for the PR 7-era cross-suite flake
    (test_comm_opt -> test_watchdog_exempts_first_call_compile): the
    root causes were (a) a scheduling race — a warm dispatch could
    finish before the parent reached its queue wait, silently passing
    a blown budget — fixed by enforcing the budget on measured wall
    time, and (b) process-global state (breaker failures, registry
    health, fault overrides, warm latency histograms) leaking across
    suites, fixed by the conftest autouse reset. This test recreates
    the leaked-state half DELIBERATELY in-process — an open breaker
    circuit under a foreign signature, an unhealthy backend, an armed
    fault on an unrelated site, and a pre-warmed latency histogram —
    and asserts the watchdog's first-call compile exemption still
    holds under an absurd budget, so neither fix can silently
    regress."""
    from tilelang_mesh_tpu.codegen.backends import registry
    from tilelang_mesh_tpu.observability import histogram as _hist
    # (a) a breaker circuit opened by a previous suite's failures
    b = global_breaker()
    for _ in range(b.threshold):
        b.record_failure("leaked.signature.from.previous.suite")
    # (b) a backend marked unhealthy by an earlier device-loss test
    registry().mark_unhealthy("tpu-pallas",
                              RuntimeError("worker unreachable"))
    # (c) warm per-kernel latency histograms (the warm-process shape
    # of the original flake)
    _hist.observe("kernel.latency", 0.004, kernel="leaked", source="x")
    monkeypatch.setenv("TL_TPU_COMM_TIMEOUT_MS", "0.001")
    # (d) a fault armed on an UNRELATED site for the whole scenario
    with inject("autotune.trial", kind="transient"):
        k = _compile(_chunk_program, **CHUNK_CFG)
        a = _shards(13)
        r1 = np.asarray(k(a))     # compile-heavy first call: exempt
        assert obs.metrics_summary()["verify"]["watchdog_timeouts"] == 0
        r2 = np.asarray(k(a))     # warm call: trips, degrades
        v = obs.metrics_summary()["verify"]
        assert v["watchdog_timeouts"] == 1
        assert v["degraded_schedules"] == 1
        np.testing.assert_allclose(r2, r1, rtol=1e-6, atol=1e-6)


def test_watchdog_raises_without_fallback(monkeypatch):
    monkeypatch.setenv("TL_TPU_COMM_TIMEOUT_MS", "60000")
    monkeypatch.setenv("TL_TPU_FALLBACK", "none")
    with inject("comm.chunk", kind="timeout"):
        k = _compile(_chunk_program, **CHUNK_CFG)
        with pytest.raises(TLTimeoutError):
            k(_shards(7))


# ---------------------------------------------------------------------------
# fault sites + reporting surfaces
# ---------------------------------------------------------------------------


def test_comm_fault_sites_registered():
    assert "comm.chunk" in FAULT_SITES
    assert "comm.fused" in FAULT_SITES


def test_metrics_summary_verify_section():
    s = obs.metrics_summary()["verify"]
    for key in ("schedules", "collectives_checked", "warnings", "errors",
                "selfcheck_runs", "selfcheck_divergence",
                "selfcheck_skipped", "sanitize_violations",
                "watchdog_timeouts", "degraded_schedules"):
        assert key in s


def test_analyzer_verify_subcommand(monkeypatch, tmp_path, capsys):
    """A traced divergence run is summarized by `analyzer verify`."""
    from tilelang_mesh_tpu.tools.analyzer import (format_verify_report,
                                                  main, summarize_verify)
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
    with inject("comm.chunk", kind="corrupt", seed=3):
        k = _compile(_chunk_program, **CHUNK_CFG)
        k(_shards(8))
    path = tmp_path / "trace.jsonl"
    obs.write_jsonl(str(path))
    records = obs.read_jsonl(str(path))
    s = summarize_verify(records)
    assert s["counters"]["verify.selfcheck.divergence"] == 1
    assert s["selfcheck_divergence"]            # kernel -> details
    assert s["degraded"]
    report = format_verify_report(records)
    assert "selfcheck divergence by kernel" in report
    assert "degraded to the unoptimized schedule" in report
    assert main(["verify", str(path)]) == 0
    out = capsys.readouterr().out
    assert "schedule verification & guardrails" in out
    assert main(["verify", str(path), "--json"]) == 0


@pytest.mark.chaos
def test_chaos_verify_driver(tmp_path, monkeypatch):
    """The CI chaos-verify entry point end to end: corruption armed on
    both comm sites, guardrails must catch it, artifacts written."""
    from tilelang_mesh_tpu.verify.chaos import main
    # the CLI sets these in its own process; pin them here so pytest's
    # env is restored after the in-process invocation
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    monkeypatch.setenv("TL_TPU_SELFCHECK", "1")
    assert main(["--out", str(tmp_path), "--seed", "11"]) == 0
    assert (tmp_path / "chaos_trace.jsonl").exists()
    assert (tmp_path / "chaos_report.json").exists()
    import json
    rep = json.loads((tmp_path / "chaos_report.json").read_text())
    assert rep["ok"] and len(rep["scenarios"]) == 2
