"""Targeted tests for the codegen-prep transform passes.

The analyses were split out of codegen/pallas.py in round 3 (matching the
reference's pass/printer separation, layout_inference.cc vs
codegen_cuda.cc):
  - transform/mem2reg.py      fragment SSA promotion legality
  - transform/pad1.py         1-D fragment (M, 1) column layout
  - transform/prefetch_guard.py  conditional prefetch redirection

Each test pins one legality edge case the round-2 verdict called out as
covered only incidentally: loop-carried state, partial stores, conditional
defs, cross-phase liveness, traced indices, DMA pad exclusion, and the
guard index-map rendering.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.codegen.pallas import generate_source
from tilelang_mesh_tpu.transform.mem2reg import plan_locals
from tilelang_mesh_tpu.transform.pad1 import decide_pad1
from tilelang_mesh_tpu.transform.plan import plan_kernel
from tilelang_mesh_tpu.transform.prefetch_guard import param_guards


def _plan(pf):
    return plan_kernel(pf.func)


def _scratch_uid(plan, scope, shape):
    """Find the unique scratch buffer with this scope + logical shape
    (alloc names are generic: 'frag', 'shared', ...)."""
    from tilelang_mesh_tpu.ir import as_int
    hits = [b for b in plan.scratch
            if b.scope == scope and
            tuple(as_int(x) for x in b.shape) == tuple(shape)]
    assert len(hits) == 1, (
        f"want one {scope}{shape} scratch, have "
        f"{[(b.name, b.scope, b.shape) for b in plan.scratch]}")
    return hits[0].uid


def _param_uid(plan, name):
    for p in plan.params:
        if p.buffer.name == name:
            return p.buffer.uid
    raise AssertionError(f"no param named {name}")


# ---------------------------------------------------------------------------
# mem2reg (SSA promotion)
# ---------------------------------------------------------------------------

def test_mem2reg_promotes_def_then_use_fragment():
    M, N = 8, 128

    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"), O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            f = T.alloc_fragment((M, N), "float32")
            for i, j in T.Parallel(M, N):
                f[i, j] = A[i, j] * 2.0
            T.copy(f, O)

    plan = _plan(scale)
    assert _scratch_uid(plan, "fragment", (8, 128)) in plan_locals(plan)
    # and the generated source has no VMEM scratch for it
    src = generate_source(plan)
    assert "frag_l" in src and "frag_s" not in src
    assert "scratch_shapes = [\n    ]" in src


def test_mem2reg_rejects_partial_store():
    """A store covering only part of the tile is not a full def: the
    buffer must keep VMEM backing (a Python rebind would lose the other
    rows)."""
    M, N = 8, 128

    @T.prim_func
    def part(A: T.Tensor((M, N), "float32"), O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            f = T.alloc_fragment((M, N), "float32")
            T.fill(f, 0.0)
            for j in T.Parallel(N):
                f[0, j] = A[0, j]          # partial: one row only
            T.copy(f, O)

    plan = _plan(part)
    assert _scratch_uid(plan, "fragment", (8, 128)) not in plan_locals(plan)


def test_mem2reg_rejects_loop_carried_state():
    """An accumulator rebound inside a lax.fori_loop body (serial loop,
    extent > unroll threshold) is loop-carried: the rebind would neither
    escape the body function nor see the outer binding."""
    M, N, K = 8, 128, 64

    @T.prim_func
    def acc_loop(A: T.Tensor((K, M, N), "float32"),
                 O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            acc = T.alloc_fragment((M, N), "float32")
            T.fill(acc, 0.0)
            for k in T.serial(K):
                s = T.alloc_shared((M, N), "float32")
                T.copy(A[k, 0, 0], s)
                for i, j in T.Parallel(M, N):
                    acc[i, j] = acc[i, j] + s[i, j]
            T.copy(acc, O)

    plan = _plan(acc_loop)
    assert _scratch_uid(plan, "fragment", (8, 128)) not in plan_locals(plan)
    # numerics still right through the scratch path
    k = tilelang.compile(acc_loop)
    a = np.random.default_rng(0).standard_normal((K, M, N)).astype(np.float32)
    out = np.empty((M, N), np.float32)
    k(a, out)
    np.testing.assert_allclose(out, a.sum(0), rtol=1e-4)


def test_mem2reg_rejects_conditional_def_escaping_scope():
    """A def inside T.If read outside the If: the rebind happens in a
    pl.when body function and would not escape to the outer reader."""
    M, N = 8, 128

    @T.prim_func
    def cond_def(A: T.Tensor((M, N), "float32"),
                 O: T.Tensor((M, N), "float32")):
        with T.Kernel(2) as bx:
            f = T.alloc_fragment((M, N), "float32")
            T.fill(f, 0.0)
            with T.If(bx == 0):
                for i, j in T.Parallel(M, N):
                    f[i, j] = A[i, j]
            T.copy(f, O[0, 0])

    plan = _plan(cond_def)
    assert _scratch_uid(plan, "fragment", (8, 128)) not in plan_locals(plan)


def test_mem2reg_conditional_def_and_use_same_scope_promotes():
    """Def and all uses inside ONE If body: rebind never escapes, so
    promotion is legal."""
    M, N = 8, 128

    @T.prim_func
    def cond_local(A: T.Tensor((M, N), "float32"),
                   O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            f = T.alloc_fragment((M, N), "float32")
            with T.If(bx == 0):
                for i, j in T.Parallel(M, N):
                    f[i, j] = A[i, j] + 1.0
                T.copy(f, O)

    plan = _plan(cond_local)
    assert _scratch_uid(plan, "fragment", (8, 128)) in plan_locals(plan)


def test_mem2reg_rejects_cross_phase_liveness():
    """Defined in the pipelined init phase, accumulated in main: the
    value must live in VMEM across grid steps."""
    M, N, KN = 8, 128, 4

    @T.prim_func
    def pip(A: T.Tensor((KN * M, N), "float32"),
            O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            acc = T.alloc_fragment((M, N), "float32")
            s = T.alloc_shared((M, N), "float32")
            for ko in T.Pipelined(KN):
                with T.If(ko == 0):
                    T.fill(acc, 0.0)
                T.copy(A[ko * M, 0], s)
                for i, j in T.Parallel(M, N):
                    acc[i, j] = acc[i, j] + s[i, j]
            T.copy(acc, O)

    plan = _plan(pip)
    assert plan.pipeline_axis is not None
    assert _scratch_uid(plan, "fragment", (8, 128)) not in plan_locals(plan)


def test_mem2reg_rejects_grid_var_index():
    """Indexing a fragment row by the grid var: traced start, promotion
    must be rejected (Python slices cannot take traced values)."""
    R, C = 8, 128

    @T.prim_func
    def rowsel(A: T.Tensor((R, C), "float32"), O: T.Tensor((R, C), "float32")):
        with T.Kernel(R) as bx:
            f = T.alloc_fragment((R, C), "float32")
            for i, j in T.Parallel(R, C):
                f[i, j] = A[i, j] * 3.0
            T.copy(f[bx, 0], O[bx, 0])

    plan = _plan(rowsel)
    assert _scratch_uid(plan, "fragment", (8, 128)) not in plan_locals(plan)


# ---------------------------------------------------------------------------
# pad1 (column layout)
# ---------------------------------------------------------------------------

def test_pad1_applies_to_1d_stats_fragment():
    M, N = 8, 128

    @T.prim_func
    def rowmax(A: T.Tensor((M, N), "float32"), O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            m = T.alloc_fragment((M,), "float32")
            s = T.alloc_fragment((M, N), "float32")
            T.copy(A, s)
            T.reduce_max(s, m, dim=1)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] - m[i]
            T.copy(s, O)

    plan = _plan(rowmax)
    assert _scratch_uid(plan, "fragment", (8,)) in decide_pad1(plan)
    src = generate_source(plan)
    # the (M,) stats value is kept in (M, 1) column space: the reduce is
    # emitted with keepdims=True so the row broadcast needs no relayout
    assert "rt.reduce('max', " in src and ", 1, True," in src
    # numerics: row-max subtraction
    k = tilelang.compile(rowmax)
    a = np.random.default_rng(2).standard_normal((M, N)).astype(np.float32)
    out = np.empty_like(a)
    k(a, out)
    np.testing.assert_allclose(out, a - a.max(1, keepdims=True), rtol=1e-6)


def test_pad1_excluded_for_smem_and_2d():
    M, N = 8, 128

    @T.prim_func
    def mixed(A: T.Tensor((M, N), "float32"), O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            v = T.alloc_var("int32")
            s = T.alloc_shared((M, N), "float32")
            v[0] = 1
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] + 1.0
            T.copy(s, O)

    plan = _plan(mixed)
    padded = decide_pad1(plan)
    assert _scratch_uid(plan, "local.var", (1,)) not in padded   # smem scalar
    assert _scratch_uid(plan, "shared", (8, 128)) not in padded   # 2-D


def test_pad1_dropped_for_sync_dma_partner():
    """A 1-D buffer copied against an HBM-resident ('any') param goes
    through rt.dma, whose .at[] windows carry no pad column — the pad
    must be dropped on the VMEM side too."""
    N = 128

    @T.prim_func
    def stage(A: T.Tensor((N,), "float32"), O: T.Tensor((N,), "float32")):
        with T.Kernel(1) as bx:
            s1 = T.alloc_shared((N,), "float32")
            sems = T.alloc_semaphore(1)
            T.copy_async(A, s1, sems, 0)
            T.copy_wait(A, s1, sems, 0)
            T.copy(s1, O)

    plan = _plan(stage)
    assert _scratch_uid(plan, "shared", (128,)) not in decide_pad1(plan)


# ---------------------------------------------------------------------------
# prefetch_guard
# ---------------------------------------------------------------------------

def _causal_like(read_in_epi=False):
    """A flash-attention-shaped kernel: V read only when ko <= bx."""
    BM, BN, D, NK = 8, 8, 128, 4

    if read_in_epi:
        @T.prim_func
        def f(Q: T.Tensor((BM, D), "float32"),
              V: T.Tensor((NK * BN, D), "float32"),
              O: T.Tensor((BM, D), "float32")):
            with T.Kernel(2) as bx:
                acc = T.alloc_fragment((BM, D), "float32")
                vs = T.alloc_shared((BN, D), "float32")
                for ko in T.Pipelined(NK):
                    with T.If(ko == 0):
                        T.fill(acc, 0.0)
                    with T.If(ko <= bx):
                        T.copy(V[ko * BN, 0], vs)
                        for i, j in T.Parallel(BM, D):
                            acc[i, j] = acc[i, j] + vs[i, j]
                    with T.If(ko == NK - 1):
                        T.copy(V[0, 0], vs)        # epi-step read, unguarded
                        for i, j in T.Parallel(BM, D):
                            acc[i, j] = acc[i, j] + vs[i, j]
                        T.copy(acc, O)
        return f

    @T.prim_func
    def f(Q: T.Tensor((BM, D), "float32"),
          V: T.Tensor((NK * BN, D), "float32"),
          O: T.Tensor((BM, D), "float32")):
        with T.Kernel(2) as bx:
            acc = T.alloc_fragment((BM, D), "float32")
            vs = T.alloc_shared((BN, D), "float32")
            for ko in T.Pipelined(NK):
                with T.If(ko == 0):
                    T.fill(acc, 0.0)
                with T.If(ko <= bx):
                    T.copy(V[ko * BN, 0], vs)
                    for i, j in T.Parallel(BM, D):
                        acc[i, j] = acc[i, j] + vs[i, j]
                with T.If(ko == NK - 1):
                    T.copy(acc, O)
    return f


def test_prefetch_guard_applied_to_causally_skipped_param():
    pf = _causal_like()
    plan = _plan(pf)
    assert plan.pipeline_axis is not None
    guards = param_guards(plan)
    assert _param_uid(plan, "V") in guards
    assert _param_uid(plan, "Q") not in guards
    # the printer renders the guard as a where() on the pipeline-driven dim
    src = generate_source(plan)
    assert "jnp.where(" in src
    # and numerics agree with the unguarded interpretation
    k = tilelang.compile(pf)
    rng = np.random.default_rng(3)
    q = rng.standard_normal((8, 128)).astype(np.float32)
    v = rng.standard_normal((32, 128)).astype(np.float32)
    out = np.empty((8, 128), np.float32)
    k(q, v, out)
    # bx=1 wrote last: rows sum blocks ko<=1 (none skipped... both grid
    # rows write O; last writer bx=1 accumulates ko in {0,1})
    np.testing.assert_allclose(
        out, v[:8] + v[8:16], rtol=1e-5)


def test_prefetch_guard_removed_when_param_read_elsewhere():
    """The same param also read on an unguarded step: redirection would
    starve that read, so no guard may be emitted."""
    pf = _causal_like(read_in_epi=True)
    plan = _plan(pf)
    guards = param_guards(plan)
    assert _param_uid(plan, "V") not in guards


def test_prefetch_guard_noop_without_pipeline_axis():
    M, N = 8, 128

    @T.prim_func
    def plain(A: T.Tensor((M, N), "float32"),
              O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            T.copy(s, O)

    plan = _plan(plain)
    if plan.pipeline_axis is None:
        assert param_guards(plan) == {}
