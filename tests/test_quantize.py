"""w4a16 dequant GEMM + fp8 GEMM (BASELINE config #3; reference
examples/dequantize_gemm + benchmark/matmul_fp8 behavior)."""

import numpy as np
import pytest

import jax.numpy as jnp

from tilelang_mesh_tpu.quantize import (dequantize_int4_planar_ref,
                                        pack_int4, quantize_int4_planar,
                                        unpack_int4_ref)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    q = rng.integers(-8, 8, (64, 32)).astype(np.int8)
    assert (unpack_int4_ref(pack_int4(q)) == q).all()


def test_planar_quant_reconstruction():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((512, 64)).astype(np.float32)
    packed, scales = quantize_int4_planar(w, group_size=128)
    deq = dequantize_int4_planar_ref(packed, scales, group_size=128)
    planar = np.concatenate([w[:256], w[256:]], axis=0)
    # int4 quantization error is bounded by scale/2 per group
    g = scales.reshape(2, 2, 64)
    err = np.abs(deq - planar)
    assert err.max() <= scales.max() * 0.5 + 1e-6


def test_dequant_gemm_matches_dequantized_reference():
    from tilelang_mesh_tpu.ops.dequant_gemm import dequant_matmul
    rng = np.random.default_rng(2)
    M, N, K = 128, 128, 512
    gs = 128
    a = jnp.asarray(rng.standard_normal((M, K)), jnp.float32)
    w = rng.standard_normal((K, N)).astype(np.float32)
    packed, scales = quantize_int4_planar(w, group_size=gs)
    out = dequant_matmul(a, jnp.asarray(packed), jnp.asarray(scales),
                         group_size=gs, block_K2=gs)
    # reference: A @ planar-dequantized W (undo the planar row order)
    deq = dequantize_int4_planar_ref(packed, scales, group_size=gs)
    w_eff = np.concatenate([deq[:K // 2], deq[K // 2:]], axis=0)
    a_np = np.asarray(a)
    ref = np.concatenate([a_np[:, :K // 2], a_np[:, K // 2:]], 1) @ w_eff
    assert_allclose(np.asarray(out), ref, rtol=2e-2, atol=2e-1)


def test_fp8_gemm():
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel
    rng = np.random.default_rng(3)
    M = N = K = 256
    k = matmul_kernel(M, N, K, 128, 128, 128, in_dtype="float8_e4m3fn",
                      out_dtype="float32")
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.3, jnp.float8_e4m3fn)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.3, jnp.float8_e4m3fn)
    out = k(a, b)
    ref = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    assert_allclose(np.asarray(out), ref, rtol=5e-2, atol=5e-1)
